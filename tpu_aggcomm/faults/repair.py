"""Schedule repair: reroute around dead links and dead aggregators.

The repair pass is a *program transform* over ``Schedule.programs`` —
schedules stay data (core/schedule.py), no backend knows how repair works,
and the output must survive the same trust gates as any schedule: byte-exact
``--verify`` against the local oracle on every backend that executes it, and
a static traffic-auditor proof of its ``-c`` bound.

Two repairs, applied in this order:

1. **Fallback-aggregator election** (``deadagg:aI``): the I-th aggregator
   rank has failed in its aggregator role. A deterministic election picks
   the lowest-ranked live non-aggregator (avoiding every fault-named rank
   when possible), the pattern is re-homed via
   ``AggregatorPattern.rank_list_override``, and the schedule is simply
   *regenerated* — the method generators already know how to build a
   correct program for any rank_list, so election needs no program surgery
   and works on every backend.

2. **Dead-link detour** (``deadlink:S>D``): the payload for the dead edge
   is rerouted S -> V -> D via a live relay intermediate V on a fresh
   matching channel (``Op.chan`` — a detour sharing a directed pair with a
   pattern edge still matches uniquely). Mechanics per dead edge:

   - S's original send is retargeted to V in place (ISSEND downgraded to
     eager ISEND: V posts its relay receive at its program *tail*, and a
     rendezvous send blocking mid-program on a tail-posted receive would
     deadlock the oracle);
   - D's original receive is removed and its token dropped from D's
     waitalls (blocking mid-program on the late relay hop would deadlock:
     D stuck => D never posts later receives => rendezvous senders to D
     block => V never reaches its relay ops);
   - V appends: receive into a private staging row (``to_stage``), wait,
     forward to D from that staging row (``from_stage``), wait;
   - D appends: receive into the ORIGINAL recv slot, wait — so the
     repaired schedule fills exactly the bytes the healthy one fills
     (byte-exact ``--verify``).

   Relay hops occupy two fresh trailing rounds (hop 1 completes strictly
   before hop 2 begins — the collective backends apply rounds as
   sequential program steps, so a same-round relay would read unfilled
   staging). Token lifetimes are sequential (post, wait, post, wait), so
   the in-flight peak the traffic auditor proves never grows past the
   healthy bound.

jax-free (tests pin this with a poisoned-jax subprocess): repair runs on
CLI/replay paths where jax may not import.
"""

from __future__ import annotations

from dataclasses import replace

from tpu_aggcomm.core.schedule import Op, OpKind, Schedule, TimerBucket
from tpu_aggcomm.faults.spec import FaultSpec, FaultSpecError, parse_fault

__all__ = ["RepairError", "repair_schedule"]


class RepairError(ValueError):
    """The fault cannot be repaired on this schedule (dense collective,
    TAM, blocking receive on the dead edge, no live relay...)."""


_SEND_KINDS = (OpKind.ISEND, OpKind.ISSEND, OpKind.SEND)


def _next_token(prog) -> int:
    """1 + the largest token id referenced anywhere in a rank's program."""
    mx = -1
    for op in prog:
        mx = max(mx, op.token, *op.tokens) if op.tokens else max(mx, op.token)
    return mx + 1


def _max_round(programs) -> int:
    return max((op.round for prog in programs for op in prog), default=0)


def _elect_fallbacks(schedule, spec: FaultSpec):
    """Deterministic fallback election for every dead aggregator index.

    Returns ``(new_pattern, dead_agg_ranks)`` — the re-homed pattern and
    the ORIGINAL ranks whose aggregator role died (the detour pass avoids
    them as relay intermediates)."""
    p = schedule.pattern
    rank_list = [int(r) for r in p.rank_list]
    dead_agg_ranks = [rank_list[i] for i in spec.deadaggs]
    fault_named = ({r for r, _ in spec.slow}
                   | {s for s, _ in spec.deadlinks}
                   | {d for _, d in spec.deadlinks}
                   | set(dead_agg_ranks))
    taken = set(rank_list)
    for i in spec.deadaggs:
        # preference: lowest live rank that is neither an aggregator nor
        # named by any fault clause; relaxed: any non-aggregator that is
        # not itself a dead aggregator (a slow replacement beats none)
        cand = next((r for r in range(p.nprocs)
                     if r not in taken and r not in fault_named), None)
        if cand is None:
            cand = next((r for r in range(p.nprocs)
                         if r not in taken and r not in dead_agg_ranks), None)
        if cand is None:
            raise RepairError(
                f"no live rank available to replace dead aggregator "
                f"a{i} (rank {rank_list[i]}) in nprocs={p.nprocs}")
        rank_list[i] = cand
        taken.add(cand)
    return (replace(p, rank_list_override=tuple(rank_list)), dead_agg_ranks)


def _pick_relay(nprocs: int, s: int, d: int, *, dead_links: set,
                avoid: set) -> int:
    """Deterministic relay choice for dead edge s->d: the lowest-ranked
    rank v with live links s->v and v->d, preferring ranks not named by
    any fault clause."""
    def ok(v: int, strict: bool) -> bool:
        if v in (s, d):
            return False
        if (s, v) in dead_links or (v, d) in dead_links:
            return False
        return not (strict and v in avoid)

    for strict in (True, False):
        for v in range(nprocs):
            if ok(v, strict):
                return v
    raise RepairError(
        f"no live relay intermediate for dead link {s}>{d} "
        f"(nprocs={nprocs})")


def _detour_dead_links(schedule, spec: FaultSpec, dead_agg_ranks):
    """Reroute every dead pattern edge via a live relay. Returns the
    repaired (programs, n_staging, dead_edges)."""
    progs = [[replace(op) for op in prog] for prog in schedule.programs]
    dead_links = set(spec.deadlinks)
    avoid = ({r for r, _ in spec.slow}
             | {x for e in spec.deadlinks for x in e}
             | set(dead_agg_ranks))
    base_round = _max_round(progs) + 1
    next_tok = [_next_token(prog) for prog in progs]
    dead_edges = []
    n_staging = 0
    for s, d in spec.deadlinks:
        send_op = next((op for op in progs[s]
                        if op.kind in _SEND_KINDS and op.peer == d
                        and op.nbytes > 0 and op.chan == 0), None)
        if send_op is None:
            sr = next((op for op in progs[s]
                       if op.kind is OpKind.SENDRECV and op.peer == d
                       and op.nbytes > 0), None)
            if sr is not None:
                raise RepairError(
                    f"dead link {s}>{d}: m={schedule.method_id} "
                    f"({schedule.name}) sends it inside a blocking "
                    f"SENDRECV pair; the paired exchange cannot be "
                    f"retargeted — no repair")
            continue  # the pattern has no s->d payload; nothing to reroute
        recv_op = next((op for op in progs[d]
                        if op.kind is OpKind.IRECV and op.peer == s
                        and op.chan == 0), None)
        if recv_op is None:
            blocking = next((op for op in progs[d]
                             if op.kind in (OpKind.RECV, OpKind.SENDRECV)
                             and (op.peer == s or op.peer2 == s)), None)
            if blocking is not None:
                raise RepairError(
                    f"dead link {s}>{d}: m={schedule.method_id} "
                    f"({schedule.name}) receives it with a blocking "
                    f"{blocking.kind.name}; the detour arrives after the "
                    f"blocking point and would deadlock — no repair")
            raise RepairError(
                f"dead link {s}>{d}: send found but no matching receive "
                f"in m={schedule.method_id} ({schedule.name})")
        v = _pick_relay(schedule.pattern.nprocs, s, d,
                        dead_links=dead_links, avoid=avoid)
        chan = 1 + n_staging
        stage = n_staging
        n_staging += 1
        nb = send_op.nbytes
        # hop 1: retarget s's send in place; eager (see module docstring)
        send_op.peer = v
        send_op.chan = chan
        send_op.round = base_round
        if send_op.kind is OpKind.ISSEND:
            send_op.kind = OpKind.ISEND
        # drop d's original receive and its token from d's waitalls
        progs[d].remove(recv_op)
        for op in progs[d]:
            if op.kind is OpKind.WAITALL and recv_op.token in op.tokens:
                op.tokens = tuple(t for t in op.tokens
                                  if t != recv_op.token)
        # relay rank v: stage in, forward out (sequential token lifetimes)
        t1, t2 = next_tok[v], next_tok[v] + 1
        next_tok[v] += 2
        progs[v] += [
            Op(OpKind.IRECV, peer=s, slot=stage, round=base_round,
               token=t1, bucket=TimerBucket.POST, nbytes=nb, chan=chan,
               to_stage=True),
            Op(OpKind.WAITALL, tokens=(t1,), round=base_round,
               bucket=TimerBucket.RECV_WAIT),
            Op(OpKind.ISEND, peer=d, slot=stage, round=base_round + 1,
               token=t2, bucket=TimerBucket.POST, nbytes=nb, chan=chan,
               from_stage=True),
            Op(OpKind.WAITALL, tokens=(t2,), round=base_round + 1,
               bucket=TimerBucket.SEND_WAIT),
        ]
        # d: re-receive into the ORIGINAL slot, from v
        t3 = next_tok[d]
        next_tok[d] += 1
        progs[d] += [
            Op(OpKind.IRECV, peer=v, slot=recv_op.slot,
               round=base_round + 1, token=t3, bucket=TimerBucket.POST,
               nbytes=nb, chan=chan),
            Op(OpKind.WAITALL, tokens=(t3,), round=base_round + 1,
               bucket=TimerBucket.RECV_WAIT),
        ]
        dead_edges.append((s, d))
    # Refusal scan: the oracle DROPS every chan-0 message on a dead link
    # (payload or 0-byte sync alike — backends/local.py try_deliver), so
    # any crossing op still left after the detours strands its receiver
    # at runtime. Before the model checker existed this fell through the
    # "no s->d payload; nothing to reroute" case and returned a
    # deadlocking program for e.g. the pairwise methods, whose 0-byte
    # SENDRECV sync exchange touches every directed pair. Refuse
    # instead — the checker (analysis/check.py) and the oracle agree.
    # (Signal handshakes ride separate plumbing with no drop rule and
    # are deliberately not scanned.)
    for r, prog in enumerate(progs):
        for op in prog:
            crossing = None
            if (op.kind in _SEND_KINDS and op.chan == 0
                    and (r, op.peer) in dead_links):
                crossing = (r, op.peer)
            elif op.kind is OpKind.SENDRECV:
                if (r, op.peer) in dead_links:
                    crossing = (r, op.peer)
                elif (op.peer2, r) in dead_links:
                    crossing = (op.peer2, r)
            elif (op.kind in (OpKind.IRECV, OpKind.RECV) and op.chan == 0
                    and (op.peer, r) in dead_links):
                crossing = (op.peer, r)
            if crossing:
                raise RepairError(
                    f"dead link {crossing[0]}>{crossing[1]}: rank {r} "
                    f"still crosses it with a {op.kind.name} "
                    f"({op.nbytes} B) after detouring — the link drops "
                    f"it and the receiver deadlocks; no repair for "
                    f"m={schedule.method_id} ({schedule.name})")
    return progs, n_staging, tuple(dead_edges)


def repair_schedule(schedule: Schedule, spec, *, barrier_type: int = 0):
    """Repair ``schedule`` for fault ``spec`` (a FaultSpec or spec string).

    Returns a new Schedule whose programs route every payload the healthy
    schedule delivers, with ``fault``/``variant`` stamped to the canonical
    spec (distinct compiled-cache key), ``n_staging`` relay rows, and the
    rerouted ``dead_edges`` recorded. Slow-rank clauses change no program
    — they are realized by the backends' injection layer — but the stamp
    still forces a distinct compiled program (the injected delay loop).
    Raises :class:`RepairError` when no safe reroute exists.
    """
    if isinstance(spec, str):
        spec = parse_fault(spec)
    if spec.empty:
        return schedule
    if getattr(schedule, "programs", None) is None:
        raise RepairError(
            f"m={schedule.method_id} has no op programs (TAM's staged "
            f"engine); fault repair needs a round-structured schedule")
    if schedule.collective:
        raise RepairError(
            f"m={schedule.method_id} ({schedule.name}) is a dense "
            f"collective; fault repair needs a round-structured schedule")
    p = schedule.pattern
    spec.validate_against(p.nprocs, p.cb_nodes)
    for s, d in spec.deadlinks:
        if s == d:
            raise FaultSpecError(
                f"deadlink {s}>{d} is a self-link (COPY edges cannot die)")

    dead_agg_ranks: list = []
    if spec.deadaggs:
        from tpu_aggcomm.core.methods import compile_method
        pattern2, dead_agg_ranks = _elect_fallbacks(schedule, spec)
        schedule = compile_method(schedule.method_id, pattern2,
                                  barrier_type=barrier_type)

    progs, n_staging, dead_edges = _detour_dead_links(
        schedule, spec, dead_agg_ranks)

    canon = spec.canonical()
    repaired = replace(schedule, programs=progs, fault=canon,
                       variant=canon, n_staging=n_staging,
                       dead_edges=dead_edges)
    try:
        repaired.validate()
    except AssertionError as e:  # pragma: no cover - self-check
        raise RepairError(f"repair self-check failed: {e}") from e
    return repaired
