"""JAX version compatibility shims.

The framework targets the current jax API (``jax.shard_map``,
``lax.pcast``); the pinned container jax (0.4.x) predates both. Every
call site imports the two names from here so the whole package runs on
either API without version branches at use sites.

- :func:`shard_map` — ``jax.shard_map`` when present, else the
  ``jax.experimental.shard_map`` original with ``check_rep=False`` (the
  old replication checker predates the varying-type system the mesh
  programs are written for, and rejects valid programs the new
  ``check_vma`` accepts).
- :func:`pcast` — ``lax.pcast`` when present, else identity: the
  replicated→varying cast only exists to satisfy the new varying-type
  checker; under 0.4.x semantics the value is already usable as-is.

jax is imported lazily inside each shim: bench.py's supervisor process
must stay importable without touching jax (a wedged TPU tunnel can hang
``import jax`` — CLAUDE.md).
"""

from __future__ import annotations

__all__ = ["shard_map", "pcast", "tpu_compiler_params"]


def shard_map(f, *, mesh, in_specs, out_specs, **kw):
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    # the varying-type checker toggle doesn't exist pre-jax.shard_map;
    # check_rep=False is its closest 0.4.x analog (disable rep checking)
    kw.pop("check_vma", None)
    kw.setdefault("check_rep", False)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def pcast(x, axes, to="varying"):
    from jax import lax
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to=to)
    return x


def tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` (current name) or ``TPUCompilerParams``
    (its 0.4.x name). Fields the installed class doesn't know are
    dropped (e.g. ``has_side_effects`` predates 0.4.x — there the
    kernel's liveness is carried by its consumed output instead)."""
    import dataclasses

    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kw.items() if k in names})
