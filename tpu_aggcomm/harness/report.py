"""Console + CSV reporting, byte-compatible with the reference.

- :func:`summarize_results` — the rank-0 console block and the appended
  ``results.csv`` row with auto-header (mpi_test.c:2068-2118). Numbers are
  printed with C's ``%lf`` (6 decimal places).
- :func:`save_all_timing` — the per-rank per-rep CSV dumps
  (``{prefix}{send_wait_all_times,total_times,post_request_time,
  barrier_time}_{comm_size}.csv``; mpi_test.c:2008-2066).
- :func:`append_provenance` — a sidecar ``*.provenance.csv`` row per
  results.csv row recording which backend actually executed the method
  (``--backend pallas_dma`` delegates TAM methods to jax_sim and dense
  collectives to jax_ici) and whether the four phase columns are direct
  per-op measurements or an attribution of a measured total
  (harness/attribution.py). The main CSV stays byte-compatible with the
  reference (mpi_test.c:2068-2118) — provenance rides alongside, so
  attributed rows can never be mistaken for measured ones downstream.
"""

from __future__ import annotations

import os

from tpu_aggcomm.harness.timer import Timer

__all__ = ["summarize_results", "save_all_timing", "config_banner",
           "append_provenance", "provenance_path"]

_CSV_HEADER = (
    "Method,# of processes,# of aggregators,data size,max comm,ntimes,"
    "aggregator type,rank 0 post_request_time,rank 0 send waitall time,"
    "rank 0 recv waitall time,rank 0 total time,max post_request_time,"
    "max send waitall time,max recv waitall time,max total time\n")


def _f(x: float) -> str:
    return f"{x:.6f}"


def config_banner(procs: int, cb_nodes: int, proc_node: int, data_size: int,
                  comm_size: int, ntimes: int, rank_list) -> str:
    """The rank-0 startup banner (mpi_test.c:2170-2179)."""
    aggs = "".join(f"{int(r)}, " for r in rank_list)
    return (f"total number of processes = {procs}, cb_nodes = {cb_nodes}, "
            f"proc_node = {proc_node}, data size = {data_size}, "
            f"comm_size = {comm_size}, ntimes={ntimes}\n"
            f"aggregators = {aggs}\n")


def summarize_results(procs: int, cb_nodes: int, data_size: int,
                      comm_size: int, ntimes: int, agg_type: int,
                      filename: str, prefix: str, timer0: Timer,
                      max_timer: Timer, *, out=None) -> str:
    """Print the per-method console block and append a results.csv row.

    ``prefix`` is the method label (e.g. "All to many"); ``timer0`` is rank
    0's timer, ``max_timer`` the max-over-ranks reduction. Returns the
    console block. ``filename=None`` skips the CSV.
    """
    block = (
        "| --------------------------------------\n"
        f"| {prefix} rank 0 request post time = {_f(timer0.post_request_time)}\n"
        f"| {prefix} rank 0 send waitall time = {_f(timer0.send_wait_all_time)}\n"
        f"| {prefix} rank 0 recv waitall time = {_f(timer0.recv_wait_all_time)}\n"
        f"| {prefix} rank 0 total time = {_f(timer0.total_time)}\n"
        f"| {prefix} max request post time = {_f(max_timer.post_request_time)}\n"
        f"| {prefix} max send waitall time = {_f(max_timer.send_wait_all_time)}\n"
        f"| {prefix} max recv waitall time = {_f(max_timer.recv_wait_all_time)}\n"
        f"| {prefix} max total time = {_f(max_timer.total_time)}\n")
    print(block, end="", file=out)
    if filename:
        write_header = not os.path.exists(filename)
        # count BEFORE appending, then stamp the cache with the new count
        # and size — the writer is the one place the count is known
        # without a re-read, which keeps a sweep's sidecar appends O(1)
        n_before = _data_rows(filename)
        with open(filename, "a") as fh:
            if write_header:
                fh.write(_CSV_HEADER)
            fh.write(
                f"{prefix},{procs},{cb_nodes},{data_size},{comm_size},"
                f"{ntimes},{agg_type},"
                f"{_f(timer0.post_request_time)},{_f(timer0.send_wait_all_time)},"
                f"{_f(timer0.recv_wait_all_time)},{_f(timer0.total_time)},"
                f"{_f(max_timer.post_request_time)},{_f(max_timer.send_wait_all_time)},"
                f"{_f(max_timer.recv_wait_all_time)},{_f(max_timer.total_time)}\n")
        _ROW_COUNT_CACHE[filename] = (n_before + 1,
                                      os.path.getsize(filename))
    return block


_PROV_HEADER = ("results row,Method,backend requested,backend executed,"
                "phase columns\n")

#: results-CSV data-row counts, cached by (path -> (rows, file size)) so a
#: long sweep's per-row sidecar appends stay O(1) instead of re-reading
#: the whole CSV each time (ADVICE r4 item 4). The recorded size detects
#: any out-of-band change to the file and forces a recount.
_ROW_COUNT_CACHE: dict[str, tuple[int, int]] = {}


def _data_rows(filename: str) -> int:
    """Data rows (excluding the auto-header) currently in ``filename``."""
    try:
        size = os.path.getsize(filename)
    except OSError:
        _ROW_COUNT_CACHE.pop(filename, None)
        return 0
    cached = _ROW_COUNT_CACHE.get(filename)
    if cached is not None and cached[1] == size:
        return cached[0]
    with open(filename) as fh:
        n = max(0, sum(1 for _ in fh) - 1)
    _ROW_COUNT_CACHE[filename] = (n, size)
    return n

#: phase-column provenance vocabulary (the third sidecar column). Labels
#: are COLUMN-accurate (VERDICT r4 item 7b): a "+attributed(...)" suffix
#: names exactly which part of the row is model-distributed rather than
#: measured — a sidecar reader can never over-read a row as fully
#: measured when only a boundary was.
#:   measured            direct per-op host timing (native)
#:   measured-rounds(post,deliver)+attributed(waits)
#:                       the FULL 2-D measurement (jax_sim
#:                       measure_round_splits, unrolled schedules): per
#:                       round, BOTH the preparation window and the
#:                       delivery window are chained-truncation
#:                       measurements; only the mixing of a round's
#:                       delivery window among a rank's wait buckets is
#:                       structural
#:   measured-rounds+attributed(buckets)
#:                       per-round durations MEASURED by chained round-
#:                       prefix truncation differencing
#:                       (measure_round_times on jax_sim/jax_shard/
#:                       jax_ici, zero dispatch-sync); within each
#:                       round, the measured time is distributed among
#:                       the buckets charged in that round by op weights
#:                       (rounds whose charges are a single bucket —
#:                       e.g. m=2's per-round send Waitalls — are
#:                       therefore fully measured columns)
#:   measured-hops(P2,P3,P4)+attributed(ranks)
#:                       TAM's 3-hop relay durations MEASURED by chained
#:                       hop-prefix truncation differencing (jax_sim
#:                       measure_tam_hops); which column a rank's wall
#:                       window lands in follows the reference's own
#:                       bracket placement (proxies charge P3 to
#:                       send_wait, l_d_t.c:1162-1195)
#:   measured-split(post,deliver)+attributed(waits)
#:                       truncation-differenced on-device measurement of
#:                       the post/deliver boundary (jax_sim
#:                       measure_phase_split); the delivery side is
#:                       distributed among wait buckets by op weights
#:   total-only          only total_time measured; phase columns zero (local)
#:   attributed          whole-rep measured total split by the
#:                       fenced-segment model (harness/attribution.py)
#:   attributed-rounds   per-round dispatch-timed totals split within each
#:                       round (--profile-rounds; host sync per round)
#:   attributed-chained  differenced serial-chain total, then attributed
PHASE_SOURCES = ("measured",
                 "measured-rounds(post,deliver)+attributed(waits)",
                 "measured-rounds+attributed(buckets)",
                 "measured-hops(P2,P3,P4)+attributed(ranks)",
                 "measured-split(post,deliver)+attributed(waits)",
                 "total-only", "attributed",
                 "attributed-rounds", "attributed-chained")


def provenance_path(filename: str) -> str:
    """Sidecar path for a results CSV: ``results.csv`` ->
    ``results.provenance.csv``."""
    stem = filename[:-4] if filename.endswith(".csv") else filename
    return stem + ".provenance.csv"


def append_provenance(filename: str, method_name: str, requested: str,
                      executed: str, phases: str) -> str:
    """Append one provenance row describing the LAST results.csv row.

    ``requested`` is the --backend the user selected; ``executed`` the
    backend that actually ran the rep (delegation makes them differ);
    ``phases`` one of :data:`PHASE_SOURCES`. Append-mode with auto-header,
    like the main CSV. The join key is explicit — the ``results row``
    column carries the 1-based data-row index of the main CSV at append
    time — so a results.csv that predates the sidecar (append mode
    accumulates across invocations and framework versions) can never
    silently shift labels onto the wrong rows."""
    if phases not in PHASE_SOURCES:
        raise ValueError(f"unknown phase source {phases!r}; "
                         f"expected one of {PHASE_SOURCES}")
    nrows = _data_rows(filename)
    path = provenance_path(filename)
    write_header = not os.path.exists(path)
    if not write_header:
        # a sidecar written under an older schema must never get rows of
        # the current schema appended beneath its header (columns would
        # silently shift) — rotate it aside and start fresh
        with open(path) as fh:
            if fh.readline() != _PROV_HEADER:
                k, bak = 0, path + ".old-schema"
                while os.path.exists(bak):   # never clobber a prior backup
                    k += 1
                    bak = f"{path}.old-schema.{k}"
                os.replace(path, bak)
                write_header = True
    import csv
    with open(path, "a", newline="") as fh:
        if write_header:
            fh.write(_PROV_HEADER)
        # csv.writer, not f-string joins: the phase-source vocabulary
        # contains commas (measured-hops(P2,P3,P4)+attributed(ranks)),
        # which must be quoted or every downstream DictReader splits the
        # label across columns
        csv.writer(fh, lineterminator="\n").writerow(
            [nrows, method_name, requested, executed, phases])
    return path


def save_all_timing(procs: int, ntimes: int, comm_size: int,
                    rep_timers: list[list[Timer]], prefix: str = "",
                    outdir: str = ".") -> list[str]:
    """Per-rank per-rep CSV dumps (mpi_test.c:2008-2066).

    ``rep_timers[rep][rank]`` is rank's Timer for that rep. Writes one file
    per timing field, one row per rank: ``rank,rep0,rep1,...``.
    """
    fields = [
        ("send_wait_all_times", "send_wait_all_time"),
        ("total_times", "total_time"),
        ("post_request_time", "post_request_time"),
        ("barrier_time", "barrier_time"),
    ]
    written = []
    for fname_part, attr in fields:
        path = os.path.join(outdir, f"{prefix}{fname_part}_{comm_size}.csv")
        with open(path, "w") as fh:
            for rank in range(procs):
                row = [str(rank)]
                for rep in range(ntimes):
                    row.append(_f(getattr(rep_timers[rep][rank], attr)))
                fh.write(",".join(row) + "\n")
        written.append(path)
    return written
