"""Experiment driver: the analog of the reference ``main`` loop
(mpi_test.c:2120-2347) — iter × method dispatch, max-over-ranks reduction,
console/CSV reporting, optional verification."""

from __future__ import annotations

import os
import statistics
import sys
import time
from dataclasses import dataclass

from tpu_aggcomm.backends import get_backend
from tpu_aggcomm.core.methods import METHODS, compile_method, method_ids
from tpu_aggcomm.core.pattern import AggregatorPattern
from tpu_aggcomm.core.schedule import schedule_shape_key
from tpu_aggcomm.harness.attribution import cell_recording
from tpu_aggcomm.harness.report import (append_provenance, config_banner,
                                        save_all_timing, summarize_results)
from tpu_aggcomm.harness.timer import max_reduce
from tpu_aggcomm.obs import ledger, trace
from tpu_aggcomm.resilience import (check_boundary, classify_error,
                                    derive_deadline, retry_call)
from tpu_aggcomm.resilience.watchdog import (schedule_floor_s,
                                             soft_deadline_check)

__all__ = ["ExperimentConfig", "run_experiment"]


@dataclass
class ExperimentConfig:
    """Mirrors the reference CLI grammar ``hp:c:m:d:a:i:k:t:r:b:``
    (mpi_test.c:2130-2166) plus the backend switch."""

    nprocs: int
    cb_nodes: int = 1            # -a
    method: int = 0              # -m  (0 = run all dispatched methods)
    data_size: int = 0           # -d
    comm_size: int = 200_000_000 # -c
    iters: int = 1               # -i
    ntimes: int = 1              # -k
    proc_node: int = 1           # -p
    agg_type: int = 1            # -t
    prefix: str = ""             # -r
    barrier_type: int = 0        # -b
    backend: str = "local"       # --backend
    verify: bool = False         # --verify
    results_csv: str | None = "results.csv"
    profile_rounds: bool = False
    chained: bool = False        # jax_sim/jax_shard/jax_ici: chained timing
    measured_phases: bool = False  # jax_sim/jax_shard/jax_ici: measured
    #                                per-round times (round-prefix
    #                                truncation differencing); TAM hops on
    #                                jax_sim; single-round schedules fall
    #                                back to the post/deliver split on
    #                                jax_sim, attributed-chained elsewhere
    xprof: str | None = None     # --xprof LOGDIR: profile ONE extra rep
    #                              per method under jax.profiler.trace and
    #                              report its divergence vs the
    #                              reconstructed attribution (cross-check
    #                              only — the timed path is untouched)
    fault: str | None = None     # --fault SPEC: fault-injection scenario
    #                              ("slow:rR*F,deadlink:S>D,deadagg:aI");
    #                              schedules are repaired (faults/repair.py)
    #                              before dispatch and backends realize the
    #                              injected degradation (faults/inject.py)


def run_experiment(cfg: ExperimentConfig, *, out=None) -> list[dict]:
    """Run the experiment loop; returns one record per (iter, method) with
    rank-0 and max timers."""
    if cfg.data_size < 1:
        raise ValueError("data_size (-d) must be >= 1 "
                         "(the reference's -d 0 default sends empty messages; "
                         "pass an explicit size)")
    if cfg.chained and cfg.backend not in ("jax_sim", "jax_shard",
                                           "jax_ici", "pallas_fused"):
        raise ValueError("--chained requires --backend jax_sim, jax_shard, "
                         "jax_ici or pallas_fused (serial-chained on-device "
                         "measurement)")
    if cfg.chained and cfg.profile_rounds:
        raise ValueError("--chained and --profile-rounds are exclusive "
                         "(one program vs per-round programs)")
    if cfg.profile_rounds and cfg.backend not in ("jax_ici", "jax_sim",
                                                  "jax_shard"):
        raise ValueError(
            "--profile-rounds requires --backend jax_ici, jax_sim or "
            "jax_shard (per-round fenced segments exist only there; "
            "local/native time each op directly, pallas_dma attributes "
            "whole-rep time)")
    if cfg.measured_phases:
        if cfg.backend not in ("jax_sim", "jax_shard", "jax_ici"):
            raise ValueError(
                "--measured-phases requires --backend jax_sim, jax_shard "
                "or jax_ici (truncation-differenced round/phase "
                "measurement exists only on the chained rank-axis "
                "programs)")
        if cfg.profile_rounds:
            raise ValueError("--measured-phases and --profile-rounds are "
                             "exclusive")
    fspec = None
    if cfg.fault:
        from tpu_aggcomm.faults import parse_fault
        fspec = parse_fault(cfg.fault)
        if fspec.empty:
            fspec = None
    if fspec is not None and cfg.measured_phases:
        raise ValueError(
            "--measured-phases is not supported with --fault (round-prefix "
            "truncation would replay the injected delay once per prefix); "
            "use --chained timing for faulted runs")
    backend = get_backend(cfg.backend)
    pattern = AggregatorPattern(
        nprocs=cfg.nprocs, cb_nodes=cfg.cb_nodes,
        data_size=cfg.data_size, placement=cfg.agg_type,
        proc_node=cfg.proc_node, comm_size=cfg.comm_size)
    print(config_banner(cfg.nprocs, cfg.cb_nodes, cfg.proc_node,
                        cfg.data_size, cfg.comm_size, cfg.ntimes,
                        pattern.rank_list), end="", file=out)

    methods = method_ids() if cfg.method == 0 else [cfg.method]
    for m in methods:
        if m not in METHODS:
            raise ValueError(f"unknown method id {m}; valid ids: "
                             f"{sorted(METHODS)}")
    if cfg.chained and cfg.backend == "jax_ici":
        # fail BEFORE any method runs: a run-all sweep must not crash
        # mid-run (and leave a partial CSV) when it reaches m=15/16.
        # (jax_shard chains TAM through the blocked engine's scaffold
        # since round 5; jax_ici's two-level mesh engine still times
        # whole reps)
        tam_selected = [m for m in methods if METHODS[m].tam]
        if tam_selected:
            raise ValueError(
                f"--chained on --backend {cfg.backend} does not support "
                f"the TAM methods {tam_selected} (the two-level mesh "
                f"engine times whole reps); use --backend jax_sim or "
                f"jax_shard for a chained run-all, or pick a non-TAM "
                f"method with -m")
    # schedules do not depend on the iteration (only the fill seed does):
    # compile once per method, reuse across iters. Schedule-build walls go
    # to the run ledger (a list append outside any timed window).
    compiled = {}
    for m in methods:
        t0 = time.perf_counter()
        compiled[m] = compile_method(m, pattern,
                                     barrier_type=cfg.barrier_type)
        ledger.record_compile(
            f"m{m}:{METHODS[m].name}",
            seconds=time.perf_counter() - t0, kind="schedule-build",
            backend=cfg.backend)
    if cfg.backend == "pallas_fused":
        # fail BEFORE any method runs, same discipline as the jax_ici TAM
        # guard: a run-all sweep hitting an unfusable method mid-run would
        # leave a partial CSV. TAM and the dense collectives have no
        # throttle rounds to fuse (native/fuse.py refuses them by name);
        # -m 0 on this backend means "the fusable subset", while naming
        # one of them explicitly must still refuse upfront.
        unfusable = [m for m in methods
                     if METHODS[m].tam or compiled[m].collective]
        if cfg.method == 0:
            methods = [m for m in methods if m not in unfusable]
        elif unfusable:
            raise ValueError(
                f"--backend pallas_fused does not support methods "
                f"{unfusable} (TAM's staged engine and the dense "
                f"collectives have no throttle rounds to fuse); run "
                f"them on jax_sim")
    if fspec is not None:
        # repair BEFORE any method runs: an unrepairable method in a
        # run-all sweep must fail upfront, not mid-run with a partial CSV
        from tpu_aggcomm.faults import repair_schedule
        bad = [m for m in methods
               if METHODS[m].tam or compiled[m].collective]
        if bad:
            raise ValueError(
                f"--fault does not support methods {bad} (TAM's staged "
                f"engine and the dense collectives have no round-"
                f"structured op programs to repair); pick round-structured "
                f"methods with -m")
        canon = fspec.canonical()
        for m in methods:
            t0 = time.perf_counter()
            compiled[m] = repair_schedule(compiled[m], fspec,
                                          barrier_type=cfg.barrier_type)
            ledger.record_compile(
                f"m{m}:{METHODS[m].name}[{canon}]",
                seconds=time.perf_counter() - t0, kind="schedule-repair",
                backend=cfg.backend)
    if cfg.measured_phases:
        # fail upfront, like the chained TAM guard: the truncation
        # measurement exists for round-structured schedules everywhere
        # and for TAM's 3-hop relay on jax_sim (measure_tam_hops);
        # dense collectives genuinely have no decomposition
        bad = [m for m in methods
               if compiled[m].collective
               or (METHODS[m].tam and cfg.backend != "jax_sim")]
        if bad:
            raise ValueError(
                f"--measured-phases does not support methods {bad} here "
                f"(dense collectives have no decomposition to truncate; "
                f"TAM hop measurement runs on jax_sim only); pick "
                f"round-structured methods with -m")
        # ... and only for schedules shallow enough to compile one prefix
        # chain per round — fail BEFORE any method runs, not mid-sweep
        # with a partial CSV (the pairwise methods are always nprocs
        # rounds regardless of -c)
        from tpu_aggcomm.harness.chained import MAX_MEASURED_ROUNDS
        deep = [m for m in methods
                if not METHODS[m].tam
                and len({int(e[4]) for e in compiled[m].data_edges()})
                > MAX_MEASURED_ROUNDS]
        if deep:
            raise ValueError(
                f"--measured-phases does not support methods {deep} here: "
                f"more than {MAX_MEASURED_ROUNDS} throttle rounds (one "
                f"prefix chain is compiled per round); use "
                f"--profile-rounds for very deep schedules")
    _preflight_probe(cfg.backend)
    # watchdog inputs: roofline floors (once per method — the schedule
    # does not change across iters) and observed walls per method
    floors: dict[int, float | None] = {}
    prior_walls: dict[int, list[float]] = {}
    rpc_probe = ledger.manifest().get("rpc_probe_s")
    records = []
    for i in range(cfg.iters):
        for m in methods:
            # a deferred SIGINT/SIGTERM (resilience/watchdog) lands HERE,
            # between dispatches — never mid-kernel
            check_boundary(f"m{m}:i{i}")
            spec = METHODS[m]
            sched = compiled[m]
            kwargs = {}
            if cfg.profile_rounds and backend.name in ("jax_ici", "jax_sim",
                                                       "jax_shard"):
                kwargs["profile_rounds"] = True
            if cfg.chained:
                kwargs["chained"] = True
            if cfg.measured_phases:
                kwargs["measured_phases"] = True
            rec = trace.current()
            if m not in floors:
                floors[m] = schedule_floor_s(sched, cfg.backend)
            deadline = derive_deadline(
                floor_s=floors[m], ntimes=cfg.ntimes,
                rpc_probe_s=rpc_probe,
                prior_walls=prior_walls.get(m, ()))
            t_dispatch = time.perf_counter()

            def dispatch():
                # one ATTEMPT = the whole backend.run with a fresh cell
                # sink and its own span — a failed attempt's partial cell
                # stream must not pollute the accepted attribution
                if rec is not None:
                    with cell_recording() as c, \
                            rec.span("backend.run", method=m,
                                     method_name=spec.name, iter=i,
                                     backend=cfg.backend):
                        rv, tm = backend.run(sched, ntimes=cfg.ntimes,
                                             iter_=i, verify=cfg.verify,
                                             **kwargs)
                    return rv, tm, c
                rv, tm = backend.run(sched, ntimes=cfg.ntimes, iter_=i,
                                     verify=cfg.verify, **kwargs)
                return rv, tm, None

            # transient tunnel errors get bounded seeded retries; verify/
            # program/compile-class errors raise on the first attempt
            recv, timers, calls = retry_call(dispatch,
                                             site=f"dispatch:m{m}:i{i}")
            wall = time.perf_counter() - t_dispatch
            soft_deadline_check(f"dispatch:m{m}:i{i}", wall_s=wall,
                                deadline_s=deadline, out=out)
            prior_walls.setdefault(m, []).append(wall)
            if i == 0:
                # first dispatch of this method = XLA compile (for the
                # compiled backends) + the run itself; an honest wall
                # around that compile-containing boundary for the ledger
                # — the label says "first-dispatch", never "compile"
                ledger.record_compile(
                    f"m{m}:{spec.name}",
                    seconds=time.perf_counter() - t_dispatch,
                    kind="first-dispatch", backend=cfg.backend)
                _sample_device(rec)
            max_timer = max_reduce(timers)
            summarize_results(cfg.nprocs, cfg.cb_nodes, cfg.data_size,
                              cfg.comm_size, cfg.ntimes, cfg.agg_type,
                              cfg.results_csv, spec.name, timers[0],
                              max_timer, out=out)
            # provenance sidecar, one row per results row (VERDICT r3
            # item 8): which backend executed (delegation differs from
            # the request) and whether phase columns are measured or
            # attributed — the main CSV stays reference-byte-compatible
            executed, phases = getattr(backend, "last_provenance",
                                       (backend.name, "total-only"))
            if rec is not None:
                rec.record_method_run(
                    sched, method=m, name=spec.name, iter_=i,
                    ntimes=cfg.ntimes, requested=cfg.backend,
                    executed=executed, phase_source=phases,
                    timers=timers, calls=calls,
                    rep_timers=getattr(backend, "last_rep_timers", None),
                    fault=getattr(sched, "fault", None))
            if cfg.results_csv:
                append_provenance(cfg.results_csv, spec.name, cfg.backend,
                                  executed, phases)
            if m == 13:
                rep_timers = getattr(backend, "last_rep_timers", None)
                if rep_timers:
                    save_all_timing(cfg.nprocs, cfg.ntimes, cfg.comm_size,
                                    rep_timers, cfg.prefix)
            records.append({
                "iter": i, "method": m, "name": spec.name,
                "timer0": timers[0], "max_timer": max_timer,
                "backend_executed": executed, "phase_source": phases,
                # the journal identity of what actually ran (fault variant
                # included) — sweep --resume records these per cell
                "shape_key": str(schedule_shape_key(sched)),
            })
            if cfg.xprof and i == 0:
                _xprof_crosscheck(backend, sched, cfg, m, spec.name,
                                  max_timer, out=out)
        print("| --------------------------------------", file=out)
    return records


def _preflight_probe(backend_name: str) -> None:
    """Pre-flight tunnel health check (resilience/watchdog, ISSUE 7):
    one trivial jitted dispatch retried under the transient policy, then
    the median of 3 timed round trips lands as the manifest's
    ``rpc_probe_s`` (the same field bench.py's measure child records) —
    so a dead tunnel fails HERE, classified, before any schedule
    dispatch compiles through it.

    Same jax discipline as :func:`_sample_device`: only runs when a
    backend already imported jax — local/native oracle runs stay
    jax-free and probe nothing."""
    if backend_name in ("local", "native"):
        return
    jax = sys.modules.get("jax")
    if jax is None:
        return

    def probe() -> float:
        import jax.numpy as jnp
        f = jax.jit(lambda x: x + jnp.uint32(1))
        int(jax.device_get(f(jnp.uint32(0))))   # compile + warm
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            int(jax.device_get(f(jnp.uint32(1))))
            samples.append(time.perf_counter() - t0)
        return statistics.median(samples)

    rpc = retry_call(probe, site="preflight.rpc_probe")
    ledger.record_device(rpc_probe_s=rpc)
    rec = ledger.record_resilience("preflight.rpc_probe",
                                   kind="preflight", rpc_probe_s=rpc)
    trace.instant("ledger.resilience", **rec)


def _sample_device(rec) -> None:
    """Record device facts + an HBM sample in the ledger (and, when
    tracing, the trace's HBM counter track).

    Only consults jax when a backend already imported it — the local/
    native oracles must stay jax-free (a dead tunnel can hang any fresh
    jax initialization, and runner-level telemetry must never change
    which processes touch jax). ``memory_stats`` is None/raising on
    platforms without an allocator report (CPU): recorded as absent,
    never guessed."""
    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        d = jax.devices()[0]
        ledger.record_device(platform=d.platform,
                             device_kind=getattr(d, "device_kind", None))
        stats = getattr(d, "memory_stats", lambda: None)() or {}
    except Exception as e:  # lint: broad-ok (device telemetry best-effort; see below)
        # telemetry stays best-effort, but the swallow is classified and
        # visible in the ledger instead of silent (ISSUE 7)
        srec = ledger.record_resilience(
            "runner.sample_device", kind="suppressed",
            error_class=classify_error(e),
            error=f"{type(e).__name__}: {e}"[:500])
        trace.instant("ledger.resilience", **srec)
        return
    peak = stats.get("peak_bytes_in_use")
    ledger.record_hbm_peak(peak)
    if rec is not None and stats:
        rec.hbm_sample(bytes_in_use=stats.get("bytes_in_use"),
                       peak_bytes=peak)


def _xprof_crosscheck(backend, sched, cfg, method: int, name: str,
                      max_timer, *, out=None) -> dict:
    """``--xprof``: run ONE extra plain rep under ``jax.profiler.trace``
    and report its divergence against the reconstructed attribution
    (max-over-ranks total / ntimes). The extra rep runs AFTER the timed
    run and outside every recording window, so round semantics and the
    timed path are untouched; the reconstructed cells stay the source of
    truth (obs/ledger.py docstring)."""
    logdir = os.path.join(cfg.xprof, f"m{method}_{name}")
    profiled = None
    err = err_class = None
    try:
        import jax
        t0 = time.perf_counter()
        with jax.profiler.trace(logdir):
            backend.run(sched, ntimes=1, iter_=0, verify=False)
        profiled = time.perf_counter() - t0
    except Exception as e:  # lint: broad-ok (profiler or backend trouble: report, not raise)
        err = f"{type(e).__name__}: {e}"
        err_class = classify_error(e)
        srec = ledger.record_resilience(
            "xprof", kind="suppressed", error_class=err_class,
            error=err[:500])
        trace.instant("ledger.resilience", **srec)
    recon = max_timer.total_time / max(cfg.ntimes, 1)
    report = ledger.xprof_report(
        label=f"m{method} {name} [{cfg.backend}]", logdir=logdir,
        profiled_wall_s=profiled, reconstructed_s=recon, error=err,
        error_class=err_class)
    trace.instant("ledger.xprof",
                  **{k: v for k, v in report.items() if k != "logdir"})
    print(f"| {ledger.render_xprof(report)}", file=out)
    return report
