"""Per-phase timers with max-over-ranks reduction.

One unified Timer type (the reference has two divergent structs — 5 fields
in mpi_test.c:25-31, 4 in lustre_driver_test.c:22-27 — sharing memory
through an extern; SURVEY.md §2.2 flags this as a hazard not to replicate).

Buckets: request-post, send-waitall, recv-waitall, barrier, total
(mpi_test.c:25-31). Reduction across ranks is element-wise MAX, mirroring
``MPI_Reduce(…, 5, MPI_DOUBLE, MPI_MAX, …)`` (mpi_test.c:2184); on the JAX
backend this is a host-side max over per-device timings (device timing is
whole-program — see backends/jax_ici.py for how phases are attributed).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_aggcomm.core.schedule import TimerBucket

__all__ = ["Timer", "max_reduce", "accumulate"]


@dataclass
class Timer:
    post_request_time: float = 0.0
    send_wait_all_time: float = 0.0
    recv_wait_all_time: float = 0.0
    barrier_time: float = 0.0
    total_time: float = 0.0

    def add(self, bucket: TimerBucket, seconds: float) -> None:
        if bucket is TimerBucket.POST:
            self.post_request_time += seconds
        elif bucket is TimerBucket.RECV_WAIT:
            self.recv_wait_all_time += seconds
        elif bucket is TimerBucket.SEND_WAIT:
            self.send_wait_all_time += seconds
        elif bucket is TimerBucket.RECV_AND_SEND_WAIT:
            self.recv_wait_all_time += seconds
            self.send_wait_all_time += seconds
        elif bucket is TimerBucket.BARRIER:
            self.barrier_time += seconds
        # TimerBucket.NONE: untimed segment

    def as_array(self) -> np.ndarray:
        return np.array([self.post_request_time, self.send_wait_all_time,
                         self.recv_wait_all_time, self.barrier_time,
                         self.total_time])

    @staticmethod
    def from_array(a) -> "Timer":
        a = np.asarray(a, dtype=np.float64)
        return Timer(float(a[0]), float(a[1]), float(a[2]), float(a[3]),
                     float(a[4]))

    def __iadd__(self, other: "Timer") -> "Timer":
        self.post_request_time += other.post_request_time
        self.send_wait_all_time += other.send_wait_all_time
        self.recv_wait_all_time += other.recv_wait_all_time
        self.barrier_time += other.barrier_time
        self.total_time += other.total_time
        return self


def max_reduce(timers: list[Timer]) -> Timer:
    """Element-wise max across ranks (the MPI_Reduce MAX analog)."""
    if not timers:
        return Timer()
    return Timer.from_array(np.stack([t.as_array() for t in timers]).max(axis=0))


def accumulate(timers: list[Timer]) -> Timer:
    out = Timer()
    for t in timers:
        out += t
    return out
