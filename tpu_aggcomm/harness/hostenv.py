"""Host-process environment scrubbing for CPU-pinned jax children.

The execution environment arms a TPU tunnel through a sitecustomize that
registers the axon PJRT platform at interpreter start whenever
``PALLAS_AXON_POOL_IPS`` is set — ``JAX_PLATFORMS=cpu`` alone is ignored
(CLAUDE.md gotcha), and a dead tunnel hangs ``jax.devices()`` forever.
The one safe way to pin a child process to the CPU backend is to scrub
every arming variable from its environment *before* Python starts. This
module is stdlib-only so supervising parents can import it without
touching jax or numpy.
"""

from __future__ import annotations

import os

__all__ = ["scrubbed_cpu_env", "env_summary"]

_ARMING_PREFIXES = ("PALLAS_AXON", "AXON_", "TPU_")


def env_summary() -> dict:
    """Scrubbed environment provenance for the run ledger
    (tpu_aggcomm/obs/ledger.py).

    Tunnel-arming variables are reported by NAME only — their values
    (pool IPs and the like) are infrastructure addresses and must never
    land in a committed artifact. ``JAX_PLATFORMS``/``XLA_FLAGS`` values
    are included verbatim: they are the two knobs that decide which
    backend and device mesh produced a number, exactly what a past-vs-
    present comparison needs to audit.
    """
    return {
        "armed_vars": sorted(k for k in os.environ
                             if k.startswith(_ARMING_PREFIXES)),
        "tunnel_armed": bool(os.environ.get("PALLAS_AXON_POOL_IPS")),
        "jax_platforms": os.environ.get("JAX_PLATFORMS"),
        "xla_flags": os.environ.get("XLA_FLAGS"),
    }


def scrubbed_cpu_env(n_devices: int | None = None) -> dict:
    """A child environment pinned to the CPU backend.

    Drops every tunnel-arming variable by prefix (the round-1 lesson:
    popping just ``PALLAS_AXON_POOL_IPS`` is not enough to future-proof
    against other arming vars), sets ``JAX_PLATFORMS=cpu``, and — when
    ``n_devices`` is given — forces a virtual ``n_devices``-device host
    mesh via ``XLA_FLAGS``; otherwise XLA_FLAGS is removed so a stale
    device-count from the caller can't leak in.
    """
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(_ARMING_PREFIXES)}
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is None:
        env.pop("XLA_FLAGS", None)
    else:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={n_devices}")
    return env
