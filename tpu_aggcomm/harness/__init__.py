"""Timing, verification, and reporting harness."""
