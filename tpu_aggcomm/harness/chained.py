"""Serial-chained differenced timing — the measurement scaffold for TPUs
behind a dispatch tunnel.

A tunneled TPU pays a ~60-90 ms RPC round trip per dispatch, far larger
than one rep of any pattern here, so naive wall timing measures the tunnel.
The honest method (used by bench.py and the jax_sim backend):

- chain ``iters`` reps strictly serially inside ONE compiled program (the
  caller's ``chain_factory(iters)`` must make rep r+1 data-depend on rep r
  so XLA can neither fuse, hoist, nor elide iterations);
- force completion by reading back a checksum (block_until_ready alone does
  not guarantee execution through the tunnel);
- cancel the fixed dispatch overhead by differencing two chain lengths:
  ``per_rep = (T(big) - T(small)) / (big - small)``, best-of-``windows``
  per length, median over ``trials`` (differencing is noise-sensitive).
"""

from __future__ import annotations

import statistics
import time

from tpu_aggcomm.obs import trace

__all__ = ["differenced_per_rep", "differenced_trials",
           "differenced_round_times", "scanned_chain", "xor_word",
           "MAX_MEASURED_ROUNDS"]

#: Round-count guard for measured per-round times: one chain family is
#: compiled per round, so an n=1024 c=1 schedule (1024 rounds) would
#: compile for hours — callers reject such schedules upfront and point
#: at --profile-rounds instead.
MAX_MEASURED_ROUNDS = 64


def xor_word(tok, lane_dtype):
    """The chain perturbation, shared by every chained backend: a scalar
    token (a checksum of the previous rep's delivered state, mod 251)
    becomes a byte-replicated word in the carry's lane dtype, XORed into
    the send buffer. Byte-replication keeps the uint32-lane and uint8
    paths perturbing identical byte streams (carry-free), so chained
    numbers stay comparable across backends."""
    import jax.numpy as jnp

    from tpu_aggcomm.backends.pallas_local import rep_word
    return (rep_word(tok) if lane_dtype == jnp.uint32
            else tok.astype(jnp.uint8))


def _slim_cost(raw) -> dict | None:
    """The two HLO cost-analysis numbers worth keeping (flops, bytes
    accessed) from jax's Lowered.cost_analysis() — which returns a dict
    on current jax, or a per-device list of dicts on older versions."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    out = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed")):
        v = raw.get(key)
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out or None


def differenced_trials(chain_factory, send0, *, iters_small: int,
                       iters_big: int, trials: int = 3,
                       windows: int = 3) -> list[float]:
    """Per-trial per-rep seconds from differenced serial-chain timings.

    ``chain_factory(iters)`` returns a jitted ``chain(send0) -> array``
    running ``iters`` serially-dependent reps; ``send0`` is the on-device
    initial state. Both chain lengths are built (and therefore compiled)
    exactly once, then re-timed across trials.
    """
    import jax
    import jax.numpy as jnp

    if iters_big <= iters_small:
        raise ValueError("iters_big must exceed iters_small")
    checksum = jax.jit(lambda v: v.astype(jnp.uint32).sum())

    def timed(f) -> float:
        best = float("inf")
        for _ in range(windows):
            t0 = time.perf_counter()
            int(jax.device_get(checksum(f(send0))))  # forced completion
            best = min(best, time.perf_counter() - t0)
        return best

    f_small = chain_factory(iters_small)
    f_big = chain_factory(iters_big)
    # compile telemetry for the run ledger (obs/ledger.py): the explicit
    # lower() wall (host-side tracing/StableHLO emission — jitted chains
    # expose .lower; plain callables skip) plus guarded HLO cost stats.
    # Never lower().compile() here: the AOT path does not share the jit
    # dispatch cache, so it would compile the chain a SECOND time through
    # the tunnel just to time the first.
    from tpu_aggcomm.obs import ledger
    from tpu_aggcomm.resilience import classify_error, retry_call
    lower_s = cost = None
    if hasattr(f_big, "lower"):
        # telemetry is best-effort, but a swallowed failure must still be
        # classified and land in the ledger as a suppressed record — a
        # compile-class error here foreshadows the warmup failing too
        try:
            t0 = time.perf_counter()
            lowered = f_big.lower(send0)
            lower_s = time.perf_counter() - t0
            try:
                cost = _slim_cost(lowered.cost_analysis())
            except Exception as e:  # lint: broad-ok (cost_analysis optional across jax versions)
                cost = None
                rec = ledger.record_resilience(
                    "chained.cost_analysis", kind="suppressed",
                    error_class=classify_error(e),
                    error=f"{type(e).__name__}: {e}"[:500])
                trace.instant("ledger.resilience", **rec)
        except Exception as e:  # lint: broad-ok (compile telemetry best-effort; error ledgered)
            lower_s = None
            rec = ledger.record_resilience(
                "chained.lower", kind="suppressed",
                error_class=classify_error(e),
                error=f"{type(e).__name__}: {e}"[:500])
            trace.instant("ledger.resilience", **rec)

    def warmup() -> tuple[float, float]:
        with trace.span("chained.warmup", iters_small=iters_small,
                        iters_big=iters_big):
            t0 = time.perf_counter()
            int(jax.device_get(checksum(f_small(send0))))  # compile + warm
            w_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            int(jax.device_get(checksum(f_big(send0))))
            w_b = time.perf_counter() - t0
        return w_s, w_b

    # the warmup is the FIRST dispatch through the tunnel, so a flaky
    # link surfaces here; transients get bounded seeded retries (a retry
    # recompiles nothing — the jit cache survives the failed dispatch)
    warm_small, warm_big = retry_call(warmup, site="chained.warmup")
    rec = ledger.record_compile(
        f"chain(iters={iters_small}/{iters_big})",
        seconds=warm_small + warm_big, kind="compile+warmup",
        lower_seconds=lower_s, cost=cost,
        warmup_small_s=warm_small, warmup_big_s=warm_big)
    trace.instant("ledger.compile", **rec)
    per = []
    # noise budget: a jittery link can invert a diff; keep a floor so
    # small-trials windows=1 callers (chained pt2pt with -k 1) are not
    # one bad window away from aborting
    retries = max(trials, 3)
    while len(per) < trials:
        t_s = timed(f_small)
        t_b = timed(f_big)
        v = (t_b - t_s) / (iters_big - iters_small)
        # measured differencing evidence for the flight recorder: the
        # two chain wall times behind each accepted/rejected trial
        trace.instant("chained.trial", iters_small=iters_small,
                      iters_big=iters_big, t_small=t_s, t_big=t_b,
                      per_rep=v, accepted=v > 0)
        if v > 0:
            per.append(v)
        elif retries > 0:
            retries -= 1   # non-positive diff = pure noise artifact; redo
        else:
            raise RuntimeError(
                f"differenced timing unstable: T({iters_big})={t_b:.6f}s <= "
                f"T({iters_small})={t_s:.6f}s repeatedly — increase "
                f"iters_big or reduce link noise")
    # the accepted trial set, as one instant: obs/compare.py bootstraps
    # whole-rep deltas from this when both sides of a diff carry it
    trace.instant("chained.samples", iters_small=iters_small,
                  iters_big=iters_big, samples=list(per))
    return per


def differenced_per_rep(chain_factory, send0, *, iters_small: int,
                        iters_big: int, trials: int = 3,
                        windows: int = 3) -> float:
    """Median per-rep seconds over ``differenced_trials``."""
    return statistics.median(differenced_trials(
        chain_factory, send0, iters_small=iters_small, iters_big=iters_big,
        trials=trials, windows=windows))


def differenced_round_times(make_prefix_chain, send0, round_ids,
                            per_full: float, *, iters_small: int,
                            iters_big: int, trials: int = 3,
                            windows: int = 3, memo: dict | None = None
                            ) -> dict:
    """Shared tail of ``measure_round_times`` (jax_sim AND jax_shard —
    one definition, so the additivity contract the tests pin cannot
    drift between tiers): difference the round-prefix chains.

    ``make_prefix_chain(k)`` returns a ``chain_factory`` whose reps run
    only rounds 0..k-1 (full fidelity, same lowering and scaffold as the
    full rep); ``per_full`` is the full-rep differenced time. Round k's
    duration is the increment between consecutive prefix times; noise
    handling clamps increments at 0 and rescales so they sum EXACTLY to
    ``per_full`` (the uniform fallback covers the degenerate all-zero
    case). Returns ``{round id: seconds}`` in program order.

    ``memo`` (a caller-held dict, prefix index -> differenced seconds)
    shares the expensive per-prefix measurements with other consumers of
    the same prefix family (jax_sim's measure_round_splits times the
    identical P prefixes) — each prefix chain is compiled and timed at
    most once per schedule."""
    import numpy as np

    R = len(round_ids)
    if R == 1:
        return {round_ids[0]: per_full}
    bounds = []
    for k in range(1, R):
        if memo is not None and k in memo:
            bounds.append(memo[k])
            continue
        t = differenced_per_rep(
            make_prefix_chain(k), send0, iters_small=iters_small,
            iters_big=iters_big, trials=trials, windows=windows)
        if memo is not None:
            memo[k] = t
        bounds.append(t)
    bounds.append(per_full)
    inc = np.maximum(np.diff(np.asarray([0.0] + bounds)), 0.0)
    s = float(inc.sum())
    inc = inc * (per_full / s) if s > 0 else np.full(R, per_full / R)
    return dict(zip(round_ids, inc.tolist()))


def scanned_chain(rep, *, n_recv_slots: int, w: int, jdt, axis: str,
                  iters: int):
    """Shared scan scaffold for mesh-tier chained measurement (jax_ici):
    returns ``chain_local(send_local) -> send_local`` running ``iters``
    serially-dependent reps, rep r+1's send XOR-perturbed by a psum over
    rep r's delivered rows — so reps can neither fuse nor elide, and
    every device depends on every other device's previous rep.

    ``rep(send_local, recv0_local) -> recv_local`` is one device's whole
    rep (tables closed over). jax_sim/jax_shard keep layout-specific
    variants of this scaffold (dense rank-axis / compacted flat layouts);
    the token formula ``(psum(live rows) + r) % 251`` must stay identical
    across all of them so chained numbers remain comparable between
    backends."""
    import jax.numpy as jnp
    from jax import lax

    from tpu_aggcomm.compat import pcast

    def chain_local(send_local):
        def body(s, r):
            recv0 = pcast(
                jnp.zeros((n_recv_slots + 1, w), dtype=jdt),
                (axis,), to="varying")
            recv = rep(s, recv0)
            tok = (lax.psum(
                jnp.sum(recv[:n_recv_slots, 0].astype(jnp.uint32)),
                axis).astype(jnp.int32) + r) % 251
            return s ^ xor_word(tok, jdt), ()

        out, _ = lax.scan(body, send_local,
                          jnp.arange(iters, dtype=jnp.int32), unroll=1)
        return out

    return chain_local
