"""Point-to-point latency microbenchmark.

TPU-native analog of ``pt2pt_test`` (mpi_sendrecv_test.c:15-74): one
logical sender and one receiver; per rep, ``runs`` back-to-back transfers,
then a barrier; mean/std over ``ntimes`` reps; per-rep times written to
``sendrecv_results.csv``.

On a mesh with ≥2 devices the transfer is a real single-edge
``lax.ppermute`` 1→0 over ICI (or the virtual CPU mesh). The reference's
Issend/Irecv+Wait pair becomes one ppermute step — rendezvous and delivery
are one event on a lockstep collective backend; what's measured is the
per-message link latency, same quantity as the reference.

The ``runs`` transfers are chained serially inside one compiled program
via ``lax.scan`` (unroll=1) with an XOR perturbation per step, so compile
time is constant in ``runs`` (the reference sweeps -i into the thousands,
mpi_sendrecv_test.c:87) and XLA can neither batch nor elide steps.
``chained=True`` additionally replaces per-dispatch wall times with the
differenced two-chain-length measurement (harness/chained.py): through
the TPU tunnel a single dispatch measures the ~60-90 ms RPC, not the
link (VERDICT r1 item 8).

Deliberate non-reproduction: the reference main prints the integer
values of ``MPI_STATUS(ES)_IGNORE`` before running
(mpi_sendrecv_test.c:98-100) — a debug probe of MPI-implementation
pointer constants with no TPU analog; faking those numbers would be
parity theater, so the line is omitted.
"""

from __future__ import annotations

import time

import numpy as np

from tpu_aggcomm.compat import shard_map as _compat_shard_map

__all__ = ["pt2pt_statistics"]


def _make_chain_factory(mesh, data_size: int):
    """Chain factory over the lane layout for ``data_size``: payloads ride
    as uint32 lanes when 4-aligned (CLAUDE.md: u8 paths are 4-5x slower on
    TPU) and the perturbation is a byte-replicated word XOR."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tpu_aggcomm.backends.lanes import lane_layout

    _, jdt, _w = lane_layout(data_size)
    rep = 0x01010101 if jdt == jnp.uint32 else 1  # byte-replicated word

    def make_chain(steps: int):
        def local_fn(x):
            v = x[0]

            def body(v, r):
                v = lax.ppermute(v, "p", [(1, 0)])
                (v,) = lax.optimization_barrier((v,))
                # serial dependence: step k+1 sends step k's delivery,
                # XOR-perturbed so steps cannot fuse, hoist, or elide
                return v ^ r, ()

            xs = ((jnp.arange(steps, dtype=jnp.int32) % 251)
                  .astype(jdt) * jdt(rep))
            v, _ = lax.scan(body, v, xs, unroll=1)
            return v[None]

        return jax.jit(_compat_shard_map(local_fn, mesh=mesh, in_specs=P("p"),
                                     out_specs=P("p")))

    return make_chain


def pt2pt_statistics(data_size: int, ntimes: int, runs: int, *,
                     filename: str = "sendrecv_results.csv",
                     out=None, devices=None, chained: bool = False) -> dict:
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < 2:
        raise ValueError("pt2pt needs >= 2 devices "
                         "(the reference requires exactly 2 ranks)")
    mesh = Mesh(np.array(devs[:2]), ("p",))
    sharding = NamedSharding(mesh, P("p"))
    make_chain = _make_chain_factory(mesh, data_size)

    from tpu_aggcomm.backends.lanes import to_lanes
    buf = jax.device_put(
        to_lanes(np.arange(2 * data_size, dtype=np.uint8)
                 .reshape(2, data_size), data_size),
        sharding)

    if chained:
        # Each rep is an INDEPENDENT differenced window (one fresh
        # T(big)-T(small) pair), so the CSV rows are real samples and the
        # reported std is the actual spread of the link measurement — the
        # reference's output IS mean/std over reps
        # (mpi_sendrecv_test.c:52-64). Chains compile once; only the
        # re-timed windows repeat.
        from tpu_aggcomm.harness.chained import differenced_trials
        per_transfers = differenced_trials(make_chain, buf,
                                           iters_small=50, iters_big=1050,
                                           trials=max(ntimes, 1), windows=1)
        times = [p * runs for p in per_transfers]
        total = sum(times)
    else:
        fn = make_chain(runs)
        fn(buf).block_until_ready()  # warm-up compile
        times = []
        t_all = time.perf_counter()
        for _ in range(ntimes):
            t0 = time.perf_counter()
            fn(buf).block_until_ready()
            times.append(time.perf_counter() - t0)
        total = time.perf_counter() - t_all

    times_a = np.array(times)
    mean = float(times_a.mean())
    std = float(np.sqrt(np.maximum((times_a ** 2).mean() - mean * mean, 0.0)))
    if filename:
        with open(filename, "w") as fh:
            for t in times:
                fh.write(f"{t:.6f}\n")
    print(f"rank 0, mean = {mean:.6f}, std = {std:.6f}, ntimes = {ntimes}, "
          f"total_timing = {total:.6f}, mean*ntimes = {mean * ntimes:.6f}",
          file=out)
    return {"mean": mean, "std": std, "ntimes": ntimes, "total": total,
            "times": times}
