"""Point-to-point latency microbenchmark.

TPU-native analog of ``pt2pt_test`` (mpi_sendrecv_test.c:15-74): one
logical sender and one receiver; per rep, ``runs`` back-to-back transfers,
then a barrier; mean/std over ``ntimes`` reps; per-rep times written to
``sendrecv_results.csv``.

On a mesh with ≥2 devices the transfer is a real single-edge
``lax.ppermute`` 1→0 over ICI (or the virtual CPU mesh). The reference's
Issend/Irecv+Wait pair becomes one ppermute step — rendezvous and delivery
are one event on a lockstep collective backend; what's measured is the
per-message link latency, same quantity as the reference.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["pt2pt_statistics"]


def pt2pt_statistics(data_size: int, ntimes: int, runs: int, *,
                     filename: str = "sendrecv_results.csv",
                     out=None, devices=None) -> dict:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < 2:
        raise ValueError("pt2pt needs >= 2 devices "
                         "(the reference requires exactly 2 ranks)")
    mesh = Mesh(np.array(devs[:2]), ("p",))
    sharding = NamedSharding(mesh, P("p"))

    def local_fn(x):
        # rank 1 -> rank 0, `runs` sequential transfers (chained so XLA
        # cannot batch them into one)
        v = x[0]
        for _ in range(runs):
            v = lax.ppermute(v, "p", [(1, 0)])
            (v,) = lax.optimization_barrier((v,))
        return v[None]

    fn = jax.jit(jax.shard_map(local_fn, mesh=mesh, in_specs=P("p"),
                               out_specs=P("p")))

    buf = jax.device_put(
        np.arange(2 * data_size, dtype=np.uint8).reshape(2, data_size),
        sharding)
    fn(buf).block_until_ready()  # warm-up compile

    times = []
    t_all = time.perf_counter()
    for _ in range(ntimes):
        t0 = time.perf_counter()
        fn(buf).block_until_ready()
        times.append(time.perf_counter() - t0)
    total = time.perf_counter() - t_all

    times_a = np.array(times)
    mean = float(times_a.mean())
    std = float(np.sqrt(np.maximum((times_a ** 2).mean() - mean * mean, 0.0)))
    if filename:
        with open(filename, "w") as fh:
            for t in times:
                fh.write(f"{t:.6f}\n")
    print(f"rank 0, mean = {mean:.6f}, std = {std:.6f}, ntimes = {ntimes}, "
          f"total_timing = {total:.6f}, mean*ntimes = {mean * ntimes:.6f}",
          file=out)
    return {"mean": mean, "std": std, "ntimes": ntimes, "total": total,
            "times": times}
