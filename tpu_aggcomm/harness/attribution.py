"""Per-phase timer attribution for the compiled backends — the
*fenced-segment approximation*.

The reference brackets every phase of every method with ``MPI_Wtime``:
request posting, per-round recv Waitalls, the final send Waitall, barriers
(e.g. m=1 at mpi_test.c:1768-1815), then max-reduces the 5-field Timer
across ranks (mpi_test.c:2184). Post vs. wait attribution is *the* quantity
the benchmark studies. XLA compiles a whole rep (or a whole throttle round
in ``--profile-rounds`` mode) into one fused program step, so those phases
cannot be bracketed individually on the jax backends — only segment wall
times exist.

This module maps measured segment times back onto the schedule's own
TimerBucket structure. Every timed op of a rank's program contributes a
weight to its bucket:

- nonblocking posts (Issend/Isend/Irecv/signal sends charged to
  post_request, mpi_test.c:1770-1781) — a per-call constant,
  ``POST_COST_BYTES`` byte-equivalents: posting cost is software call
  overhead, independent of payload;
- Waitalls and blocking sends/recvs — the bytes their completion covers
  (transfer time scales with bytes in flight); pure-synchronization waits
  (0-byte signals, mpi_test.c:1283-1301) fall back to the per-call
  constant;
- barriers charged to a bucket (m=13 ``-b`` modes, mpi_test.c:861-874;
  m=17's in-round barrier charges post, mpi_test.c:1188) — the per-call
  constant (latency-bound global sync).

A measured time is then split per rank proportionally to that rank's
weights (per round when per-round segment times are available, over the
whole program otherwise). A rank's phase columns sum to the measured
total, with one reference-faithful exception: RECV_AND_SEND_WAIT ops
charge their share to BOTH wait columns (the reference brackets a
non-aggregator's Waitall once and adds it to both fields,
mpi_test.c:1505-1510), so those ranks' column sums can exceed total —
never undershoot. Ops the reference leaves untimed (TimerBucket.NONE,
e.g. m=7 senders' blocking Sends, mpi_test.c:1055-1114) stay zero here
too, exactly like the reference CSVs.

Calibration: ``POST_COST_BYTES = 512`` reproduces the reference README's
own post/waitall split — at n=32, a=14, d=2048, c=3 the README reports
post 0.011989 s of total 0.055115 s (README.md:47-49), a 21.8% post
share; the weight model gives an aggregator rank 46 posts * 512 = 23.5 KiB
of post weight against 94.2 KiB of wait weight = a 20% share.

This is an *approximation*, clearly labelled: it distributes honest
measured wall time by schedule structure; it does not measure each phase
independently (impossible inside one XLA program — SURVEY.md §7 hard
part 3). The native and local backends measure per-op host time directly
and do not use this module.
"""

from __future__ import annotations

import contextlib

import numpy as np

from tpu_aggcomm.core.schedule import OpKind, Schedule, TimerBucket
from tpu_aggcomm.harness.timer import Timer

__all__ = ["POST_COST_BYTES", "attribute_total", "attribute_rounds",
           "attribute_round_splits", "attribute_measured_split",
           "rank_round_weights", "tam_rank_weights", "attribute_tam_total",
           "attribute_tam_hops", "weights_for", "cell_recording",
           "CELL_LABELS"]

#: Per-call overhead of posting one nonblocking op / one pure-sync wait /
#: one barrier, expressed in byte-equivalents of transfer time. See module
#: docstring for the README-based calibration.
POST_COST_BYTES = 512

_NB_POSTS = (OpKind.ISEND, OpKind.ISSEND, OpKind.IRECV, OpKind.SIGNAL_SEND)
_BLOCKING = (OpKind.SEND, OpKind.RECV, OpKind.SENDRECV, OpKind.SIGNAL_RECV)


# ---------------------------------------------------------------------------
# Attribution cell stream — the flight recorder's view of this module.
#
# When a sink is active (obs tracing), every attribute_* call appends one
# "call" dict {"kind", "total", "cells"} whose cells mirror the call's
# Timer writes: ``(rank, round, bucket label, seconds)`` with the EXACT
# float handed to ``Timer.add`` (same expression, same order), so a trace
# re-aggregates float-exactly to the Timer columns (obs.trace.aggregate_run
# replays the additions in cell order). ``round`` is an int throttle
# round, -1 for a whole-rep charge, or a TAM hop label ("P2"/"P3"/"P4").
# Off by default: one ``is None`` test per attribution call.

_CELL_SINK: list | None = None

#: TimerBucket -> flight-recorder cell label. The label vocabulary the
#: obs layer analyzes (obs/trace.py BUCKET_FIELDS mirrors the values) —
#: public so analytics code names buckets without importing jax-adjacent
#: schedule enums at runtime.
CELL_LABELS = {
    TimerBucket.POST: "post",
    TimerBucket.SEND_WAIT: "send_wait",
    TimerBucket.RECV_WAIT: "recv_wait",
    TimerBucket.RECV_AND_SEND_WAIT: "recv+send_wait",
    TimerBucket.BARRIER: "barrier",
}
_CELL_LABELS = CELL_LABELS

#: cell round label for charges with no per-round decomposition
WHOLE_REP = -1


@contextlib.contextmanager
def cell_recording():
    """Capture the attribution cell stream of the enclosed block; yields
    the list the attribute_* calls append to. A delegating backend's
    inner attribution calls (pallas_dma -> jax_sim/jax_ici) land in the
    same capture — the runner wraps the whole ``backend.run``. Nested
    captures restore the previous sink on exit (innermost wins while
    active)."""
    global _CELL_SINK
    prev = _CELL_SINK
    _CELL_SINK = sink = []
    try:
        yield sink
    finally:
        _CELL_SINK = prev


def _open_call(kind: str, total: float):
    """One attribution call's record, or None when no sink is active."""
    if _CELL_SINK is None:
        return None
    call = {"kind": kind, "total": float(total), "cells": []}
    _CELL_SINK.append(call)
    return call


def _rank_charges(prog) -> list[tuple[int, TimerBucket, float]]:
    """(round, bucket, weight) for every timed op of one rank's program."""
    tok_bytes: dict[int, int] = {}
    charges: list[tuple[int, TimerBucket, float]] = []
    for op in prog:
        if op.kind in _NB_POSTS and op.token >= 0:
            tok_bytes[op.token] = op.nbytes
        if op.bucket is TimerBucket.NONE:
            continue
        if op.kind is OpKind.WAITALL:
            w = float(sum(tok_bytes.get(t, 0) for t in op.tokens))
            if w == 0.0:
                w = float(POST_COST_BYTES)   # pure-sync waitall
        elif op.kind is OpKind.BARRIER:
            w = float(POST_COST_BYTES)
        elif op.kind in _BLOCKING:
            w = float(max(op.nbytes, POST_COST_BYTES))
        else:                                # nonblocking post
            w = float(POST_COST_BYTES)
        charges.append((op.round, op.bucket, w))
    return charges


def rank_round_weights(schedule: Schedule):
    """Per rank: dict ``(round, bucket) -> weight`` over all timed ops."""
    out = []
    for prog in schedule.programs:
        acc: dict[tuple[int, TimerBucket], float] = {}
        for rnd, bucket, w in _rank_charges(prog):
            key = (rnd, bucket)
            acc[key] = acc.get(key, 0.0) + w
        out.append(acc)
    return out


_WEIGHT_CACHE: dict = {}


def weights_for(schedule):
    """Cached attribution weights for a schedule — THE one place that
    dispatches between the TAM byte-split, collective total-only (None),
    and op-program weights. Keyed by :func:`schedule_shape_key` (the
    shared cache-key contract — a shape-only key would silently attribute
    one method's time with another's bucket structure, e.g. m=4 vs m=11,
    which lower identically but charge different buckets)."""
    from tpu_aggcomm.core.schedule import schedule_shape_key
    if getattr(schedule, "assignment", None) is not None:
        key = (schedule.pattern, schedule.method_id, "tam")
        if key not in _WEIGHT_CACHE:
            _WEIGHT_CACHE[key] = tam_rank_weights(schedule)
        return _WEIGHT_CACHE[key]
    if schedule.collective:
        return None
    key = schedule_shape_key(schedule)
    if key not in _WEIGHT_CACHE:
        _WEIGHT_CACHE[key] = rank_round_weights(schedule)
    return _WEIGHT_CACHE[key]


def attribute_total(schedule, total_seconds: float,
                    weights=None) -> list[Timer]:
    """Split one measured whole-rep time per rank by aggregate op weights.

    Collective schedules (m=5/8) are total-only, exactly like the
    reference which brackets only the Alltoallw loop (mpi_test.c:624-648).
    TAM schedules use the byte-weighted phase split (attribute_tam_total).
    ``weights`` (rank_round_weights / tam_rank_weights output) may be
    precomputed once per schedule and passed in by backends that attribute
    many reps.
    """
    if getattr(schedule, "assignment", None) is not None:
        return attribute_tam_total(schedule, total_seconds, weights=weights)
    if schedule.collective:
        _open_call("collective-total", total_seconds)
        return [Timer(total_time=total_seconds)
                for _ in range(schedule.nprocs)]
    call = _open_call("total", total_seconds)
    timers = []
    for rank, acc in enumerate(weights if weights is not None
                               else rank_round_weights(schedule)):
        t = Timer(total_time=total_seconds)
        wsum = sum(acc.values())
        if wsum > 0:
            for (rnd, bucket), w in acc.items():
                s = total_seconds * w / wsum
                t.add(bucket, s)
                if call is not None:
                    call["cells"].append(
                        (rank, rnd, _CELL_LABELS[bucket], s))
        timers.append(t)
    return timers


def attribute_measured_split(schedule, post_seconds: float,
                             deliver_seconds: float,
                             weights=None) -> list[Timer]:
    """Per-rank timers from a MEASURED two-way rep decomposition.

    ``post_seconds`` / ``deliver_seconds`` come from chained
    prefix-differencing (jax_sim.measure_phase_split): the rep's
    message-preparation (gather) side and its delivery (scatter) side,
    each a differenced on-device measurement. Unlike
    :func:`attribute_total`, the post-vs-wait BOUNDARY is measured here —
    only the distribution of the delivery side among a rank's wait
    buckets still uses the op weights (which wait a rank was in during
    the delivery window is structural, not observable from outside the
    program).

    Per rank: the post column gets the measured gather time if the rank
    posts at all (on a fused program every rank shares the same wall
    windows — during the gather window the posting ranks are posting,
    everyone else is already waiting); the rest of the rank's total is
    distributed over its wait/barrier buckets by weight, with the
    RECV_AND_SEND_WAIT both-columns convention preserved.
    """
    total = post_seconds + deliver_seconds
    call = _open_call("measured-split", total)
    timers = []
    for rank, acc in enumerate(weights if weights is not None
                               else rank_round_weights(schedule)):
        t = Timer(total_time=total)
        post_w = sum(w for (_r, b), w in acc.items()
                     if b is TimerBucket.POST)
        waits = {k: w for k, w in acc.items()
                 if k[1] is not TimerBucket.POST}
        p_r = post_seconds if post_w > 0 else 0.0
        if p_r:
            t.add(TimerBucket.POST, p_r)
            if call is not None:
                call["cells"].append((rank, WHOLE_REP, "post", p_r))
        rest = total - p_r
        wsum = sum(waits.values())
        if wsum > 0:
            for (rnd, bucket), w in waits.items():
                s = rest * w / wsum
                t.add(bucket, s)
                if call is not None:
                    call["cells"].append(
                        (rank, rnd, _CELL_LABELS[bucket], s))
        elif post_w > 0:
            t.add(TimerBucket.POST, rest)   # post-only rank
            if call is not None:
                call["cells"].append((rank, WHOLE_REP, "post", rest))
        timers.append(t)
    return timers


def attribute_round_splits(schedule, splits: dict[int, tuple],
                           weights=None) -> list[Timer]:
    """Per-rank timers from a MEASURED 2-D decomposition
    (jax_sim.measure_round_splits): per round, both the preparation
    (post) and delivery windows are measurements; only the distribution
    of a round's delivery window among a rank's wait/barrier buckets is
    structural. Per rank per round: the post window lands on POST if the
    rank posts in that round (everyone shares wall windows on a fused
    program — non-posting ranks spend it waiting, so it joins their
    deliver share); the deliver share splits over the round's wait
    buckets by weight, preserving the RECV_AND_SEND_WAIT both-columns
    convention."""
    total = float(sum(p + d for p, d in splits.values()))
    call = _open_call("round-splits", total)
    timers = []
    for rank, acc in enumerate(weights if weights is not None
                               else rank_round_weights(schedule)):
        t = Timer(total_time=total)
        for rnd, (post, deliver) in splits.items():
            sel = {bucket: w for (r, bucket), w in acc.items() if r == rnd}
            if not sel:
                continue                    # idle round for this rank
            post_w = sel.get(TimerBucket.POST, 0.0)
            waits = {b: w for b, w in sel.items()
                     if b is not TimerBucket.POST}
            p_r = post if post_w > 0 else 0.0
            if p_r:
                t.add(TimerBucket.POST, p_r)
                if call is not None:
                    call["cells"].append((rank, rnd, "post", p_r))
            rest = (post - p_r) + deliver
            wsum = sum(waits.values())
            if wsum > 0:
                for bucket, w in waits.items():
                    s = rest * w / wsum
                    t.add(bucket, s)
                    if call is not None:
                        call["cells"].append(
                            (rank, rnd, _CELL_LABELS[bucket], s))
            elif post_w > 0:
                t.add(TimerBucket.POST, rest)   # post-only round
                if call is not None:
                    call["cells"].append((rank, rnd, "post", rest))
        timers.append(t)
    return timers


def attribute_rounds(schedule, round_times: dict[int, float],
                     weights=None) -> list[Timer]:
    """Split measured per-round segment times (``round id -> seconds``)
    per rank by that round's op weights; rounds a rank does not participate
    in charge it nothing (it was idle there). Every rank's total is the
    whole program's elapsed time (sum of segments), as in the reference
    where total_time brackets the full rep loop."""
    total = float(sum(round_times.values()))
    call = _open_call("rounds", total)
    timers = []
    for rank, acc in enumerate(weights if weights is not None
                               else rank_round_weights(schedule)):
        t = Timer(total_time=total)
        for rnd, dt in round_times.items():
            sel = {bucket: w for (r, bucket), w in acc.items() if r == rnd}
            wsum = sum(sel.values())
            if wsum > 0:
                for bucket, w in sel.items():
                    s = dt * w / wsum
                    t.add(bucket, s)
                    if call is not None:
                        call["cells"].append(
                            (rank, rnd, _CELL_LABELS[bucket], s))
        timers.append(t)
    return timers


# ---------------------------------------------------------------------------
# TAM (m=15/16): collective_write charges its intra-node phases (P1 size
# exchange, P2 gather, P4 delivery Waitalls) to recv_wait_all and the
# inter-node proxy exchange (P3 size handshake + payload Waitalls) to
# send_wait_all (lustre_driver_test.c:1015-1017, 1104-1106, 1162-1195,
# 1264-1266). post_request_time is never written by the engine — it stays
# 0 in reference TAM rows too.

def tam_rank_weights(tam) -> tuple[np.ndarray, np.ndarray]:
    """(recv_wait_weight, send_wait_weight) per rank, in bytes, from the
    proxy-engine route structure: a rank's P2 traffic (slabs packed to /
    gathered at its proxy) and P4 traffic (slabs delivered from its proxy)
    weigh recv_wait; a proxy's inter-node P3 runs weigh send_wait."""
    from tpu_aggcomm.core.pattern import Direction

    p = tam.pattern
    na = tam.assignment
    ds = p.data_size
    node_of = na.node_of
    if p.direction is Direction.ALL_TO_MANY:
        senders = list(range(p.nprocs))
        dests_of = lambda s: [int(r) for r in p.rank_list]   # noqa: E731
    else:
        senders = [int(r) for r in p.rank_list]
        dests_of = lambda s: list(range(p.nprocs))           # noqa: E731

    rw = np.zeros(p.nprocs, dtype=np.float64)
    sw = np.zeros(p.nprocs, dtype=np.float64)
    # proxy = lowest rank of each node (gather_node_information's rule,
    # lustre_driver_test.c:330-338)
    proxies: dict[int, int] = {}
    for r in range(p.nprocs):
        proxies.setdefault(int(node_of[r]), r)

    for s in senders:
        sp = proxies[int(node_of[s])]
        for d in dests_of(s):
            dp = proxies[int(node_of[d])]
            if s != sp:                    # P2: pack + gather at the proxy
                rw[s] += ds
                rw[sp] += ds
            if int(node_of[s]) != int(node_of[d]):   # P3: proxy <-> proxy
                sw[sp] += ds
                sw[dp] += ds
            if d != dp:                    # P4: proxy -> final destination
                rw[dp] += ds
                rw[d] += ds
    return rw, sw


def attribute_tam_total(tam, total_seconds: float,
                        weights=None) -> list[Timer]:
    """Per-rank byte-weighted split of a measured TAM rep time between
    recv_wait (intra-node P2/P4) and send_wait (inter-node P3)."""
    rw, sw = weights if weights is not None else tam_rank_weights(tam)
    call = _open_call("tam-total", total_seconds)
    timers = []
    for r in range(tam.pattern.nprocs):
        t = Timer(total_time=total_seconds)
        wsum = rw[r] + sw[r]
        if wsum > 0:
            t.recv_wait_all_time = total_seconds * rw[r] / wsum
            t.send_wait_all_time = total_seconds * sw[r] / wsum
            if call is not None:
                call["cells"].append(
                    (r, WHOLE_REP, "recv_wait", t.recv_wait_all_time))
                call["cells"].append(
                    (r, WHOLE_REP, "send_wait", t.send_wait_all_time))
        timers.append(t)
    return timers


def attribute_tam_hops(tam, p2: float, p3: float, p4: float,
                       weights=None) -> list[Timer]:
    """Per-rank timers from a MEASURED 3-hop TAM decomposition
    (jax_sim.measure_tam_hops) — unlike :func:`attribute_tam_total`, the
    phase BOUNDARIES are measurements; only which column a rank's wall
    window lands in is structural, and that mapping is the reference's
    own bracket placement: a proxy charges the inter-node exchange
    window to send_wait and its intra-node windows to recv_wait
    (l_d_t.c:1015-1017, 1162-1195, 1264-1266); a non-proxy spends the
    whole exchange window blocked in its delivery recv, so its P3 share
    lands in recv_wait (the reference's non-proxy ranks bracket no P3
    code at all — their time accrues in the P2/P4 Waitalls that
    surround it)."""
    rw, sw = weights if weights is not None else tam_rank_weights(tam)
    total = p2 + p3 + p4
    call = _open_call("tam-hops", total)
    timers = []
    for r in range(tam.pattern.nprocs):
        t = Timer(total_time=total)
        if sw[r] > 0:
            t.send_wait_all_time = p3
            t.recv_wait_all_time = p2 + p4
            if call is not None:
                # per-hop cells; sequential re-aggregation reproduces
                # p2 + p4 and p3 exactly
                call["cells"].append((r, "P2", "recv_wait", p2))
                call["cells"].append((r, "P3", "send_wait", p3))
                call["cells"].append((r, "P4", "recv_wait", p4))
        elif rw[r] > 0:
            t.recv_wait_all_time = total
            if call is not None:
                # non-proxy: blocked in recv across all three hop
                # windows; (p2 + p3) + p4 == total, left-to-right
                call["cells"].append((r, "P2", "recv_wait", p2))
                call["cells"].append((r, "P3", "recv_wait", p3))
                call["cells"].append((r, "P4", "recv_wait", p4))
        timers.append(t)
    return timers
