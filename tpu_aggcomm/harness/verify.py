"""Deterministic-fill payload generation and verification.

The reference's real correctness mechanism (SURVEY.md §4): every payload
byte is a pure function of (sender, offset, slot-seed, iteration) —
``MAP_DATA(a,b,c,d) = a+b+c+d`` truncated to char (mpi_test.c:23,71-92).
Send slab ``slot`` of rank ``r`` is filled with seed ``slot``; the checker
on the receiving side recomputes the expected bytes from the *sender's*
identity. In the reference the benchmark-path checks are commented out
(mpi_test.c:136-143, 205-219); here verification is a first-class
``--verify`` flag, never disabled by editing code.

The TAM engine uses a second map, ``MAP_DATA3(a,b,c) = 1+3a+5b+7c``
(lustre_driver_test.c:20,46-58), keyed by (sender, receiver-index, offset).
"""

from __future__ import annotations

import numpy as np

from tpu_aggcomm.core.pattern import AggregatorPattern, Direction

__all__ = ["fill_slab", "expected_recv", "make_send_slabs", "verify_recv",
           "recv_slot_counts", "fill_slab_tam", "VerificationError"]


def recv_slot_counts(p: "AggregatorPattern") -> list[int]:
    """How many recv slabs each rank owns — THE single definition of the
    recv-buffer layout (prepare_* analog, mpi_test.c:94-133/162-202):
    all-to-many aggregators own nprocs slabs (others none); many-to-all
    ranks all own cb_nodes slabs. Backends must derive their buffers from
    this so they cannot diverge from the verifier."""
    agg_index = p.agg_index
    if p.direction is Direction.ALL_TO_MANY:
        return [p.nprocs if agg_index[r] >= 0 else 0 for r in range(p.nprocs)]
    return [p.cb_nodes] * p.nprocs


class VerificationError(AssertionError):
    pass


def fill_slab(rank: int, size: int, seed: int, iter_: int) -> np.ndarray:
    """MAP_DATA(rank, offset, seed, iter) as uint8 (mpi_test.c:23, 71-77)."""
    return ((rank + seed + iter_ + np.arange(size, dtype=np.int64)) % 256
            ).astype(np.uint8)


def fill_slab_tam(sender: int, recv_index: int, size: int) -> np.ndarray:
    """MAP_DATA(a,b,c) = 1+3a+5b+7c of the TAM engine
    (lustre_driver_test.c:20): a = sender, b = receiver index, c = offset."""
    return ((1 + 3 * sender + 5 * recv_index
             + 7 * np.arange(size, dtype=np.int64)) % 256).astype(np.uint8)


def make_send_slabs(p: AggregatorPattern, iter_: int) -> list[np.ndarray | None]:
    """Per-rank send slab matrices, shape (nslots, data_size) uint8.

    ALL_TO_MANY: every rank has cb_nodes slots (slot = aggregator index,
    mpi_test.c:193-198). MANY_TO_ALL: aggregators have nprocs slots (slot =
    destination rank, mpi_test.c:106-110); non-aggregators have None.
    """
    out: list[np.ndarray | None] = []
    agg_index = p.agg_index
    for rank in range(p.nprocs):
        if p.direction is Direction.ALL_TO_MANY:
            nslots = p.cb_nodes
        elif agg_index[rank] >= 0:
            nslots = p.nprocs
        else:
            out.append(None)
            continue
        slabs = np.stack([fill_slab(rank, p.data_size, s, iter_)
                          for s in range(nslots)])
        out.append(slabs)
    return out


def expected_recv(p: AggregatorPattern, rank: int, iter_: int) -> np.ndarray | None:
    """The full expected recv slab matrix for ``rank`` (or None if this rank
    receives nothing). Mirrors the commented-out reference checks:
    all-to-many aggregators check slab ``src`` against fill(src, seed=myindex)
    (mpi_test.c:213-217); many-to-all ranks check slab ``i`` against
    fill(rank_list[i], seed=rank) (mpi_test.c:138-141)."""
    agg_index = p.agg_index
    if p.direction is Direction.ALL_TO_MANY:
        if agg_index[rank] < 0:
            return None
        myindex = int(agg_index[rank])
        return np.stack([fill_slab(src, p.data_size, myindex, iter_)
                         for src in range(p.nprocs)])
    return np.stack([fill_slab(int(p.rank_list[i]), p.data_size, rank, iter_)
                     for i in range(p.cb_nodes)])


def verify_recv(p: AggregatorPattern, recv_bufs: list[np.ndarray | None],
                iter_: int) -> None:
    """Raise VerificationError if any delivered slab mismatches the
    deterministic fill."""
    for rank in range(p.nprocs):
        exp = expected_recv(p, rank, iter_)
        if exp is None:
            continue
        got = recv_bufs[rank]
        if got is None:
            raise VerificationError(f"rank {rank}: expected recv data, got none")
        if got.shape != exp.shape:
            raise VerificationError(
                f"rank {rank}: recv shape {got.shape} != expected {exp.shape}")
        bad = np.nonzero(~(got == exp).all(axis=1))[0]
        if len(bad):
            s = int(bad[0])
            raise VerificationError(
                f"rank {rank}: wrong payload in slab {s}: "
                f"got {got[s][:8]}... expected {exp[s][:8]}...")
