"""Deterministic-fill payload generation and verification.

The reference's real correctness mechanism (SURVEY.md §4): every payload
byte is a pure function of (sender, offset, slot-seed, iteration) —
``MAP_DATA(a,b,c,d) = a+b+c+d`` truncated to char (mpi_test.c:23,71-92).
Send slab ``slot`` of rank ``r`` is filled with seed ``slot``; the checker
on the receiving side recomputes the expected bytes from the *sender's*
identity. In the reference the benchmark-path checks are commented out
(mpi_test.c:136-143, 205-219); here verification is a first-class
``--verify`` flag, never disabled by editing code.

The TAM engine uses a second map, ``MAP_DATA3(a,b,c) = 1+3a+5b+7c``
(lustre_driver_test.c:20,46-58), keyed by (sender, receiver-index, offset).
"""

from __future__ import annotations

import numpy as np

from tpu_aggcomm.core.pattern import AggregatorPattern, Direction

__all__ = ["fill_slab", "expected_recv", "make_send_slabs", "verify_recv",
           "recv_slot_counts", "slot_shapes", "fill_slab_tam",
           "VerificationError"]


def slot_shapes(p: "AggregatorPattern") -> tuple[int, int]:
    """(n_send_slots, n_recv_slots) per rank — THE single definition of the
    slab-matrix shapes (prepare_* analog, mpi_test.c:94-133/162-202):
    all-to-many ranks send cb_nodes slabs and aggregators receive nprocs;
    many-to-all aggregators send nprocs slabs and ranks receive cb_nodes."""
    if p.direction is Direction.ALL_TO_MANY:
        return p.cb_nodes, p.nprocs
    return p.nprocs, p.cb_nodes


def recv_slot_counts(p: "AggregatorPattern") -> list[int]:
    """How many recv slabs each rank owns — THE single definition of the
    recv-buffer layout (prepare_* analog, mpi_test.c:94-133/162-202):
    all-to-many aggregators own nprocs slabs (others none); many-to-all
    ranks all own cb_nodes slabs. Backends must derive their buffers from
    this so they cannot diverge from the verifier."""
    agg_index = p.agg_index
    if p.direction is Direction.ALL_TO_MANY:
        return [p.nprocs if agg_index[r] >= 0 else 0 for r in range(p.nprocs)]
    return [p.cb_nodes] * p.nprocs


class VerificationError(AssertionError):
    pass


def fill_slab(rank: int, size: int, seed: int, iter_: int) -> np.ndarray:
    """MAP_DATA(rank, offset, seed, iter) as uint8 (mpi_test.c:23, 71-77)."""
    return ((rank + seed + iter_ + np.arange(size, dtype=np.int64)) % 256
            ).astype(np.uint8)


def fill_slab_tam(sender: int, recv_index: int, size: int) -> np.ndarray:
    """MAP_DATA(a,b,c) = 1+3a+5b+7c of the TAM engine
    (lustre_driver_test.c:20): a = sender, b = receiver index, c = offset."""
    return ((1 + 3 * sender + 5 * recv_index
             + 7 * np.arange(size, dtype=np.int64)) % 256).astype(np.uint8)


def make_send_slabs(p: AggregatorPattern, iter_: int) -> list[np.ndarray | None]:
    """Per-rank send slab matrices, shape (nslots, data_size) uint8.

    ALL_TO_MANY: every rank has cb_nodes slots (slot = aggregator index,
    mpi_test.c:193-198). MANY_TO_ALL: aggregators have nprocs slots (slot =
    destination rank, mpi_test.c:106-110); non-aggregators have None.
    """
    agg_index = p.agg_index
    ar = np.arange(p.data_size, dtype=np.int64)
    if p.direction is Direction.ALL_TO_MANY:
        # one broadcast for the whole payload: (nprocs, cb_nodes, size)
        ranks = np.arange(p.nprocs, dtype=np.int64)
        seeds = np.arange(p.cb_nodes, dtype=np.int64)
        big = ((ranks[:, None, None] + seeds[None, :, None] + iter_ + ar)
               % 256).astype(np.uint8)
        return [big[r] for r in range(p.nprocs)]
    seeds = np.arange(p.nprocs, dtype=np.int64)
    out: list[np.ndarray | None] = []
    for rank in range(p.nprocs):
        if agg_index[rank] < 0:
            out.append(None)
            continue
        out.append(((rank + seeds[:, None] + iter_ + ar) % 256)
                   .astype(np.uint8))
    return out


def expected_recv(p: AggregatorPattern, rank: int, iter_: int) -> np.ndarray | None:
    """The full expected recv slab matrix for ``rank`` (or None if this rank
    receives nothing). Mirrors the commented-out reference checks:
    all-to-many aggregators check slab ``src`` against fill(src, seed=myindex)
    (mpi_test.c:213-217); many-to-all ranks check slab ``i`` against
    fill(rank_list[i], seed=rank) (mpi_test.c:138-141)."""
    agg_index = p.agg_index
    ar = np.arange(p.data_size, dtype=np.int64)
    if p.direction is Direction.ALL_TO_MANY:
        if agg_index[rank] < 0:
            return None
        myindex = int(agg_index[rank])
        srcs = np.arange(p.nprocs, dtype=np.int64)
        return ((srcs[:, None] + myindex + iter_ + ar) % 256).astype(np.uint8)
    return ((np.asarray(p.rank_list, dtype=np.int64)[:, None] + rank + iter_
             + ar) % 256).astype(np.uint8)


def verify_recv(p: AggregatorPattern, recv_bufs: list[np.ndarray | None],
                iter_: int) -> None:
    """Raise VerificationError if any delivered slab mismatches the
    deterministic fill. The MANY_TO_ALL side (every rank receives) is
    checked with one broadcast comparison so flagship rank counts
    (16,384 ranks, script_theta_*.sh:3) verify in milliseconds."""
    if p.direction is Direction.MANY_TO_ALL:
        ar = np.arange(p.data_size, dtype=np.int64)
        ranks = np.arange(p.nprocs, dtype=np.int64)
        exp_all = ((np.asarray(p.rank_list)[None, :, None]
                    + ranks[:, None, None] + iter_ + ar) % 256
                   ).astype(np.uint8)         # (nprocs, cb_nodes, size)
        exp_shape = exp_all.shape[1:]
        for r in range(p.nprocs):
            if recv_bufs[r] is None:
                raise VerificationError(
                    f"rank {r}: expected recv data, got none")
            if recv_bufs[r].shape != exp_shape:
                raise VerificationError(
                    f"rank {r}: recv shape {recv_bufs[r].shape} != "
                    f"expected {exp_shape}")
        got_all = np.stack(recv_bufs)
        ok = (got_all == exp_all).all(axis=2)
        if not ok.all():
            rank, s = (int(x) for x in np.argwhere(~ok)[0])
            raise VerificationError(
                f"rank {rank}: wrong payload in slab {s}: "
                f"got {got_all[rank, s][:8]}... "
                f"expected {exp_all[rank, s][:8]}...")
        return
    for rank in range(p.nprocs):
        exp = expected_recv(p, rank, iter_)
        if exp is None:
            continue
        got = recv_bufs[rank]
        if got is None:
            raise VerificationError(f"rank {rank}: expected recv data, got none")
        if got.shape != exp.shape:
            raise VerificationError(
                f"rank {rank}: recv shape {got.shape} != expected {exp.shape}")
        bad = np.nonzero(~(got == exp).all(axis=1))[0]
        if len(bad):
            s = int(bad[0])
            raise VerificationError(
                f"rank {rank}: wrong payload in slab {s}: "
                f"got {got[s][:8]}... expected {exp[s][:8]}...")
