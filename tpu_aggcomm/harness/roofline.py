"""Bytes-touched roofline model for the compiled rank-axis lowerings
(VERDICT r4 item 4).

The question the model answers: is a measured per-rep time the HBM
memory-bound floor, or multiples off it? The reference publishes raw
times with no floor analysis (README.md:40-71); on a TPU the floor is
computable because every rep is a fixed set of arena passes. One rep of
a round-structured schedule touches:

- ``gather_read``    — every payload edge's slab read from the send
  arena (sum over rounds of E_r * d);
- ``scatter_write``  — the same bytes landed in the recv arena;
- ``zero_init``      — the recv arena zeroed once per rep (XLA may fold
  this into the first scatter; kept as its own term because the
  measured programs materialize the zeros when rounds are fenced);
- ``intermediate``   — the packed blocks materialized around the
  ``lax.all_to_all`` boundary in the jax_shard block lowering: one
  write + one read of the round's padded block volume (ndev^2 * M_r *
  d, padding included — the collective is a fusion barrier, so these
  are real HBM passes). Zero for jax_sim (no collective inside a rep)
  and zero for jax_shard on a 1-device mesh since the single-dev round
  specialization (``_apply_block_round(single_dev=True)``) skips the
  identity all_to_all and its mask, letting XLA fuse the round into one
  gather-scatter pass;
- ``refence_walks`` — the conservative extra for fenced multi-round
  programs: every ``optimization_barrier`` / scan-carry step may force
  a full recv-arena copy (read + write), which is exactly the "each
  round re-walks the full recv arena" behavior RESULTS_TPU.md measured
  (the -c 2048 cell costing 4x the unthrottled cell at the same
  pattern volume).

``total(fenced=False)`` is the optimistic floor (rounds touch only
their own bytes); ``total(fenced=True)`` the conservative bound. A
measured time between the two floors at HBM bandwidth is memory-bound;
a time above the fenced bound is overhead (index walks, small rows,
dispatch) — the distinction the flagship analysis needs.

Chained measurement adds ``chain_overhead_bytes`` per rep (the XOR
perturbation's send-arena read+write and the checksum's recv read) —
exposed separately so differenced chain numbers can be compared
honestly against run() numbers.

Scope: these are HBM floors. A pattern whose whole working set is
VMEM-resident can legitimately beat them — the README config's 1.73
µs/rep on the fused Pallas kernel sits below its 4.9 µs HBM floor for
exactly that reason (128 KiB of arenas never leave VMEM inside the
chained program). The floors bind at flagship sizes, where arenas are
hundreds of MB to GB.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["RepBytes", "rep_bytes", "tam_rep_bytes",
           "chain_overhead_bytes", "floor_seconds", "HBM_V5E_GBPS"]

#: TPU v5e (the chip behind the tunnel) peak HBM bandwidth, GB/s
#: (public spec: 819 GB/s per chip).
HBM_V5E_GBPS = 819.0


@dataclass
class RepBytes:
    """Per-rep bytes-touched breakdown (all plain ints, host-computed)."""

    gather_read: int
    scatter_write: int
    zero_init: int
    intermediate: int
    refence_walks: int
    rounds: int
    edges: int

    def total(self, *, fenced: bool = False) -> int:
        t = (self.gather_read + self.scatter_write + self.zero_init
             + self.intermediate)
        return t + (self.refence_walks if fenced else 0)

    def floor_seconds(self, bandwidth_gbps: float = HBM_V5E_GBPS, *,
                      fenced: bool = False) -> float:
        return floor_seconds(self.total(fenced=fenced), bandwidth_gbps)


def floor_seconds(nbytes: int, bandwidth_gbps: float = HBM_V5E_GBPS
                  ) -> float:
    """Seconds to move ``nbytes`` at ``bandwidth_gbps`` GB/s."""
    return nbytes / (bandwidth_gbps * 1e9)


def _recv_arena_bytes(p, lowering: str, ndev: int) -> int:
    """Recv-arena footprint of the lowering (incl. trash rows)."""
    from tpu_aggcomm.harness.verify import recv_slot_counts, slot_shapes

    if lowering == "jax_sim":
        _, n_recv_slots = slot_shapes(p)
        return p.nprocs * (n_recv_slots + 1) * p.data_size
    counts = np.asarray(recv_slot_counts(p))
    from tpu_aggcomm.backends.jax_shard import recv_layout
    bsz = -(-p.nprocs // ndev)
    _, F = recv_layout(counts, ndev, bsz)
    return ndev * F * p.data_size


def rep_bytes(schedule, *, lowering: str = "jax_sim", ndev: int = 1
              ) -> RepBytes:
    """Model one rep of ``schedule`` under a lowering.

    ``lowering``: "jax_sim" (dense rank-axis, one device) or "jax_shard"
    (compacted block lowering over ``ndev`` devices; ndev == 1 is the
    single-chip flagship tier with the fused single-dev rounds). TAM
    schedules are modeled by :func:`tam_rep_bytes` (the 3-hop relay has
    a different materialization structure)."""
    from tpu_aggcomm.backends.jax_shard import _schedule_edges
    from tpu_aggcomm.tam.engine import TamMethod

    if isinstance(schedule, TamMethod):
        raise ValueError("TAM reps are modeled by tam_rep_bytes, "
                         "not the rank-axis rep_bytes")
    if lowering not in ("jax_sim", "jax_shard"):
        raise ValueError(f"unknown lowering {lowering!r}")
    if lowering == "jax_sim" and ndev != 1:
        raise ValueError("jax_sim is single-device by construction")

    p = schedule.pattern
    d = p.data_size
    edges = _schedule_edges(schedule)
    nedges = len(edges)
    round_ids = sorted({int(r) for r in edges[:, 4]}) if nedges else []
    R = max(len(round_ids), 1)

    gather_read = nedges * d
    scatter_write = nedges * d
    zero_init = _recv_arena_bytes(p, lowering, ndev)

    intermediate = 0
    if lowering == "jax_shard" and ndev > 1:
        # padded block volume around the all_to_all, one write + one read
        bsz = -(-p.nprocs // ndev)
        for r in round_ids:
            sel = edges[edges[:, 4] == r]
            pair = (sel[:, 0] // bsz) * ndev + (sel[:, 1] // bsz)
            M = int(np.bincount(pair, minlength=ndev * ndev).max())
            intermediate += 2 * ndev * ndev * M * d

    # every inter-round fence may re-walk the recv arena (read + write)
    refence_walks = 2 * (R - 1) * zero_init
    return RepBytes(gather_read=gather_read, scatter_write=scatter_write,
                    zero_init=zero_init, intermediate=intermediate,
                    refence_walks=refence_walks, rounds=R, edges=nedges)


def tam_rep_bytes(tam) -> RepBytes:
    """Model one rep of the single-chip 3-hop TAM route (jax_sim
    ``_tam_rep``): the staged and exchanged slab arrays are REAL
    materializations (each hop is a fenced program step), so they count
    as ``intermediate`` — one write + one read of E slab rows per hop
    boundary — exactly like the block lowering's all_to_all blocks. The
    measured hop times (``measure_tam_hops``) are judged against the
    floors this returns: p3's floor is one intermediate pass, p2/p4's
    the gather/scatter plus their share of the zero-init."""
    from tpu_aggcomm.backends.jax_sim import _tam_tables
    from tpu_aggcomm.tam.engine import TamMethod

    if not isinstance(tam, TamMethod):
        raise ValueError("tam_rep_bytes models TAM schedules; use "
                         "rep_bytes for round-structured/collective ones")
    p = tam.pattern
    d = p.data_size
    stage_idx, exch_idx, _dst, _slot = _tam_tables(tam)
    E = len(stage_idx)
    assert len(exch_idx) == E
    zero_init = _recv_arena_bytes(p, "jax_sim", 1)
    # P2 reads the send arena rows once, P4 writes the recv arena rows
    # once; the two fenced hop boundaries each materialize E rows
    # (staged write+read, exch write+read)
    return RepBytes(gather_read=E * d, scatter_write=E * d,
                    zero_init=zero_init, intermediate=2 * 2 * E * d,
                    refence_walks=0, rounds=3, edges=E)


def chain_overhead_bytes(schedule, *, lowering: str = "jax_sim",
                         ndev: int = 1) -> int:
    """Extra bytes per rep added by the chained-measurement scaffold: the
    XOR perturbation reads + writes the whole send arena and the checksum
    reads the recv arena's live rows."""
    from tpu_aggcomm.harness.verify import slot_shapes

    p = schedule.pattern
    if lowering == "jax_sim":
        n_send_slots, _ = slot_shapes(p)
        send_arena = p.nprocs * n_send_slots * p.data_size
    else:
        from tpu_aggcomm.backends.jax_shard import recv_layout
        from tpu_aggcomm.core.pattern import Direction
        n = p.nprocs
        if p.direction is Direction.ALL_TO_MANY:
            scounts = np.full(n, p.cb_nodes, dtype=np.int64)
        else:
            scounts = np.where(np.asarray(p.agg_index) >= 0, n, 0)
        bsz = -(-n // ndev)
        _, Fs = recv_layout(scounts, ndev, bsz)
        send_arena = ndev * Fs * p.data_size
    return 2 * send_arena + _recv_arena_bytes(p, lowering, ndev)
