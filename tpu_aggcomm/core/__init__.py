"""Pure, device-free pattern/topology/schedule layer."""
