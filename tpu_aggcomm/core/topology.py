"""Node topology: simulated and discovered rank→node maps.

The reference runs its hierarchical engine either on a *simulated* topology
(``static_node_assignment``, lustre_driver_test.c:359-429 — node structure
fabricated arithmetically so multi-node behavior is testable on any
launcher) or a *discovered* one (``gather_node_information``,
lustre_driver_test.c:267-344 — hostname Allgather + sort).

TPU-native equivalents:

- :func:`static_node_assignment` — same arithmetic fabrication, used for
  tests and for mapping logical ranks onto a 2-axis (node × local) mesh.
- :func:`mesh_node_assignment` — discovery from a live ``jax.sharding.Mesh``
  / device list, grouping devices by host process (the ICI-slice analog of
  "ranks sharing a node").

Unlike the reference (per-rank output views), we compute the global
assignment once; per-rank views are cheap numpy slices.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["NodeAssignment", "static_node_assignment", "mesh_node_assignment"]


@dataclass(frozen=True)
class NodeAssignment:
    """Global rank→node structure.

    Fields mirror the reference's six outputs (lustre_driver_test.c:359):
    ``nnodes`` = nrecvs, ``node_of`` = process_node_list, ``proxies`` =
    global_receivers (one designated rank per node, the lowest-numbered),
    ``node_sizes`` = node_size, and ``local_ranks(node)`` replaces the
    per-rank local_ranks array.
    """

    nprocs: int
    nnodes: int
    node_of: np.ndarray     # shape (nprocs,): rank -> node id
    proxies: np.ndarray     # shape (nnodes,): node -> proxy rank (lowest on node)
    node_sizes: np.ndarray  # shape (nnodes,): ranks per node

    def __post_init__(self):
        if len(self.node_of) != self.nprocs:
            raise ValueError("node_of must have nprocs entries")
        if int(self.node_sizes.sum()) != self.nprocs:
            raise ValueError("node_sizes must sum to nprocs")

    def local_ranks(self, node: int) -> np.ndarray:
        """Sorted ranks living on ``node`` (reference: local_ranks array)."""
        return np.nonzero(self.node_of == node)[0]

    def proxy_of(self, rank: int) -> int:
        """The proxy (lowest local rank) of ``rank``'s node."""
        return int(self.proxies[int(self.node_of[rank])])

    def is_proxy(self, rank: int) -> bool:
        return self.proxy_of(rank) == rank


def static_node_assignment(nprocs: int, nprocs_node: int,
                           kind: int = 0) -> NodeAssignment:
    """Fabricate a node map from (nprocs, ranks-per-node) arithmetically.

    kind 0: contiguous blocks — node = rank // nprocs_node (the reference's
    ``else`` branch). kind 1: round-robin — the first ``remainder * nnodes``
    ranks cycle over all nodes, the rest cycle over the first
    ``nprocs // nprocs_node`` nodes (reference: lustre_driver_test.c:365-402).
    The last node may be smaller when nprocs_node does not divide nprocs.
    """
    if nprocs_node < 1 or nprocs_node > nprocs:
        raise ValueError("nprocs_node must be in [1, nprocs]")
    nnodes = (nprocs + nprocs_node - 1) // nprocs_node
    node_of = np.empty(nprocs, dtype=np.int64)
    if kind == 1:
        remainder = nprocs % nprocs_node
        temp = nprocs // nprocs_node
        for i in range(nprocs):
            if i < remainder * nnodes:
                node_of[i] = i % nnodes
            else:
                node_of[i] = (i - remainder * nnodes) % temp
    else:
        node_of[:] = np.arange(nprocs) // nprocs_node
    node_sizes = np.bincount(node_of, minlength=nnodes).astype(np.int64)
    proxies = np.array(
        [np.nonzero(node_of == n)[0][0] for n in range(nnodes)],
        dtype=np.int64)
    return NodeAssignment(nprocs=nprocs, nnodes=nnodes, node_of=node_of,
                          proxies=proxies, node_sizes=node_sizes)


def mesh_node_assignment(devices=None) -> NodeAssignment:
    """Discover the node map from live JAX devices.

    The TPU analog of hostname discovery (lustre_driver_test.c:267-344):
    logical rank = position in ``devices`` (flattened mesh order), "node" =
    the device's host process (``device.process_index``) — the boundary at
    which transfers stop being intra-host ICI-slice traffic. Falls back to
    one node if all devices share a process (single-host, the common case).
    """
    import jax

    if devices is None:
        devices = jax.devices()
    devices = list(np.asarray(devices).reshape(-1))
    nprocs = len(devices)
    proc_ids = sorted({d.process_index for d in devices})
    proc_to_node = {p: i for i, p in enumerate(proc_ids)}
    node_of = np.array([proc_to_node[d.process_index] for d in devices],
                       dtype=np.int64)
    nnodes = len(proc_ids)
    node_sizes = np.bincount(node_of, minlength=nnodes).astype(np.int64)
    proxies = np.array(
        [np.nonzero(node_of == n)[0][0] for n in range(nnodes)],
        dtype=np.int64)
    return NodeAssignment(nprocs=nprocs, nnodes=nnodes, node_of=node_of,
                          proxies=proxies, node_sizes=node_sizes)
