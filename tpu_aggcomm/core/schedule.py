"""Schedule intermediate representation.

A *schedule* is what a reference "method" (the ``-m`` switch,
mpi_test.c:2132-2134) compiles to: one op-program per rank, describing
exactly which messages are posted, in which order, with which completion
(waitall) structure, which synchronization mode (eager / rendezvous /
blocking), and which timer bucket each phase charges.

Two views of the same schedule:

- **per-rank op programs** (`Schedule.programs`) — the ground truth, faithful
  to the reference's per-rank MPI call sequences. The local oracle and the
  native C++ runtime execute these directly, preserving rendezvous and
  blocking semantics.
- **global round/edge view** (`Schedule.rounds()`) — edges grouped by the
  round in which their transfer completes. The JAX/ICI backend lowers each
  round to masked collective steps (ppermute batches / all_to_all); this is
  the TPU-idiomatic reinterpretation: MPI's per-rank progress becomes
  mesh-global program steps. The semantic difference (per-rank unordered
  completion vs. deterministic global steps) is intentional and documented —
  see SURVEY.md §7 "hard parts" (5).

Op vocabulary (mirrors the reference's L0 call set, SURVEY.md §5.8):
ISEND (eager, MPI_Isend), ISSEND (rendezvous, MPI_Issend), IRECV, SEND/RECV
(blocking), SENDRECV (paired blocking), WAITALL (token subset), BARRIER,
COPY (self-edge memcpy), SIGNAL_SEND/SIGNAL_RECV (0-byte handshake on a
separate channel — the dup'ed signal_comm of mpi_test.c:1252).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np

from tpu_aggcomm.core.pattern import AggregatorPattern

__all__ = ["OpKind", "Op", "Schedule", "TimerBucket",
           "ScheduleAsymmetryError", "barrier_signatures",
           "check_barrier_symmetry", "barrier_rounds_of",
           "schedule_shape_key"]


class ScheduleAsymmetryError(AssertionError):
    """The schedule's barrier structure differs across ranks.

    Rank 0's barrier signature stands in for every rank's in
    :func:`barrier_rounds_of` and :func:`schedule_shape_key` — an
    asymmetric schedule would deadlock at runtime (generation-matched
    n-rank joins) AND alias cache entries it must not share, so both
    entry points refuse it instead of assuming symmetry.
    """


def barrier_signatures(schedule) -> list:
    """Per-rank barrier signature: the tuple of round tags of each
    rank's BARRIER ops, in program order. Equal across ranks iff the
    schedule is barrier-SPMD-symmetric."""
    progs = getattr(schedule, "programs", None) or ()
    return [tuple(op.round for op in prog if op.kind is OpKind.BARRIER)
            for prog in progs]


def check_barrier_symmetry(schedule) -> tuple:
    """Prove every rank shares rank 0's barrier signature and return it.

    Raises :class:`ScheduleAsymmetryError` naming the first divergent
    rank otherwise. O(total ops) — cheap next to anything a signature
    consumer does with the result.
    """
    sigs = barrier_signatures(schedule)
    ref = sigs[0] if sigs else ()
    for rank, sig in enumerate(sigs):
        if sig != ref:
            raise ScheduleAsymmetryError(
                f"{getattr(schedule, 'name', schedule)}: barrier "
                f"structure is not SPMD-symmetric — rank {rank} has "
                f"signature {sig}, rank 0 has {ref}; refusing to reuse "
                f"rank 0's signature for shape keys")
    return ref


def barrier_rounds_of(schedule) -> dict:
    """round -> number of MPI_Barrier ops in it. Barrier structure being
    SPMD-symmetric is CHECKED (:func:`check_barrier_symmetry`), not
    assumed: an asymmetric schedule raises rather than silently
    reporting rank 0's view."""
    out: dict[int, int] = {}
    for rnd in check_barrier_symmetry(schedule):
        out[rnd] = out.get(rnd, 0) + 1
    return out


def schedule_shape_key(schedule) -> tuple:
    """THE cache-key contract for anything derived from a schedule's shape
    (compiled programs, attribution weights): ``(pattern, method_id,
    collective, barrier signature)``. The method id is load-bearing —
    methods can lower to identical comm shapes while charging different
    timer buckets (m=4 vs m=11); the barrier signature is the one
    schedule-shape input not captured by (pattern, method_id): m=13's
    ``-b`` modes compile different programs from the same pattern.
    ``variant`` (the canonical fault spec stamped by faults/repair.py)
    keeps repaired/fault-injected programs from aliasing the healthy
    compiled cache entries — same pattern, different program. The
    barrier signature is rank 0's only after
    :func:`check_barrier_symmetry` proves every rank matches it — an
    asymmetric schedule must poison cache reuse (raise), never alias a
    symmetric entry."""
    barrier_sig = check_barrier_symmetry(schedule)
    return (schedule.pattern, schedule.method_id,
            getattr(schedule, "collective", False), barrier_sig,
            getattr(schedule, "variant", ""),
            getattr(schedule, "fault", None))


class OpKind(enum.IntEnum):
    ISEND = 0        # eager nonblocking send
    ISSEND = 1       # rendezvous nonblocking send (MPI_Issend semantics)
    IRECV = 2        # nonblocking receive
    SEND = 3         # blocking send
    RECV = 4         # blocking receive
    SENDRECV = 5     # paired blocking send+receive
    WAITALL = 6      # complete a set of nonblocking tokens
    BARRIER = 7      # global barrier
    COPY = 8         # local memcpy (self-edge)
    SIGNAL_SEND = 9  # 0-byte nonblocking send on the signal channel
    SIGNAL_RECV = 10 # 0-byte blocking receive on the signal channel
    ALLTOALLW = 11   # dense vendor collective (whole pattern in one call)


class TimerBucket(enum.Enum):
    """Which Timer field a timed segment charges (reference Timer,
    mpi_test.c:25-31)."""

    POST = "post_request_time"
    RECV_WAIT = "recv_wait_all_time"
    SEND_WAIT = "send_wait_all_time"
    RECV_AND_SEND_WAIT = "recv+send"  # waitall charged to both (non-agg paths)
    BARRIER = "barrier_time"
    NONE = "none"


@dataclass
class Op:
    """One step of a rank's program. Field meaning depends on kind:

    sends: ``peer`` = destination rank, ``slot`` = index into the sender's
    slab array. recvs: ``peer`` = source rank, ``slot`` = index into the
    receiver's slab array. SENDRECV: send to (peer, slot), receive from
    (peer2, slot2). WAITALL: ``tokens`` = token ids to complete. COPY:
    local ``slot`` (send side) → ``slot2`` (recv side). ``round`` tags the
    global round in which the transfer completes (collective-backend view).
    ``nbytes`` = payload size (0 ⇒ pure synchronization message).
    """

    kind: OpKind
    peer: int = -1
    slot: int = -1
    peer2: int = -1
    slot2: int = -1
    round: int = 0
    token: int = -1
    tokens: tuple[int, ...] = ()
    bucket: TimerBucket = TimerBucket.NONE
    nbytes: int = 0
    #: Matching channel. 0 = the pattern's data channel (message matching
    #: by directed (src, dst) pair, unique per rep — mpi_test.c:1776).
    #: Nonzero channels carry relay hops added by the dead-link repair
    #: pass (faults/repair.py): each rerouted edge gets its own channel so
    #: a detour sharing a directed pair with a pattern edge (or another
    #: detour) still matches uniquely.
    chan: int = 0
    #: Send side reads from the rank's RECEIVE staging row ``slot`` (set on
    #: the relay intermediate's forward hop) instead of its send slabs.
    from_stage: bool = False
    #: Receive side lands in the staging row ``slot`` (past the pattern's
    #: recv slots) instead of a pattern recv slot.
    to_stage: bool = False


@dataclass
class Schedule:
    """A compiled method: one op program per rank plus pattern metadata."""

    pattern: AggregatorPattern
    method_id: int
    name: str              # reference label, e.g. "All to many balanced"
    programs: list[list[Op]]
    collective: bool = False  # True for alltoallw-style dense methods
    uses_rendezvous: bool = False
    per_rep: bool = True   # program covers ONE rep; harness loops ntimes
    #: Canonical fault spec (faults/spec.py) realized in this schedule's
    #: programs, or None for a healthy schedule. Backends read it to apply
    #: the injection layer (slow-rank work, dead-edge masking).
    fault: str | None = None
    #: Program-variant tag folded into :func:`schedule_shape_key`. The
    #: repair pass stamps the canonical fault spec here so compiled caches
    #: never alias a repaired program with the healthy one.
    variant: str = ""
    #: Number of relay staging rows appended past every rank's pattern
    #: recv slots (dead-link repair). 0 for healthy schedules.
    n_staging: int = 0
    #: Directed (src, dst) pattern edges that the fault killed and the
    #: repair rerouted — validate() exempts these from chan-0 coverage.
    dead_edges: tuple[tuple[int, int], ...] = ()

    @property
    def nprocs(self) -> int:
        return self.pattern.nprocs

    def data_edges(self) -> np.ndarray:
        """All payload-carrying (src, dst, slot_src, slot_dst, round) tuples.

        Derived from the *send* side ops plus COPY self-edges, with
        ``slot_dst`` joined from :meth:`recv_slot_table` (directed pairs
        are unique per rep in every reference method, so the join is
        exact; -1 only when no matching receive exists). Shape (E, 5).
        Relay hops (chan != 0) are included — they are real traffic; their
        ``slot_dst`` is the logical landing index (staging rows count past
        the pattern recv slots). Consumers that must distinguish staging
        use :meth:`data_edges_ext`.
        """
        return self.data_edges_ext()[:, :5]

    def data_edges_ext(self) -> np.ndarray:
        """Extended edge view: (src, dst, slot_src, slot_dst, round, chan,
        flags), shape (E, 7). ``flags`` bit 0 = the send side reads from
        the source rank's staging row ``slot_src``; bit 1 = the receive
        lands in the destination's staging row ``slot_dst``. chan-0 rows
        reproduce :meth:`data_edges` exactly on healthy schedules."""
        rows = []
        rtable = self.recv_slot_table()
        relay = self.relay_recv_table()
        for rank, prog in enumerate(self.programs):
            for op in prog:
                if (op.kind in (OpKind.ISEND, OpKind.ISSEND, OpKind.SEND)
                        and op.nbytes > 0):
                    if op.chan:
                        dslot, to_stage = relay.get(
                            (rank, op.peer, op.chan), (-1, False))
                    else:
                        dslot, to_stage = rtable.get((rank, op.peer), -1), False
                    flags = (1 if op.from_stage else 0) | (2 if to_stage else 0)
                    rows.append((rank, op.peer, op.slot, dslot, op.round,
                                 op.chan, flags))
                elif op.kind is OpKind.SENDRECV and op.nbytes > 0:
                    dslot = rtable.get((rank, op.peer), -1)
                    rows.append((rank, op.peer, op.slot, dslot, op.round, 0, 0))
                elif op.kind is OpKind.COPY:
                    rows.append((rank, rank, op.slot, op.slot2, op.round, 0, 0))
        return np.array(rows, dtype=np.int64).reshape(-1, 7)

    def rounds(self) -> list[np.ndarray]:
        """Edges grouped by completion round: list of (E_k, 2) arrays of
        (src, dst), self-edges included. Rounds are indexed densely from 0."""
        edges = self.data_edges()
        if len(edges) == 0:
            return []
        out = []
        for r in range(int(edges[:, 4].max()) + 1):
            sel = edges[edges[:, 4] == r]
            out.append(sel[:, :2])
        return out

    def recv_slot_table(self) -> dict[tuple[int, int], int]:
        """(src, dst) → receiver slot index, from the receive-side ops.

        Message matching is by directed pair, which is unique per rep in
        every reference method (tags are ``src + dst`` per edge,
        mpi_test.c:1776 — unique per direction within a rep). Relay-hop
        receives (chan != 0) live in :meth:`relay_recv_table` instead.
        """
        table: dict[tuple[int, int], int] = {}
        for rank, prog in enumerate(self.programs):
            for op in prog:
                if op.chan:
                    continue
                if op.kind in (OpKind.IRECV, OpKind.RECV):
                    table[(op.peer, rank)] = op.slot
                elif op.kind is OpKind.SENDRECV:
                    table[(op.peer2, rank)] = op.slot2
                elif op.kind is OpKind.COPY:
                    table[(rank, rank)] = op.slot2
        return table

    def relay_recv_table(self) -> dict[tuple[int, int, int],
                                       tuple[int, bool]]:
        """(src, dst, chan) → (receiver slot, lands_in_staging) for the
        relay-channel receives (chan != 0) the repair pass appends."""
        table: dict[tuple[int, int, int], tuple[int, bool]] = {}
        for rank, prog in enumerate(self.programs):
            for op in prog:
                if op.chan and op.kind in (OpKind.IRECV, OpKind.RECV):
                    table[(op.peer, rank, op.chan)] = (op.slot, op.to_stage)
        return table

    def validate(self) -> None:
        """Sanity-check the schedule: every data send has a matching
        receive, duplicates are checked per matching key (src, dst, chan),
        and chan-0 coverage equals the pattern's expected edges minus any
        ``dead_edges`` the repair rerouted (whose payloads arrive via the
        relay channels instead). Collective schedules get the per-edge
        checks too (their payload rides ALLTOALLW, so any point-to-point
        op they carry must still match) plus conservation of the dense
        matrices: recvcounts must be the exact transpose of sendcounts
        and every rank must post the same number of collective calls."""
        table = self.recv_slot_table()
        relay = self.relay_recv_table()
        edges = self.data_edges_ext()
        seen = set()
        chan0 = set()
        for src, dst, _sslot, _dslot, _r, chan, _flags in edges:
            key = (int(src), int(dst), int(chan))
            if key in seen:
                raise AssertionError(f"{self.name}: duplicate edge {key}")
            seen.add(key)
            if chan:
                if key not in relay:
                    raise AssertionError(
                        f"{self.name}: relay send {key} has no matching recv")
            else:
                chan0.add(key[:2])
                if key[:2] not in table:
                    raise AssertionError(
                        f"{self.name}: send {key[:2]} has no matching recv")
        if self.collective:
            send, recv = self.pattern.dense_counts()
            if (send.T != recv).any():
                raise AssertionError(
                    f"{self.name}: dense sendcounts do not transpose to "
                    f"recvcounts — {int(send.sum())} B posted vs "
                    f"{int(recv.sum())} B expected")
            arity = {sum(1 for op in prog if op.kind is OpKind.ALLTOALLW)
                     for prog in self.programs}
            if len(arity) > 1:
                raise AssertionError(
                    f"{self.name}: collective call arity differs across "
                    f"ranks: {sorted(arity)}")
        # expected coverage: every (sender, receiver) pair of the pattern,
        # less the dead edges whose chan-0 message the repair removed
        p = self.pattern
        expected = {(int(s), int(d)) for s in p.senders for d in p.receivers}
        expected -= {(int(s), int(d)) for s, d in self.dead_edges}
        if not self.collective and chan0 != expected:
            missing = sorted(expected - chan0)[:5]
            extra = sorted(chan0 - expected)[:5]
            raise AssertionError(
                f"{self.name}: edge coverage mismatch; missing={missing} extra={extra}")
