"""Method registry: reference schedules 0-20 compiled to the op IR.

Each ``gen_*`` function reproduces one reference pattern algorithm
(mpi_test.c:313-1950) as per-rank op programs. The method ids, names, and
direction match the reference dispatch table (mpi_test.c:2181-2338) exactly;
``method 0`` means "run all" there and is handled by the driver here too.

Conventions (from prepare_* — mpi_test.c:94-133, 162-202):

- ALL_TO_MANY: every rank owns ``cb_nodes`` send slabs (slot = aggregator
  index); aggregators own ``nprocs`` recv slabs (slot = source rank).
- MANY_TO_ALL: aggregators own ``nprocs`` send slabs (slot = dest rank);
  every rank owns ``cb_nodes`` recv slabs (slot = aggregator index).
- Every slab is exactly ``data_size`` bytes (span=1, mpi_test.c:98).

Timer-bucket annotations follow each reference function's MPI_Wtime
bracketing exactly (who charges post_request / recv_wait_all /
send_wait_all, and the non-aggregator double-charge paths).

Known reference quirks reproduced or deliberately fixed (documented where
they occur): methods 4, 6, 11, 12 do not reset the mutated throttle between
reps in the reference (e.g. mpi_test.c:1604) — our programs are per-rep, so
every rep uses the first-rep round sizes; that is the obviously intended
behavior and the deviation only affects reps ≥ 2 of those methods.
"""

from __future__ import annotations

from dataclasses import dataclass, replace as _replace
from typing import Callable

import numpy as np

from tpu_aggcomm.core.pattern import AggregatorPattern, Direction, node_robin_map
from tpu_aggcomm.core.schedule import Op, OpKind, Schedule, TimerBucket

__all__ = ["METHODS", "MethodSpec", "compile_method", "method_ids"]


# --------------------------------------------------------------------------
# small helpers

def _balanced_partition(procs: int, cb: int):
    """ceiling/floor partition of [0, procs) into cb blocks
    (reference: mpi_test.c:1447-1452)."""
    remainder = procs % cb
    ceil_ = (procs + cb - 1) // cb
    floor_ = procs // cb
    offs = [j * ceil_ if j < remainder else remainder * ceil_ + (j - remainder) * floor_
            for j in range(cb)]
    return offs, remainder, ceil_, floor_


def _send_start(pos: int, remainder: int, ceil_: int, floor_: int) -> int:
    """Which balanced block a (possibly permuted) rank position falls in
    (reference: mpi_test.c:1449-1453)."""
    if pos >= remainder * ceil_:
        return remainder + (pos - remainder * ceil_) // floor_
    return pos // ceil_


def _window_contains(pos: int, temp: int, cs: int, procs: int) -> bool:
    """Membership of ``pos`` in the rotating window [temp, temp+cs) mod procs,
    with the reference's exact straddle test (mpi_test.c:1484-1496)."""
    if (temp >= procs and temp + cs >= procs) or (temp < procs and temp + cs < procs):
        return (temp % procs) <= pos < ((temp + cs) % procs)
    return pos >= temp or pos < (temp + cs) % procs


class _Prog:
    """Per-rank program builder with token bookkeeping."""

    def __init__(self):
        self.ops: list[Op] = []
        self._next_token = 0

    def nb(self, kind: OpKind, peer: int, slot: int, rnd: int, nbytes: int,
           bucket: TimerBucket = TimerBucket.NONE) -> int:
        tok = self._next_token
        self._next_token += 1
        self.ops.append(Op(kind=kind, peer=peer, slot=slot, round=rnd,
                           token=tok, nbytes=nbytes, bucket=bucket))
        return tok

    def blocking(self, kind: OpKind, peer: int, slot: int, rnd: int, nbytes: int,
                 bucket: TimerBucket = TimerBucket.NONE):
        self.ops.append(Op(kind=kind, peer=peer, slot=slot, round=rnd,
                           nbytes=nbytes, bucket=bucket))

    def sendrecv(self, dst: int, sslot: int, src: int, rslot: int, rnd: int,
                 nbytes: int, bucket: TimerBucket = TimerBucket.NONE):
        self.ops.append(Op(kind=OpKind.SENDRECV, peer=dst, slot=sslot,
                           peer2=src, slot2=rslot, round=rnd, nbytes=nbytes,
                           bucket=bucket))

    def copy(self, sslot: int, rslot: int, rnd: int):
        self.ops.append(Op(kind=OpKind.COPY, slot=sslot, slot2=rslot, round=rnd))

    def waitall(self, tokens: list[int], bucket: TimerBucket, rnd: int = 0):
        if tokens:
            self.ops.append(Op(kind=OpKind.WAITALL, tokens=tuple(tokens),
                               bucket=bucket, round=rnd))

    def barrier(self, rnd: int = 0, bucket: TimerBucket = TimerBucket.NONE):
        self.ops.append(Op(kind=OpKind.BARRIER, round=rnd, bucket=bucket))


def _wait_bucket(isagg: bool) -> TimerBucket:
    """Waitall bucket for methods that charge send_wait too on non-aggregators
    (e.g. mpi_test.c:1505-1510)."""
    return TimerBucket.RECV_WAIT if isagg else TimerBucket.RECV_AND_SEND_WAIT


def _dense_slots(p: AggregatorPattern):
    """Slot maps for the dense (translate-based) methods — the analog of
    sdispls/rdispls from *_alltoall_translate (mpi_test.c:233-311):
    ``sslot_of[dst]`` = index into the sender's slab array for a message to
    ``dst``; ``rslot_of[src]`` = index into the receiver's slab array for a
    message from ``src``."""
    agg_index = p.agg_index
    if p.direction is Direction.ALL_TO_MANY:
        sslot_of = agg_index           # send slab = aggregator index of dst
        rslot_of = np.arange(p.nprocs)  # recv slab = source rank
    else:
        sslot_of = np.arange(p.nprocs)  # send slab = dest rank
        rslot_of = agg_index            # recv slab = aggregator index of src
    return sslot_of, rslot_of


# --------------------------------------------------------------------------
# m=1 / m=2 — canonical unordered methods (mpi_test.c:1748-1824, 1871-1950)

def gen_all_to_many(p: AggregatorPattern) -> Schedule:
    """m=1: every rank Issends its cb_nodes slabs up front; aggregators drain
    sources in ``steps`` strided rounds of throttled Irecv+Waitall
    (mpi_test.c:1748-1824). Transfer round of edge (s → agg) is s % steps."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    progs = []
    unthrottled = p.comm_size >= procs
    steps = 1 if unthrottled else (procs + p.comm_size - 1) // p.comm_size
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        if unthrottled:
            toks = []
            if isagg:
                for i in range(procs):
                    toks.append(b.nb(OpKind.IRECV, i, i, 0, ds, TimerBucket.POST))
            for i in range(cb):
                toks.append(b.nb(OpKind.ISSEND, int(p.rank_list[i]), i, 0, ds,
                                 TimerBucket.POST))
            b.waitall(toks, TimerBucket.RECV_WAIT)
        else:
            send_toks = [b.nb(OpKind.ISSEND, int(p.rank_list[i]), i,
                              rank % steps, ds, TimerBucket.POST)
                         for i in range(cb)]
            for k in range(steps):
                recv_toks = []
                if isagg:
                    for i in range(k, procs, steps):
                        recv_toks.append(b.nb(OpKind.IRECV, i, i, k, ds,
                                              TimerBucket.POST))
                b.waitall(recv_toks, TimerBucket.RECV_WAIT, rnd=k)
            b.waitall(send_toks, TimerBucket.SEND_WAIT, rnd=steps - 1)
        progs.append(b.ops)
    return Schedule(p, 1, "All to many", progs, uses_rendezvous=True)


def gen_many_to_all(p: AggregatorPattern) -> Schedule:
    """m=2: mirror of m=1 — recvs pre-posted, aggregator Issends strided with
    a per-round send Waitall (mpi_test.c:1871-1950)."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    progs = []
    unthrottled = p.comm_size >= procs
    steps = 1 if unthrottled else (procs + p.comm_size - 1) // p.comm_size
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        if unthrottled:
            toks = [b.nb(OpKind.IRECV, int(p.rank_list[i]), i, 0, ds,
                         TimerBucket.POST) for i in range(cb)]
            if isagg:
                for i in range(procs):
                    toks.append(b.nb(OpKind.ISSEND, i, i, 0, ds, TimerBucket.POST))
            b.waitall(toks, TimerBucket.RECV_WAIT)
        else:
            recv_toks = [b.nb(OpKind.IRECV, int(p.rank_list[i]), i,
                              rank % steps, ds, TimerBucket.POST)
                         for i in range(cb)]
            for k in range(steps):
                send_toks = []
                if isagg:
                    for i in range(k, procs, steps):
                        send_toks.append(b.nb(OpKind.ISSEND, i, i, k, ds,
                                              TimerBucket.POST))
                b.waitall(send_toks, TimerBucket.SEND_WAIT, rnd=k)
            b.waitall(recv_toks, TimerBucket.RECV_WAIT, rnd=steps - 1)
        progs.append(b.ops)
    return Schedule(p, 2, "Many to all", progs, uses_rendezvous=True)


# --------------------------------------------------------------------------
# m=3 / m=17 / m=18 — balanced rotation family (mpi_test.c:1422-1517,
# 1135-1227, 1229-1336)

def _gen_balanced_a2m(p: AggregatorPattern, *, robin: bool, handshake: bool,
                      method_id: int, name: str) -> Schedule:
    """Shared body of the all-to-many balanced family. Aggregator j Irecvs a
    rotating round-k window of source positions; each sender walks aggregator
    blocks backward from its own partition while its position lies in the
    aggregator's window (``send_start`` persists across rounds). Variants:
    m=17 permutes positions by the node-robin map and barriers inside every
    round (mpi_test.c:1188); m=18 adds the 0-byte receiver→sender signal
    handshake on a separate channel before each Issend (mpi_test.c:1283-1301)."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    offs, remainder, ceil_, floor_ = _balanced_partition(procs, cb)
    bblock = min(p.comm_size, procs)
    robin_map = node_robin_map(procs, p.proc_node) if robin else None
    pos_of = np.argsort(robin_map) if robin else np.arange(procs)
    progs = []
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        myindex = int(agg_index[rank])
        pos = int(pos_of[rank])
        send_start = _send_start(pos, remainder, ceil_, floor_)
        rnd = 0
        k = 0
        cs = bblock
        while k < procs:
            if procs - k < cs:
                cs = procs - k
            toks = []
            if isagg:
                for i in range(cs):
                    temp = (k + i + offs[myindex]) % procs
                    if robin:
                        src = int(robin_map[temp])
                        toks.append(b.nb(OpKind.IRECV, src, src, rnd, ds,
                                         TimerBucket.POST))
                    elif temp != rank:
                        toks.append(b.nb(OpKind.IRECV, temp, temp, rnd, ds,
                                         TimerBucket.POST))
                        if handshake:
                            toks.append(b.nb(OpKind.SIGNAL_SEND, temp, -1, rnd,
                                             0, TimerBucket.POST))
                    else:
                        b.copy(myindex, temp, rnd)
            if robin:
                b.barrier(rnd, TimerBucket.POST)  # mpi_test.c:1188
            # sender walk (mpi_test.c:1479-1502); m=3 leaves it untimed,
            # m=17/18 charge it to post_request.
            send_bucket = TimerBucket.POST if (robin or handshake) else TimerBucket.NONE
            for _ in range(cb):
                temp = k + offs[send_start]
                if not _window_contains(pos, temp, cs, procs):
                    break
                dst = int(p.rank_list[send_start])
                if robin or dst != rank:
                    if handshake:
                        b.blocking(OpKind.SIGNAL_RECV, dst, -1, rnd, 0,
                                   send_bucket)
                    toks.append(b.nb(OpKind.ISSEND, dst, send_start, rnd, ds,
                                     send_bucket))
                send_start = (send_start - 1 + cb) % cb
            b.waitall(toks, _wait_bucket(isagg), rnd=rnd)
            k += cs
            rnd += 1
        progs.append(b.ops)
    return Schedule(p, method_id, name, progs, uses_rendezvous=True)


def gen_all_to_many_balanced(p: AggregatorPattern) -> Schedule:
    return _gen_balanced_a2m(p, robin=False, handshake=False, method_id=3,
                             name="All to many balanced")


def gen_all_to_many_node_robin(p: AggregatorPattern) -> Schedule:
    return _gen_balanced_a2m(p, robin=True, handshake=False, method_id=17,
                             name="All to many node robin")


def gen_all_to_many_balanced_control(p: AggregatorPattern) -> Schedule:
    return _gen_balanced_a2m(p, robin=False, handshake=True, method_id=18,
                             name="All to many balanced control")


def gen_many_to_all_balanced(p: AggregatorPattern) -> Schedule:
    """m=4: mirror of m=3 — each rank Irecvs from aggregators whose rotating
    window covers it (same backward walk), aggregators Issend their round
    window; one Waitall per round (mpi_test.c:1576-1663)."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    offs, remainder, ceil_, floor_ = _balanced_partition(procs, cb)
    progs = []
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        myindex = int(agg_index[rank])
        send_start = _send_start(rank, remainder, ceil_, floor_)
        rnd = 0
        k = 0
        cs = min(p.comm_size, procs)
        while k < procs:
            if procs - k < cs:
                cs = procs - k
            toks = []
            for _ in range(cb):
                temp = k + offs[send_start]
                if not _window_contains(rank, temp, cs, procs):
                    break
                src = int(p.rank_list[send_start])
                if src != rank:
                    toks.append(b.nb(OpKind.IRECV, src, send_start, rnd, ds,
                                     TimerBucket.POST))
                send_start = (send_start - 1 + cb) % cb
            if isagg:
                for i in range(cs):
                    temp = (k + i + offs[myindex]) % procs
                    if temp != rank:
                        toks.append(b.nb(OpKind.ISSEND, temp, temp, rnd, ds,
                                         TimerBucket.POST))
                    else:
                        b.copy(temp, myindex, rnd)
            b.waitall(toks, TimerBucket.RECV_WAIT, rnd=rnd)
            k += cs
            rnd += 1
        progs.append(b.ops)
    return Schedule(p, 4, "Many to all balanced", progs, uses_rendezvous=True)


# --------------------------------------------------------------------------
# m=5 / m=8 — dense vendor collective (mpi_test.c:599-654, 885-940)

def _gen_benchmark(p: AggregatorPattern, method_id: int, name: str) -> Schedule:
    """One Alltoallw per rep — the "let the library schedule it" control arm.
    TPU lowering: one lax.all_to_all with zero-masked slots."""
    progs = []
    for _rank in range(p.nprocs):
        b = _Prog()
        b.ops.append(Op(kind=OpKind.ALLTOALLW, round=0, nbytes=p.data_size))
        progs.append(b.ops)
    return Schedule(p, method_id, name, progs, collective=True)


def gen_many_to_all_benchmark(p: AggregatorPattern) -> Schedule:
    return _gen_benchmark(p, 5, "Many to all benchmark")


def gen_all_to_many_benchmark(p: AggregatorPattern) -> Schedule:
    return _gen_benchmark(p, 8, "All to many benchmark")


# --------------------------------------------------------------------------
# m=6 — fully synchronous rotation (mpi_test.c:1665-1746)

def gen_all_to_many_sync(p: AggregatorPattern) -> Schedule:
    """m=6: blocking rotation. At step (k, i) rank r targets aggregator index
    (r+k+i) mod cb; aggregator with index a drains every source ≡ (a-k-i)
    mod cb. Aggregator pairs exchange via Sendrecv; self-edges via memcpy.
    The whole step is charged to recv_wait_all (mpi_test.c:1685, 1736)."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    progs = []
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        myindex = int(agg_index[rank])
        rnd = 0
        k = 0
        cs = min(p.comm_size, cb)
        while k < cb:
            if cb - k < cs:
                cs = cb - k
            for i in range(cs):
                temp = (rank + k + i) % cb
                if isagg:
                    temp2 = (myindex - k - i + cb) % cb
                    dst = int(p.rank_list[temp])
                    if dst != rank and temp2 != rank:
                        b.sendrecv(dst, temp, temp2, temp2, rnd, ds,
                                   TimerBucket.RECV_WAIT)
                    elif dst == rank:
                        b.copy(temp, rank, rnd)
                        if temp2 != rank:
                            b.blocking(OpKind.RECV, temp2, temp2, rnd, ds,
                                       TimerBucket.RECV_WAIT)
                    else:  # temp2 == rank: self delivery done by the copy branch
                        b.blocking(OpKind.SEND, dst, temp, rnd, ds,
                                   TimerBucket.RECV_WAIT)
                    for x in range(temp2 + cb, procs, cb):
                        if x != rank:
                            b.blocking(OpKind.RECV, x, x, rnd, ds,
                                       TimerBucket.RECV_WAIT)
                else:
                    b.blocking(OpKind.SEND, int(p.rank_list[temp]), temp, rnd,
                               ds, TimerBucket.RECV_WAIT)
                rnd += 1
            k += cs
        progs.append(b.ops)
    return Schedule(p, 6, "All to many sync", progs)


# --------------------------------------------------------------------------
# m=7 / m=12 — half-sync all-to-many (mpi_test.c:1055-1114, 999-1053)

def gen_all_to_many_half_sync(p: AggregatorPattern) -> Schedule:
    """m=7: aggregators pre-post the round's Irecvs; senders use blocking
    Send; Waitall per round charged to recv_wait_all (mpi_test.c:1105-1109)."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    progs = []
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        myindex = int(agg_index[rank])
        rnd = 0
        k = 0
        cs = min(p.comm_size, cb)
        while k < cb:
            if cb - k < cs:
                cs = cb - k
            toks = []
            if isagg:
                for i in range(cs):
                    for x in range((myindex - k - i + cb) % cb, procs, cb):
                        toks.append(b.nb(OpKind.IRECV, x, x, rnd, ds))
            for i in range(cs):
                temp = (rank + k + i) % cb
                b.blocking(OpKind.SEND, int(p.rank_list[temp]), temp, rnd, ds)
            b.waitall(toks, TimerBucket.RECV_WAIT, rnd=rnd)
            k += cs
            rnd += 1
        progs.append(b.ops)
    return Schedule(p, 7, "All to many half sync", progs)


def gen_all_to_many_half_sync2(p: AggregatorPattern) -> Schedule:
    """m=12: all ranks Issend the round's targets; aggregators drain sources
    with blocking Recv interleaved; Waitall for the sends
    (mpi_test.c:999-1053)."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    progs = []
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        myindex = int(agg_index[rank])
        rnd = 0
        k = 0
        cs = min(p.comm_size, cb)
        while k < cb:
            if cb - k < cs:
                cs = cb - k
            toks = []
            for i in range(cs):
                temp = (rank + k + i) % cb
                toks.append(b.nb(OpKind.ISSEND, int(p.rank_list[temp]), temp,
                                 rnd, ds))
            if isagg:
                for i in range(cs):
                    for x in range((myindex - k - i + cb) % cb, procs, cb):
                        b.blocking(OpKind.RECV, x, x, rnd, ds)
            b.waitall(toks, TimerBucket.RECV_WAIT, rnd=rnd)
            k += cs
            rnd += 1
        progs.append(b.ops)
    return Schedule(p, 12, "All to many half sync 2", progs, uses_rendezvous=True)


# --------------------------------------------------------------------------
# m=11 — half-sync many-to-all (mpi_test.c:942-997)

def gen_many_to_all_half_sync(p: AggregatorPattern) -> Schedule:
    """m=11: aggregators Issend a strided round window; receivers drain their
    aggregators with blocking Recv in schedule order; per-round send Waitall."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    stride = (procs + cb - 1) // cb
    progs = []
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        myindex = int(agg_index[rank])
        rnd = 0
        k = 0
        cs = min(p.comm_size, procs)
        while k < procs:
            if procs - k < cs:
                cs = procs - k
            toks = []
            if isagg:
                for i in range(cs):
                    temp = (stride * myindex + k + i) % procs
                    toks.append(b.nb(OpKind.ISSEND, temp, temp, rnd, ds,
                                     TimerBucket.POST))
            for x in range(cs):
                for i in range(cb):
                    if rank == (k + i * stride + x) % procs:
                        b.blocking(OpKind.RECV, int(p.rank_list[i]), i, rnd,
                                   ds, TimerBucket.RECV_WAIT)
            b.waitall(toks, TimerBucket.RECV_WAIT, rnd=rnd)
            k += cs
            rnd += 1
        progs.append(b.ops)
    return Schedule(p, 11, "Many to all half sync", progs, uses_rendezvous=True)


# --------------------------------------------------------------------------
# m=9 / m=10 — MPICH pairwise exchange (mpi_test.c:421-597)

def _gen_pairwise(p: AggregatorPattern, method_id: int, name: str) -> Schedule:
    """XOR partners when nprocs is a power of two, else ring shift; one
    blocking Sendrecv per step. Zero-byte slots still synchronize (the
    reference posts them with count 0). Only total time is measured."""
    procs = p.nprocs
    send, _recv = p.dense_counts()
    sslot_of, rslot_of = _dense_slots(p)
    progs = []
    pof2 = procs & (procs - 1) == 0
    for rank in range(procs):
        b = _Prog()
        for i in range(procs):
            if pof2:
                src = dst = rank ^ i
            else:
                src = (rank - i + procs) % procs
                dst = (rank + i) % procs
            b.sendrecv(dst, int(sslot_of[dst]), src, int(rslot_of[src]), i,
                       int(send[rank, dst]))
        progs.append(b.ops)
    return Schedule(p, method_id, name, progs)


def gen_all_to_many_pairwise(p: AggregatorPattern) -> Schedule:
    return _gen_pairwise(p, 9, "All to many pairwise")


def gen_many_to_all_pairwise(p: AggregatorPattern) -> Schedule:
    return _gen_pairwise(p, 10, "Many to all pairwise")


# --------------------------------------------------------------------------
# m=13 / m=14 / m=19 — MPICH scattered alltoallv schedule
# (mpi_test.c:797-882, 656-720, 722-795)

def _gen_scattered(p: AggregatorPattern, method_id: int, name: str, *,
                   eager: bool, barrier_type: int = 0) -> Schedule:
    """Blocks of ``bblock`` Irecv (from rank+i+ii) and Issend/Isend (to
    rank-i-ii), Waitall per block. m=13 adds optional barrier per block
    (barrier_type=2) or per rep (=1); m=19 uses eager Isend, times posting
    only on non-aggregators, and ends the rep with an untimed barrier."""
    procs = p.nprocs
    send, recv = p.dense_counts()
    sslot_of, rslot_of = _dense_slots(p)
    agg_index = p.agg_index
    bblock = min(p.comm_size, procs)
    progs = []
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        rnd = 0
        for ii in range(0, procs, bblock):
            ss = min(procs - ii, bblock)
            toks = []
            recv_bucket = TimerBucket.NONE if eager else TimerBucket.POST
            for i in range(ss):
                dst = (rank + i + ii) % procs
                if recv[rank, dst]:
                    toks.append(b.nb(OpKind.IRECV, dst, int(rslot_of[dst]), rnd,
                                     int(recv[rank, dst]), recv_bucket))
            for i in range(ss):
                dst = (rank - i - ii + procs) % procs
                if send[rank, dst]:
                    kind = OpKind.ISEND if eager else OpKind.ISSEND
                    bucket = (TimerBucket.POST if (not eager or not isagg)
                              else TimerBucket.NONE)
                    toks.append(b.nb(kind, dst, int(sslot_of[dst]), rnd,
                                     int(send[rank, dst]), bucket))
            wb = TimerBucket.RECV_WAIT if method_id == 14 else _wait_bucket(isagg)
            b.waitall(toks, wb, rnd=rnd)
            if barrier_type == 2:
                b.barrier(rnd, TimerBucket.BARRIER)
            rnd += 1
        if barrier_type == 1:
            b.barrier(rnd - 1, TimerBucket.BARRIER)
        if method_id == 19:
            b.barrier(rnd - 1)  # mpi_test.c:785 — untimed, inside total
        progs.append(b.ops)
    return Schedule(p, method_id, name, progs, uses_rendezvous=not eager)


def gen_all_to_many_scattered(p: AggregatorPattern, barrier_type: int = 0) -> Schedule:
    return _gen_scattered(p, 13, "All to many scattered", eager=False,
                          barrier_type=barrier_type)


def gen_many_to_all_scattered(p: AggregatorPattern) -> Schedule:
    return _gen_scattered(p, 14, "Many to all scattered", eager=False)


def gen_all_to_many_scattered_isend(p: AggregatorPattern) -> Schedule:
    return _gen_scattered(p, 19, "All to many scattered isend", eager=True)


# --------------------------------------------------------------------------
# m=20 — balanced with all sends pre-posted (mpi_test.c:1338-1419)

def gen_all_to_many_balanced_pre_send(p: AggregatorPattern) -> Schedule:
    """m=20: every rank Issends ALL its slabs once at rep start (walking
    backward from its partition, skipping self), then aggregators run the
    balanced Irecv rounds; separate send Waitall at rep end. A pre-posted
    send's transfer round is the round in which its receiver posts the
    matching Irecv."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    offs, remainder, ceil_, floor_ = _balanced_partition(procs, cb)
    bblock = min(p.comm_size, procs)
    progs = []
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        myindex = int(agg_index[rank])
        send_start = _send_start(rank, remainder, ceil_, floor_)
        send_toks = []
        for k in range(cb):
            i = (send_start - k + cb) % cb
            dst = int(p.rank_list[i])
            if dst != rank:
                # receiver (block i) posts our Irecv in round ((rank - offs[i]) mod procs) // bblock
                rnd_s = ((rank - offs[i]) % procs) // bblock
                send_toks.append(b.nb(OpKind.ISSEND, dst, i, rnd_s, ds))
        rnd = 0
        k = 0
        cs = bblock
        while k < procs:
            if procs - k < cs:
                cs = procs - k
            toks = []
            if isagg:
                for i in range(cs):
                    temp = (k + i + offs[myindex]) % procs
                    if temp != rank:
                        toks.append(b.nb(OpKind.IRECV, temp, temp, rnd, ds,
                                         TimerBucket.POST))
                    else:
                        b.copy(myindex, temp, rnd)
            b.waitall(toks, TimerBucket.RECV_WAIT, rnd=rnd)
            k += cs
            rnd += 1
        b.waitall(send_toks, TimerBucket.SEND_WAIT, rnd=max(rnd - 1, 0))
        progs.append(b.ops)
    return Schedule(p, 20, "All to many balanced presend", progs,
                    uses_rendezvous=True)


# --------------------------------------------------------------------------
# dead-but-kept reference variants (SURVEY.md §2.1 C20/C24): registered so
# the design space stays visible, but not dispatched by the reference main.

def gen_many_to_all_balanced_boundary(p: AggregatorPattern) -> Schedule:
    """Dead code in the reference (mpi_test.c:1519-1574): strided windows on
    both sides with per-round waitall."""
    procs, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    stride = (procs + cb - 1) // cb
    progs = []
    for rank in range(procs):
        b = _Prog()
        isagg = agg_index[rank] >= 0
        myindex = int(agg_index[rank])
        rnd = 0
        k = 0
        cs = min(p.comm_size, procs)
        while k < procs:
            if procs - k < cs:
                cs = procs - k
            toks = []
            for x in range(cs):
                for i in range(cb):
                    if rank == (k + i * stride + x) % procs:
                        toks.append(b.nb(OpKind.IRECV, int(p.rank_list[i]), i,
                                         rnd, ds, TimerBucket.POST))
            if isagg:
                for i in range(cs):
                    temp = (stride * myindex + k + i) % procs
                    toks.append(b.nb(OpKind.ISSEND, temp, temp, rnd, ds,
                                     TimerBucket.POST))
            b.waitall(toks, TimerBucket.RECV_WAIT, rnd=rnd)
            k += cs
            rnd += 1
        progs.append(b.ops)
    return Schedule(p, 21, "Many to all balanced boundary", progs,
                    uses_rendezvous=True)


def gen_many_to_all_interleaved(p: AggregatorPattern) -> Schedule:
    """Dead code in the reference (mpi_test.c:1826-1869): unthrottled branch
    of m=2 with recvs first; the throttled branch is empty there, so this
    schedule ignores comm_size."""
    q = p if p.comm_size >= p.nprocs else _replace(p, comm_size=200_000_000)
    s = gen_many_to_all(q)
    return Schedule(p, 22, "Many to all interleaved", s.programs,
                    uses_rendezvous=True)


# --------------------------------------------------------------------------
# registry

@dataclass(frozen=True)
class MethodSpec:
    method_id: int
    name: str
    direction: Direction
    generator: Callable[[AggregatorPattern], Schedule]
    dispatched: bool = True  # False = dead code kept for parity
    tam: bool = False
    #: Canonical composition string for synthesized methods
    #: (tpu_aggcomm/synth/ — ids >= synth.SYNTH_ID_BASE); None for the
    #: 22 reference methods. Carrying it HERE is what makes a winner a
    #: first-class method: schedule_shape_key, caches, journals,
    #: traffic, check, fuse, and serve consume the registry unchanged.
    composition: str | None = None


def _tam_generator(p: AggregatorPattern) -> Schedule:
    from tpu_aggcomm.tam.engine import gen_tam_schedule  # lazy: avoid cycle
    return gen_tam_schedule(p)


METHODS: dict[int, MethodSpec] = {
    1: MethodSpec(1, "All to many", Direction.ALL_TO_MANY, gen_all_to_many),
    2: MethodSpec(2, "Many to all", Direction.MANY_TO_ALL, gen_many_to_all),
    3: MethodSpec(3, "All to many balanced", Direction.ALL_TO_MANY,
                  gen_all_to_many_balanced),
    4: MethodSpec(4, "Many to all balanced", Direction.MANY_TO_ALL,
                  gen_many_to_all_balanced),
    5: MethodSpec(5, "Many to all benchmark", Direction.MANY_TO_ALL,
                  gen_many_to_all_benchmark),
    6: MethodSpec(6, "All to many sync", Direction.ALL_TO_MANY,
                  gen_all_to_many_sync),
    7: MethodSpec(7, "All to many half sync", Direction.ALL_TO_MANY,
                  gen_all_to_many_half_sync),
    8: MethodSpec(8, "All to many benchmark", Direction.ALL_TO_MANY,
                  gen_all_to_many_benchmark),
    9: MethodSpec(9, "All to many pairwise", Direction.ALL_TO_MANY,
                  gen_all_to_many_pairwise),
    10: MethodSpec(10, "Many to all pairwise", Direction.MANY_TO_ALL,
                   gen_many_to_all_pairwise),
    11: MethodSpec(11, "Many to all half sync", Direction.MANY_TO_ALL,
                   gen_many_to_all_half_sync),
    12: MethodSpec(12, "All to many half sync 2", Direction.ALL_TO_MANY,
                   gen_all_to_many_half_sync2),
    13: MethodSpec(13, "All to many scattered", Direction.ALL_TO_MANY,
                   gen_all_to_many_scattered),
    14: MethodSpec(14, "Many to all scattered", Direction.MANY_TO_ALL,
                   gen_many_to_all_scattered),
    15: MethodSpec(15, "All to many TAM", Direction.ALL_TO_MANY,
                   _tam_generator, tam=True),
    16: MethodSpec(16, "Many to all TAM", Direction.MANY_TO_ALL,
                   _tam_generator, tam=True),
    17: MethodSpec(17, "All to many node robin", Direction.ALL_TO_MANY,
                   gen_all_to_many_node_robin),
    18: MethodSpec(18, "All to many balanced control", Direction.ALL_TO_MANY,
                   gen_all_to_many_balanced_control),
    19: MethodSpec(19, "All to many scattered isend", Direction.ALL_TO_MANY,
                   gen_all_to_many_scattered_isend),
    20: MethodSpec(20, "All to many balanced presend", Direction.ALL_TO_MANY,
                   gen_all_to_many_balanced_pre_send),
    21: MethodSpec(21, "Many to all balanced boundary", Direction.MANY_TO_ALL,
                   gen_many_to_all_balanced_boundary, dispatched=False),
    22: MethodSpec(22, "Many to all interleaved", Direction.MANY_TO_ALL,
                   gen_many_to_all_interleaved, dispatched=False),
}


def method_ids(include_dead: bool = False) -> list[int]:
    out = [m for m, s in sorted(METHODS.items())
           if include_dead or s.dispatched]
    try:  # TAM methods are dispatchable only once the engine module exists
        import tpu_aggcomm.tam.engine  # noqa: F401
    except ImportError:
        out = [m for m in out if not METHODS[m].tam]
    return out


def compile_method(method_id: int, pattern: AggregatorPattern,
                   barrier_type: int = 0) -> Schedule:
    """Compile a method id + pattern into a Schedule. The pattern's
    ``direction`` is overridden by the method's inherent direction, exactly
    like the reference where direction is baked into each function."""
    if method_id not in METHODS:
        raise ValueError(f"unknown method id {method_id}; valid ids: "
                         f"{sorted(METHODS)}")
    spec = METHODS[method_id]
    if pattern.direction is not spec.direction:
        pattern = _replace(pattern, direction=spec.direction)
    if method_id == 13:
        return gen_all_to_many_scattered(pattern, barrier_type=barrier_type)
    return spec.generator(pattern)
