"""Aggregator communication patterns.

The reference benchmark studies traffic between the full set of ``nprocs``
ranks and a chosen subset of ``cb_nodes`` *aggregator* ranks (ROMIO's
"collective buffering nodes"). This module reproduces, as pure index-array
computations, the reference's pattern metadata:

- aggregator placement policies 0..3 (reference: mpi_test.c:1952-2006,
  ``create_aggregator_list``),
- the node-robin permutation map  (reference: mpi_test.c:1116-1133,
  ``node_robin_map``),
- the round-robin aggregator re-shuffle across physical nodes
  (reference: lustre_driver_test.c:1374-1414, ``reorder_ranklist``).

Everything here is replicated computation: every rank derives the same
tables, exactly as in the reference (which calls create_aggregator_list on
every rank). On TPU, these tables parameterize mesh-axis schedules; they are
host-side numpy, never traced.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace

import numpy as np

__all__ = [
    "Direction",
    "Placement",
    "AggregatorPattern",
    "create_aggregator_list",
    "node_robin_map",
    "reorder_ranklist",
]


class Direction(enum.Enum):
    """Traffic direction relative to the aggregator subset.

    ALL_TO_MANY: every rank sends one slab to every aggregator (the *write*
    funnel of two-phase collective I/O). MANY_TO_ALL: every aggregator sends
    one slab to every rank (the *read* fan-out).
    """

    ALL_TO_MANY = "all_to_many"
    MANY_TO_ALL = "many_to_all"

    @property
    def senders_are_all(self) -> bool:
        return self is Direction.ALL_TO_MANY


class Placement(enum.IntEnum):
    """Aggregator placement policy — the reference's ``-t`` flag (0..3)."""

    FIRST = 0        # aggregators = ranks 0..cb_nodes-1
    SPREAD = 1       # ceiling/floor even spread (reference default)
    SPREAD_SHIFT = 2 # even spread shifted by -16 mod nprocs
    NODE_ROBIN = 3   # stride proc_node, wrapping with +1 offset per lap


def create_aggregator_list(
    nprocs: int, cb_nodes: int, placement: int | Placement = Placement.SPREAD,
    proc_node: int = 1,
) -> np.ndarray:
    """Return the ``cb_nodes`` aggregator ranks for a placement policy.

    Pure function of the config — the reference computes the same list
    redundantly on every rank (mpi_test.c:1952-2006). Policy semantics:

    - 0 (FIRST): ``[0, 1, ..., cb_nodes-1]``.
    - 1 (SPREAD): split ``nprocs`` into ``cb_nodes`` quasi-equal blocks of
      ceiling/floor size; aggregator i sits at the start of block i. The
      first ``nprocs // cb_nodes`` blocks get the ceiling size. (Note the
      reference reuses ``procs / cb_nodes`` for the *remainder* variable —
      we reproduce that behavior exactly, it is part of the layout.)
    - 2 (SPREAD_SHIFT): policy 1 shifted by -16 (mod nprocs).
    - 3 (NODE_ROBIN): stride ``proc_node`` (one aggregator per simulated
      node); on wrapping past nprocs, restart at ``lap_count`` offset within
      the node.
    """
    placement = Placement(placement)
    if cb_nodes < 1 or cb_nodes > nprocs:
        raise ValueError(f"cb_nodes must be in [1, nprocs]; got {cb_nodes} for nprocs={nprocs}")
    out = np.empty(cb_nodes, dtype=np.int64)
    if placement is Placement.FIRST:
        out[:] = np.arange(cb_nodes)
    elif placement in (Placement.SPREAD, Placement.SPREAD_SHIFT):
        # NB: the reference sets remainder = procs / cb_nodes (integer div),
        # not procs % cb_nodes. Kept verbatim: it only matters when
        # procs/cb_nodes < cb_nodes and changes which blocks are ceiling-sized.
        remainder = nprocs // cb_nodes
        ceiling = (nprocs + cb_nodes - 1) // cb_nodes
        floor = nprocs // cb_nodes
        for i in range(cb_nodes):
            if i < remainder:
                r = ceiling * i
            else:
                r = ceiling * remainder + floor * (i - remainder)
            if placement is Placement.SPREAD_SHIFT:
                r = (r - 16 + nprocs * 16) % nprocs
            out[i] = r
    else:  # NODE_ROBIN
        pos = 0
        for i in range(cb_nodes):
            out[i] = pos
            pos += proc_node
            if pos >= nprocs:
                pos = pos % proc_node + 1
    return out


def node_robin_map(nprocs: int, proc_node: int) -> np.ndarray:
    """Round-robin slot→rank permutation with stride ``proc_node``.

    ``map[i]`` is the rank occupying schedule slot ``i``: slots walk rank 0,
    proc_node, 2*proc_node, ... then wrap to 1, 1+proc_node, ... so that
    consecutive slots live on different simulated nodes
    (reference: mpi_test.c:1116-1133).
    """
    out = np.empty(nprocs, dtype=np.int64)
    count = 0
    lap = 0
    for i in range(nprocs):
        out[i] = count
        count += proc_node
        if count >= nprocs:
            lap += 1
            count = lap
    return out


def reorder_ranklist(process_node_list: np.ndarray, rank_list: np.ndarray,
                     nnodes: int) -> np.ndarray:
    """Round-robin re-shuffle of aggregators across physical nodes.

    Groups the aggregator ranks by home node, then deals them out one node at
    a time so consecutive aggregators land on distinct nodes
    (reference: lustre_driver_test.c:1374-1414).
    """
    cb_nodes = len(rank_list)
    per_node: list[list[int]] = [[] for _ in range(nnodes)]
    for r in rank_list:
        per_node[int(process_node_list[int(r)])].append(int(r))
    out = np.empty(cb_nodes, dtype=np.int64)
    idx = [0] * nnodes
    j = 0
    for i in range(cb_nodes):
        while idx[j] == len(per_node[j]):
            j = (j + 1) % nnodes
        out[i] = per_node[j][idx[j]]
        idx[j] += 1
        j = (j + 1) % nnodes
    return out


@dataclass(frozen=True)
class AggregatorPattern:
    """The full traffic-pattern specification for one benchmark run.

    Mirrors the reference CLI config (mpi_test.c:2130-2166): ``nprocs`` ranks
    exchange fixed-size ``data_size``-byte slabs with ``cb_nodes`` aggregator
    ranks placed by ``placement``; ``comm_size`` throttles in-flight messages
    per round; ``proc_node`` sets the simulated ranks-per-node.

    Message-size model: span=1 in the reference (mpi_test.c:98,122-123) —
    every (rank, aggregator) edge carries exactly ``data_size`` bytes. That
    uniformity is what lets dense TPU collectives (all_to_all with masked
    slots) express the pattern exactly.
    """

    nprocs: int
    cb_nodes: int
    data_size: int = 2048
    direction: Direction = Direction.ALL_TO_MANY
    placement: Placement = Placement.SPREAD
    proc_node: int = 1
    comm_size: int = 200_000_000  # reference default: effectively unthrottled
    #: Explicit aggregator ranks overriding the placement policy — the
    #: fault-repair path's fallback-aggregator election (faults/repair.py)
    #: re-homes a dead aggregator's role here. COMPARED (unlike the derived
    #: ``rank_list``): two patterns with different elected aggregators must
    #: hash/compare distinct or every schedule cache keyed by the pattern
    #: (jax_sim._cache, tune/cache.py) would alias them.
    rank_list_override: tuple[int, ...] | None = None
    rank_list: np.ndarray = field(init=False, repr=False, compare=False)

    def __post_init__(self):
        if self.nprocs < 1:
            raise ValueError("nprocs must be >= 1")
        if not (1 <= self.cb_nodes <= self.nprocs):
            raise ValueError("cb_nodes must be in [1, nprocs]")
        if self.data_size < 1:
            raise ValueError("data_size must be >= 1")
        if self.comm_size < 1:
            raise ValueError("comm_size must be >= 1")
        if self.rank_list_override is not None:
            ov = tuple(int(r) for r in self.rank_list_override)
            if len(ov) != self.cb_nodes:
                raise ValueError(
                    f"rank_list_override has {len(ov)} ranks; "
                    f"cb_nodes={self.cb_nodes}")
            if len(set(ov)) != len(ov):
                raise ValueError(f"rank_list_override has duplicates: {ov}")
            if any(not 0 <= r < self.nprocs for r in ov):
                raise ValueError(
                    f"rank_list_override out of range [0, {self.nprocs}): {ov}")
            object.__setattr__(self, "rank_list_override", ov)
            object.__setattr__(self, "rank_list",
                               np.asarray(ov, dtype=np.int64))
            return
        object.__setattr__(
            self, "rank_list",
            create_aggregator_list(self.nprocs, self.cb_nodes,
                                   self.placement, self.proc_node))

    # -- derived tables ----------------------------------------------------

    @property
    def is_agg(self) -> np.ndarray:
        """Boolean mask of length nprocs: True where the rank is an aggregator."""
        mask = np.zeros(self.nprocs, dtype=bool)
        mask[self.rank_list] = True
        return mask

    @property
    def agg_index(self) -> np.ndarray:
        """rank → index into rank_list (or -1 for non-aggregators)."""
        idx = np.full(self.nprocs, -1, dtype=np.int64)
        for i, r in enumerate(self.rank_list):
            idx[int(r)] = i
        return idx

    @property
    def senders(self) -> np.ndarray:
        if self.direction is Direction.ALL_TO_MANY:
            return np.arange(self.nprocs)
        return np.asarray(self.rank_list)

    @property
    def receivers(self) -> np.ndarray:
        if self.direction is Direction.ALL_TO_MANY:
            return np.asarray(self.rank_list)
        return np.arange(self.nprocs)

    @property
    def n_edges(self) -> int:
        return self.nprocs * self.cb_nodes

    @property
    def total_bytes(self) -> int:
        """Total payload moved per repetition (includes self-edges, as the
        reference does)."""
        return self.n_edges * self.data_size

    def reversed(self) -> "AggregatorPattern":
        d = (Direction.MANY_TO_ALL if self.direction is Direction.ALL_TO_MANY
             else Direction.ALL_TO_MANY)
        return replace(self, direction=d)

    def dense_counts(self) -> tuple[np.ndarray, np.ndarray]:
        """Dense per-(src,dst) byte-count matrices for alltoallw-style dispatch.

        Returns ``(sendcounts, recvcounts)`` each of shape (nprocs, nprocs):
        ``sendcounts[r, d]`` is what rank r sends to rank d; ``recvcounts`` is
        its transpose view. Reproduces the translate step
        (reference: mpi_test.c:233-311) without the displacement plumbing —
        slab layout is uniform so displacements are implied.
        """
        send = np.zeros((self.nprocs, self.nprocs), dtype=np.int64)
        if self.direction is Direction.ALL_TO_MANY:
            send[:, self.rank_list] = self.data_size
        else:
            send[self.rank_list, :] = self.data_size
        return send, send.T.copy()
