"""Synthetic variable-size workloads (``initialize_setting`` analog).

The reference's TAM debug harness drives its engines with four synthetic
I/O workloads (``initialize_setting``, lustre_driver_test.c:447-549) named
after Lustre OST-stripe regimes.  Each workload picks a *destination /
aggregator set* and gives every rank a **variable-size** message for every
destination: ``1 + src % blocklen`` bytes (l_d_t.c:471 and siblings) —
unlike the benchmark driver's uniform ``span=1`` slabs, message size varies
per sender.  Payload bytes are the TAM deterministic fill
``MAP_DATA(a,b,c) = 1 + 3a + 5b + 7c`` keyed by (sender rank, receiver
rank, byte offset) (l_d_t.c:20, fill at 474-476 etc.), and the checker is
``test_correctness`` (l_d_t.c:46-58).

Aggregator sets per stripe type (l_d_t.c:10-13, 455-546):

- ``SAME``    (0): the node proxies (``global_receivers``) — one OST per node.
- ``GREATER`` (1): the odd ranks (``2i + 1``) — more OSTs than nodes.
- ``LESS``    (2): the first ``nprocs // 2`` ranks.
- ``ALL``     (3): every rank.

The reference materialises per-rank ``send_size/recv_size/send_buf/recv_buf``
arrays; here the workload is a small immutable description and buffers are
derived on demand (sizes are pure functions of rank, which is also what lets
the TPU engines compile them into static index maps).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np

from tpu_aggcomm.core.topology import NodeAssignment
from tpu_aggcomm.harness.verify import VerificationError, fill_slab_tam

__all__ = ["StripeType", "Workload", "initialize_setting"]


class StripeType(enum.IntEnum):
    """OST-stripe regime (lustre_driver_test.c:10-13)."""

    SAME = 0
    GREATER = 1
    LESS = 2
    ALL = 3


@dataclass(frozen=True)
class Workload:
    """A variable-size all-to-aggregators exchange.

    ``msg_size[src]`` bytes flow from every rank ``src`` to every rank in
    ``aggregators``; payload byte ``j`` of the (src → dst) message is
    ``MAP_DATA(src, dst, j)``.  Mirrors the *global* content of the
    reference's per-rank ``send_size/recv_size/send_buf/recv_buf`` outputs
    (l_d_t.c:447-549).
    """

    nprocs: int
    blocklen: int
    stripe: StripeType
    aggregators: np.ndarray = field(repr=False)  # destination ranks; order =
    # file-domain order (ascending from initialize_setting; node-interleaved
    # after reorder_ranklist — engines must not assume sortedness)

    def __post_init__(self):
        if self.blocklen < 1:
            raise ValueError("blocklen must be >= 1")
        a = np.asarray(self.aggregators, dtype=np.int64)
        if len(a) == 0:
            raise ValueError("workload has no aggregators")
        if a.min() < 0 or a.max() >= self.nprocs:
            raise ValueError("aggregator rank out of range")
        object.__setattr__(self, "aggregators", a)

    # -- sizes ------------------------------------------------------------

    @property
    def msg_size(self) -> np.ndarray:
        """Per-sender message size: ``1 + src % blocklen`` (l_d_t.c:471)."""
        return 1 + np.arange(self.nprocs, dtype=np.int64) % self.blocklen

    @property
    def max_msg_size(self) -> int:
        return int(min(self.blocklen, self.nprocs))

    @property
    def is_aggregator(self) -> np.ndarray:
        mask = np.zeros(self.nprocs, dtype=bool)
        mask[self.aggregators] = True
        return mask

    def send_size(self, rank: int) -> np.ndarray:
        """``send_size`` array of ``rank`` (size nprocs, 0 for non-dests)."""
        out = np.zeros(self.nprocs, dtype=np.int64)
        out[self.aggregators] = int(self.msg_size[rank])
        return out

    def recv_size(self, rank: int) -> np.ndarray:
        """``recv_size`` array of ``rank`` (all zeros unless aggregator)."""
        if not self.is_aggregator[rank]:
            return np.zeros(self.nprocs, dtype=np.int64)
        return self.msg_size.copy()

    @property
    def total_bytes(self) -> int:
        return int(self.msg_size.sum()) * len(self.aggregators)

    # -- payload ----------------------------------------------------------

    def fill(self, src: int, dst: int) -> np.ndarray:
        """The (src → dst) message: MAP_DATA(src, dst, j) for j < size(src)."""
        return fill_slab_tam(src, dst, int(self.msg_size[src]))

    def make_send_bufs(self, rank: int) -> list[np.ndarray | None]:
        """``send_buf`` of ``rank``: slot dst = message for dst (or None)."""
        out: list[np.ndarray | None] = [None] * self.nprocs
        for dst in self.aggregators:
            out[int(dst)] = self.fill(rank, int(dst))
        return out

    def alloc_recv_bufs(self, rank: int) -> list[np.ndarray | None]:
        """``recv_buf`` of ``rank``: zeroed slot per source (or all None)."""
        if not self.is_aggregator[rank]:
            return [None] * self.nprocs
        return [np.zeros(int(s), dtype=np.uint8) for s in self.msg_size]

    # -- verification (test_correctness, l_d_t.c:46-58) --------------------

    def verify_recv(self, rank: int, recv_bufs: list[np.ndarray | None]) -> None:
        """Check rank's delivered ``recv_buf`` against the deterministic
        fill; raise :class:`VerificationError` on the first mismatch."""
        if not self.is_aggregator[rank]:
            return
        for src in range(self.nprocs):
            exp = self.fill(src, rank)
            got = recv_bufs[src]
            if got is None or len(got) != len(exp):
                raise VerificationError(
                    f"aggregator {rank}: recv from {src} has size "
                    f"{0 if got is None else len(got)}, expected {len(exp)}")
            if not np.array_equal(np.asarray(got, dtype=np.uint8), exp):
                j = int(np.nonzero(np.asarray(got) != exp)[0][0])
                raise VerificationError(
                    f"unexpected result at aggregator {rank} from {src}: "
                    f"byte {j}: {int(got[j])} != {int(exp[j])}")

    def verify_all(self, recv_by_rank: dict[int, list[np.ndarray | None]]) -> None:
        for rank in self.aggregators:
            self.verify_recv(int(rank), recv_by_rank[int(rank)])


def initialize_setting(assignment: NodeAssignment, blocklen: int,
                       stripe: StripeType | int) -> Workload:
    """Build one of the four synthetic workloads (l_d_t.c:447-549).

    ``assignment`` supplies the node proxies that the SAME regime uses as
    its destination set (the reference passes ``global_receivers`` — the
    per-node proxy list from static_node_assignment / gather_node_information).
    """
    stripe = StripeType(stripe)
    n = assignment.nprocs
    if stripe is StripeType.SAME:
        aggs = np.asarray(assignment.proxies, dtype=np.int64)
    elif stripe is StripeType.GREATER:
        aggs = 2 * np.arange(n // 2, dtype=np.int64) + 1
    elif stripe is StripeType.LESS:
        aggs = np.arange(n // 2, dtype=np.int64)
    else:
        aggs = np.arange(n, dtype=np.int64)
    if len(aggs) == 0:  # n == 1 degenerate GREATER/LESS
        aggs = np.array([0], dtype=np.int64)
    return Workload(nprocs=n, blocklen=int(blocklen), stripe=stripe,
                    aggregators=np.sort(aggs))
