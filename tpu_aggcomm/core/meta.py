"""Two-level aggregator metadata.

Reproduces ``aggregator_meta_information`` (lustre_driver_test.c:88-252):
given a node map and the *global* aggregator list, choose up to ``co``
*local* aggregators per node and bind every rank to exactly one local
aggregator on its node. This is the metadata that drives the two-level
exchange engines (collective_write2/3) and, in the TPU build, the
inner-axis grouping of the TAM mesh program.

Selection modes (reference ``mode`` argument):

- mode 0: ignore global-aggregator placement; pick ``co`` local aggregators
  evenly spread over the node's sorted rank list (ceiling/floor blocks).
- mode 1: local aggregators are a superset of the node's global aggregators,
  topped up with the node's lowest non-aggregator ranks until ``co`` are
  chosen.

Binding rule (both modes, reference comment at l_d_t.c:193-198): local
aggregator j on a node owns a contiguous run of ceiling-or-floor size of the
node's sorted ranks — skipping other local aggregators — and always owns
itself (inserted in its run's last slot if not encountered while scanning).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_aggcomm.core.topology import NodeAssignment

__all__ = ["AggregatorMeta", "aggregator_meta_information"]


@dataclass(frozen=True)
class AggregatorMeta:
    """Global two-level aggregator structure.

    ``local_aggregators`` concatenates each node's chosen local aggregators
    in node order (reference output of the same name); ``owner_of`` maps each
    rank to its local aggregator (reference: process_aggregator_list);
    ``owned_ranks(agg)`` lists the ranks bound to a local aggregator
    (reference: aggregator_local_ranks, computed per-rank there).
    """

    nprocs: int
    local_aggregators: np.ndarray  # concatenated per-node local aggregator ranks
    owner_of: np.ndarray           # shape (nprocs,): rank -> owning local aggregator

    @property
    def is_local_aggregator(self) -> np.ndarray:
        mask = np.zeros(self.nprocs, dtype=bool)
        mask[self.local_aggregators] = True
        return mask

    def owned_ranks(self, agg: int) -> np.ndarray:
        return np.nonzero(self.owner_of == agg)[0]


def aggregator_meta_information(
    assignment: NodeAssignment,
    global_aggregators: np.ndarray,
    co: int,
    mode: int = 0,
) -> AggregatorMeta:
    """Choose local aggregators per node and bind every rank to one.

    See module docstring; faithful to lustre_driver_test.c:88-252 including
    the scan-with-skip binding order, so layouts are comparable with the
    reference.
    """
    if co < 1:
        raise ValueError("co must be >= 1")
    nprocs = assignment.nprocs
    is_global = np.zeros(nprocs, dtype=bool)
    is_global[np.asarray(global_aggregators, dtype=np.int64)] = True

    all_local: list[int] = []
    per_node_local: list[np.ndarray] = []
    for node in range(assignment.nnodes):
        ranks = assignment.local_ranks(node)  # sorted
        lnp = len(ranks)
        co2 = min(co, lnp)
        if mode:
            # superset of the node's global aggregators, topped up in rank order
            chosen = [int(r) for r in ranks if is_global[r]]
            if len(chosen) < co2:
                for r in ranks:
                    if int(r) not in chosen:
                        chosen.append(int(r))
                    if len(chosen) == co2:
                        break
            else:
                chosen = chosen[:co2]
        else:
            # even ceiling/floor spread over the node's sorted ranks
            remainder = lnp % co2
            ceil_ = (lnp + co2 - 1) // co2
            floor_ = lnp // co2
            chosen = []
            for j in range(co2):
                if j < remainder:
                    chosen.append(int(ranks[ceil_ * j]))
                else:
                    chosen.append(int(ranks[ceil_ * remainder + floor_ * (j - remainder)]))
        per_node_local.append(np.array(chosen, dtype=np.int64))
        all_local.extend(chosen)

    is_local = np.zeros(nprocs, dtype=bool)
    is_local[np.array(all_local, dtype=np.int64)] = True

    owner_of = np.full(nprocs, -1, dtype=np.int64)
    for node in range(assignment.nnodes):
        ranks = assignment.local_ranks(node)
        chosen = per_node_local[node]
        lnp, lna = len(ranks), len(chosen)
        if lna == 0:
            continue
        remainder = lnp % lna
        ceil_ = (lnp + lna - 1) // lna
        floor_ = lnp // lna
        base = 0  # scan cursor over the node's sorted ranks
        for j, agg in enumerate(chosen):
            group = ceil_ if j < remainder else floor_
            seen_self = False
            for k in range(group):
                if k == group - 1 and not seen_self:
                    owner_of[agg] = agg  # reserve the last slot for the aggregator itself
                    break
                # skip ranks that are OTHER local aggregators
                while base < lnp and is_local[ranks[base]] and int(ranks[base]) != int(agg):
                    base += 1
                if base >= lnp:
                    break
                if is_local[ranks[base]]:
                    seen_self = True
                owner_of[int(ranks[base])] = int(agg)
                base += 1

    return AggregatorMeta(nprocs=nprocs,
                          local_aggregators=np.array(all_local, dtype=np.int64),
                          owner_of=owner_of)
