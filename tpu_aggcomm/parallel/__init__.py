"""Multi-host mesh construction — the DCN-scale entry points.

The reference scales by launching MPI ranks across nodes (aprun over 256
Theta nodes, script_theta_*.sh) and discovering topology with a hostname
Allgather (lustre_driver_test.c:267-344). The TPU equivalents:

- :func:`distributed_init` — per-process runtime bring-up
  (``jax.distributed.initialize``), the ``MPI_Init`` analog for multi-host
  TPU pods: after it, ``jax.devices()`` spans every host's chips and
  collectives ride ICI within a slice and DCN across hosts.
- :func:`host_major_devices` — the hostname-sort analog: order devices so
  ranks on the same host are contiguous; schedules that keep neighbor
  traffic local (TAM's intra-node phases, contiguous node maps) then hit
  ICI, not DCN.
- :func:`hierarchical_mesh` — the 2-axis ``(node, local)`` mesh used by the
  hierarchical engines: the *node* axis crosses hosts (DCN), the *local*
  axis stays within a host's ICI slice. On a single host it falls back to a
  fabricated split (the static_node_assignment strategy) so the same
  program shape is testable anywhere.

Single-host processes need none of this — every backend works on
``jax.devices()`` directly; these helpers only pin the placement that makes
the hierarchy physical.
"""

from __future__ import annotations

import numpy as np

__all__ = ["distributed_init", "host_major_devices", "hierarchical_mesh",
           "warn_if_node_straddles_hosts"]


_distributed_up = False


def distributed_init(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> bool:
    """Initialize the multi-host JAX runtime (idempotent).

    With no arguments, relies on the environment/cluster auto-detection
    (the normal TPU-pod path). Returns True if initialization happened,
    False if it was already initialized or (argless) single-process. A
    bring-up failure with explicit arguments PROPAGATES — swallowing it
    would leave every host silently running a disjoint single-host job.

    Double-init is recognized by a module-level flag plus the precise
    "already initialized" message — NOT by loose substring matching:
    nearly every bring-up failure from ``jax.distributed.initialize``
    mentions "initialize" somewhere, and treating those as benign is
    exactly the silent-disjoint-job failure this wrapper exists to
    prevent (ADVICE r1, medium).
    """
    global _distributed_up
    import jax

    if _distributed_up:
        return False
    explicit = any(v is not None for v in (coordinator_address,
                                           num_processes, process_id))
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        _distributed_up = True
        return True
    except RuntimeError as e:
        msg = str(e).lower()
        # jax's actual double-init messages: "distributed.initialize should
        # only be called once" (jax 0.9); older builds said "already
        # initialized". Nothing else is treated as benign.
        if "only be called once" in msg or "already initialized" in msg:
            _distributed_up = True
            return False   # double-init (e.g. by the launcher): harmless
        if explicit:
            raise          # real bring-up failure: never swallow
        return False       # argless on a non-cluster: single-process
    except ValueError:
        if explicit:
            raise          # mistyped coordinator/process args: fail fast
        return False       # argless on a non-cluster: single-process


def host_major_devices(devices=None) -> list:
    """Devices reordered host-major — all of process 0's chips, then
    process 1's, ... — the hostname-sort of gather_node_information applied
    to a TPU device list. The sort is stable: within a host, the caller's
    ordering is preserved."""
    import jax

    if devices is None:
        devices = jax.devices()
    devices = list(np.asarray(devices).reshape(-1))
    return sorted(devices, key=lambda d: d.process_index)


def hierarchical_mesh(devices=None, proc_node: int | None = None):
    """Build the 2-axis ``(node, local)`` mesh + its NodeAssignment.

    ``proc_node=None``: node = host process (every row of the mesh is one
    host's ICI slice; the node axis is the DCN boundary; requires every
    host to contribute the same chip count). Explicit ``proc_node``: that
    many ranks per logical node, honored on any topology — each host's
    chip count must then be a multiple of ``proc_node`` so no logical node
    straddles a host (contiguous blocks in host-major order, mirroring
    static_node_assignment type 0; on a single host this is the fabricated
    split testable on the virtual CPU mesh).
    """
    from jax.sharding import Mesh

    from tpu_aggcomm.core.topology import (mesh_node_assignment,
                                           static_node_assignment)

    devs = host_major_devices(devices)
    n = len(devs)
    host_na = mesh_node_assignment(devs)
    if proc_node is None:
        na = host_na
        sizes = set(int(s) for s in na.node_sizes)
        if len(sizes) != 1:
            raise ValueError(
                f"hierarchical mesh needs uniform chips per host; got "
                f"sizes {sorted(sizes)} (pad the device list or pass an "
                f"explicit dividing proc_node)")
        L = sizes.pop()
    else:
        bad = [int(s) for s in host_na.node_sizes if s % proc_node != 0]
        if bad or n % proc_node != 0:
            raise ValueError(
                f"proc_node={proc_node} must divide every host's chip "
                f"count (host sizes {sorted(set(int(s) for s in host_na.node_sizes))}) "
                f"so no logical node straddles the DCN boundary")
        na = static_node_assignment(n, proc_node, 0)
        L = proc_node
    mesh = Mesh(np.array(devs).reshape(na.nnodes, L), ("node", "local"))
    return mesh, na


def warn_if_node_straddles_hosts(devices, L: int, context: str) -> bool:
    """Warn when a logical node of ``L`` consecutive (host-major ordered)
    devices spans more than one host process.

    The program stays correct either way — but phases billed as intra-node
    (ICI) traffic would actually ride DCN, so hierarchical measurements
    would mismeasure. Returns True if a straddle was found.
    """
    import warnings

    procs = [d.process_index for d in list(np.asarray(devices).reshape(-1))]
    straddle = any(len(set(procs[i:i + L])) > 1
                   for i in range(0, len(procs) - len(procs) % L, L))
    if straddle:
        warnings.warn(
            f"{context}: a logical node of {L} ranks spans multiple host "
            f"processes — intra-node phases will ride DCN, not ICI; pick "
            f"proc_node dividing the chips-per-host to align the hierarchy",
            RuntimeWarning, stacklevel=3)
    return straddle
