"""Multi-process (multi-controller) execution of a schedule rep.

The reference's launch model is multi-process by construction (``aprun``
over 256 Theta nodes, script_theta_all_to_many_256.sh:33; per-host
topology discovery via a hostname Allgather, lustre_driver_test.c:267-344).
The TPU analog is JAX multi-controller: every host process runs the SAME
program over a global mesh; arrays are globally sharded, each process
feeding and reading only its addressable shards, and the collectives ride
ICI within a host / DCN across hosts.

:func:`run_rep_across_processes` is the minimal end-to-end proof of that
path: it reuses the jax_ici backend's real lowering (the per-round fenced
shard_map segments — identical program shape to the single-process tier),
but replaces the two host<->device boundaries that are process-local by
construction with their multi-controller equivalents:

- input: every process computes the full deterministic fill (it is a pure
  function of rank/slot/iter — the reference's MAP_DATA discipline) and
  contributes its addressable shards via ``jax.make_array_from_callback``;
- output: each process verifies the recv rows it actually owns
  (``addressable_shards``) against :func:`expected_recv` — the same
  sender-keyed check the reference runs per rank (mpi_test.c:213-217).

Single-process runtimes are the degenerate case (every shard is
addressable), so the same function is testable on the virtual CPU mesh
and is what a 2-process bring-up (scripts/two_process_bringup.py)
drives end-to-end.
"""

from __future__ import annotations

import numpy as np

__all__ = ["run_rep_across_processes", "run_tam_across_processes"]


def _verify_rank_rows(p, rank: int, rows_lanes, iter_: int) -> bool:
    """Shared per-rank recv check for the multi-controller runners: skip
    ranks that receive nothing, byte-compare the rest against
    :func:`expected_recv` with slab-level diagnostics on mismatch.
    Returns True when the rank was actually checked."""
    import jax

    from tpu_aggcomm.backends.lanes import lanes_to_bytes
    from tpu_aggcomm.core.pattern import Direction
    from tpu_aggcomm.harness.verify import (VerificationError, expected_recv,
                                            recv_slot_counts)

    counts = recv_slot_counts(p)
    if rank >= p.nprocs or counts[rank] == 0:
        return False
    if (p.direction is Direction.ALL_TO_MANY
            and p.agg_index[rank] < 0):
        return False
    got = lanes_to_bytes(np.asarray(rows_lanes), p.data_size)
    exp = expected_recv(p, rank, iter_)
    if not np.array_equal(got[:exp.shape[0]], exp):
        bad = np.nonzero(~(got[:exp.shape[0]] == exp).all(axis=1))[0]
        s = int(bad[0])
        raise VerificationError(
            f"process {jax.process_index()}: rank {rank} slab {s}: "
            f"got {got[s][:8]}... expected {exp[s][:8]}...")
    return True


def run_rep_across_processes(pattern, method: int = 1, *, iter_: int = 0,
                             devices=None) -> dict:
    """Run one rep of ``method`` on ``pattern`` over ALL processes'
    devices; verify the locally-owned recv rows; return summary stats.

    Requires len(devices) == pattern.nprocs (one rank per device, the
    jax_ici tier). Raises VerificationError on corrupt delivery.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_aggcomm.backends.jax_ici import (AXIS, JaxIciBackend,
                                              put_global)
    from tpu_aggcomm.backends.lanes import lane_layout
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.parallel import host_major_devices

    devs = host_major_devices(devices)
    if len(devs) != pattern.nprocs:
        raise ValueError(f"need exactly {pattern.nprocs} devices (one rank "
                         f"per device), have {len(devs)}")
    sched = compile_method(method, pattern)
    p = sched.pattern   # compile_method bakes the method's direction in
    backend = JaxIciBackend(devices=devs)
    mesh = backend._mesh(p.nprocs)
    sharding = NamedSharding(mesh, P(AXIS))
    segments, _rounds, _chain, n_send_slots, n_recv_slots = \
        backend._segments_for(sched, mesh, sharding, False)

    # global arrays from per-process shards: the fill is a pure function
    # of (rank, slot, iter), so every process can compute any shard
    send_np = backend._global_send(p, iter_, n_send_slots)
    ndt, _, w = lane_layout(p.data_size)
    recv_np = np.zeros((p.nprocs, n_recv_slots + 1, w), dtype=ndt)
    send_dev = put_global(send_np, sharding)
    recv_dev = put_global(recv_np, sharding)

    for seg in segments:
        recv_dev = seg(send_dev, recv_dev)
    recv_dev.block_until_ready()

    # local-shard verification: each process checks the rows it owns
    checked = []
    for shard in recv_dev.addressable_shards:
        r0 = shard.index[0].start or 0
        rows = np.asarray(shard.data)[:, :n_recv_slots, :]
        for k in range(rows.shape[0]):
            if _verify_rank_rows(p, r0 + k, rows[k], iter_):
                checked.append(r0 + k)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "n_devices": len(devs),
        "ranks_verified": checked,
        "n_segments": len(segments),
    }


def run_tam_across_processes(pattern, method: int = 15, *, iter_: int = 0,
                             devices=None) -> dict:
    """One TAM rep (m=15/16) through the hierarchical two-level engine
    with the NODE axis crossing process boundaries (VERDICT r4 item 6) —
    the exact hop the reference's collective_write engine exists for: P3
    proxy<->proxy traffic between hosts (lustre_driver_test.c:944-1309).

    ``tam_two_level_jax`` builds the (node, local) mesh host-major, so
    with one process per simulated host and proc_node == the per-process
    device count, every hop-1 ``all_to_all`` over the node axis is
    cross-process (DCN analog) and every hop-2 over the local axis stays
    in-process (ICI analog). Output rides ``out="global"``; each process
    byte-verifies the recv rows of the ranks whose device coordinates it
    owns. Single-process runtimes are the degenerate case, so the same
    function is testable on the virtual CPU mesh."""
    import jax

    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.tam.engine import tam_two_level_jax

    tam = compile_method(method, pattern)
    p = tam.pattern     # compile_method bakes the method's direction in
    na = tam.assignment
    L = int(na.node_sizes[0])
    devs = list(devices) if devices is not None else jax.devices()
    out_dev, rep_times = tam_two_level_jax(tam, devs, iter_=iter_,
                                           out="global")

    checked = []
    for shard in out_dev.addressable_shards:
        b = shard.index[0].start or 0       # node coordinate
        lo = shard.index[1].start or 0      # local coordinate
        rows = np.asarray(shard.data)       # (1, 1, out_rows, w)
        for db in range(rows.shape[0]):
            for dl in range(rows.shape[1]):
                rank = (b + db) * L + (lo + dl)
                if _verify_rank_rows(p, rank, rows[db, dl], iter_):
                    checked.append(rank)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "mesh_shape": (na.nnodes, L),
        "ranks_verified": checked,
        "rep_seconds": rep_times,
    }
