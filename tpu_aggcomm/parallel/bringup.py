"""Multi-process (multi-controller) execution of a schedule rep.

The reference's launch model is multi-process by construction (``aprun``
over 256 Theta nodes, script_theta_all_to_many_256.sh:33; per-host
topology discovery via a hostname Allgather, lustre_driver_test.c:267-344).
The TPU analog is JAX multi-controller: every host process runs the SAME
program over a global mesh; arrays are globally sharded, each process
feeding and reading only its addressable shards, and the collectives ride
ICI within a host / DCN across hosts.

:func:`run_rep_across_processes` is the minimal end-to-end proof of that
path: it reuses the jax_ici backend's real lowering (the per-round fenced
shard_map segments — identical program shape to the single-process tier),
but replaces the two host<->device boundaries that are process-local by
construction with their multi-controller equivalents:

- input: every process computes the full deterministic fill (it is a pure
  function of rank/slot/iter — the reference's MAP_DATA discipline) and
  contributes its addressable shards via ``jax.make_array_from_callback``;
- output: each process verifies the recv rows it actually owns
  (``addressable_shards``) against :func:`expected_recv` — the same
  sender-keyed check the reference runs per rank (mpi_test.c:213-217).

Single-process runtimes are the degenerate case (every shard is
addressable), so the same function is testable on the virtual CPU mesh
and is what a 2-process bring-up (scripts/two_process_bringup.py)
drives end-to-end.
"""

from __future__ import annotations

import numpy as np

__all__ = ["run_rep_across_processes"]


def run_rep_across_processes(pattern, method: int = 1, *, iter_: int = 0,
                             devices=None) -> dict:
    """Run one rep of ``method`` on ``pattern`` over ALL processes'
    devices; verify the locally-owned recv rows; return summary stats.

    Requires len(devices) == pattern.nprocs (one rank per device, the
    jax_ici tier). Raises VerificationError on corrupt delivery.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_aggcomm.backends.jax_ici import (AXIS, JaxIciBackend,
                                              put_global)
    from tpu_aggcomm.backends.lanes import lane_layout, lanes_to_bytes
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import Direction
    from tpu_aggcomm.harness.verify import (VerificationError, expected_recv,
                                            recv_slot_counts)
    from tpu_aggcomm.parallel import host_major_devices

    p = pattern
    devs = host_major_devices(devices)
    if len(devs) != p.nprocs:
        raise ValueError(f"need exactly {p.nprocs} devices (one rank per "
                         f"device), have {len(devs)}")
    sched = compile_method(method, p)
    backend = JaxIciBackend(devices=devs)
    mesh = backend._mesh(p.nprocs)
    sharding = NamedSharding(mesh, P(AXIS))
    segments, _rounds, _chain, n_send_slots, n_recv_slots = \
        backend._segments_for(sched, mesh, sharding, False)

    # global arrays from per-process shards: the fill is a pure function
    # of (rank, slot, iter), so every process can compute any shard
    send_np = backend._global_send(p, iter_, n_send_slots)
    ndt, _, w = lane_layout(p.data_size)
    recv_np = np.zeros((p.nprocs, n_recv_slots + 1, w), dtype=ndt)
    send_dev = put_global(send_np, sharding)
    recv_dev = put_global(recv_np, sharding)

    for seg in segments:
        recv_dev = seg(send_dev, recv_dev)
    recv_dev.block_until_ready()

    # local-shard verification: each process checks the rows it owns
    counts = recv_slot_counts(p)
    agg_index = p.agg_index
    checked = []
    for shard in recv_dev.addressable_shards:
        r0 = shard.index[0].start or 0
        rows = np.asarray(shard.data)[:, :n_recv_slots, :]
        for k in range(rows.shape[0]):
            rank = r0 + k
            if counts[rank] == 0:
                continue
            if p.direction is Direction.ALL_TO_MANY and agg_index[rank] < 0:
                continue
            got = lanes_to_bytes(rows[k], p.data_size)
            exp = expected_recv(p, rank, iter_)
            if not np.array_equal(got[:exp.shape[0]], exp):
                bad = np.nonzero(~(got[:exp.shape[0]] == exp).all(axis=1))[0]
                s = int(bad[0])
                raise VerificationError(
                    f"process {jax.process_index()}: rank {rank} slab {s}: "
                    f"got {got[s][:8]}... expected {exp[s][:8]}...")
            checked.append(rank)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "n_devices": len(devs),
        "ranks_verified": checked,
        "n_segments": len(segments),
    }
