"""Autotuner: statistical racing search over (method, cb_nodes, -c, -t).

The reference answers "which posting/throttling/sync algorithm minimizes
max-over-ranks completion time for this traffic pattern?" by hand: the
Theta job scripts (script_theta_*_256.sh) enumerate ``-m``/``-c`` cells
and a human reads the CSVs. This package closes that loop with the
measurement machinery the repo already trusts:

- :mod:`tpu_aggcomm.tune.space` — the candidate grid over
  ``(method_id, cb_nodes, comm_size, agg_type)`` for one fixed
  shape/backend, with the direction and dead-method guards (an m=1 grid
  never mixes m=2 methods; m=21/22 are refused by name);
- :mod:`tpu_aggcomm.tune.race` — the statistical racing loop: each
  surviving candidate gets batches of chained differenced trials, and a
  candidate is eliminated only when the seeded bootstrap CI on its
  median delta vs the current leader excludes zero
  (``obs/metrics.bootstrap_delta_ci`` — same samples in, same
  eliminations and winner out, byte for byte);
- :mod:`tpu_aggcomm.tune.cache` — the persistent tuned-schedule cache:
  one ``TUNE_*.json`` per ``(shape, direction, backend)`` key, stamped
  with a manifest fingerprint from the v3 run ledger so environment
  drift (jax/libtpu/device-kind change) invalidates the entry instead
  of silently serving a stale winner;
- :mod:`tpu_aggcomm.tune.measure` — the jax-side sampler (fresh
  ``harness/chained.py`` differenced trials per racing batch on the
  jax_sim backend). The ONLY module here that touches jax; everything
  else stays importable under a poisoned/absent jax, because
  ``cli tune --replay`` must re-derive a verdict from artifacts on a
  machine where ``import jax`` may hang on a dead tunnel (the
  bench.py --check-regression discipline).

Entry points: ``python -m tpu_aggcomm.cli tune`` (search + persist),
``cli tune --replay TUNE_*.json`` (jax-free re-derivation), and
``--auto`` on the run/sweep commands (cache-resolved method with an
explicit warning + fallback on miss or drift).
"""

from __future__ import annotations

__all__ = ["space", "race", "cache"]
