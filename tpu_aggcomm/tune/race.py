"""The statistical racing loop — jax-free, deterministic, replayable.

Successive elimination against a running leader: every surviving
candidate gets one batch of chained differenced trials per round, and a
candidate is dropped only when the seeded percentile-bootstrap CI on
the relative median delta of its POOLED samples vs the current
leader's excludes zero on the slow side
(``obs/metrics.bootstrap_delta_ci`` — the exact kernel the regression
gate uses, same seed discipline). No p-hacking knobs: the CI seed, the
alpha, and the candidate order are all recorded in the artifact, so
feeding the recorded samples back through :func:`race` reproduces the
elimination sequence and winner byte for byte. That replay
(:func:`replay_record`) is what ``cli tune --replay`` and the tier-1 CI
step run — on a machine where jax may not even import.

Sampler contract: ``sampler(cid, batch_index) -> list[float]`` returns
that batch's per-trial seconds for one candidate. The real sampler
(tune/measure.py) runs fresh chained trials; the synthetic sampler
(:func:`make_synthetic_sampler`) draws from a seeded injected-skew
model; the replay sampler replays the recorded lists. All three drive
the SAME loop.
"""

from __future__ import annotations

import statistics
from dataclasses import dataclass, field

from tpu_aggcomm.obs.metrics import bootstrap_delta_ci

__all__ = ["RaceResult", "RaceError", "race", "replay_record",
           "make_synthetic_sampler"]


class RaceError(ValueError):
    """Unusable racing input (no candidates, empty batch, truncated
    replay record)."""


@dataclass
class RaceResult:
    """Everything the TUNE artifact persists about one race."""

    winner: str
    eliminations: list[dict]
    #: cid -> per-batch sample lists; a candidate has exactly as many
    #: batches as rounds it survived, which is what makes the record
    #: replayable without a backend.
    samples: dict[str, list[list[float]]] = field(default_factory=dict)
    batches_run: int = 0
    survivors: list[str] = field(default_factory=list)

    def medians(self) -> dict[str, float]:
        return {cid: statistics.median([x for b in batches for x in b])
                for cid, batches in self.samples.items() if any(batches)}


def race(cids, sampler, *, max_batches: int = 6, alpha: float = 0.05,
         seed: int = 0, n_boot: int = 2000) -> RaceResult:
    """Run the racing loop over candidate ids in the given order.

    Per batch: every survivor samples once; the leader is the survivor
    with the smallest pooled median (ties broken by input order — part
    of the determinism contract); every other survivor whose delta-CI
    vs the leader excludes zero on the slow side is eliminated, in
    input order, against the leader chosen at the START of the batch
    (re-electing mid-batch would make the elimination order depend on
    dict iteration details instead of the recorded sample lists). The
    race ends when one survivor remains or ``max_batches`` is
    exhausted; the final leader is the winner either way.
    """
    order = [str(c) for c in cids]
    if not order:
        raise RaceError("race needs at least one candidate")
    if len(set(order)) != len(order):
        raise RaceError("duplicate candidate ids in the race")
    samples: dict[str, list[list[float]]] = {c: [] for c in order}
    survivors = list(order)
    eliminations: list[dict] = []
    batches_run = 0

    def pooled(cid: str) -> list[float]:
        return [x for b in samples[cid] for x in b]

    for batch in range(max_batches):
        if len(survivors) <= 1:
            break
        for cid in survivors:
            got = [float(x) for x in sampler(cid, batch)]
            if not got:
                raise RaceError(f"sampler returned an empty batch for "
                                f"{cid} (batch {batch})")
            samples[cid].append(got)
        batches_run = batch + 1
        meds = {c: statistics.median(pooled(c)) for c in survivors}
        leader = min(survivors, key=lambda c: (meds[c], order.index(c)))
        still = []
        for cid in survivors:
            if cid == leader:
                still.append(cid)
                continue
            lo, hi = bootstrap_delta_ci(pooled(leader), pooled(cid),
                                        relative=True, alpha=alpha,
                                        seed=seed, n_boot=n_boot)
            if lo > 0:
                eliminations.append({
                    "batch": batch, "candidate": cid, "leader": leader,
                    "ci_pct": [lo * 100.0, hi * 100.0],
                    "median_candidate": meds[cid],
                    "median_leader": meds[leader]})
            else:
                still.append(cid)
        survivors = still

    meds = {c: statistics.median(pooled(c)) for c in survivors}
    winner = min(survivors, key=lambda c: (meds[c], order.index(c)))
    return RaceResult(winner=winner, eliminations=eliminations,
                      samples=samples, batches_run=batches_run,
                      survivors=survivors)


def replay_record(race_rec: dict) -> RaceResult:
    """Re-derive the race verdict from a recorded ``race`` block
    (artifact schema tune-v1): the recorded per-candidate batch lists
    drive the identical loop with the recorded seed/alpha/n_boot — the
    bootstrap is seeded, so the eliminations and winner come out byte
    for byte or the artifact is inconsistent. Raises RaceError on a
    truncated record (a candidate asked for a batch it never stored)."""
    recorded = race_rec.get("samples") or {}
    order = race_rec.get("order") or list(recorded)

    def sampler(cid: str, batch: int) -> list[float]:
        batches = recorded.get(cid, [])
        if batch >= len(batches):
            raise RaceError(f"replay: {cid} has no recorded batch "
                            f"{batch} (record truncated?)")
        return batches[batch]

    return race(order, sampler,
                max_batches=int(race_rec.get("max_batches", 6)),
                alpha=float(race_rec.get("alpha", 0.05)),
                seed=int(race_rec.get("seed", 0)),
                n_boot=int(race_rec.get("n_boot", 2000)))


def make_synthetic_sampler(spec: str, *, batch_trials: int = 3,
                           seed: int = 0, jitter: float = 0.03):
    """A deterministic injected-skew sampler for tests and jax-free
    smoke runs: ``spec`` is ``"BASE_US[,mID*FACTOR]..."`` — every
    candidate's latency is gaussian around BASE_US microseconds, scaled
    by its method's FACTOR (default 1.0). ``"100,m3*0.5"`` makes every
    m=3 candidate the 2x-faster oracle winner the convergence test
    checks for. Samples are seeded per (seed, cid, batch): the same
    spec always yields the same race."""
    import random

    from tpu_aggcomm.faults.spec import FaultSpecError, parse_synthetic

    # the grammar parser lives with the fault grammar (faults/spec.py) so
    # both injected-skew surfaces share one parser; re-wrap its error in
    # the tuner's exception type
    try:
        base_s, factors = parse_synthetic(spec)
    except FaultSpecError as e:
        raise RaceError(str(e)) from None

    from tpu_aggcomm.tune.space import parse_cid

    def sampler(cid: str, batch: int) -> list[float]:
        mean = base_s * factors.get(parse_cid(cid).method, 1.0)
        rng = random.Random(f"{seed}:{cid}:{batch}")
        return [max(mean * 0.1, rng.gauss(mean, jitter * mean))
                for _ in range(batch_trials)]

    return sampler
