"""Tuner search space: the candidate grid and its validity guards.

A tuning run races candidates over one FIXED traffic shape — ``(nprocs,
data_size, proc_node)`` on one backend — varying only the knobs the
reference sweeps by hand: the method id (``-m``), the aggregator count
(``-a``), the throttle (``-c``) and the placement policy (``-t``).
Everything here is pure index bookkeeping (no jax): the grid must be
constructible and re-parsable on the jax-free replay path.

Guards (SpaceError, named ids — the ``inspect compare``
TraceCompareError discipline):

- **direction consistency** — an all-to-many grid never mixes
  many-to-all methods: their max-over-ranks times answer different
  questions (write funnel vs read fan-out), so a "winner" across them
  is not a winner of anything. The error names the offending ids per
  direction.
- **dead methods** — m=21/22 are registered but not dispatched
  (``core/methods.py``); racing them would crown an algorithm the
  reference never runs. Refused by id via
  ``method_ids(include_dead=False)``.
- **TAM methods** — m=15/16 ride the hierarchical engine, whose
  per-rep chain has a different scaffold; excluded unless explicitly
  opted in (``include_tam``).
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["Candidate", "SpaceError", "build_space", "parse_cid",
           "space_direction"]


class SpaceError(ValueError):
    """Invalid tuning grid (mixed directions, dead/unknown/TAM ids,
    out-of-range axes). Always names the offending values."""


@dataclass(frozen=True)
class Candidate:
    """One point of the grid. ``cid`` is the canonical string id used as
    the JSON key in TUNE artifacts (JSON object keys must be strings)
    and in every race/elimination record."""

    method: int
    cb_nodes: int
    comm_size: int
    agg_type: int

    @property
    def cid(self) -> str:
        return (f"m{self.method}:a{self.cb_nodes}:"
                f"c{self.comm_size}:t{self.agg_type}")


def parse_cid(cid: str) -> Candidate:
    """Inverse of :attr:`Candidate.cid` — the replay path rebuilds
    candidates from recorded artifact keys with this, never via a
    backend."""
    try:
        parts = dict((p[0], int(p[1:])) for p in cid.split(":"))
        return Candidate(method=parts["m"], cb_nodes=parts["a"],
                         comm_size=parts["c"], agg_type=parts["t"])
    except (KeyError, ValueError, IndexError):
        raise SpaceError(f"malformed candidate id {cid!r} "
                         f"(expected 'mM:aA:cC:tT')")


def build_space(methods, cb_nodes_list, comm_sizes, agg_types, *,
                nprocs: int, include_tam: bool = False) -> list[Candidate]:
    """The validated candidate grid, in deterministic (input) order —
    the racing loop's tie-breaks depend on this order, so it is part of
    the reproducibility contract."""
    from tpu_aggcomm.core.methods import METHODS, method_ids

    methods = [int(m) for m in methods]
    cb_nodes_list = [int(a) for a in cb_nodes_list]
    comm_sizes = [int(c) for c in comm_sizes]
    agg_types = [int(t) for t in agg_types]
    if not (methods and cb_nodes_list and comm_sizes and agg_types):
        raise SpaceError("empty tuning grid: every axis needs at least "
                         "one value")

    unknown = sorted(m for m in methods if m not in METHODS)
    if unknown:
        raise SpaceError(f"unknown method id(s) {unknown}; valid ids: "
                         f"{sorted(METHODS)}")
    live = set(method_ids(include_dead=False))
    dead = sorted(m for m in methods if not METHODS[m].dispatched)
    if dead:
        raise SpaceError(
            f"dead method id(s) {dead} in the tuning grid: "
            f"{', '.join(f'm={m} ({METHODS[m].name})' for m in dead)} "
            f"are registered for parity but never dispatched — a tuned "
            f"winner must be a runnable method")
    tam = sorted(m for m in methods if METHODS[m].tam)
    if tam and not include_tam:
        raise SpaceError(
            f"TAM method id(s) {tam} in the tuning grid: the "
            f"hierarchical engine's rep has a different chain scaffold; "
            f"pass --include-tam to race them anyway")
    missing = sorted(m for m in methods if m not in live and m not in dead)
    if missing:
        # e.g. TAM ids when tam.engine is absent from the build
        raise SpaceError(f"method id(s) {missing} are not dispatchable "
                         f"in this build")

    by_dir: dict[str, list[int]] = {}
    for m in sorted(set(methods)):
        by_dir.setdefault(METHODS[m].direction.value, []).append(m)
    if len(by_dir) > 1:
        detail = "; ".join(f"{d}: {ids}" for d, ids in sorted(by_dir.items()))
        raise SpaceError(
            f"tuning grid mixes traffic directions ({detail}) — an "
            f"all-to-many winner and a many-to-all winner answer "
            f"different questions; tune each direction separately")

    bad_a = sorted(a for a in cb_nodes_list if not 1 <= a <= nprocs)
    if bad_a:
        raise SpaceError(f"cb_nodes value(s) {bad_a} outside "
                         f"[1, nprocs={nprocs}]")
    bad_c = sorted(c for c in comm_sizes if c < 1)
    if bad_c:
        raise SpaceError(f"comm_size value(s) {bad_c} must be >= 1")
    bad_t = sorted(t for t in agg_types if not 0 <= t <= 3)
    if bad_t:
        raise SpaceError(f"agg_type value(s) {bad_t} outside the "
                         f"reference's 0..3 placement policies")

    return [Candidate(method=m, cb_nodes=a, comm_size=c, agg_type=t)
            for m in methods for a in cb_nodes_list
            for c in comm_sizes for t in agg_types]


def space_direction(methods) -> str:
    """The (single, already-validated) direction of a method list — the
    cache-key field."""
    from tpu_aggcomm.core.methods import METHODS
    return METHODS[int(list(methods)[0])].direction.value
