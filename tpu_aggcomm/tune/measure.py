"""The jax-side tuner sampler — the ONLY tune module that touches jax.

Real tuning batches are fresh serial-chained differenced trials on the
jax_sim backend (``harness/chained.py`` scaffold — the honest
measurement through a tunneled TPU). The backend's ``measure_per_rep``
memoizes per schedule, which is exactly wrong for racing: every batch
must be a NEW measurement or the CI over batches collapses to the first
batch's samples. The sampler therefore drives the cache-bypassing
``JaxSimBackend.measure_trial_samples`` hook, while still reusing the
backend instance so jit-compiled chains are shared across batches of
the SAME candidate (re-timing is cheap; re-compiling per batch through
the tunnel is not).

Device facts are recorded into the ledger manifest before the first
sample (mirroring ``harness/runner._sample_device``) so the fingerprint
stamped into the TUNE artifact matches what a later ``--auto`` run in
the same environment computes.
"""

from __future__ import annotations

import contextlib
import threading

__all__ = ["record_device_facts", "make_jax_sim_sampler",
           "make_pallas_fused_sampler", "make_jax_shard_sampler",
           "PilotContentionError", "serve_dispatch_inflight"]


class PilotContentionError(RuntimeError):
    """A campaign sampler refused to measure while a serve dispatch is
    in flight on the same backend — the one-CPU-core discipline: two
    concurrent measured workloads inflate each other's differenced
    timings 2-3x, so a race sample taken under serve load is not a
    sample, it is noise with a seed."""


# serve-dispatch occupancy per backend name (module-level: the serve
# executor and any in-process campaign sampler share this registry).
_INFLIGHT: dict[str, int] = {}
_INFLIGHT_LOCK = threading.Lock()


@contextlib.contextmanager
def serve_dispatch_inflight(backend_name: str):
    """Mark one serve dispatch in flight on ``backend_name`` for the
    duration of the with-block (serve/server.py wraps its
    ``execute_batch`` call). jax-free — occupancy accounting only."""
    with _INFLIGHT_LOCK:
        _INFLIGHT[backend_name] = _INFLIGHT.get(backend_name, 0) + 1
    try:
        yield
    finally:
        with _INFLIGHT_LOCK:
            _INFLIGHT[backend_name] -= 1
            if _INFLIGHT[backend_name] <= 0:
                del _INFLIGHT[backend_name]


def _check_contention(backend_name: str) -> None:
    """Refuse by name when a serve dispatch is in flight on the backend
    a sampler is about to measure."""
    with _INFLIGHT_LOCK:
        n = _INFLIGHT.get(backend_name, 0)
    if n > 0:
        raise PilotContentionError(
            f"{n} serve dispatch(es) in flight on backend "
            f"{backend_name!r} — refusing to take race samples under "
            f"serve load (one-CPU-core contention skews differenced "
            f"timings 2-3x); retry when the serve queue drains")


def record_device_facts() -> None:
    """Fill the ledger manifest's platform/device_kind from the live
    jax client, so tune fingerprints and later --auto lookups see the
    same environment. Safe no-op when the device query fails."""
    import jax

    from tpu_aggcomm.obs import ledger
    try:
        dev = jax.devices()[0]
        ledger.record_device(platform=dev.platform,
                             device_kind=dev.device_kind)
    except Exception:  # lint: broad-ok (device record best-effort)
        pass


def make_jax_sim_sampler(*, nprocs: int, data_size: int, proc_node: int,
                         iters_small: int = 50, iters_big: int = 1050,
                         batch_trials: int = 3, windows: int = 1):
    """``sampler(cid, batch) -> list[float]`` over the single-device
    simulation backend: one compiled schedule per candidate (memoized),
    fresh differenced trials per batch."""
    from tpu_aggcomm.backends.jax_sim import JaxSimBackend
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.tune.space import parse_cid

    _check_contention("jax_sim")
    record_device_facts()
    backend = JaxSimBackend()
    schedules: dict[str, object] = {}

    def sampler(cid: str, batch: int) -> list[float]:
        _check_contention("jax_sim")
        if cid not in schedules:
            c = parse_cid(cid)
            schedules[cid] = compile_method(c.method, AggregatorPattern(
                nprocs=nprocs, cb_nodes=c.cb_nodes,
                data_size=max(data_size, 1), proc_node=proc_node,
                comm_size=c.comm_size, placement=c.agg_type))
        return backend.measure_trial_samples(
            schedules[cid], iters_small=iters_small, iters_big=iters_big,
            trials=batch_trials, windows=windows)

    return sampler


def make_jax_shard_sampler(*, nprocs: int, data_size: int, proc_node: int,
                           iters_small: int = 50, iters_big: int = 1050,
                           batch_trials: int = 3, windows: int = 1):
    """``sampler(cid, batch) -> list[float]`` over the XLA-partitioned
    multi-device tier — the 16,384-rank-class scaffold: fresh chained
    differenced trials through ``JaxShardBackend.measure_trial_samples``
    (compiled chains memoized per candidate, samples never cached). The
    backend's own refusals propagate by name: TAM candidates have no
    round chain here, and staged (dead-link-repaired) schedules are
    refused in the table lowering by design — race those on jax_sim."""
    from tpu_aggcomm.backends.jax_shard import JaxShardBackend
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.tune.space import parse_cid

    _check_contention("jax_shard")
    record_device_facts()
    backend = JaxShardBackend()
    schedules: dict[str, object] = {}

    def sampler(cid: str, batch: int) -> list[float]:
        _check_contention("jax_shard")
        if cid not in schedules:
            c = parse_cid(cid)
            schedules[cid] = compile_method(c.method, AggregatorPattern(
                nprocs=nprocs, cb_nodes=c.cb_nodes,
                data_size=max(data_size, 1), proc_node=proc_node,
                comm_size=c.comm_size, placement=c.agg_type))
        return backend.measure_trial_samples(
            schedules[cid], iters_small=iters_small, iters_big=iters_big,
            trials=batch_trials, windows=windows)

    return sampler


def make_pallas_fused_sampler(*, nprocs: int, data_size: int,
                              proc_node: int, iters_small: int = 50,
                              iters_big: int = 1050, batch_trials: int = 3,
                              windows: int = 1):
    """``sampler(cid, batch) -> list[float]`` over the fused-kernel
    backend — the same chained differenced scaffold as the jax_sim
    sampler (PallasFusedBackend subclasses it), so the tuner can race
    fused vs fenced under one measurement discipline. An unfusable
    candidate raises its NAMED refusal out of the race rather than
    returning fabricated samples."""
    from tpu_aggcomm.backends.pallas_fused import PallasFusedBackend
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.tune.space import parse_cid

    _check_contention("pallas_fused")
    record_device_facts()
    backend = PallasFusedBackend()
    schedules: dict[str, object] = {}

    def sampler(cid: str, batch: int) -> list[float]:
        _check_contention("pallas_fused")
        if cid not in schedules:
            c = parse_cid(cid)
            schedules[cid] = compile_method(c.method, AggregatorPattern(
                nprocs=nprocs, cb_nodes=c.cb_nodes,
                data_size=max(data_size, 1), proc_node=proc_node,
                comm_size=c.comm_size, placement=c.agg_type))
        return backend.measure_trial_samples(
            schedules[cid], iters_small=iters_small, iters_big=iters_big,
            trials=batch_trials, windows=windows)

    return sampler
