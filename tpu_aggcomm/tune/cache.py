"""The tuned-schedule cache: persistent ``TUNE_*.json`` artifacts.

One artifact per ``(shape, direction, backend)`` key, stamped with a
**manifest fingerprint** derived from the v3 run ledger
(obs/ledger.py): sha256 over the flattened manifest minus the
``DRIFT_IGNORE`` prefixes, i.e. exactly the keys ``diff_manifests``
considers drift. By construction: no drift between two manifests ⟺
identical fingerprints — so a jax/libtpu/device-kind change invalidates
the cache entry through the same lens ``--check-regression`` uses to
explain deltas, and :func:`lookup` reports WHICH keys drifted instead
of a bare miss.

Artifact schema (``"tune-v1"``, validated by
``obs/regress.validate_tune`` and ``scripts/check_bench_schema.py``)::

    {"schema": "tune-v1",
     "key": {nprocs, data_size, proc_node, direction, backend,
             fingerprint},
     "manifest": {...v3 ledger manifest...},
     "space": {methods, cb_nodes, comm_sizes, agg_types},
     "race": {seed, alpha, n_boot, max_batches, batch_trials, order,
              samples: {cid: [[trial s, ...], ...]},
              eliminations: [...], winner, batches_run, survivors},
     "winner": {method, cb_nodes, comm_size, agg_type},
     "synthetic": bool, "created_unix": float}

Everything here is jax-free (stdlib + obs/ledger): the ``--auto``
resolution path and ``cli tune --replay`` run where jax may not import.
Like every committed artifact, the stored manifest records arming env
vars by NAME only (harness.hostenv.env_summary) — pool IPs never land
in a TUNE file.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import time

from tpu_aggcomm.obs.ledger import DRIFT_IGNORE, _flatten, diff_manifests

__all__ = ["TUNE_SCHEMA", "manifest_fingerprint", "tune_key",
           "artifact_path", "save_tune", "load_tune", "lookup",
           "tune_paths"]

#: The artifact schema tag (versioned like the bench parsed-schema
#: v2/v3 generations; obs/regress.validate_tune pins the shape).
TUNE_SCHEMA = "tune-v1"


def manifest_fingerprint(manifest: dict | None) -> str:
    """Stable hex digest of the drift-relevant manifest content.

    Flattened keys with a ``DRIFT_IGNORE`` prefix (timestamps, the
    tunnel's per-run RPC probe, the git sha) are excluded — the same
    exclusions ``diff_manifests`` applies, so two manifests share a
    fingerprint exactly when the ledger would report no drift between
    them."""
    flat = _flatten(manifest or {})
    items = sorted((k, v) for k, v in flat.items()
                   if not k.startswith(DRIFT_IGNORE))
    blob = json.dumps(items, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def tune_key(*, nprocs: int, data_size: int, proc_node: int,
             direction: str, backend: str,
             manifest: dict | None = None) -> dict:
    """The cache key for one tuning context. ``fingerprint`` binds the
    entry to the environment that measured it."""
    return {"nprocs": int(nprocs), "data_size": int(data_size),
            "proc_node": int(proc_node), "direction": str(direction),
            "backend": str(backend),
            "fingerprint": manifest_fingerprint(manifest)}


def artifact_path(root: str, key: dict) -> str:
    """Deterministic artifact filename for a key (fingerprint excluded:
    a re-tune after an environment change REPLACES the stale entry for
    the same shape instead of accumulating unreachable ones)."""
    d = "a2m" if key["direction"] == "all_to_many" else "m2a"
    name = (f"TUNE_{key['backend']}_n{key['nprocs']}"
            f"_d{key['data_size']}_p{key['proc_node']}_{d}.json")
    return os.path.join(root, name)


def tune_paths(root: str) -> list[str]:
    return sorted(glob.glob(os.path.join(root, "TUNE_*.json")))


def save_tune(root: str, *, key: dict, manifest: dict | None,
              space: dict, race: dict, winner: dict,
              synthetic: bool = False,
              model_prune: dict | None = None) -> str:
    blob = {"schema": TUNE_SCHEMA, "key": dict(key),
            "manifest": manifest, "space": dict(space),
            "race": dict(race), "winner": dict(winner),
            "synthetic": bool(synthetic),
            "created_unix": time.time()}
    if model_prune is not None:
        # the --model-prune record (cli._model_prune): which committed
        # PREDICT artifact priced the grid, at what margin, and the
        # resulting kept/pruned split — enough for --replay to re-derive
        # the split with no model import
        blob["model_prune"] = dict(model_prune)
    path = artifact_path(root, key)
    from tpu_aggcomm.obs.atomic import atomic_write
    with atomic_write(path) as fh:
        json.dump(blob, fh, indent=1)
        fh.write("\n")
    return path


def load_tune(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def lookup(root: str, key: dict, *,
           manifest: dict | None = None) -> tuple[dict | None, str | None]:
    """Resolve a tuned entry for ``key``: ``(entry, None)`` on a hit,
    ``(None, reason)`` on a miss — where ``reason`` distinguishes "no
    artifact", "schema-invalid artifact" and "manifest drift" (with the
    drifted keys named), because ``--auto``'s fallback warning must say
    WHY the cache did not serve."""
    path = artifact_path(root, key)
    if not os.path.exists(path):
        return None, f"no tuned entry at {path}"
    try:
        entry = load_tune(path)
    except (OSError, ValueError) as e:
        return None, f"unreadable tune artifact {path}: {e}"
    from tpu_aggcomm.obs.regress import validate_tune
    errors = validate_tune(entry, os.path.basename(path))
    if errors:
        return None, (f"invalid tune artifact {path}: {errors[0]}"
                      + (f" (+{len(errors) - 1} more)"
                         if len(errors) > 1 else ""))
    ekey = entry.get("key", {})
    for k in ("nprocs", "data_size", "proc_node", "direction", "backend"):
        if ekey.get(k) != key.get(k):
            return None, (f"tune artifact {path} is for a different "
                          f"context ({k}={ekey.get(k)!r}, want "
                          f"{key.get(k)!r})")
    want = key.get("fingerprint")
    have = ekey.get("fingerprint")
    if want is not None and have != want:
        drift = diff_manifests(entry.get("manifest"), manifest)
        keys = ", ".join(d["key"] for d in drift[:4]) or "unknown keys"
        more = f" (+{len(drift) - 4} more)" if len(drift) > 4 else ""
        return None, (f"manifest drift vs tuned entry {path}: "
                      f"{keys}{more} — re-tune in this environment")
    return entry, None
