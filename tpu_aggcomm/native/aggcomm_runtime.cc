// Native schedule executor: N-thread rank runtime with MPI-faithful
// message semantics.
//
// This is the framework's native runtime tier (the reference's entire
// program is native C — SURVEY.md §2 "no component may be a pure-Python
// stand-in"). It executes the same compiled op programs as the Python
// backends, but with REAL concurrency semantics:
//
//   - ISSEND (MPI_Issend analog): completes only when the matching receive
//     is posted — true rendezvous, the congestion-exposing behavior the
//     reference builds its sync/half-sync studies on (mpi_test.c Issend
//     call sites).
//   - ISEND: eager — payload buffered at post time, completes immediately.
//   - SEND/RECV/SENDRECV: blocking (standard-mode send = eager buffer).
//   - WAITALL over explicit token sets; BARRIER via shared generation
//     counter; 0-byte SIGNAL channel (the dup'ed signal_comm analog,
//     mpi_test.c:1252); ALLTOALLW as barrier + direct shared-memory copy.
//
// Each rank is one thread; channels are per-(src,dst[,signal]) FIFO queues
// (message matching per directed pair is unique per rep in every reference
// schedule; FIFO covers the multi-rep no-resync case, mpi_test.c:2150).
// Per-op timer buckets mirror the reference's MPI_Wtime bracketing.
//
// C ABI only (ctypes-friendly); no Python.h dependency.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

enum OpKind : int32_t {
  kIsend = 0,
  kIssend = 1,
  kIrecv = 2,
  kSend = 3,
  kRecv = 4,
  kSendrecv = 5,
  kWaitall = 6,
  kBarrier = 7,
  kCopy = 8,
  kSignalSend = 9,
  kSignalRecv = 10,
  kAlltoallw = 11,
};

enum Bucket : int32_t {
  kPost = 0,
  kRecvWait = 1,
  kSendWait = 2,
  kRecvAndSendWait = 3,
  kBarrierB = 4,
  kNone = 5,
};

struct NOp {
  int32_t kind;
  int32_t peer;
  int32_t slot;
  int32_t peer2;
  int32_t slot2;
  int32_t token;
  int32_t nbytes;
  int32_t bucket;
  int32_t ntokens;   // WAITALL: number of tokens
  int32_t tok_ofs;   // WAITALL: offset into wait_tokens array
};

struct Timer5 {
  double post = 0, send_wait = 0, recv_wait = 0, barrier = 0, total = 0;
};

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// One message in flight on a channel. Rendezvous (Issend) vs eager (Isend/
// Send) is expressed through `send_done`: rendezvous sends pass their token
// flag (set at match time); eager sends pass null (flag set at post time).
struct Msg {
  const uint8_t* src_data = nullptr;  // sender slab (valid whole run)
  int32_t nbytes = 0;
  std::atomic<bool>* send_done = nullptr;
};

struct Channel {
  std::deque<Msg> sends;
  std::deque<std::pair<uint8_t*, std::atomic<bool>*>> recvs;  // dst buf, flag
};

struct Runtime {
  int n;
  std::mutex mu;                 // single lock: correctness over scalability
  std::condition_variable cv;    // (1-core image; contention is the workload)
  std::vector<Channel> data_ch;  // n*n
  std::vector<std::deque<int>> signal_ch;  // n*n: queue of 0-byte signals
  // barrier
  int barrier_waiting = 0;
  int64_t barrier_gen = 0;
  // alltoallw rendezvous
  int a2a_waiting = 0;
  int64_t a2a_gen = 0;

  explicit Runtime(int nprocs)
      : n(nprocs), data_ch(nprocs * nprocs), signal_ch(nprocs * nprocs) {}

  Channel& ch(int src, int dst) { return data_ch[src * n + dst]; }

  // Generation-counted rendezvous of all n ranks; caller holds `lk` on mu.
  void gen_barrier(std::unique_lock<std::mutex>& lk, int& waiting,
                   int64_t& gen) {
    int64_t my_gen = gen;
    if (++waiting == n) {
      waiting = 0;
      ++gen;
      cv.notify_all();
    } else {
      cv.wait(lk, [&] { return gen != my_gen; });
    }
  }

  // Try to match the channel head send/recv; called with mu held.
  void match(int src, int dst) {
    Channel& c = ch(src, dst);
    while (!c.sends.empty() && !c.recvs.empty()) {
      Msg m = c.sends.front();
      c.sends.pop_front();
      auto [buf, rflag] = c.recvs.front();
      c.recvs.pop_front();
      if (m.nbytes > 0 && buf != nullptr && m.src_data != nullptr) {
        std::memcpy(buf, m.src_data, m.nbytes);
      }
      if (m.send_done) m.send_done->store(true, std::memory_order_release);
      if (rflag) rflag->store(true, std::memory_order_release);
    }
  }
};

struct RankCtx {
  Runtime* rt;
  int rank;
  const NOp* ops;
  int nops;
  const int32_t* wait_tokens;
  // slab bases
  const uint8_t* send_base;   // this rank's send slabs (nslots * data_size)
  uint8_t* recv_base;         // this rank's recv slabs
  int data_size;
  // token flags for this rank
  std::vector<std::atomic<bool>> flags;
  Timer5* timers;             // per-rep Timer array (ntimes entries)
  // global alltoallw inputs
  const uint8_t* const* all_send_bases;
  const int32_t* a2a_src_slot;  // per (dst,src): sender slot or -1
  const int32_t* a2a_dst_slot;  // per (dst,src): recv slot
};

void run_rank(RankCtx* cx, int ntimes) {
  Runtime& rt = *cx->rt;
  const int n = rt.n;
  for (int rep = 0; rep < ntimes; ++rep) {
    Timer5& t = cx->timers[rep];
    for (auto& f : cx->flags) f.store(false, std::memory_order_relaxed);
    double rep_start = now_s();
    for (int i = 0; i < cx->nops; ++i) {
      const NOp& op = cx->ops[i];
      double t0 = now_s();
      switch (op.kind) {
        case kIsend:
        case kIssend: {
          std::unique_lock<std::mutex> lk(rt.mu);
          Msg m;
          m.src_data = cx->send_base + (size_t)op.slot * cx->data_size;
          m.nbytes = op.nbytes;
          m.send_done = &cx->flags[op.token];
          if (op.kind == kIsend) {
            // eager: complete at post; payload stays valid (deterministic
            // fill is never overwritten), so the copy happens at match.
            cx->flags[op.token].store(true, std::memory_order_release);
            m.send_done = nullptr;
          }
          rt.ch(cx->rank, op.peer).sends.push_back(m);
          rt.match(cx->rank, op.peer);
          rt.cv.notify_all();
          break;
        }
        case kIrecv: {
          std::unique_lock<std::mutex> lk(rt.mu);
          uint8_t* buf = cx->recv_base + (size_t)op.slot * cx->data_size;
          rt.ch(op.peer, cx->rank).recvs.push_back({buf, &cx->flags[op.token]});
          rt.match(op.peer, cx->rank);
          rt.cv.notify_all();
          break;
        }
        case kSend: {
          // standard-mode blocking send: eager buffer semantics (see the
          // oracle's rationale — strict rendezvous deadlocks m=6/7)
          std::unique_lock<std::mutex> lk(rt.mu);
          Msg m;
          m.src_data = cx->send_base + (size_t)op.slot * cx->data_size;
          m.nbytes = op.nbytes;
          rt.ch(cx->rank, op.peer).sends.push_back(m);
          rt.match(cx->rank, op.peer);
          rt.cv.notify_all();
          break;
        }
        case kRecv: {
          std::unique_lock<std::mutex> lk(rt.mu);
          uint8_t* buf = cx->recv_base + (size_t)op.slot * cx->data_size;
          std::atomic<bool> done{false};
          rt.ch(op.peer, cx->rank).recvs.push_back({buf, &done});
          rt.match(op.peer, cx->rank);
          rt.cv.notify_all();
          rt.cv.wait(lk, [&] { return done.load(std::memory_order_acquire); });
          break;
        }
        case kSendrecv: {
          // pairwise methods post zero-byte slots with slot = -1 and
          // receivers without buffers (mpi_test.c:466-478); never form the
          // pointer in those cases (UB even if unread)
          std::unique_lock<std::mutex> lk(rt.mu);
          Msg m;
          m.src_data = (op.nbytes > 0 && op.slot >= 0)
                           ? cx->send_base + (size_t)op.slot * cx->data_size
                           : nullptr;
          m.nbytes = op.nbytes;
          rt.ch(cx->rank, op.peer).sends.push_back(m);
          rt.match(cx->rank, op.peer);
          uint8_t* buf = (cx->recv_base != nullptr && op.slot2 >= 0)
                             ? cx->recv_base + (size_t)op.slot2 * cx->data_size
                             : nullptr;
          std::atomic<bool> done{false};
          rt.ch(op.peer2, cx->rank).recvs.push_back({buf, &done});
          rt.match(op.peer2, cx->rank);
          rt.cv.notify_all();
          rt.cv.wait(lk, [&] { return done.load(std::memory_order_acquire); });
          break;
        }
        case kWaitall: {
          std::unique_lock<std::mutex> lk(rt.mu);
          rt.cv.wait(lk, [&] {
            for (int k = 0; k < op.ntokens; ++k) {
              int tok = cx->wait_tokens[op.tok_ofs + k];
              if (!cx->flags[tok].load(std::memory_order_acquire)) return false;
            }
            return true;
          });
          break;
        }
        case kBarrier: {
          std::unique_lock<std::mutex> lk(rt.mu);
          rt.gen_barrier(lk, rt.barrier_waiting, rt.barrier_gen);
          break;
        }
        case kCopy: {
          std::memcpy(cx->recv_base + (size_t)op.slot2 * cx->data_size,
                      cx->send_base + (size_t)op.slot * cx->data_size,
                      cx->data_size);
          break;
        }
        case kSignalSend: {
          std::unique_lock<std::mutex> lk(rt.mu);
          rt.signal_ch[cx->rank * n + op.peer].push_back(1);
          if (op.token >= 0)
            cx->flags[op.token].store(true, std::memory_order_release);
          rt.cv.notify_all();
          break;
        }
        case kSignalRecv: {
          std::unique_lock<std::mutex> lk(rt.mu);
          auto& q = rt.signal_ch[op.peer * n + cx->rank];
          rt.cv.wait(lk, [&] { return !q.empty(); });
          q.pop_front();
          break;
        }
        case kAlltoallw: {
          // barrier in, shared-memory exchange, barrier out — the whole
          // pattern in "one collective" (mpi_test.c:627/912)
          std::unique_lock<std::mutex> lk(rt.mu);
          rt.gen_barrier(lk, rt.a2a_waiting, rt.a2a_gen);
          lk.unlock();
          if (cx->recv_base != nullptr) {
            for (int src = 0; src < n; ++src) {
              int32_t ss = cx->a2a_src_slot[cx->rank * n + src];
              if (ss < 0) continue;
              int32_t ds = cx->a2a_dst_slot[cx->rank * n + src];
              std::memcpy(cx->recv_base + (size_t)ds * cx->data_size,
                          cx->all_send_bases[src] + (size_t)ss * cx->data_size,
                          cx->data_size);
            }
          }
          // closing barrier so no rank races into the next rep's exchange
          lk.lock();
          rt.gen_barrier(lk, rt.a2a_waiting, rt.a2a_gen);
          break;
        }
      }
      double dt = now_s() - t0;
      switch (op.bucket) {
        case kPost: t.post += dt; break;
        case kRecvWait: t.recv_wait += dt; break;
        case kSendWait: t.send_wait += dt; break;
        case kRecvAndSendWait: t.recv_wait += dt; t.send_wait += dt; break;
        case kBarrierB: t.barrier += dt; break;
        default: break;
      }
    }
    t.total = now_s() - rep_start;
  }
}

// ---------------------------------------------------------------------------
// Variable-size workload engine: the collective_write proxy route executed
// natively. One thread per rank; the five phases of the reference's
// production engine (intra-node pack+gather to the node proxy, proxy↔proxy
// per-node runs, local delivery, scatter) are real memcpy walks between
// thread-shared staging buffers — the hot loops the reference times
// (pack cursors, run reorder, per-rank re-pack).
//
// Buffer layouts (all byte offsets precomputed before threads start):
//   send_msgs:  per src rank, its G messages in ascending-aggregator order,
//               each msg_sizes[src] bytes (block size G * msg_sizes[src]).
//   aggregate:  per node, local ranks' blocks in ascending-rank order.
//   run b1->b2: for src on b1 ascending, for each aggregator on b2
//               ascending: the (src -> agg) message.
//   delivery / recv_out row of aggregator g: for src in GLOBAL ascending
//               order: the (src -> g) message.

struct WlGeom {
  int n, nn, G;
  const int32_t* node_of;
  const int32_t* proxies;
  const int32_t* aggs;        // ascending aggregator ranks
  const int32_t* msg_sizes;   // per src
  std::vector<std::vector<int>> node_ranks;   // per node, ascending
  std::vector<std::vector<int>> node_aggs;    // per node, ascending gi
  std::vector<int> agg_of_rank;               // rank -> gi or -1
  std::vector<int64_t> block_bytes;           // per src: G * msg_sizes[src]
  std::vector<int64_t> agg_ofs;               // per node: aggregate offset of
                                              // each local rank (flattened)
  std::vector<int64_t> agg_ofs_start;         // per node: index into agg_ofs
  std::vector<int64_t> agg_total;             // per node: aggregate bytes
  std::vector<int64_t> run_bytes;             // (b1, b2) run size
  std::vector<int64_t> src_run_base;          // per src: its base offset in
                                              // the run node_of[src] -> b2,
                                              // PER dest node (n * nn)
  std::vector<int64_t> recv_src_ofs;          // per src: offset of its msg in
                                              // any delivery slab
  int64_t slab_bytes = 0;                     // delivery slab size

  WlGeom(int n_, int nn_, int G_, const int32_t* node_of_,
         const int32_t* proxies_, const int32_t* aggs_,
         const int32_t* msg_sizes_)
      : n(n_), nn(nn_), G(G_), node_of(node_of_), proxies(proxies_),
        aggs(aggs_), msg_sizes(msg_sizes_) {
    node_ranks.resize(nn);
    node_aggs.resize(nn);
    agg_of_rank.assign(n, -1);
    for (int r = 0; r < n; ++r) node_ranks[node_of[r]].push_back(r);
    for (int gi = 0; gi < G; ++gi) {
      agg_of_rank[aggs[gi]] = gi;
      node_aggs[node_of[aggs[gi]]].push_back(gi);
    }
    block_bytes.resize(n);
    for (int r = 0; r < n; ++r)
      block_bytes[r] = (int64_t)G * msg_sizes[r];
    agg_ofs_start.assign(nn + 1, 0);
    agg_total.assign(nn, 0);
    for (int b = 0; b < nn; ++b)
      agg_ofs_start[b + 1] = agg_ofs_start[b] + (int64_t)node_ranks[b].size();
    agg_ofs.assign(agg_ofs_start[nn], 0);
    for (int b = 0; b < nn; ++b) {
      int64_t cur = 0;
      for (size_t i = 0; i < node_ranks[b].size(); ++i) {
        agg_ofs[agg_ofs_start[b] + i] = cur;
        cur += block_bytes[node_ranks[b][i]];
      }
      agg_total[b] = cur;
    }
    run_bytes.assign((int64_t)nn * nn, 0);
    src_run_base.assign((int64_t)n * nn, 0);
    for (int b1 = 0; b1 < nn; ++b1) {
      for (int b2 = 0; b2 < nn; ++b2) {
        int64_t cur = 0;
        for (int src : node_ranks[b1]) {
          src_run_base[(int64_t)src * nn + b2] = cur;
          cur += (int64_t)msg_sizes[src] * node_aggs[b2].size();
        }
        run_bytes[(int64_t)b1 * nn + b2] = cur;
      }
    }
    recv_src_ofs.assign(n, 0);
    int64_t cur = 0;
    for (int src = 0; src < n; ++src) {
      recv_src_ofs[src] = cur;
      cur += msg_sizes[src];
    }
    slab_bytes = cur;
  }

  // position of aggregator gi within its node's ascending list
  int agg_pos_on_node(int gi) const {
    const auto& v = node_aggs[node_of[aggs[gi]]];
    for (size_t j = 0; j < v.size(); ++j)
      if (v[j] == gi) return (int)j;
    return 0;
  }
};

// Eager send: payload stays valid until matched (guaranteed by the
// end-of-rep barrier); completes at post like the runtime's kIsend.
void wl_post_send(Runtime& rt, int src, int dst, const uint8_t* data,
                  int64_t nbytes) {
  if (nbytes <= 0) return;
  std::unique_lock<std::mutex> lk(rt.mu);
  Msg m;
  m.src_data = data;
  m.nbytes = (int32_t)nbytes;
  rt.ch(src, dst).sends.push_back(m);
  rt.match(src, dst);
  rt.cv.notify_all();
}

// Blocking receive into `buf`.
void wl_recv(Runtime& rt, int src, int dst, uint8_t* buf) {
  std::unique_lock<std::mutex> lk(rt.mu);
  std::atomic<bool> done{false};
  rt.ch(src, dst).recvs.push_back({buf, &done});
  rt.match(src, dst);
  rt.cv.notify_all();
  rt.cv.wait(lk, [&] { return done.load(std::memory_order_acquire); });
}

struct WlShared {
  Runtime* rt;
  const WlGeom* g;
  const uint8_t* send_msgs;
  const int64_t* send_block_ofs;   // per src: byte offset of its block
  uint8_t* recv_out;               // G slabs, slab_bytes each
  std::vector<std::vector<uint8_t>> aggregate;   // per node
  std::vector<std::vector<uint8_t>> run_out;     // (b1, b2) packed runs
  std::vector<std::vector<uint8_t>> run_in;      // (b2, b1) received runs
  std::vector<std::vector<uint8_t>> deliver;     // per gi staging slab
};

void wl_run_rank(WlShared* sh, int rank, int ntimes, double* rep_times) {
  Runtime& rt = *sh->rt;
  const WlGeom& g = *sh->g;
  const int b = g.node_of[rank];
  const bool proxy = (g.proxies[b] == rank);
  const int gi_self = g.agg_of_rank[rank];

  for (int rep = 0; rep < ntimes; ++rep) {
    double t0 = now_s();
    // P2: pack + gather at the node proxy (l_d_t.c:1069-1105)
    if (!proxy) {
      wl_post_send(rt, rank, g.proxies[b],
                   sh->send_msgs + sh->send_block_ofs[rank],
                   g.block_bytes[rank]);
    } else {
      uint8_t* abuf = sh->aggregate[b].data();
      for (size_t i = 0; i < g.node_ranks[b].size(); ++i) {
        int lr = g.node_ranks[b][i];
        int64_t ofs = g.agg_ofs[g.agg_ofs_start[b] + i];
        if (lr == rank) {
          std::memcpy(abuf + ofs, sh->send_msgs + sh->send_block_ofs[lr],
                      g.block_bytes[lr]);
        } else if (g.block_bytes[lr] > 0) {
          wl_recv(rt, lr, rank, abuf + ofs);
        }
      }
      // P3: reorder into per-destination-node runs and exchange
      // (l_d_t.c:1121-1194)
      for (int b2 = 0; b2 < g.nn; ++b2) {
        uint8_t* run = sh->run_out[(int64_t)b * g.nn + b2].data();
        int64_t cur = 0;
        for (size_t i = 0; i < g.node_ranks[b].size(); ++i) {
          int src = g.node_ranks[b][i];
          const uint8_t* blk = abuf + g.agg_ofs[g.agg_ofs_start[b] + i];
          for (int gi : g.node_aggs[b2]) {
            std::memcpy(run + cur, blk + (int64_t)gi * g.msg_sizes[src],
                        g.msg_sizes[src]);
            cur += g.msg_sizes[src];
          }
        }
        if (b2 == b) {
          // self-node run: local memcpy (l_d_t.c:1184)
          std::memcpy(sh->run_in[(int64_t)b * g.nn + b].data(), run, cur);
        } else {
          wl_post_send(rt, rank, g.proxies[b2], run, cur);
        }
      }
      for (int b1 = 0; b1 < g.nn; ++b1) {
        if (b1 == b) continue;
        if (g.run_bytes[(int64_t)b1 * g.nn + b] == 0) continue;
        wl_recv(rt, g.proxies[b1], rank,
                sh->run_in[(int64_t)b * g.nn + b1].data());
      }
      // P4: re-pack one delivery slab per local aggregator and deliver
      // (l_d_t.c:1219-1265)
      for (int gi : g.node_aggs[b]) {
        int agg_rank = g.aggs[gi];
        int pos = g.agg_pos_on_node(gi);
        uint8_t* slab = (agg_rank == rank)
                            ? sh->recv_out + (int64_t)gi * g.slab_bytes
                            : sh->deliver[gi].data();
        for (int src = 0; src < g.n; ++src) {
          int b1 = g.node_of[src];
          const uint8_t* run = sh->run_in[(int64_t)b * g.nn + b1].data();
          int64_t o = g.src_run_base[(int64_t)src * g.nn + b] +
                      (int64_t)pos * g.msg_sizes[src];
          std::memcpy(slab + g.recv_src_ofs[src], run + o, g.msg_sizes[src]);
        }
        if (agg_rank != rank) {
          wl_post_send(rt, rank, agg_rank, slab, g.slab_bytes);
        }
      }
    }
    // P5: non-proxy aggregators receive their slab straight into recv_out
    if (gi_self >= 0 && !proxy && g.slab_bytes > 0) {
      wl_recv(rt, g.proxies[b], rank,
              sh->recv_out + (int64_t)gi_self * g.slab_bytes);
    }
    // end-of-rep rendezvous: staging buffers are reused next rep
    {
      std::unique_lock<std::mutex> lk(rt.mu);
      rt.gen_barrier(lk, rt.barrier_waiting, rt.barrier_gen);
    }
    rep_times[rep] = now_s() - t0;
  }
}

// ---------------------------------------------------------------------------
// collective_write2 (l_d_t.c:754-926): two-level local-aggregator route.
//
// Layouts:
//   send block of src:  G messages in ascending-aggregator order
//   group j (laggs[j]): members = ranks with owner_of[r] == laggs[j],
//                       ascending (the local aggregator owns itself)
//   group staging:      members' blocks back-to-back, member-ascending
//   segment (j -> gi):  for member src ascending, the (src -> gi) message
//                       (the inclusive-prefix-sum pack of l_d_t.c:881-904)
//   delivery slab gi:   for src in GLOBAL ascending order, its message
//                       (the hindexed recv view, create_recv_type 1332-1361,
//                       realized as an explicit scatter after the receive)

struct Cw2Shared {
  Runtime* rt;
  int n, G, nl;
  const int32_t* aggs;
  const int32_t* msg_sizes;
  const int32_t* owner_of;
  const int32_t* laggs;
  const uint8_t* send_msgs;
  const int64_t* send_block_ofs;
  uint8_t* recv_out;
  std::vector<std::vector<int>> members;        // per group, ascending
  std::vector<int> group_of_rank;               // rank -> group or -1
  std::vector<int> agg_of_rank;                 // rank -> gi or -1
  std::vector<int64_t> block_bytes;             // per src
  std::vector<int64_t> seg_total;               // per group
  std::vector<int64_t> recv_src_ofs;            // per src
  int64_t slab_bytes = 0;
  std::vector<std::vector<uint8_t>> stage;      // per group
  std::vector<std::vector<int64_t>> stage_ofs;  // per group: member offsets
  std::vector<std::vector<uint8_t>> seg_out;    // per group: G segments
  std::vector<std::vector<uint8_t>> seg_in;     // per gi: staging
};

void cw2_run_rank(Cw2Shared* sh, int rank, int ntimes, double* rep_times) {
  Runtime& rt = *sh->rt;
  const int j_self = sh->group_of_rank[rank];
  const int gi_self = sh->agg_of_rank[rank];
  const int owner = sh->owner_of[rank];
  for (int rep = 0; rep < ntimes; ++rep) {
    double t0 = now_s();
    // hop 1: member -> its local aggregator (packed send, l_d_t.c:848-856)
    if (owner != rank && sh->block_bytes[rank] > 0) {
      wl_post_send(rt, rank, owner,
                   sh->send_msgs + sh->send_block_ofs[rank],
                   sh->block_bytes[rank]);
    }
    if (j_self >= 0) {
      auto& st = sh->stage[j_self];
      for (size_t i = 0; i < sh->members[j_self].size(); ++i) {
        int m = sh->members[j_self][i];
        uint8_t* dstp = st.data() + sh->stage_ofs[j_self][i];
        if (m == rank) {
          std::memcpy(dstp, sh->send_msgs + sh->send_block_ofs[m],
                      sh->block_bytes[m]);
        } else if (sh->block_bytes[m] > 0) {
          wl_recv(rt, m, rank, dstp);
        }
      }
      // hop 2: one packed segment per global destination
      auto& so = sh->seg_out[j_self];
      const int64_t segsz = sh->seg_total[j_self];
      for (int gi = 0; gi < sh->G; ++gi) {
        uint8_t* seg = so.data() + (int64_t)gi * segsz;
        int64_t cur = 0;
        for (size_t i = 0; i < sh->members[j_self].size(); ++i) {
          int src = sh->members[j_self][i];
          const uint8_t* blk = st.data() + sh->stage_ofs[j_self][i];
          std::memcpy(seg + cur, blk + (int64_t)gi * sh->msg_sizes[src],
                      sh->msg_sizes[src]);
          cur += sh->msg_sizes[src];
        }
        int dst = sh->aggs[gi];
        if (dst == rank) {
          // self segment: direct scatter (the memcpy arm)
          uint8_t* slab = sh->recv_out + (int64_t)gi * sh->slab_bytes;
          int64_t o = 0;
          for (int src : sh->members[j_self]) {
            std::memcpy(slab + sh->recv_src_ofs[src], seg + o,
                        sh->msg_sizes[src]);
            o += sh->msg_sizes[src];
          }
        } else if (segsz > 0) {
          wl_post_send(rt, rank, dst, seg, segsz);
        }
      }
    }
    // destination: one segment per group, scattered via the recv index map
    if (gi_self >= 0) {
      uint8_t* slab = sh->recv_out + (int64_t)gi_self * sh->slab_bytes;
      auto& in = sh->seg_in[gi_self];
      for (int j = 0; j < sh->nl; ++j) {
        if (sh->laggs[j] == rank) continue;  // own group handled above
        if (sh->seg_total[j] <= 0) continue;
        wl_recv(rt, sh->laggs[j], rank, in.data());
        int64_t o = 0;
        for (int src : sh->members[j]) {
          std::memcpy(slab + sh->recv_src_ofs[src], in.data() + o,
                      sh->msg_sizes[src]);
          o += sh->msg_sizes[src];
        }
      }
    }
    {
      std::unique_lock<std::mutex> lk(rt.mu);
      rt.gen_barrier(lk, rt.barrier_waiting, rt.barrier_gen);
    }
    rep_times[rep] = now_s() - t0;
  }
}

}  // namespace

extern "C" {

// Execute the collective_write2 two-level route natively. laggs is the
// group order (meta.local_aggregators); owner_of binds each rank to its
// local aggregator. Other layouts match agg_run_workload_proxy.
int agg_run_workload_cw2(int nprocs, int n_aggs, int n_laggs, int ntimes,
                         const int32_t* aggs, const int32_t* msg_sizes,
                         const int32_t* owner_of, const int32_t* laggs,
                         const uint8_t* send_msgs,
                         const int64_t* send_block_ofs,
                         uint8_t* recv_out, double* rep_times_out) {
  Cw2Shared sh;
  Runtime rt(nprocs);
  sh.rt = &rt;
  sh.n = nprocs;
  sh.G = n_aggs;
  sh.nl = n_laggs;
  sh.aggs = aggs;
  sh.msg_sizes = msg_sizes;
  sh.owner_of = owner_of;
  sh.laggs = laggs;
  sh.send_msgs = send_msgs;
  sh.send_block_ofs = send_block_ofs;
  sh.recv_out = recv_out;

  sh.group_of_rank.assign(nprocs, -1);
  for (int j = 0; j < n_laggs; ++j) sh.group_of_rank[laggs[j]] = j;
  sh.agg_of_rank.assign(nprocs, -1);
  for (int gi = 0; gi < n_aggs; ++gi) sh.agg_of_rank[aggs[gi]] = gi;
  sh.members.resize(n_laggs);
  for (int r = 0; r < nprocs; ++r) {
    if (owner_of[r] < 0 || owner_of[r] >= nprocs) return 1;  // unbound rank
    int j = sh.group_of_rank[owner_of[r]];
    if (j < 0) return 1;  // binding points at a non-local-aggregator
    sh.members[j].push_back(r);
  }
  sh.block_bytes.resize(nprocs);
  for (int r = 0; r < nprocs; ++r)
    sh.block_bytes[r] = (int64_t)n_aggs * msg_sizes[r];
  sh.recv_src_ofs.assign(nprocs, 0);
  int64_t cur = 0;
  for (int src = 0; src < nprocs; ++src) {
    sh.recv_src_ofs[src] = cur;
    cur += msg_sizes[src];
  }
  sh.slab_bytes = cur;
  sh.stage.resize(n_laggs);
  sh.stage_ofs.resize(n_laggs);
  sh.seg_total.assign(n_laggs, 0);
  sh.seg_out.resize(n_laggs);
  for (int j = 0; j < n_laggs; ++j) {
    int64_t o = 0;
    for (int m : sh.members[j]) {
      sh.stage_ofs[j].push_back(o);
      o += sh.block_bytes[m];
      sh.seg_total[j] += msg_sizes[m];
    }
    sh.stage[j].resize(std::max<int64_t>(o, 1));
    sh.seg_out[j].resize(
        std::max<int64_t>((int64_t)n_aggs * sh.seg_total[j], 1));
  }
  sh.seg_in.resize(n_aggs);
  int64_t max_seg = 1;
  for (int j = 0; j < n_laggs; ++j)
    max_seg = std::max(max_seg, sh.seg_total[j]);
  for (int gi = 0; gi < n_aggs; ++gi) sh.seg_in[gi].resize(max_seg);

  std::vector<std::thread> threads;
  threads.reserve(nprocs);
  for (int r = 0; r < nprocs; ++r) {
    threads.emplace_back(cw2_run_rank, &sh, r, ntimes,
                         rep_times_out + (size_t)r * ntimes);
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// collective_write3 (l_d_t.c:604-728): shared-window intra hop.
//
// The reference allocates an MPI-3 shared window per node (647-663): every
// group member *fills* its staging region, a fence publishes it, and the
// local aggregator *reads* all members' staging zero-copy via
// MPI_Win_shared_query (667-671) before exchanging hindexed segments
// directly with the destination aggregators (705-711). Threads of one
// process genuinely share memory, so the analog is exact here: the window
// is a plain per-node buffer, the fence is the generation barrier, and the
// aggregator's group pack reads members' staging with NO channel traffic —
// the intra-node hop costs zero messages, unlike cw2's member sends.

namespace {

struct Cw3Shared {
  Runtime* rt;
  int G, nl;
  const int32_t* node_of;
  const int32_t* aggs;
  const int32_t* msg_sizes;
  const int32_t* laggs;
  const uint8_t* send_msgs;
  const int64_t* send_block_ofs;
  uint8_t* recv_out;
  std::vector<std::vector<int>> members;        // per group, ascending
  std::vector<int> group_of_rank;               // rank -> group or -1
  std::vector<int> agg_of_rank;                 // rank -> gi or -1
  std::vector<int64_t> block_bytes;             // per src
  std::vector<int64_t> seg_total;               // per group
  std::vector<int64_t> recv_src_ofs;            // per src
  int64_t slab_bytes = 0;
  std::vector<std::vector<uint8_t>> window;     // per node: shared staging
  std::vector<int64_t> win_ofs;                 // per rank: offset in window
  std::vector<std::vector<uint8_t>> seg_out;    // per group: G segments
  std::vector<std::vector<uint8_t>> seg_in;     // per gi: staging
};

void cw3_run_rank(Cw3Shared* sh, int rank, int ntimes, double* rep_times) {
  Runtime& rt = *sh->rt;
  const int b = sh->node_of[rank];
  const int j_self = sh->group_of_rank[rank];
  const int gi_self = sh->agg_of_rank[rank];
  for (int rep = 0; rep < ntimes; ++rep) {
    double t0 = now_s();
    // window fill (l_d_t.c:647-663): my packed block into the node window
    if (sh->block_bytes[rank] > 0) {
      std::memcpy(sh->window[b].data() + sh->win_ofs[rank],
                  sh->send_msgs + sh->send_block_ofs[rank],
                  sh->block_bytes[rank]);
    }
    // the fence (MPI_Win_fence): staging visible node-wide after this
    {
      std::unique_lock<std::mutex> lk(rt.mu);
      rt.gen_barrier(lk, rt.barrier_waiting, rt.barrier_gen);
    }
    if (j_self >= 0) {
      // zero-copy group read (shared_query, 667-671) + hindexed segment
      // exchange with every destination aggregator (705-711)
      auto& so = sh->seg_out[j_self];
      const int64_t segsz = sh->seg_total[j_self];
      for (int gi = 0; gi < sh->G; ++gi) {
        uint8_t* seg = so.data() + (int64_t)gi * segsz;
        int64_t cur = 0;
        for (int src : sh->members[j_self]) {
          const uint8_t* blk =
              sh->window[sh->node_of[src]].data() + sh->win_ofs[src];
          std::memcpy(seg + cur, blk + (int64_t)gi * sh->msg_sizes[src],
                      sh->msg_sizes[src]);
          cur += sh->msg_sizes[src];
        }
        int dst = sh->aggs[gi];
        if (dst == rank) {
          uint8_t* slab = sh->recv_out + (int64_t)gi * sh->slab_bytes;
          int64_t o = 0;
          for (int src : sh->members[j_self]) {
            std::memcpy(slab + sh->recv_src_ofs[src], seg + o,
                        sh->msg_sizes[src]);
            o += sh->msg_sizes[src];
          }
        } else if (segsz > 0) {
          wl_post_send(rt, rank, dst, seg, segsz);
        }
      }
    }
    if (gi_self >= 0) {
      uint8_t* slab = sh->recv_out + (int64_t)gi_self * sh->slab_bytes;
      auto& in = sh->seg_in[gi_self];
      for (int j = 0; j < sh->nl; ++j) {
        if (sh->laggs[j] == rank) continue;  // own group handled above
        if (sh->seg_total[j] <= 0) continue;
        wl_recv(rt, sh->laggs[j], rank, in.data());
        int64_t o = 0;
        for (int src : sh->members[j]) {
          std::memcpy(slab + sh->recv_src_ofs[src], in.data() + o,
                      sh->msg_sizes[src]);
          o += sh->msg_sizes[src];
        }
      }
    }
    // end-of-rep rendezvous: window + segment buffers reused next rep
    {
      std::unique_lock<std::mutex> lk(rt.mu);
      rt.gen_barrier(lk, rt.barrier_waiting, rt.barrier_gen);
    }
    rep_times[rep] = now_s() - t0;
  }
}

}  // namespace

extern "C" {

// Execute the collective_write3 shared-window route natively. Every
// destination must be a local aggregator (rc=2 otherwise — the reference
// sends only to local_aggregators; use meta mode 1) and no group may span
// nodes (rc=3: a shared window lives on one node).
int agg_run_workload_cw3(int nprocs, int n_aggs, int n_laggs, int nnodes,
                         int ntimes, const int32_t* node_of,
                         const int32_t* aggs, const int32_t* msg_sizes,
                         const int32_t* owner_of, const int32_t* laggs,
                         const uint8_t* send_msgs,
                         const int64_t* send_block_ofs,
                         uint8_t* recv_out, double* rep_times_out) {
  Cw3Shared sh;
  Runtime rt(nprocs);
  sh.rt = &rt;
  sh.G = n_aggs;
  sh.nl = n_laggs;
  sh.node_of = node_of;
  sh.aggs = aggs;
  sh.msg_sizes = msg_sizes;
  sh.laggs = laggs;
  sh.send_msgs = send_msgs;
  sh.send_block_ofs = send_block_ofs;
  sh.recv_out = recv_out;

  sh.group_of_rank.assign(nprocs, -1);
  for (int j = 0; j < n_laggs; ++j) sh.group_of_rank[laggs[j]] = j;
  sh.agg_of_rank.assign(nprocs, -1);
  for (int gi = 0; gi < n_aggs; ++gi) {
    sh.agg_of_rank[aggs[gi]] = gi;
    if (sh.group_of_rank[aggs[gi]] < 0) return 2;  // dst not a local agg
  }
  sh.members.resize(n_laggs);
  for (int r = 0; r < nprocs; ++r) {
    if (owner_of[r] < 0 || owner_of[r] >= nprocs) return 1;
    int j = sh.group_of_rank[owner_of[r]];
    if (j < 0) return 1;
    if (node_of[owner_of[r]] != node_of[r]) return 3;  // group spans nodes
    sh.members[j].push_back(r);
  }
  sh.block_bytes.resize(nprocs);
  for (int r = 0; r < nprocs; ++r)
    sh.block_bytes[r] = (int64_t)n_aggs * msg_sizes[r];
  sh.recv_src_ofs.assign(nprocs, 0);
  int64_t cur = 0;
  for (int src = 0; src < nprocs; ++src) {
    sh.recv_src_ofs[src] = cur;
    cur += msg_sizes[src];
  }
  sh.slab_bytes = cur;
  // per-node shared window: node ranks' blocks back-to-back (rank-ascending)
  sh.window.resize(nnodes);
  sh.win_ofs.assign(nprocs, 0);
  {
    std::vector<int64_t> node_cur(nnodes, 0);
    for (int r = 0; r < nprocs; ++r) {
      int b = node_of[r];
      if (b < 0 || b >= nnodes) return 1;
      sh.win_ofs[r] = node_cur[b];
      node_cur[b] += sh.block_bytes[r];
    }
    for (int b = 0; b < nnodes; ++b)
      sh.window[b].resize(std::max<int64_t>(node_cur[b], 1));
  }
  sh.seg_total.assign(n_laggs, 0);
  sh.seg_out.resize(n_laggs);
  for (int j = 0; j < n_laggs; ++j) {
    for (int m : sh.members[j]) sh.seg_total[j] += msg_sizes[m];
    sh.seg_out[j].resize(
        std::max<int64_t>((int64_t)n_aggs * sh.seg_total[j], 1));
  }
  sh.seg_in.resize(n_aggs);
  int64_t max_seg = 1;
  for (int j = 0; j < n_laggs; ++j)
    max_seg = std::max(max_seg, sh.seg_total[j]);
  for (int gi = 0; gi < n_aggs; ++gi) sh.seg_in[gi].resize(max_seg);

  std::vector<std::thread> threads;
  threads.reserve(nprocs);
  for (int r = 0; r < nprocs; ++r) {
    threads.emplace_back(cw3_run_rank, &sh, r, ntimes,
                         rep_times_out + (size_t)r * ntimes);
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"

extern "C" {

// Execute the collective_write proxy route natively on a variable-size
// workload. Layouts documented above; rep_times_out is n * ntimes doubles
// (per-rank wall time per rep). Returns 0 on success.
int agg_run_workload_proxy(int nprocs, int nnodes, int n_aggs, int ntimes,
                           const int32_t* node_of, const int32_t* proxies,
                           const int32_t* aggs, const int32_t* msg_sizes,
                           const uint8_t* send_msgs,
                           const int64_t* send_block_ofs,
                           uint8_t* recv_out, double* rep_times_out) {
  WlGeom geom(nprocs, nnodes, n_aggs, node_of, proxies, aggs, msg_sizes);
  Runtime rt(nprocs);
  WlShared sh;
  sh.rt = &rt;
  sh.g = &geom;
  sh.send_msgs = send_msgs;
  sh.send_block_ofs = send_block_ofs;
  sh.recv_out = recv_out;
  sh.aggregate.resize(nnodes);
  for (int b = 0; b < nnodes; ++b)
    sh.aggregate[b].resize(std::max<int64_t>(geom.agg_total[b], 1));
  sh.run_out.resize((int64_t)nnodes * nnodes);
  sh.run_in.resize((int64_t)nnodes * nnodes);
  for (int b1 = 0; b1 < nnodes; ++b1) {
    for (int b2 = 0; b2 < nnodes; ++b2) {
      int64_t sz = std::max<int64_t>(geom.run_bytes[(int64_t)b1 * nnodes + b2], 1);
      sh.run_out[(int64_t)b1 * nnodes + b2].resize(sz);
      sh.run_in[(int64_t)b2 * nnodes + b1].resize(sz);
    }
  }
  sh.deliver.resize(n_aggs);
  for (int gi = 0; gi < n_aggs; ++gi)
    sh.deliver[gi].resize(std::max<int64_t>(geom.slab_bytes, 1));

  std::vector<std::thread> threads;
  threads.reserve(nprocs);
  for (int r = 0; r < nprocs; ++r) {
    threads.emplace_back(wl_run_rank, &sh, r, ntimes,
                         rep_times_out + (size_t)r * ntimes);
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"

extern "C" {

// Execute one compiled schedule. Arrays are flattened per rank:
//   ops[prog_ofs[r] .. prog_ofs[r+1])   rank r's op program
//   wait_tokens                         shared token-id pool for WAITALLs
//   send_slabs + send_ofs[r]*data_size  rank r's send slabs (contiguous)
//   recv_bufs + recv_ofs[r]*data_size   rank r's recv slabs (contiguous;
//                                       recv_ofs[r] < 0 => rank receives
//                                       nothing)
//   a2a_src_slot/a2a_dst_slot           (n*n) alltoallw slot maps, or null
//   timers_out                          n * ntimes * 5 doubles
// Returns 0 on success.
int agg_run_schedule(int nprocs, int ntimes, int data_size,
                     const NOp* ops, const int32_t* prog_ofs,
                     const int32_t* wait_tokens,
                     const uint8_t* send_slabs, const int32_t* send_ofs,
                     uint8_t* recv_bufs, const int32_t* recv_ofs,
                     const int32_t* a2a_src_slot, const int32_t* a2a_dst_slot,
                     int32_t max_token, double* timers_out) {
  Runtime rt(nprocs);
  std::vector<RankCtx> ctxs(nprocs);
  std::vector<std::vector<Timer5>> timers(nprocs,
                                          std::vector<Timer5>(ntimes));
  std::vector<const uint8_t*> send_bases(nprocs);
  for (int r = 0; r < nprocs; ++r) {
    send_bases[r] = send_slabs + (size_t)send_ofs[r] * data_size;
  }
  for (int r = 0; r < nprocs; ++r) {
    RankCtx& cx = ctxs[r];
    cx.rt = &rt;
    cx.rank = r;
    cx.ops = ops + prog_ofs[r];
    cx.nops = prog_ofs[r + 1] - prog_ofs[r];
    cx.wait_tokens = wait_tokens;
    cx.send_base = send_bases[r];
    cx.recv_base =
        recv_ofs[r] < 0 ? nullptr
                        : recv_bufs + (size_t)recv_ofs[r] * data_size;
    cx.data_size = data_size;
    cx.flags = std::vector<std::atomic<bool>>(max_token + 1);
    cx.timers = timers[r].data();
    cx.all_send_bases = send_bases.data();
    cx.a2a_src_slot = a2a_src_slot;
    cx.a2a_dst_slot = a2a_dst_slot;
  }
  std::vector<std::thread> threads;
  threads.reserve(nprocs);
  for (int r = 0; r < nprocs; ++r) {
    threads.emplace_back(run_rank, &ctxs[r], ntimes);
  }
  for (auto& th : threads) th.join();
  for (int r = 0; r < nprocs; ++r) {
    for (int m = 0; m < ntimes; ++m) {
      const Timer5& t = timers[r][m];
      double* o = timers_out + ((size_t)r * ntimes + m) * 5;
      o[0] = t.post;
      o[1] = t.send_wait;
      o[2] = t.recv_wait;
      o[3] = t.barrier;
      o[4] = t.total;
    }
  }
  return 0;
}

}  // extern "C"
