// Native schedule executor: N-thread rank runtime with MPI-faithful
// message semantics.
//
// This is the framework's native runtime tier (the reference's entire
// program is native C — SURVEY.md §2 "no component may be a pure-Python
// stand-in"). It executes the same compiled op programs as the Python
// backends, but with REAL concurrency semantics:
//
//   - ISSEND (MPI_Issend analog): completes only when the matching receive
//     is posted — true rendezvous, the congestion-exposing behavior the
//     reference builds its sync/half-sync studies on (mpi_test.c Issend
//     call sites).
//   - ISEND: eager — payload buffered at post time, completes immediately.
//   - SEND/RECV/SENDRECV: blocking (standard-mode send = eager buffer).
//   - WAITALL over explicit token sets; BARRIER via shared generation
//     counter; 0-byte SIGNAL channel (the dup'ed signal_comm analog,
//     mpi_test.c:1252); ALLTOALLW as barrier + direct shared-memory copy.
//
// Each rank is one thread; channels are per-(src,dst[,signal]) FIFO queues
// (message matching per directed pair is unique per rep in every reference
// schedule; FIFO covers the multi-rep no-resync case, mpi_test.c:2150).
// Per-op timer buckets mirror the reference's MPI_Wtime bracketing.
//
// C ABI only (ctypes-friendly); no Python.h dependency.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

enum OpKind : int32_t {
  kIsend = 0,
  kIssend = 1,
  kIrecv = 2,
  kSend = 3,
  kRecv = 4,
  kSendrecv = 5,
  kWaitall = 6,
  kBarrier = 7,
  kCopy = 8,
  kSignalSend = 9,
  kSignalRecv = 10,
  kAlltoallw = 11,
};

enum Bucket : int32_t {
  kPost = 0,
  kRecvWait = 1,
  kSendWait = 2,
  kRecvAndSendWait = 3,
  kBarrierB = 4,
  kNone = 5,
};

struct NOp {
  int32_t kind;
  int32_t peer;
  int32_t slot;
  int32_t peer2;
  int32_t slot2;
  int32_t token;
  int32_t nbytes;
  int32_t bucket;
  int32_t ntokens;   // WAITALL: number of tokens
  int32_t tok_ofs;   // WAITALL: offset into wait_tokens array
};

struct Timer5 {
  double post = 0, send_wait = 0, recv_wait = 0, barrier = 0, total = 0;
};

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// One message in flight on a channel. Rendezvous (Issend) vs eager (Isend/
// Send) is expressed through `send_done`: rendezvous sends pass their token
// flag (set at match time); eager sends pass null (flag set at post time).
struct Msg {
  const uint8_t* src_data = nullptr;  // sender slab (valid whole run)
  int32_t nbytes = 0;
  std::atomic<bool>* send_done = nullptr;
};

struct Channel {
  std::deque<Msg> sends;
  std::deque<std::pair<uint8_t*, std::atomic<bool>*>> recvs;  // dst buf, flag
};

struct Runtime {
  int n;
  std::mutex mu;                 // single lock: correctness over scalability
  std::condition_variable cv;    // (1-core image; contention is the workload)
  std::vector<Channel> data_ch;  // n*n
  std::vector<std::deque<int>> signal_ch;  // n*n: queue of 0-byte signals
  // barrier
  int barrier_waiting = 0;
  int64_t barrier_gen = 0;
  // alltoallw rendezvous
  int a2a_waiting = 0;
  int64_t a2a_gen = 0;

  explicit Runtime(int nprocs)
      : n(nprocs), data_ch(nprocs * nprocs), signal_ch(nprocs * nprocs) {}

  Channel& ch(int src, int dst) { return data_ch[src * n + dst]; }

  // Generation-counted rendezvous of all n ranks; caller holds `lk` on mu.
  void gen_barrier(std::unique_lock<std::mutex>& lk, int& waiting,
                   int64_t& gen) {
    int64_t my_gen = gen;
    if (++waiting == n) {
      waiting = 0;
      ++gen;
      cv.notify_all();
    } else {
      cv.wait(lk, [&] { return gen != my_gen; });
    }
  }

  // Try to match the channel head send/recv; called with mu held.
  void match(int src, int dst) {
    Channel& c = ch(src, dst);
    while (!c.sends.empty() && !c.recvs.empty()) {
      Msg m = c.sends.front();
      c.sends.pop_front();
      auto [buf, rflag] = c.recvs.front();
      c.recvs.pop_front();
      if (m.nbytes > 0 && buf != nullptr && m.src_data != nullptr) {
        std::memcpy(buf, m.src_data, m.nbytes);
      }
      if (m.send_done) m.send_done->store(true, std::memory_order_release);
      if (rflag) rflag->store(true, std::memory_order_release);
    }
  }
};

struct RankCtx {
  Runtime* rt;
  int rank;
  const NOp* ops;
  int nops;
  const int32_t* wait_tokens;
  // slab bases
  const uint8_t* send_base;   // this rank's send slabs (nslots * data_size)
  uint8_t* recv_base;         // this rank's recv slabs
  int data_size;
  // token flags for this rank
  std::vector<std::atomic<bool>> flags;
  Timer5* timers;             // per-rep Timer array (ntimes entries)
  // global alltoallw inputs
  const uint8_t* const* all_send_bases;
  const int32_t* a2a_src_slot;  // per (dst,src): sender slot or -1
  const int32_t* a2a_dst_slot;  // per (dst,src): recv slot
};

void run_rank(RankCtx* cx, int ntimes) {
  Runtime& rt = *cx->rt;
  const int n = rt.n;
  for (int rep = 0; rep < ntimes; ++rep) {
    Timer5& t = cx->timers[rep];
    for (auto& f : cx->flags) f.store(false, std::memory_order_relaxed);
    double rep_start = now_s();
    for (int i = 0; i < cx->nops; ++i) {
      const NOp& op = cx->ops[i];
      double t0 = now_s();
      switch (op.kind) {
        case kIsend:
        case kIssend: {
          std::unique_lock<std::mutex> lk(rt.mu);
          Msg m;
          m.src_data = cx->send_base + (size_t)op.slot * cx->data_size;
          m.nbytes = op.nbytes;
          m.send_done = &cx->flags[op.token];
          if (op.kind == kIsend) {
            // eager: complete at post; payload stays valid (deterministic
            // fill is never overwritten), so the copy happens at match.
            cx->flags[op.token].store(true, std::memory_order_release);
            m.send_done = nullptr;
          }
          rt.ch(cx->rank, op.peer).sends.push_back(m);
          rt.match(cx->rank, op.peer);
          rt.cv.notify_all();
          break;
        }
        case kIrecv: {
          std::unique_lock<std::mutex> lk(rt.mu);
          uint8_t* buf = cx->recv_base + (size_t)op.slot * cx->data_size;
          rt.ch(op.peer, cx->rank).recvs.push_back({buf, &cx->flags[op.token]});
          rt.match(op.peer, cx->rank);
          rt.cv.notify_all();
          break;
        }
        case kSend: {
          // standard-mode blocking send: eager buffer semantics (see the
          // oracle's rationale — strict rendezvous deadlocks m=6/7)
          std::unique_lock<std::mutex> lk(rt.mu);
          Msg m;
          m.src_data = cx->send_base + (size_t)op.slot * cx->data_size;
          m.nbytes = op.nbytes;
          rt.ch(cx->rank, op.peer).sends.push_back(m);
          rt.match(cx->rank, op.peer);
          rt.cv.notify_all();
          break;
        }
        case kRecv: {
          std::unique_lock<std::mutex> lk(rt.mu);
          uint8_t* buf = cx->recv_base + (size_t)op.slot * cx->data_size;
          std::atomic<bool> done{false};
          rt.ch(op.peer, cx->rank).recvs.push_back({buf, &done});
          rt.match(op.peer, cx->rank);
          rt.cv.notify_all();
          rt.cv.wait(lk, [&] { return done.load(std::memory_order_acquire); });
          break;
        }
        case kSendrecv: {
          // pairwise methods post zero-byte slots with slot = -1 and
          // receivers without buffers (mpi_test.c:466-478); never form the
          // pointer in those cases (UB even if unread)
          std::unique_lock<std::mutex> lk(rt.mu);
          Msg m;
          m.src_data = (op.nbytes > 0 && op.slot >= 0)
                           ? cx->send_base + (size_t)op.slot * cx->data_size
                           : nullptr;
          m.nbytes = op.nbytes;
          rt.ch(cx->rank, op.peer).sends.push_back(m);
          rt.match(cx->rank, op.peer);
          uint8_t* buf = (cx->recv_base != nullptr && op.slot2 >= 0)
                             ? cx->recv_base + (size_t)op.slot2 * cx->data_size
                             : nullptr;
          std::atomic<bool> done{false};
          rt.ch(op.peer2, cx->rank).recvs.push_back({buf, &done});
          rt.match(op.peer2, cx->rank);
          rt.cv.notify_all();
          rt.cv.wait(lk, [&] { return done.load(std::memory_order_acquire); });
          break;
        }
        case kWaitall: {
          std::unique_lock<std::mutex> lk(rt.mu);
          rt.cv.wait(lk, [&] {
            for (int k = 0; k < op.ntokens; ++k) {
              int tok = cx->wait_tokens[op.tok_ofs + k];
              if (!cx->flags[tok].load(std::memory_order_acquire)) return false;
            }
            return true;
          });
          break;
        }
        case kBarrier: {
          std::unique_lock<std::mutex> lk(rt.mu);
          rt.gen_barrier(lk, rt.barrier_waiting, rt.barrier_gen);
          break;
        }
        case kCopy: {
          std::memcpy(cx->recv_base + (size_t)op.slot2 * cx->data_size,
                      cx->send_base + (size_t)op.slot * cx->data_size,
                      cx->data_size);
          break;
        }
        case kSignalSend: {
          std::unique_lock<std::mutex> lk(rt.mu);
          rt.signal_ch[cx->rank * n + op.peer].push_back(1);
          if (op.token >= 0)
            cx->flags[op.token].store(true, std::memory_order_release);
          rt.cv.notify_all();
          break;
        }
        case kSignalRecv: {
          std::unique_lock<std::mutex> lk(rt.mu);
          auto& q = rt.signal_ch[op.peer * n + cx->rank];
          rt.cv.wait(lk, [&] { return !q.empty(); });
          q.pop_front();
          break;
        }
        case kAlltoallw: {
          // barrier in, shared-memory exchange, barrier out — the whole
          // pattern in "one collective" (mpi_test.c:627/912)
          std::unique_lock<std::mutex> lk(rt.mu);
          rt.gen_barrier(lk, rt.a2a_waiting, rt.a2a_gen);
          lk.unlock();
          if (cx->recv_base != nullptr) {
            for (int src = 0; src < n; ++src) {
              int32_t ss = cx->a2a_src_slot[cx->rank * n + src];
              if (ss < 0) continue;
              int32_t ds = cx->a2a_dst_slot[cx->rank * n + src];
              std::memcpy(cx->recv_base + (size_t)ds * cx->data_size,
                          cx->all_send_bases[src] + (size_t)ss * cx->data_size,
                          cx->data_size);
            }
          }
          // closing barrier so no rank races into the next rep's exchange
          lk.lock();
          rt.gen_barrier(lk, rt.a2a_waiting, rt.a2a_gen);
          break;
        }
      }
      double dt = now_s() - t0;
      switch (op.bucket) {
        case kPost: t.post += dt; break;
        case kRecvWait: t.recv_wait += dt; break;
        case kSendWait: t.send_wait += dt; break;
        case kRecvAndSendWait: t.recv_wait += dt; t.send_wait += dt; break;
        case kBarrierB: t.barrier += dt; break;
        default: break;
      }
    }
    t.total = now_s() - rep_start;
  }
}

}  // namespace

extern "C" {

// Execute one compiled schedule. Arrays are flattened per rank:
//   ops[prog_ofs[r] .. prog_ofs[r+1])   rank r's op program
//   wait_tokens                         shared token-id pool for WAITALLs
//   send_slabs + send_ofs[r]*data_size  rank r's send slabs (contiguous)
//   recv_bufs + recv_ofs[r]*data_size   rank r's recv slabs (contiguous;
//                                       recv_ofs[r] < 0 => rank receives
//                                       nothing)
//   a2a_src_slot/a2a_dst_slot           (n*n) alltoallw slot maps, or null
//   timers_out                          n * ntimes * 5 doubles
// Returns 0 on success.
int agg_run_schedule(int nprocs, int ntimes, int data_size,
                     const NOp* ops, const int32_t* prog_ofs,
                     const int32_t* wait_tokens,
                     const uint8_t* send_slabs, const int32_t* send_ofs,
                     uint8_t* recv_bufs, const int32_t* recv_ofs,
                     const int32_t* a2a_src_slot, const int32_t* a2a_dst_slot,
                     int32_t max_token, double* timers_out) {
  Runtime rt(nprocs);
  std::vector<RankCtx> ctxs(nprocs);
  std::vector<std::vector<Timer5>> timers(nprocs,
                                          std::vector<Timer5>(ntimes));
  std::vector<const uint8_t*> send_bases(nprocs);
  for (int r = 0; r < nprocs; ++r) {
    send_bases[r] = send_slabs + (size_t)send_ofs[r] * data_size;
  }
  for (int r = 0; r < nprocs; ++r) {
    RankCtx& cx = ctxs[r];
    cx.rt = &rt;
    cx.rank = r;
    cx.ops = ops + prog_ofs[r];
    cx.nops = prog_ofs[r + 1] - prog_ofs[r];
    cx.wait_tokens = wait_tokens;
    cx.send_base = send_bases[r];
    cx.recv_base =
        recv_ofs[r] < 0 ? nullptr
                        : recv_bufs + (size_t)recv_ofs[r] * data_size;
    cx.data_size = data_size;
    cx.flags = std::vector<std::atomic<bool>>(max_token + 1);
    cx.timers = timers[r].data();
    cx.all_send_bases = send_bases.data();
    cx.a2a_src_slot = a2a_src_slot;
    cx.a2a_dst_slot = a2a_dst_slot;
  }
  std::vector<std::thread> threads;
  threads.reserve(nprocs);
  for (int r = 0; r < nprocs; ++r) {
    threads.emplace_back(run_rank, &ctxs[r], ntimes);
  }
  for (auto& th : threads) th.join();
  for (int r = 0; r < nprocs; ++r) {
    for (int m = 0; m < ntimes; ++m) {
      const Timer5& t = timers[r][m];
      double* o = timers_out + ((size_t)r * ntimes + m) * 5;
      o[0] = t.post;
      o[1] = t.send_wait;
      o[2] = t.recv_wait;
      o[3] = t.barrier;
      o[4] = t.total;
    }
  }
  return 0;
}

}  // extern "C"
