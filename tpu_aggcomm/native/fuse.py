"""Schedule→Mosaic fusion: one Pallas kernel per whole throttled schedule.

Every method-registry schedule the jax tiers run is a *static step
program* (core/schedule.py: schedules are data), yet the fenced jax_sim
lowering pays one ``lax.optimization_barrier``-fenced XLA program step
per throttle round — on the tunneled v5e that stack of host-level fences
is why registry methods sit at 38–70 µs while the fused ``pallas_local``
dense exchange runs at ~1.72 µs (RESULTS_TPU.md; ROADMAP open item 2).
The persistent-schedule result of arXiv 2604.05099 (build once, execute
many) says the whole program belongs in one kernel.

This module is that lowering, split in two halves:

- **schedule-analysis half (jax-free)** — :func:`fuse_plan` turns
  ``Schedule.programs`` into a :class:`FusePlan`: per-round edge lists
  over the dense rank-axis arenas, fusability decided by NAMED refusal
  (:class:`UnfusableScheduleError` — TAM, dense collectives, staged
  dead-link repairs, slow-rank injection, oversize kernels). The step
  export (:func:`plan_round_matrices`, :func:`semaphore_deps`) and the
  :func:`cross_check_export` gate against ``obs/traffic.py`` live here
  too, so ``inspect check``/``inspect traffic`` can audit the fused
  program exactly where a wedged tunnel hangs ``import jax``.
- **kernel-build half (lazy jax)** — :func:`build_fused_rep` emits the
  Pallas kernel: per round, every edge becomes one in-kernel
  ``pltpu.make_async_copy`` from the sender's send-arena row into the
  receiver's recv-arena row; ALL of a round's copies post before any
  wait (in-flight copies per round = the throttle ``-c``, the
  pallas_dma_conc Issend-storm discipline), and the round's semaphore
  drain is the fence — round k+1's copy descriptors are program-ordered
  after round k's waits, so rounds remain distinct program steps in
  exactly the sense the ``-c`` invariants require. Reference
  MPI_Barrier rounds need no extra steps on one chip: the round drain
  already closes every rank's happens-before edge (all ranks live in
  the one kernel), which the plan records via ``barriers`` for the
  step-export auditors.

The rep signature matches ``JaxSimBackend._one_rep`` exactly
(``rep(send (n, S, w) lanes) -> recv (n, R+1, w) lanes``, trash row
last), so the fused backend inherits the chained serial-scan differenced
measurement, verification, and attribution unchanged
(backends/pallas_fused.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu_aggcomm.core.schedule import Schedule, barrier_rounds_of

__all__ = ["MAX_FUSED_EDGES", "UnfusableScheduleError", "FusedExportError",
           "FusePlan", "fuse_plan", "plan_round_matrices", "semaphore_deps",
           "cross_check_export", "export_sweep", "render_export_sweep",
           "build_fused_rep"]

#: Hard ceiling on the per-kernel copy count: each edge unrolls to one
#: DMA start + wait pair in the Mosaic instruction stream, so a flagship
#: shape (n=16,384) would emit a multi-million-instruction kernel. The
#: quiet-chip grids this lowering targets (n=32) sit near 450 edges;
#: oversize schedules refuse by name instead of wedging the compiler.
MAX_FUSED_EDGES = 16384


class UnfusableScheduleError(ValueError):
    """Schedule cannot lower to one fused kernel — named reason, never a
    silent fallback (the jax_shard staged-schedule refusal discipline)."""


class FusedExportError(ValueError):
    """The fused step export drifted from the op-program traffic
    accounting — the two views of one schedule must never disagree."""


@dataclass(frozen=True)
class FusePlan:
    """The fused kernel's step program, derived ONLY from the schedule.

    ``rounds`` is a tuple of ``(round_id, edges)`` in strictly increasing
    round order; each edge is ``(src, sslot, dst, dslot)`` over the dense
    rank-axis arenas (``dslot`` indexes pattern recv slots; the trash row
    is ``n_recv_slots``). ``barriers`` maps round id -> reference
    MPI_Barrier count attached to that round (fence-structure export
    only: on one chip the round drain already IS the global fence).
    """

    nprocs: int
    data_size: int
    n_send_slots: int
    n_recv_slots: int
    rounds: tuple
    barriers: tuple  # sorted (round_id, count) pairs

    @property
    def n_edges(self) -> int:
        return sum(len(edges) for _r, edges in self.rounds)

    def barrier_counts(self) -> dict:
        return dict(self.barriers)


def fuse_plan(schedule) -> FusePlan:
    """Build the fused step program, or refuse by name.

    Fusable = round-structured, non-collective, non-TAM, no relay
    staging rows, no slow-rank injection, every edge joinable to a recv
    slot. Dead-link realization for UNREPAIRED faulted schedules matches
    the other lowerings (``faults/inject.dead_edge_mask``): named chan-0
    edges drop their payload so ``--verify`` fails visibly — a repaired
    schedule has no such edge left.
    """
    from tpu_aggcomm.faults.inject import dead_edge_mask
    from tpu_aggcomm.faults.spec import parse_fault

    if not isinstance(schedule, Schedule):
        raise UnfusableScheduleError(
            f"{getattr(schedule, 'name', schedule)!r}: the hierarchical "
            f"TAM engine has no rank op programs to fuse (m=15/16 run "
            f"their 3-hop relay on jax_sim)")
    if schedule.collective:
        raise UnfusableScheduleError(
            f"schedule {schedule.name!r} is a dense collective (m=5/8): "
            f"it lowers to one vendor exchange and has no throttle "
            f"rounds to fuse")
    if getattr(schedule, "n_staging", 0):
        raise UnfusableScheduleError(
            f"repaired schedule (fault={schedule.fault!r}): the fused "
            f"kernel cannot represent relay staging rows; run the "
            f"detour on local or jax_sim (the jax_shard refusal)")
    spec = parse_fault(getattr(schedule, "fault", None))
    if spec.slow:
        raise UnfusableScheduleError(
            f"schedule {schedule.name!r} carries slow-rank injection "
            f"(fault={schedule.fault!r}): the fused kernel does not "
            f"lower delay loops; run slow-rank scenarios on jax_sim "
            f"or jax_shard")

    p = schedule.pattern
    from tpu_aggcomm.harness.verify import slot_shapes
    n_send_slots, n_recv_slots = slot_shapes(p)

    ext = schedule.data_edges_ext()
    ext = ext[dead_edge_mask(ext, spec)]
    if len(ext) and (ext[:, 6] != 0).any():
        raise UnfusableScheduleError(
            f"schedule {schedule.name!r} has staging-flagged edges "
            f"without staging rows — refusing to guess a lowering")
    if len(ext) and (ext[:, 3] < 0).any():
        bad = ext[ext[:, 3] < 0][0]
        raise UnfusableScheduleError(
            f"schedule {schedule.name!r}: edge {int(bad[0])}->"
            f"{int(bad[1])} in round {int(bad[4])} has no matching "
            f"receive slot to land in")
    if len(ext) > MAX_FUSED_EDGES:
        raise UnfusableScheduleError(
            f"schedule {schedule.name!r} has {len(ext)} copy edges, over "
            f"the fused-kernel ceiling of {MAX_FUSED_EDGES} (each edge "
            f"unrolls to one in-kernel DMA); use the fenced jax_sim "
            f"lowering at this scale")

    barriers = barrier_rounds_of(schedule)
    rounds = []
    n_rounds = int(ext[:, 4].max()) + 1 if len(ext) else 0
    for r in range(n_rounds):
        sel = ext[ext[:, 4] == r]
        if len(sel) == 0:
            continue
        seen: dict = {}
        edges = []
        for row in sel:
            src, dst, ss, ds = (int(row[0]), int(row[1]), int(row[2]),
                                int(row[3]))
            cell = (dst, ds)
            if cell in seen:
                raise UnfusableScheduleError(
                    f"schedule {schedule.name!r}: recv slot {cell} is "
                    f"written twice in round {r} (by {seen[cell]} and "
                    f"{src}) — racing in-flight copies")
            seen[cell] = src
            edges.append((src, ss, dst, ds))
        rounds.append((r, tuple(edges)))

    orphans = set(barriers) - {r for r, _e in rounds}
    if orphans:
        raise UnfusableScheduleError(
            f"schedule {schedule.name!r} has barrier-only rounds "
            f"{sorted(orphans)} with no data edges; the fused round "
            f"lowering cannot represent a standalone fence")
    return FusePlan(nprocs=p.nprocs, data_size=p.data_size,
                    n_send_slots=n_send_slots, n_recv_slots=n_recv_slots,
                    rounds=tuple(rounds),
                    barriers=tuple(sorted(barriers.items())))


def plan_round_matrices(plan: FusePlan) -> dict:
    """The fused step export: per-round ``{(src, dst): bytes}`` payload
    matrices, every edge one ``data_size`` arena-row copy — the view
    :func:`cross_check_export` pins against ``obs/traffic.round_edges``."""
    out: dict = {}
    for r, edges in plan.rounds:
        cell: dict = {}
        for (src, _ss, dst, _ds) in edges:
            cell[(src, dst)] = cell.get((src, dst), 0) + plan.data_size
        out[r] = cell
    return out


def semaphore_deps(plan: FusePlan) -> list:
    """The in-kernel wait graph as ``(earlier_round, later_round)``
    pairs: round k+1's copy starts are program-ordered after round k's
    semaphore drain, so the transitive order covers every round pair —
    the fence structure tests pin against ``analysis/check.py``'s
    round-monotonicity property."""
    ids = [r for r, _e in plan.rounds]
    return list(zip(ids, ids[1:]))


def cross_check_export(schedule) -> dict:
    """Prove the fused step export equals the op-program traffic view.

    Returns ``{"status": "MATCH", ...}`` or ``{"status": "SKIPPED",
    "reason": ...}`` (unfusable schedules refuse by design — a refusal
    is not a drift); raises :class:`FusedExportError` when the two
    accountings disagree, naming the divergent round and cell. The
    payload universe on both sides is network edges + COPY self-edges
    (``Schedule.data_edges`` == ``round_edges``' edges+copies), so the
    fused kernel's per-round src→dst matrices can never drift from what
    ``inspect traffic`` audits and bounds against ``-c``.
    """
    from tpu_aggcomm.faults.spec import parse_fault
    from tpu_aggcomm.obs.traffic import round_edges

    spec = parse_fault(getattr(schedule, "fault", None))
    if spec.deadlinks and isinstance(schedule, Schedule):
        from tpu_aggcomm.faults.inject import dead_edge_mask
        if not dead_edge_mask(schedule.data_edges_ext(), spec).all():
            return {"status": "SKIPPED",
                    "reason": "unrepaired dead-link realization drops "
                              "payload by design (masked edges would "
                              "fail --verify visibly); the export "
                              "cross-check audits healthy or repaired "
                              "schedules"}
    try:
        plan = fuse_plan(schedule)
    except UnfusableScheduleError as e:
        return {"status": "SKIPPED", "reason": str(e)}

    fused = plan_round_matrices(plan)
    program: dict = {}
    for r, cell in round_edges(schedule).items():
        merged: dict = {}
        for table in (cell["edges"], cell["copies"]):
            for pair, nbytes in table.items():
                merged[pair] = merged.get(pair, 0) + int(nbytes)
        if merged:
            program[r] = merged

    for r in sorted(set(fused) | set(program)):
        f, g = fused.get(r, {}), program.get(r, {})
        for pair in sorted(set(f) | set(g)):
            if f.get(pair, 0) != g.get(pair, 0):
                raise FusedExportError(
                    f"schedule {schedule.name!r} round {r}: fused plan "
                    f"moves {f.get(pair, 0)} bytes for "
                    f"{pair[0]}->{pair[1]}, op programs say "
                    f"{g.get(pair, 0)}")

    deps = semaphore_deps(plan)
    ids = [r for r, _e in plan.rounds]
    if ids != sorted(ids):
        raise FusedExportError(
            f"schedule {schedule.name!r}: fused rounds out of order "
            f"({ids})")
    if plan.barrier_counts() != barrier_rounds_of(schedule):
        raise FusedExportError(
            f"schedule {schedule.name!r}: fused barrier export "
            f"{plan.barrier_counts()} != schedule barriers "
            f"{barrier_rounds_of(schedule)}")
    return {"status": "MATCH", "rounds": len(plan.rounds),
            "edges": plan.n_edges, "fences": len(deps),
            "bytes": plan.n_edges * plan.data_size}


def export_sweep(nprocs: int, cb_nodes: int, comm_size: int, *,
                 data_size: int = 2048, proc_node: int = 1,
                 agg_type: int = 0, fault: str | None = None,
                 barrier_type: int = 0) -> list:
    """Cross-check every registry method's fused export at one shape —
    the ``inspect check/traffic --fused-export`` gate body (jax-free).
    Drift is a row, not an exception, so one bad method cannot hide the
    rest of the sweep."""
    from tpu_aggcomm.core.methods import METHODS, compile_method, method_ids
    from tpu_aggcomm.core.pattern import AggregatorPattern

    p = AggregatorPattern(nprocs=nprocs, cb_nodes=cb_nodes,
                          data_size=data_size, placement=agg_type,
                          proc_node=proc_node, comm_size=comm_size)
    rows = []
    for m in method_ids():
        sched = compile_method(m, p, barrier_type=barrier_type)
        if fault:
            from tpu_aggcomm.faults import (FaultSpecError, RepairError,
                                            repair_schedule)
            try:
                sched = repair_schedule(sched, fault,
                                        barrier_type=barrier_type)
            except (FaultSpecError, RepairError) as e:
                rows.append({"method": m, "name": METHODS[m].name,
                             "status": "SKIPPED",
                             "reason": f"repair refused: {e}"})
                continue
        try:
            rep = cross_check_export(sched)
        except FusedExportError as e:
            rows.append({"method": m, "name": METHODS[m].name,
                         "status": "DRIFT", "reason": str(e)})
            continue
        rows.append({"method": m, "name": METHODS[m].name, **rep})
    return rows


def render_export_sweep(rows: list, *, fault: str | None = None) -> str:
    lines = [f"fused step export vs op-program traffic"
             f"{' (fault=' + fault + ')' if fault else ''}:"]
    for r in rows:
        if r["status"] == "MATCH":
            lines.append(f"  m={r['method']:>2} {r['name']:<26} MATCH "
                         f"({r['rounds']} rounds, {r['edges']} edges, "
                         f"{r['fences']} fences)")
        else:
            lines.append(f"  m={r['method']:>2} {r['name']:<26} "
                         f"{r['status']}: {r['reason']}")
    n_drift = sum(1 for r in rows if r["status"] == "DRIFT")
    lines.append(f"  {sum(1 for r in rows if r['status'] == 'MATCH')} "
                 f"matched, {sum(1 for r in rows if r['status'] == 'SKIPPED')} "
                 f"skipped (unfusable by design), {n_drift} drifted")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# kernel-build half — everything below imports jax, lazily


def _row_geometry(lane_dtype, w: int) -> tuple:
    """(sublanes, lanes) of one arena slot row, tile-aligned for the
    lane dtype: uint32 rides the (8, 128) tile, uint8 the pallas_dma
    (4, 128) discipline. Rows are always copied WHOLE so the DMA engine
    never slices inside a tile."""
    sub = 8 if np.dtype(lane_dtype).itemsize == 4 else 4
    lanes = max(128, -(-(-(-w // sub)) // 128) * 128)  # pad128(ceil(w/sub))
    return sub, lanes


def build_fused_rep(plan: FusePlan, *, lane, interpret: bool):
    """Emit ``rep(send (n, S, w) lanes) -> recv (n, R+1, w) lanes`` — one
    ``pl.pallas_call`` over the whole plan.

    Arenas are ``(n, slots, sub, lanes)`` in the lane dtype; each slot
    row is one tile-aligned ``(sub, lanes)`` block so every copy is a
    whole-row DMA with STATIC indices (no dynamic sublane slicing —
    the Mosaic legality rule pallas_dma's first compiled runs surfaced).
    The recv output aliases a zero-initialized input (Mosaic forbids
    direct stores into ANY-space refs). Per round: start every edge's
    ``make_async_copy``, then drain them on the shared DMA semaphore —
    the drain is the round fence.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tpu_aggcomm.compat import tpu_compiler_params

    _ndt, jdt, w = lane
    n, S, R = plan.nprocs, plan.n_send_slots, plan.n_recv_slots
    sub, lanes = _row_geometry(np.dtype(jdt), w)
    rounds = plan.rounds

    def kernel(send_r, recv0_r, recv_r, sem):
        del recv0_r  # recv_r aliases it; zeroing happens in XLA
        for _rid, edges in rounds:
            copies = [pltpu.make_async_copy(
                send_r.at[src, ss], recv_r.at[dst, ds], sem)
                for (src, ss, dst, ds) in edges]
            for c in copies:      # the round's in-flight window (-c wide)
                c.start()
            for c in copies:      # the drain IS the round fence
                c.wait()

    grid_call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, R + 1, sub, lanes), jdt),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA],
        compiler_params=tpu_compiler_params(has_side_effects=True),
        input_output_aliases={1: 0},
        interpret=interpret,
    )

    pad = sub * lanes - w

    def rep(send):
        sa = jnp.pad(send, ((0, 0), (0, 0), (0, pad)))
        sa = sa.reshape(n, S, sub, lanes)
        recv0 = jnp.zeros((n, R + 1, sub, lanes), dtype=jdt)
        out = grid_call(sa, recv0)
        return out.reshape(n, R + 1, sub * lanes)[:, :, :w]

    return rep
