"""TPU-native lowerings and runtimes.

Two residents share this package:

- ``aggcomm_runtime.cc`` — the C++ threaded rank runtime behind the
  ``native`` backend (ctypes bindings in ``backends/native.py``; the
  shared library is built on demand into ``native/build/``).
- :mod:`tpu_aggcomm.native.fuse` — the Schedule→Mosaic fusion layer
  behind the ``pallas_fused`` backend: whole throttled schedules
  compiled to ONE Pallas kernel in which in-kernel DMA-semaphore waits
  are the round fences.

The package is declared jax-pure (``analysis/lint.py:PURE_PACKAGES``):
module import must never touch jax — ``fuse``'s schedule-analysis half
(plan building, step export, the traffic cross-check) runs precisely
where a wedged tunnel hangs ``import jax``; only its kernel-build
functions import jax, lazily, when a backend asks for a rep.
"""
