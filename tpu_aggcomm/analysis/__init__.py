"""Static analysis: schedule model checking + codebase invariant linting.

Two halves, both jax-free (obs discipline — everything here must run
where ``import jax`` may hang on a dead tunnel):

- :mod:`tpu_aggcomm.analysis.check` — a symbolic per-rank executor over
  ``Schedule.programs`` that builds the waits-for event graph (blocking
  SEND/RECV, ISSEND rendezvous coupling, WAITALL token subsets, BARRIER
  joins) and PROVES, or REFUTES with a named witness: deadlock-freedom
  (acyclicity — the offending cycle is named), recv-slot race-freedom
  (no two in-flight writes to one (rank, row) between matching
  WAITALLs), byte conservation (per-edge sends == recvs, cross-checked
  against ``obs/traffic.py`` matrices and the pattern's expected
  coverage), barrier SPMD symmetry, and round-fence monotonicity — for
  healthy AND fault-repaired schedules. Surfaced as
  ``cli inspect check`` (``-m 0`` sweeps every method as the ci_tier1
  gate). The properties mirror ``backends/local.py`` semantics exactly
  (SEND modeled eager, ISSEND rendezvous, generation-matched barriers),
  so a static REFUTED agrees with a runtime ``DeadlockError`` /
  ``VerificationError`` — tests/test_analysis.py pins that agreement
  per defect class.
- :mod:`tpu_aggcomm.analysis.lint` — an AST/import-graph linter that
  mechanically enforces the CLAUDE.md invariants: jax-import purity of
  the declared-pure module set (``PURE_PACKAGES``/``pure_modules`` —
  the one derived rule list the poisoned-jax subprocess pins
  parameterize from), no ``.lower().compile()``, no broad ``except``
  outside pragma-classified sites, one-shot JSON artifact writers
  routed through ``obs.atomic_write``, and no env *values* (pool IPs)
  in any committed JSON artifact. ``scripts/lint_invariants.py`` runs
  it as the ci_tier1 gate, naming file:line offenders.

The motivating consumer is ROADMAP item 2 (Schedule→Mosaic fusion):
removing the ``optimization_barrier`` round fences is only safe against
schedules whose ordering properties are machine-checked, not merely
observed by the oracle at one shape.
"""

from tpu_aggcomm.analysis.check import (CheckError, check_schedule,
                                        check_sweep, render_check,
                                        render_check_sweep)
from tpu_aggcomm.analysis.lint import PURE_PACKAGES, pure_modules, run_lint

__all__ = ["CheckError", "check_schedule", "check_sweep", "render_check",
           "render_check_sweep", "PURE_PACKAGES", "pure_modules",
           "run_lint"]
