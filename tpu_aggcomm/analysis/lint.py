"""Codebase invariant linter: the CLAUDE.md rules, mechanically checked.

Every rule here used to live only in prose (CLAUDE.md "Invariants to
preserve") plus scattered per-feature subprocess tests. This module is
the ONE derived rule set — the poisoned-jax test pins parameterize from
:func:`pure_modules`, and ``scripts/lint_invariants.py`` runs
:func:`run_lint` as a ci_tier1 gate with named file:line offenders.

Rules:

1. **jax-import purity** — the declared-pure packages (obs, faults,
   resilience, analysis, core, and tune minus ``tune/measure.py``) must
   not reach ``jax``/``jaxlib`` through their module-level import
   closure. Function-level lazy imports are exempt by construction (the
   AST walk skips function bodies) — that is exactly the pattern the
   tree uses to defer jax. This is the static twin of the poisoned-jax
   subprocess pins: the linter proves no import path exists, the
   subprocess proves the interpreter agrees.
2. **no ``.lower().compile()``** — the AOT path does not share the jit
   cache and would double-compile through the tunnel (CLAUDE.md ledger
   invariant). Anywhere in the scan scope. The ONE sanctioned use is a
   compile-only acceptance probe that never dispatches (CLAUDE.md says
   to probe compile-only first) — such a site carries a
   ``# lint: aot-ok (reason)`` pragma.
3. **no broad ``except``** — bare ``except:`` / ``except Exception`` /
   ``except BaseException`` is banned unless the line carries a
   ``# lint: broad-ok (reason)`` pragma: unclassified swallowing is how
   a PROGRAM error gets retried as if TRANSIENT. The pragma is the
   classification.
4. **atomic artifact writes** — every ``json.dump`` call must sit
   lexically inside ``with atomic_write(...)`` (obs/atomic.py itself
   exempt): a one-shot artifact written with a plain ``open`` can tear
   on a mid-write kill. Append-mode journals use ``write(json.dumps +
   "\\n")`` line appends, which this rule deliberately does not match.
5. **no env values in committed artifacts** — committed JSON/JSONL
   artifacts must not contain dotted-quad IPs, and when
   ``PALLAS_AXON_POOL_IPS`` is set in the linting environment its
   values must not appear anywhere in them (the ledger records env vars
   by NAME only).

Scan scope for rules 2-4: ``tpu_aggcomm/``, ``scripts/``, ``bench.py``,
``__graft_entry__.py``. tests/ are exempt (they deliberately seed
violations to prove the linter catches them).

jax-free by the same discipline it enforces (and it enforces it on
itself: ``analysis`` is in :data:`PURE_PACKAGES`).
"""

from __future__ import annotations

import ast
import os
import re

__all__ = ["PURE_PACKAGES", "BROAD_OK_PRAGMA", "pure_modules",
           "module_import_closure", "run_lint", "render_lint"]

#: package (under tpu_aggcomm/) -> module stems excluded from the purity
#: rule. tune/measure.py is THE one declared jax importer among the pure
#: packages (tune/__init__.py documents it).
PURE_PACKAGES: dict = {
    "core": (),
    "obs": (),
    "faults": (),
    "resilience": (),
    "analysis": (),
    "tune": ("measure",),
    "native": (),
    "model": (),
    "serve": ("executor",),
    "synth": (),
    "pilot": (),
}

BROAD_OK_PRAGMA = "# lint: broad-ok"
AOT_OK_PRAGMA = "# lint: aot-ok"

_JAX_ROOTS = ("jax", "jaxlib")

#: committed artifact globs (repo root) for rule 5
_ARTIFACT_GLOBS = ("BENCH_r*.json", "MULTICHIP_r*.json", "TUNE_*.json",
                   "TRAFFIC_*.json", "PREDICT_*.json", "COMPARE_*.json",
                   "SERVE_r*.json", "SYNTH_r*.json", "WORKLOAD_r*.json",
                   "WATCH_r*.json", "PILOT_r*.json", "FLOW_r*.json",
                   "*.trace.json",
                   "*.trace.jsonl", "BASELINE.json", "*.journal.jsonl")

_IPV4 = re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b")


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _scan_files(root: str) -> list:
    """Python files under the lint scope, repo-relative, sorted."""
    out = []
    for sub in ("tpu_aggcomm", "scripts"):
        for dirpath, dirnames, filenames in os.walk(os.path.join(root, sub)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.relpath(os.path.join(dirpath, f),
                                               root))
    for f in ("bench.py", "__graft_entry__.py"):
        if os.path.exists(os.path.join(root, f)):
            out.append(f)
    return sorted(out)


def _parse(root: str, relpath: str):
    with open(os.path.join(root, relpath), encoding="utf-8") as fh:
        src = fh.read()
    return src, ast.parse(src, filename=relpath)


# ---------------------------------------------------------------------------
# Rule 1: jax-import purity

def _module_name(relpath: str) -> str:
    """tpu_aggcomm/obs/traffic.py -> tpu_aggcomm.obs.traffic;
    package __init__ maps to the package name itself."""
    parts = relpath[:-3].split(os.sep)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _toplevel_imports(tree) -> list:
    """Module-level imported names (with line numbers), skipping
    function bodies — a lazy in-function import is the sanctioned way
    to defer jax, so it must not count against the importer."""
    out = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Import):
                for a in child.names:
                    out.append((a.name, child.lineno))
            elif isinstance(child, ast.ImportFrom):
                if child.module and child.level == 0:
                    base = child.module
                    out.append((base, child.lineno))
                    for a in child.names:
                        # `from pkg import sub` may bind a submodule:
                        # record the candidate; the resolver keeps it
                        # only if such a module exists
                        out.append((f"{base}.{a.name}", child.lineno))
            else:
                walk(child)

    walk(tree)
    return out


def _project_modules(root: str) -> dict:
    """module name -> relpath for every module under tpu_aggcomm/."""
    mods = {}
    for rel in _scan_files(root):
        if rel.split(os.sep)[0] == "tpu_aggcomm":
            mods[_module_name(rel)] = rel
    return mods


def pure_modules(root: str | None = None) -> list:
    """The modules the purity rule covers, as importable dotted names —
    the single source the poisoned-jax subprocess pins (tests/_jaxfree.py)
    parameterize from."""
    root = root or _repo_root()
    mods = _project_modules(root)
    out = []
    for name in sorted(mods):
        parts = name.split(".")
        if len(parts) < 2 or parts[0] != "tpu_aggcomm":
            continue
        pkg = parts[1]
        if pkg not in PURE_PACKAGES:
            continue
        if len(parts) > 2 and parts[2] in PURE_PACKAGES[pkg]:
            continue
        out.append(name)
    return out


def module_import_closure(root: str | None = None) -> dict:
    """module -> (direct deps, direct external roots, lines) for every
    project module, from module-level imports only. Importing a
    submodule also executes its ancestor package __init__s — those are
    edges too."""
    root = root or _repo_root()
    mods = _project_modules(root)
    graph = {}
    for name, rel in mods.items():
        _src, tree = _parse(root, rel)
        deps = set()
        externals = {}
        for imp, lineno in _toplevel_imports(tree):
            top = imp.split(".")[0]
            if top == "tpu_aggcomm":
                target = imp
                while target and target not in mods:
                    target = target.rsplit(".", 1)[0] if "." in target else ""
                if target:
                    parts = target.split(".")
                    for k in range(1, len(parts) + 1):
                        anc = ".".join(parts[:k])
                        if anc in mods and anc != name:
                            deps.add(anc)
            elif top in _JAX_ROOTS:
                externals.setdefault(top, lineno)
        graph[name] = (deps, externals, rel)
    return graph


def check_purity(root: str | None = None) -> list:
    root = root or _repo_root()
    graph = module_import_closure(root)
    offenders = []
    memo: dict = {}

    def reaches_jax(name, stack=()):
        """First (module, jax_root, line) reachable from name, or None."""
        if name in memo:
            return memo[name]
        if name in stack:
            return None  # cycle: resolved by the other frames
        deps, externals, _rel = graph[name]
        hit = None
        if externals:
            top, lineno = sorted(externals.items())[0]
            hit = (name, top, lineno)
        else:
            for dep in sorted(deps):
                sub = reaches_jax(dep, stack + (name,))
                if sub:
                    hit = sub
                    break
        memo[name] = hit
        return hit

    for name in pure_modules(root):
        hit = reaches_jax(name)
        if hit:
            via_mod, jax_root, lineno = hit
            via = ("directly" if via_mod == name
                   else f"via {via_mod}")
            offenders.append({
                "rule": "jax-purity",
                "file": graph[via_mod][2], "line": lineno,
                "detail": f"declared-pure module {name} reaches "
                          f"'{jax_root}' at module level {via} "
                          f"({graph[via_mod][2]}:{lineno}) — lazy "
                          f"function-level import required"})
    # dedupe: many pure modules funnel through one bad import site
    seen = set()
    uniq = []
    for o in offenders:
        key = (o["file"], o["line"])
        if key not in seen:
            seen.add(key)
            uniq.append(o)
    return uniq


# ---------------------------------------------------------------------------
# Rules 2-4: per-file AST rules

def check_file_rules(root: str | None = None) -> list:
    root = root or _repo_root()
    offenders = []
    for rel in _scan_files(root):
        src, tree = _parse(root, rel)
        srclines = src.splitlines()

        # rule 2: .lower().compile()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "compile"
                    and isinstance(node.func.value, ast.Call)
                    and isinstance(node.func.value.func, ast.Attribute)
                    and node.func.value.func.attr == "lower"):
                if AOT_OK_PRAGMA in srclines[node.lineno - 1]:
                    continue
                offenders.append({
                    "rule": "aot-compile", "file": rel, "line": node.lineno,
                    "detail": ".lower().compile() double-compiles through "
                              "the tunnel (AOT path does not share the "
                              "jit cache) — use plain jit dispatch and "
                              "time host boundaries"})

        # rule 3: broad except without the classification pragma
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            names = []
            t = node.type
            if t is None:
                names = ["<bare>"]
            elif isinstance(t, ast.Name):
                names = [t.id]
            elif isinstance(t, ast.Tuple):
                names = [e.id for e in t.elts if isinstance(e, ast.Name)]
            broad = [x for x in names
                     if x in ("<bare>", "Exception", "BaseException")]
            if not broad:
                continue
            line = srclines[node.lineno - 1]
            if BROAD_OK_PRAGMA in line:
                continue
            offenders.append({
                "rule": "broad-except", "file": rel, "line": node.lineno,
                "detail": f"except {broad[0]} without a "
                          f"'{BROAD_OK_PRAGMA} (reason)' pragma — "
                          f"unclassified swallowing retries PROGRAM "
                          f"errors as if TRANSIENT; classify or narrow"})

        # rule 4: json.dump outside atomic_write
        if rel == os.path.join("tpu_aggcomm", "obs", "atomic.py"):
            continue

        def with_uses_atomic(w) -> bool:
            for item in w.items:
                cx = item.context_expr
                if isinstance(cx, ast.Call):
                    f = cx.func
                    if (isinstance(f, ast.Name) and f.id == "atomic_write") \
                            or (isinstance(f, ast.Attribute)
                                and f.attr == "atomic_write"):
                        return True
            return False

        def walk_dump(node, inside):
            for child in ast.iter_child_nodes(node):
                now = inside
                if isinstance(child, ast.With) and with_uses_atomic(child):
                    now = True
                if (isinstance(child, ast.Call)
                        and isinstance(child.func, ast.Attribute)
                        and child.func.attr == "dump"
                        and isinstance(child.func.value, ast.Name)
                        and child.func.value.id == "json"
                        and not now):
                    offenders.append({
                        "rule": "atomic-artifact", "file": rel,
                        "line": child.lineno,
                        "detail": "json.dump outside 'with "
                                  "atomic_write(...)' — a kill mid-write "
                                  "tears the artifact; route one-shot "
                                  "writers through obs.atomic_write "
                                  "(append-mode journals use line-append "
                                  "write(json.dumps...))"})
                walk_dump(child, now)

        walk_dump(tree, False)
    return offenders


# ---------------------------------------------------------------------------
# Rule 5: committed artifacts carry no env values

def check_artifacts(root: str | None = None) -> list:
    import glob

    root = root or _repo_root()
    offenders = []
    pool = os.environ.get("PALLAS_AXON_POOL_IPS", "")
    pool_vals = [v for v in re.split(r"[,\s;]+", pool) if v]
    files = []
    for pat in _ARTIFACT_GLOBS:
        files.extend(glob.glob(os.path.join(root, pat)))
    for path in sorted(set(files)):
        rel = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                for lineno, line in enumerate(fh, 1):
                    m = _IPV4.search(line)
                    if m:
                        offenders.append({
                            "rule": "artifact-env", "file": rel,
                            "line": lineno,
                            "detail": f"dotted-quad address "
                                      f"'{m.group(0)}' in a committed "
                                      f"artifact — env values (pool IPs) "
                                      f"must never be recorded; the "
                                      f"ledger stores env var NAMES only"})
                    for v in pool_vals:
                        if v in line:
                            offenders.append({
                                "rule": "artifact-env", "file": rel,
                                "line": lineno,
                                "detail": "a PALLAS_AXON_POOL_IPS value "
                                          "appears in a committed "
                                          "artifact (value withheld)"})
        except OSError as e:
            offenders.append({"rule": "artifact-env", "file": rel,
                              "line": 0, "detail": f"unreadable: {e}"})
    return offenders


# ---------------------------------------------------------------------------

def run_lint(root: str | None = None) -> list:
    """All rules over the tree: list of offender dicts
    ``{"rule", "file", "line", "detail"}``, empty = clean."""
    root = root or _repo_root()
    out = []
    out.extend(check_purity(root))
    out.extend(check_file_rules(root))
    out.extend(check_artifacts(root))
    return sorted(out, key=lambda o: (o["rule"], o["file"], o["line"]))


def render_lint(offenders: list, root: str | None = None) -> str:
    n_mods = len(pure_modules(root))
    if not offenders:
        return (f"invariant lint: clean ({n_mods} declared-pure modules, "
                f"{len(PURE_PACKAGES)} packages; rules: jax-purity, "
                f"aot-compile, broad-except, atomic-artifact, "
                f"artifact-env)\n")
    lines = [f"invariant lint: {len(offenders)} offender(s)"]
    for o in offenders:
        lines.append(f"  {o['file']}:{o['line']}: [{o['rule']}] "
                     f"{o['detail']}")
    return "\n".join(lines) + "\n"
