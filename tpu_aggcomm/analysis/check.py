"""Schedule model checker: prove liveness and conservation statically.

Everything here is symbolic execution over ``Schedule.programs`` — no
backend, no jax, no measured callback. The completion semantics mirror
``backends/local.py`` (the runtime oracle) op for op:

- ISEND / SIGNAL_SEND complete at post (eager); SEND is modeled eager
  too (MPICH buffers benchmark-sized payloads eagerly and m=6/7 NEED
  that — see the oracle's SEND comment);
- ISSEND completes only when the matching receive is POSTED
  (rendezvous — delivery in the oracle happens at ``try_deliver`` as
  soon as both sides are posted);
- IRECV/RECV complete at delivery, i.e. when the matching send is
  posted; SENDRECV posts its send half eagerly and blocks on its recv
  half; WAITALL completes when every listed token's op completed;
- BARRIER / ALLTOALLW are n-rank generation joins;
- a chan-0 message on a dead link (``schedule.fault`` deadlinks) is
  DROPPED: it never delivers and never completes anything.

Five properties per schedule, each PROVEN or REFUTED with a named
witness (never a bare boolean):

1. **deadlock_freedom** — the Issue/Complete event graph is acyclic and
   every required completion has a match. Refutation names either the
   unmatched op (e.g. a rendezvous send whose receive was never posted)
   or the offending cycle, rank/op by rank/op.
2. **race_freedom** — no two in-flight writes to the same (rank, recv
   row) overlap: an IRECV's write interval spans post → its WAITALL
   (never-waited = open), blocking RECV / SENDRECV-recv / COPY write at
   their program point; staging rows are a separate namespace.
3. **conservation** — per matching key (src, dst, chan): exactly one
   send and one matching receive, byte counts equal where both sides
   declare them; chan-0 delivered bytes (+ COPY memcpys) equal the
   pattern's expected coverage, dead edges excepted — each dead edge
   must instead be covered by a relay detour chain. Cross-checked
   against ``obs.traffic.round_edges`` so the two static views can
   never drift apart silently.
4. **barrier_symmetry** — every rank's barrier (round-tag) signature is
   identical (the property ``core.schedule.barrier_rounds_of`` /
   ``schedule_shape_key`` now *check* instead of assume).
5. **round_monotonicity** — per rank, blocking-op round tags never
   decrease; matched send/recv (and signal) pairs agree on their round
   tag; a WAITALL's round is >= the round of every rendezvous-send /
   recv token it completes (eager tokens complete at post and are
   exempt — the repair pass legitimately retags a detoured eager send
   to its relay round).

Schedules with no rank op programs (the hierarchical TAM engine) are
EXEMPT, exactly like the traffic auditor.
"""

from __future__ import annotations

import math

__all__ = ["CheckError", "CHECK_SCHEMA", "PROPERTIES", "check_schedule",
           "check_sweep", "render_check", "render_check_sweep",
           "write_artifact"]

CHECK_SCHEMA = "check-v1"

PROPERTIES = ("deadlock_freedom", "race_freedom", "conservation",
              "barrier_symmetry", "round_monotonicity")

# cap per-property witness lists in reports/artifacts (the first
# offender is the proof; thousands of them are noise)
MAX_WITNESSES = 8


class CheckError(ValueError):
    """A schedule cannot be checked as asked (unknown method id,
    malformed fault spec...)."""


def _op_kinds():
    from tpu_aggcomm.core.schedule import OpKind
    return OpKind


def _dead_pairs(schedule) -> set:
    """Directed chan-0 pairs whose link drops messages (the oracle's
    injection rule for UNREPAIRED faulted schedules). Repaired
    schedules have no chan-0 op left on these pairs, so the set is
    harmless there."""
    fault = getattr(schedule, "fault", None)
    if not fault:
        return set()
    from tpu_aggcomm.faults.spec import parse_fault
    return set(parse_fault(fault).deadlinks)


def _op_label(rank: int, idx: int, op) -> dict:
    OpKind = _op_kinds()
    d = {"rank": rank, "op_index": idx, "kind": OpKind(op.kind).name,
         "round": int(op.round)}
    if op.kind is OpKind.WAITALL:
        d["tokens"] = list(op.tokens)
    elif op.peer >= 0:
        d["peer"] = int(op.peer)
    return d


# ---------------------------------------------------------------------------
# Property 1: deadlock freedom (the waits-for event graph)

def _deadlock_freedom(schedule) -> dict:
    OpKind = _op_kinds()
    progs = schedule.programs
    n = len(progs)
    dead = _dead_pairs(schedule)

    # node ids: per op, Issue = 2*opid, Complete = 2*opid + 1; virtual
    # join nodes (barrier / alltoallw generations) appended after.
    base = [0] * n
    total = 0
    for r, prog in enumerate(progs):
        base[r] = total
        total += len(prog)

    def issue(r, i):
        return 2 * (base[r] + i)

    def complete(r, i):
        return 2 * (base[r] + i) + 1

    deps: list[list[int]] = [[] for _ in range(2 * total)]
    never_desc: dict[int, str] = {}  # sentinel nodes that can never fire

    def never(desc: str) -> int:
        nid = len(deps)
        deps.append([])
        never_desc[nid] = desc
        return nid

    # matching tables (message matching is by (src, dst, chan), unique
    # per rep — mpi_test.c:1776; signals match FIFO per directed pair)
    send_post: dict = {}
    recv_post: dict = {}
    sig_send: dict = {}
    sig_recv: dict = {}
    token_of: list[dict] = [dict() for _ in range(n)]
    barrier_ops: list[list[int]] = [[] for _ in range(n)]
    a2aw_ops: list[list[int]] = [[] for _ in range(n)]
    dup = None
    for r, prog in enumerate(progs):
        for i, op in enumerate(prog):
            k = op.kind
            if k in (OpKind.ISEND, OpKind.ISSEND, OpKind.SEND):
                key = (r, op.peer, op.chan)
                if key in send_post:
                    dup = dup or f"duplicate send on matching key {key}"
                send_post[key] = (r, i)
            elif k in (OpKind.IRECV, OpKind.RECV):
                key = (op.peer, r, op.chan)
                if key in recv_post:
                    dup = dup or f"duplicate recv on matching key {key}"
                recv_post[key] = (r, i)
            elif k is OpKind.SENDRECV:
                skey = (r, op.peer, 0)
                rkey = (op.peer2, r, 0)
                if skey in send_post:
                    dup = dup or f"duplicate send on matching key {skey}"
                if rkey in recv_post:
                    dup = dup or f"duplicate recv on matching key {rkey}"
                send_post[skey] = (r, i)
                recv_post[rkey] = (r, i)
            elif k is OpKind.SIGNAL_SEND:
                sig_send.setdefault((r, op.peer), []).append((r, i))
            elif k is OpKind.SIGNAL_RECV:
                sig_recv.setdefault((op.peer, r), []).append((r, i))
            elif k is OpKind.BARRIER:
                barrier_ops[r].append(i)
            elif k is OpKind.ALLTOALLW:
                a2aw_ops[r].append(i)
            if op.token >= 0:
                token_of[r][op.token] = i
    if dup:
        # ambiguous matching is a structural defect: the waits-for graph
        # is not well defined, which is itself a refutation
        return {"verdict": "REFUTED", "detail": dup, "unmatched": [],
                "cycle": []}

    # virtual generation joins: one node per barrier / collective
    # generation, depending on every rank's g-th Issue (linear in n
    # instead of the n^2 all-pairs join)
    def join_nodes(per_rank_ops, what):
        counts = {len(x) for x in per_rank_ops}
        gens = max(len(x) for x in per_rank_ops) if per_rank_ops else 0
        nodes = []
        for g in range(gens):
            nid = len(deps)
            deps.append([])
            for r in range(n):
                if g < len(per_rank_ops[r]):
                    deps[nid].append(issue(r, per_rank_ops[r][g]))
                else:
                    deps[nid].append(never(
                        f"{what} generation {g}: rank {r} has only "
                        f"{len(per_rank_ops[r])} {what} op(s) — the "
                        f"n-rank join can never release (arity skew)"))
            nodes.append(nid)
        return nodes, len(counts) > 1

    barrier_join, _ = join_nodes(barrier_ops, "barrier")
    a2aw_join, _ = join_nodes(a2aw_ops, "alltoallw")

    BLOCKING = (OpKind.RECV, OpKind.SENDRECV, OpKind.WAITALL,
                OpKind.BARRIER, OpKind.SIGNAL_RECV, OpKind.ALLTOALLW)

    def match_send(key, r, i, what):
        """Dep for 'the matching send of key is posted'."""
        if key[2] == 0 and (key[0], key[1]) in dead:
            return never(f"{what} at rank {r} op {i}: the {key[0]}>"
                         f"{key[1]} link is dead — the message is "
                         f"dropped and never delivers")
        if key in send_post:
            sr, si = send_post[key]
            return issue(sr, si)
        return never(f"{what} at rank {r} op {i}: no matching send "
                     f"posted for (src={key[0]}, dst={key[1]}, "
                     f"chan={key[2]})")

    def match_recv(key, r, i, what):
        """Dep for 'the matching receive of key is posted'."""
        if key[2] == 0 and (key[0], key[1]) in dead:
            return never(f"{what} at rank {r} op {i}: the {key[0]}>"
                         f"{key[1]} link is dead — rendezvous can "
                         f"never complete")
        if key in recv_post:
            rr, ri = recv_post[key]
            return issue(rr, ri)
        return never(f"{what} at rank {r} op {i}: no matching receive "
                     f"posted for (src={key[0]}, dst={key[1]}, "
                     f"chan={key[2]})")

    bar_seen = [0] * n
    a2aw_seen = [0] * n
    for r, prog in enumerate(progs):
        for i, op in enumerate(prog):
            k = op.kind
            # program order: issuing op i needs op i-1 issued, plus
            # completed when op i-1 blocks the program counter
            if i > 0:
                deps[issue(r, i)].append(issue(r, i - 1))
                if prog[i - 1].kind in BLOCKING:
                    deps[issue(r, i)].append(complete(r, i - 1))
            c = deps[complete(r, i)]
            c.append(issue(r, i))
            if k is OpKind.ISSEND:
                c.append(match_recv((r, op.peer, op.chan), r, i,
                                    "rendezvous ISSEND"))
            elif k in (OpKind.IRECV, OpKind.RECV):
                c.append(match_send((op.peer, r, op.chan), r, i,
                                    k.name))
            elif k is OpKind.SENDRECV:
                c.append(match_send((op.peer2, r, 0), r, i,
                                    "SENDRECV recv half"))
            elif k is OpKind.WAITALL:
                for t in op.tokens:
                    ti = token_of[r].get(t)
                    if ti is None:
                        c.append(never(
                            f"WAITALL at rank {r} op {i} waits on token "
                            f"{t} that no op of rank {r} ever posts"))
                    else:
                        c.append(complete(r, ti))
            elif k is OpKind.BARRIER:
                c.append(barrier_join[bar_seen[r]])
                bar_seen[r] += 1
            elif k is OpKind.SIGNAL_RECV:
                pair = (op.peer, r)
                ordinal = len([x for x in sig_recv.get(pair, ())
                               if x[0] == r and x[1] <= i]) - 1
                sends = sig_send.get(pair, ())
                if ordinal < len(sends):
                    sr, si = sends[ordinal]
                    c.append(issue(sr, si))
                else:
                    c.append(never(
                        f"SIGNAL_RECV at rank {r} op {i}: only "
                        f"{len(sends)} signal(s) ever sent on pair "
                        f"{pair}, need {ordinal + 1}"))
            elif k is OpKind.ALLTOALLW:
                c.append(a2aw_join[a2aw_seen[r]])
                a2aw_seen[r] += 1
            # ISEND / SEND / SIGNAL_SEND / COPY: complete at issue

    # Kahn propagation over the AND-dependency graph: a node fires when
    # every dep fired; sentinel ("never") nodes cannot fire
    n_nodes = len(deps)
    pending = [len(d) for d in deps]
    rev: list[list[int]] = [[] for _ in range(n_nodes)]
    for node, ds in enumerate(deps):
        for d in ds:
            rev[d].append(node)
    fired = [False] * n_nodes
    queue = [node for node in range(n_nodes)
             if pending[node] == 0 and node not in never_desc]
    while queue:
        node = queue.pop()
        if fired[node]:
            continue
        fired[node] = True
        for succ in rev[node]:
            pending[succ] -= 1
            if pending[succ] == 0 and succ not in never_desc:
                queue.append(succ)

    stuck = [node for node in range(2 * total) if not fired[node]]
    if not stuck:
        return {"verdict": "PROVEN",
                "detail": f"all {2 * total} issue/complete events fire: "
                          f"acyclic waits-for graph, every required "
                          f"completion matched",
                "unmatched": [], "cycle": []}

    # name the refutation: unmatched root causes first, then a cycle
    import bisect

    def describe(node):
        opid, kind = divmod(node, 2)
        r = bisect.bisect_right(base, opid) - 1
        while not progs[r]:
            r -= 1
        i = opid - base[r]
        d = _op_label(r, i, progs[r][i])
        d["event"] = "complete" if kind else "issue"
        return d

    # root causes: never-deps of ANY unfired node — virtual join nodes
    # included, so "barrier generation g: rank r has fewer barriers"
    # surfaces even though the join sits between the op and the sentinel
    unfired = [node for node in range(n_nodes)
               if not fired[node] and node not in never_desc]
    unmatched = []
    seen_desc = set()
    for node in unfired:
        for d in deps[node]:
            if d in never_desc and never_desc[d] not in seen_desc:
                seen_desc.add(never_desc[d])
                unmatched.append(never_desc[d])
    # cycle extraction: follow unsatisfied deps among unfired nodes
    # (virtual joins are traversed but elided from the description)
    cycle = []
    unfired_set = set(unfired)
    visited = set()
    for start in stuck:
        if start in visited:
            continue
        path, on_path = [], {}
        node = start
        while node is not None and node not in visited:
            if node in on_path:
                cyc = path[path.index(node):]
                cycle = [describe(x) for x in cyc if x < 2 * total]
                break
            on_path[node] = True
            path.append(node)
            node = next((d for d in deps[node]
                         if d in unfired_set), None)
        if cycle:
            break
        visited.update(path)
    head = (f"{len(stuck)} of {2 * total} events can never fire"
            if not unmatched else unmatched[0])
    if cycle and not unmatched:
        head = (f"waits-for cycle through {len(cycle)} events, e.g. "
                f"rank {cycle[0]['rank']} op {cycle[0]['op_index']} "
                f"({cycle[0]['kind']})")
    return {"verdict": "REFUTED", "detail": head,
            "unmatched": unmatched[:MAX_WITNESSES],
            "cycle": cycle[:4 * MAX_WITNESSES]}


# ---------------------------------------------------------------------------
# Property 2: recv-slot race freedom

def _race_freedom(schedule) -> dict:
    OpKind = _op_kinds()
    races = []
    checked = 0
    for r, prog in enumerate(schedule.programs):
        # token -> pc of the WAITALL completing it (first one listing it)
        wait_pc: dict[int, int] = {}
        for i, op in enumerate(prog):
            if op.kind is OpKind.WAITALL:
                for t in op.tokens:
                    wait_pc.setdefault(t, i)
        intervals: dict[tuple, list] = {}
        for i, op in enumerate(prog):
            if op.kind is OpKind.IRECV and op.nbytes > 0:
                row = (("stage" if op.to_stage else "slot"), op.slot)
                end = wait_pc.get(op.token, math.inf)
                intervals.setdefault(row, []).append((i, end, i))
            elif op.kind is OpKind.RECV and op.nbytes > 0:
                row = (("stage" if op.to_stage else "slot"), op.slot)
                intervals.setdefault(row, []).append((i, i, i))
            elif op.kind is OpKind.SENDRECV and op.nbytes > 0:
                intervals.setdefault(("slot", op.slot2), []).append((i, i, i))
            elif op.kind is OpKind.COPY:
                intervals.setdefault(("slot", op.slot2), []).append((i, i, i))
        for row, ivs in intervals.items():
            checked += len(ivs)
            ivs.sort()
            for (s1, e1, i1), (s2, _e2, i2) in zip(ivs, ivs[1:]):
                if s2 <= e1:
                    races.append({
                        "rank": r, "row": list(row),
                        "ops": [i1, i2],
                        "detail": f"rank {r} {row[0]} {row[1]}: write "
                                  f"of op {i2} is in flight while the "
                                  f"write of op {i1} (completed at "
                                  f"{'op %d' % e1 if e1 != math.inf else 'no WAITALL — open interval'}) "
                                  f"is still outstanding"})
    if races:
        return {"verdict": "REFUTED",
                "detail": races[0]["detail"],
                "races": races[:MAX_WITNESSES]}
    return {"verdict": "PROVEN",
            "detail": f"{checked} receive-row write intervals, no two "
                      f"in flight on the same (rank, row)",
            "races": []}


# ---------------------------------------------------------------------------
# Property 3: byte conservation

def _conservation(schedule) -> dict:
    OpKind = _op_kinds()
    p = schedule.pattern
    offenders = []
    if getattr(schedule, "collective", False):
        send, recv = p.dense_counts()
        tx = int(send.sum())
        rx = int(recv.sum())
        if tx != rx or (send.T != recv).any():
            offenders.append(f"dense matrices disagree: {tx} B sent vs "
                             f"{rx} B received")
        counts = [sum(1 for op in prog if op.kind is OpKind.ALLTOALLW)
                  for prog in schedule.programs]
        if len(set(counts)) > 1:
            offenders.append(f"collective join arity differs across "
                             f"ranks: {sorted(set(counts))}")
        if offenders:
            return {"verdict": "REFUTED", "detail": offenders[0],
                    "offenders": offenders, "edges": 0, "bytes": tx}
        return {"verdict": "PROVEN",
                "detail": f"dense collective: send matrix transposes "
                          f"to the recv matrix, {tx} B each way, "
                          f"uniform {counts[0]}-call join on all "
                          f"{p.nprocs} ranks",
                "offenders": [], "edges": int((send > 0).sum()),
                "bytes": tx}

    dead = _dead_pairs(schedule)
    sends: dict = {}
    recvs: dict = {}
    copies: dict = {}
    for r, prog in enumerate(schedule.programs):
        for i, op in enumerate(prog):
            k = op.kind
            if k in (OpKind.ISEND, OpKind.ISSEND, OpKind.SEND):
                sends[(r, op.peer, op.chan)] = (op.nbytes, i,
                                                op.from_stage)
            elif k in (OpKind.IRECV, OpKind.RECV):
                recvs[(op.peer, r, op.chan)] = (op.nbytes, i,
                                                op.to_stage)
            elif k is OpKind.SENDRECV:
                sends[(r, op.peer, 0)] = (op.nbytes, i, False)
                # the recv half declares no independent byte count (the
                # op's nbytes is the SEND count — m=9/10 pairwise posts
                # asymmetric halves): existence-only
                recvs[(op.peer2, r, 0)] = (None, i, False)
            elif k is OpKind.COPY:
                copies[(r, r)] = copies.get((r, r), 0) + p.data_size

    delivered: dict = {}
    for key, (nb, _i, _st) in sends.items():
        src, dst, chan = key
        if nb and key not in recvs:
            offenders.append(f"send {key} ({nb} B) has no matching "
                             f"receive — bytes are lost")
            continue
        rnb = recvs.get(key, (None, None, None))[0]
        if nb and rnb is not None and rnb != nb:
            offenders.append(f"byte mismatch on {key}: send posts "
                             f"{nb} B, receive expects {rnb} B")
        if chan == 0 and (src, dst) in dead:
            if nb:
                offenders.append(f"send {key} ({nb} B) crosses the "
                                 f"dead {src}>{dst} link — dropped, "
                                 f"never delivered")
            continue
        if nb and chan == 0:
            delivered[(src, dst)] = delivered.get((src, dst), 0) + nb
    for key, (rnb, _i, _st) in recvs.items():
        if key not in sends:
            offenders.append(f"receive {key} has no matching send — "
                             f"it can never be satisfied")

    # pattern coverage: every (sender, receiver) pair must get its
    # data_size bytes on chan 0 or via COPY; a dead edge must instead
    # be covered by a relay detour chain (chan != 0, staged hop)
    dead_edges = {(int(s), int(d))
                  for s, d in getattr(schedule, "dead_edges", ())}
    expected = {(int(s), int(d)) for s in p.senders for d in p.receivers}
    for s, d in sorted(expected):
        got = delivered.get((s, d), 0) + copies.get((s, d), 0)
        if (s, d) in dead_edges:
            if got:
                offenders.append(f"dead edge ({s}, {d}) still delivers "
                                 f"{got} B on the data channel")
            hop1 = any(k[0] == s and k[2] and v[2]
                       for k, v in recvs.items())
            hop2 = any(k[1] == d and k[2] and v[2]
                       for k, v in sends.items())
            if not (hop1 and hop2):
                offenders.append(f"dead edge ({s}, {d}) has no relay "
                                 f"detour chain (staged hop via a live "
                                 f"intermediate)")
        elif got != p.data_size:
            offenders.append(f"pair ({s}, {d}) delivers {got} B, "
                             f"pattern expects {p.data_size} B")
    for pair in sorted(set(delivered) - expected):
        if delivered[pair]:
            offenders.append(f"pair {pair} delivers "
                             f"{delivered[pair]} B outside the "
                             f"pattern's sender x receiver coverage")

    # cross-check against the traffic auditor's matrix: two independent
    # walks over the same programs must count the same bytes per pair
    from tpu_aggcomm.obs.traffic import round_edges
    tm: dict = {}
    for c in round_edges(schedule).values():
        for pair, b in c["edges"].items():
            tm[pair] = tm.get(pair, 0) + b
    mine: dict = {}
    for (src, dst, _chan), (nb, _i, _st) in sends.items():
        if nb:
            mine[(src, dst)] = mine.get((src, dst), 0) + nb
    if tm != mine:
        diff = {k: (mine.get(k, 0), tm.get(k, 0))
                for k in set(mine) | set(tm)
                if mine.get(k, 0) != tm.get(k, 0)}
        offenders.append(f"traffic-matrix cross-check disagrees on "
                         f"{len(diff)} pair(s), e.g. "
                         f"{sorted(diff.items())[:3]}")

    total = sum(v for v in delivered.values()) + sum(copies.values())
    if offenders:
        return {"verdict": "REFUTED", "detail": offenders[0],
                "offenders": offenders[:MAX_WITNESSES],
                "edges": len(sends), "bytes": total}
    return {"verdict": "PROVEN",
            "detail": f"{len(sends)} matched sends, {total} B delivered "
                      f"== pattern coverage; traffic-matrix cross-check "
                      f"agrees",
            "offenders": [], "edges": len(sends), "bytes": total}


# ---------------------------------------------------------------------------
# Property 4: barrier SPMD symmetry

def _barrier_symmetry(schedule) -> dict:
    from tpu_aggcomm.core.schedule import barrier_signatures
    sigs = barrier_signatures(schedule)
    ref = sigs[0] if sigs else ()
    bad = [r for r, s in enumerate(sigs) if s != ref]
    if bad:
        r = bad[0]
        return {"verdict": "REFUTED",
                "detail": f"barrier signature of rank {r} is "
                          f"{list(sigs[r])}, rank 0 has {list(ref)} — "
                          f"the schedule is not SPMD-symmetric "
                          f"({len(bad)} divergent rank(s))",
                "signature": list(ref), "divergent_ranks":
                    bad[:MAX_WITNESSES]}
    return {"verdict": "PROVEN",
            "detail": f"all {len(sigs)} ranks share the barrier "
                      f"signature {list(ref)}",
            "signature": list(ref), "divergent_ranks": []}


# ---------------------------------------------------------------------------
# Property 5: round-fence monotonicity

def _round_monotonicity(schedule) -> dict:
    OpKind = _op_kinds()
    offenders = []
    BLOCKING = (OpKind.RECV, OpKind.SENDRECV, OpKind.WAITALL,
                OpKind.BARRIER, OpKind.SIGNAL_RECV, OpKind.ALLTOALLW)
    send_round: dict = {}
    recv_round: dict = {}
    sig_round: dict = {}
    for r, prog in enumerate(schedule.programs):
        last = -1
        token_op: dict[int, object] = {}
        for i, op in enumerate(prog):
            k = op.kind
            if k in BLOCKING:
                if op.round < last:
                    offenders.append(
                        f"rank {r} op {i} ({k.name}) at round "
                        f"{op.round} after a blocking op at round "
                        f"{last} — the fence order runs backward")
                last = max(last, op.round)
            if k in (OpKind.ISEND, OpKind.ISSEND, OpKind.SEND):
                send_round[(r, op.peer, op.chan)] = op.round
            elif k in (OpKind.IRECV, OpKind.RECV):
                recv_round[(op.peer, r, op.chan)] = op.round
            elif k is OpKind.SENDRECV:
                send_round[(r, op.peer, 0)] = op.round
                recv_round[(op.peer2, r, 0)] = op.round
            elif k is OpKind.SIGNAL_SEND:
                sig_round.setdefault((r, op.peer), []).append(op.round)
            elif k is OpKind.SIGNAL_RECV:
                sig_round.setdefault((op.peer, r, "recv"),
                                     []).append(op.round)
            if op.token >= 0:
                token_op[op.token] = op
            if k is OpKind.WAITALL:
                for t in op.tokens:
                    o = token_op.get(t)
                    if o is not None and o.kind in (OpKind.ISSEND,
                                                    OpKind.IRECV) \
                            and o.round > op.round:
                        offenders.append(
                            f"rank {r} WAITALL op {i} at round "
                            f"{op.round} completes a {o.kind.name} "
                            f"token tagged round {o.round} — the wait "
                            f"closes a fence that opens later")
    for key, rnd in send_round.items():
        if key in recv_round and recv_round[key] != rnd:
            offenders.append(
                f"matched pair {key} disagrees on its round: send "
                f"tagged {rnd}, receive tagged {recv_round[key]}")
    for pair, rounds in sig_round.items():
        if len(pair) == 2 and (pair[0], pair[1], "recv") in sig_round:
            got = sig_round[(pair[0], pair[1], "recv")]
            if sorted(rounds) != sorted(got):
                offenders.append(
                    f"signal pair {pair} round tags disagree: sends "
                    f"{sorted(rounds)}, receives {sorted(got)}")
    if offenders:
        return {"verdict": "REFUTED", "detail": offenders[0],
                "offenders": offenders[:MAX_WITNESSES]}
    return {"verdict": "PROVEN",
            "detail": "blocking rounds non-decreasing on every rank; "
                      "every matched pair agrees on its round tag",
            "offenders": []}


# ---------------------------------------------------------------------------
# The report

def check_schedule(schedule) -> dict:
    """Run all five properties over one compiled schedule → check-v1
    dict. Verdict is PROVEN only when every property is; EXEMPT for
    schedules with no rank op programs (the TAM engine)."""
    p = schedule.pattern
    cfg = {"method": schedule.method_id, "name": schedule.name,
           "nprocs": p.nprocs, "cb_nodes": p.cb_nodes,
           "data_size": p.data_size, "comm_size": p.comm_size,
           "proc_node": p.proc_node, "agg_type": int(p.placement),
           "direction": p.direction.value}
    if getattr(schedule, "fault", None):
        cfg["fault"] = schedule.fault
        # the repair pass stamps variant=canonical spec; a fault stamp
        # WITHOUT that variant is an injected, unrepaired program
        cfg["repaired"] = (getattr(schedule, "variant", "")
                          == schedule.fault)
    base = {"schema": CHECK_SCHEMA, "config": cfg}
    if (getattr(schedule, "programs", None) is None
            or getattr(schedule, "assignment", None) is not None):
        note = ("hierarchical TAM engine: traffic rides mesh "
                "collectives, no rank op programs to model-check")
        base.update({"verdict": "EXEMPT",
                     "properties": {k: {"verdict": "EXEMPT",
                                        "detail": note}
                                    for k in PROPERTIES}})
        return base
    props = {
        "deadlock_freedom": _deadlock_freedom(schedule),
        "race_freedom": _race_freedom(schedule),
        "conservation": _conservation(schedule),
        "barrier_symmetry": _barrier_symmetry(schedule),
        "round_monotonicity": _round_monotonicity(schedule),
    }
    verdict = ("REFUTED" if any(v["verdict"] == "REFUTED"
                                for v in props.values()) else "PROVEN")
    base.update({"verdict": verdict, "properties": props})
    return base


def check_sweep(nprocs: int, cb_nodes: int, comm_size: int,
                data_size: int = 2048, proc_node: int = 1,
                agg_type: int = 1, include_dead: bool = True,
                fault: str | None = None,
                barrier_type: int = 0) -> list:
    """Model-check every method in METHODS at one shape — the jax-free
    static gate (scripts/ci_tier1.sh). With ``fault``, each repairable
    method is checked in its REPAIRED form (methods the repair pass
    refuses are reported SKIPPED with the reason, not failed — refusal
    is designed behavior, e.g. jax_shard-style blocking exchanges)."""
    from tpu_aggcomm.core.methods import METHODS, compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern

    p = AggregatorPattern(nprocs=nprocs, cb_nodes=cb_nodes,
                          data_size=data_size, placement=agg_type,
                          proc_node=proc_node, comm_size=comm_size)
    rows = []
    for mid in sorted(METHODS):
        if not include_dead and not METHODS[mid].dispatched:
            continue
        sched = compile_method(mid, p, barrier_type=barrier_type)
        row = {"method": mid, "name": METHODS[mid].name}
        if fault:
            from tpu_aggcomm.faults import (FaultSpecError, RepairError,
                                            repair_schedule)
            try:
                sched = repair_schedule(sched, fault,
                                        barrier_type=barrier_type)
            except (FaultSpecError, RepairError) as e:
                row.update({"verdict": "SKIPPED", "detail": str(e),
                            "refuted": []})
                rows.append(row)
                continue
        rep = check_schedule(sched)
        refuted = [k for k, v in rep["properties"].items()
                   if v["verdict"] == "REFUTED"]
        detail = (rep["properties"][refuted[0]]["detail"] if refuted
                  else rep["properties"]["deadlock_freedom"]["detail"]
                  if rep["verdict"] != "EXEMPT"
                  else rep["properties"]["deadlock_freedom"]["detail"])
        row.update({"verdict": rep["verdict"], "refuted": refuted,
                    "detail": detail})
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# Renderers / artifact

def render_check(report: dict) -> str:
    cfg = report["config"]
    head = (f"schedule check: m={cfg['method']} \"{cfg['name']}\" "
            f"({cfg['direction']}) n={cfg['nprocs']} a={cfg['cb_nodes']} "
            f"c={cfg['comm_size']} d={cfg['data_size']} B")
    if cfg.get("fault"):
        head += (f" [fault-{'repaired' if cfg.get('repaired') else 'INJECTED (unrepaired)'}: "
                 f"{cfg['fault']}]")
    lines = [head]
    for name in PROPERTIES:
        prop = report["properties"][name]
        lines.append(f"  {name:20s} {prop['verdict']:8s} {prop['detail']}")
        if prop["verdict"] != "REFUTED":
            continue
        for u in prop.get("unmatched", []):
            lines.append(f"    unmatched: {u}")
        cyc = prop.get("cycle", [])
        if cyc:
            lines.append(f"    cycle ({len(cyc)} events):")
            # one line per event keeps the witness pasteable into a bug
            for ev in cyc:
                tgt = (f" tokens={ev['tokens']}" if "tokens" in ev
                       else f" peer={ev['peer']}" if "peer" in ev else "")
                lines.append(f"      rank {ev['rank']:4d} op "
                             f"{ev['op_index']:4d} {ev['kind']:11s} "
                             f"round {ev['round']:3d}{tgt} "
                             f"[{ev['event']}]")
        for o in prop.get("offenders", [])[:MAX_WITNESSES]:
            lines.append(f"    offender: {o}")
        for rc in prop.get("races", [])[:MAX_WITNESSES]:
            lines.append(f"    race: {rc['detail']}")
    lines.append(f"verdict: {report['verdict']}")
    return "\n".join(lines) + "\n"


def render_check_sweep(rows: list, nprocs: int, cb_nodes: int,
                       comm_size: int, fault: str | None = None) -> str:
    head = (f"model-check sweep: {len(rows)} methods at n={nprocs} "
            f"a={cb_nodes} c={comm_size}")
    if fault:
        head += f" under fault \"{fault}\" (repaired)"
    lines = [head]
    n_ref = 0
    for r in rows:
        if r["verdict"] == "REFUTED":
            n_ref += 1
            lines.append(f"  m={r['method']:2d} {r['name']:34s} REFUTED  "
                         f"[{','.join(r['refuted'])}] {r['detail']}")
        elif r["verdict"] in ("EXEMPT", "SKIPPED"):
            lines.append(f"  m={r['method']:2d} {r['name']:34s} "
                         f"{r['verdict']:8s} {r['detail']}")
        else:
            lines.append(f"  m={r['method']:2d} {r['name']:34s} PROVEN   "
                         f"{r['detail']}")
    lines.append(f"REFUTED: {n_ref} of {len(rows)}")
    return "\n".join(lines) + "\n"


def write_artifact(path: str, report: dict) -> str:
    """Write a check-v1 JSON artifact (atomic_write: a kill mid-write
    can't tear it)."""
    import json

    from tpu_aggcomm.obs.atomic import atomic_write
    with atomic_write(path) as fh:
        json.dump(report, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return path
