"""Crash-safe JSONL run journal — the resume substrate.

A journal is an append-only JSONL file with two line kinds:

- **session header** — ``{"journal": "resilience-journal-v1",
  "fingerprint": fp, "manifest": {...}, "created_unix": t}``: the v3
  ledger manifest of the process that wrote the following entries, plus
  its :func:`tune.cache.manifest_fingerprint` (so no drift ⟺ same
  fingerprint, the exact lens the tune cache and ``--check-regression``
  use).
- **entry** — ``{"key": {...}, "status": "done"|"fail",
  "fingerprint": fp, ...extras (shape_keys, artifacts, wall_s)}``: one
  completed (or failed) unit of work, keyed by the caller's full config
  dict — for sweeps that includes the fault spec, and the recorded
  ``shape_keys`` carry ``schedule_shape_key`` strings for provenance.

Crash safety is asymmetric by design: writes are append+flush+fsync
(never a whole-file rewrite — concurrent with a kill, the worst case is
one torn final line), and :meth:`RunJournal.entries` silently skips any
line that does not parse — a job killed mid-append loses at most the
entry being written, never the journal.

Resume semantics mirror the tune cache (tune/cache.py lookup): an entry
counts as completed only when its fingerprint matches the CURRENT
manifest's; on mismatch the drifted keys are NAMED (via
``diff_manifests`` against the stored session manifest) and the caller
re-runs the cell. jax-free throughout.
"""

from __future__ import annotations

import json
import os
import time

from tpu_aggcomm.obs.ledger import diff_manifests

__all__ = ["JOURNAL_SCHEMA", "RunJournal"]

JOURNAL_SCHEMA = "resilience-journal-v1"


class RunJournal:
    """One journal file. Stateless between calls: every read re-scans
    the file, so concurrent appenders (a resumed job next to a
    straggling old one) see each other's completed entries."""

    def __init__(self, path: str):
        self.path = path

    # -- reading -----------------------------------------------------------
    def _scan(self) -> tuple[dict, list[dict]]:
        """(headers: fingerprint -> manifest, entries). Torn/corrupt
        lines are skipped — crash-safety is the reader's job."""
        headers: dict = {}
        entries: list[dict] = []
        try:
            fh = open(self.path)
        except OSError:
            return headers, entries
        with fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if rec.get("journal") == JOURNAL_SCHEMA:
                    fp = rec.get("fingerprint")
                    if fp is not None:
                        headers[fp] = rec.get("manifest")
                elif isinstance(rec.get("key"), dict):
                    entries.append(rec)
        return headers, entries

    def entries(self) -> list[dict]:
        return self._scan()[1]

    def sessions(self) -> dict:
        """fingerprint -> manifest for every session header in the file
        — the lens :mod:`tpu_aggcomm.serve.recover` names drift through
        when a ``--recover`` pre-warm meets entries written by a
        different environment."""
        return self._scan()[0]

    # -- writing -----------------------------------------------------------
    def _append(self, rec: dict) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(self.path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    def begin_session(self, manifest: dict | None) -> str:
        """Record this process's manifest (once per fingerprint) and
        return the fingerprint to stamp entries with."""
        from tpu_aggcomm.tune.cache import manifest_fingerprint
        fp = manifest_fingerprint(manifest)
        headers, _ = self._scan()
        if fp not in headers:
            self._append({"journal": JOURNAL_SCHEMA, "fingerprint": fp,
                          "manifest": manifest,
                          "created_unix": time.time()})
        return fp

    def record(self, key: dict, *, fingerprint: str, status: str = "done",
               **extra) -> dict:
        """Append one entry (``extra``: shape_keys, artifacts, wall_s…;
        None values dropped, record_compile discipline)."""
        rec = {"key": dict(key), "status": str(status),
               "fingerprint": str(fingerprint)}
        for k, v in extra.items():
            if v is not None:
                rec[k] = v
        self._append(rec)
        return rec

    # -- resume ------------------------------------------------------------
    def completed(self, key: dict, *, fingerprint: str,
                  manifest: dict | None = None
                  ) -> tuple[bool, str | None]:
        """Is ``key`` done under the CURRENT environment?

        ``(True, None)`` — a ``status="done"`` entry exists with a
        matching fingerprint. ``(False, reason)`` — entries exist only
        under a different fingerprint: ``reason`` names the drifted
        manifest keys (tune-cache semantics; re-run the cell).
        ``(False, None)`` — no entry at all."""
        headers, entries = self._scan()
        stale_fp = None
        for rec in entries:
            if rec.get("key") != key or rec.get("status") != "done":
                continue
            if rec.get("fingerprint") == fingerprint:
                return True, None
            stale_fp = rec.get("fingerprint")
        if stale_fp is None:
            return False, None
        drift = diff_manifests(headers.get(stale_fp), manifest)
        keys = ", ".join(d["key"] for d in drift[:4]) or \
            f"fingerprint {stale_fp} != {fingerprint}"
        more = f" (+{len(drift) - 4} more)" if len(drift) > 4 else ""
        return False, (f"manifest drift vs journal entry: {keys}{more} "
                       f"— re-running")

    def seen(self, key: dict) -> bool:
        """Any entry (any status, any fingerprint) for ``key``? Callers
        use this to decide whether the journal is authoritative over
        legacy completion heuristics for a cell."""
        return any(rec.get("key") == key for rec in self.entries())
