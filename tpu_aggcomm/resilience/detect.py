"""Online fault detection: measured round walls → proposed --fault spec.

Closes the PR 6 detect→repair loop from the measurement side: the
straggler statistics of a recorded run (``obs.metrics.round_stats``,
used VERBATIM — the same numbers ``inspect trace`` prints) are matched
against the slow-rank fault signature, and a *proposed* ``--fault``
spec string in the PR 6 grammar comes out (validated by a
``parse_fault`` round trip, so a proposal is always re-injectable).

Detection is ADVISORY ONLY — an extra output line on ``inspect trace``;
it never alters schedules, timers, or verdicts. The signature is
deliberately conservative (a rank must dominate the critical path in a
strict majority of >= 3 rounds AND by a meaningful factor) because a false
"rank R is degraded" line would send an operator chasing ghosts.

jax-free (obs.metrics + faults.spec are jax-free).
"""

from __future__ import annotations

import statistics

__all__ = ["propose_fault_specs", "render_proposals",
           "MIN_FACTOR", "MIN_ROUNDS", "CRIT_SHARE"]

#: A rank must be the critical rank in MORE than this share of the
#: (multi-rank, per-round) stats rows to be proposed as degraded —
#: strictly more: "critical in 1 of 2 rounds" is a coin flip, and the
#: committed healthy FAULT trace trips exactly that on host jitter.
CRIT_SHARE = 0.5
#: ... and its rounds' max/p50 ratio (round_stats numbers, verbatim)
#: must reach this factor: below it, ordinary scheduling jitter.
MIN_FACTOR = 1.5
#: ... over at least this many usable rounds: two rounds cannot show
#: persistence, and persistence is the whole slow-rank signature.
MIN_ROUNDS = 3


def propose_fault_specs(events: list[dict]) -> list[dict]:
    """Slow-rank proposals for every run in a trace event list.

    Each proposal: ``{"run", "method", "name", "rank", "factor",
    "spec", "crit_rounds", "rounds"}`` where ``spec`` is a canonical
    PR 6 fault string (``slow:rR*F``). Runs without per-round
    multi-rank decomposition (collectives, single-rank rows) yield
    nothing — no data, no guess."""
    from tpu_aggcomm.faults.spec import parse_fault
    from tpu_aggcomm.obs.metrics import round_stats

    proposals = []
    for run in (e for e in events if e.get("ev") == "run"):
        rid = run["id"]
        stats = [s for s in round_stats(events, rid)
                 if s["ranks"] > 1 and s["p50"] > 0]
        if len(stats) < MIN_ROUNDS:
            continue
        crit_count: dict[int, int] = {}
        for s in stats:
            crit_count[s["critical_rank"]] = \
                crit_count.get(s["critical_rank"], 0) + 1
        rank = max(crit_count, key=crit_count.get)
        if crit_count[rank] <= CRIT_SHARE * len(stats):
            continue
        factors = [s["max"] / s["p50"] for s in stats
                   if s["critical_rank"] == rank]
        factor = statistics.median(factors)
        if factor < MIN_FACTOR:
            continue
        # round-trip through the PR 6 parser: a proposal must BE a valid
        # injectable spec, canonical form, or it is not emitted at all
        spec = parse_fault(f"slow:r{int(rank)}*{factor:.2g}").canonical()
        proposals.append({
            "run": rid, "method": run.get("method"),
            "name": run.get("name"), "rank": int(rank),
            "factor": round(factor, 2), "spec": spec,
            "crit_rounds": crit_count[rank], "rounds": len(stats)})
    return proposals


def render_proposals(proposals: list[dict]) -> str:
    """Advisory lines for ``inspect trace`` (empty string when there is
    nothing to say — healthy traces stay byte-identical)."""
    if not proposals:
        return ""
    lines = []
    for p in proposals:
        lines.append(
            f"resilience: run {p['run']} (m={p['method']} "
            f"\"{p['name']}\") — rank {p['rank']} critical in "
            f"{p['crit_rounds']}/{p['rounds']} rounds, median max/p50 "
            f"{p['factor']:.2f}x; proposed fault spec (advisory, "
            f"re-injectable via --fault): {p['spec']}")
    return "\n".join(lines) + "\n"
