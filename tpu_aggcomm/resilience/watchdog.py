"""Deadline watchdog + round-boundary-only cancellation.

Two policies, both SOFT by construction:

- **Deadlines** never kill anything. A mid-kernel SIGTERM has wedged
  the axon tunnel for hours, twice (CLAUDE.md gotchas), so the deadline
  derived here (roofline floor × reps × slack, prior observed walls,
  the tunnel's RPC probe) is checked AFTER a dispatch returns: an
  overrun produces a ``kind="deadline"`` resilience record, a trace
  instant and a stderr warning — evidence for the operator, not a
  signal to the kernel.
- **Cancellation** lands only at round boundaries. Inside
  :func:`safe_cancellation`, SIGINT/SIGTERM set a deferred flag instead
  of interrupting; the dispatch loop calls :func:`check_boundary`
  between cells and the pending cancellation materializes there as
  :class:`CancelledAtBoundary` — after the in-flight program finished,
  never mid-kernel. A second SIGINT (an operator insisting at a
  genuinely hung prompt) restores the default handler, so the escape
  hatch exists but requires explicit insistence. The tunnel-wedge rule
  becomes enforced policy, not a comment.

jax-free; the roofline import inside :func:`schedule_floor_s` is gated
to the jax lowerings it models (harness/roofline.py pulls in
backends/jax_shard), so local/native oracle runs never touch it.
"""

from __future__ import annotations

import signal
import sys
import threading

from tpu_aggcomm.obs import ledger, trace

__all__ = ["CancelledAtBoundary", "safe_cancellation", "check_boundary",
           "cancellation_pending", "derive_deadline", "schedule_floor_s",
           "soft_deadline_check"]


class CancelledAtBoundary(RuntimeError):
    """A deferred SIGINT/SIGTERM honored at a round boundary."""


# Module-level state: one cancellation scope per process (signal
# handlers are process-global anyway).
_STATE = {"active": False, "pending": None, "sigint_count": 0}


class safe_cancellation:
    """Context manager deferring SIGINT/SIGTERM to round boundaries.

    Only installs handlers on the main thread (signal.signal raises
    elsewhere); off the main thread it is a transparent no-op and
    Python's default delivery applies."""

    def __enter__(self):
        self._installed = []
        if threading.current_thread() is not threading.main_thread():
            return self
        _STATE.update(active=True, pending=None, sigint_count=0)
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                old = signal.signal(sig, _defer_signal)
            except (ValueError, OSError):
                continue
            self._installed.append((sig, old))
        return self

    def __exit__(self, *exc):
        for sig, old in self._installed:
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        _STATE.update(active=False, pending=None, sigint_count=0)
        return False


def _defer_signal(signum, frame) -> None:
    name = signal.Signals(signum).name
    if signum == signal.SIGINT:
        _STATE["sigint_count"] += 1
        if _STATE["sigint_count"] >= 2:
            # the operator insists: restore default delivery and raise —
            # the documented escape hatch for a genuinely hung program
            signal.signal(signal.SIGINT, signal.default_int_handler)
            raise KeyboardInterrupt
    _STATE["pending"] = name
    print(f"# {name} received: deferring cancellation to the next round "
          f"boundary — killing a TPU client mid-kernel can wedge the "
          f"tunnel (CLAUDE.md)"
          + ("; press Ctrl-C again to force" if signum == signal.SIGINT
             else ""),
          file=sys.stderr, flush=True)


def cancellation_pending() -> str | None:
    """The deferred signal name, if one arrived inside the scope."""
    return _STATE["pending"] if _STATE["active"] else None


def check_boundary(label: str) -> None:
    """Honor a deferred cancellation HERE (a round/cell boundary: no
    program in flight). No-op — one dict lookup — otherwise."""
    sig = cancellation_pending()
    if sig is None:
        return
    rec = ledger.record_resilience(label, kind="cancel", signal=sig)
    trace.instant("ledger.resilience", **rec)
    _STATE["pending"] = None
    raise CancelledAtBoundary(
        f"cancelled at round boundary {label} (deferred {sig}); "
        f"re-run with --resume to continue from the journal")


# --------------------------------------------------------------------------
# Soft deadlines.

def schedule_floor_s(schedule, backend_name: str) -> float | None:
    """The roofline fenced floor for one rep of ``schedule`` under a
    jax lowering; None for backends the model does not cover (local/
    native oracles, TAM/collective schedules) — roofline imports the
    jax_shard lowering, so the gate keeps oracle runs jax-free."""
    if backend_name not in ("jax_sim", "jax_shard"):
        return None
    try:
        from tpu_aggcomm.harness.roofline import rep_bytes
        return rep_bytes(schedule, lowering=backend_name).floor_seconds(
            fenced=True)
    except Exception:  # lint: broad-ok (floor model advisory; ETA falls back)
        return None


def derive_deadline(*, floor_s: float | None = None, ntimes: int = 1,
                    rpc_probe_s: float | None = None,
                    prior_walls=(), slack: float = 50.0,
                    min_deadline_s: float = 30.0) -> float:
    """A generous soft deadline (seconds) for one dispatch.

    Takes the MAX of three honest estimates — ``slack ×`` the roofline
    floor for the whole dispatch (floor × reps, plus a per-dispatch RPC
    term when the tunnel probe measured one), ``5 ×`` the slowest prior
    wall observed for the same site family (compile excluded once a
    wall exists), and an absolute floor that absorbs first-dispatch
    compilation. Generous by design: this deadline flags, it never
    kills."""
    candidates = [float(min_deadline_s)]
    if floor_s is not None and floor_s > 0:
        candidates.append(slack * floor_s * max(int(ntimes), 1)
                          + 10.0 * (rpc_probe_s or 0.1))
    walls = [w for w in prior_walls if isinstance(w, (int, float)) and w > 0]
    if walls:
        candidates.append(5.0 * max(walls))
    return max(candidates)


def soft_deadline_check(site: str, *, wall_s: float,
                        deadline_s: float | None, out=None) -> bool:
    """After a dispatch RETURNED: record + warn if it overran its soft
    deadline. Returns True on overrun. Never interrupts anything."""
    if deadline_s is None or wall_s <= deadline_s:
        return False
    rec = ledger.record_resilience(
        site, kind="deadline", wall_s=wall_s, deadline_s=deadline_s)
    trace.instant("ledger.resilience", **rec)
    print(f"# watchdog: {site} took {wall_s:.1f}s (soft deadline "
          f"{deadline_s:.1f}s) — tunnel or chip may be degraded; "
          f"advisory only, nothing was interrupted",
          file=out if out is not None else sys.stderr)
    return True
