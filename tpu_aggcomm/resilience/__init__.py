"""Resilient execution supervisor (ISSUE 7).

PR 6 made the framework tolerate *declared* faults (``--fault`` →
static schedule repair). This package handles the runtime failure modes
the project actually hits on the tunnel host — transient axon-tunnel
RPC errors, kills that must never land mid-kernel, OOM-killed capture
jobs — as policy instead of folklore:

- :mod:`tpu_aggcomm.resilience.policy` — error taxonomy
  (transient-tunnel / compile / verify / program), seeded
  exponential-backoff retry with deterministic replay, and the chaos
  injection hook the CI smoke gate drives.
- :mod:`tpu_aggcomm.resilience.journal` — crash-safe JSONL run journal
  keyed by config + manifest fingerprint (``sweep --resume``,
  ``scripts/tpu_capture_all.py --resume``).
- :mod:`tpu_aggcomm.resilience.watchdog` — soft per-dispatch deadlines
  derived from the roofline floor + prior walls, and round-boundary-only
  cancellation (the tunnel-wedge rule as enforced policy).
- :mod:`tpu_aggcomm.resilience.detect` — advisory fault detection:
  measured round walls (``obs.metrics.round_stats``, verbatim) matched
  against the PR 6 fault grammar, emitting a *proposed* ``--fault``
  spec string. Advisory output only — never a silent behavior change.

Everything here is jax-free (obs discipline — the replay, resume and
journal paths run where ``import jax`` may hang on a dead tunnel);
``obs.trace``/``obs.ledger``, which this package records into, are
jax-free too.
"""

from tpu_aggcomm.resilience.policy import (RETRYABLE, RetryPolicy,
                                           classify_error, replay_attempts,
                                           retry_call)
from tpu_aggcomm.resilience.journal import RunJournal
from tpu_aggcomm.resilience.watchdog import (CancelledAtBoundary,
                                             check_boundary,
                                             derive_deadline,
                                             safe_cancellation)
from tpu_aggcomm.resilience.detect import propose_fault_specs

__all__ = ["RETRYABLE", "RetryPolicy", "classify_error", "replay_attempts",
           "retry_call", "RunJournal", "CancelledAtBoundary",
           "check_boundary", "derive_deadline", "safe_cancellation",
           "propose_fault_specs"]
