"""Error taxonomy + seeded retry/backoff — the resilience policy core.

The taxonomy turns the bare ``except Exception`` swallows the harness
used to carry into named verdicts:

- ``transient-tunnel`` — the axon tunnel's RPC layer hiccuped
  (UNAVAILABLE / DEADLINE_EXCEEDED / socket trouble). The ONLY
  retryable class: the tunnel historically recovers (bench.py's probe
  window exists for the same reason), and the programs are idempotent
  (deterministic fills), so a bounded re-dispatch is honest.
- ``compile`` — Mosaic/XLA lowering or compilation rejected the
  program. Deterministic: retrying re-runs the same compiler on the
  same input.
- ``verify`` — ``--verify`` found wrong bytes. NEVER retried: a
  correctness failure must surface (bench.py's RC_CORRECTNESS rule).
- ``program`` — everything else (schedule deadlock, API misuse).

Retry backoff is **seeded**: the jittered exponential schedule comes
from ``random.Random(seed)``, every attempt lands in the trace
(``ledger.resilience`` instants) and the ledger's resilience records,
and :func:`replay_attempts` re-derives the schedule from the recorded
policy fields alone — same seed + same error sequence ⟹ same attempt
timeline, reproducible jax-free from committed artifacts (the tune
``--replay`` discipline applied to retries).

Chaos injection (``TPU_AGGCOMM_CHAOS="site:N,..."``) makes a retry site
fail its first N attempts with a synthetic transient error — exercised
by ``scripts/chaos_smoke.py`` in ci_tier1.sh. Inert (one memoized env
lookup) when the variable is unset.

jax-free (stdlib + obs.trace/obs.ledger, which are jax-free): the
classification and replay paths run where ``import jax`` may hang.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time

from tpu_aggcomm.obs import ledger, trace

__all__ = ["TRANSIENT", "COMPILE", "VERIFY", "PROGRAM", "RETRYABLE",
           "ChaosError", "classify_error", "RetryPolicy", "retry_call",
           "replay_attempts", "maybe_chaos_fail", "retries_exhausted"]

TRANSIENT = "transient-tunnel"
COMPILE = "compile"
VERIFY = "verify"
PROGRAM = "program"

#: Only tunnel transients are retryable: compile and program errors are
#: deterministic, and a verify failure must surface, never be re-rolled.
RETRYABLE = frozenset({TRANSIENT})

# Classification is by exception-type NAME (walking the MRO) plus
# message tokens — never by importing backend/jax exception types here:
# this module must classify errors it could not itself import (jaxlib's
# XlaRuntimeError carries the gRPC status in its message).
_VERIFY_TYPES = frozenset({"VerificationError"})
_PROGRAM_TYPES = frozenset({"DeadlockError", "RepairError",
                            "FaultSpecError"})
_TRANSIENT_TYPES = frozenset({"ConnectionError", "ConnectionResetError",
                              "ConnectionAbortedError",
                              "ConnectionRefusedError", "BrokenPipeError",
                              "TimeoutError", "ChaosError"})
_TRANSIENT_TOKENS = ("unavailable", "deadline_exceeded",
                     "deadline exceeded", "socket closed",
                     "connection reset", "connection refused",
                     "broken pipe", "tunnel", "unreachable",
                     "rpc failed", "injected transient")
_COMPILE_TOKENS = ("mosaic", "lowering", "compilation", "compile",
                   "stablehlo", "mlir", "hlo")


class ChaosError(ConnectionError):
    """The synthetic transient raised by chaos injection — a real
    ConnectionError subclass so it classifies as transient-tunnel by
    type AND by message, exactly like the tunnel errors it mimics."""


def classify_error(exc: BaseException) -> str:
    """One of ``transient-tunnel`` / ``compile`` / ``verify`` /
    ``program``. Type names take precedence over message tokens (a
    VerificationError mentioning "connection" in its diff stays a
    verify error); unknown errors default to ``program`` — the
    NON-retryable default, so an unclassified failure can never loop."""
    names = {c.__name__ for c in type(exc).__mro__}
    if names & _VERIFY_TYPES:
        return VERIFY
    if names & _PROGRAM_TYPES:
        return PROGRAM
    if names & _TRANSIENT_TYPES:
        return TRANSIENT
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(t in msg for t in _TRANSIENT_TOKENS):
        return TRANSIENT
    if any(t in msg for t in _COMPILE_TOKENS):
        return COMPILE
    return PROGRAM


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with seeded exponential backoff + jitter.

    The whole backoff schedule is a pure function of the policy fields
    (``random.Random(seed)``), so two runs with the same policy and the
    same error sequence produce the SAME attempt timeline — the
    invariant :func:`replay_attempts` audits from artifacts."""

    max_attempts: int = 3
    backoff_base_s: float = 0.25
    backoff_mult: float = 2.0
    jitter_frac: float = 0.25
    seed: int = 0

    @classmethod
    def from_env(cls, env=None) -> "RetryPolicy":
        """Policy from ``TPU_AGGCOMM_RETRY_{MAX,BASE,MULT,JITTER,SEED}``
        (defaults above) — how CI/capture sessions shrink or stretch the
        schedule without code changes."""
        e = os.environ if env is None else env
        return cls(
            max_attempts=int(e.get("TPU_AGGCOMM_RETRY_MAX", 3)),
            backoff_base_s=float(e.get("TPU_AGGCOMM_RETRY_BASE", 0.25)),
            backoff_mult=float(e.get("TPU_AGGCOMM_RETRY_MULT", 2.0)),
            jitter_frac=float(e.get("TPU_AGGCOMM_RETRY_JITTER", 0.25)),
            seed=int(e.get("TPU_AGGCOMM_RETRY_SEED", 0)))

    def backoff_schedule(self) -> list[float]:
        """Seconds to sleep after failed attempt k (k = 1-based index
        into this list): ``base * mult**k * (1 + jitter*U[0,1))`` with a
        seeded RNG. Deterministic from the policy fields alone."""
        rng = random.Random(self.seed)
        return [self.backoff_base_s * self.backoff_mult ** k
                * (1.0 + self.jitter_frac * rng.random())
                for k in range(max(self.max_attempts - 1, 0))]

    def as_record(self) -> dict:
        """The policy fields every attempt record carries, so replay
        needs nothing but the artifact."""
        return {"max_attempts": self.max_attempts,
                "backoff_base_s": self.backoff_base_s,
                "backoff_mult": self.backoff_mult,
                "jitter_frac": self.jitter_frac,
                "seed": self.seed}


# --------------------------------------------------------------------------
# Chaos injection (ci_tier1.sh smoke gate).

_CHAOS: dict | None = None


def _chaos_budget() -> dict:
    global _CHAOS
    if _CHAOS is None:
        _CHAOS = {}
        spec = os.environ.get("TPU_AGGCOMM_CHAOS", "")
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            # rpartition: the count is after the LAST colon, so chaos
            # keys may themselves contain colons ("serve:admit:3" arms
            # the serve:admit site family with budget 3)
            name, _, n = part.rpartition(":")
            try:
                _CHAOS[name.strip()] = int(n)
            except ValueError:
                raise ValueError(
                    f"malformed TPU_AGGCOMM_CHAOS entry {part!r} "
                    f"(want 'site:N')")
    return _CHAOS


def _reset_chaos() -> None:
    """Forget the memoized chaos budget (tests only)."""
    global _CHAOS
    _CHAOS = None


def maybe_chaos_fail(site: str) -> None:
    """Raise a synthetic transient while the site's injected-failure
    budget lasts. A chaos key matches a site exactly or as a ``:``
    prefix ("dispatch" matches "dispatch:m1:i0")."""
    budget = _chaos_budget()
    if not budget:
        return
    for prefix, left in budget.items():
        if left > 0 and (site == prefix or site.startswith(prefix + ":")):
            budget[prefix] = left - 1
            raise ChaosError(
                f"UNAVAILABLE: injected transient fault at {site} "
                f"(chaos {prefix!r}, {left - 1} left)")


# --------------------------------------------------------------------------
# The retry loop.

#: Attribute stamped on a TRANSIENT error that retry_call re-raised only
#: because the policy's attempt budget ran out — the signal the serve
#: layer's health state machine keys DEGRADED on (a deterministic
#: program/verify error is the REQUEST's fault; an exhausted transient
#: is the TUNNEL's).
_EXHAUSTED_ATTR = "_tpu_aggcomm_retries_exhausted"


def retries_exhausted(exc: BaseException) -> bool:
    """Did :func:`retry_call` raise ``exc`` because a TRANSIENT error
    outlived the whole attempt budget (as opposed to a non-retryable
    class that raised on attempt 1)?"""
    return bool(getattr(exc, _EXHAUSTED_ATTR, False))


def retry_call(fn, *, site: str, policy: RetryPolicy | None = None,
               classify=classify_error, sleep=time.sleep):
    """Run ``fn()`` under the classified retry policy.

    EVERY attempt — including a first-try success — lands as a
    ``kind="attempt"`` resilience record in the ledger AND a
    ``ledger.resilience`` trace instant, carrying the policy fields and
    (for retries) the exact backoff slept, so the timeline replays
    deterministically from artifacts. Non-retryable errors (and the
    final exhausted attempt) re-raise unchanged."""
    pol = policy if policy is not None else RetryPolicy.from_env()
    backoffs = pol.backoff_schedule()
    for attempt in range(1, max(pol.max_attempts, 1) + 1):
        try:
            maybe_chaos_fail(site)
            result = fn()
        except Exception as e:  # lint: broad-ok (THE classification site: classify() decides)
            cls = classify(e)
            retryable = cls in RETRYABLE and attempt < pol.max_attempts
            backoff = backoffs[attempt - 1] if retryable else None
            rec = ledger.record_resilience(
                site, kind="attempt", attempt=attempt,
                outcome="retry" if retryable else "raise",
                error_class=cls,
                error=f"{type(e).__name__}: {e}"[:500],
                backoff_s=backoff, **pol.as_record())
            trace.instant("ledger.resilience", **rec)
            if not retryable:
                if cls in RETRYABLE:
                    # transient, but the budget is spent: mark it so
                    # callers (serve health state machine) can tell an
                    # exhausted tunnel from a deterministic failure.
                    try:
                        setattr(e, _EXHAUSTED_ATTR, True)
                    except Exception:  # lint: broad-ok (exceptions with __slots__ refuse attributes; the marker is advisory)
                        pass
                raise
            sleep(backoff)
            continue
        rec = ledger.record_resilience(
            site, kind="attempt", attempt=attempt, outcome="ok",
            **pol.as_record())
        trace.instant("ledger.resilience", **rec)
        return result
    raise AssertionError("unreachable: final attempt raises or returns")


# --------------------------------------------------------------------------
# Deterministic replay from artifacts (tune --replay discipline).

def replay_attempts(records: list[dict]) -> tuple[str, list[str]]:
    """Audit recorded attempt timelines: ``("REPRODUCED", [])`` when
    every site's recorded backoffs match the schedule re-derived from
    its recorded policy fields and the attempt sequence is well-formed
    (contiguous attempts, retries strictly before the terminal
    ok/raise); ``("MISMATCH", problems)`` otherwise.

    ``records`` are ``kind="attempt"`` resilience records, from a bench
    artifact's ``resilience`` list or a trace's ``ledger.resilience``
    instants — jax-free either way."""
    problems: list[str] = []
    by_site: dict[str, list[dict]] = {}
    for r in records:
        if r.get("kind") != "attempt":
            continue
        by_site.setdefault(str(r.get("site")), []).append(r)
    for site, recs in by_site.items():
        recs = sorted(recs, key=lambda r: int(r.get("attempt", 0)))
        want_attempts = list(range(1, len(recs) + 1))
        got_attempts = [int(r.get("attempt", 0)) for r in recs]
        if got_attempts != want_attempts:
            problems.append(f"{site}: attempt sequence {got_attempts} "
                            f"is not contiguous from 1")
            continue
        pol = RetryPolicy(
            max_attempts=int(recs[0].get("max_attempts", 0)),
            backoff_base_s=float(recs[0].get("backoff_base_s", 0.0)),
            backoff_mult=float(recs[0].get("backoff_mult", 0.0)),
            jitter_frac=float(recs[0].get("jitter_frac", 0.0)),
            seed=int(recs[0].get("seed", 0)))
        schedule = pol.backoff_schedule()
        for r in recs[:-1]:
            if r.get("outcome") != "retry":
                problems.append(
                    f"{site}: attempt {r.get('attempt')} has outcome "
                    f"{r.get('outcome')!r} but is not the last attempt")
        if recs[-1].get("outcome") not in ("ok", "raise"):
            problems.append(f"{site}: terminal attempt has outcome "
                            f"{recs[-1].get('outcome')!r}")
        for r in recs:
            if r.get("outcome") != "retry":
                continue
            k = int(r["attempt"]) - 1
            if k >= len(schedule):
                problems.append(f"{site}: attempt {r['attempt']} retried "
                                f"beyond the policy's schedule")
                continue
            want = schedule[k]
            got = r.get("backoff_s")
            if not isinstance(got, (int, float)) \
                    or abs(float(got) - want) > 1e-12:
                problems.append(
                    f"{site}: attempt {r['attempt']} recorded backoff "
                    f"{got!r}, seeded schedule says {want!r}")
    return ("REPRODUCED" if not problems else "MISMATCH", problems)
