"""Composable schedule primitives — the synthesizer's building blocks.

A :class:`Composition` is a small, canonically-stringable choice of
primitives that :func:`build_schedule` compiles to per-rank op programs
in the existing Schedule IR (core/schedule.py) — the same IR every
backend lowers and every analysis consumes, which is the whole design:
a synthesized schedule is checkable (analysis/check.py), auditable
(obs/traffic.py), priceable (model/predict.py), and runnable with zero
new backend code.

Primitive axes (HiCCL-style decomposition, arxiv 2408.05962):

- ``order`` — which sources feed an aggregator in which throttle round:
  ``strided`` (m=1's ``s % R`` classes), ``blocked`` (contiguous
  windows ``s // w``), ``rotated`` (m=13's rank-relative windows
  ``((s - d) % n) // w`` — every sender's load spreads across rounds),
  ``tree`` (k-ary fan-in: sources feed in reverse-BFS order of a k-ary
  tree rooted at the aggregator, ``fanin`` per round).
- ``sync`` — ``eager`` (ISEND), ``rendezvous`` (ISSEND, the reference
  default), or ``crossed`` (rendezvous sends WAITED before that
  round's recvs are posted — deliberately deadlock-prone; the model
  checker refutes the cyclic instances by name, which is exactly what
  the search's hard pruning is for).
- ``selfedge`` — the aggregator's message to itself as a ``wire``
  send/recv pair (m=1) or a local ``copy`` (m=3's memcpy).
- ``wait`` — ``round`` (per-round waitalls over that round's tokens)
  or ``tail`` (recvs waited per round, send tokens deferred to one
  final SEND_WAIT waitall, m=1's shape).
- ``relay`` — stage the last ``relay`` ring-predecessor sources of
  each aggregator through an intermediate rank (the fault-repair
  detour IR verbatim: staging rows, nonzero channels, ``dead_edges``
  bookkeeping — faults/repair.py), exercising multi-hop composition
  on a healthy pattern.
- ``window`` — how round count is derived from the ``-c`` throttle:
  ``chunk`` (at most ``min(c,n)`` sources per aggregator per round —
  the m=1 unit every reference method chunks by), ``posted`` (rounds
  sized to the documented peak-in-flight budget itself,
  ``min(c,n)+cb``: the smallest round count whose per-rank posted
  requests — recvs plus sends, both waited at the round fence — stay
  within the bound every reference method is audited against), or
  ``drain`` (ONE data round: every send posted nonblocking up front,
  the incast drained by BLOCKING recvs in the chunk-map order — the
  m=6/10/12 blocking discipline generalized to its fixed point:
  blocking recvs post no requests, so the audit sees only the sends,
  ``<= cb <= min(c,n)+cb``, and the whole aggregation needs a single
  round fence). The references chunk or block per cb-class; ``posted``
  and ``drain`` are the axes they never compose, and both need
  strictly fewer round fences at small ``c`` while the auditor still
  proves CONFORMS.

Throttle honesty: ``window=chunk`` assigns at most ``min(comm_size,
nprocs)`` sources per aggregator per round (``fanin`` may tighten
that) — the m=1 unit the ``-c`` bound documents. ``window=posted``
instead solves for the smallest round count whose statically-computed
peak posted requests respect the same documented bound the auditor
enforces (obs/traffic.py:documented_bound, ``min(c,n)+cb`` for
synthesized ids) — never beyond it, and the traffic audit re-verifies
the built schedule rather than trusting the solver.

Slot conventions are the registry's (core/methods.py module
docstring): ALL_TO_MANY send slot = aggregator index / recv slot =
source rank; MANY_TO_ALL send slot = dest rank / recv slot =
aggregator index — so harness/verify.py accepts synthesized schedules
unchanged.

jax-free: this module imports core only (numpy-backed), never jax.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.core.schedule import Op, OpKind, Schedule, TimerBucket

__all__ = ["Composition", "CompositionError", "parse_composition",
           "build_schedule", "ORDERS", "SYNCS", "SELFEDGES", "WAITS",
           "WINDOWS"]

ORDERS = ("strided", "blocked", "rotated", "tree")
SYNCS = ("eager", "rendezvous", "crossed")
SELFEDGES = ("wire", "copy")
WAITS = ("round", "tail")
WINDOWS = ("chunk", "posted", "drain")

_DEFAULTS = {"order": "rotated", "sync": "rendezvous", "self": "wire",
             "wait": "round", "fanin": 0, "relay": 0, "window": "chunk"}


class CompositionError(ValueError):
    """Malformed or unbuildable composition (named field + reason)."""


@dataclass(frozen=True)
class Composition:
    """One point in the synthesis space. ``canonical()`` is THE identity
    used everywhere downstream (MethodSpec.composition, Schedule.variant,
    artifact rows) — sorted ``key=value`` fields joined by ``|``, so two
    equal compositions can never alias under different spellings."""

    order: str = "rotated"
    sync: str = "rendezvous"
    selfedge: str = "wire"
    wait: str = "round"
    fanin: int = 0
    relay: int = 0
    window: str = "chunk"

    def __post_init__(self):
        if self.order not in ORDERS:
            raise CompositionError(
                f"order={self.order!r} not in {ORDERS}")
        if self.sync not in SYNCS:
            raise CompositionError(f"sync={self.sync!r} not in {SYNCS}")
        if self.selfedge not in SELFEDGES:
            raise CompositionError(
                f"self={self.selfedge!r} not in {SELFEDGES}")
        if self.wait not in WAITS:
            raise CompositionError(f"wait={self.wait!r} not in {WAITS}")
        if self.window not in WINDOWS:
            raise CompositionError(
                f"window={self.window!r} not in {WINDOWS}")
        if self.order == "tree":
            if self.fanin < 2:
                raise CompositionError(
                    f"order=tree needs fanin >= 2, got {self.fanin}")
        elif self.fanin != 0:
            raise CompositionError(
                f"fanin={self.fanin} only composes with order=tree")
        if self.sync == "crossed" and self.wait != "round":
            raise CompositionError(
                "sync=crossed implies per-round send waits; compose it "
                "with wait=round")
        if self.relay < 0:
            raise CompositionError(f"relay={self.relay} must be >= 0")
        if self.window == "posted":
            if self.wait != "round":
                raise CompositionError(
                    "window=posted budgets a round's posted recvs AND "
                    "sends against the in-flight bound, so both must "
                    "drain at the round fence; compose it with "
                    "wait=round")
            if self.order == "tree":
                raise CompositionError(
                    "window=posted resizes flat round maps; order=tree "
                    "rounds derive from fan-in depth, not the chunk "
                    "width")
            if self.relay != 0:
                raise CompositionError(
                    "window=posted budgets the main rounds only; relay "
                    "staging posts extra requests outside that budget "
                    "(compose relay with window=chunk)")
        if self.window == "drain":
            if self.wait != "round":
                raise CompositionError(
                    "window=drain has a single data round whose send "
                    "tokens drain at that round's fence; compose it "
                    "with wait=round")
            if self.order == "tree":
                raise CompositionError(
                    "window=drain collapses the round map; order=tree "
                    "rounds derive from fan-in depth and cannot "
                    "collapse")
            if self.relay != 0:
                raise CompositionError(
                    "window=drain has no later round for a staged hop "
                    "to land in (compose relay with window=chunk)")

    def canonical(self) -> str:
        return (f"fanin={self.fanin}|order={self.order}"
                f"|relay={self.relay}|self={self.selfedge}"
                f"|sync={self.sync}|wait={self.wait}"
                f"|window={self.window}")

    def __str__(self) -> str:  # pragma: no cover - convenience
        return self.canonical()


def parse_composition(text: str) -> Composition:
    """Parse ``key=value|key=value`` (any order, missing keys default).
    The inverse of :meth:`Composition.canonical`; raises
    :class:`CompositionError` naming the offending token."""
    fields = dict(_DEFAULTS)
    for token in str(text).split("|"):
        token = token.strip()
        if not token:
            continue
        if "=" not in token:
            raise CompositionError(
                f"composition token {token!r} is not key=value")
        key, _, val = token.partition("=")
        key, val = key.strip(), val.strip()
        if key not in fields:
            raise CompositionError(
                f"unknown composition key {key!r} "
                f"(have {sorted(fields)})")
        if key in ("fanin", "relay"):
            try:
                fields[key] = int(val)
            except ValueError:
                raise CompositionError(
                    f"composition {key}={val!r} is not an integer") \
                    from None
        else:
            fields[key] = val
    return Composition(order=fields["order"], sync=fields["sync"],
                       selfedge=fields["self"], wait=fields["wait"],
                       fanin=fields["fanin"], relay=fields["relay"],
                       window=fields["window"])


# --------------------------------------------------------------------------
# round assignment

def _tree_rounds(n: int, k: int, width: int) -> list[int]:
    """Round of each rank-relative position under k-ary fan-in order:
    positions feed leaves-first (reverse BFS of the k-ary heap rooted at
    position 0, ties by position), ``min(k, width)`` per round."""
    def depth(p: int) -> int:
        d = 0
        while p > 0:
            p = (p - 1) // k
            d += 1
        return d

    per_round = min(k, width)
    order = sorted(range(n), key=lambda p: (-depth(p), p))
    rounds = [0] * n
    for idx, p in enumerate(order):
        rounds[p] = idx // per_round
    return rounds


class _RoundMap:
    """Edge -> throttle round for one composition at one pattern shape.

    ``far`` is the fan-aggregation rank (the incast/outcast hub: the
    aggregator), ``leaf`` the rank on the wide side (source for a2m,
    destination for m2a) — the same formulas serve both directions."""

    def __init__(self, comp: Composition, nprocs: int, width: int,
                 n_rounds: int | None = None):
        self.order = comp.order
        self.n = nprocs
        self.width = width
        if comp.order == "tree":
            self._tree = _tree_rounds(nprocs, comp.fanin, width)
            self.n_rounds = max(self._tree) + 1
        elif n_rounds is not None:
            # window=posted: round count solved against the in-flight
            # budget; the flat formulas keep working off the rebalanced
            # width = ceil(n / rounds).
            self.n_rounds = int(n_rounds)
            self.width = (nprocs + self.n_rounds - 1) // self.n_rounds
        else:
            self.n_rounds = (nprocs + width - 1) // width

    def round_of(self, leaf: int, far: int) -> int:
        if self.order == "strided":
            return leaf % self.n_rounds
        if self.order == "blocked":
            return leaf // self.width
        if self.order == "rotated":
            return ((leaf - far) % self.n) // self.width
        return self._tree[(leaf - far) % self.n]


def _wire_jobs(rank: int, rmap: _RoundMap, comp: Composition,
               p: AggregatorPattern, relayed: set):
    """The chan-0 jobs of one rank under one round map, as
    ``(sends, recvs, copies)`` dicts ``rnd -> [(peer, slot)]`` /
    ``rnd -> [(sslot, rslot)]`` — THE single source of round structure
    for both :func:`build_schedule` and the ``window=posted`` budget
    solver (the solver must count exactly the requests the builder will
    post, or the solved round count proves nothing)."""
    agg_index = p.agg_index
    a2m = p.direction is Direction.ALL_TO_MANY
    myidx = int(agg_index[rank])
    isagg = myidx >= 0
    sends: dict[int, list[tuple[int, int]]] = {}   # rnd -> [(dst, slot)]
    recvs: dict[int, list[tuple[int, int]]] = {}   # rnd -> [(src, slot)]
    copies: dict[int, list[tuple[int, int]]] = {}  # rnd -> [(ss, rs)]
    if a2m:
        for j, d in enumerate(int(r) for r in p.rank_list):
            if (rank, d) in relayed:
                continue
            rnd = rmap.round_of(rank, d)
            if d == rank and comp.selfedge == "copy":
                # send slab j -> own recv row `rank` (source = self)
                copies.setdefault(rnd, []).append((j, rank))
            else:
                sends.setdefault(rnd, []).append((d, j))
        if isagg:
            for s in range(p.nprocs):
                if (s, rank) in relayed:
                    continue
                if s == rank and comp.selfedge == "copy":
                    continue  # delivered by the COPY above
                recvs.setdefault(rmap.round_of(s, rank),
                                 []).append((s, s))
    else:
        if isagg:
            for d in range(p.nprocs):
                rnd = rmap.round_of(d, rank)
                if d == rank and comp.selfedge == "copy":
                    # send slab `rank` -> own recv row myidx
                    copies.setdefault(rnd, []).append((rank, myidx))
                else:
                    sends.setdefault(rnd, []).append((d, d))
        for j, a in enumerate(int(r) for r in p.rank_list):
            if a == rank and comp.selfedge == "copy":
                continue
            recvs.setdefault(rmap.round_of(rank, a), []).append((a, j))
    return sends, recvs, copies


def _posted_rounds(comp: Composition, p: AggregatorPattern,
                   r_chunk: int) -> int:
    """The ``window=posted`` round count: the smallest R whose
    per-(rank, round) posted requests — that round's wire recvs plus
    wire sends, all outstanding together until the round-fence waitall
    (``posted`` implies ``wait=round``) — stay within the documented
    synthesized-id bound ``min(c,n)+cb``
    (obs/traffic.py:documented_bound). Counts come from the SAME job
    maps the builder emits; COPY self-edges post nothing. Falls back to
    the chunked count when no smaller R conforms (the audit then sees a
    schedule identical in shape to ``window=chunk``)."""
    n = p.nprocs
    budget = min(p.comm_size, n) + p.cb_nodes
    width = min(p.comm_size, n)
    for rounds in range(1, r_chunk):
        rmap = _RoundMap(comp, n, width, n_rounds=rounds)
        peak = 0
        for rank in range(n):
            sends, recvs, _ = _wire_jobs(rank, rmap, comp, p, set())
            for rnd in range(rounds):
                load = len(recvs.get(rnd, ())) + len(sends.get(rnd, ()))
                peak = max(peak, load)
        if peak <= budget:
            return rounds
    return r_chunk


# --------------------------------------------------------------------------
# relay staging (the repair detour IR on a healthy pattern)

def _relay_assignments(comp: Composition, p: AggregatorPattern):
    """Deterministic (src, dst, send_slot, via, chan, stage) tuples for
    the ``relay`` primitive: the ``relay`` ring-predecessor sources of
    each aggregator detour through the next live non-endpoint rank."""
    n = p.nprocs
    if comp.relay == 0:
        return []
    if p.direction is not Direction.ALL_TO_MANY:
        raise CompositionError(
            "relay staging composes with the all-to-many direction only "
            "(the m2a mirror has no incast to stage)")
    if comp.relay > n - 2:
        raise CompositionError(
            f"relay={comp.relay} needs at least relay+2 ranks, "
            f"have nprocs={n}")
    out = []
    stage = 0
    for j_idx, d in enumerate(int(r) for r in p.rank_list):
        for t in range(comp.relay):
            s = (d - 1 - t) % n
            via = next((s + off) % n for off in range(1, n)
                       if (s + off) % n not in (s, d))
            out.append((s, d, j_idx, via, 1 + stage, stage))
            stage += 1
    return out


# --------------------------------------------------------------------------
# schedule builder

def _wait_bucket(isagg: bool, has_recv: bool, has_send: bool):
    if has_recv and has_send:
        return (TimerBucket.RECV_WAIT if isagg
                else TimerBucket.RECV_AND_SEND_WAIT)
    return TimerBucket.RECV_WAIT if has_recv else TimerBucket.SEND_WAIT


class _Prog:
    """Per-rank program builder (the registry's token bookkeeping,
    extended with chan/staging fields for the relay hops)."""

    def __init__(self):
        self.ops: list[Op] = []
        self._next_token = 0

    def nb(self, kind: OpKind, peer: int, slot: int, rnd: int, nbytes: int,
           bucket: TimerBucket = TimerBucket.NONE, *, chan: int = 0,
           from_stage: bool = False, to_stage: bool = False) -> int:
        tok = self._next_token
        self._next_token += 1
        self.ops.append(Op(kind=kind, peer=peer, slot=slot, round=rnd,
                           token=tok, nbytes=nbytes, bucket=bucket,
                           chan=chan, from_stage=from_stage,
                           to_stage=to_stage))
        return tok

    def blocking(self, kind: OpKind, peer: int, slot: int, rnd: int,
                 nbytes: int, bucket: TimerBucket = TimerBucket.NONE):
        self.ops.append(Op(kind=kind, peer=peer, slot=slot, round=rnd,
                           nbytes=nbytes, bucket=bucket))

    def copy(self, sslot: int, rslot: int, rnd: int):
        self.ops.append(Op(kind=OpKind.COPY, slot=sslot, slot2=rslot,
                           round=rnd))

    def waitall(self, tokens, bucket: TimerBucket, rnd: int = 0):
        if tokens:
            self.ops.append(Op(kind=OpKind.WAITALL, tokens=tuple(tokens),
                               bucket=bucket, round=rnd))


def build_schedule(comp: Composition, p: AggregatorPattern, *,
                   method_id: int = 100, name: str | None = None) -> Schedule:
    """Compile one composition against one pattern.

    The canonical composition string is stamped into
    ``Schedule.variant`` (prefix ``synth:``) so ``schedule_shape_key``
    — and with it every compiled/tuned/served cache and every resume
    journal — distinguishes compositions even before registration
    assigns distinct method ids."""
    n, cb, ds = p.nprocs, p.cb_nodes, p.data_size
    agg_index = p.agg_index
    width = min(p.comm_size, n)
    rmap = _RoundMap(comp, n, width)
    if comp.window == "posted" and comp.order != "tree":
        rmap = _RoundMap(comp, n, width,
                         n_rounds=_posted_rounds(comp, p, rmap.n_rounds))
    R = rmap.n_rounds
    relays = _relay_assignments(comp, p)
    relayed = {(s, d) for s, d, _, _, _, _ in relays}
    send_kind = OpKind.ISEND if comp.sync == "eager" else OpKind.ISSEND

    progs = []
    for rank in range(n):
        b = _Prog()
        myidx = int(agg_index[rank])
        isagg = myidx >= 0

        # chan-0 jobs by round -------------------------------------------
        sends, recvs, copies = _wire_jobs(rank, rmap, comp, p, relayed)

        if comp.window == "drain":
            # ONE data round: sends posted nonblocking up front (every
            # rank's, so no drain can wait on a message that was never
            # posted), then the incast drained by BLOCKING recvs in the
            # chunk-map order. Blocking recvs post no requests — the
            # in-flight audit sees only the sends (<= cb), the m=6/10/12
            # conformance argument.
            toks_s = [b.nb(send_kind, d, sl, 0, ds, TimerBucket.POST)
                      for rnd in range(R) for d, sl in sends.get(rnd, ())]
            if comp.sync == "crossed":
                # send waits BEFORE the drain — the rendezvous instances
                # cycle and the checker refutes them by name
                b.waitall(toks_s, TimerBucket.SEND_WAIT, 0)
            for rnd in range(R):
                for ss, rs in copies.get(rnd, ()):
                    b.copy(ss, rs, 0)
            for rnd in range(R):
                for s, sl in recvs.get(rnd, ()):
                    b.blocking(OpKind.RECV, s, sl, 0, ds,
                               TimerBucket.RECV_WAIT)
            if comp.sync != "crossed":
                b.waitall(toks_s, TimerBucket.SEND_WAIT, 0)
            progs.append(b.ops)
            continue

        # main rounds -----------------------------------------------------
        pending_sends: list[int] = []
        for rnd in range(R):
            r_jobs = recvs.get(rnd, ())
            s_jobs = sends.get(rnd, ())
            if comp.sync == "crossed":
                # sends waited BEFORE this round's recvs are posted — the
                # deliberately cyclic shape the checker exists to refute
                toks_s = [b.nb(send_kind, d, sl, rnd, ds, TimerBucket.POST)
                          for d, sl in s_jobs]
                b.waitall(toks_s, TimerBucket.SEND_WAIT, rnd)
                for ss, rs in copies.get(rnd, ()):
                    b.copy(ss, rs, rnd)
                toks_r = [b.nb(OpKind.IRECV, s, sl, rnd, ds,
                               TimerBucket.POST) for s, sl in r_jobs]
                b.waitall(toks_r, TimerBucket.RECV_WAIT, rnd)
                continue
            toks_r = [b.nb(OpKind.IRECV, s, sl, rnd, ds, TimerBucket.POST)
                      for s, sl in r_jobs]
            for ss, rs in copies.get(rnd, ()):
                b.copy(ss, rs, rnd)
            toks_s = [b.nb(send_kind, d, sl, rnd, ds, TimerBucket.POST)
                      for d, sl in s_jobs]
            if comp.wait == "round":
                b.waitall(toks_r + toks_s,
                          _wait_bucket(isagg, bool(toks_r), bool(toks_s)),
                          rnd)
            else:
                b.waitall(toks_r, TimerBucket.RECV_WAIT, rnd)
                pending_sends.extend(toks_s)
        b.waitall(pending_sends, TimerBucket.SEND_WAIT, max(R - 1, 0))

        # relay staging rounds (repair detour IR, faults/repair.py) -------
        for stage_rnd in (R, R + 1):
            toks_r, toks_s = [], []
            for s, d, j, via, chan, stage in relays:
                if stage_rnd == R and rank == s:
                    toks_s.append(b.nb(OpKind.ISEND, via, j, R, ds,
                                       TimerBucket.POST, chan=chan))
                if stage_rnd == R and rank == via:
                    toks_r.append(b.nb(OpKind.IRECV, s, stage, R, ds,
                                       TimerBucket.POST, chan=chan,
                                       to_stage=True))
                if stage_rnd == R + 1 and rank == via:
                    toks_s.append(b.nb(OpKind.ISEND, d, stage, R + 1, ds,
                                       TimerBucket.POST, chan=chan,
                                       from_stage=True))
                if stage_rnd == R + 1 and rank == d:
                    toks_r.append(b.nb(OpKind.IRECV, via, s, R + 1, ds,
                                       TimerBucket.POST, chan=chan))
            b.waitall(toks_r, TimerBucket.RECV_WAIT, stage_rnd)
            b.waitall(toks_s, TimerBucket.SEND_WAIT, stage_rnd)
        progs.append(b.ops)

    canon = comp.canonical()
    sched = Schedule(
        p, method_id, name or f"Synth {canon}", progs,
        uses_rendezvous=comp.sync in ("rendezvous", "crossed"),
        variant=f"synth:{canon}",
        n_staging=len(relays),
        dead_edges=tuple(sorted((s, d) for s, d in relayed)))
    sched.validate()
    return sched
