"""SYNTH_r*.json — the committed synthesis artifact (schema synth-v1).

One artifact is one complete, replayable synthesis run at one n=32-class
grid cell: the seeded search trace (every composition evaluated, every
prune named), the registration block (method id -> canonical
composition — what :func:`tpu_aggcomm.synth.register.ensure_registered`
re-installs in a later process), the measured race of the registered
finalists against every dispatched reference method of the same
direction (the tuner's race record verbatim, seeded and
sample-complete), and the winner with its PROVEN/CONFORMS verdicts.

Determinism contract (the tune/PREDICT discipline): same config + seed
+ embedded model parameters ⟹ the same search block byte-for-byte, and
the recorded race samples ⟹ the same eliminations and winner
byte-for-byte (`tune.race.replay_record`). :func:`replay_artifact`
re-derives BOTH jax-free — that is the ci_tier1.sh gate. Writes go
through ``obs.atomic_write`` (one-shot artifact writer rule).
"""

from __future__ import annotations

import glob
import json
import os
import time

from tpu_aggcomm.synth.register import (SYNTH_ID_BASE,
                                        register_composition,
                                        registered_synth_ids)
from tpu_aggcomm.synth.search import SearchError, search

__all__ = ["SYNTH_SCHEMA", "next_artifact_path", "reference_methods",
           "run_synth", "save_artifact", "load_artifact",
           "replay_artifact"]

SYNTH_SCHEMA = "synth-v1"


def next_artifact_path(root: str = ".") -> str:
    """First unused ``SYNTH_rNN.json`` under ``root`` (NN = 01, 02, …)."""
    taken = set(os.path.basename(p)
                for p in glob.glob(os.path.join(root, "SYNTH_r*.json")))
    n = 1
    while f"SYNTH_r{n:02d}.json" in taken:
        n += 1
    return os.path.join(root, f"SYNTH_r{n:02d}.json")


def reference_methods(direction: str = "a2m") -> list[int]:
    """Every dispatched, non-TAM reference method of one direction — the
    field the synthesized finalists must beat."""
    from tpu_aggcomm.core.methods import METHODS, method_ids
    from tpu_aggcomm.synth.search import _direction

    d = _direction(direction)
    return [m for m in method_ids(include_dead=False)
            if m < SYNTH_ID_BASE and not METHODS[m].tam
            and METHODS[m].direction is d]


def run_synth(*, nprocs: int, cb_nodes: int, comm_size: int,
              data_size: int = 2048, proc_node: int = 1, agg_type: int = 1,
              direction: str = "a2m", seed: int = 0,
              params: dict | None = None, params_source: str | None = None,
              init: int = 32, mutate_rounds: int = 3, beam: int = 4,
              top_k: int = 3, fanins=(2, 4), relays=(0, 2),
              id_base: int | None = None, sampler=None,
              backend: str = "jax_sim", synthetic: str | None = None,
              max_batches: int = 6, batch_trials: int = 3,
              alpha: float = 0.05, log=None) -> dict:
    """The whole pipeline: search -> register finalists -> race them
    against the reference field at the same cell -> artifact dict.

    ``sampler`` follows the tuner's contract (``sampler(cid, batch) ->
    [seconds]``); the CLI passes tune/measure.py's jax_sim sampler for
    measured runs or ``tune.race.make_synthetic_sampler`` for the
    jax-free smoke path (recorded in ``synthetic``). The race order is
    reference ids first, finalists last — ties break toward the
    reference, so a synthesized winner never wins on order."""
    from tpu_aggcomm.obs.ledger import manifest
    from tpu_aggcomm.tune import race as race_mod
    from tpu_aggcomm.tune.space import Candidate

    say = log or (lambda *_: None)
    sr = search(nprocs=nprocs, cb_nodes=cb_nodes, comm_size=comm_size,
                data_size=data_size, proc_node=proc_node,
                agg_type=agg_type, direction=direction, seed=seed,
                params=params, params_source=params_source, init=init,
                mutate_rounds=mutate_rounds, beam=beam, top_k=top_k,
                fanins=fanins, relays=relays)
    say(f"synth: searched {sr['evaluated']}/{sr['space_size']} "
        f"compositions (pruned: {sr['pruned']}), "
        f"{len(sr['finalists'])} finalist(s)")
    if not sr["finalists"]:
        raise SearchError(
            "search left no finalists: every composition was pruned "
            "(see the rows' pruned_by fields)")

    base = id_base if id_base is not None else \
        max([SYNTH_ID_BASE] + registered_synth_ids()) + 1
    registration: dict[str, dict] = {}
    for i, canon in enumerate(sr["finalists"]):
        spec = register_composition(canon, method_id=base + i,
                                    direction=direction)
        registration[str(spec.method_id)] = {
            "composition": canon, "direction": direction,
            "name": spec.name}

    refs = reference_methods(direction)
    cell = dict(cb_nodes=cb_nodes, comm_size=comm_size, agg_type=agg_type)
    cids = [Candidate(method=m, **cell).cid
            for m in refs + sorted(int(k) for k in registration)]
    say(f"synth: racing {len(cids)} candidate(s) "
        f"({len(refs)} reference + {len(registration)} synthesized), "
        f"seed {seed}")
    res = race_mod.race(cids, sampler, max_batches=max_batches,
                        alpha=alpha, seed=seed)
    race_rec = {"seed": int(seed), "alpha": float(alpha), "n_boot": 2000,
                "max_batches": int(max_batches),
                "batch_trials": int(batch_trials), "order": cids,
                "samples": res.samples, "eliminations": res.eliminations,
                "winner": res.winner, "batches_run": res.batches_run,
                "survivors": res.survivors}

    win_mid = int(res.winner.split(":", 1)[0][1:])
    meds = res.medians()
    winner = {"cid": res.winner, "method_id": win_mid,
              "median_s": meds[res.winner],
              "synthesized": win_mid > SYNTH_ID_BASE}
    if winner["synthesized"]:
        entry = registration[str(win_mid)]
        row = next(r for r in sr["rows"]
                   if r["composition"] == entry["composition"])
        winner.update(composition=entry["composition"],
                      check_verdict="PROVEN", traffic_verdict="CONFORMS",
                      predicted_rank=row["rank"], price_s=row["price_s"])
    return {"schema": SYNTH_SCHEMA, "created_unix": time.time(),
            "seed": int(seed), "backend": backend,
            "synthetic": synthetic, "config": sr["config"],
            "inputs": {"params": params, "params_source": params_source},
            "search": sr, "registration": registration,
            "race": race_rec, "winner": winner, "manifest": manifest()}


def save_artifact(path: str, artifact: dict) -> str:
    from tpu_aggcomm.obs import atomic_write
    with atomic_write(path) as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_artifact(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def replay_artifact(path: str) -> tuple[bool, list[str]]:
    """Re-derive a committed artifact jax-free: the search block from
    (config, seed, embedded params) and the race verdict from the
    recorded samples. Returns ``(reproduced, diffs)`` — any diff names
    the block that failed, the tune/PREDICT replay discipline."""
    from tpu_aggcomm.tune import race as race_mod

    blob = load_artifact(path)
    diffs: list[str] = []
    sr_rec = blob.get("search") or {}
    cfg = dict(sr_rec.get("config") or {})
    try:
        sr_new = search(
            nprocs=cfg["nprocs"], cb_nodes=cfg["cb_nodes"],
            comm_size=cfg["comm_size"], data_size=cfg["data_size"],
            proc_node=cfg["proc_node"], agg_type=cfg["agg_type"],
            direction=cfg["direction"], seed=blob.get("seed", 0),
            params=(blob.get("inputs") or {}).get("params"),
            params_source=(blob.get("inputs") or {}).get("params_source"),
            init=sr_rec.get("init", 32),
            mutate_rounds=sr_rec.get("mutate_rounds", 3),
            beam=sr_rec.get("beam", 4), top_k=sr_rec.get("top_k", 3),
            fanins=sr_rec.get("fanins", (2, 4)),
            relays=sr_rec.get("relays", (0, 2)))
    except (KeyError, SearchError) as e:
        return False, [f"search replay failed: {e}"]
    if json.loads(json.dumps(sr_new)) != sr_rec:
        for key in sr_new:
            if json.loads(json.dumps(sr_new[key])) != sr_rec.get(key):
                diffs.append(f"search.{key} does not re-derive")

    # registration must be exactly the finalists, ids in finalist order
    reg = blob.get("registration") or {}
    mids = sorted(int(k) for k in reg)
    expect = sr_rec.get("finalists") or []
    got = [reg[str(m)]["composition"] for m in mids]
    if got != expect:
        diffs.append(f"registration compositions {got} != search "
                     f"finalists {expect}")

    try:
        res = race_mod.replay_record(blob.get("race") or {})
        rec = blob["race"]
        if res.winner != rec.get("winner"):
            diffs.append(f"race winner re-derives to {res.winner}, "
                         f"recorded {rec.get('winner')}")
        if json.loads(json.dumps(res.eliminations)) \
                != rec.get("eliminations"):
            diffs.append("race eliminations do not re-derive")
    except (KeyError, race_mod.RaceError) as e:
        diffs.append(f"race replay failed: {e}")

    win = blob.get("winner") or {}
    if win.get("synthesized"):
        mid = str(win.get("method_id"))
        if reg.get(mid, {}).get("composition") != win.get("composition"):
            diffs.append(f"winner composition is not registration[{mid}]")
    return not diffs, diffs
