"""Registration: synthesized winners become first-class METHODS entries.

Ids >= :data:`SYNTH_ID_BASE` are the reserved synthesized range; each
registered id carries its canonical composition string in
``MethodSpec.composition`` and compiles through the ordinary
``compile_method`` path, so every downstream consumer —
``schedule_shape_key``, the compiled/tuned/served caches, resume
journals, ``inspect traffic``/``inspect check`` sweeps, fuse export,
and the serve layer — works on a synthesized method with zero special
cases.

Registration is OPT-IN and side-effect-explicit: importing this module
registers nothing, and the CLI only scans committed ``SYNTH_r*.json``
artifacts when ``--synth-root`` is passed (or when a requested method
id falls in the synthesized range), so every existing command's output
stays byte-identical without the flag. Re-registering the same
(id, composition, direction) is an idempotent no-op; a conflicting
re-registration is a named error — an id that silently changed meaning
would alias every cache keyed by it.

jax-free: core.methods / core.pattern are numpy-only, so registration
(and artifact replay through it) runs where a wedged tunnel hangs
``import jax``.
"""

from __future__ import annotations

import glob
import json
import os

from tpu_aggcomm.core.methods import METHODS, MethodSpec
from tpu_aggcomm.core.pattern import Direction
from tpu_aggcomm.synth.primitives import build_schedule, parse_composition

__all__ = ["SYNTH_ID_BASE", "RegisterError", "register_composition",
           "registered_synth_ids", "ensure_registered"]

#: First id of the reserved synthesized method range. 100 itself is the
#: search-phase placeholder (synth/search.py UNREGISTERED_ID); winners
#: get 101, 102, … from the committed artifact's registration block.
SYNTH_ID_BASE = 100


class RegisterError(ValueError):
    """Refused registration (reserved-range violation or a conflicting
    id reuse), with both sides named."""


def register_composition(composition, *, method_id: int,
                         direction: str = "a2m",
                         name: str | None = None) -> MethodSpec:
    """Install one composition as ``METHODS[method_id]`` and return the
    spec. ``composition`` may be a canonical string or a Composition."""
    comp = composition if hasattr(composition, "canonical") \
        else parse_composition(composition)
    canon = comp.canonical()
    mid = int(method_id)
    if mid <= SYNTH_ID_BASE:
        raise RegisterError(
            f"method id {mid} is outside the synthesized range "
            f"(ids must be > SYNTH_ID_BASE={SYNTH_ID_BASE}; the base "
            f"itself is the unregistered search placeholder)")
    short = {"a2m": Direction.ALL_TO_MANY, "m2a": Direction.MANY_TO_ALL}
    try:
        direc = short.get(str(direction)) or Direction(direction)
    except ValueError:
        raise RegisterError(f"unknown direction {direction!r}") from None
    existing = METHODS.get(mid)
    if existing is not None:
        if (existing.composition == canon
                and existing.direction is direc):
            return existing  # idempotent re-registration
        raise RegisterError(
            f"method id {mid} is already registered as "
            f"{existing.composition or existing.name!r} "
            f"({existing.direction.value}); refusing to rebind it to "
            f"{canon!r} ({direc.value}) — a reused id would alias every "
            f"shape-keyed cache")

    def _generator(p, _comp=comp, _mid=mid, _name=name):
        return build_schedule(_comp, p, method_id=_mid, name=_name)

    spec = MethodSpec(mid, name or f"Synthesized {canon}", direc,
                      _generator, composition=canon)
    METHODS[mid] = spec
    return spec


def registered_synth_ids() -> list[int]:
    """Currently-registered synthesized method ids, sorted."""
    return sorted(m for m, s in METHODS.items()
                  if m > SYNTH_ID_BASE and s.composition is not None)


def ensure_registered(root: str = ".", *, quiet: bool = True) -> dict:
    """Register every method recorded in the ``registration`` blocks of
    the committed ``SYNTH_r*.json`` artifacts under ``root`` (sorted
    path order, so later artifacts see earlier ids already bound).
    Returns ``{method_id: composition}`` for everything registered or
    already present. Unreadable artifacts are skipped with a named
    stderr note (never silently) — a broken artifact must not take the
    registry down with it."""
    import sys

    out: dict[int, str] = {}
    for path in sorted(glob.glob(os.path.join(root, "SYNTH_r*.json"))):
        try:
            with open(path, encoding="utf-8") as f:
                blob = json.load(f)
            reg = blob.get("registration") or {}
            for mid_text, entry in sorted(reg.items(),
                                          key=lambda kv: int(kv[0])):
                spec = register_composition(
                    entry["composition"], method_id=int(mid_text),
                    direction=entry.get("direction", "a2m"),
                    name=entry.get("name"))
                out[spec.method_id] = spec.composition
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"synth: skipping unreadable artifact {path}: {e}",
                  file=sys.stderr)
            if not quiet:
                raise
    return out
