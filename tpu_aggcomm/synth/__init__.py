"""Schedule synthesizer: generate, prove, price, and race new
aggregator schedules (ROADMAP item 2 — the HiCCL-style composition
of primitives, arxiv 2408.05962).

The package composes every prior subsystem and is jax-free end to end
(``synth`` is in analysis/lint.py PURE_PACKAGES; tests/test_synth.py
pins it with a poisoned-jax subprocess): primitives emit per-rank op
programs in the existing Schedule IR, the search prunes with
``analysis/check.py`` verdicts and ``obs/traffic.py`` bounds, prices
with ``model/predict.py``, and the measured arbitration rides the
tuner's seeded racing (``tune/race.py`` — the only jax on the path,
and only at artifact-build time; ``synth --replay`` re-derives the
whole search + race jax-free).
"""

from tpu_aggcomm.synth.artifact import (SYNTH_SCHEMA, load_artifact,
                                        next_artifact_path,
                                        reference_methods, replay_artifact,
                                        run_synth, save_artifact)
from tpu_aggcomm.synth.primitives import (Composition, CompositionError,
                                          build_schedule,
                                          parse_composition)
from tpu_aggcomm.synth.register import (SYNTH_ID_BASE, RegisterError,
                                        ensure_registered,
                                        register_composition,
                                        registered_synth_ids)
from tpu_aggcomm.synth.search import SearchError, enumerate_space, search

__all__ = ["Composition", "CompositionError", "parse_composition",
           "build_schedule", "SearchError", "enumerate_space", "search",
           "SYNTH_ID_BASE", "RegisterError", "register_composition",
           "registered_synth_ids", "ensure_registered", "SYNTH_SCHEMA",
           "run_synth", "save_artifact", "load_artifact",
           "replay_artifact", "next_artifact_path", "reference_methods"]
