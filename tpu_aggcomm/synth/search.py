"""Seeded enumeration + local search over schedule compositions.

The search is jax-free and fully deterministic given its seed: the
enumeration order, the seeded sample of the initial population, the
beam-mutation neighborhoods, and every verdict (checker, auditor,
dominance, pricing) are pure functions of (config, seed, params) — the
same discipline as the tuner's racing and the regression gate, which is
what lets ``synth --replay`` re-derive the whole search trace
byte-for-byte from the committed artifact on a machine where jax may
not even import.

Pruning pipeline per composition (ISSUE 15 / ROADMAP item 2):

1. **build** — :class:`~tpu_aggcomm.synth.primitives.CompositionError`
   refusals (e.g. relay on the m2a mirror) are recorded INVALID.
2. **check** — ``analysis/check.py`` verdicts are hard pruning: a
   named refutation (the waits-for cycle, the racing slot) kills the
   branch and the property name lands in ``pruned_by``.
3. **traffic** — ``obs/traffic.py``'s in-flight audit against the
   documented ``-c`` bound; an over-posting composition is REFUTED
   statically, with peak/bound recorded.
4. **dominance** — a survivor strictly worse on every static axis
   (rounds, bytes, bottleneck, peak, staging) than some other survivor
   is pruned as dominated; ties survive (the race arbitrates).
5. **price** — ``model/predict.py``'s calibrated floor ranks the
   survivors (the multi-fidelity prior); without parameters the
   structural key ranks instead and the artifact says so.

Predictions never gate alone (the model invariant): pricing only
ORDERS the finalists — the measured race in synth/artifact.py decides.
"""

from __future__ import annotations

import random
from dataclasses import replace as _replace

from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.synth.primitives import (ORDERS, SELFEDGES, SYNCS, WAITS,
                                          WINDOWS, Composition,
                                          CompositionError, build_schedule,
                                          parse_composition)

__all__ = ["SearchError", "UNREGISTERED_ID", "enumerate_space",
           "evaluate_composition", "search"]

#: Placeholder method id for search-phase schedules (the base of the
#: reserved synthesized range; registration assigns BASE+1, BASE+2, …).
#: Never registered itself, and ``Schedule.variant`` carries the
#: canonical composition, so two candidates sharing this id can never
#: alias a shape-keyed cache entry.
UNREGISTERED_ID = 100


class SearchError(ValueError):
    """Unusable search input (empty space, malformed config)."""


_DIRECTIONS = {"a2m": Direction.ALL_TO_MANY, "m2a": Direction.MANY_TO_ALL,
               Direction.ALL_TO_MANY.value: Direction.ALL_TO_MANY,
               Direction.MANY_TO_ALL.value: Direction.MANY_TO_ALL}


def _direction(text: str) -> Direction:
    try:
        return _DIRECTIONS[str(text)]
    except KeyError:
        raise SearchError(f"unknown direction {text!r} "
                          f"(want one of {sorted(_DIRECTIONS)})") from None


def make_pattern(cfg: dict) -> AggregatorPattern:
    """The one pattern constructor every synth phase shares (search,
    pricing, registration smoke, artifact replay) — mirrors
    tune/measure.py so the search evaluates the very schedule the race
    would measure."""
    return AggregatorPattern(
        nprocs=int(cfg["nprocs"]), cb_nodes=int(cfg["cb_nodes"]),
        data_size=max(int(cfg.get("data_size", 2048)), 1),
        proc_node=int(cfg.get("proc_node", 1)),
        comm_size=int(cfg["comm_size"]),
        placement=int(cfg.get("agg_type", 1)),
        direction=_direction(cfg.get("direction", "a2m")))


def enumerate_space(*, fanins=(2, 4), relays=(0, 2)) -> list[Composition]:
    """The full valid composition grid, sorted by canonical string —
    the deterministic universe the seeded sample draws from."""
    out = []
    for order in ORDERS:
        for fanin in (tuple(fanins) if order == "tree" else (0,)):
            for sync in SYNCS:
                for wait in (("round",) if sync == "crossed" else WAITS):
                    for selfedge in SELFEDGES:
                        for relay in relays:
                            windows = (WINDOWS if (order != "tree"
                                                   and wait == "round"
                                                   and relay == 0)
                                       else ("chunk",))
                            for window in windows:
                                out.append(Composition(
                                    order=order, sync=sync,
                                    selfedge=selfedge, wait=wait,
                                    fanin=fanin, relay=relay,
                                    window=window))
    return sorted(set(out), key=lambda c: c.canonical())


def evaluate_composition(comp: Composition, pattern: AggregatorPattern,
                         params: dict | None = None) -> dict:
    """One composition through build → check → traffic → features →
    price. Returns the artifact row; ``pruned_by`` is None iff the
    composition survives the hard filters (dominance is cross-row and
    applied later)."""
    from tpu_aggcomm.analysis.check import check_schedule
    from tpu_aggcomm.model.features import schedule_features
    from tpu_aggcomm.model.predict import floor_from_features
    from tpu_aggcomm.obs.traffic import audit_schedule

    row = {"composition": comp.canonical(), "verdict": "PROVEN",
           "pruned_by": None, "rounds": None, "bytes": None,
           "bottleneck": None, "peak": None, "bound": None,
           "staging": 0, "price_s": None, "rank": None}
    try:
        sched = build_schedule(comp, pattern, method_id=UNREGISTERED_ID)
    except CompositionError as e:
        row["verdict"] = "INVALID"
        row["pruned_by"] = f"build:{e}"
        return row

    rep = check_schedule(sched)
    if rep["verdict"] != "PROVEN":
        bad = [k for k, v in rep["properties"].items()
               if v.get("verdict") == "REFUTED"]
        prop = bad[0] if bad else "unknown"
        row["verdict"] = "REFUTED"
        row["pruned_by"] = f"check:{prop}"
        row["check_detail"] = rep["properties"].get(prop, {}).get("detail")
        return row

    audit = audit_schedule(sched)
    conf = audit["conformance"]
    row["peak"], row["bound"] = conf["peak"], conf["bound"]
    if conf["verdict"] != "CONFORMS":
        row["verdict"] = "REFUTED"
        row["pruned_by"] = (f"traffic:peak {conf['peak']} > bound "
                            f"{conf['bound']} ({conf['bound_formula']})")
        return row

    feats = schedule_features(sched)
    row["rounds"] = feats["rounds"]
    row["bytes"] = feats["bytes"]
    row["bottleneck"] = feats["bottleneck"]
    row["staging"] = sched.n_staging
    if params:
        row["price_s"] = floor_from_features(feats, params)
    return row


def _static_key(row: dict) -> tuple:
    return (row["rounds"], row["bytes"], row["bottleneck"], row["peak"],
            row["staging"])


def _dominates(a: dict, b: dict) -> bool:
    ka, kb = _static_key(a), _static_key(b)
    return all(x <= y for x, y in zip(ka, kb)) and ka != kb


def _rank_key(row: dict) -> tuple:
    if row["price_s"] is not None:
        return (0, row["price_s"], row["composition"])
    return (1, row["rounds"], row["bytes"], row["bottleneck"],
            row["composition"])


def _neighbors(comp: Composition, fanins, relays) -> list[Composition]:
    """All single-field mutations of one composition, canonical-sorted;
    invalid combinations are silently not neighbors."""
    out = []
    axes = {
        "order": [(o, f) for o in ORDERS
                  for f in (tuple(fanins) if o == "tree" else (0,))],
        "sync": list(SYNCS), "selfedge": list(SELFEDGES),
        "wait": list(WAITS), "relay": list(relays),
        "window": list(WINDOWS)}
    for field, values in axes.items():
        for v in values:
            try:
                if field == "order":
                    cand = _replace(comp, order=v[0], fanin=v[1])
                else:
                    cand = _replace(comp, **{field: v})
            except CompositionError:
                continue
            if cand != comp:
                out.append(cand)
    return sorted(set(out), key=lambda c: c.canonical())


def search(*, nprocs: int, cb_nodes: int, comm_size: int,
           data_size: int = 2048, proc_node: int = 1, agg_type: int = 1,
           direction: str = "a2m", seed: int = 0,
           params: dict | None = None, params_source: str | None = None,
           init: int = 32, mutate_rounds: int = 3, beam: int = 4,
           top_k: int = 3, fanins=(2, 4), relays=(0, 2)) -> dict:
    """Run the seeded search at one pattern shape → the ``search`` block
    of the synth-v1 artifact (rows in evaluation order, prune counters,
    ranked survivors, ``top_k`` finalists)."""
    cfg = {"nprocs": int(nprocs), "cb_nodes": int(cb_nodes),
           "comm_size": int(comm_size), "data_size": int(data_size),
           "proc_node": int(proc_node), "agg_type": int(agg_type),
           "direction": direction}
    pattern = make_pattern(cfg)
    space = enumerate_space(fanins=fanins, relays=relays)
    if not space:
        raise SearchError("empty composition space")

    rng = random.Random(int(seed))
    if init >= len(space):
        frontier = list(space)
    else:
        frontier = rng.sample(space, int(init))

    rows: list[dict] = []
    seen: set[str] = set()

    def consider(comps) -> None:
        for comp in comps:
            canon = comp.canonical()
            if canon in seen:
                continue
            seen.add(canon)
            rows.append(evaluate_composition(comp, pattern, params))

    consider(frontier)
    for _ in range(int(mutate_rounds)):
        alive = sorted((r for r in rows if r["pruned_by"] is None),
                       key=_rank_key)
        if not alive:
            break
        nxt: list[Composition] = []
        for r in alive[:int(beam)]:
            nxt.extend(_neighbors(parse_composition(r["composition"]),
                                  fanins, relays))
        consider(nxt)

    # cross-row dominance over everything that survived the hard filters
    alive = [r for r in rows if r["pruned_by"] is None]
    for r in alive:
        for other in alive:
            if other is not r and _dominates(other, r):
                r["pruned_by"] = f"dominated:{other['composition']}"
                break
    survivors = sorted((r for r in alive if r["pruned_by"] is None),
                       key=_rank_key)
    for i, r in enumerate(survivors):
        r["rank"] = i + 1

    def _count(prefix: str) -> int:
        return sum(1 for r in rows
                   if (r["pruned_by"] or "").startswith(prefix))

    pruned = {"invalid": _count("build:"), "check": _count("check:"),
              "traffic": _count("traffic:"),
              "dominated": _count("dominated:")}

    return {"seed": int(seed), "config": cfg,
            "space_size": len(space), "evaluated": len(rows),
            "init": int(init), "mutate_rounds": int(mutate_rounds),
            "beam": int(beam), "top_k": int(top_k),
            "fanins": list(fanins), "relays": list(relays),
            "priced": bool(params), "params_source": params_source,
            "pruned": pruned, "rows": rows,
            "survivors": [r["composition"] for r in survivors],
            "finalists": [r["composition"]
                          for r in survivors[:int(top_k)]]}
