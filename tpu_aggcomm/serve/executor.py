"""The serve data plane: compile chained reps, batch same-shape requests.

THE one jax-importing module in ``tpu_aggcomm/serve`` (declared in
``analysis/lint.PURE_PACKAGES`` exactly like ``tune/measure.py``): the
control plane (protocol/cache/server) must keep running where a wedged
tunnel hangs ``import jax``, so everything device-shaped funnels
through here, lazily.

Batching: same-shape requests are stacked onto a NEW LEADING request
axis of the jax_sim program — ``jax.vmap`` over :meth:`one_rep`, so
every throttle round keeps its ``lax.optimization_barrier`` fence (or
its scan-carry step) per batch element exactly as in the sequential
program; vmap adds an axis, it never re-schedules rounds — fusing
rounds away would invalidate the ``-c`` semantics the whole benchmark
studies, and the batched-vs-sequential byte-exactness pin in
tests/test_serve.py holds the line. Batches are padded to the next
power of two (replicating the tail request's payload) so the jit cache
holds at most ``log2(max_batch)+1`` batched programs instead of one
per observed batch size; padded lanes are sliced off before any result
leaves this module.

``pallas_fused`` chains are cached for compile amortization but always
execute per-request: the fused kernel's in-kernel DMA semaphores are
the round fence, and a vmap over remote-DMA pallas_calls is not a
lowering this repo has validated — refusing to batch is the
jax_shard/staged-schedule discipline, not a silent fallback.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["CompiledChain", "build_chain", "execute_batch",
           "prewarm_chain", "recv_bytes", "batched_recv_bytes"]

#: Backends the server may compile chains for. jax_shard needs the
#: multichip driver harness (__graft_entry__) and refuses staged
#: schedules; it joins here the day the driver grows a serve entry.
CHAIN_BACKENDS = ("jax_sim", "pallas_fused")


class CompiledChain:
    """One cached compiled rep family for a (schedule, backend)."""

    def __init__(self, schedule, backend, backend_name: str, single,
                 batched):
        self.schedule = schedule
        self.backend = backend
        self.backend_name = backend_name
        self.single = single          # jitted rep(send) -> recv
        self.batched = batched        # jitted vmap(rep), or None
        self.shape_key = backend._key(schedule)


def _pad_to(n: int) -> int:
    """Smallest power of two >= n (bounds the batched jit cache)."""
    p = 1
    while p < n:
        p *= 2
    return p


def _ensure_barrier_batching_rule() -> None:
    """Teach ``jax.vmap`` about ``lax.optimization_barrier``.

    jax (0.4.x) ships no batching rule for the barrier primitive, so a
    vmap over the fenced rep refuses outright. The rule is semantically
    forced: the barrier is the identity on values — batching binds the
    SAME primitive on the batched operands and passes the batch dims
    through untouched. Crucially this keeps every round fence in the
    batched program (one barrier per round over the whole request
    slab): vmap adds an axis, the rounds stay distinct program steps —
    the ``-c`` semantics survive batching by construction, pinned by
    the batched-vs-sequential byte-exactness tests."""
    from jax._src.lax import lax as _lax_internal
    from jax.interpreters import batching

    prim = getattr(_lax_internal, "optimization_barrier_p", None)
    if prim is None or prim in batching.primitive_batchers:
        return

    def _barrier_batcher(args, dims):
        return prim.bind(*args), dims

    batching.primitive_batchers[prim] = _barrier_batcher


def build_chain(schedule, backend_name: str) -> tuple[CompiledChain, float]:
    """Compile the chain for ``schedule`` on ``backend_name``.

    Returns ``(chain, compile_seconds)`` where the seconds are an
    honest host wall around jit + first dispatch (the ledger
    "compile+warmup" discipline — never ``.lower().compile()``, which
    would not share the jit cache through the tunnel)."""
    import jax

    if backend_name not in CHAIN_BACKENDS:
        raise ValueError(f"serve: unknown chain backend "
                         f"{backend_name!r}; valid: {CHAIN_BACKENDS}")
    t0 = time.perf_counter()
    if backend_name == "pallas_fused":
        from tpu_aggcomm.backends.pallas_fused import PallasFusedBackend
        backend = PallasFusedBackend()
        rep = backend.one_rep(schedule)   # named refusal if unfusable
        single = jax.jit(rep)
        batched = None
    else:
        from tpu_aggcomm.backends.jax_sim import JaxSimBackend
        backend = JaxSimBackend()
        rep = backend.one_rep(schedule)
        single = jax.jit(rep)
        _ensure_barrier_batching_rule()
        batched = jax.jit(jax.vmap(rep))
    # warm the single-rep program now: the cold request pays compile
    # exactly once, every warm hit is dispatch-only
    p = schedule.pattern
    send0 = jax.device_put(backend._global_send(p, 0), backend._dev())
    single(send0).block_until_ready()
    chain = CompiledChain(schedule, backend, backend_name, single, batched)
    return chain, time.perf_counter() - t0


def prewarm_chain(shape: dict, backend_name: str):
    """Rebuild one journal-recorded request shape into a compiled chain
    — the ``--recover`` pre-warm path. ``shape`` is the dict of
    ``ServeRequest.shape_fields`` the admission journal record carries;
    returns ``(chain, compile_seconds, shape_key)`` keyed by the same
    ``schedule_shape_key`` a live request would compute, so the warmed
    entry is a cache HIT for the replayed traffic, never an alias."""
    from tpu_aggcomm.core.schedule import schedule_shape_key
    from tpu_aggcomm.serve.protocol import parse_request, request_schedule

    req = parse_request(dict(shape))
    schedule = request_schedule(req)
    chain, compile_s = build_chain(schedule, backend_name)
    return chain, compile_s, schedule_shape_key(schedule)


def execute_batch(chain: CompiledChain, requests) -> list[dict]:
    """Run one same-shape batch; one result dict per request, in order.

    Each result: ``{"verified": bool | None, "error": str | None}`` —
    recv buffers are verified in-process against the deterministic-fill
    oracle (harness/verify.py) and never shipped over the wire (a
    batched 16 MB slab is a benchmark payload, not a response body).
    ``verified`` is None when the request did not ask for --verify.
    """
    import jax

    schedule = chain.schedule
    backend = chain.backend
    p = schedule.pattern
    dev = backend._dev()
    n_req = len(requests)
    sends = np.stack([backend._global_send(p, r.iter_)
                      for r in requests])
    if chain.batched is not None and n_req > 1:
        padded = _pad_to(n_req)
        if padded > n_req:
            pad = np.broadcast_to(sends[-1], (padded - n_req,)
                                  + sends.shape[1:])
            sends = np.concatenate([sends, pad], axis=0)
        out = chain.batched(jax.device_put(sends, dev))
        out.block_until_ready()
        recv_all = np.asarray(jax.device_get(out))[:n_req]
    else:
        outs = []
        for i in range(n_req):
            o = chain.single(jax.device_put(sends[i], dev))
            o.block_until_ready()
            outs.append(np.asarray(jax.device_get(o)))
        recv_all = np.stack(outs)

    _, n_recv_slots = backend._slots(p)
    results = []
    for i, req in enumerate(requests):
        res = {"verified": None, "error": None}
        if req.verify:
            from tpu_aggcomm.harness.verify import (VerificationError,
                                                    verify_recv)
            recv_np = backend._to_bytes(p, recv_all[i][:, :n_recv_slots, :])
            recv_bufs = backend._split_recv(p, recv_np)
            try:
                verify_recv(p, recv_bufs, req.iter_)
                res["verified"] = True
            except VerificationError as e:
                res["verified"] = False
                res["error"] = f"verify failed: {e}"
        results.append(res)
    return results


def recv_bytes(chain: CompiledChain, iter_: int) -> list:
    """One sequential rep's recv buffers in byte layout (test hook: the
    batched-vs-sequential byte-exactness pin compares these against the
    batched path slice-for-slice)."""
    import jax

    backend = chain.backend
    p = chain.schedule.pattern
    send = jax.device_put(backend._global_send(p, iter_), backend._dev())
    out = chain.single(send)
    out.block_until_ready()
    _, n_recv_slots = backend._slots(p)
    recv = np.asarray(jax.device_get(out))[:, :n_recv_slots, :]
    return backend._split_recv(p, backend._to_bytes(p, recv))


def batched_recv_bytes(chain: CompiledChain, iters) -> list[list]:
    """The batched path's recv buffers, one byte-layout list per
    request (same test hook; must equal :func:`recv_bytes` per iter)."""
    import jax

    if chain.batched is None:
        raise ValueError(f"serve: backend {chain.backend_name!r} does "
                         f"not batch (pallas_fused executes per-request)")
    backend = chain.backend
    p = chain.schedule.pattern
    n_req = len(iters)
    sends = np.stack([backend._global_send(p, it) for it in iters])
    padded = _pad_to(n_req)
    if padded > n_req:
        pad = np.broadcast_to(sends[-1], (padded - n_req,)
                              + sends.shape[1:])
        sends = np.concatenate([sends, pad], axis=0)
    out = chain.batched(jax.device_put(sends, backend._dev()))
    out.block_until_ready()
    recv_all = np.asarray(jax.device_get(out))[:n_req]
    _, n_recv_slots = backend._slots(p)
    return [backend._split_recv(
                p, backend._to_bytes(p, recv_all[i][:, :n_recv_slots, :]))
            for i in range(n_req)]
