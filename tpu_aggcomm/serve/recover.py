"""Crash recovery for the serve layer: journal replay + cache pre-warm.

A server journal (resilience/journal.py) is write-only while the server
lives; this module is the read side, run at ``--recover`` startup —
**jax-free**, because the one time you need recovery is precisely when
the previous process died ugly and the tunnel may hang ``import jax``.

Two halves:

- :func:`replay_journal` re-derives the request ledger from the
  torn-line-tolerant entries alone — which requests were admitted,
  which reached a terminal status (``done``/``fail``/``shed``), which
  were **lost in flight** (admitted, never finished: the crash ate
  them) — and cross-checks every ``drain`` record's counts against the
  entries preceding it: ``REPRODUCED`` when the journal agrees with
  itself, ``MISMATCH`` with named problems otherwise (the
  ``replay_attempts`` discipline applied to the request lifecycle).
- :func:`prewarm_plan` turns the admitted records' shape dicts into a
  compile worklist for the compiled-chain cache, through the SAME lens
  every cache in this repo uses (``schedule_shape_key`` + backend +
  manifest fingerprint): entries whose session fingerprint differs from
  the recovering process's are SKIPPED with the drifted manifest keys
  named via ``diff_manifests`` — a drifted environment must recompile
  on first request, never serve a stale warm.

The pre-warm compiles themselves happen in serve/executor.py (the jax
door); this module only decides WHAT to warm and WHY something was
skipped.
"""

from __future__ import annotations

import json

from tpu_aggcomm.obs.ledger import diff_manifests
from tpu_aggcomm.obs.workload import attribute_phases
from tpu_aggcomm.resilience.journal import RunJournal

__all__ = ["replay_journal", "prewarm_plan", "render_recovery"]


def replay_journal(path: str) -> dict:
    """Re-derive the request ledger from a server journal.

    Returns ``{"verdict": "REPRODUCED"|"MISMATCH", "problems": [...],
    "completed": [rids], "failed": [rids], "shed": [rids],
    "lost": [rids], "states": [...], "drains": [...],
    "sessions": {fp: manifest}, "admitted": {rid: record},
    "n_entries": int}``. Torn lines were already skipped by the journal
    reader (crash safety is the reader's job); ``lost`` names requests
    the crash ate — admitted with no terminal record."""
    j = RunJournal(path)
    sessions = j.sessions()
    entries = j.entries()
    admitted: dict = {}
    terminal: dict = {}
    states: list[dict] = []
    drains: list[dict] = []
    problems: list[str] = []
    counts = {"done": 0, "fail": 0, "shed": 0}
    for rec in entries:
        key = rec.get("key") or {}
        status = rec.get("status")
        if "request" in key:
            rid = key["request"]
            if status == "admitted":
                if rid in admitted:
                    problems.append(f"request {rid}: duplicate admission "
                                    f"record")
                admitted[rid] = rec
            elif status in counts:
                if rid in terminal:
                    problems.append(
                        f"request {rid}: duplicate terminal record "
                        f"({terminal[rid].get('status')} then {status})")
                    continue
                if status in ("done", "fail") and rid not in admitted:
                    problems.append(f"request {rid}: {status} without an "
                                    f"admission record")
                # phase stamps (when present) must be monotone in the
                # canonical admit -> ... -> respond order: a reordered
                # or hand-mangled journal line is named here, never
                # silently accepted (obs/workload.py is the one
                # attribution arithmetic)
                if "phases" in rec:
                    _, pproblems = attribute_phases(rec.get("phases"))
                    for p in pproblems:
                        problems.append(f"request {rid}: {p}")
                terminal[rid] = rec
                counts[status] += 1
        elif "state" in key and status == "state":
            states.append(rec)
        elif "drain" in key and status == "drain":
            drains.append(rec)
            # a drain record is a CLAIM about the entries before it —
            # re-derive each count and name any disagreement
            for fld, have in (("completed", counts["done"]),
                              ("failed", counts["fail"]),
                              ("shed", counts["shed"])):
                want = rec.get(fld)
                if want is not None and want != have:
                    problems.append(
                        f"drain record claims {fld}={want}, the journal "
                        f"entries before it re-derive {have}")
            want_lost = rec.get("lost")
            have_lost = sorted(r for r in admitted if r not in terminal)
            if want_lost is not None and sorted(want_lost) != have_lost:
                problems.append(
                    f"drain record claims lost={sorted(want_lost)}, the "
                    f"journal entries re-derive {have_lost}")

    def _with(status):
        return sorted(r for r in terminal
                      if terminal[r].get("status") == status)

    return {"verdict": "REPRODUCED" if not problems else "MISMATCH",
            "problems": problems,
            "completed": _with("done"), "failed": _with("fail"),
            "shed": _with("shed"),
            "lost": sorted(r for r in admitted if r not in terminal),
            "states": states, "drains": drains, "sessions": sessions,
            "admitted": admitted, "n_entries": len(entries)}


def prewarm_plan(report: dict, *, fingerprint: str,
                 manifest: dict | None) -> tuple[list[dict], list[str]]:
    """(worklist, skips) for the compiled-chain cache pre-warm.

    Each worklist item is ``{"shape": <shape-fields dict>, "backend":
    str, "requests": [rids]}``, one per distinct (shape, backend) among
    the journal's admitted records. An item whose recording session's
    fingerprint differs from ``fingerprint`` lands in ``skips`` instead,
    with the drifted manifest keys named (tune-cache / RunJournal
    semantics: drift = named skip, never a stale warm)."""
    groups: dict = {}
    for rid in sorted(report.get("admitted", {})):
        rec = report["admitted"][rid]
        shape = rec.get("shape")
        backend = rec.get("backend")
        if not isinstance(shape, dict) or not isinstance(backend, str):
            continue   # pre-v2 journals carry no shape dict: nothing to warm
        key = (json.dumps(shape, sort_keys=True), backend)
        g = groups.setdefault(key, {"shape": shape, "backend": backend,
                                    "fingerprint": rec.get("fingerprint"),
                                    "requests": []})
        g["requests"].append(rid)
    warm: list[dict] = []
    skips: list[str] = []
    for (shape_json, backend), g in sorted(groups.items()):
        if g["fingerprint"] != fingerprint:
            drift = diff_manifests(
                report.get("sessions", {}).get(g["fingerprint"]), manifest)
            keys = ", ".join(d["key"] for d in drift[:4]) or \
                f"fingerprint {g['fingerprint']} != {fingerprint}"
            more = f" (+{len(drift) - 4} more)" if len(drift) > 4 else ""
            skips.append(f"{backend} shape {shape_json}: manifest drift "
                         f"vs journal session ({keys}{more}) — not "
                         f"pre-warming, first request recompiles")
        else:
            warm.append({"shape": g["shape"], "backend": backend,
                         "requests": g["requests"]})
    return warm, skips


def render_recovery(report: dict) -> list[str]:
    """Human lines for the recovery report (stderr; the ready JSON line
    carries the machine form)."""
    lines = [f"journal replay {report['verdict']}: "
             f"{len(report['completed'])} completed, "
             f"{len(report['failed'])} failed, "
             f"{len(report['shed'])} shed, "
             f"{len(report['lost'])} lost in flight "
             f"({report['n_entries']} entries)"]
    if report["completed"]:
        lines.append(f"completed requests: {report['completed']}")
    if report["lost"]:
        lines.append(f"LOST in flight (admitted, never finished — the "
                     f"crash ate them): {report['lost']}")
    for d in report["drains"]:
        lines.append(f"clean drain recorded: reason="
                     f"{d.get('reason')!r}, completed={d.get('completed')}")
    for p in report["problems"]:
        lines.append(f"MISMATCH: {p}")
    return lines
