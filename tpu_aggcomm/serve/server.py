"""Aggregation-as-a-service: the persistent schedule server.

A long-lived process that admits pattern requests over a loopback
socket (serve/protocol.py), compiles each distinct schedule ONCE into a
cached chained rep (serve/cache.py + serve/executor.py) and batches
same-shape requests onto a new leading request axis — build-once/
execute-many, the persistent-communication optimization the one-shot
CLI cannot express (each invocation repays schedule build + jit +
tunnel warmup before its first rep).

Division of labor, enforced by the purity contract
(analysis/lint.PURE_PACKAGES + the poisoned-jax pin in
tests/test_serve.py): THIS module is control plane — sockets, queueing,
admission control, batch formation, cache policy, journal, metrics,
retry, lifecycle — and never imports jax; ``serve/executor.py`` is the
one jax door. An operator must be able to query ``stats``/``health`` on
(and cleanly stop) a server whose tunnel has wedged so badly that
``import jax`` hangs in fresh processes.

Overload protection (the fair-weather server hardened):

- **Admission control** — the request queue is bounded (``--max-queue``)
  and the admission decision happens at enqueue time: over capacity the
  client gets a framed ``SHED[queue-full]`` response naming the depth
  and the limit — never a silent drop, never a hang. Handler threads
  are a bounded pool (``--max-conns``); a connection beyond the pool
  gets a framed ``SHED[connection-limit]`` line and a close.
- **Soft deadlines** — a request may carry ``deadline_ms``; expired
  requests are shed at batch boundaries BEFORE compile/dispatch (the
  ``safe_cancellation`` discipline: never mid-kernel), and admission
  consults the cost model's jax-free analytic floor (tpu_aggcomm/model)
  to pre-shed requests that provably cannot meet their budget
  (``SHED[deadline_floor]`` — advisory: predictions never gate a
  request that COULD meet its budget, only ones the floor proves out).
- **Lifecycle** — READY → DEGRADED (a retry budget exhausted on
  tunnel-class transients: TPU-backed runs are shed by name, the
  jax-free ops still answer) → DRAINING (SIGTERM or a shutdown op:
  admissions close, in-flight batches finish at their fenced
  boundaries, the journal is flushed, a ledger ``drain`` record lands).
  Exposed via the ``health`` op and a ``/metrics`` state gauge behind
  the existing import-level gate.
- **Crash recovery** — ``--recover JOURNAL`` replays the torn-line-
  tolerant per-request journal at startup (serve/recover.py): completed
  and in-flight-lost requests reported by name, the compiled-chain
  cache pre-warmed from the journal's shape records under the
  ``schedule_shape_key`` + backend + manifest-fingerprint lens (drift =
  named skip, not a stale warm).

Every shed/state/drain decision lands in trace + ledger resilience
records AND the journal, so the whole lifecycle re-derives from
artifacts alone (serve/recover.replay_journal — the replay_attempts
discipline applied to requests). Chaos sites ``serve:admit`` /
``serve:compile`` / ``serve:dispatch`` inject synthetic transients
through the same ``TPU_AGGCOMM_CHAOS`` budget as everything else.

Wired substrate, not regrown:

- **Cache keying** — ``schedule_shape_key`` + backend + manifest
  fingerprint (tune-cache lens); drift ⟹ named eviction + recompile.
- **Resilience** — every compile/dispatch goes through
  ``resilience.retry_call`` (unique site per batch), so tunnel-class
  transients retry with the seeded backoff, every attempt lands in
  trace + ledger, and ``replay_attempts`` reproduces the timeline.
- **Journal** — per-request accounting through ``RunJournal`` (append
  + fsync, torn-line-tolerant readers): a killed server loses at most
  the record being written.
- **Metrics** — the opt-in obs/export ``/metrics`` endpoint (OFF by
  default; the import itself is gated) serves queue depth, request
  latency histograms and the lifecycle state gauge whose ``_exact``
  summary quantiles use the same ``obs.metrics.percentile`` arithmetic
  as every other exposition.

The listener binds 127.0.0.1 ONLY — serving is for the operator's
machine, not the network (the obs/export discipline); a non-loopback
host refuses by name.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque

from tpu_aggcomm.faults import FaultSpecError, RepairError
from tpu_aggcomm.obs import ledger, trace
from tpu_aggcomm.obs.metrics import percentile
from tpu_aggcomm.obs.workload import (batch_fill_ratio, padded_slots,
                                      payload_bytes)
from tpu_aggcomm.resilience.journal import RunJournal
from tpu_aggcomm.resilience.policy import (RetryPolicy, retries_exhausted,
                                           retry_call)
from tpu_aggcomm.serve.cache import CompiledChainCache
from tpu_aggcomm.serve.protocol import (PROTOCOL, ProtocolError,
                                        parse_request, read_msg,
                                        request_schedule, send_msg)

__all__ = ["ScheduleServer", "SERVE_BACKENDS", "SERVE_STATES"]

#: Backends the server compiles chains for (mirrors
#: serve/executor.CHAIN_BACKENDS without importing the jax module).
SERVE_BACKENDS = ("jax_sim", "pallas_fused")

#: The lifecycle state machine, in order. READY admits; DEGRADED (a
#: retry budget exhausted on tunnel-class transients) sheds TPU-backed
#: runs but still answers the jax-free ops; DRAINING admits nothing and
#: finishes in-flight work at fenced boundaries.
SERVE_STATES = ("ready", "degraded", "draining")

_LOOPBACK = ("127.0.0.1", "localhost")

#: Sentinel: floor params not loaded yet (lazy — most servers never see
#: a deadline and must not pay a PREDICT_*.json scan at startup).
_FLOOR_UNSET = object()


class _Pending:
    """One enqueued request awaiting its batch."""

    __slots__ = ("req", "rid", "schedule", "shape_key", "backend_name",
                 "served_method", "t0", "deadline", "event", "response",
                 "marks", "depth_at_admit")

    def __init__(self, req, rid, schedule, shape_key, backend_name,
                 served_method=None):
        self.req = req
        self.rid = rid
        self.schedule = schedule
        self.shape_key = shape_key
        self.backend_name = backend_name
        # the method id that actually executes — differs from
        # req.method only under an installed promotion, and then it is
        # ALWAYS named in the response + journal (zero silent swaps)
        self.served_method = (served_method if served_method is not None
                              else req.method)
        self.t0 = time.monotonic()
        self.deadline = (self.t0 + req.deadline_ms / 1e3
                         if req.deadline_ms is not None else None)
        self.event = threading.Event()
        self.response: dict = {}
        # phase-boundary stamps relative to t0, in obs/workload.py's
        # canonical BOUNDARIES order; the journal carries them verbatim
        # and the profiler's phase attribution is their consecutive
        # differences — never a separate host timing
        self.marks: dict = {"admit": 0.0}
        self.depth_at_admit: int | None = None

    def mark(self, boundary: str) -> None:
        self.marks[boundary] = time.monotonic() - self.t0


class ScheduleServer:
    """The persistent aggregation server. Construct, then
    :meth:`serve_forever` (blocking) or :meth:`start` (thread)."""

    def __init__(self, *, backend: str = "jax_sim",
                 host: str = "127.0.0.1", port: int = 0,
                 max_batch: int = 8, batch_window_s: float = 0.005,
                 max_queue: int = 256, max_conns: int = 64,
                 journal_path: str | None = None,
                 metrics_port: int | None = None,
                 retry_policy: RetryPolicy | None = None,
                 recover: str | None = None,
                 predict_root: str = "."):
        import socket

        if host not in _LOOPBACK:
            raise ValueError(
                f"serve: refusing to bind {host!r} — the server binds "
                f"127.0.0.1 only (loopback telemetry discipline, "
                f"obs/export.py); tunnel remote clients through ssh")
        if backend not in SERVE_BACKENDS:
            raise ValueError(f"serve: unknown backend {backend!r}; "
                             f"valid: {SERVE_BACKENDS}")
        self._backend = backend
        self._max_batch = max(1, int(max_batch))
        self._batch_window_s = max(0.0, float(batch_window_s))
        self._max_queue = max(1, int(max_queue))
        self._max_conns = max(1, int(max_conns))
        self._conn_slots = threading.Semaphore(self._max_conns)
        self._retry_policy = retry_policy
        self._predict_root = predict_root

        self._listener = socket.create_server((host, port))
        self._listener.settimeout(0.2)
        self.host = host
        self.port = self._listener.getsockname()[1]

        self._cv = threading.Condition()
        self._queue: deque[_Pending] = deque()
        self._stop = False
        self._schedules: dict[tuple, tuple] = {}   # sig -> (sched, key, mid)
        # installed promotions (autopilot swap op): shape sig -> the
        # validated record + install seq. A promoted sig re-resolves to
        # the NEW method's schedule; demote deletes the entry (and the
        # resolved-schedule cache line) so the old method serves again.
        self._promotions: dict[tuple, dict] = {}
        self._promo_seq = 0
        # per-shape_key serve stats (repr(shape_key) -> counters);
        # latency_sum is the SAME latency the journal records per
        # request, accumulated in journal order (float-consistency pin
        # in tests/test_serve.py)
        self._per_shape: dict[str, dict] = {}
        self._floor_params = _FLOOR_UNSET
        self._floors: dict = {}                    # shape_key -> float | None
        self._cache = CompiledChainCache()
        self._man = ledger.manifest()
        from tpu_aggcomm.tune.cache import manifest_fingerprint
        self._fp = manifest_fingerprint(self._man)

        self._journal = RunJournal(journal_path) if journal_path else None
        if self._journal is not None:
            self._journal.begin_session(self._man)

        # counters (all under _cv's lock for mutation)
        self._rid = 0
        self._batch_seq = 0
        self._reserved = 0        # admission slots between bound-check and enqueue
        self._n_completed = 0
        self._n_errors = 0
        self._n_failed = 0        # _finish failures only (journaled 1:1)
        self._n_shed_rec = 0      # per-request sheds (journaled 1:1 when armed)
        self._n_compiles = 0
        self._n_batches = 0
        self._n_batched_requests = 0
        self._max_batch_seen = 0
        # batch-efficiency counters (cumulative over dispatched batches)
        # — the SAME obs/workload.py arithmetic the profiler re-derives
        # from the journal, so /metrics and WORKLOAD_r*.json cannot
        # drift (telemetry_gate.py cross-checks float-exact)
        self._fill_requests = 0   # requests dispatched
        self._fill_slots = 0      # padded slots those requests occupied
        self._waste_bytes = 0     # (padded - n) * payload bytes
        self._warm_s: list[float] = []
        self._cold_s: list[float] = []
        self._shed: dict[str, int] = {}

        # lifecycle state machine (READY until proven otherwise)
        self._state = "ready"
        self._state_seq = 0
        self._degraded_reason: str | None = None
        self._drain_reason: str | None = None

        # OFF by default; the /metrics import itself is the gate (the
        # zero-cost obs invariant) — armed, the hot path pays one
        # is-not-None check per request
        self._registry = None
        self._metrics = None
        self._slo = None
        env_armed = os.environ.get("TPU_AGGCOMM_METRICS_PORT", "").strip()
        if metrics_port is not None or env_armed:
            from tpu_aggcomm.obs.export import MetricsRegistry, serve_from_env
            registry = MetricsRegistry()
            self._metrics = serve_from_env(registry.render,
                                           port=metrics_port)
            if self._metrics is not None:
                self._registry = registry
                self._state_gauge("ready")
                # burn-rate gauges over rolling SLO windows — same
                # measure_window arithmetic as `inspect watch`, loaded
                # only behind the same import-level gate
                from tpu_aggcomm.obs.watch import LiveSlo
                self._slo = LiveSlo(registry)

        self._recover = None
        if recover:
            self._recover = self._run_recovery(recover)

        self._exec_thread = threading.Thread(
            target=self._executor_loop, name="tpu-aggcomm-serve-exec",
            daemon=True)
        self._accept_thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------
    def ready_info(self) -> dict:
        info = {"serve": "ready", "protocol": PROTOCOL,
                "host": self.host, "port": self.port,
                "backend": self._backend, "pid": os.getpid(),
                "max_batch": self._max_batch,
                "max_queue": self._max_queue,
                "max_conns": self._max_conns,
                "state": self._state}
        if self._metrics is not None:
            info["metrics_url"] = self._metrics.url
        if self._recover is not None:
            info["recover"] = self._recover
        return info

    def _state_gauge(self, state: str) -> None:
        if self._registry is not None:
            from tpu_aggcomm.obs.export import SERVE_STATE_VALUES
            self._registry.gauge("tpu_aggcomm_serve_state",
                                 float(SERVE_STATE_VALUES.get(state, -1)))

    def _set_state(self, state: str, reason: str) -> None:
        """One lifecycle transition: ledger + trace + journal + gauge —
        every transition re-derivable from artifacts alone."""
        with self._cv:
            if self._state == state:
                return
            prev = self._state
            self._state = state
            self._state_seq += 1
            seq = self._state_seq
        rec = ledger.record_resilience("serve:lifecycle", kind="state",
                                       state=state, prev=prev,
                                       reason=str(reason)[:500])
        trace.instant("ledger.resilience", **rec)
        if self._journal is not None:
            self._journal.record({"state": seq}, fingerprint=self._fp,
                                 status="state", state=state, prev=prev,
                                 reason=str(reason)[:500])
        self._state_gauge(state)
        print(f"serve: state {prev} -> {state} ({reason})",
              file=sys.stderr)

    def _enter_degraded(self, reason: str) -> None:
        """Tunnel-class retry budget exhausted: stop accepting TPU-backed
        work (shed by name) while the jax-free ops keep answering. Sticky
        until restart — a tunnel that ate a whole retry budget is not
        presumed healed by the next request."""
        with self._cv:
            if self._state != "ready":
                return
            self._degraded_reason = str(reason)
        self._set_state("degraded", reason)

    def begin_drain(self, reason: str) -> None:
        """Graceful drain: admissions close (new runs shed by name),
        in-flight batches finish at their fenced boundaries — never
        mid-kernel — then the journal gets the drain record."""
        with self._cv:
            already = self._state == "draining"
            if not already:
                self._drain_reason = str(reason)
        if not already:
            self._set_state("draining", reason)
        self.stop()

    def _install_sigterm(self):
        """SIGTERM = graceful drain. Main-thread only (the
        safe_cancellation discipline: signal handlers install nowhere
        else); returns the previous handler, or None if not installed."""
        if threading.current_thread() is not threading.main_thread():
            return None
        import signal

        def _on_term(signum, frame):
            print("serve: SIGTERM — draining (admissions close; "
                  "in-flight batches finish at their fenced boundaries, "
                  "never mid-kernel)", file=sys.stderr, flush=True)
            self.begin_drain("SIGTERM")

        try:
            return signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            return None

    def serve_forever(self) -> None:
        """Accept loop; returns after :meth:`stop` (or a shutdown op /
        SIGTERM) once the queue has drained."""
        import socket

        self._exec_thread.start()
        old_term = self._install_sigterm()
        try:
            while not self._stop:
                try:
                    conn, _addr = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not self._conn_slots.acquire(blocking=False):
                    self._shed_conn(conn)
                    continue
                threading.Thread(target=self._handle_conn_slot,
                                 args=(conn,),
                                 name="tpu-aggcomm-serve-conn",
                                 daemon=True).start()
        finally:
            if old_term is not None:
                import signal
                try:
                    signal.signal(signal.SIGTERM, old_term)
                except (ValueError, OSError):
                    pass
        self._exec_thread.join(timeout=60.0)
        if self._exec_thread.is_alive():
            # the drain join used to be fire-and-forget: a stuck
            # executor returned "clean" with a live thread and an
            # unflushed request ledger. Name it instead.
            rec = ledger.record_resilience(
                "serve:drain", kind="suppressed", error_class="program",
                error="executor thread still alive after the 60 s drain "
                      "join — in-flight work may be lost; the journal "
                      "carries no drain record")
            trace.instant("ledger.resilience", **rec)
            print("serve: WARNING — executor thread did not drain within "
                  "60 s; in-flight work may be lost (ledger 'suppressed' "
                  "record written, no drain record)", file=sys.stderr)
        else:
            self._finish_drain()
        self.close()

    def _finish_drain(self) -> None:
        """The drain epilogue: ledger + journal drain record carrying
        counts re-derivable from the journal entries alone
        (serve/recover.replay_journal cross-checks them)."""
        with self._cv:
            reason = self._drain_reason or "stop"
            lost = [p.rid for p in self._queue]
            completed = self._n_completed
            failed = self._n_failed
            shed_rec = self._n_shed_rec
            shed_all = dict(self._shed)
        rec = ledger.record_resilience(
            "serve:drain", kind="drain", reason=reason,
            completed=completed, failed=failed, shed=shed_rec, lost=lost)
        trace.instant("ledger.resilience", **rec)
        if self._journal is not None:
            self._journal.record({"drain": 1}, fingerprint=self._fp,
                                 status="drain", reason=reason,
                                 completed=completed, failed=failed,
                                 shed=shed_rec, lost=lost)
        extra = f", LOST {lost}" if lost else ""
        print(f"serve: drained ({reason}) — {completed} completed, "
              f"{failed} failed, {sum(shed_all.values())} shed{extra}",
              file=sys.stderr)

    def start(self) -> threading.Thread:
        """Run :meth:`serve_forever` on a daemon thread (tests)."""
        self._accept_thread = threading.Thread(
            target=self.serve_forever, name="tpu-aggcomm-serve-accept",
            daemon=True)
        self._accept_thread.start()
        return self._accept_thread

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def close(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass
        if self._metrics is not None:
            self._metrics.close()
            self._metrics = None

    def join(self, timeout: float | None = None) -> None:
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=timeout)

    # -- crash recovery ----------------------------------------------------
    def _run_recovery(self, path: str) -> dict:
        """Replay the journal and pre-warm the compiled-chain cache
        (serve/recover.py decides what and why; executor compiles)."""
        from tpu_aggcomm.serve.recover import (prewarm_plan,
                                               render_recovery,
                                               replay_journal)
        report = replay_journal(path)
        for line in render_recovery(report):
            print(f"serve: recover: {line}", file=sys.stderr)
        warm, skips = prewarm_plan(report, fingerprint=self._fp,
                                   manifest=self._man)
        prewarmed = 0
        for i, w in enumerate(warm):
            try:
                from tpu_aggcomm.serve import executor
                chain, compile_s, shape_key = retry_call(
                    lambda w=w: executor.prewarm_chain(w["shape"],
                                                       w["backend"]),
                    site=f"serve:prewarm:{i}",
                    policy=self._retry_policy)
            except Exception as e:  # lint: broad-ok (pre-warm is advisory: a shape that no longer compiles must not kill recovery — its first live request reports the error)
                skips.append(f"{w['backend']} shape {w['shape']}: "
                             f"pre-warm failed: {type(e).__name__}: {e}")
                continue
            ledger.record_compile(
                f"serve:{w['backend']}:prewarm{i}", seconds=compile_s,
                kind="compile+warmup", backend=w["backend"], prewarm=True)
            self._cache.put(shape_key, w["backend"], fingerprint=self._fp,
                            manifest=self._man, chain=chain,
                            compile_s=compile_s, prewarmed=True)
            with self._cv:
                self._n_compiles += 1
            prewarmed += 1
        for s in skips:
            print(f"serve: recover: skip — {s}", file=sys.stderr)
        return {"journal": path, "verdict": report["verdict"],
                "completed": report["completed"],
                "failed": report["failed"], "shed": report["shed"],
                "lost": report["lost"], "prewarmed": prewarmed,
                "skipped": skips}

    # -- the cost-model floor (jax-free pre-shed) --------------------------
    def _load_floor_params(self) -> dict | None:
        """Params from the newest committed PREDICT_*.json for this
        platform (falling back to the cpu calibration like
        floor_from_trace_events) — None if there is no usable artifact;
        the floor is then simply not consulted (admission stays open)."""
        try:
            from tpu_aggcomm.model.predict import newest_predict_path
            path = newest_predict_path(self._predict_root)
            if path is None:
                return None
            with open(path) as fh:
                blob = json.load(fh)
            platforms = blob.get("platforms") or {}
            platform = str(self._man.get("platform") or "cpu")
            entry = platforms.get(platform) or platforms.get("cpu") or {}
            params = entry.get("params")
            if isinstance(params, dict):
                return {"path": path, "params": params}
        except Exception as e:  # lint: broad-ok (floor is advisory: a malformed PREDICT artifact must not break admission)
            print(f"serve: cost-model floor unavailable "
                  f"({type(e).__name__}: {e}) — deadline_floor pre-shed "
                  f"disabled", file=sys.stderr)
        return None

    def _floor_for(self, schedule, shape_key) -> float | None:
        """The analytic lower bound (seconds) for one rep of
        ``schedule``, or None when unpriceable/uncalibrated. Cached per
        shape_key; jax-free (model features come from op programs)."""
        if self._floor_params is _FLOOR_UNSET:
            self._floor_params = self._load_floor_params()
        if self._floor_params is None:
            return None
        with self._cv:
            if shape_key in self._floors:
                return self._floors[shape_key]
        try:
            from tpu_aggcomm.model.features import schedule_features
            from tpu_aggcomm.model.predict import floor_from_features
            floor = float(floor_from_features(
                schedule_features(schedule), self._floor_params["params"]))
        except Exception:  # lint: broad-ok (floor is advisory: an unpriceable schedule — dense collectives the traffic matrices refuse — admits normally)
            floor = None
        with self._cv:
            self._floors[shape_key] = floor
        return floor

    # -- load shedding -----------------------------------------------------
    def _record_shed(self, rid: int | None, reason: str, detail: str,
                     *, site: str | None = None, phases: dict | None = None,
                     **extra) -> dict:
        """One shed decision: counter + ledger + trace + journal +
        metrics, and the framed response the client gets — always by
        name, never a silent drop. ``phases`` (the boundary stamps the
        request traversed before the shed) lands in the journal record
        only — the profiler attributes honestly over the prefix."""
        with self._cv:
            self._shed[reason] = self._shed.get(reason, 0) + 1
            if rid is not None:
                self._n_shed_rec += 1
        rec = ledger.record_resilience(
            site or (f"serve:admit:r{rid}" if rid is not None
                     else "serve:admit"),
            kind="shed", reason=reason, detail=detail[:500], **extra)
        trace.instant("ledger.resilience", **rec)
        if self._registry is not None:
            self._registry.counter("tpu_aggcomm_serve_shed",
                                   reason=reason)
        if self._slo is not None and rid is not None:
            self._slo.record(status="shed", shed_reason=reason,
                             deadline_ms=extra.get("deadline_ms"))
        if self._journal is not None and rid is not None:
            self._journal.record({"request": rid}, fingerprint=self._fp,
                                 status="shed", reason=reason,
                                 detail=detail[:500], phases=phases,
                                 **extra)
        return {"ok": False, "shed": reason, "request_id": rid,
                "error": f"SHED[{reason}]: {detail}"}

    def _shed_pending(self, p: _Pending, reason: str, detail: str,
                      **extra) -> None:
        """Shed an already-queued request at a batch boundary."""
        p.mark("respond")
        p.response = self._record_shed(
            p.rid, reason, detail, site=f"serve:dispatch:r{p.rid}",
            phases=dict(p.marks), **extra)
        p.response["latency_s"] = p.marks["respond"]
        p.event.set()

    def _shed_conn(self, conn) -> None:
        """All handler slots busy: one framed SHED line on the raw
        socket, then close — the client learns WHY, immediately."""
        with self._cv:
            self._shed["connection-limit"] = \
                self._shed.get("connection-limit", 0) + 1
        rec = ledger.record_resilience(
            "serve:admit:conn", kind="shed", reason="connection-limit",
            detail=f"all {self._max_conns} handler slots busy")
        trace.instant("ledger.resilience", **rec)
        if self._registry is not None:
            self._registry.counter("tpu_aggcomm_serve_shed",
                                   reason="connection-limit")
        try:
            conn.sendall((json.dumps(
                {"ok": False, "shed": "connection-limit",
                 "error": f"SHED[connection-limit]: all "
                          f"{self._max_conns} handler slots are busy "
                          f"(--max-conns) — retry"}) + "\n")
                .encode("utf-8"))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _sweep_deadlines(self, batch: list[_Pending],
                         boundary: str) -> list[_Pending]:
        """Shed expired-deadline requests at a batch boundary (never
        mid-kernel: the only places this runs are before compile and
        before dispatch)."""
        now = time.monotonic()
        live: list[_Pending] = []
        for p in batch:
            if p.deadline is not None and now > p.deadline:
                self._shed_pending(
                    p, "deadline-expired",
                    f"soft deadline {p.req.deadline_ms:g} ms expired "
                    f"{boundary} (deadlines shed at fenced batch "
                    f"boundaries only, never mid-kernel)",
                    deadline_ms=p.req.deadline_ms)
            else:
                live.append(p)
        return live

    # -- request intake ----------------------------------------------------
    def _schedule_for(self, req, backend_name: str):
        """(schedule, shape_key, served_method) for a request — compiled
        and (under a fault spec) repaired once per distinct shape,
        jax-free. An installed promotion re-routes the REQUESTED shape
        to the promoted method's schedule; the served method id is
        threaded through to the response and journal so a swap is never
        silent."""
        sig = tuple(getattr(req, f) for f in req.shape_fields) \
            + (backend_name,)
        with self._cv:
            hit = self._schedules.get(sig)
            promo = self._promotions.get(sig)
        if hit is not None:
            return hit
        served_method = req.method
        if promo is not None:
            import dataclasses
            served_method = promo["record"]["new_method"]
            req = dataclasses.replace(req, method=served_method)
        schedule = request_schedule(req)
        from tpu_aggcomm.core.schedule import schedule_shape_key
        shape_key = schedule_shape_key(schedule)
        with self._cv:
            self._schedules[sig] = (schedule, shape_key, served_method)
        return schedule, shape_key, served_method

    def _handle_conn_slot(self, conn) -> None:
        try:
            self._handle_conn(conn)
        finally:
            self._conn_slots.release()

    def _handle_conn(self, conn) -> None:
        with conn:
            fh = conn.makefile("rw", encoding="utf-8")
            with fh:
                while True:
                    msg = read_msg(fh)
                    if msg is None:
                        return
                    op = msg.get("op")
                    if op == "run":
                        self._handle_run(fh, msg)
                    elif op == "stats":
                        send_msg(fh, self.stats())
                    elif op == "health":
                        send_msg(fh, self.health())
                    elif op == "swap":
                        send_msg(fh, self.swap(msg.get("record")))
                    elif op == "demote":
                        send_msg(fh, self.demote(msg.get("record"),
                                                 msg.get("reason")))
                    elif op == "shutdown":
                        send_msg(fh, {"ok": True, "stopping": True})
                        self.begin_drain("shutdown op")
                        return
                    else:
                        send_msg(fh, {"ok": False,
                                      "error": f"unknown op {op!r}"})

    def _handle_run(self, fh, msg: dict) -> None:
        try:
            req = parse_request(msg)
            backend_name = req.backend or self._backend
            if backend_name not in SERVE_BACKENDS:
                raise ProtocolError(
                    f"run request backend {backend_name!r} is not "
                    f"servable; valid: {SERVE_BACKENDS}")
            schedule, shape_key, served_method = \
                self._schedule_for(req, backend_name)
        except (ProtocolError, FaultSpecError, RepairError,
                ValueError) as e:
            with self._cv:
                self._n_errors += 1
            send_msg(fh, {"ok": False, "error": str(e)})
            return
        with self._cv:
            self._rid += 1
            rid = self._rid
            state = self._state
            stopping = self._stop
        # lifecycle gates: a DEGRADED/DRAINING server refuses TPU-backed
        # work by name (stats/health/shutdown keep answering)
        if state == "degraded":
            send_msg(fh, self._record_shed(
                rid, "degraded",
                f"server is DEGRADED ({self._degraded_reason}); run "
                f"requests are shed until restart — stats/health/"
                f"shutdown still answer"))
            return
        if state == "draining" or stopping:
            send_msg(fh, self._record_shed(
                rid, "draining",
                "server is DRAINING — admissions are closed; in-flight "
                "work finishes at its fenced boundaries"))
            return
        # advisory cost-model pre-shed: the jax-free analytic floor vs
        # the request's soft budget — shed only what provably cannot fit
        if req.deadline_ms is not None:
            floor = self._floor_for(schedule, shape_key)
            if floor is not None and floor > req.deadline_ms / 1e3:
                send_msg(fh, self._record_shed(
                    rid, "deadline_floor",
                    f"analytic cost-model floor {floor * 1e3:.3f} ms "
                    f"exceeds the {req.deadline_ms:g} ms budget — the "
                    f"request provably cannot meet its deadline "
                    f"(advisory floor, tpu_aggcomm/model)",
                    floor_s=floor, deadline_ms=req.deadline_ms))
                return
        # the admission decision itself is a retry/chaos site
        # ("serve:admit"): a transient here retries under the seeded
        # policy; an exhausted budget flips the server DEGRADED
        try:
            retry_call(lambda: None, site=f"serve:admit:r{rid}",
                       policy=self._retry_policy)
        except Exception as e:  # lint: broad-ok (an admission failure is the request's response, never the server's death)
            if retries_exhausted(e):
                self._enter_degraded(
                    f"retry budget exhausted at serve:admit:r{rid}: "
                    f"{type(e).__name__}: {e}")
            with self._cv:
                self._n_errors += 1
            send_msg(fh, {"ok": False, "request_id": rid,
                          "error": f"admit failed: "
                                   f"{type(e).__name__}: {e}"})
            return
        # bounded queue: the admission decision happens at enqueue time
        # (a reserved slot covers the journal write below, so concurrent
        # admits cannot overshoot the bound)
        with self._cv:
            depth = len(self._queue) + self._reserved
            over = depth >= self._max_queue
            if not over:
                self._reserved += 1
        if over:
            send_msg(fh, self._record_shed(
                rid, "queue-full",
                f"queue depth {depth} >= --max-queue {self._max_queue}; "
                f"retry later or raise the bound",
                depth=depth, limit=self._max_queue))
            return
        pending = _Pending(req, rid, schedule, shape_key, backend_name,
                           served_method)
        pending.depth_at_admit = depth
        try:
            # admission journal record BEFORE the executor can see the
            # pending: a done/fail always follows its admitted record
            # (serve/recover.replay_journal pins the ordering), and the
            # shape dict is what --recover pre-warms from; t_unix +
            # queue_depth feed the workload profiler's arrival-process
            # and congestion statistics (obs/workload.py)
            if self._journal is not None:
                shape = {f: getattr(req, f) for f in req.shape_fields}
                self._journal.record(
                    {"request": rid}, fingerprint=self._fp,
                    status="admitted", shape=shape, backend=backend_name,
                    iter=req.iter_, deadline_ms=req.deadline_ms,
                    served_method=served_method,
                    t_unix=time.time(), queue_depth=depth)
        finally:
            with self._cv:
                self._reserved -= 1
                self._queue.append(pending)
                depth = len(self._queue)
                self._cv.notify_all()
        if self._registry is not None:
            self._registry.gauge("tpu_aggcomm_serve_queue_depth", depth)
        pending.event.wait()
        try:
            send_msg(fh, pending.response)
        except OSError:
            pass   # client vanished mid-wait; the journal has the verdict

    # -- the batching executor --------------------------------------------
    def _extract_same(self, head: _Pending, room: int) -> list[_Pending]:
        """Pull up to ``room`` queued requests sharing head's compiled
        program identity ((shape_key, backend) — iter/verify differ
        freely: same program, different payload)."""
        out: list[_Pending] = []
        keep: deque[_Pending] = deque()
        while self._queue and len(out) < room:
            p = self._queue.popleft()
            if (p.shape_key == head.shape_key
                    and p.backend_name == head.backend_name):
                p.mark("queue")
                out.append(p)
            else:
                keep.append(p)
        keep.extend(self._queue)
        self._queue.clear()
        self._queue.extend(keep)
        return out

    def _executor_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait(0.1)
                if not self._queue and self._stop:
                    return
                head = self._queue.popleft()
                head.mark("queue")
            batch = [head]
            deadline = time.monotonic() + self._batch_window_s
            while len(batch) < self._max_batch:
                with self._cv:
                    batch.extend(self._extract_same(
                        head, self._max_batch - len(batch)))
                if len(batch) >= self._max_batch \
                        or time.monotonic() >= deadline:
                    break
                time.sleep(min(0.0005,
                               max(deadline - time.monotonic(), 0.0)))
            if self._registry is not None:
                with self._cv:
                    depth = len(self._queue)
                self._registry.gauge("tpu_aggcomm_serve_queue_depth",
                                     depth)
            self._run_batch(batch)

    def _fail_batch(self, batch, disposition: str, err: str, *,
                    seq: int, padded: int | None = None) -> None:
        for p in batch:
            self._finish(p, batch_n=len(batch), disposition=disposition,
                         compile_s=None, verified=None, error=err,
                         batch_seq=seq, batch_padded=padded)

    def _run_batch(self, batch: list[_Pending]) -> None:
        # deadline sweep BEFORE compile: an expired request must not pay
        # (or charge the batch for) a compile it cannot use
        batch = self._sweep_deadlines(batch, "before compile")
        if not batch:
            return
        head = batch[0]
        with self._cv:
            self._batch_seq += 1
            seq = self._batch_seq
            self._n_batches += 1
            self._max_batch_seen = max(self._max_batch_seen, len(batch))
            if len(batch) > 1:
                self._n_batched_requests += len(batch)
        for p in batch:   # the --batch-window-ms gather is over
            p.mark("batch")
        from tpu_aggcomm.serve import executor

        entry, reason = self._cache.lookup(
            head.shape_key, head.backend_name, fingerprint=self._fp,
            manifest=self._man)
        compile_s = None
        disposition = "hit"
        if entry is None:
            disposition = "evict" if reason.startswith("manifest drift") \
                else "miss"
            print(f"serve: {reason}", file=sys.stderr)
            try:
                chain, compile_s = retry_call(
                    lambda: executor.build_chain(head.schedule,
                                                 head.backend_name),
                    site=f"serve:compile:b{seq}",
                    policy=self._retry_policy)
            except Exception as e:  # lint: broad-ok (fault isolation: a compile error is the batch's response, never the server's death)
                if retries_exhausted(e):
                    self._enter_degraded(
                        f"retry budget exhausted at serve:compile:b{seq}: "
                        f"{type(e).__name__}: {e}")
                self._fail_batch(batch, disposition,
                                 f"compile failed: {type(e).__name__}: {e}",
                                 seq=seq)
                return
            ledger.record_compile(
                f"serve:{head.backend_name}:b{seq}", seconds=compile_s,
                kind="compile+warmup", backend=head.backend_name)
            entry = self._cache.put(
                head.shape_key, head.backend_name, fingerprint=self._fp,
                manifest=self._man, chain=chain, compile_s=compile_s)
            with self._cv:
                self._n_compiles += 1
        for p in batch:   # cache lookup (+ compile, on a miss) resolved
            p.mark("cache")
        # deadline sweep again AFTER compile, BEFORE dispatch: the
        # compile wall may have outlived a budget, and shedding here is
        # still a fenced boundary (nothing dispatched yet)
        batch = self._sweep_deadlines(batch, "after compile, before "
                                             "dispatch")
        if not batch:
            return
        # batch-efficiency accounting at the dispatch boundary, through
        # the SAME obs/workload.py arithmetic the profiler re-derives —
        # a dispatch-failed batch still occupied its padded slab
        padded = padded_slots(len(batch), head.backend_name)
        head_shape = {f: getattr(head.req, f)
                      for f in head.req.shape_fields}
        waste = (padded - len(batch)) * payload_bytes(head_shape)
        with self._cv:
            self._fill_requests += len(batch)
            self._fill_slots += padded
            self._waste_bytes += waste
            fill_req, fill_slots = self._fill_requests, self._fill_slots
            waste_total = self._waste_bytes
        if self._registry is not None:
            ratio = batch_fill_ratio(fill_req, fill_slots)
            if ratio is not None:
                self._registry.gauge("tpu_aggcomm_serve_batch_fill_ratio",
                                     ratio)
            self._registry.gauge("tpu_aggcomm_serve_padding_waste_bytes",
                                 float(waste_total))
        chain = entry["chain"]
        # occupancy marker for the pilot's contention guard
        # (tune/measure.py): an in-process campaign sampler refuses to
        # take race samples while this dispatch is in flight on the
        # same backend (one CPU core — concurrent measured workloads
        # corrupt each other's differenced timings)
        from tpu_aggcomm.tune.measure import serve_dispatch_inflight
        rec = trace.current()
        try:
            with serve_dispatch_inflight(head.backend_name), \
                    trace.span("serve.batch", seq=seq, cid=f"b{seq}",
                               n=len(batch),
                               backend=head.backend_name,
                               method=head.schedule.method_id,
                               padded=padded,
                               rids=[p.rid for p in batch]):
                t_disp = time.perf_counter()
                results = retry_call(
                    lambda: executor.execute_batch(
                        chain, [p.req for p in batch]),
                    site=f"serve:dispatch:b{seq}",
                    policy=self._retry_policy)
                disp_wall = time.perf_counter() - t_disp
        except Exception as e:  # lint: broad-ok (fault isolation: a dispatch error is the batch's response, never the server's death)
            if retries_exhausted(e):
                self._enter_degraded(
                    f"retry budget exhausted at serve:dispatch:b{seq}: "
                    f"{type(e).__name__}: {e}")
            self._fail_batch(batch, disposition,
                             f"dispatch failed: {type(e).__name__}: {e}",
                             seq=seq, padded=padded)
            return
        if rec is not None:   # one armed-recorder check on the hot path
            self._record_dispatch_run(rec, head, seq, disp_wall)
        for p in batch:
            p.mark("dispatch")
        for p, res in zip(batch, results):
            self._finish(p, batch_n=len(batch), disposition=disposition,
                         compile_s=compile_s, verified=res["verified"],
                         error=res["error"], batch_seq=seq,
                         batch_padded=padded)

    def _record_dispatch_run(self, rec, head: _Pending, seq: int,
                             wall_s: float) -> None:
        """One ATTRIBUTED run event per traced batch dispatch, stamped
        with the batch correlation id (``cid="b<seq>"``) via
        ``trace.run_context`` — the hook the flow joiner (obs/flow.py)
        uses to tie a request's journal record to the round timeline of
        the dispatch that served it. The measured host wall around the
        dispatch is split by the fenced-segment model
        (``harness.attribution.attribute_total`` — contextlib/numpy/core
        only, never jax: the control plane stays pure) and labelled
        ``"attributed"`` (report.py:PHASE_SOURCES), never oversold as
        measured rounds. Called only when the recorder is armed; a
        recording failure must never sink the batch it describes."""
        try:
            from tpu_aggcomm.harness.attribution import (attribute_total,
                                                         cell_recording)
            try:
                from tpu_aggcomm.core.methods import METHODS
                name = METHODS[head.schedule.method_id].name
            except (ImportError, KeyError):
                name = f"m{head.schedule.method_id}"
            with cell_recording() as calls:
                timers = attribute_total(head.schedule, wall_s)
            with trace.run_context(cid=f"b{seq}"):
                rec.record_method_run(
                    head.schedule, method=head.schedule.method_id,
                    name=name, iter_=seq, ntimes=1,
                    requested=head.backend_name,
                    executed=head.backend_name,
                    phase_source="attributed", timers=timers,
                    calls=calls,
                    fault=getattr(head.schedule, "fault", None))
        except Exception as e:  # lint: broad-ok (observability enrichment must never sink the batch it describes)
            print(f"serve: dispatch trace record failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)

    def _finish(self, p: _Pending, *, batch_n: int, disposition: str,
                compile_s, verified, error, batch_seq: int,
                batch_padded: int | None = None) -> None:
        p.mark("respond")
        latency = p.marks["respond"]   # same clock read as the stamp
        ok = error is None
        p.response = {"ok": ok, "request_id": p.rid,
                      "verified": verified, "error": error,
                      "latency_s": latency, "batch_n": batch_n,
                      "cache": disposition, "compile_s": compile_s,
                      "backend": p.backend_name,
                      "served_method": p.served_method,
                      "shape_key": repr(p.shape_key)}
        with self._cv:
            if ok:
                self._n_completed += 1
                (self._warm_s if disposition == "hit"
                 else self._cold_s).append(latency)
            else:
                self._n_errors += 1
                self._n_failed += 1
            # per-shape counters: exactly one row update per journaled
            # done/fail, same latency value, same order — the pilot's
            # target-ranking evidence (float-consistency pin in
            # tests/test_serve.py)
            row = self._per_shape.setdefault(
                repr(p.shape_key),
                {"hit": 0, "miss": 0, "requests": 0,
                 "latency_sum": 0.0})
            row["hit" if disposition == "hit" else "miss"] += 1
            row["requests"] += 1
            row["latency_sum"] += latency
        if self._registry is not None:
            self._registry.observe("tpu_aggcomm_serve_request_seconds",
                                   latency, backend=p.backend_name,
                                   cache=disposition)
            self._registry.counter("tpu_aggcomm_serve_requests",
                                   backend=p.backend_name,
                                   outcome="ok" if ok else "error")
        if self._slo is not None:
            self._slo.record(
                status="done" if ok else "fail", wall_s=latency,
                cache=disposition, deadline_ms=p.req.deadline_ms,
                batch={"seq": batch_seq, "n": batch_n,
                       "padded": batch_padded})
        # the batch correlation id rides in BOTH the journal record and
        # the trace instant (satellite of the flow contract: when the
        # journal tail is torn, inspect flow can still join on traces
        # alone) and matches the run event's run_context cid exactly
        cid = f"b{batch_seq}"
        trace.instant("serve.request", rid=p.rid, ok=ok,
                      backend=p.backend_name, cache=disposition,
                      batch_seq=batch_seq, batch_n=batch_n, cid=cid,
                      wall_s=latency, phases=dict(p.marks))
        if self._journal is not None:
            self._journal.record(
                {"request": p.rid}, fingerprint=self._fp,
                status="done" if ok else "fail",
                shape_keys=[repr(p.shape_key)], backend=p.backend_name,
                served_method=p.served_method,
                iter=p.req.iter_, latency_s=latency, batch_n=batch_n,
                cache=disposition, error=error, phases=dict(p.marks),
                batch_seq=batch_seq, batch_padded=batch_padded,
                cid=cid, queue_depth=p.depth_at_admit)
        p.event.set()

    # -- stats -------------------------------------------------------------
    @staticmethod
    def _quantiles(samples: list[float]) -> dict | None:
        if not samples:
            return None
        return {"p50": percentile(samples, 50.0),
                "p95": percentile(samples, 95.0),
                "p99": percentile(samples, 99.0)}

    def health(self) -> dict:
        """The lifecycle view — jax-free, answered in every state (the
        whole point: you ask a sick server how sick it is)."""
        with self._cv:
            return {"ok": True, "op": "health", "protocol": PROTOCOL,
                    "state": self._state,
                    "degraded_reason": self._degraded_reason,
                    "draining": self._state == "draining",
                    "queue_depth": len(self._queue),
                    "max_queue": self._max_queue,
                    "max_conns": self._max_conns,
                    "shed": dict(self._shed),
                    "completed": self._n_completed,
                    "errors": self._n_errors}

    def stats(self) -> dict:
        with self._cv:
            warm = list(self._warm_s)
            cold = list(self._cold_s)
            out = {"ok": True, "protocol": PROTOCOL,
                   "backend": self._backend, "port": self.port,
                   "fingerprint": self._fp,
                   "state": self._state,
                   "degraded_reason": self._degraded_reason,
                   "queue_depth": len(self._queue),
                   "max_queue": self._max_queue,
                   "completed": self._n_completed,
                   "errors": self._n_errors,
                   "shed": dict(self._shed),
                   "per_shape": {k: dict(v)
                                 for k, v in self._per_shape.items()},
                   "promotions": sorted(
                       ({"seq": v["seq"], "record": v["record"]}
                        for v in self._promotions.values()),
                       key=lambda r: r["seq"]),
                   "cache": dict(self._cache.stats(),
                                 compiles=self._n_compiles),
                   "batch": {"batches": self._n_batches,
                             "max_batch": self._max_batch_seen,
                             "batched_requests": self._n_batched_requests,
                             "dispatched_requests": self._fill_requests,
                             "padded_slots": self._fill_slots,
                             "fill_ratio": batch_fill_ratio(
                                 self._fill_requests, self._fill_slots),
                             "padding_waste_bytes": self._waste_bytes}}
        out["latency_s"] = self._quantiles(warm + cold)
        out["warm"] = {"n": len(warm),
                       "quantiles": self._quantiles(warm)}
        out["cold"] = {"n": len(cold),
                       "quantiles": self._quantiles(cold)}
        if self._metrics is not None:
            out["metrics_url"] = self._metrics.url
        return out

    # -- autopilot promotions ----------------------------------------------
    def _promo_sig(self, record: dict) -> tuple:
        """The schedule-resolution signature a promotion overrides —
        the SAME tuple _schedule_for keys on, built through the same
        parse_request path (identity, never guesswork)."""
        req = parse_request(dict(record["shape"]))
        return tuple(getattr(req, f) for f in req.shape_fields) \
            + (record["backend"],)

    def _refuse_swap(self, op: str, why: str) -> dict:
        print(f"serve: {op} refused: {why}", file=sys.stderr)
        return {"ok": False, "op": op, "error": f"{op} refused: {why}"}

    def swap(self, record) -> dict:
        """Apply one validated promotion record (the pilot's ``swap``
        op). The record is the ONLY currency accepted: structural
        validation, fingerprint match, registration of a synthesized
        winner, then a byte-exact ``--verify`` of the NEW method through
        the NORMAL request queue — the override installs only on a
        verified pass, and the installation is journaled by name."""
        from tpu_aggcomm.pilot.promote import validate_promotion_record
        problems = validate_promotion_record(record)
        if problems:
            return self._refuse_swap("swap", "; ".join(problems))
        if record["fingerprint"] != self._fp:
            return self._refuse_swap(
                "swap",
                f"record fingerprint {record['fingerprint'][:12]}… does "
                f"not match this server's manifest fingerprint "
                f"{self._fp[:12]}… — a win measured under a drifted "
                f"manifest does not transfer")
        backend = record["backend"]
        if backend not in SERVE_BACKENDS:
            return self._refuse_swap(
                "swap", f"backend {backend!r} is not servable; valid: "
                        f"{SERVE_BACKENDS}")
        with self._cv:
            state = self._state
        if state != "ready":
            return self._refuse_swap(
                "swap", f"server is {state.upper()} — promotions apply "
                        f"to a READY server only")
        from tpu_aggcomm.core.methods import METHODS
        if record["new_method"] not in METHODS \
                and record.get("composition"):
            from tpu_aggcomm.synth.register import (RegisterError,
                                                    register_composition)
            old_spec = METHODS.get(record["old_method"])
            if old_spec is None:
                return self._refuse_swap(
                    "swap", f"old_method {record['old_method']} is not "
                            f"a registered method on this server")
            try:
                register_composition(record["composition"],
                                     method_id=record["new_method"],
                                     direction=old_spec.direction.value)
            except (RegisterError, ValueError) as e:
                return self._refuse_swap(
                    "swap", f"cannot register composition "
                            f"{record['composition']!r} as method "
                            f"{record['new_method']}: {e}")
        try:
            sig = self._promo_sig(record)
            verify_req = parse_request(dict(
                record["shape"], method=record["new_method"],
                backend=backend, verify=True))
            schedule = request_schedule(verify_req)
            from tpu_aggcomm.core.schedule import schedule_shape_key
            shape_key = schedule_shape_key(schedule)
        except (ProtocolError, FaultSpecError, RepairError,
                ValueError) as e:
            return self._refuse_swap(
                "swap", f"promoted method does not compile for this "
                        f"shape: {type(e).__name__}: {e}")
        with self._cv:
            if sig in self._promotions:
                return self._refuse_swap(
                    "swap", f"a promotion (seq "
                            f"{self._promotions[sig]['seq']}) is "
                            f"already installed at this shape — demote "
                            f"it first")
            self._rid += 1
            rid = self._rid
            depth = len(self._queue) + self._reserved
        # the acceptance bar: the NEW method, byte-exact vs the local
        # oracle, through the normal queue (same batching, same
        # journal) — never a side-door execution
        pending = _Pending(verify_req, rid, schedule, shape_key,
                           backend, record["new_method"])
        pending.depth_at_admit = depth
        if self._journal is not None:
            shape = {f: getattr(verify_req, f)
                     for f in verify_req.shape_fields}
            self._journal.record(
                {"request": rid}, fingerprint=self._fp,
                status="admitted", shape=shape, backend=backend,
                iter=verify_req.iter_, deadline_ms=None,
                served_method=record["new_method"],
                purpose="swap-verify", t_unix=time.time(),
                queue_depth=depth)
        with self._cv:
            self._queue.append(pending)
            self._cv.notify_all()
        if not pending.event.wait(timeout=600.0):
            return self._refuse_swap(
                "swap", "verify leg timed out after 600 s — nothing "
                        "installed")
        resp = pending.response
        if not (resp.get("ok") and resp.get("verified") is True):
            return {"ok": True, "op": "swap", "installed": False,
                    "verified": resp.get("verified"),
                    "verify_rid": rid,
                    "error": resp.get("error")
                    or "verify leg did not return a verified pass — "
                       "nothing installed"}
        with self._cv:
            self._promo_seq += 1
            seq = self._promo_seq
            self._promotions[sig] = {"seq": seq, "record": record}
            # drop the resolved-schedule line so the next request at
            # this sig re-resolves through the promotion
            self._schedules.pop(sig, None)
        if self._journal is not None:
            self._journal.record(
                {"promotion": seq}, fingerprint=self._fp, status="swap",
                record=record, verify_rid=rid, t_unix=time.time())
        trace.instant("serve.swap", seq=seq,
                      old_method=record["old_method"],
                      new_method=record["new_method"],
                      new_cid=record["new_cid"],
                      win_ci_pct=record["win_ci_pct"])
        print(f"serve: promotion seq {seq}: m{record['old_method']} "
              f"({record['old_cid']}) -> m{record['new_method']} "
              f"({record['new_cid']}), win CI "
              f"[{record['win_ci_pct'][0]:.1f}%, "
              f"{record['win_ci_pct'][1]:.1f}%], verified rid {rid}",
              file=sys.stderr)
        return {"ok": True, "op": "swap", "installed": True,
                "verified": True, "seq": seq, "verify_rid": rid,
                "record": record}

    def demote(self, record, reason) -> dict:
        """Reverse one promotion. Accepts only the SAME record that
        installed it (byte-level identity — never a lookalike) plus a
        non-empty reason naming the regression verdict; re-installs the
        old entry by deleting the override and its resolved-schedule
        cache line, journaled by name."""
        from tpu_aggcomm.pilot.promote import (records_equal,
                                               validate_promotion_record)
        if not isinstance(reason, str) or not reason.strip():
            return self._refuse_swap(
                "demote", "a demotion must name the regression verdict "
                          "that motivates it (empty reason refused)")
        problems = validate_promotion_record(record)
        if problems:
            return self._refuse_swap("demote", "; ".join(problems))
        try:
            sig = self._promo_sig(record)
        except (ProtocolError, ValueError) as e:
            return self._refuse_swap(
                "demote", f"record shape does not parse: {e}")
        with self._cv:
            inst = self._promotions.get(sig)
            if inst is None:
                return self._refuse_swap(
                    "demote", "no promotion is installed at this shape")
            if not records_equal(inst["record"], record):
                return self._refuse_swap(
                    "demote", f"record does not match the installed "
                              f"promotion (seq {inst['seq']}) — "
                              f"demotion must present the SAME record "
                              f"that promoted, never a lookalike")
            seq = inst["seq"]
            del self._promotions[sig]
            self._schedules.pop(sig, None)
        if self._journal is not None:
            self._journal.record(
                {"promotion": seq}, fingerprint=self._fp,
                status="demote", record=record, reason=reason,
                t_unix=time.time())
        trace.instant("serve.demote", seq=seq,
                      old_method=record["old_method"],
                      new_method=record["new_method"], reason=reason)
        print(f"serve: demotion of promotion seq {seq}: "
              f"m{record['new_method']} -> m{record['old_method']} "
              f"restored — {reason}", file=sys.stderr)
        return {"ok": True, "op": "demote", "seq": seq,
                "restored_method": record["old_method"],
                "reason": reason}
