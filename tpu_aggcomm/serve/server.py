"""Aggregation-as-a-service: the persistent schedule server.

A long-lived process that admits pattern requests over a loopback
socket (serve/protocol.py), compiles each distinct schedule ONCE into a
cached chained rep (serve/cache.py + serve/executor.py) and batches
same-shape requests onto a new leading request axis — build-once/
execute-many, the persistent-communication optimization the one-shot
CLI cannot express (each invocation repays schedule build + jit +
tunnel warmup before its first rep).

Division of labor, enforced by the purity contract
(analysis/lint.PURE_PACKAGES + the poisoned-jax pin in
tests/test_serve.py): THIS module is control plane — sockets, queueing,
batch formation, cache policy, journal, metrics, retry — and never
imports jax; ``serve/executor.py`` is the one jax door. An operator
must be able to query ``stats`` on (and cleanly stop) a server whose
tunnel has wedged so badly that ``import jax`` hangs in fresh
processes.

Wired substrate, not regrown:

- **Cache keying** — ``schedule_shape_key`` + backend + manifest
  fingerprint (tune-cache lens); drift ⟹ named eviction + recompile.
- **Resilience** — every compile/dispatch goes through
  ``resilience.retry_call`` (unique site per batch), so tunnel-class
  transients retry with the seeded backoff, every attempt lands in
  trace + ledger, and ``replay_attempts`` reproduces the timeline.
- **Journal** — per-request accounting through ``RunJournal`` (append
  + fsync, torn-line-tolerant readers): a killed server loses at most
  the record being written.
- **Metrics** — the opt-in obs/export ``/metrics`` endpoint (OFF by
  default; the import itself is gated) serves queue depth and request
  latency histograms whose ``_exact`` summary quantiles use the same
  ``obs.metrics.percentile`` arithmetic as every other exposition.

The listener binds 127.0.0.1 ONLY — serving is for the operator's
machine, not the network (the obs/export discipline); a non-loopback
host refuses by name.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from tpu_aggcomm.faults import FaultSpecError, RepairError
from tpu_aggcomm.obs import ledger, trace
from tpu_aggcomm.obs.metrics import percentile
from tpu_aggcomm.resilience.journal import RunJournal
from tpu_aggcomm.resilience.policy import RetryPolicy, retry_call
from tpu_aggcomm.serve.cache import CompiledChainCache
from tpu_aggcomm.serve.protocol import (PROTOCOL, ProtocolError,
                                        parse_request, read_msg,
                                        request_schedule, send_msg)

__all__ = ["ScheduleServer", "SERVE_BACKENDS"]

#: Backends the server compiles chains for (mirrors
#: serve/executor.CHAIN_BACKENDS without importing the jax module).
SERVE_BACKENDS = ("jax_sim", "pallas_fused")

_LOOPBACK = ("127.0.0.1", "localhost")


class _Pending:
    """One enqueued request awaiting its batch."""

    __slots__ = ("req", "rid", "schedule", "shape_key", "backend_name",
                 "t0", "event", "response")

    def __init__(self, req, rid, schedule, shape_key, backend_name):
        self.req = req
        self.rid = rid
        self.schedule = schedule
        self.shape_key = shape_key
        self.backend_name = backend_name
        self.t0 = time.monotonic()
        self.event = threading.Event()
        self.response: dict = {}


class ScheduleServer:
    """The persistent aggregation server. Construct, then
    :meth:`serve_forever` (blocking) or :meth:`start` (thread)."""

    def __init__(self, *, backend: str = "jax_sim",
                 host: str = "127.0.0.1", port: int = 0,
                 max_batch: int = 8, batch_window_s: float = 0.005,
                 journal_path: str | None = None,
                 metrics_port: int | None = None,
                 retry_policy: RetryPolicy | None = None):
        import socket

        if host not in _LOOPBACK:
            raise ValueError(
                f"serve: refusing to bind {host!r} — the server binds "
                f"127.0.0.1 only (loopback telemetry discipline, "
                f"obs/export.py); tunnel remote clients through ssh")
        if backend not in SERVE_BACKENDS:
            raise ValueError(f"serve: unknown backend {backend!r}; "
                             f"valid: {SERVE_BACKENDS}")
        self._backend = backend
        self._max_batch = max(1, int(max_batch))
        self._batch_window_s = max(0.0, float(batch_window_s))
        self._retry_policy = retry_policy

        self._listener = socket.create_server((host, port))
        self._listener.settimeout(0.2)
        self.host = host
        self.port = self._listener.getsockname()[1]

        self._cv = threading.Condition()
        self._queue: deque[_Pending] = deque()
        self._stop = False
        self._schedules: dict[tuple, tuple] = {}   # shape sig -> (sched, key)
        self._cache = CompiledChainCache()
        self._man = ledger.manifest()
        from tpu_aggcomm.tune.cache import manifest_fingerprint
        self._fp = manifest_fingerprint(self._man)

        self._journal = RunJournal(journal_path) if journal_path else None
        if self._journal is not None:
            self._journal.begin_session(self._man)

        # counters (all under _cv's lock for mutation)
        self._rid = 0
        self._batch_seq = 0
        self._n_completed = 0
        self._n_errors = 0
        self._n_compiles = 0
        self._n_batches = 0
        self._n_batched_requests = 0
        self._max_batch_seen = 0
        self._warm_s: list[float] = []
        self._cold_s: list[float] = []

        # OFF by default; the /metrics import itself is the gate (the
        # zero-cost obs invariant) — armed, the hot path pays one
        # is-not-None check per request
        self._registry = None
        self._metrics = None
        env_armed = os.environ.get("TPU_AGGCOMM_METRICS_PORT", "").strip()
        if metrics_port is not None or env_armed:
            from tpu_aggcomm.obs.export import MetricsRegistry, serve_from_env
            registry = MetricsRegistry()
            self._metrics = serve_from_env(registry.render,
                                           port=metrics_port)
            if self._metrics is not None:
                self._registry = registry

        self._exec_thread = threading.Thread(
            target=self._executor_loop, name="tpu-aggcomm-serve-exec",
            daemon=True)
        self._accept_thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------
    def ready_info(self) -> dict:
        info = {"serve": "ready", "protocol": PROTOCOL,
                "host": self.host, "port": self.port,
                "backend": self._backend, "pid": os.getpid(),
                "max_batch": self._max_batch}
        if self._metrics is not None:
            info["metrics_url"] = self._metrics.url
        return info

    def serve_forever(self) -> None:
        """Accept loop; returns after :meth:`stop` (or a shutdown op)
        once the queue has drained."""
        import socket

        self._exec_thread.start()
        while not self._stop:
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()
        self._exec_thread.join(timeout=60.0)
        self.close()

    def start(self) -> threading.Thread:
        """Run :meth:`serve_forever` on a daemon thread (tests)."""
        self._accept_thread = threading.Thread(
            target=self.serve_forever, name="tpu-aggcomm-serve-accept",
            daemon=True)
        self._accept_thread.start()
        return self._accept_thread

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def close(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass
        if self._metrics is not None:
            self._metrics.close()
            self._metrics = None

    def join(self, timeout: float | None = None) -> None:
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=timeout)

    # -- request intake ----------------------------------------------------
    def _schedule_for(self, req, backend_name: str):
        """(schedule, shape_key) for a request — compiled and (under a
        fault spec) repaired once per distinct shape, jax-free."""
        sig = tuple(getattr(req, f if f != "fault" else "fault")
                    for f in req.shape_fields) + (backend_name,)
        with self._cv:
            hit = self._schedules.get(sig)
        if hit is not None:
            return hit
        schedule = request_schedule(req)
        from tpu_aggcomm.core.schedule import schedule_shape_key
        shape_key = schedule_shape_key(schedule)
        with self._cv:
            self._schedules[sig] = (schedule, shape_key)
        return schedule, shape_key

    def _handle_conn(self, conn) -> None:
        with conn:
            fh = conn.makefile("rw", encoding="utf-8")
            with fh:
                while True:
                    msg = read_msg(fh)
                    if msg is None:
                        return
                    op = msg.get("op")
                    if op == "run":
                        self._handle_run(fh, msg)
                    elif op == "stats":
                        send_msg(fh, self.stats())
                    elif op == "shutdown":
                        send_msg(fh, {"ok": True, "stopping": True})
                        self.stop()
                        return
                    else:
                        send_msg(fh, {"ok": False,
                                      "error": f"unknown op {op!r}"})

    def _handle_run(self, fh, msg: dict) -> None:
        try:
            req = parse_request(msg)
            backend_name = req.backend or self._backend
            if backend_name not in SERVE_BACKENDS:
                raise ProtocolError(
                    f"run request backend {backend_name!r} is not "
                    f"servable; valid: {SERVE_BACKENDS}")
            schedule, shape_key = self._schedule_for(req, backend_name)
        except (ProtocolError, FaultSpecError, RepairError,
                ValueError) as e:
            with self._cv:
                self._n_errors += 1
            send_msg(fh, {"ok": False, "error": str(e)})
            return
        with self._cv:
            if self._stop:
                send_msg(fh, {"ok": False,
                              "error": "server is shutting down"})
                return
            self._rid += 1
            pending = _Pending(req, self._rid, schedule, shape_key,
                               backend_name)
            self._queue.append(pending)
            depth = len(self._queue)
            self._cv.notify_all()
        if self._registry is not None:
            self._registry.gauge("tpu_aggcomm_serve_queue_depth", depth)
        pending.event.wait()
        send_msg(fh, pending.response)

    # -- the batching executor --------------------------------------------
    def _extract_same(self, head: _Pending, room: int) -> list[_Pending]:
        """Pull up to ``room`` queued requests sharing head's compiled
        program identity ((shape_key, backend) — iter/verify differ
        freely: same program, different payload)."""
        out: list[_Pending] = []
        keep: deque[_Pending] = deque()
        while self._queue and len(out) < room:
            p = self._queue.popleft()
            if (p.shape_key == head.shape_key
                    and p.backend_name == head.backend_name):
                out.append(p)
            else:
                keep.append(p)
        keep.extend(self._queue)
        self._queue.clear()
        self._queue.extend(keep)
        return out

    def _executor_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait(0.1)
                if not self._queue and self._stop:
                    return
                head = self._queue.popleft()
            batch = [head]
            deadline = time.monotonic() + self._batch_window_s
            while len(batch) < self._max_batch:
                with self._cv:
                    batch.extend(self._extract_same(
                        head, self._max_batch - len(batch)))
                if len(batch) >= self._max_batch \
                        or time.monotonic() >= deadline:
                    break
                time.sleep(min(0.0005,
                               max(deadline - time.monotonic(), 0.0)))
            if self._registry is not None:
                with self._cv:
                    depth = len(self._queue)
                self._registry.gauge("tpu_aggcomm_serve_queue_depth",
                                     depth)
            self._run_batch(batch)

    def _fail_batch(self, batch, disposition: str, err: str) -> None:
        for p in batch:
            self._finish(p, batch_n=len(batch), disposition=disposition,
                         compile_s=None, verified=None, error=err)

    def _run_batch(self, batch: list[_Pending]) -> None:
        head = batch[0]
        with self._cv:
            self._batch_seq += 1
            seq = self._batch_seq
            self._n_batches += 1
            self._max_batch_seen = max(self._max_batch_seen, len(batch))
            if len(batch) > 1:
                self._n_batched_requests += len(batch)
        from tpu_aggcomm.serve import executor

        entry, reason = self._cache.lookup(
            head.shape_key, head.backend_name, fingerprint=self._fp,
            manifest=self._man)
        compile_s = None
        disposition = "hit"
        if entry is None:
            disposition = "evict" if reason.startswith("manifest drift") \
                else "miss"
            print(f"serve: {reason}", file=sys.stderr)
            try:
                chain, compile_s = retry_call(
                    lambda: executor.build_chain(head.schedule,
                                                 head.backend_name),
                    site=f"serve.compile:b{seq}",
                    policy=self._retry_policy)
            except Exception as e:  # lint: broad-ok (fault isolation: a compile error is the batch's response, never the server's death)
                self._fail_batch(batch, disposition,
                                 f"compile failed: {type(e).__name__}: {e}")
                return
            ledger.record_compile(
                f"serve:{head.backend_name}:b{seq}", seconds=compile_s,
                kind="compile+warmup", backend=head.backend_name)
            entry = self._cache.put(
                head.shape_key, head.backend_name, fingerprint=self._fp,
                manifest=self._man, chain=chain, compile_s=compile_s)
            with self._cv:
                self._n_compiles += 1
        chain = entry["chain"]
        try:
            with trace.span("serve.batch", seq=seq, n=len(batch),
                            backend=head.backend_name,
                            method=head.schedule.method_id):
                results = retry_call(
                    lambda: executor.execute_batch(
                        chain, [p.req for p in batch]),
                    site=f"serve.dispatch:b{seq}",
                    policy=self._retry_policy)
        except Exception as e:  # lint: broad-ok (fault isolation: a dispatch error is the batch's response, never the server's death)
            self._fail_batch(batch, disposition,
                             f"dispatch failed: {type(e).__name__}: {e}")
            return
        for p, res in zip(batch, results):
            self._finish(p, batch_n=len(batch), disposition=disposition,
                         compile_s=compile_s, verified=res["verified"],
                         error=res["error"])

    def _finish(self, p: _Pending, *, batch_n: int, disposition: str,
                compile_s, verified, error) -> None:
        latency = time.monotonic() - p.t0
        ok = error is None
        p.response = {"ok": ok, "request_id": p.rid,
                      "verified": verified, "error": error,
                      "latency_s": latency, "batch_n": batch_n,
                      "cache": disposition, "compile_s": compile_s,
                      "backend": p.backend_name,
                      "shape_key": repr(p.shape_key)}
        with self._cv:
            if ok:
                self._n_completed += 1
                (self._warm_s if disposition == "hit"
                 else self._cold_s).append(latency)
            else:
                self._n_errors += 1
        if self._registry is not None:
            self._registry.observe("tpu_aggcomm_serve_request_seconds",
                                   latency, backend=p.backend_name,
                                   cache=disposition)
            self._registry.counter("tpu_aggcomm_serve_requests",
                                   backend=p.backend_name,
                                   outcome="ok" if ok else "error")
        if self._journal is not None:
            self._journal.record(
                {"request": p.rid}, fingerprint=self._fp,
                status="done" if ok else "fail",
                shape_keys=[repr(p.shape_key)], backend=p.backend_name,
                iter=p.req.iter_, latency_s=latency, batch_n=batch_n,
                cache=disposition, error=error)
        p.event.set()

    # -- stats -------------------------------------------------------------
    @staticmethod
    def _quantiles(samples: list[float]) -> dict | None:
        if not samples:
            return None
        return {"p50": percentile(samples, 50.0),
                "p95": percentile(samples, 95.0),
                "p99": percentile(samples, 99.0)}

    def stats(self) -> dict:
        with self._cv:
            warm = list(self._warm_s)
            cold = list(self._cold_s)
            out = {"ok": True, "protocol": PROTOCOL,
                   "backend": self._backend, "port": self.port,
                   "fingerprint": self._fp,
                   "queue_depth": len(self._queue),
                   "completed": self._n_completed,
                   "errors": self._n_errors,
                   "cache": dict(self._cache.stats(),
                                 compiles=self._n_compiles),
                   "batch": {"batches": self._n_batches,
                             "max_batch": self._max_batch_seen,
                             "batched_requests": self._n_batched_requests}}
        out["latency_s"] = self._quantiles(warm + cold)
        out["warm"] = {"n": len(warm),
                       "quantiles": self._quantiles(warm)}
        out["cold"] = {"n": len(cold),
                       "quantiles": self._quantiles(cold)}
        if self._metrics is not None:
            out["metrics_url"] = self._metrics.url
        return out
