"""The serve wire protocol: JSON lines over a loopback TCP socket.

One request per line, one response per line, both plain JSON objects —
the same torn-line-tolerant framing every journal in this repo uses, so
a client killed mid-send costs the server one unparsable line, never a
wedged connection state machine. The ops:

- ``{"op": "run", ...pattern fields...}`` — execute one rep of the
  requested (method, shape, fault, backend) and answer with the request
  latency, the cache disposition (hit/miss/evict) and the ``--verify``
  verdict when asked for. An optional ``deadline_ms`` (positive number)
  is a SOFT budget: the server sheds the request at an admission or
  batch boundary once it expires (never mid-kernel), answering
  ``{"ok": false, "shed": "deadline-expired", ...}`` by name.
- ``{"op": "stats"}`` — the server's counters (cache, batching, queue
  depth, latency quantiles) as one JSON object.
- ``{"op": "health"}`` — the lifecycle state machine's view: state
  (ready/degraded/draining), queue depth vs bound, per-reason shed
  counts. Answered even when the server is DEGRADED (jax-free op).
- ``{"op": "shutdown"}`` — graceful drain (stop admitting, finish
  in-flight batches, flush the journal) and stop.
- ``{"op": "swap", "record": {...}}`` — apply a validated promotion
  record (tpu_aggcomm/pilot/promote.py): the server re-verifies the new
  method byte-exact through its normal queue before installing the
  override, journals the promotion by name, and refuses anything the
  record's own evidence does not support.
- ``{"op": "demote", "record": {...}, "reason": "..."}`` — reverse a
  promotion by presenting the SAME record that installed it plus the
  regression verdict that motivates the rollback.

Everything in this module is jax-free (stdlib + core + faults): the
client side and the request -> Schedule compilation run precisely where
a wedged axon tunnel hangs ``import jax`` — an operator must be able to
ask a sick server for ``stats`` from a fresh process.
"""

from __future__ import annotations

import json
import socket
from dataclasses import dataclass, field

__all__ = ["PROTOCOL", "ProtocolError", "ServeRequest", "parse_request",
           "request_schedule", "read_msg", "send_msg", "ServeClient"]

#: Wire-protocol tag answered by the server's ready line and ``stats``.
PROTOCOL = "serve-proto-v1"


class ProtocolError(ValueError):
    """A malformed request/response — named field, never a traceback."""


#: ``run`` fields -> (required, default). Mirrors the CLI bench flags
#: (cli.py build_parser) so a request is a one-shot invocation minus the
#: process cold start.
_FIELDS = {
    "method": (True, None),
    "nprocs": (True, None),
    "cb_nodes": (True, None),
    "comm_size": (True, None),
    "data_size": (False, 2048),
    "proc_node": (False, 1),
    "agg_type": (False, 0),
    "barrier_type": (False, 0),
    "iter": (False, 0),
}


@dataclass(frozen=True)
class ServeRequest:
    """One validated ``run`` request."""

    method: int
    nprocs: int
    cb_nodes: int
    comm_size: int
    data_size: int = 2048
    proc_node: int = 1
    agg_type: int = 0
    barrier_type: int = 0
    iter_: int = 0
    verify: bool = False
    fault: str | None = None
    backend: str | None = None      # None = the server's default backend
    deadline_ms: float | None = None  # soft budget; None = no deadline

    #: Shape identity for batching/caching — everything that changes the
    #: compiled program. ``iter_`` and ``verify`` deliberately excluded:
    #: same program, different payload fill / post-processing.
    shape_fields: tuple = field(default=("method", "nprocs", "cb_nodes",
                                         "comm_size", "data_size",
                                         "proc_node", "agg_type",
                                         "barrier_type", "fault"),
                                init=False, repr=False, compare=False)


def parse_request(obj) -> ServeRequest:
    """Validate one ``run`` request dict into a :class:`ServeRequest`."""
    if not isinstance(obj, dict):
        raise ProtocolError("request must be a JSON object")
    vals = {}
    for name, (required, default) in _FIELDS.items():
        v = obj.get(name, default)
        if v is None:
            if required:
                raise ProtocolError(f"run request missing required "
                                    f"field {name!r}")
            continue
        if isinstance(v, bool) or not isinstance(v, int):
            raise ProtocolError(f"run request field {name!r} must be an "
                                f"integer, got {v!r}")
        vals["iter_" if name == "iter" else name] = int(v)
    fault = obj.get("fault")
    if fault is not None and not isinstance(fault, str):
        raise ProtocolError(f"run request field 'fault' must be a spec "
                            f"string, got {fault!r}")
    backend = obj.get("backend")
    if backend is not None and not isinstance(backend, str):
        raise ProtocolError(f"run request field 'backend' must be a "
                            f"string, got {backend!r}")
    verify = obj.get("verify", False)
    if not isinstance(verify, bool):
        raise ProtocolError(f"run request field 'verify' must be a "
                            f"bool, got {verify!r}")
    deadline_ms = obj.get("deadline_ms")
    if deadline_ms is not None:
        if isinstance(deadline_ms, bool) \
                or not isinstance(deadline_ms, (int, float)) \
                or deadline_ms <= 0:
            raise ProtocolError(f"run request field 'deadline_ms' must "
                                f"be a positive number, got "
                                f"{deadline_ms!r}")
        deadline_ms = float(deadline_ms)
    return ServeRequest(verify=verify, fault=fault or None,
                        backend=backend, deadline_ms=deadline_ms, **vals)


def request_schedule(req: ServeRequest):
    """Compile (and, under a fault spec, repair) the requested schedule.

    jax-free — core/methods + faults/repair only, the same build path
    ``harness/runner.py`` takes, so the server's compiled-chain cache is
    keyed by exactly the ``schedule_shape_key`` every other cache uses.
    Raises FaultSpecError/RepairError/ValueError with the runner's named
    messages; the server turns those into ``{"ok": false}`` responses.
    """
    from tpu_aggcomm.core.methods import METHODS, compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern

    if req.method not in METHODS:
        raise ProtocolError(f"unknown method id {req.method}; valid ids: "
                            f"{sorted(METHODS)}")
    pattern = AggregatorPattern(
        nprocs=req.nprocs, cb_nodes=req.cb_nodes, data_size=req.data_size,
        placement=req.agg_type, proc_node=req.proc_node,
        comm_size=req.comm_size)
    schedule = compile_method(req.method, pattern,
                              barrier_type=req.barrier_type)
    if req.fault:
        from tpu_aggcomm.faults import parse_fault, repair_schedule
        fspec = parse_fault(req.fault)
        if not fspec.empty:
            schedule = repair_schedule(schedule, fspec,
                                       barrier_type=req.barrier_type)
    return schedule


# ---------------------------------------------------------------------------
# Framing.

def send_msg(fh, obj: dict) -> None:
    """One JSON object, one line, flushed — the journal discipline."""
    fh.write(json.dumps(obj) + "\n")
    fh.flush()


def read_msg(fh) -> dict | None:
    """The next parsable JSON object line, or None at EOF. Unparsable
    lines are skipped (torn-line tolerance, resilience/journal.py)."""
    for line in fh:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            return rec
    return None


class ServeClient:
    """A blocking client for one server address (jax-free).

    Connects lazily and routes every roundtrip through the seeded
    classified retry (``resilience.retry_call``): tunnel-class
    transients — connection refused/reset, a per-request socket
    ``timeout`` expiring against a wedged server — reconnect and retry
    under the policy's bounded backoff; protocol/program errors raise
    on attempt 1 (a malformed request retried is malformed twice).
    Retrying a ``run`` is honest because requests are idempotent: the
    payload is a deterministic fill, so a duplicate execution returns
    the same bytes. A dead port therefore fails with a NAMED
    ConnectionRefusedError after the budget — never a silent forever-
    block (``retries_exhausted(e)`` distinguishes it).

    Usage::

        with ServeClient(port) as c:
            r = c.run(method=3, nprocs=32, cb_nodes=8, comm_size=4,
                      verify=True)
            assert r["ok"] and r["verified"]
    """

    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float | None = 300.0, retry_policy=None):
        self._addr = (host, port)
        self._timeout = timeout
        self._retry_policy = retry_policy
        self._sock = None
        self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _connect(self) -> None:
        if self._sock is None:
            self._sock = socket.create_connection(self._addr,
                                                  timeout=self._timeout)
            self._fh = self._sock.makefile("rw", encoding="utf-8")

    def _once(self, obj: dict) -> dict:
        """One send/recv on the current connection; any socket trouble
        closes it so the next retry attempt reconnects fresh."""
        self._connect()
        try:
            send_msg(self._fh, obj)
            resp = read_msg(self._fh)
        except OSError:
            self.close()
            raise
        if resp is None:
            self.close()
            raise ProtocolError("server closed the connection without "
                                "a response")
        return resp

    def _roundtrip(self, obj: dict) -> dict:
        from tpu_aggcomm.resilience.policy import retry_call
        op = str(obj.get("op", "?"))
        return retry_call(lambda: self._once(obj),
                          site=f"serve:client:{op}",
                          policy=self._retry_policy)

    def run(self, **fields) -> dict:
        return self._roundtrip(dict(fields, op="run"))

    def stats(self) -> dict:
        return self._roundtrip({"op": "stats"})

    def health(self) -> dict:
        return self._roundtrip({"op": "health"})

    def shutdown(self) -> dict:
        return self._roundtrip({"op": "shutdown"})

    def swap(self, record: dict) -> dict:
        """Apply a promotion record (tpu_aggcomm/pilot/promote.py). The
        server refuses anything that fails validation and re-verifies
        the new method byte-exact before installing."""
        return self._roundtrip({"op": "swap", "record": record})

    def demote(self, record: dict, reason: str) -> dict:
        """Reverse a promotion by the SAME record that installed it;
        ``reason`` must name the regression verdict."""
        return self._roundtrip({"op": "demote", "record": record,
                                "reason": reason})

    def close(self) -> None:
        sock, fh = self._sock, self._fh
        self._sock = self._fh = None
        try:
            if fh is not None:
                fh.close()
        except OSError:
            pass
        try:
            if sock is not None:
                sock.close()
        except OSError:
            pass
