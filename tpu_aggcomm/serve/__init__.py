"""Aggregation-as-a-service: persistent schedule server + compiled-chain
cache + same-shape request batching + overload protection.

Package layout (the purity split is the point — see
analysis/lint.PURE_PACKAGES):

- ``protocol.py`` — JSON-lines wire protocol + retrying client,
  jax-free.
- ``cache.py`` — the compiled-chain cache with manifest-drift eviction
  (tune-cache keying), jax-free.
- ``server.py`` — the control plane: socket accept loop (bounded
  handler pool), admission control + deadline shedding, lifecycle
  state machine (ready/degraded/draining), batching queue, journal,
  metrics, retry; jax-free.
- ``recover.py`` — ``--recover`` journal replay + cache pre-warm
  planning (drift = named skip), jax-free.
- ``executor.py`` — THE one jax door: compile chains, vmap-batch
  same-shape requests, recovery pre-warm compiles (declared in
  PURE_PACKAGES like tune/measure.py).
"""

from tpu_aggcomm.serve.cache import CompiledChainCache
from tpu_aggcomm.serve.protocol import (PROTOCOL, ProtocolError,
                                        ServeClient, ServeRequest,
                                        parse_request, request_schedule)
from tpu_aggcomm.serve.recover import (prewarm_plan, render_recovery,
                                       replay_journal)
from tpu_aggcomm.serve.server import (SERVE_BACKENDS, SERVE_STATES,
                                      ScheduleServer)

__all__ = ["PROTOCOL", "ProtocolError", "ServeClient", "ServeRequest",
           "parse_request", "request_schedule", "CompiledChainCache",
           "ScheduleServer", "SERVE_BACKENDS", "SERVE_STATES",
           "replay_journal", "prewarm_plan", "render_recovery"]
