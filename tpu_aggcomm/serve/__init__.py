"""Aggregation-as-a-service: persistent schedule server + compiled-chain
cache + same-shape request batching.

Package layout (the purity split is the point — see
analysis/lint.PURE_PACKAGES):

- ``protocol.py`` — JSON-lines wire protocol + client, jax-free.
- ``cache.py`` — the compiled-chain cache with manifest-drift eviction
  (tune-cache keying), jax-free.
- ``server.py`` — the control plane: socket accept loop, batching
  queue, journal, metrics, retry; jax-free.
- ``executor.py`` — THE one jax door: compile chains, vmap-batch
  same-shape requests (declared in PURE_PACKAGES like tune/measure.py).
"""

from tpu_aggcomm.serve.cache import CompiledChainCache
from tpu_aggcomm.serve.protocol import (PROTOCOL, ProtocolError,
                                        ServeClient, ServeRequest,
                                        parse_request, request_schedule)
from tpu_aggcomm.serve.server import SERVE_BACKENDS, ScheduleServer

__all__ = ["PROTOCOL", "ProtocolError", "ServeClient", "ServeRequest",
           "parse_request", "request_schedule", "CompiledChainCache",
           "ScheduleServer", "SERVE_BACKENDS"]
