"""The compiled-chain cache: build-once/execute-many behind the server.

Entries are keyed ``(schedule_shape_key, backend)`` — the exact key
every compiled-program cache in this repo uses (jax_sim._cache, the
tune cache, the resume journals); the canonical fault spec rides inside
``schedule_shape_key`` so a repaired program can never alias its
healthy sibling. Each entry is additionally stamped with the manifest
fingerprint of the environment that compiled it (tune/cache.py
``manifest_fingerprint``: no drift ⟺ same fingerprint). A lookup under
a drifted manifest EVICTS the entry and names the divergent keys via
``diff_manifests`` — the same reason string discipline as
``tune.cache.lookup`` and ``RunJournal.completed`` — because a chain
compiled for a different jax/libtpu/device must recompile, never serve
stale.

jax-free: the cache stores the executor's compiled chains as opaque
values and never looks inside them — eviction policy must keep working
where ``import jax`` hangs.
"""

from __future__ import annotations

import threading

from tpu_aggcomm.obs.ledger import diff_manifests

__all__ = ["CompiledChainCache"]


class CompiledChainCache:
    """In-process cache of compiled chained reps, drift-evicting."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[tuple, dict] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.prewarmed = 0

    @staticmethod
    def _key(shape_key, backend: str) -> tuple:
        return (shape_key, str(backend))

    def lookup(self, shape_key, backend: str, *, fingerprint: str,
               manifest: dict | None = None
               ) -> tuple[dict | None, str | None]:
        """``(entry, None)`` on a fingerprint-valid hit; ``(None,
        reason)`` on a miss — where a drift miss EVICTS the stale entry
        and ``reason`` names the drifted manifest keys (tune-cache
        semantics: the caller recompiles, the log says why)."""
        key = self._key(shape_key, backend)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None, (f"no cached chain for {backend}:"
                              f"{shape_key!r} — compiling")
            if e["fingerprint"] != fingerprint:
                del self._entries[key]
                self.evictions += 1
                self.misses += 1
                drift = diff_manifests(e.get("manifest"), manifest)
                keys = ", ".join(d["key"] for d in drift[:4]) or \
                    f"fingerprint {e['fingerprint']} != {fingerprint}"
                more = f" (+{len(drift) - 4} more)" if len(drift) > 4 \
                    else ""
                return None, (f"manifest drift vs cached chain "
                              f"{backend}:{shape_key!r}: {keys}{more} "
                              f"— evicted, recompiling")
            self.hits += 1
            e["hits"] += 1
            return e, None

    def put(self, shape_key, backend: str, *, fingerprint: str,
            manifest: dict | None, chain, compile_s: float,
            prewarmed: bool = False) -> dict:
        """Install a freshly compiled chain (replaces any entry the
        drift eviction left behind). ``prewarmed=True`` marks a chain
        rebuilt from a recovery journal (``--recover``) rather than a
        live request — same keying lens, counted separately so the
        recovery report is auditable."""
        entry = {"chain": chain, "fingerprint": str(fingerprint),
                 "manifest": manifest, "compile_s": float(compile_s),
                 "hits": 0, "prewarmed": bool(prewarmed)}
        with self._lock:
            self._entries[self._key(shape_key, backend)] = entry
            if prewarmed:
                self.prewarmed += 1
        return entry

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions,
                    "prewarmed": self.prewarmed}
