"""Property-based tests (hypothesis) over the pure layers.

The reference validates its 22 algorithms by redundancy — they all compute
the same exchange (SURVEY.md §4.5). These properties pin that invariant
over randomized configurations instead of hand-picked ones: every compiled
schedule must cover exactly the pattern's edge set with matched sends and
receives (`Schedule.validate`), the oracle must deliver verified payloads,
and the collective lowerings must preserve the edge set.
"""

import numpy as np
import pytest

# the container image does not ship hypothesis (and nothing may be pip
# installed there); skip the whole module with a precise reason instead
# of failing collection
pytest.importorskip(
    "hypothesis",
    reason="hypothesis not installed in this environment (no network "
           "installs allowed); property tests need it")
from hypothesis import HealthCheck, assume, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from tpu_aggcomm.core.methods import METHODS, compile_method, method_ids
from tpu_aggcomm.core.pattern import (AggregatorPattern,
                                      create_aggregator_list, node_robin_map)

NON_TAM = [m for m in method_ids(include_dead=True) if not METHODS[m].tam]

COMMON = dict(deadline=None,
              suppress_health_check=[HealthCheck.too_slow])


@st.composite
def pattern_cfg(draw, max_procs: int = 12):
    nprocs = draw(st.integers(2, max_procs))
    cb_nodes = draw(st.integers(1, nprocs))
    placement = draw(st.integers(0, 3))
    divisors = [d for d in range(1, nprocs + 1) if nprocs % d == 0]
    proc_node = draw(st.sampled_from(divisors))
    comm_size = draw(st.integers(1, 2 * nprocs))
    data_size = draw(st.integers(1, 8))
    # placements must yield distinct aggregators for the pattern to be
    # well-formed (the reference silently degenerates otherwise)
    ranks = create_aggregator_list(nprocs, cb_nodes, placement, proc_node)
    assume(len(set(int(r) for r in ranks)) == cb_nodes)
    return AggregatorPattern(nprocs=nprocs, cb_nodes=cb_nodes,
                             data_size=data_size, comm_size=comm_size,
                             placement=placement, proc_node=proc_node)


@settings(max_examples=60, **COMMON)
@given(nprocs=st.integers(1, 64), cb=st.integers(1, 64),
       placement=st.integers(0, 3), proc_node=st.integers(1, 8))
def test_aggregator_list_in_range(nprocs, cb, placement, proc_node):
    assume(cb <= nprocs)
    ranks = create_aggregator_list(nprocs, cb, placement, proc_node)
    assert len(ranks) == cb
    assert ((ranks >= 0) & (ranks < nprocs)).all()


@settings(max_examples=40, **COMMON)
@given(nprocs=st.integers(1, 96), proc_node=st.integers(1, 12))
def test_node_robin_map_is_permutation(nprocs, proc_node):
    assume(nprocs % proc_node == 0)
    m = node_robin_map(nprocs, proc_node)
    assert sorted(int(x) for x in m) == list(range(nprocs))


@settings(max_examples=50, **COMMON)
@given(p=pattern_cfg(), method=st.sampled_from(NON_TAM))
def test_every_schedule_validates(p, method):
    sched = compile_method(method, p)
    sched.validate()  # edge coverage + send/recv matching


@settings(max_examples=50, **COMMON)
@given(p=pattern_cfg())
def test_dense_counts_match_pattern(p):
    send, recv = p.dense_counts()
    np.testing.assert_array_equal(recv, send.T)
    # total bytes = every sender -> every receiver, one slab each
    assert send.sum() == len(p.senders) * len(p.receivers) * p.data_size
    # sender rows: senders address every receiver; others are zero
    senders = set(int(s) for s in p.senders)
    for r in range(p.nprocs):
        row = send[r].sum()
        assert row == (len(p.receivers) * p.data_size if r in senders else 0)


@settings(max_examples=40, **COMMON)
@given(p=pattern_cfg(), method=st.sampled_from(NON_TAM))
def test_color_lowering_preserves_edges(p, method):
    from tpu_aggcomm.backends.jax_ici import lower_schedule
    sched = compile_method(method, p)
    if sched.collective:
        return
    low = lower_schedule(sched)
    got = sorted((int(s), int(d))
                 for c in low.perms for (s, d) in c)
    want = sorted((int(s), int(d)) for s, d in sched.data_edges()[:, :2])
    assert got == want
    for color in low.perms:   # each color: a partial permutation
        srcs = [s for s, _ in color]
        dsts = [d for _, d in color]
        assert len(set(srcs)) == len(srcs) and len(set(dsts)) == len(dsts)


@settings(max_examples=25, **COMMON)
@given(p=pattern_cfg(max_procs=10), method=st.sampled_from(NON_TAM),
       iter_=st.integers(0, 3))
def test_oracle_delivery_verifies(p, method, iter_):
    from tpu_aggcomm.backends.local import LocalBackend
    LocalBackend().run(compile_method(method, p), verify=True, iter_=iter_)


@settings(max_examples=15, **COMMON)
@given(p=pattern_cfg(max_procs=8), direction_m=st.sampled_from([15, 16]),
       iter_=st.integers(0, 2))
def test_tam_oracle_verifies(p, direction_m, iter_):
    from tpu_aggcomm.harness.verify import verify_recv
    from tpu_aggcomm.tam.engine import tam_oracle
    sched = compile_method(direction_m, p)
    recv = tam_oracle(sched, iter_=iter_)
    verify_recv(sched.pattern, recv, iter_)


@settings(max_examples=8, **COMMON)
@given(p=pattern_cfg(max_procs=8), method=st.sampled_from(NON_TAM))
def test_jax_sim_matches_oracle_random(p, method):
    from tpu_aggcomm.backends.jax_sim import JaxSimBackend
    from tpu_aggcomm.backends.local import LocalBackend
    sched = compile_method(method, p)
    recv_s, _ = JaxSimBackend().run(sched, verify=True)
    recv_o, _ = LocalBackend().run(sched, verify=True)
    for a, b in zip(recv_s, recv_o):
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(a, b)
