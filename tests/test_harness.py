"""Reporting/CLI/runner tests: console block format, results.csv schema,
per-rank CSV dumps, pt2pt, CLI flag grammar."""

import io
import os

import numpy as np
import pytest

from tpu_aggcomm.cli import build_parser, main
from tpu_aggcomm.core.methods import method_ids
from tpu_aggcomm.harness.report import save_all_timing, summarize_results
from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment
from tpu_aggcomm.harness.timer import Timer, max_reduce


class TestTimer:
    def test_max_reduce(self):
        a = Timer(post_request_time=1.0, total_time=2.0)
        b = Timer(post_request_time=0.5, total_time=3.0, barrier_time=1.0)
        m = max_reduce([a, b])
        assert m.post_request_time == 1.0
        assert m.total_time == 3.0
        assert m.barrier_time == 1.0


class TestReport:
    def test_console_block_format(self, tmp_path):
        out = io.StringIO()
        t = Timer(post_request_time=0.011989, send_wait_all_time=0.045943,
                  total_time=0.055115)
        block = summarize_results(32, 14, 2048, 3, 1, 1, None, "All to many",
                                  t, t, out=out)
        # match the reference's %lf console lines (README.md:44-49)
        assert "| All to many max total time = 0.055115\n" in block
        assert "| All to many rank 0 request post time = 0.011989\n" in block

    def test_results_csv_roundtrip(self, tmp_path):
        path = str(tmp_path / "results.csv")
        t = Timer(total_time=1.5)
        summarize_results(8, 3, 64, 2, 1, 1, path, "All to many", t, t,
                          out=io.StringIO())
        summarize_results(8, 3, 64, 2, 1, 1, path, "Many to all", t, t,
                          out=io.StringIO())
        lines = open(path).read().splitlines()
        assert lines[0].startswith("Method,# of processes,")
        assert len(lines) == 3  # header + 2 rows (append mode, header once)
        assert lines[1].split(",")[0] == "All to many"

    def test_save_all_timing(self, tmp_path):
        rep_timers = [[Timer(total_time=float(r)) for r in range(4)]
                      for _ in range(2)]
        files = save_all_timing(4, 2, 7, rep_timers, prefix="x_",
                                outdir=str(tmp_path))
        assert len(files) == 4
        total = open(os.path.join(tmp_path, "x_total_times_7.csv")).read()
        rows = total.splitlines()
        assert rows[2].startswith("2,2.000000,2.000000")


class TestRunner:
    def test_run_all_methods_local(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)  # m=13 writes per-rank CSVs to cwd
        out = io.StringIO()
        cfg = ExperimentConfig(nprocs=8, cb_nodes=3, data_size=32,
                               comm_size=3, verify=True,
                               results_csv=str(tmp_path / "r.csv"))
        records = run_experiment(cfg, out=out)
        # all dispatched non-TAM methods (TAM excluded until engine lands)
        assert len(records) >= 18
        text = out.getvalue()
        assert "total number of processes = 8, cb_nodes = 3" in text
        assert "| All to many balanced max total time = " in text

    def test_single_method_jax(self, tmp_path):
        cfg = ExperimentConfig(nprocs=8, cb_nodes=3, data_size=16,
                               method=1, backend="jax_ici", verify=True,
                               results_csv=str(tmp_path / "r.csv"))
        records = run_experiment(cfg, out=io.StringIO())
        assert len(records) == 1
        assert records[0]["max_timer"].total_time > 0

    def test_m13_writes_per_rank_csvs(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        cfg = ExperimentConfig(nprocs=8, cb_nodes=3, data_size=16, method=13,
                               comm_size=2, ntimes=2, verify=True,
                               results_csv=None)
        run_experiment(cfg, out=io.StringIO())
        assert os.path.exists("total_times_2.csv")
        assert len(open("total_times_2.csv").read().splitlines()) == 8


class TestCli:
    def test_parser_reference_flags(self):
        ap = build_parser()
        a = ap.parse_args(["-m", "1", "-a", "14", "-d", "2048", "-c", "3",
                           "-i", "2", "-k", "1", "-p", "1", "-t", "1",
                           "-r", "pre_", "-b", "2"])
        assert (a.method, a.cb_nodes, a.data_size, a.comm_size) == (1, 14, 2048, 3)
        assert (a.iters, a.ntimes, a.proc_node, a.agg_type) == (2, 1, 1, 1)
        assert (a.prefix, a.barrier_type) == ("pre_", 2)

    def test_cli_end_to_end(self, tmp_path, capsys):
        rc = main(["-n", "8", "-m", "2", "-a", "3", "-d", "64", "--verify",
                   "--results-csv", str(tmp_path / "res.csv")])
        assert rc == 0
        out = capsys.readouterr().out
        assert "| Many to all max total time = " in out
        assert os.path.exists(tmp_path / "res.csv")

    def test_cli_pt2pt(self, tmp_path, capsys, monkeypatch):
        monkeypatch.chdir(tmp_path)
        rc = main(["pt2pt", "-d", "256", "-k", "3", "-i", "4"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "mean = " in out and "std = " in out
        assert len(open("sendrecv_results.csv").read().splitlines()) == 3


class TestRunAllEveryBackend:
    """VERDICT r1 item 2: the reference's default mode is run-all
    (mpi_test.c:2181-2338, `-m 0`) and it completes on every backend —
    including the TAM methods 15/16, which route to a hierarchical engine
    when the selected backend executes only flat schedules."""

    @pytest.mark.parametrize("backend", ["local", "native", "jax_sim",
                                         "jax_ici", "pallas_dma"])
    def test_run_all(self, backend, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)  # m=13 writes per-rank CSVs to cwd
        out = io.StringIO()
        cfg = ExperimentConfig(nprocs=8, cb_nodes=3, data_size=32,
                               comm_size=3, backend=backend, verify=True,
                               results_csv=str(tmp_path / "r.csv"))
        records = run_experiment(cfg, out=out)
        ran = {r["method"] for r in records}
        assert {15, 16} <= ran, f"TAM methods missing from run-all: {ran}"
        assert len(records) == len(method_ids())
        text = out.getvalue()
        assert "| All to many TAM max total time = " in text
        assert "| Many to all TAM max total time = " in text


class TestPt2pt:
    """pt2pt hardening (VERDICT r1 item 8): scan-chained transfers keep
    compile time constant in -i, and --chained gives differenced
    per-transfer timing."""

    def test_large_runs_compiles_fast(self, tmp_path, monkeypatch, capsys):
        # reference-scale -i (mpi_sendrecv_test.c sweeps into the
        # thousands): a Python-unrolled loop would take minutes to trace
        import time

        from tpu_aggcomm.harness.pt2pt import pt2pt_statistics

        monkeypatch.chdir(tmp_path)
        t0 = time.perf_counter()
        r = pt2pt_statistics(64, 2, 5000, out=io.StringIO())
        elapsed = time.perf_counter() - t0
        assert len(r["times"]) == 2
        assert elapsed < 60, f"scan chain should compile fast, took {elapsed:.0f}s"

    def test_chained_mode(self, tmp_path, monkeypatch):
        # VERDICT r3 item 7: each rep is an INDEPENDENT differenced
        # window, so rows are real samples (reference output is mean/std
        # over reps, mpi_sendrecv_test.c:52-64) — distinct values with
        # overwhelming probability, never synthetic copies of one mean.
        from tpu_aggcomm.harness.pt2pt import pt2pt_statistics

        monkeypatch.chdir(tmp_path)
        r = pt2pt_statistics(64, 3, 10, chained=True, out=io.StringIO())
        assert len(r["times"]) == 3
        assert all(t > 0 for t in r["times"])
        assert len(set(r["times"])) > 1, \
            "chained reps must be independent measurements, not copies"
        assert r["std"] > 0

    def test_cli_chained_flag(self, tmp_path, capsys, monkeypatch):
        monkeypatch.chdir(tmp_path)
        rc = main(["pt2pt", "-d", "64", "-k", "2", "-i", "8", "--chained"])
        assert rc == 0
        assert "mean = " in capsys.readouterr().out
