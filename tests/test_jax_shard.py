"""Sharded rank-axis backend (backends/jax_shard.py): B logical ranks per
device over the virtual 8-device CPU mesh — the multi-chip realization of
the reference's 16,384-rank flagship scale (script_theta_*.sh:3,11;
DISTRIBUTED.md "Mapping the Theta flagship to a pod"; VERDICT r2 item 3)."""

import numpy as np
import pytest

from tpu_aggcomm.backends.jax_shard import (JaxShardBackend,
                                            block_round_tables,
                                            _schedule_edges)
from tpu_aggcomm.backends.local import LocalBackend
from tpu_aggcomm.core.methods import METHODS, compile_method, method_ids
from tpu_aggcomm.core.pattern import AggregatorPattern

NON_TAM = [m for m in method_ids(include_dead=True) if not METHODS[m].tam]


@pytest.mark.parametrize("method", NON_TAM)
def test_shard_matches_oracle(method):
    """Every method, 16 ranks over 8 devices (B=2): byte-exact vs the
    local oracle."""
    p = AggregatorPattern(16, 5, data_size=32, comm_size=3)
    sched = compile_method(method, p)
    recv_s, timers = JaxShardBackend().run(sched, verify=True, iter_=0)
    recv_o, _ = LocalBackend().run(sched, verify=True, iter_=0)
    for a, b in zip(recv_s, recv_o):
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(a, b)
    assert timers[0].total_time > 0


@pytest.mark.parametrize("method", [15, 16])
def test_shard_tam_sharded_route(method):
    """TAM methods run the XLA-partitioned 3-hop route with the rank axis
    sharded; delivery stays byte-exact."""
    p = AggregatorPattern(16, 5, data_size=32, comm_size=3, proc_node=4)
    sched = compile_method(method, p)
    recv_s, timers = JaxShardBackend().run(sched, verify=True, iter_=0)
    recv_o, _ = LocalBackend().run(sched, verify=True, iter_=0)
    for a, b in zip(recv_s, recv_o):
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("method,cs,bt", [(1, 1, 0), (13, 2, 2), (17, 3, 0)])
def test_shard_throttle_and_barriers(method, cs, bt):
    """Throttled rounds and in-round barriers survive the block lowering."""
    p = AggregatorPattern(16, 5, data_size=16, comm_size=cs, proc_node=2)
    sched = compile_method(method, p, barrier_type=bt)
    recv_s, _ = JaxShardBackend().run(sched, verify=True)
    recv_o, _ = LocalBackend().run(sched, verify=True)
    for a, b in zip(recv_s, recv_o):
        if a is not None:
            np.testing.assert_array_equal(a, b)


def test_shard_uneven_device_split():
    """nprocs not divisible by the pool size: the mesh shrinks to the
    largest divisor (12 ranks -> 6 devices, B=2)."""
    p = AggregatorPattern(12, 4, data_size=32, comm_size=4)
    b = JaxShardBackend()
    sched = compile_method(1, p)
    recv, _ = b.run(sched, verify=True)
    _fn, mesh, ndev, bsz, _extra = b._compiled(sched)
    assert ndev == 6 and bsz == 2


def test_shard_explicit_ranks_per_device():
    p = AggregatorPattern(16, 3, data_size=32, comm_size=8)
    b = JaxShardBackend(ranks_per_device=4)
    sched = compile_method(2, p)
    recv, _ = b.run(sched, verify=True)
    _fn, mesh, ndev, bsz, _extra = b._compiled(sched)
    assert ndev == 4 and bsz == 4
    with pytest.raises(ValueError, match="must divide"):
        JaxShardBackend(ranks_per_device=5).run(sched)


def test_block_tables_pad_and_order():
    """Hand-checked block tables: 4 ranks over 2 devices, one round with
    an uneven pair load pads to M and lands b-major."""
    edges = np.array([
        # src dst sslot dslot round
        [0, 2, 0, 0, 0],
        [1, 2, 0, 1, 0],   # dev0 -> dev1: 2 messages
        [2, 1, 0, 2, 0],   # dev1 -> dev0: 1 message
    ], dtype=np.int64)
    send_base = np.array([0, 1, 0, 1])     # 1 send slot per rank, bsz=2
    recv_base = np.array([0, 4, 0, 4])     # 4 recv slots per rank
    tabs = block_round_tables(edges, ndev=2, bsz=2, send_base=send_base,
                              recv_base=recv_base, F=9)
    (r, pack, scat, M) = tabs[0]
    assert r == 0 and M == 2
    assert pack.shape == (2, 2, 2)
    # dev0 ships local ranks 0,1 slot 0 to dev1
    assert list(pack[0, 1]) == [0, 1]
    assert list(pack[0, 0]) == [-1, -1]
    # dev1 (b=1) lands dev0's block at local rank 0 slots 0,1
    assert list(scat[1, 0]) == [0, 1]
    assert list(scat[0, 0]) == [8, 8]      # trash = F - 1


def test_collective_edges_are_pattern_volume():
    """m=8's synthesized single round carries exactly nprocs*cb_nodes
    edges — pattern volume, not the dense n^2."""
    p = AggregatorPattern(16, 5, data_size=32)
    sched = compile_method(8, p)
    edges = _schedule_edges(sched)
    assert len(edges) == 16 * 5
    assert set(edges[:, 4]) == {0}


def test_flagship_rank_count_m1_m8():
    """16,384 logical ranks (2,048 per device) — the reference's flagship
    rank count (script_theta_all_to_many_256.sh:3) — verified end-to-end
    on the 8-device mesh for the throttled m=1 and the dense m=8.
    (a=16/d=8 keeps the suite fast; the full a=256 flagship shape is the
    RESULTS_TPU.md / DISTRIBUTED.md artifact.)"""
    p = AggregatorPattern(16384, 16, data_size=8, comm_size=8192)
    b = JaxShardBackend()
    for m in (1, 8):
        sched = compile_method(m, p)
        recv, timers = b.run(sched, verify=True)
        assert timers[0].total_time > 0
    _fn, mesh, ndev, bsz, _extra = b._compiled(sched)
    assert ndev == 8 and bsz == 2048


def test_shard_chained_measurement():
    """Serial-chained differenced per-rep measurement on the device mesh
    (the multi-chip analog of jax_sim --chained): positive per-rep time,
    attributed phase columns, delivery still verified."""
    p = AggregatorPattern(16, 5, data_size=32, comm_size=4)
    b = JaxShardBackend()
    sched = compile_method(1, p)
    recv, timers = b.run(sched, verify=True, chained=True, ntimes=2)
    assert timers[0].total_time > 0
    assert timers[0].post_request_time > 0
    per = b.measure_per_rep(sched)          # cached, no remeasure
    assert np.isclose(timers[0].total_time, per * 2)
    # TAM + chained routes through the blocked engine's chain scaffold
    # (round 5; it used to raise) — verified delivery, chained provenance
    recv_t, _ = b.run(compile_method(15, p), chained=True, verify=True)
    assert b.last_provenance == ("jax_shard", "attributed-chained")


def test_block_tables_property_random():
    """Property: for random edge sets, every edge appears in exactly one
    (pack, scat) position, pack/scat positions correspond (same (a,b,j)),
    and all padding lands on -1 / trash."""
    rng = np.random.default_rng(7)
    ndev, bsz = 4, 3
    n = ndev * bsz
    n_sslots, n_rslots = 3, n
    send_base = np.arange(n) % bsz * n_sslots
    recv_base = np.arange(n) % bsz * n_rslots
    F = bsz * n_rslots + 1
    for _trial in range(5):
        E = int(rng.integers(1, 40))
        src = rng.integers(0, n, E)
        # unique (src, dst) pairs; dslot unique per (dst) for uniqueness
        pairs = set()
        rows = []
        for s in src:
            d = int(rng.integers(0, n))
            if (int(s), d) in pairs:
                continue
            pairs.add((int(s), d))
            rows.append((int(s), d, int(rng.integers(0, n_sslots)),
                         len([1 for (ss, dd) in pairs if dd == d]) - 1, 0))
        edges = np.array(rows, dtype=np.int64)
        tabs = block_round_tables(edges, ndev=ndev, bsz=bsz,
                                  send_base=send_base,
                                  recv_base=recv_base, F=F)
        (_r, pack, scat, M) = tabs[0]
        # scat[b, a, j] corresponds to pack[a, b, j]
        got = set()
        for a in range(ndev):
            for bdev in range(ndev):
                for j in range(M):
                    pk = int(pack[a, bdev, j])
                    sc = int(scat[bdev, a, j])
                    if pk < 0:
                        assert sc == F - 1          # pad -> trash
                    else:
                        got.add((a, bdev, pk, sc))
                        assert sc != F - 1
        assert len(got) == len(edges)
        # every edge is represented with its encoded flat indices
        want = {(s // bsz, d // bsz,
                 int(send_base[s]) + sl, int(recv_base[d]) + dl)
                for (s, d, sl, dl, _rr) in rows}
        assert got == want


def test_shard_scanned_rounds_byte_exact():
    """>=32 barrier-free rounds take the lax.scan lowering; delivery stays
    byte-exact vs the local oracle."""
    p = AggregatorPattern(64, 5, data_size=16, comm_size=1)   # 64 rounds
    sched = compile_method(1, p)
    recv_s, _ = JaxShardBackend().run(sched, verify=True)
    recv_o, _ = LocalBackend().run(sched, verify=True)
    for a, b in zip(recv_s, recv_o):
        if a is not None:
            np.testing.assert_array_equal(a, b)


def test_flagship_throttled_scan_rounds():
    """Flagship rank count with a mid-grid throttle (c=256 -> 64 scanned
    rounds) — the exact cell shape of the Theta sweep."""
    p = AggregatorPattern(16384, 16, data_size=8, comm_size=256)
    recv, timers = JaxShardBackend().run(compile_method(1, p), verify=True)
    assert timers[0].total_time > 0


def test_shard_single_device_mesh():
    """Degenerate 1-device mesh — the path scripts/tpu_flagship.py rides
    on the one real chip (every block all_to_all a self-exchange, the
    compacted layouts doing the memory work): byte-exact vs the oracle
    for the throttled m=1 and dense m=8, chained measurement positive."""
    import jax
    one = [jax.devices()[0]]
    p = AggregatorPattern(12, 5, data_size=32, comm_size=4)
    for mid in (1, 8):
        sched = compile_method(mid, p)
        b = JaxShardBackend(devices=one)
        recv_s, _ = b.run(sched, verify=True)
        recv_o, _ = LocalBackend().run(sched, verify=True)
        for got, want in zip(recv_s, recv_o):
            if want is not None:
                np.testing.assert_array_equal(got, want)
    b = JaxShardBackend(devices=one)
    # default chain lengths/trials: short chains on a us-scale rep are
    # inside host-timer noise and make the differenced diff go negative
    per = b.measure_per_rep(compile_method(1, p))
    assert per > 0


@pytest.mark.parametrize("method", [1, 17])
def test_shard_profile_rounds(method):
    """profile_rounds on the sharded tier: one timed dispatch per throttle
    round (built from the same _apply_block_round as the whole-rep
    program), per-round times mapped onto the phase buckets, delivery
    byte-exact vs the oracle — including the barrier-carrying m=17."""
    p = AggregatorPattern(16, 5, data_size=32, comm_size=4, proc_node=2)
    b = JaxShardBackend()
    sched = compile_method(method, p)
    recv_s, timers = b.run(sched, verify=True, profile_rounds=True)
    assert timers[0].total_time > 0
    [round_times] = b.last_round_times
    assert len(round_times) >= 2            # throttled: >= 2 rounds
    assert all(t > 0 for t in round_times)
    recv_o, _ = LocalBackend().run(sched, verify=True)
    for got, want in zip(recv_s, recv_o):
        if want is not None:
            np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="exclusive"):
        b.run(sched, chained=True, profile_rounds=True)


def test_shard_profile_rounds_collective_fallback():
    """Dense collective methods have one synthesized round — nothing to
    decompose: profiled mode falls back to whole-rep timing with a single
    segment per rep (jax_sim behavior), and last_round_times is fresh,
    not stale from a previously profiled schedule."""
    p = AggregatorPattern(16, 5, data_size=32, comm_size=4)
    b = JaxShardBackend()
    b.run(compile_method(1, p), profile_rounds=True)     # populates rounds
    assert len(b.last_round_times[0]) > 1
    recv, timers = b.run(compile_method(8, p), verify=True,
                         profile_rounds=True, ntimes=2)
    assert timers[0].total_time > 0
    assert [len(rt) for rt in b.last_round_times] == [1, 1]
