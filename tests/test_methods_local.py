"""Every method: schedule validity + oracle execution + deterministic-fill
verification + liveness (no deadlock under MPI rendezvous semantics).

This is the cross-validation-by-redundancy strategy of the reference
(SURVEY.md §4.5) made systematic: 20+ schedules computing the same exchange,
each checked against the pure-fill oracle.
"""

import numpy as np
import pytest

from tpu_aggcomm.backends.local import LocalBackend
from tpu_aggcomm.core.methods import METHODS, compile_method, method_ids
from tpu_aggcomm.core.pattern import AggregatorPattern

NON_TAM = [m for m in method_ids(include_dead=True) if not METHODS[m].tam]

CONFIGS = [
    # (procs, cb_nodes, data_size, comm_size, placement)
    (8, 3, 16, 200_000_000, 1),   # unthrottled
    (8, 3, 16, 2, 1),             # throttled
    (16, 16, 8, 5, 1),            # all ranks are aggregators
    (16, 1, 8, 3, 1),             # single aggregator
    (12, 5, 32, 4, 0),            # first-N placement, non-divisible
    (32, 14, 64, 3, 1),           # the README flagship config shape
    (16, 4, 8, 3, 3),             # node-robin placement (proc_node=4)
]


@pytest.mark.parametrize("method", NON_TAM)
@pytest.mark.parametrize("procs,cb,ds,cs,t", CONFIGS)
def test_method_delivers_and_verifies(method, procs, cb, ds, cs, t):
    p = AggregatorPattern(procs, cb, data_size=ds, comm_size=cs, placement=t,
                          proc_node=4 if t == 3 else 1)
    sched = compile_method(method, p)
    sched.validate()
    recv, _ = LocalBackend().run(sched, verify=True, iter_=0)


@pytest.mark.parametrize("method", [1, 2, 3, 4, 13])
def test_multiple_iters_change_payload(method):
    p = AggregatorPattern(8, 3, data_size=16, comm_size=3)
    sched = compile_method(method, p)
    r0, _ = LocalBackend().run(sched, verify=True, iter_=0)
    r1, _ = LocalBackend().run(sched, verify=True, iter_=1)
    a = next(x for x in r0 if x is not None)
    b = next(x for x in r1 if x is not None)
    assert not np.array_equal(a, b)


def test_barrier_type_variants_m13():
    p = AggregatorPattern(8, 3, data_size=16, comm_size=2)
    for bt in (0, 1, 2):
        sched = compile_method(13, p, barrier_type=bt)
        LocalBackend().run(sched, verify=True)


def test_rounds_view_consistent():
    p = AggregatorPattern(8, 3, data_size=16, comm_size=2)
    for m in NON_TAM:
        sched = compile_method(m, p)
        if sched.collective:
            continue
        rounds = sched.rounds()
        total = sum(len(r) for r in rounds)
        assert total == p.nprocs * p.cb_nodes, sched.name
