"""Native C++ runtime backend: every method delivers verified data with
real thread-level rendezvous semantics, timers populated."""

import numpy as np
import pytest

from tpu_aggcomm.backends.native import NativeBackend, build_library
from tpu_aggcomm.core.methods import METHODS, compile_method, method_ids
from tpu_aggcomm.core.pattern import AggregatorPattern

NON_TAM = [m for m in method_ids(include_dead=True) if not METHODS[m].tam]


def test_builds():
    assert build_library().endswith(".so")


@pytest.mark.parametrize("method", NON_TAM)
def test_native_all_methods(method):
    p = AggregatorPattern(8, 3, data_size=64, comm_size=3)
    sched = compile_method(method, p)
    recv, timers = NativeBackend().run(sched, verify=True)
    assert timers[0].total_time > 0


@pytest.mark.parametrize("method,cs", [(1, 1), (3, 2), (6, 1), (12, 2),
                                       (18, 3), (20, 2)])
def test_native_throttled(method, cs):
    p = AggregatorPattern(12, 5, data_size=32, comm_size=cs)
    sched = compile_method(method, p)
    NativeBackend().run(sched, verify=True, ntimes=3)


def test_native_matches_oracle():
    from tpu_aggcomm.backends.local import LocalBackend
    p = AggregatorPattern(8, 3, data_size=32, comm_size=2)
    for m in (1, 2, 5, 9, 13):
        sched = compile_method(m, p)
        recv_n, _ = NativeBackend().run(sched, verify=True)
        recv_o, _ = LocalBackend().run(sched, verify=True)
        for a, b in zip(recv_n, recv_o):
            if a is None:
                assert b is None
            else:
                np.testing.assert_array_equal(a, b)


def test_native_rep_timers():
    p = AggregatorPattern(8, 3, data_size=16, comm_size=3)
    b = NativeBackend()
    b.run(compile_method(13, p), ntimes=4)
    assert len(b.last_rep_timers) == 4
    assert all(t.total_time > 0 for t in b.last_rep_timers[0])


def test_native_routes_tam_to_oracle():
    # run-all (-m 0) must complete on this backend (VERDICT r1 item 2):
    # TAM methods route to the host proxy-path engine, delivery verified
    p = AggregatorPattern(8, 3, data_size=16, proc_node=2)
    for m in (15, 16):
        recv, timers = NativeBackend().run(compile_method(m, p), verify=True)
        assert timers[0].total_time > 0


# ---------------------------------------------------------------------------
# native variable-size workload engine (agg_run_workload_proxy)

def test_native_workload_proxy_all_stripes():
    from tpu_aggcomm.backends.native import run_workload_proxy
    from tpu_aggcomm.core.topology import static_node_assignment
    from tpu_aggcomm.core.workload import StripeType, initialize_setting

    for kind in (0, 1):
        for stripe in StripeType:
            na = static_node_assignment(12, 4, kind)
            wl = initialize_setting(na, 7, stripe)
            recv, times = run_workload_proxy(wl, na, ntimes=2)
            wl.verify_all(recv)
            assert len(times) == 2 and all(t > 0 for t in times)


def test_native_workload_proxy_matches_oracle():
    from tpu_aggcomm.backends.native import run_workload_proxy
    from tpu_aggcomm.core.topology import static_node_assignment
    from tpu_aggcomm.core.workload import StripeType, initialize_setting
    from tpu_aggcomm.tam.workload_engines import cw_proxy

    na = static_node_assignment(9, 3, 0)
    wl = initialize_setting(na, 4, StripeType.GREATER)
    recv_n, _ = run_workload_proxy(wl, na)
    recv_o, _ = cw_proxy(wl, na)
    for g in recv_o:
        for src in range(9):
            np.testing.assert_array_equal(recv_n[g][src], recv_o[g][src])


def test_native_workload_proxy_degenerate_shapes():
    from tpu_aggcomm.backends.native import run_workload_proxy
    from tpu_aggcomm.core.topology import static_node_assignment
    from tpu_aggcomm.core.workload import StripeType, initialize_setting

    # one rank per node, single node, blocklen > nprocs
    for (n, p) in [(6, 1), (5, 5), (1, 1)]:
        na = static_node_assignment(n, p, 0)
        wl = initialize_setting(na, 10, StripeType.ALL)
        recv, _ = run_workload_proxy(wl, na)
        wl.verify_all(recv)


@pytest.mark.parametrize("stripe", [0, 1, 2, 3])
@pytest.mark.parametrize("co,mode", [(1, 0), (2, 0), (2, 1)])
def test_native_cw2_matches_oracle(stripe, co, mode):
    from tpu_aggcomm.backends.native import run_workload_cw2
    from tpu_aggcomm.core.meta import aggregator_meta_information
    from tpu_aggcomm.core.topology import static_node_assignment
    from tpu_aggcomm.core.workload import StripeType, initialize_setting
    from tpu_aggcomm.tam.workload_engines import cw2_local_agg

    na = static_node_assignment(8, 4, 0)
    wl = initialize_setting(na, 5, StripeType(stripe))
    meta = aggregator_meta_information(na, wl.aggregators, co, mode)
    recv_n, times = run_workload_cw2(wl, meta, ntimes=2)
    wl.verify_all(recv_n)
    recv_o, _ = cw2_local_agg(wl, na, meta)
    for dst in recv_o:
        for src in range(wl.nprocs):
            np.testing.assert_array_equal(recv_n[dst][src],
                                          recv_o[dst][src])
    assert len(times) == 2


def test_native_cw2_uneven_and_robin():
    from tpu_aggcomm.backends.native import run_workload_cw2
    from tpu_aggcomm.core.meta import aggregator_meta_information
    from tpu_aggcomm.core.topology import static_node_assignment
    from tpu_aggcomm.core.workload import StripeType, initialize_setting

    for nprocs, pn, kind in [(7, 3, 0), (8, 2, 1), (9, 4, 0)]:
        na = static_node_assignment(nprocs, pn, kind)
        wl = initialize_setting(na, 4, StripeType.GREATER)
        meta = aggregator_meta_information(na, wl.aggregators, 2, 1)
        recv, _ = run_workload_cw2(wl, meta)
        wl.verify_all(recv)


@pytest.mark.parametrize("stripe", [0, 1, 2, 3])
def test_native_workload_cw3_matches_oracle(stripe):
    """The native shared-window engine (threads share the per-node window
    for real) delivers byte-for-byte what the cw3_shared oracle computes."""
    from tpu_aggcomm.backends.native import run_workload_cw3
    from tpu_aggcomm.core.meta import aggregator_meta_information
    from tpu_aggcomm.core.topology import static_node_assignment
    from tpu_aggcomm.core.workload import StripeType, initialize_setting
    from tpu_aggcomm.tam.workload_engines import cw3_shared

    na = static_node_assignment(8, 4, 0)
    wl = initialize_setting(na, 5, StripeType(stripe))
    meta = aggregator_meta_information(na, wl.aggregators, 4, 1)
    recv_o, _stats = cw3_shared(wl, na, meta)
    recv_n, times = run_workload_cw3(wl, na, meta, ntimes=3)
    wl.verify_all(recv_n)
    assert set(recv_n) == set(recv_o)
    for g in recv_o:
        for s in range(wl.nprocs):
            assert np.array_equal(recv_o[g][s], recv_n[g][s]), (g, s)
    assert len(times) == 3 and all(t > 0 for t in times)


def test_native_workload_cw3_rejects_mode0_meta():
    from tpu_aggcomm.backends.native import run_workload_cw3
    from tpu_aggcomm.core.meta import aggregator_meta_information
    from tpu_aggcomm.core.topology import static_node_assignment
    from tpu_aggcomm.core.workload import StripeType, initialize_setting

    na = static_node_assignment(8, 4, 0)
    wl = initialize_setting(na, 5, StripeType.LESS)
    meta = aggregator_meta_information(na, wl.aggregators, 1, 0)
    with pytest.raises(ValueError, match="local aggregators"):
        run_workload_cw3(wl, na, meta)
