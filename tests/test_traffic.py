"""Traffic auditor (tpu_aggcomm/obs/traffic.py) guarantees:

- the whole audit path — single-method audit AND the -m 0 conformance
  sweep — runs where jax cannot import (poisoned-jax subprocess: the
  same recipe as the tune --replay and supervisor pins);
- ``Schedule.data_edges()`` carries a real receiver slot (joined from
  ``recv_slot_table``) for nonblocking-send AND SENDRECV methods — the
  historical slot_dst=-1 placeholder is a regression;
- the in-flight accounting proves CONFORMS for every non-dead method
  over a grid of (nprocs, cb_nodes, comm_size) shapes, and REFUTES a
  synthetic over-poster naming the offending (rank, round, count);
- m=13's ``-b`` barrier modes audit to distinct barrier signatures
  (none / one per rep / one per block);
- the measured overlay joins the static matrix with flight-recorder
  round walls FLOAT-EXACTLY (the walls are obs.metrics.round_stats
  verbatim; eff_bps and frac_roofline are pure arithmetic on them);
- the traffic-v1 artifact written by ``inspect traffic --json``
  validates under obs.regress.validate_traffic (the same check
  scripts/check_bench_schema.py applies to committed TRAFFIC_*.json);
- satellite: inspect trace/compare/ledger exit nonzero with a one-line
  stderr message — no traceback — on missing or corrupt artifacts.
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_aggcomm.core.methods import METHODS, compile_method
from tpu_aggcomm.core.pattern import AggregatorPattern
from tpu_aggcomm.core.schedule import Op, OpKind, Schedule
from tpu_aggcomm.obs.traffic import (TrafficError, audit_schedule,
                                     conformance_sweep, documented_bound,
                                     incast_depths, inflight_audit,
                                     measured_overlay, pearson, round_edges,
                                     round_traffic)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pattern(nprocs=8, cb_nodes=2, data_size=64, comm_size=2,
             proc_node=1, placement=1):
    return AggregatorPattern(nprocs=nprocs, cb_nodes=cb_nodes,
                             data_size=data_size, proc_node=proc_node,
                             comm_size=comm_size, placement=placement)


# ------------------------------------------------------------- jax-free pin

def _poisoned_env(tmp_path):
    """Shared recipe (tests/_jaxfree.py, parameterized by the linter's
    purity contract) — the audit must not even try to import jax."""
    import _jaxfree
    return _jaxfree.poisoned_env(
        tmp_path, "the traffic auditor must not import jax")


def test_audit_survives_poisoned_jax(tmp_path):
    """The ISSUE acceptance command, byte-for-byte, where jax is broken."""
    r = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "traffic",
         "-m", "3", "-n", "32", "-a", "8", "-c", "4"],
        cwd=REPO, env=_poisoned_env(tmp_path), capture_output=True,
        text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "conformance: CONFORMS" in r.stdout
    assert "max incast" in r.stdout
    assert "dst" in r.stdout          # the per-round matrix actually printed


def test_sweep_survives_poisoned_jax(tmp_path):
    """The ci_tier1.sh gate command, byte-for-byte, where jax is broken."""
    r = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "traffic",
         "-m", "0", "-n", "32", "-a", "8", "-c", "4"],
        cwd=REPO, env=_poisoned_env(tmp_path), capture_output=True,
        text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "REFUTED: 0 of" in r.stdout


# --------------------------------------------- data_edges slot_dst (sat. 1)

@pytest.mark.parametrize("method", [1, 6])
def test_data_edges_carries_receiver_slot(method):
    """Regression: the send-side rows of ``data_edges()`` must join the
    receiver's slot from ``recv_slot_table`` — m=1 (nonblocking ISSEND
    sends) and m=6 (paired SENDRECV) both used to emit the -1
    placeholder in column 3."""
    sched = compile_method(method, _pattern())
    rtable = sched.recv_slot_table()
    edges = sched.data_edges()
    assert len(edges) > 0
    for src, dst, _sslot, dslot, _rnd in edges:
        key = (int(src), int(dst))
        assert key in rtable, f"send {key} has no matching recv"
        assert int(dslot) == rtable[key], (
            f"edge {key}: slot_dst {int(dslot)} != recv_slot_table "
            f"{rtable[key]}")
    assert not (edges[:, 3] == -1).any()


# ------------------------------------------------------- matrix accounting

def test_round_edges_match_data_edges():
    """The traffic matrix and the schedule's own edge view must agree on
    the payload universe (network edges; COPY tracked apart)."""
    sched = compile_method(1, _pattern())
    per_round = round_edges(sched)
    d = sched.pattern.data_size
    # m=1 posts real MPI self-sends (ISSEND to self) — they ARE edges
    from_edges = {}
    for src, dst, _ss, _ds, rnd in sched.data_edges():
        from_edges[(int(rnd), int(src), int(dst))] = d
    from_traffic = {(r, s, t): b
                    for r, c in per_round.items()
                    for (s, t), b in c["edges"].items()}
    assert from_traffic == from_edges


def test_incast_depths_counts_distinct_sources():
    edges = {(0, 7): 64, (1, 7): 64, (2, 7): 64, (3, 5): 64}
    assert incast_depths(edges) == {7: 3, 5: 1}


def test_round_traffic_summary_totals():
    sched = compile_method(1, _pattern())
    rt = round_traffic(sched)
    assert rt is not None
    audit = audit_schedule(sched)
    assert sum(r["bytes"] for r in rt.values()) == audit["totals"]["bytes"]
    assert all(set(v) == {"msgs", "bytes", "max_incast"}
               for v in rt.values())


def test_tam_engine_raises_traffic_error():
    sched = compile_method(15, _pattern(proc_node=4))
    with pytest.raises(TrafficError):
        round_edges(sched)
    with pytest.raises(TrafficError):
        inflight_audit(sched)
    assert audit_schedule(sched)["conformance"]["verdict"] == "EXEMPT"


# ------------------------------------------------- conformance (tentpole)

def test_conformance_grid_all_methods():
    """Every non-dead method CONFORMS (or is EXEMPT) on a grid of small
    shapes — the static proof that the schedule generators respect the
    -c semantics the benchmark studies. Dead methods are audited too
    (m=22 documents its own unthrottled bound)."""
    for nprocs, cb, c in [(4, 1, 1), (8, 2, 2), (8, 4, 3), (16, 4, 8),
                          (12, 3, 2)]:
        rows = conformance_sweep(nprocs, cb, c, data_size=256)
        assert len(rows) == len(METHODS)
        refuted = [r for r in rows if r["verdict"] == "REFUTED"]
        assert not refuted, (
            f"n={nprocs} a={cb} c={c}: {[(r['method'], r['peak'], r['bound']) for r in refuted]}")
        for r in rows:
            if r["verdict"] == "CONFORMS":
                assert r["peak"] <= r["bound"]


def test_conformance_property_hypothesis():
    """Property form of the grid test: random small shapes, every
    dispatched method stays within its documented bound."""
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    @hyp.settings(max_examples=25, deadline=None)
    @hyp.given(nprocs=st.integers(2, 16), cb=st.integers(1, 8),
               c=st.integers(1, 16))
    def prop(nprocs, cb, c):
        hyp.assume(cb <= nprocs)
        rows = conformance_sweep(nprocs, cb, c, data_size=64,
                                 include_dead=False)
        assert all(r["verdict"] != "REFUTED" for r in rows)

    prop()


def test_refuted_overposter_names_offender():
    """A hand-built schedule that posts 3 rendezvous sends before its
    waitall under a -c 1 throttle must be REFUTED with the offending
    (rank, round, count) named — the auditor cannot only ever agree."""
    p = _pattern(nprocs=4, cb_nodes=2, comm_size=1)
    bound, _ = documented_bound(12, p)
    assert bound == 1                       # min(c, cb) = min(1, 2)
    programs = [[
        Op(OpKind.ISSEND, peer=1, slot=0, round=0, token=0, nbytes=64),
        Op(OpKind.ISSEND, peer=2, slot=1, round=0, token=1, nbytes=64),
        Op(OpKind.ISSEND, peer=3, slot=2, round=0, token=2, nbytes=64),
        Op(OpKind.WAITALL, tokens=(0, 1, 2)),
    ]]
    for r in (1, 2, 3):
        programs.append([
            Op(OpKind.IRECV, peer=0, slot=0, round=0, token=0, nbytes=64),
            Op(OpKind.WAITALL, tokens=(0,)),
        ])
    sched = Schedule(pattern=p, method_id=12, name="synthetic overposter",
                     programs=programs)
    audit = audit_schedule(sched)
    conf = audit["conformance"]
    assert conf["verdict"] == "REFUTED"
    assert conf["peak"] == 3 and conf["bound"] == 1
    assert conf["offenders"][0] == {"rank": 0, "round": 0, "count": 3}
    # and the CLI renderer surfaces it
    from tpu_aggcomm.obs.traffic import render_audit
    text = render_audit(audit)
    assert "REFUTED" in text and "rank    0 round   0: 3 outstanding" in text


def test_m13_barrier_modes_distinct_signatures():
    """m=13's -b modes compile different programs from the same pattern;
    the audit's barrier signature must tell them apart (0 = none,
    1 = one per rep in the last round, 2 = one per block)."""
    p = _pattern()
    sigs = {}
    for bt in (0, 1, 2):
        audit = audit_schedule(compile_method(13, p, barrier_type=bt))
        assert audit["conformance"]["verdict"] == "CONFORMS"
        sigs[bt] = audit["barrier_rounds"]
    assert sigs[0] == {}
    assert sum(sigs[1].values()) == 1
    assert sum(sigs[2].values()) > 1
    # per-block mode fences every round the per-rep mode fences, and more
    assert set(sigs[1]) <= set(sigs[2])


def test_inflight_blocking_methods_post_nothing():
    """Fully blocking methods hold zero nonblocking tokens — bound 0,
    peak 0, and the signal channel stays separate."""
    for mid in (6, 9, 10):
        sched = compile_method(mid, _pattern())
        ranks = inflight_audit(sched)
        assert max(r["peak"] for r in ranks) == 0, mid


# ------------------------------------------------- measured overlay (exact)

def _traced_jax_sim_run(tmp_path):
    import io

    from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment
    from tpu_aggcomm.obs import trace

    cfg = ExperimentConfig(nprocs=8, cb_nodes=2, data_size=64, comm_size=2,
                           method=1, ntimes=3, backend="jax_sim",
                           verify=True,
                           results_csv=str(tmp_path / "r.csv"))
    trace.enable()
    try:
        run_experiment(cfg, out=io.StringIO())
    finally:
        paths = trace.flush(str(tmp_path / "ov"))
        trace.disable()
    return paths[0]


def test_overlay_walls_match_trace_float_exactly(tmp_path):
    """The overlay's round walls ARE obs.metrics.round_stats — not a
    recomputation — and eff/frac columns are pure arithmetic on them."""
    from tpu_aggcomm.harness.roofline import floor_seconds
    from tpu_aggcomm.obs.metrics import round_stats
    from tpu_aggcomm.obs.trace import load_events

    jsonl = _traced_jax_sim_run(tmp_path)
    events = load_events(jsonl)
    sched = compile_method(1, _pattern())
    audit = audit_schedule(sched)
    overlay = measured_overlay(audit, events)
    stats = {s["round"]: s for s in round_stats(events, overlay["run"])
             if isinstance(s["round"], int) and s["round"] >= 0}
    assert overlay["rounds"], "jax_sim trace must carry per-round slices"
    byts = {r["round"]: r["bytes"] for r in audit["rounds"]}
    for row in overlay["rounds"]:
        wall = stats[row["round"]]["wall"]
        assert row["wall_s"] == wall                       # float-exact
        assert row["eff_bps"] == byts[row["round"]] / wall
        assert row["frac_roofline"] == \
            floor_seconds(byts[row["round"]]) / wall
    isj = overlay["incast_straggler"]
    assert "pearson_recv_bytes_vs_total_s" in isj
    assert isj["critical_rank"] in range(8)


def test_overlay_refuses_mismatched_trace(tmp_path):
    jsonl = _traced_jax_sim_run(tmp_path)
    from tpu_aggcomm.obs.trace import load_events
    events = load_events(jsonl)
    audit = audit_schedule(compile_method(3, _pattern(nprocs=16)))
    with pytest.raises(TrafficError):
        measured_overlay(audit, events)


def test_pearson_basics():
    assert pearson([1, 2, 3], [2, 4, 6]) == pytest.approx(1.0)
    assert pearson([1, 2, 3], [6, 4, 2]) == pytest.approx(-1.0)
    assert pearson([1, 1, 1], [1, 2, 3]) is None      # constant side
    assert pearson([1], [2]) is None                  # too short


# ------------------------------------------------------- artifact (schema)

def test_cli_json_artifact_validates(tmp_path, capsys):
    from tpu_aggcomm.cli import main
    from tpu_aggcomm.obs.regress import validate_traffic

    path = str(tmp_path / "TRAFFIC_t.json")
    rc = main(["inspect", "traffic", "-m", "3", "-n", "32", "-a", "8",
               "-c", "4", "--json", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "conformance: CONFORMS" in out
    with open(path) as fh:
        blob = json.load(fh)
    assert validate_traffic(blob, "TRAFFIC_t.json") == []
    assert blob["schema"] == "traffic-v1"
    assert blob["config"]["method"] == 3


def test_validate_traffic_rejects_contradiction(tmp_path, capsys):
    """A verdict its own numbers contradict must fail validation — the
    check_bench_schema.py gate for committed TRAFFIC_*.json."""
    from tpu_aggcomm.cli import main
    from tpu_aggcomm.obs.regress import validate_traffic

    path = str(tmp_path / "TRAFFIC_bad.json")
    main(["inspect", "traffic", "-m", "3", "-n", "8", "-a", "2",
          "-c", "2", "--json", path])
    capsys.readouterr()
    with open(path) as fh:
        blob = json.load(fh)
    blob["conformance"]["verdict"] = "REFUTED"        # but no offenders
    assert validate_traffic(blob, "bad") != []
    blob["conformance"]["verdict"] = "CONFORMS"
    blob["conformance"]["peak"] = blob["conformance"]["bound"] + 1
    assert validate_traffic(blob, "bad") != []


def test_committed_traffic_artifacts_validate():
    """Every committed TRAFFIC_*.json passes the same validation the
    schema checker script applies."""
    import glob

    from tpu_aggcomm.obs.regress import validate_traffic
    paths = sorted(glob.glob(os.path.join(REPO, "TRAFFIC_*.json")))
    assert paths, "expected at least one committed TRAFFIC_*.json"
    for p in paths:
        with open(p) as fh:
            blob = json.load(fh)
        assert validate_traffic(blob, os.path.basename(p)) == [], p


# ------------------------------------------- CLI error handling (satellite 2)

def _cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tpu_aggcomm.cli"] + args,
                          cwd=cwd, capture_output=True, text=True,
                          timeout=120)


@pytest.mark.parametrize("argv", [
    ["inspect", "trace", "/nonexistent/x.trace.jsonl"],
    ["inspect", "ledger", "/nonexistent/x.trace.jsonl"],
    ["inspect", "traffic", "-m", "1", "--trace",
     "/nonexistent/x.trace.jsonl"],
])
def test_cli_missing_artifact_one_line_error(argv):
    r = _cli(argv)
    assert r.returncode != 0
    assert "Traceback" not in r.stderr, r.stderr
    assert r.stderr.strip(), "expected a one-line stderr message"


def test_cli_corrupt_artifact_one_line_error(tmp_path):
    bad = tmp_path / "bad.trace.jsonl"
    bad.write_text('{"ev": "run", truncated garbage\n')
    bad2 = tmp_path / "bad2.trace.jsonl"
    bad2.write_text("not json at all\n")
    for argv in (["inspect", "trace", str(bad)],
                 ["inspect", "compare", str(bad), str(bad2)],
                 ["inspect", "ledger", str(bad)]):
        r = _cli(argv)
        assert r.returncode != 0, argv
        assert "Traceback" not in r.stderr, (argv, r.stderr)
        assert r.stderr.strip(), argv


def test_cli_truncated_trace_one_line_error(tmp_path):
    """A trace cut mid-write (last line sliced) must fail cleanly."""
    jsonl = _traced_jax_sim_run(tmp_path)
    with open(jsonl) as fh:
        data = fh.read()
    cut = tmp_path / "cut.trace.jsonl"
    head = data[:len(data) // 2].rsplit("\n", 1)[0]
    cut.write_text(head + '\n{"ev": "span", "trunc')
    r = _cli(["inspect", "trace", str(cut)])
    assert r.returncode != 0
    assert "Traceback" not in r.stderr, r.stderr


def test_cli_sweep_rejects_json_and_trace():
    from tpu_aggcomm.cli import main
    with pytest.raises(SystemExit):
        main(["inspect", "traffic", "-m", "0", "--json", "/tmp/x.json"])
    with pytest.raises(SystemExit):
        main(["inspect", "traffic"])          # -m required
