"""Autopilot tests (tpu_aggcomm/pilot/ + the serve swap/demote ops —
ISSUE 19).

The pins that define the subsystem:

- **Deterministic folding**: the same profile + per-shape stats
  snapshot fold to the byte-identical ranked target list (the replay
  contract's foundation).
- **Advisory until proven**: a campaign winner changes nothing without
  a seeded-bootstrap win CI excluding zero AND a byte-exact verify
  through the server's normal queue; every refusal is named.
- **Named, reversible promotions**: a swap installs only a validated
  record under a matching manifest fingerprint; demote accepts only
  the SAME record (never a lookalike) plus a reason naming the
  regression verdict; the served method visibly flips both ways.
- **Rollback closes the loop**: an engineered post-promotion
  regression ⟹ seeded watchtower changepoint ⟹ a live demotion row
  with the verdict named ⟹ the old method serves again — and the
  artifact recording all of it replays REPRODUCED.
- **One-CPU-core discipline**: campaign samplers refuse by name while
  a serve dispatch is in flight on the same backend
  (PilotContentionError — a sample taken under serve load is noise
  with a seed).
- **jax-free planner**: folding, campaign replay, artifact validation
  and ``cli pilot --replay`` all run where ``import jax`` raises
  (poisoned-jax subprocess, the obs/analysis discipline).
"""

import copy
import json
import shutil
import subprocess
import sys
import time

import pytest

import _jaxfree

REPO = _jaxfree.REPO

from tpu_aggcomm.core.methods import METHODS
from tpu_aggcomm.obs.regress import validate_pilot
from tpu_aggcomm.obs.workload import profile_journal
from tpu_aggcomm.pilot import (CampaignError, PilotError, PromotionError,
                               fold_targets, make_promotion_record,
                               render_pilot, replay_pilot, run_campaign,
                               run_pilot, validate_promotion_record,
                               write_pilot)
from tpu_aggcomm.pilot.artifact import (demotion_rows, derive_decision,
                                        mark_skips, next_pilot_path)
from tpu_aggcomm.pilot.campaign import replay_campaign
from tpu_aggcomm.pilot.plan import shape_stats_key
from tpu_aggcomm.pilot.promote import records_equal
from tpu_aggcomm.serve.protocol import ServeClient
from tpu_aggcomm.serve.server import ScheduleServer
from tpu_aggcomm.tune.race import make_synthetic_sampler

#: The hot request shape every test drives (method 1, a2m): the
#: synthetic spec "120,m3*0.6" makes the reference method 3 the
#: provable winner at this cell.
SHAPE = {"method": 1, "nprocs": 8, "cb_nodes": 4, "comm_size": 2,
         "data_size": 256}
SPEC = "120,m3*0.6"


@pytest.fixture(autouse=True)
def _registry_guard():
    """Campaign registration mutates the global METHODS table; every
    test leaves it exactly as found (the synth suite's contract)."""
    before = set(METHODS)
    yield
    for mid in set(METHODS) - before:
        del METHODS[mid]


@pytest.fixture
def fake_executor(monkeypatch):
    """The real serve control plane with instant execution — the
    journal stamps, per-shape counters and swap/demote plumbing are
    what's under test. ``delay`` is mutable so a test can engineer a
    wall-clock regression mid-run."""
    from tpu_aggcomm.serve import executor

    delay = {"s": 0.0}

    def fake_build(schedule, backend_name):
        return object(), 1e-3

    def fake_exec(chain, reqs):
        if delay["s"]:
            time.sleep(delay["s"])
        return [{"verified": True if r.verify else None, "error": None}
                for r in reqs]

    monkeypatch.setattr(executor, "build_chain", fake_build)
    monkeypatch.setattr(executor, "execute_batch", fake_exec)
    return delay


def _drive(port, payloads):
    """Sequential back-to-back requests (one client): a tight burst,
    so the profiler's hot-shape/burstiness proposals fire."""
    out = []
    with ServeClient(port, timeout=300.0) as c:
        for p in payloads:
            out.append(c.run(**p))
    assert all(r["ok"] for r in out), out
    return out


def _skewed_traffic(port):
    """10x the hot shape + 2x a minority shape — the mix both the CLI
    smoke and the committed exemplar use."""
    return _drive(port, [dict(SHAPE, verify=True, iter=i)
                         for i in range(10)]
                  + [dict(SHAPE, method=3, verify=True, iter=i)
                     for i in range(2)])


def _server(tmp_path, **kw):
    srv = ScheduleServer(backend="jax_sim", port=0, max_batch=4,
                         batch_window_s=0.01,
                         journal_path=str(tmp_path
                                          / "serve_pilot.journal.jsonl"),
                         **kw)
    srv.start()
    return srv


def _full_shape(**over):
    """The journal's admitted ``shape`` block for SHAPE — the FULL
    shape-fields dict (what fold targets and demotion matching key on),
    not the 5-field request we drive with."""
    from tpu_aggcomm.serve.protocol import parse_request
    req = parse_request(dict(SHAPE, **over))
    return {f: getattr(req, f) for f in req.shape_fields}


def _record(fingerprint, **over):
    rec = {"shape": dict(SHAPE), "backend": "jax_sim",
           "old_method": 1, "old_cid": "m1:a4:c2:t0",
           "new_method": 3, "new_cid": "m3:a4:c2:t0",
           "composition": None, "win_ci_pct": [5.0, 10.0],
           "seed": 0, "alpha": 0.05, "n_boot": 200,
           "fingerprint": fingerprint, "artifact": None}
    rec.update(over)
    return rec


# ---------------------------------------------------------------------------
# Folding: measured traffic -> ranked targets, deterministically.


def test_fold_targets_deterministic_and_ranked(fake_executor, tmp_path):
    srv = _server(tmp_path)
    try:
        _skewed_traffic(srv.port)
    finally:
        srv.stop()
        srv.close()
    journal = str(tmp_path / "serve_pilot.journal.jsonl")
    p1 = profile_journal([journal], seed=0)
    p2 = profile_journal([journal], seed=0)
    t1, t2 = fold_targets(p1), fold_targets(p2)
    assert json.loads(json.dumps(t1)) == json.loads(json.dumps(t2))
    assert t1, "the skewed mix must propose at least one target"
    for t in t1:
        assert t["incumbent_cid"] == "m1:a4:c2:t0"
        assert t["direction"] == "all_to_many"
        assert t["stats"] is None  # no per-shape snapshot supplied

    # a per-shape stats snapshot attaches by schedule identity and
    # ranks by measured latency mass
    key = shape_stats_key(SHAPE, "jax_sim")
    assert key is not None
    per_shape = {key: {"hit": 9, "miss": 1, "requests": 10,
                       "latency_sum": 123.5}}
    ranked = fold_targets(p1, per_shape)
    assert ranked[0]["stats"] == per_shape[key]
    assert ranked[0]["rank"] == 0

    # a malformed proposal shape is refused by name, never absorbed
    with pytest.raises(PilotError, match="integer 'cb_nodes'"):
        fold_targets({"proposals": [{"kind": "hot-shape",
                                     "shape": {"method": 1, "nprocs": 8,
                                               "comm_size": 2}}]})
    # an unregistered synthesized incumbent names the --synth-root fix
    bad = {"proposals": [{"kind": "hot-shape",
                          "shape": dict(SHAPE, method=999)}]}
    with pytest.raises(PilotError, match="synth-root"):
        fold_targets(bad)


# ---------------------------------------------------------------------------
# Campaigns: synthetic race, win CI, byte-for-byte replay.


def _hot_target():
    return {"index": 0, "kind": "hot-shape", "shape": dict(SHAPE),
            "backend": "jax_sim", "incumbent_cid": "m1:a4:c2:t0",
            "direction": "all_to_many", "reason": "test", "stats_key": None,
            "stats": None, "rank": 0, "skipped": None}


def test_campaign_synthetic_race_and_replay():
    sampler = make_synthetic_sampler(SPEC, seed=0, batch_trials=3)
    c = run_campaign(_hot_target(), sampler, seed=0, max_batches=4)
    assert c["winner"]["cid"] == "m3:a4:c2:t0"
    assert c["winner"]["synthesized"] is False
    assert c["improved"] is True and c["win_ci_pct"][0] > 0
    # references-first order: the incumbent is in the reference field,
    # nothing synthesized was registered for a hot-shape target
    assert c["search"] is None and c["registration"] is None
    assert c["race"]["order"][0] == "m1:a4:c2:t0"

    assert replay_campaign(c) == []

    # a mutated sample is named, not absorbed (the exact symptom
    # depends on where the re-derived race diverges first)
    bad = copy.deepcopy(c)
    bad["race"]["samples"]["m3:a4:c2:t0"][0][0] *= 100.0
    problems = replay_campaign(bad)
    assert problems and all("race" in p or "re-derive" in p
                            for p in problems)

    # an improved flag the recorded CI contradicts is named
    lie = copy.deepcopy(c)
    lie["improved"] = False
    assert any("contradicts its own win CI" in p
               for p in replay_campaign(lie))


def test_campaign_bursty_target_runs_search_and_registers():
    t = dict(_hot_target(), kind="bursty-arrivals")
    sampler = make_synthetic_sampler(SPEC, seed=0, batch_trials=3)
    c = run_campaign(t, sampler, seed=0, max_batches=4, id_base=900)
    assert c["search"] is not None and c["search"]["finalists"]
    assert c["registration"], "finalists must register before racing"
    for mid, reg in c["registration"].items():
        assert int(mid) >= 900 and int(mid) in METHODS
        assert reg["composition"]
    # synthesized candidates raced AFTER the reference field
    order = c["race"]["order"]
    ref_end = max(i for i, cid in enumerate(order)
                  if int(cid.split(":")[0][1:]) < 900)
    assert all(int(cid.split(":")[0][1:]) >= 900
               for cid in order[ref_end + 1:])
    assert replay_campaign(c) == []


def test_campaign_refuses_unraceable_target():
    from tpu_aggcomm.synth.search import SearchError
    t = dict(_hot_target(), direction="nope")
    with pytest.raises((CampaignError, SearchError),
                       match="unknown direction"):
        run_campaign(t, make_synthetic_sampler(SPEC, seed=0),
                     seed=0, max_batches=2)


# ---------------------------------------------------------------------------
# Promotion records: the only currency a swap accepts.


def test_promotion_record_refusals_are_named():
    sampler = make_synthetic_sampler(SPEC, seed=0, batch_trials=3)
    c = run_campaign(_hot_target(), sampler, seed=0, max_batches=4)
    rec = make_promotion_record(_hot_target(), c, fingerprint="fp")
    assert validate_promotion_record(rec) == []
    assert rec["old_method"] == 1 and rec["new_method"] == 3
    assert rec["composition"] is None

    # a non-improved campaign can never mint a record
    flat = copy.deepcopy(c)
    flat["improved"] = False
    with pytest.raises(PromotionError, match="not an improvement"):
        make_promotion_record(_hot_target(), flat, fingerprint="fp")

    # a win CI touching zero is refused citing the bootstrap gate
    bad = dict(rec, win_ci_pct=[-0.1, 4.0])
    assert any("seeded-bootstrap gate" in p
               for p in validate_promotion_record(bad))
    # a no-op swap is refused, not silently applied
    noop = dict(rec, new_method=1, new_cid="m1:a4:c2:t0")
    assert any("no-op swap" in p
               for p in validate_promotion_record(noop))
    # a synthesized id without its composition cannot be reversed
    synth = dict(rec, new_method=901, new_cid="m901:a4:c2:t0")
    assert any("no canonical composition" in p
               for p in validate_promotion_record(synth))
    # a reference id must NOT carry one
    ref = dict(rec, composition="fanin=2|order=flat")
    assert any("reference id" in p
               for p in validate_promotion_record(ref))
    # identity is byte-level
    assert records_equal(rec, json.loads(json.dumps(rec)))
    assert not records_equal(rec, dict(rec, win_ci_pct=[6.0, 9.0]))


# ---------------------------------------------------------------------------
# The serve ops: swap installs behind verify, demote reverses by the
# same record, every refusal named.


def test_swap_and_demote_lifecycle(fake_executor, tmp_path):
    srv = _server(tmp_path)
    try:
        fp = srv.stats()["fingerprint"]

        # fingerprint drift is refused by name — a win measured under
        # a drifted manifest does not transfer
        drifted = srv.swap(_record("somebody-elses-fingerprint"))
        assert not drifted["ok"]
        assert "does not transfer" in drifted["error"]

        # a structurally invalid record never reaches the queue
        unproven = srv.swap(_record(fp, win_ci_pct=[-1.0, 3.0]))
        assert not unproven["ok"]
        assert "seeded-bootstrap gate" in unproven["error"]

        # demotion without an installed promotion is named
        none_yet = srv.demote(_record(fp), "watch: regression")
        assert not none_yet["ok"]
        assert "no promotion is installed" in none_yet["error"]

        # the real swap: verify leg through the NORMAL queue, then the
        # served method visibly flips
        rec = _record(fp)
        before = _drive(srv.port, [dict(SHAPE, verify=True)])[0]
        assert before["served_method"] == 1
        res = srv.swap(rec)
        assert res["ok"] and res["installed"] and res["verified"] is True
        assert res["seq"] == 1 and res["verify_rid"]
        after = _drive(srv.port, [dict(SHAPE, verify=True)])[0]
        assert after["served_method"] == 3
        assert srv.stats()["promotions"] == [{"seq": 1, "record": rec}]

        # double-install is refused by name
        dup = srv.swap(_record(fp))
        assert not dup["ok"] and "demote it first" in dup["error"]

        # demote: empty reason refused, lookalike refused, the SAME
        # record restores the old method
        noname = srv.demote(rec, "   ")
        assert not noname["ok"]
        assert "name the regression verdict" in noname["error"]
        lookalike = srv.demote(_record(fp, win_ci_pct=[6.0, 10.0]),
                               "watch: regression")
        assert not lookalike["ok"]
        assert "never a lookalike" in lookalike["error"]
        down = srv.demote(rec, "watch: confirmed request-wall step up")
        assert down["ok"] and down["restored_method"] == 1
        restored = _drive(srv.port, [dict(SHAPE, verify=True)])[0]
        assert restored["served_method"] == 1
        assert srv.stats()["promotions"] == []
    finally:
        srv.stop()
        srv.close()

    # the journal carries the named swap + demote records
    recs = [json.loads(line) for line in
            (tmp_path / "serve_pilot.journal.jsonl")
            .read_text().splitlines() if line.strip()]
    promo = [r for r in recs
             if isinstance(r.get("key"), dict) and "promotion" in r["key"]]
    assert [r["status"] for r in promo] == ["swap", "demote"]
    assert promo[0]["record"] == promo[1]["record"]
    assert "step up" in promo[1]["reason"]
    verify_leg = [r for r in recs if r.get("purpose") == "swap-verify"]
    assert verify_leg and verify_leg[0]["served_method"] == 3


def test_per_shape_counters_feed_fold(fake_executor, tmp_path):
    """stats()['per_shape'] rows join fold_targets by schedule
    identity — the pilot's ranking evidence is the server's own
    accounting, never a re-measurement."""
    srv = _server(tmp_path)
    try:
        _skewed_traffic(srv.port)
        st = srv.stats()
    finally:
        srv.stop()
        srv.close()
    key = shape_stats_key(SHAPE, "jax_sim")
    assert key in st["per_shape"]
    row = st["per_shape"][key]
    assert row["requests"] == 10 and row["hit"] + row["miss"] == 10
    profile = profile_journal(
        [str(tmp_path / "serve_pilot.journal.jsonl")], seed=0)
    targets = fold_targets(profile, st["per_shape"])
    assert targets[0]["stats"] == row
    assert targets[0]["stats_key"] == key


# ---------------------------------------------------------------------------
# run_pilot end-to-end: live promotion, artifact, replay (incl. under
# poisoned jax).


def test_run_pilot_live_promotes_and_replays(fake_executor, tmp_path):
    srv = _server(tmp_path)
    try:
        _skewed_traffic(srv.port)
        journal = str(tmp_path / "serve_pilot.journal.jsonl")
        body = run_pilot([journal], seed=0, serve_port=srv.port,
                         synthetic=SPEC, max_batches=4)
        actions = [d["action"] for d in body["decisions"]]
        assert "promote" in actions
        # zero silent method changes: every promote decision carries
        # the applied record, and promotions == promote records
        promoted = [d["record"] for d in body["decisions"]
                    if d["action"] == "promote"]
        assert promoted == body["promotions"] and promoted
        assert promoted[0]["new_method"] == 3
        after = _drive(srv.port, [dict(SHAPE, verify=True)])[0]
        assert after["served_method"] == 3
        # the journal snapshot froze what the pilot read: the verify
        # leg's appended records never leak into the recorded profile
        assert body["journals"][0]["name"] == \
            "serve_pilot.journal.jsonl"
        assert body["requests"]["admitted"] == 12
    finally:
        srv.stop()
        srv.close()

    out = next_pilot_path(str(tmp_path))
    blob = write_pilot(out, body)
    assert validate_pilot(blob, "PILOT_r01.json") == []
    rep = replay_pilot(out)
    assert rep["verdict"] == "REPRODUCED", rep["problems"]
    assert "promote" in render_pilot(body)

    # a promotion the artifact's own campaigns contradict is named by
    # the validator (the zero-silent-method-changes contract)
    lie = copy.deepcopy(blob)
    lie["promotions"] = []
    assert any("promote" in e for e in
               validate_pilot(lie, "PILOT_r01.json"))
    # a shrunk journal is named by replay
    shutil.copy(out, str(tmp_path / "PILOT_r77.json"))
    trimmed = (tmp_path / "serve_pilot.journal.jsonl")
    lines = trimmed.read_text().splitlines(keepends=True)
    sub = tmp_path / "short"
    sub.mkdir()
    shutil.copy(out, str(sub / "PILOT_r77.json"))
    (sub / "serve_pilot.journal.jsonl").write_text("".join(lines[:3]))
    short = replay_pilot(str(sub / "PILOT_r77.json"))
    assert short["verdict"] == "MISMATCH"
    assert any("shrank" in p for p in short["problems"])

    # the committed artifact replays where `import jax` raises — the
    # jax-free planner pin, via the CLI gate itself
    env = _jaxfree.poisoned_env(tmp_path,
                                "pilot --replay must be jax-free")
    r = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "pilot",
         "--replay", out], capture_output=True, text=True, env=env,
        cwd=str(tmp_path), timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "REPRODUCED" in r.stdout


def test_run_pilot_dry_run_never_contacts_a_server(fake_executor,
                                                  tmp_path):
    srv = _server(tmp_path)
    try:
        _skewed_traffic(srv.port)
    finally:
        srv.stop()
        srv.close()
    journal = str(tmp_path / "serve_pilot.journal.jsonl")
    body = run_pilot([journal], seed=0, synthetic=SPEC, max_batches=4)
    assert body["mode"] == "dry-run"
    assert body["per_shape"] is None and body["promotions"] == []
    for d in body["decisions"]:
        assert d["action"] in ("would-promote", "keep-incumbent",
                               "no-win")
        assert d["swap"] is None
    out = next_pilot_path(str(tmp_path))
    write_pilot(out, body)
    rep = replay_pilot(out)
    assert rep["verdict"] == "REPRODUCED", rep["problems"]


# ---------------------------------------------------------------------------
# Rollback: regression -> watch verdict -> live demotion -> the old
# method serves again, and the artifact replays (satellite 3).


def test_rollback_demotes_on_engineered_regression(fake_executor,
                                                   tmp_path):
    delay = fake_executor
    srv = _server(tmp_path)
    try:
        fp = srv.stats()["fingerprint"]
        # healthy epoch on the incumbent
        _drive(srv.port, [dict(SHAPE, verify=True, iter=i)
                          for i in range(10)])
        # the record's shape must be the journal's FULL shape-fields
        # block — that is what fold targets and wall matching key on
        rec = _record(fp, shape=_full_shape())
        assert srv.swap(rec)["installed"] is True
        # the promotion regresses: engineered wall-clock step up
        delay["s"] = 0.35
        _drive(srv.port, [dict(SHAPE, verify=True, iter=i)
                          for i in range(8)])
        delay["s"] = 0.0

        journal = str(tmp_path / "serve_pilot.journal.jsonl")
        body = run_pilot([journal], seed=0, serve_port=srv.port,
                         synthetic=SPEC, max_batches=4)
        # the demotion row names the watch verdict and the server
        # confirmed the reversal
        assert len(body["demotions"]) == 1
        row = body["demotions"][0]
        assert row["action"] == "demote" and row["seq"] == 1
        assert "confirmed request-wall step up" in row["reason"]
        assert row["detection"]["direction"] == "up"
        assert row["outcome"]["ok"] is True
        assert row["outcome"]["restored_method"] == 1
        # targets on the (still-snapshotted) promoted shape were
        # skipped, not raced mid-promotion
        assert all(t["skipped"] == "already-promoted"
                   for t in body["targets"])
        assert body["campaigns"] == [] and body["promotions"] == []

        # the old method serves again, byte-for-byte the same path
        restored = _drive(srv.port, [dict(SHAPE, verify=True)])[0]
        assert restored["served_method"] == 1
        assert srv.stats()["promotions"] == []
    finally:
        srv.stop()
        srv.close()

    out = next_pilot_path(str(tmp_path))
    blob = write_pilot(out, body)
    assert validate_pilot(blob, "PILOT_rollback.json") == []
    rep = replay_pilot(out)
    assert rep["verdict"] == "REPRODUCED", rep["problems"]

    # a demotion row whose recorded detection contradicts its action
    # fails validation — the verdict must follow its own evidence
    lie = copy.deepcopy(blob)
    lie["demotions"][0]["action"] = "hold"
    assert any("demotion" in e.lower() or "hold" in e
               for e in validate_pilot(lie, "PILOT_rollback.json"))


def test_demotion_rows_is_pure_and_seeded():
    rec = _record("fp")
    installed = [{"seq": 1, "record": rec}]
    flat = [{"status": "done", "shape": dict(SHAPE), "wall_s": w}
            for w in [0.010, 0.011, 0.010, 0.012, 0.011, 0.010,
                      0.011, 0.010]]
    step = flat + [{"status": "done", "shape": dict(SHAPE),
                    "wall_s": w}
                   for w in [0.30, 0.31, 0.30, 0.32, 0.31, 0.30,
                             0.31, 0.30]]
    hold = demotion_rows(installed, flat, seed=0)
    assert hold[0]["action"] == "hold" and hold[0]["n_walls"] == 8
    demote = demotion_rows(installed, step, seed=0)
    assert demote[0]["action"] == "demote"
    assert "watch: confirmed" in demote[0]["reason"]
    # seeded: same inputs, byte-identical rows
    assert json.loads(json.dumps(demote)) == \
        json.loads(json.dumps(demotion_rows(installed, step, seed=0)))
    # other shapes' walls never count against this promotion
    other = [{"status": "done", "shape": dict(SHAPE, method=3),
              "wall_s": 9.9}] * 16
    assert demotion_rows(installed, other, seed=0)[0]["n_walls"] == 0


def test_mark_skips_and_decision_arithmetic():
    t = _hot_target()
    installed = [{"seq": 1, "record": _record("fp")}]
    marked = mark_skips([t], installed)
    assert marked[0]["skipped"] == "already-promoted"
    assert mark_skips([t], [])[0]["skipped"] is None

    sampler = make_synthetic_sampler(SPEC, seed=0, batch_trials=3)
    c = run_campaign(t, sampler, seed=0, max_batches=4)
    would = derive_decision(t, c, mode="dry-run", fingerprint="fp",
                            swap=None)
    assert would["action"] == "would-promote"
    unattempted = derive_decision(t, c, mode="live", fingerprint="fp",
                                  swap=None)
    assert unattempted["action"] == "swap-unattempted"
    ok = derive_decision(t, c, mode="live", fingerprint="fp",
                         swap={"ok": True, "installed": True,
                               "verified": True})
    assert ok["action"] == "promote"
    unverified = derive_decision(t, c, mode="live", fingerprint="fp",
                                 swap={"ok": True, "verified": False})
    assert unverified["action"] == "verify-failed"
    refused = derive_decision(t, c, mode="live", fingerprint="fp",
                              swap={"ok": False, "error": "nope"})
    assert refused["action"] == "swap-refused"


# ---------------------------------------------------------------------------
# One-CPU-core contention guard (satellite 2).


def test_sampler_refuses_under_serve_dispatch():
    from tpu_aggcomm.tune.measure import (PilotContentionError,
                                          make_jax_sim_sampler,
                                          serve_dispatch_inflight)
    # factory-time refusal, naming the backend and the remedy
    with serve_dispatch_inflight("jax_sim"):
        with pytest.raises(PilotContentionError,
                           match="jax_sim.*serve queue drains"):
            make_jax_sim_sampler(nprocs=8, data_size=64, proc_node=1)
    # per-call refusal: a sampler built while quiet still refuses the
    # moment a dispatch is in flight
    sampler = make_jax_sim_sampler(nprocs=8, data_size=64, proc_node=1)
    with serve_dispatch_inflight("jax_sim"):
        with pytest.raises(PilotContentionError, match="1 serve"):
            sampler("m1:a4:c2:t0", 0)
    # other backends are not blocked; exit releases the slot
    with serve_dispatch_inflight("pallas_fused"):
        pass  # jax_sim unaffected
    sampler  # still usable once the queue drained (no raise on check)
    from tpu_aggcomm.tune.measure import _check_contention
    _check_contention("jax_sim")


# ---------------------------------------------------------------------------
# jax purity: the planner answers where a wedged tunnel hangs import.


def test_pilot_planner_is_jaxfree(tmp_path):
    env = _jaxfree.poisoned_env(tmp_path,
                                "the pilot planner must not import jax")
    code = _jaxfree.pure_import_code("tpu_aggcomm.pilot")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, cwd=REPO,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
