"""Lane layout round-trips (backends/lanes.py)."""

import numpy as np
import pytest

from tpu_aggcomm.backends.lanes import lane_layout, lanes_to_bytes, to_lanes


@pytest.mark.parametrize("ds", [4, 8, 2048])
def test_aligned_uses_u32(ds):
    ndt, _, w = lane_layout(ds)
    assert ndt == np.uint32 and w == ds // 4


@pytest.mark.parametrize("ds", [1, 2, 3, 5, 30])
def test_unaligned_stays_u8(ds):
    ndt, _, w = lane_layout(ds)
    assert ndt == np.uint8 and w == ds


@pytest.mark.parametrize("ds", [1, 3, 4, 12, 2048])
def test_round_trip_is_identity(ds):
    rng = np.random.default_rng(ds)
    a = rng.integers(0, 256, size=(3, 5, ds), dtype=np.uint8)
    lanes = to_lanes(a, ds)
    back = lanes_to_bytes(lanes, ds)
    np.testing.assert_array_equal(a, back)
    _, _, w = lane_layout(ds)
    assert lanes.shape == (3, 5, w)


def test_to_lanes_handles_noncontiguous():
    a = np.arange(2 * 4 * 16, dtype=np.uint8).reshape(2, 4, 16)
    view = a[:, ::2, :]  # non-contiguous
    lanes = to_lanes(view, 16)
    np.testing.assert_array_equal(lanes_to_bytes(lanes, 16), view)
