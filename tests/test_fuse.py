"""Schedule→Mosaic fusion (native/fuse.py + backends/pallas_fused.py):
one Pallas kernel per whole throttled schedule, in-kernel DMA-semaphore
drains as the round fences.

Pins, per ISSUE 10:

- byte-exact interpret-mode ``--verify`` against the local oracle for
  EVERY fusable method id, healthy and fault-repaired;
- unfusable schedules (TAM, dense collectives, staged dead-link
  repairs, slow-rank injection) refuse with a NAMED error — never a
  silent fallback to the fenced lowering;
- round ordering by construction: the fused semaphore dependency chain
  totally orders the same round ids the model checker's round-fence
  property proves monotone (analysis/check.py) — a round-k+1 arrival
  before round-k completion is unrepresentable;
- the step export equals the op-program traffic accounting
  (cross_check_export), and a perturbed export is a NAMED drift;
- fuse's schedule-analysis half stays importable jax-free (poisoned-jax
  subprocess pin parameterized from the purity contract itself).
"""

import subprocess
import sys

import numpy as np
import pytest

import _jaxfree
from tpu_aggcomm.backends.pallas_fused import (FusedBackendError,
                                               PallasFusedBackend)
from tpu_aggcomm.core.methods import METHODS, compile_method, method_ids
from tpu_aggcomm.core.pattern import AggregatorPattern
from tpu_aggcomm.core.schedule import barrier_rounds_of
from tpu_aggcomm.native import fuse
from tpu_aggcomm.native.fuse import (MAX_FUSED_EDGES, FusedExportError,
                                     UnfusableScheduleError,
                                     cross_check_export, export_sweep,
                                     fuse_plan, plan_round_matrices,
                                     semaphore_deps)

NON_TAM = [m for m in method_ids(include_dead=True) if not METHODS[m].tam]
FUSABLE = [m for m in NON_TAM
           if not compile_method(m, AggregatorPattern(8, 3, data_size=32,
                                                      comm_size=3))
           .collective]
COLLECTIVE = [m for m in NON_TAM if m not in FUSABLE]


def _pattern(**kw):
    kw.setdefault("data_size", 32)
    kw.setdefault("comm_size", 3)
    return AggregatorPattern(kw.pop("nprocs", 8), kw.pop("cb_nodes", 3),
                             **kw)


def _backend():
    return PallasFusedBackend(interpret=True)


# ---------------------------------------------------------------------------
# byte-exact verify vs the local oracle (interpret mode, CPU)


@pytest.mark.parametrize("method", FUSABLE)
def test_fused_matches_oracle(method):
    from tpu_aggcomm.backends.local import LocalBackend
    p = _pattern()
    sched = compile_method(method, p)
    recv_f, timers = _backend().run(sched, verify=True, iter_=0)
    recv_o, _ = LocalBackend().run(sched, verify=True, iter_=0)
    for a, b in zip(recv_f, recv_o):
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(a, b)
    assert timers[0].total_time > 0


def test_fused_uint8_lane_path():
    # data_size not 4-aligned: the kernel arena rides uint8 lanes on the
    # pallas_dma (4, 128) tile discipline instead of uint32 (8, 128)
    p = _pattern(data_size=33)
    _backend().run(compile_method(1, p), verify=True)


def test_fused_throttle_and_iters():
    p = _pattern(nprocs=12, cb_nodes=5, data_size=16, comm_size=2,
                 proc_node=2)
    b = _backend()
    _, timers = b.run(compile_method(3, p), ntimes=2, verify=True, iter_=1)
    assert len(b.last_rep_timers) == 2


def test_fused_chained_measurement():
    b = _backend()
    per_rep = b.measure_per_rep(compile_method(1, _pattern()),
                                iters_small=5, iters_big=505, trials=2,
                                windows=1)
    assert per_rep > 0
    assert len(b.last_samples) == 2


def test_fused_fault_repaired_verify():
    # a repaired schedule with NO staging rows (the dead link is not in
    # this shape's pattern; the dead aggregator is re-homed by election)
    # must fuse and verify byte-exact — fault coverage without refusal
    from tpu_aggcomm.faults import repair_schedule
    p = _pattern(nprocs=32, cb_nodes=8, data_size=64, comm_size=4,
                 placement=1)
    sched = repair_schedule(compile_method(1, p),
                            "deadlink:17>2,deadagg:a3")
    assert sched.n_staging == 0 and sched.fault
    _backend().run(sched, verify=True)


def test_fused_unrepaired_deadlink_fails_visibly():
    # UNREPAIRED dead-link realization must drop payload and fail
    # --verify loudly (the shared backends' injection rule) — never
    # deliver stale/zero bytes silently
    from dataclasses import replace

    from tpu_aggcomm.harness.verify import VerificationError
    p = _pattern(nprocs=8, cb_nodes=3, placement=1)
    sched = compile_method(1, p)
    agg = int(sched.pattern.rank_list[0])
    src = next(r for r in range(p.nprocs) if r != agg)
    bad = replace(sched, fault=f"deadlink:{src}>{agg}")
    with pytest.raises(VerificationError):
        _backend().run(bad, verify=True)


# ---------------------------------------------------------------------------
# named refusals — never a silent fallback


@pytest.mark.parametrize("method", COLLECTIVE)
def test_fused_refuses_collectives(method):
    with pytest.raises(UnfusableScheduleError, match="dense collective"):
        fuse_plan(compile_method(method, _pattern()))


def test_fused_refuses_tam():
    tam = [m for m in method_ids() if METHODS[m].tam]
    if not tam:
        pytest.skip("TAM engine not importable")
    sched = compile_method(tam[0], _pattern(nprocs=8, cb_nodes=2,
                                            proc_node=4))
    with pytest.raises(UnfusableScheduleError, match="TAM"):
        fuse_plan(sched)


def test_fused_refuses_staged_repair():
    # the same detour jax_shard refuses (relay staging rows) must refuse
    # here too, naming the jax_sim/local escape hatch
    from tpu_aggcomm.faults import repair_schedule
    sched = repair_schedule(compile_method(1, _pattern()), "deadlink:5>3")
    assert sched.n_staging > 0
    with pytest.raises(UnfusableScheduleError, match="staging rows"):
        fuse_plan(sched)


def test_fused_refuses_slow_injection():
    from tpu_aggcomm.faults import repair_schedule
    sched = repair_schedule(compile_method(1, _pattern()), "slow:r3*4.0")
    with pytest.raises(UnfusableScheduleError, match="slow-rank"):
        fuse_plan(sched)


def test_fused_edge_ceiling_named(monkeypatch):
    monkeypatch.setattr(fuse, "MAX_FUSED_EDGES", 4)
    with pytest.raises(UnfusableScheduleError, match="ceiling"):
        fuse_plan(compile_method(1, _pattern()))
    assert MAX_FUSED_EDGES > 4  # the real cap is untouched


def test_fused_refuses_round_prefix_truncation():
    with pytest.raises(ValueError, match="round-prefix truncation"):
        _backend()._one_rep(compile_method(1, _pattern()), upto=1)


def test_fused_refuses_profile_and_phases():
    sched = compile_method(1, _pattern())
    with pytest.raises(ValueError, match="ONE"):
        _backend().run(sched, profile_rounds=True)
    with pytest.raises(ValueError, match="FENCED"):
        _backend().run(sched, measured_phases=True)


def test_fused_off_tpu_named_error(monkeypatch):
    # interpret NOT requested on a CPU-only host: the first rep build
    # must raise the named environment error, not fall back silently
    monkeypatch.delenv("TPU_AGGCOMM_FUSED_INTERPRET", raising=False)
    b = PallasFusedBackend()
    with pytest.raises(FusedBackendError, match="interpret"):
        b.run(compile_method(1, _pattern()), verify=True)


def test_fused_interpret_env_gate(monkeypatch):
    monkeypatch.setenv("TPU_AGGCOMM_FUSED_INTERPRET", "1")
    PallasFusedBackend().run(compile_method(1, _pattern()), verify=True)


def test_runner_gate_refuses_unfusable_named():
    from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment
    import io
    cfg = ExperimentConfig(nprocs=8, cb_nodes=3, method=5, data_size=32,
                           comm_size=3, backend="pallas_fused",
                           results_csv=None)
    with pytest.raises(ValueError, match="pallas_fused does not support"):
        run_experiment(cfg, out=io.StringIO())


# ---------------------------------------------------------------------------
# round ordering: the semaphore chain IS the fence structure


@pytest.mark.parametrize("method", FUSABLE)
def test_semaphore_deps_match_check_round_fences(method):
    sched = compile_method(method, _pattern())
    plan = fuse_plan(sched)
    ids = [r for r, _e in plan.rounds]
    # the plan's rounds are exactly the schedule's data-edge rounds, in
    # strictly increasing order — no round merged away, none reordered
    assert ids == sorted({int(e[4]) for e in sched.data_edges()})
    # the wait graph totally orders consecutive rounds: transitively,
    # every round k+1 copy start is ordered after every round k wait —
    # the in-kernel form of the fence the checker's round-monotonicity
    # property proves on the op programs
    assert semaphore_deps(plan) == list(zip(ids, ids[1:]))
    from tpu_aggcomm.analysis.check import check_schedule
    report = check_schedule(sched)
    assert report["verdict"] == "PROVEN"
    assert report["properties"]["round_monotonicity"]["verdict"] == "PROVEN"
    # barrier fences survive the export byte-for-byte
    assert plan.barrier_counts() == barrier_rounds_of(sched)


def test_recv_slot_write_race_refused(monkeypatch):
    # two same-round writes into one (dst, slot) cell can race in flight;
    # fuse_plan must name the racing cell, mirroring the checker's
    # race-freedom property
    from tpu_aggcomm.core.schedule import Schedule
    sched = compile_method(1, _pattern())
    real = Schedule.data_edges_ext

    def racy(self):
        ext = real(self).copy()
        same = np.where(ext[:, 4] == ext[0, 4])[0]
        assert len(same) >= 2
        i, j = same[0], same[1]
        ext[j, 1], ext[j, 3] = ext[i, 1], ext[i, 3]
        return ext

    monkeypatch.setattr(Schedule, "data_edges_ext", racy)
    with pytest.raises(UnfusableScheduleError, match="written twice"):
        fuse_plan(sched)


# ---------------------------------------------------------------------------
# step export vs op-program traffic — the two accountings never drift


@pytest.mark.parametrize("method", FUSABLE)
def test_cross_check_export_matches(method):
    rep = cross_check_export(compile_method(method, _pattern()))
    assert rep["status"] == "MATCH"
    assert rep["edges"] > 0 and rep["rounds"] > 0


def test_cross_check_export_skips_unfusable():
    rep = cross_check_export(compile_method(COLLECTIVE[0], _pattern()))
    assert rep["status"] == "SKIPPED"
    assert "collective" in rep["reason"]


def test_cross_check_export_names_drift(monkeypatch):
    sched = compile_method(1, _pattern())
    real = plan_round_matrices(fuse_plan(sched))
    r0 = min(real)
    pair = next(iter(real[r0]))
    perturbed = {r: dict(c) for r, c in real.items()}
    perturbed[r0][pair] += 1

    monkeypatch.setattr(fuse, "plan_round_matrices", lambda _p: perturbed)
    with pytest.raises(FusedExportError, match=f"round {r0}"):
        cross_check_export(sched)


def test_export_sweep_gate_shape():
    rows = export_sweep(8, 3, 3, data_size=32, proc_node=1, agg_type=1)
    assert rows
    for r in rows:
        if r["method"] in COLLECTIVE:
            assert r["status"] == "SKIPPED", r
        elif r["method"] in FUSABLE:
            assert r["status"] == "MATCH", r
    assert sum(r["status"] == "MATCH" for r in rows) >= 10
    assert not any(r["status"] == "DRIFT" for r in rows)


def test_tune_sampler_races_fused(tmp_path):
    # the pallas_fused sampler rides the same cache-bypassing trial hook
    from tpu_aggcomm.tune.measure import make_pallas_fused_sampler
    import os
    os.environ["TPU_AGGCOMM_FUSED_INTERPRET"] = "1"
    try:
        sampler = make_pallas_fused_sampler(
            nprocs=8, data_size=32, proc_node=1, iters_small=5,
            iters_big=505, batch_trials=2)
        samples = sampler("m1:a3:c3:t1", 0)
        assert len(samples) == 2 and all(s > 0 for s in samples)
    finally:
        del os.environ["TPU_AGGCOMM_FUSED_INTERPRET"]


# ---------------------------------------------------------------------------
# purity: the schedule-analysis half must run where jax cannot import


def test_fuse_analysis_half_is_jax_free(tmp_path):
    code = _jaxfree.pure_import_code("tpu_aggcomm.native")
    env = _jaxfree.poisoned_env(tmp_path)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr


def test_fuse_plan_runs_jax_free(tmp_path):
    code = (
        "from tpu_aggcomm.core.methods import compile_method\n"
        "from tpu_aggcomm.core.pattern import AggregatorPattern\n"
        "from tpu_aggcomm.native.fuse import (cross_check_export,\n"
        "                                     fuse_plan)\n"
        "import sys\n"
        "s = compile_method(1, AggregatorPattern(8, 3, data_size=32,\n"
        "                                        comm_size=3))\n"
        "plan = fuse_plan(s)\n"
        "assert plan.n_edges > 0\n"
        "assert cross_check_export(s)['status'] == 'MATCH'\n"
        "assert 'jax' not in sys.modules\n")
    env = _jaxfree.poisoned_env(
        tmp_path, reason="fuse's plan/export half must run on a host "
                         "whose tunnel is wedged")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
