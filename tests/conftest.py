"""Test config: force an 8-device virtual CPU mesh before JAX import.

Mirrors the reference's simulated-topology strategy (SURVEY.md §4.2):
multi-"node" structure is exercised without real multi-chip hardware, via
XLA's host-platform device partitioning.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
