"""Test config: force an 8-device virtual CPU mesh before JAX import.

Mirrors the reference's simulated-topology strategy (SURVEY.md §4.2):
multi-"node" structure is exercised without real multi-chip hardware, via
XLA's host-platform device partitioning.
"""

import os

# Force CPU even when the axon TPU tunnel is registered (its sitecustomize
# sets jax_platforms programmatically, so the env var alone is not enough):
# the test suite always runs on the virtual 8-device mesh (one real chip
# can't host an 8-rank pattern; TPU runs happen via bench.py / the CLI).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
