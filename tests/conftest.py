"""Test config: an 8-device virtual CPU mesh, forced before JAX import.

Mirrors the reference's simulated-topology strategy (SURVEY.md §4.2):
multi-"node" structure is exercised without real multi-chip hardware, via
XLA's host-platform device partitioning. ``TPU_AGGCOMM_TEST_TPU=1`` opts
out of the CPU forcing so the platform-gated ``*_on_tpu`` tests can run
against the real chip — in that mode everything else is auto-skipped
(the 1-chip device set can't host the 8-rank meshes, and blanket runs
through the tunnel risk wedging it; see CLAUDE.md gotchas).
"""

import os

import pytest

_TPU_OPT_IN = os.environ.get("TPU_AGGCOMM_TEST_TPU") == "1"

if not _TPU_OPT_IN:
    # Force CPU even when the axon TPU tunnel is registered (its
    # sitecustomize sets jax_platforms programmatically, so the env var
    # alone is not enough).
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 `-m 'not slow'` gate; still runs "
        "in the plain full-suite invocation")


def pytest_collection_modifyitems(config, items):
    if not _TPU_OPT_IN:
        return
    skip = pytest.mark.skip(
        reason="TPU_AGGCOMM_TEST_TPU=1: only *_on_tpu tests run against "
               "the real chip; unset the var for the CPU-mesh suite")
    for item in items:
        # originalname survives parameterization ("foo_on_tpu[1]")
        name = getattr(item, "originalname", None) or item.name
        if not name.endswith("_on_tpu"):
            item.add_marker(skip)
