"""Watchtower tests (obs/watch.py + obs/slo.py — ISSUE 18).

The pins that define the subsystem:

- **One SLO arithmetic**: ``burn_rate``/``measure_window`` are shared
  verbatim between the evaluator, the server's live gauges
  (``LiveSlo``) and the artifact fold (``watch_registry``) — rendered
  gauge values equal re-computed window values float-exactly.
- **Seeded detection**: the changepoint scan is the regression-gate
  double gate (point step beyond tolerance AND seeded-bootstrap CI
  excluding zero); same streams + same seed ⟹ the same anomalies
  byte-for-byte.
- **Named causes**: every attribution verdict cites a stream from
  ``EVIDENCE_STREAMS`` and the fallback is UNEXPLAINED with the
  residual quantified — a bare "ANOMALY" is a regression, and
  ``validate_watch`` rejects it.
- **Artifacts are self-proving**: ``WATCH_r*.json`` validates,
  replays REPRODUCED from the stream basenames recorded inside it,
  and every corruption is named, not absorbed.
- **Crash honesty**: torn journal/trace lines are COUNTED into the
  integrity block (never silently skipped), admitted-but-unterminated
  requests are named lost — and ``inspect live`` surfaces the same
  counters.
- **jax-free**: obs/watch.py, obs/slo.py and ``cli inspect watch``
  run where ``import jax`` raises (poisoned-jax subprocess, the obs
  discipline — monitoring must answer on a wedged tunnel).
"""

import json
import os
import subprocess
import sys
import threading

import pytest

import _jaxfree

REPO = _jaxfree.REPO

from tpu_aggcomm.obs.regress import validate_watch
from tpu_aggcomm.obs.slo import (DEFAULT_SLO, SloError, burn_rate, load_slo,
                                 validate_slo)
from tpu_aggcomm.obs.watch import (CHANGE_TOLERANCE, EVIDENCE_STREAMS,
                                   LiveSlo, attribute_anomaly,
                                   detect_changepoint, evaluate_slo,
                                   measure_window, replay_watch,
                                   tail_journal, watch_registry,
                                   watch_streams, write_watch)
from tpu_aggcomm.resilience.journal import RunJournal

_SHAPE = {"method": 3, "nprocs": 8, "cb_nodes": 2, "comm_size": 2,
          "data_size": 64}


# ---------------------------------------------------------------------------
# Synthetic journals (the test_workload_profile recipe, plus cache/shed
# dispositions and lifecycle records the watchtower consumes).


def _stamps(scale=1.0):
    return {"admit": 0.0, "queue": 0.001 * scale, "batch": 0.002 * scale,
            "cache": 0.0021 * scale, "dispatch": 0.004 * scale,
            "respond": 0.0042 * scale}


def _write_journal(path, rows, *, torn_tail=False, lost_rid=None,
                   states=(), manifest=None):
    """``rows`` entries: {"stamps": ..., "cache": ..., "status": ...,
    "reason": ..., "deadline_ms": ...} — journal-field shaped."""
    j = RunJournal(str(path))
    fp = j.begin_session(manifest if manifest is not None
                         else {"jax": "0.0-test"})
    t0 = 1_700_000_000.0
    for i, row in enumerate(rows):
        j.record({"request": i}, fingerprint=fp, status="admitted",
                 shape=dict(_SHAPE), backend="jax_sim", iter=i,
                 t_unix=t0 + 0.05 * i, queue_depth=i % 3,
                 deadline_ms=row.get("deadline_ms"))
        status = row.get("status", "done")
        if status == "shed":
            j.record({"request": i}, fingerprint=fp, status="shed",
                     reason=row.get("reason", "queue-full"))
            continue
        stamps = row["stamps"]
        j.record({"request": i}, fingerprint=fp, status=status,
                 latency_s=stamps.get("respond"), batch_n=1,
                 cache=row.get("cache", "hit"), phases=dict(stamps),
                 batch_seq=i, batch_padded=row.get("padded", 1),
                 queue_depth=None)
    for st in states:
        j.record({"state": 1}, fingerprint=fp, status="state", **st)
    if lost_rid is not None:
        j.record({"request": lost_rid}, fingerprint=fp,
                 status="admitted", shape=dict(_SHAPE),
                 backend="jax_sim", t_unix=t0 + 99.0, queue_depth=0)
    if torn_tail:
        with open(path, "a") as fh:
            fh.write('{"key": {"request": 500}, "status": "don')
    return path


def _step_rows(n_before=6, n_after=6, after_scale=2.0, **over):
    rows = [dict({"stamps": _stamps(1.0)}, **over) for _ in range(n_before)]
    rows += [dict({"stamps": _stamps(after_scale)}, **over)
             for _ in range(n_after)]
    return rows


def _write_trace(path, walls_by_round):
    """A minimal trace stream: one run, one rep, two ranks per round —
    round_stats' wall (max over ranks) lands exactly on the given
    values."""
    events = [{"ev": "run", "id": 0, "method": 3, "name": "theta",
               "backend": "jax_sim", "nprocs": 8, "data_size": 64}]
    for rnd, wall in enumerate(walls_by_round):
        for rank in (0, 1):
            events.append({"ev": "span", "run": 0, "rep": 0, "rank": rank,
                           "round": rnd, "bucket": "sendrecv",
                           "dur_s": wall if rank == 0 else wall * 0.5})
    with open(path, "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")
    return path


# ---------------------------------------------------------------------------
# The SLO spec + window arithmetic.


def test_slo_spec_validation_and_load(tmp_path):
    assert validate_slo(DEFAULT_SLO) == []
    bad = json.loads(json.dumps(DEFAULT_SLO))
    bad["objectives"][0]["target"] = 1.0  # zero error budget: refused
    errs = validate_slo(bad)
    assert errs and any("target" in e for e in errs)
    bad2 = json.loads(json.dumps(DEFAULT_SLO))
    bad2["objectives"][0]["kind"] = "vibes"
    assert any("kind" in e for e in validate_slo(bad2))
    # load_slo: parse/validate errors raise SloError naming the file
    p = tmp_path / "slo.json"
    p.write_text("{not json")
    with pytest.raises(SloError, match="slo.json"):
        load_slo(str(p))
    p.write_text(json.dumps(DEFAULT_SLO))
    assert load_slo(str(p))["schema"] == DEFAULT_SLO["schema"]


def test_burn_rate_is_the_one_arithmetic():
    assert burn_rate(0, 10, 0.1) == 0.0
    assert burn_rate(1, 10, 0.1) == 1.0   # exactly on budget
    assert burn_rate(2, 10, 0.1) == 2.0   # burning 2x
    assert burn_rate(0, 0, 0.1) is None   # vacuous, not compliant


def test_measure_window_kinds_and_vacuous_windows():
    rows = [{"rid": 0, "status": "done", "cache": "hit", "wall_s": 0.01,
             "phases": {}, "deadline_ms": 100, "batch": {"seq": 0, "n": 3,
                                                         "padded": 4}},
            {"rid": 1, "status": "done", "cache": "miss", "wall_s": 5.0,
             "phases": {}, "deadline_ms": None, "batch": {"seq": 0, "n": 3,
                                                          "padded": 4}},
            {"rid": 2, "status": "shed", "shed_reason": "deadline",
             "wall_s": None, "phases": {}, "deadline_ms": 50,
             "batch": None}]
    warm = measure_window(rows, {"kind": "warm-latency", "target": 0.9,
                                 "threshold_s": 2.0})
    # only the done+hit request qualifies; its wall is under threshold
    assert (warm["total"], warm["bad"], warm["sli"]) == (1, 0, 0.01)
    good = measure_window(rows, {"kind": "goodput", "target": 0.9})
    assert (good["total"], good["bad"]) == (3, 1)
    assert good["burn"] == burn_rate(1, 3, 1.0 - 0.9)  # SAME arithmetic
    assert not good["compliant"]
    shed = measure_window(rows, {"kind": "shed-rate", "target": 0.9})
    assert (shed["total"], shed["bad"]) == (3, 1)
    dl = measure_window(rows, {"kind": "deadline-miss", "target": 0.9})
    # rid 0 inside its deadline; rid 2 is a deadline shed
    assert (dl["total"], dl["bad"]) == (2, 1)
    pad = measure_window(rows, {"kind": "padding-waste", "target": 0.5})
    # one unique batch: 3 of 4 padded slots filled
    assert (pad["total"], pad["bad"], pad["sli"]) == (4, 1, 0.75)
    # vacuous window: burn None, compliant None — not a violation
    vac = measure_window([], {"kind": "goodput", "target": 0.9})
    assert vac["burn"] is None and vac["compliant"] is None
    with pytest.raises(ValueError, match="vibes"):
        measure_window(rows, {"kind": "vibes", "target": 0.9})


def test_evaluate_slo_tumbling_windows_include_the_tail():
    rows = [{"rid": i, "status": "done", "cache": "hit",
             "wall_s": 0.01, "phases": {}, "deadline_ms": None,
             "batch": None} for i in range(10)]
    ev = evaluate_slo(rows, DEFAULT_SLO)
    assert ev["compliant"] is True
    good = [o for o in ev["objectives"] if o["kind"] == "goodput"][0]
    fast = good["windows"]["fast"]
    # 10 rows over 8-request tumbling windows = one full + one partial
    assert [e["n"] for e in fast] == [8, 2]
    assert (fast[0]["start_rid"], fast[1]["end_rid"]) == (0, 9)


# ---------------------------------------------------------------------------
# Seeded changepoint detection.


def test_detect_changepoint_seeded_and_double_gated():
    flat = [1.0] * 16
    assert detect_changepoint(flat) is None
    short = [1.0] * 3 + [5.0] * 4      # < 2 * MIN_SEGMENT
    assert detect_changepoint(short) is None
    step = [1.0] * 6 + [2.0] * 6
    det = detect_changepoint(step, seed=0)
    assert det is not None and det["index"] == 6
    assert det["direction"] == "up" and det["delta_rel"] > CHANGE_TOLERANCE
    lo, hi = det["ci_rel"]
    assert lo > 0  # CI excludes zero
    # seeded: byte-identical on re-run; a different seed changes only
    # the bootstrap CI, never the split
    again = detect_changepoint(step, seed=0)
    assert json.dumps(det) == json.dumps(again)
    other = detect_changepoint(step, seed=7)
    assert other["index"] == det["index"] and other["seed"] == 7
    # a step under tolerance is discarded by the point gate
    assert detect_changepoint([1.0] * 6 + [1.1] * 6) is None
    down = detect_changepoint([2.0] * 6 + [1.0] * 6)
    assert down["direction"] == "down" and down["ci_rel"][1] < 0


# ---------------------------------------------------------------------------
# Root-cause attribution: a fixed chain of NAMED verdicts.


_DET = {"index": 6, "delta_rel": 0.5, "direction": "up"}


def _rows_for(split=6, n=12, **after_over):
    rows = []
    for i in range(n):
        r = {"rid": i, "status": "done", "cache": "hit", "wall_s": 0.01,
             "phases": {"cache": 0.001}, "shed_reason": None,
             "deadline_ms": None, "batch": None}
        if i >= split:
            r.update(after_over)
        rows.append(r)
    return rows


_NO_EVIDENCE = {"sessions": [], "states": [], "resilience_retries":
                {"count": 0, "sites": []}, "explain": {}}


def test_attribution_chain_every_verdict_named():
    rows = _rows_for()
    # ledger: manifest drift between journal sessions
    ev = dict(_NO_EVIDENCE, sessions=[
        {"fingerprint": "a", "drift": []},
        {"fingerprint": "b", "drift": ["versions.jax: 1 -> 2"]}])
    v = attribute_anomaly(_DET, rows=rows, evidence=ev, split_rid=6)
    assert v["cause"] == "cache-eviction/compile-storm"
    assert v["evidence"] == "ledger" and "versions.jax" in v["detail"]
    # ledger: evictions after the step
    v = attribute_anomaly(_DET, rows=_rows_for(cache="evict"),
                          evidence=_NO_EVIDENCE, split_rid=6)
    assert v["evidence"] == "ledger" and "eviction" in v["detail"]
    # ledger: miss-fraction rise
    v = attribute_anomaly(_DET, rows=_rows_for(cache="miss"),
                          evidence=_NO_EVIDENCE, split_rid=6)
    assert v["evidence"] == "ledger" and "miss fraction" in v["detail"]
    # resilience: DEGRADED lifecycle
    ev = dict(_NO_EVIDENCE, states=[{"state": "degraded", "prev": "ready",
                                     "reason": "retries_exhausted"}])
    v = attribute_anomaly(_DET, rows=rows, evidence=ev, split_rid=6)
    assert v["cause"] == "tunnel-degradation"
    assert v["evidence"] == "resilience"
    # resilience: retry attempts in the trace records
    ev = dict(_NO_EVIDENCE, resilience_retries={"count": 3,
                                                "sites": ["dispatch"]})
    v = attribute_anomaly(_DET, rows=rows, evidence=ev, split_rid=6)
    assert v["evidence"] == "resilience" and "dispatch" in v["detail"]
    # shed: cascade with the reasons named
    v = attribute_anomaly(_DET, evidence=_NO_EVIDENCE, split_rid=6,
                          rows=_rows_for(status="shed", wall_s=None,
                                         shed_reason="queue-full"))
    assert v["cause"] == "shed-cascade" and v["evidence"] == "shed"
    assert "queue-full" in v["detail"]
    # explain: the cost model names the bound
    v = attribute_anomaly(
        _DET, rows=rows, evidence=_NO_EVIDENCE,
        explain_rounds=[{"round": 7, "verdict": "incast-bound",
                         "deviation_rel": 0.0}])
    assert v["cause"] == "incast-bound" and v["evidence"] == "explain"
    # fallback: UNEXPLAINED with the residual QUANTIFIED — never bare
    v = attribute_anomaly(_DET, rows=rows, evidence=_NO_EVIDENCE,
                          split_rid=6)
    assert v["cause"] == "UNEXPLAINED" and v["evidence"] == "none"
    assert "%" in v["detail"]
    # every verdict above named a stream from the contract enum
    assert all(e in EVIDENCE_STREAMS for e in
               ("ledger", "resilience", "shed", "explain", "none"))


# ---------------------------------------------------------------------------
# The pipeline: tail → evaluate → detect → attribute.


def test_tail_journal_counts_torn_lines(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [{"stamps": _stamps()}], torn_tail=True)
    with open(jpath, "a") as fh:
        fh.write("\n[1, 2]\n")  # parseable but not a record: counted too
    tail = tail_journal(str(jpath))
    assert tail["skipped_lines"] == 2
    assert len(tail["sessions"]) == 1 and len(tail["records"]) == 2
    # a missing journal is empty, not an exception
    assert tail_journal(str(tmp_path / "nope.jsonl"))["records"] == []


def test_watch_streams_detects_and_stays_deterministic(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl", _step_rows())
    body = watch_streams([str(jpath)])
    assert body["problems"] == []
    assert body["requests"]["admitted"] == 12
    # wall_s is the canonical phase sum (identical computation)
    for r in body["per_request"]:
        assert r["wall_s"] == sum(r["phases"].values())
    assert body["evaluation"]["compliant"] is True
    # the engineered step is found, located, and honestly UNEXPLAINED
    [a] = body["anomalies"]
    assert a["stream"] == "request-walls" and a["at_rid"] == 6
    assert a["cause"] == "UNEXPLAINED" and a["evidence"] == "none"
    assert "%" in a["detail"]
    # deterministic: same streams + seed ⟹ byte-identical body
    again = watch_streams([str(jpath)])
    assert json.dumps(body, sort_keys=True) == \
        json.dumps(again, sort_keys=True)
    # an invalid SLO spec is refused by name, not absorbed
    bad = json.loads(json.dumps(DEFAULT_SLO))
    bad["objectives"][0]["target"] = 2.0
    with pytest.raises(ValueError, match="invalid SLO spec"):
        watch_streams([str(jpath)], slo=bad)


def test_watch_streams_attributes_miss_storm_to_ledger(tmp_path):
    rows = _step_rows()
    for r in rows[6:]:
        r["cache"] = "miss"
    jpath = _write_journal(tmp_path / "serve.journal.jsonl", rows)
    [a] = watch_streams([str(jpath)])["anomalies"]
    assert a["cause"] == "cache-eviction/compile-storm"
    assert a["evidence"] == "ledger"


def test_watch_streams_round_walls_and_degraded(tmp_path):
    jpath = _write_journal(
        tmp_path / "serve.journal.jsonl",
        [{"stamps": _stamps()}] * 2,
        states=({"state": "degraded", "prev": "ready",
                 "reason": "retries_exhausted"},))
    tpath = _write_trace(tmp_path / "run.trace.jsonl",
                         [1e-3] * 6 + [3e-3] * 6)
    body = watch_streams([str(jpath)], [str(tpath)])
    [a] = body["anomalies"]
    assert a["stream"] == "round-walls:run.trace.jsonl#run0"
    assert a["at_round"] == 6
    # the DEGRADED lifecycle record wins the attribution chain
    assert a["cause"] == "tunnel-degradation"
    assert a["evidence"] == "resilience"
    assert body["evidence"]["states"][0]["state"] == "degraded"


def test_integrity_counts_torn_and_lost(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [{"stamps": _stamps()}] * 2,
                           torn_tail=True, lost_rid=99)
    tpath = tmp_path / "run.trace.jsonl"
    _write_trace(tpath, [1e-3] * 4)
    with open(tpath, "a") as fh:
        fh.write('{"ev": "span", "run": 0, "re')
    body = watch_streams([str(jpath)], [str(tpath)])
    assert body["integrity"] == {"journal_torn_lines": 1,
                                 "trace_torn_lines": 1,
                                 "lost_requests": [99]}
    assert body["requests"]["lost"] == [99]


# ---------------------------------------------------------------------------
# Artifacts: validate, replay, and name every corruption.


def test_artifact_validates_replays_and_names_corruption(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl", _step_rows())
    body = watch_streams([str(jpath)])
    art = tmp_path / "WATCH_r07.json"
    blob = write_watch(str(art), body)
    assert validate_watch(blob) == []
    rep = replay_watch(str(art))
    assert rep["verdict"] == "REPRODUCED", rep["problems"]

    def probe(mutate, want):
        bad = json.loads(json.dumps(blob))
        mutate(bad)
        errs = validate_watch(bad)
        assert errs and any(want in e for e in errs), (want, errs)

    probe(lambda b: b["per_request"][0].__setitem__("wall_s", 1.0),
          "canonical")
    probe(lambda b: b["evaluation"].__setitem__("compliant", False),
          "re-derive")
    probe(lambda b: b["anomalies"][0].__setitem__("cause", "ANOMALY"),
          "re-derive")
    probe(lambda b: b["anomalies"][0].__setitem__("evidence", "vibes"),
          "evidence stream")
    probe(lambda b: b.__setitem__("anomalies", []), "omits")
    probe(lambda b: b["requests"].__setitem__("completed", 99), "rows")
    probe(lambda b: b.__setitem__("problems", ["oops"]),
          "must not be committed")
    # ...and a doctored artifact must fail --replay with the key named
    doctored = json.loads(json.dumps(blob))
    doctored["requests"]["completed"] = 99
    with open(tmp_path / "WATCH_r08.json", "w") as fh:
        json.dump(doctored, fh)
    rep = replay_watch(str(tmp_path / "WATCH_r08.json"))
    assert rep["verdict"] == "MISMATCH"
    assert any("'requests'" in p for p in rep["problems"])
    # a replay whose streams went missing names THEM
    os.rename(jpath, tmp_path / "gone.jsonl")
    rep = replay_watch(str(art))
    assert rep["verdict"] == "MISMATCH"
    assert any("not found" in p for p in rep["problems"])


def test_committed_exemplar_artifact_accepts():
    path = os.path.join(REPO, "WATCH_r01.json")
    with open(path) as fh:
        blob = json.load(fh)
    assert validate_watch(blob, "WATCH_r01.json") == []
    rep = replay_watch(path)
    assert rep["verdict"] == "REPRODUCED", rep["problems"]
    # the committed exemplar's one anomaly is the honest kind: a step
    # with no matching evidence, quantified — never a bare "ANOMALY"
    [a] = blob["anomalies"]
    assert a["cause"] == "UNEXPLAINED" and a["evidence"] in EVIDENCE_STREAMS


# ---------------------------------------------------------------------------
# The live side: gauges share measure_window, the hook is gated.


def test_live_slo_gauges_match_measure_window():
    from tpu_aggcomm.obs.export import MetricsRegistry
    from tpu_aggcomm.obs.regress import parse_openmetrics
    reg = MetricsRegistry()
    live = LiveSlo(reg)
    rows = []
    for i in range(10):
        wall = 0.01 if i < 7 else 5.0
        live.record(status="done", wall_s=wall, cache="hit")
        rows.append({"rid": i, "status": "done", "wall_s": wall,
                     "phases": {}, "cache": "hit", "shed_reason": None,
                     "deadline_ms": None, "batch": None})
    parsed = parse_openmetrics(reg.render())
    samples = {(s["name"], tuple(sorted(s["labels"].items()))): s["value"]
               for s in parsed["samples"]}
    warm = [o for o in DEFAULT_SLO["objectives"]
            if o["kind"] == "warm-latency"][0]
    for w in DEFAULT_SLO["windows"]:
        want = measure_window(rows[-w["requests"]:], warm)["burn"]
        got = samples.get(("tpu_aggcomm_slo_burn_rate",
                           (("objective", warm["name"]),
                            ("window", w["name"]))))
        assert got == want  # identical arithmetic ⟹ == on floats
    with pytest.raises(ValueError, match="invalid SLO spec"):
        LiveSlo(MetricsRegistry(), slo={"schema": "slo-v1", "windows": [],
                                        "objectives": []})


def test_watch_registry_folds_artifact_numbers_verbatim(tmp_path):
    from tpu_aggcomm.obs.export import MetricsRegistry
    jpath = _write_journal(tmp_path / "serve.journal.jsonl", _step_rows())
    blob = write_watch(str(tmp_path / "WATCH_r01.json"),
                       watch_streams([str(jpath)]))
    reg = MetricsRegistry()
    watch_registry(blob, reg)
    text = reg.render()
    assert "tpu_aggcomm_slo_burn_rate" in text
    assert "tpu_aggcomm_slo_compliant_all 1.0" in text
    assert "tpu_aggcomm_watch_anomalies 1.0" in text


def test_serve_hook_is_import_gated(tmp_path, monkeypatch):
    """An unarmed server never constructs LiveSlo (nor loads
    obs.export/obs.watch on its account); an armed one records terminal
    requests through it."""
    from tpu_aggcomm.serve import executor
    from tpu_aggcomm.serve.protocol import ServeClient
    from tpu_aggcomm.serve.server import ScheduleServer
    monkeypatch.setattr(executor, "build_chain",
                        lambda schedule, backend_name: (object(), 1e-3))
    monkeypatch.setattr(
        executor, "execute_batch",
        lambda chain, reqs: [{"verified": None, "error": None}
                             for _ in reqs])
    monkeypatch.delenv("TPU_AGGCOMM_METRICS_PORT", raising=False)
    srv = ScheduleServer(port=0, max_batch=2, batch_window_s=0.01)
    assert srv._slo is None  # OFF by default: the hot path stays bare
    srv.close()
    srv = ScheduleServer(port=0, max_batch=2, batch_window_s=0.01,
                         metrics_port=0)
    assert srv._slo is not None
    srv.start()
    try:
        with ServeClient(srv.port, timeout=120.0) as c:
            assert c.run(**dict(_SHAPE, iter=0))["ok"]
    finally:
        srv.stop()
        srv.close()
    text = srv._registry.render()
    assert "tpu_aggcomm_slo_burn_rate" in text
    assert 'tpu_aggcomm_slo_compliant{objective="goodput"} 1.0' in text


# ---------------------------------------------------------------------------
# Satellites: inspect live integrity + history discovery.


def test_live_surfaces_torn_and_lost_by_name(tmp_path):
    from tpu_aggcomm.obs.live import (render_live, sweep_status,
                                      tail_events_counted)
    tpath = tmp_path / "x.trace.jsonl"
    _write_trace(tpath, [1e-3])
    with open(tpath, "a") as fh:
        fh.write('{"ev": "span", "tor')
    events, skipped = tail_events_counted(str(tpath))
    assert skipped == 1 and events[0]["ev"] == "run"
    # a serve journal pointed at inspect live: torn lines + the
    # admitted-but-never-terminal request land in the integrity block
    csv = tmp_path / "r.csv"
    _write_journal(str(csv) + ".journal.jsonl",
                   [{"stamps": _stamps()}], torn_tail=True, lost_rid=42)
    status = sweep_status(str(csv), trace_paths=[str(tpath)])
    assert status["integrity"]["journal_torn_lines"] == 1
    assert status["integrity"]["trace_torn_lines"] == 1
    assert status["integrity"]["lost_requests"] == [42]
    text = render_live(status)
    assert "torn journal line" in text and "LOST in flight" in text
    assert "[42]" in text


def test_history_discovers_watch_series(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl", _step_rows())
    write_watch(str(tmp_path / "WATCH_r02.json"),
                watch_streams([str(jpath)]))
    from tpu_aggcomm.obs.history import (build_index, check_trends,
                                         watch_series)
    series = watch_series(str(tmp_path))
    pts = series["slo worst burn"]
    assert len(pts) == 1 and pts[0]["round"] == 2
    assert pts[0]["unit"] == "x" and pts[0]["samples_n"] == 12
    assert pts[0]["compliant"] is True and pts[0]["anomalies"] == 1
    idx = build_index(str(tmp_path))
    assert [w["file"] for w in idx["watch"]] == ["WATCH_r02.json"]
    assert idx["watch"][0]["causes"] == ["UNEXPLAINED"]
    assert "slo worst burn" in check_trends(str(tmp_path))["series"]


# ---------------------------------------------------------------------------
# The jax-free pins (the obs discipline, subprocess-enforced).


def test_watchtower_is_jaxfree(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl", _step_rows())
    code = (
        _jaxfree.pure_import_code("tpu_aggcomm.obs.watch") +
        "; " + _jaxfree.pure_import_code("tpu_aggcomm.obs.slo") +
        "; from tpu_aggcomm.obs.watch import watch_streams, write_watch, "
        "replay_watch"
        f"; b = watch_streams([{str(jpath)!r}])"
        "; assert b['problems'] == [] and len(b['anomalies']) == 1"
        "; assert b['anomalies'][0]['cause'] == 'UNEXPLAINED'"
        f"; write_watch({str(tmp_path / 'WATCH_r01.json')!r}, b)"
        f"; r = replay_watch({str(tmp_path / 'WATCH_r01.json')!r})"
        "; assert r['verdict'] == 'REPRODUCED', r['problems']"
        "; import sys; assert 'jax' not in sys.modules")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=str(tmp_path),
        env=_jaxfree.poisoned_env(
            tmp_path, "the watchtower must answer where a wedged tunnel "
                      "hangs import jax"),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_cli_inspect_watch_is_jaxfree(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl", _step_rows())
    env = _jaxfree.poisoned_env(
        tmp_path, "inspect watch must answer on a wedged tunnel")
    art = tmp_path / "WATCH_r03.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "watch",
         str(jpath), "--seed", "0", "--json", str(art)],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "watchtower over" in proc.stdout
    assert "ANOMALY [request-walls]" in proc.stdout
    assert "watch artifact written" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "watch",
         "--replay", str(art)],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "REPRODUCED" in proc.stdout


def test_cli_follow_refuses_json(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [{"stamps": _stamps()}])
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "watch",
         str(jpath), "--follow", "--json", str(tmp_path / "w.json")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode != 0
    assert "--follow" in proc.stderr and "--json" in proc.stderr
