"""Pure-layer tests: placements, node maps, robin maps, 2-level metadata.

Oracle values are derived from the reference formulas (cited per test)."""

import numpy as np
import pytest

from tpu_aggcomm.core.meta import aggregator_meta_information
from tpu_aggcomm.core.pattern import (AggregatorPattern, Direction, Placement,
                                      create_aggregator_list, node_robin_map,
                                      reorder_ranklist)
from tpu_aggcomm.core.topology import static_node_assignment


class TestPlacement:
    def test_first(self):
        # mpi_test.c:1971-1977 (type 0)
        np.testing.assert_array_equal(create_aggregator_list(32, 5, 0),
                                      [0, 1, 2, 3, 4])

    def test_spread_readme_config(self):
        # README config: 32 procs, 14 aggregators, type 1 (default)
        lst = create_aggregator_list(32, 14, 1)
        assert len(lst) == 14
        assert len(set(lst.tolist())) == 14
        assert all(0 <= r < 32 for r in lst)
        # reference formula: remainder = 32/14 = 2, ceiling = 3, floor = 2
        # i<2: 3i ; else: 6 + 2(i-2)
        expect = [0, 3, 6, 8, 10, 12, 14, 16, 18, 20, 22, 24, 26, 28]
        np.testing.assert_array_equal(lst, expect)

    def test_spread_even_divide(self):
        lst = create_aggregator_list(32, 4, 1)
        # remainder = 8 >= cb_nodes, so all blocks use ceiling = 8
        np.testing.assert_array_equal(lst, [0, 8, 16, 24])

    def test_spread_shift(self):
        lst1 = create_aggregator_list(64, 4, 1)
        lst2 = create_aggregator_list(64, 4, 2)
        np.testing.assert_array_equal(lst2, (lst1 - 16) % 64)

    def test_node_robin_placement(self):
        # mpi_test.c:1991-2003: stride proc_node, wrap to lap%proc_node+1
        lst = create_aggregator_list(16, 6, 3, proc_node=4)
        np.testing.assert_array_equal(lst, [0, 4, 8, 12, 1, 5])

    def test_all_placements_unique_and_bounded(self):
        for procs, cb in [(8, 3), (32, 14), (64, 16), (17, 5)]:
            for t in [0, 1, 2]:
                lst = create_aggregator_list(procs, cb, t)
                assert len(set(lst.tolist())) == cb, (procs, cb, t)
                assert all(0 <= r < procs for r in lst)


class TestRobinMap:
    def test_stride(self):
        # mpi_test.c:1116-1133: procs=8, proc_node=2 -> 0,2,4,6,1,3,5,7
        np.testing.assert_array_equal(node_robin_map(8, 2),
                                      [0, 2, 4, 6, 1, 3, 5, 7])

    def test_permutation(self):
        for procs, pn in [(8, 2), (12, 3), (16, 4), (10, 5)]:
            m = node_robin_map(procs, pn)
            assert sorted(m.tolist()) == list(range(procs))


class TestNodeAssignment:
    def test_contiguous(self):
        # lustre_driver_test.c:402-427 (type 0)
        na = static_node_assignment(10, 4, 0)
        assert na.nnodes == 3
        np.testing.assert_array_equal(na.node_of,
                                      [0, 0, 0, 0, 1, 1, 1, 1, 2, 2])
        np.testing.assert_array_equal(na.proxies, [0, 4, 8])
        np.testing.assert_array_equal(na.node_sizes, [4, 4, 2])

    def test_round_robin(self):
        # lustre_driver_test.c:365-401 (type 1): nprocs=10, nprocs_node=4
        # remainder=2, temp=2, nrecvs=3; ranks 0..5 cycle 3 nodes, 6..9 cycle 2
        na = static_node_assignment(10, 4, 1)
        assert na.nnodes == 3
        np.testing.assert_array_equal(na.node_of,
                                      [0, 1, 2, 0, 1, 2, 0, 1, 0, 1])
        np.testing.assert_array_equal(na.local_ranks(0), [0, 3, 6, 8])
        np.testing.assert_array_equal(na.local_ranks(2), [2, 5])
        np.testing.assert_array_equal(na.proxies, [0, 1, 2])

    def test_even_divide(self):
        na = static_node_assignment(16, 4, 0)
        assert na.nnodes == 4
        np.testing.assert_array_equal(na.node_sizes, [4, 4, 4, 4])
        assert na.proxy_of(13) == 12
        assert na.is_proxy(12) and not na.is_proxy(13)


class TestReorderRanklist:
    def test_round_robin_across_nodes(self):
        # lustre_driver_test.c:1374-1414
        na = static_node_assignment(8, 4, 0)  # nodes: {0-3}, {4-7}
        ranks = np.array([0, 1, 2, 4])
        out = reorder_ranklist(na.node_of, ranks, na.nnodes)
        # deal alternating node0, node1, node0, ... -> 0, 4, 1, 2
        np.testing.assert_array_equal(out, [0, 4, 1, 2])


class TestAggregatorMeta:
    def test_even_spread_mode0(self):
        # lustre_driver_test.c:170-179: co local aggs evenly over node ranks
        na = static_node_assignment(8, 4, 0)
        meta = aggregator_meta_information(na, np.array([0, 4]), co=2, mode=0)
        # node 0 ranks [0,1,2,3]: lnp=4, co2=2 -> aggs at ranks[0], ranks[2]
        np.testing.assert_array_equal(meta.local_aggregators, [0, 2, 4, 6])
        # binding: every rank bound to an agg on its own node; aggs own themselves
        assert meta.owner_of[0] == 0 and meta.owner_of[2] == 2
        assert all(meta.owner_of[r] in (0, 2) for r in range(4))
        assert all(meta.owner_of[r] in (4, 6) for r in range(4, 8))

    def test_superset_mode1(self):
        # lustre_driver_test.c:144-167: local aggs ⊇ node's global aggs
        na = static_node_assignment(8, 4, 0)
        meta = aggregator_meta_information(na, np.array([1, 3, 5]), co=2, mode=1)
        assert 1 in meta.local_aggregators and 3 in meta.local_aggregators
        assert 5 in meta.local_aggregators
        # node 0 has 2 global aggs -> exactly those; node 1 has 1, topped to 2
        node1 = [a for a in meta.local_aggregators if a >= 4]
        assert len(node1) == 2 and 5 in node1

    def test_every_rank_bound(self):
        for co in [1, 2, 3]:
            for mode in [0, 1]:
                na = static_node_assignment(12, 4, 0)
                meta = aggregator_meta_information(na, np.array([0, 6]), co=co,
                                                   mode=mode)
                assert (meta.owner_of >= 0).all()
                # owner is always on the same node
                for r in range(12):
                    assert na.node_of[meta.owner_of[r]] == na.node_of[r]
                # every local aggregator owns itself
                for a in meta.local_aggregators:
                    assert meta.owner_of[a] == a


class TestPattern:
    def test_basic(self):
        p = AggregatorPattern(32, 14, data_size=2048)
        assert p.is_agg.sum() == 14
        assert p.agg_index[int(p.rank_list[3])] == 3
        assert p.total_bytes == 32 * 14 * 2048
        assert p.reversed().direction is Direction.MANY_TO_ALL

    def test_dense_counts(self):
        p = AggregatorPattern(8, 3, data_size=64)
        send, recv = p.dense_counts()
        assert send.sum() == 8 * 3 * 64
        np.testing.assert_array_equal(send.T, recv)
        # only aggregator columns are nonzero
        assert (send[:, p.rank_list] == 64).all()

    def test_validation(self):
        with pytest.raises(ValueError):
            AggregatorPattern(4, 5)
        with pytest.raises(ValueError):
            AggregatorPattern(0, 0)
