"""Roofline bytes-touched model (VERDICT r4 item 4) + the single-device
round specialization it motivated.

The model is host-side and lands on CPU now; the flagship measurement
(42.3 ms/rep @ n=4096 d=2048, RESULTS_TPU.md) rides the TPU capture.
These tests pin the model's structure — edge accounting, the
intermediate term's appearance/disappearance, fenced vs optimistic
bounds — and pin the fused single-dev lowering byte-for-byte against
the general path and the verifier.
"""

import numpy as np
import pytest

import jax

from tpu_aggcomm.core.methods import compile_method
from tpu_aggcomm.core.pattern import AggregatorPattern
from tpu_aggcomm.harness.roofline import (HBM_V5E_GBPS, chain_overhead_bytes,
                                          floor_seconds, rep_bytes)

FLAGSHIP = dict(nprocs=4096, cb_nodes=256, data_size=2048,
                comm_size=999999999)   # the RESULTS_TPU.md d=2048 cell


class TestModel:
    def test_unthrottled_m1_moves_pattern_bytes_once(self):
        p = AggregatorPattern(**FLAGSHIP)
        rb = rep_bytes(compile_method(1, p), lowering="jax_shard", ndev=1)
        pattern = 4096 * 256 * 2048
        assert rb.edges == 4096 * 256
        assert rb.gather_read == pattern
        assert rb.scatter_write == pattern
        assert rb.rounds == 1
        assert rb.intermediate == 0            # fused single-dev rounds
        assert rb.refence_walks == 0           # nothing to re-fence
        # the floor the measured 42.3 ms is judged against
        assert 0.005 < rb.floor_seconds(HBM_V5E_GBPS) < 0.010

    @pytest.mark.slow  # ~60 s: builds a 4096-rank schedule twice — a
    def test_throttle_rounds_add_refence_walks_only(self):  # stress cell
        p = AggregatorPattern(nprocs=4096, cb_nodes=256, data_size=2048,
                              comm_size=1024)  # 4 rounds
        rb1 = rep_bytes(compile_method(1, AggregatorPattern(**FLAGSHIP)),
                        lowering="jax_shard", ndev=1)
        rb4 = rep_bytes(compile_method(1, p), lowering="jax_shard", ndev=1)
        assert rb4.rounds == 4
        # same pattern volume; only the fencing bound grows
        assert rb4.gather_read == rb1.gather_read
        assert rb4.total() == rb1.total()
        assert rb4.total(fenced=True) > rb4.total()
        assert rb4.refence_walks == 2 * 3 * rb4.zero_init

    def test_multi_device_pays_the_collective_boundary(self):
        p = AggregatorPattern(nprocs=64, cb_nodes=8, data_size=256,
                              comm_size=64)
        sched = compile_method(1, p)
        rb1 = rep_bytes(sched, lowering="jax_shard", ndev=1)
        rb8 = rep_bytes(sched, lowering="jax_shard", ndev=8)
        assert rb1.intermediate == 0
        # one write + one read of the padded block volume
        assert rb8.intermediate >= 2 * rb8.edges * p.data_size
        assert rb8.total() > rb1.total()

    def test_jax_sim_has_no_collective_term(self):
        p = AggregatorPattern(nprocs=32, cb_nodes=14, data_size=2048,
                              comm_size=3)
        rb = rep_bytes(compile_method(1, p), lowering="jax_sim")
        assert rb.intermediate == 0
        assert rb.rounds == 11
        assert rb.gather_read == 32 * 14 * 2048

    def test_collective_and_guards(self):
        p = AggregatorPattern(nprocs=32, cb_nodes=14, data_size=2048,
                              comm_size=3)
        rb = rep_bytes(compile_method(8, p), lowering="jax_sim")
        assert rb.rounds == 1 and rb.edges == 32 * 14
        with pytest.raises(ValueError, match="tam_rep_bytes"):
            rep_bytes(compile_method(15, p))
        with pytest.raises(ValueError, match="single-device"):
            rep_bytes(compile_method(1, p), lowering="jax_sim", ndev=2)
        assert chain_overhead_bytes(compile_method(1, p)) > 0
        assert floor_seconds(819e9, 819.0) == pytest.approx(1.0)

    def test_tam_rep_bytes(self):
        from tpu_aggcomm.harness.roofline import tam_rep_bytes

        p = AggregatorPattern(nprocs=32, cb_nodes=14, data_size=2048,
                              comm_size=3, proc_node=4)
        for mid in (15, 16):
            rb = tam_rep_bytes(compile_method(mid, p))
            assert rb.edges == 32 * 14
            assert rb.gather_read == rb.scatter_write == 32 * 14 * 2048
            # the two fenced hop boundaries each materialize E rows
            assert rb.intermediate == 4 * 32 * 14 * 2048
            assert rb.rounds == 3 and rb.refence_walks == 0
            assert rb.floor_seconds() > 0
        with pytest.raises(ValueError, match="models TAM"):
            tam_rep_bytes(compile_method(1, p))


class TestSingleDevRounds:
    """The fused 1-device lowering (skips the identity all_to_all and the
    padding mask) must deliver byte-identical results to the general
    path — the flagship tier's correctness gate."""

    @pytest.mark.parametrize("method", [1, 8, 13, 17])
    def test_byte_equal_vs_multi_device_path(self, method):
        from tpu_aggcomm.backends.jax_shard import JaxShardBackend

        p = AggregatorPattern(nprocs=16, cb_nodes=6, data_size=256,
                              comm_size=4)
        sched = compile_method(method, p)
        one = JaxShardBackend(devices=jax.devices()[:1])
        full = JaxShardBackend(devices=jax.devices()[:8])
        recv1, _ = one.run(sched, verify=True, iter_=3)
        recv8, _ = full.run(sched, verify=True, iter_=3)
        for a, b in zip(recv1, recv8):
            if a is None:
                assert b is None
            else:
                np.testing.assert_array_equal(a, b)

    def test_chained_and_measured_rounds_on_one_device(self):
        from tpu_aggcomm.backends.jax_shard import JaxShardBackend

        p = AggregatorPattern(nprocs=16, cb_nodes=6, data_size=256,
                              comm_size=8)   # 2 rounds
        sched = compile_method(1, p)
        b = JaxShardBackend(devices=jax.devices()[:1])
        rt = b.measure_round_times(sched)
        assert len(rt) == 2
        assert sum(rt.values()) == pytest.approx(
            b.measure_per_rep(sched), rel=1e-9)
