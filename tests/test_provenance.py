"""Transport provenance + phase-column marking (VERDICT r3 item 8).

Every results.csv row gets a sidecar ``results.provenance.csv`` row
recording which backend actually executed the method (``--backend
pallas_dma`` delegates TAM methods to jax_sim and the dense vendor-
collective methods to jax_ici, backends/pallas_dma.py) and whether the
four phase columns are direct measurements or an attribution of a
measured total (harness/attribution.py). The main CSV stays byte-
compatible with the reference (mpi_test.c:2068-2118) — provenance rides
alongside, so attributed rows can't be read as measured downstream.
"""

import csv
import os

import pytest

from tpu_aggcomm.harness.report import (PHASE_SOURCES, append_provenance,
                                        provenance_path)
from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment


def _rows(path):
    with open(path) as fh:
        return list(csv.DictReader(fh))


def _run(tmp_path, backend, method, **kw):
    cfg = ExperimentConfig(
        nprocs=8, cb_nodes=3, data_size=64, comm_size=2, method=method,
        backend=backend, verify=True,
        results_csv=str(tmp_path / "results.csv"), **kw)
    import io
    recs = run_experiment(cfg, out=io.StringIO())
    return recs, _rows(provenance_path(str(tmp_path / "results.csv")))


def test_provenance_path():
    assert provenance_path("results.csv") == "results.provenance.csv"
    assert provenance_path("x/y.csv") == "x/y.provenance.csv"


def test_append_rejects_unknown_vocabulary(tmp_path):
    with pytest.raises(ValueError, match="unknown phase source"):
        append_provenance(str(tmp_path / "r.csv"), "m", "local", "local",
                          "guessed")


def test_comma_bearing_labels_round_trip(tmp_path):
    """The hop/split vocabulary contains commas — the sidecar must quote
    them so a DictReader recovers the label whole, not split across
    columns."""
    import csv as _csv

    res = tmp_path / "r.csv"
    res.write_text("header\nrow1\n")
    path = append_provenance(
        str(res), "All to many TAM", "jax_sim", "jax_sim",
        "measured-hops(P2,P3,P4)+attributed(ranks)")
    with open(path, newline="") as fh:
        rows = list(_csv.DictReader(fh))
    assert rows[0]["phase columns"] == \
        "measured-hops(P2,P3,P4)+attributed(ranks)"
    assert rows[0]["results row"] == "1"


def test_local_rows_are_total_only(tmp_path):
    recs, rows = _run(tmp_path, "local", 1)
    assert rows[-1]["backend requested"] == "local"
    assert rows[-1]["backend executed"] == "local"
    assert rows[-1]["phase columns"] == "total-only"
    assert recs[-1]["phase_source"] == "total-only"


def test_native_rows_are_measured_but_tam_delegates(tmp_path):
    _, rows = _run(tmp_path, "native", 1)
    assert (rows[-1]["backend executed"], rows[-1]["phase columns"]) == \
        ("native", "measured")
    # TAM runs on the host proxy-path oracle (backends/native.py): the
    # sidecar must say the local oracle executed, total-only
    _, rows = _run(tmp_path, "native", 15)
    assert (rows[-1]["backend executed"], rows[-1]["phase columns"]) == \
        ("local", "total-only")
    assert rows[-1]["backend requested"] == "native"


def test_jax_sim_marks_attribution_modes(tmp_path):
    _, rows = _run(tmp_path, "jax_sim", 1)
    assert rows[-1]["phase columns"] == "attributed"
    _, rows = _run(tmp_path, "jax_sim", 1, chained=True)
    assert rows[-1]["phase columns"] == "attributed-chained"
    _, rows = _run(tmp_path, "jax_sim", 1, profile_rounds=True)
    assert rows[-1]["phase columns"] == "attributed-rounds"


@pytest.mark.parametrize("backend", ["jax_sim", "jax_ici", "jax_shard"])
def test_single_round_profile_downgrades_everywhere(tmp_path, backend):
    # unthrottled m=1 on a small pattern compiles to ONE round: there is
    # no multi-round split to measure, so every tier must label the row
    # whole-rep "attributed" — backends may not disagree for the same
    # schedule (code-review r4 finding)
    cfg = ExperimentConfig(
        nprocs=8, cb_nodes=3, data_size=64, comm_size=200_000_000,
        method=1, backend=backend, verify=True, profile_rounds=True,
        results_csv=str(tmp_path / "results.csv"))
    import io
    run_experiment(cfg, out=io.StringIO())
    rows = _rows(provenance_path(str(tmp_path / "results.csv")))
    assert rows[-1]["phase columns"] == "attributed"


def test_pallas_dma_records_delegation(tmp_path):
    # semaphore transport proper
    _, rows = _run(tmp_path, "pallas_dma", 1)
    assert (rows[-1]["backend executed"], rows[-1]["phase columns"]) == \
        ("pallas_dma", "attributed")
    # dense collective -> jax_ici; TAM -> jax_sim (backends/pallas_dma.py)
    _, rows = _run(tmp_path, "pallas_dma", 8)
    assert rows[-1]["backend executed"] == "jax_ici"
    _, rows = _run(tmp_path, "pallas_dma", 15)
    assert rows[-1]["backend executed"] == "jax_sim"
    assert all(r["backend requested"] == "pallas_dma" for r in rows[-3:])


def test_jax_ici_tam_profile_rounds_is_whole_rep_attribution(tmp_path):
    # the two-level TAM engine times whole reps even under
    # --profile-rounds (there is no round structure to split); the
    # sidecar must not claim per-round measured totals
    _, rows = _run(tmp_path, "jax_ici", 15, profile_rounds=True)
    assert (rows[-1]["backend executed"], rows[-1]["phase columns"]) == \
        ("jax_ici", "attributed")


def test_run_all_rows_align_with_results_csv(tmp_path):
    # -m 0: one provenance row per results.csv row, same order, same
    # method labels — the sidecar is row-aligned metadata, not a summary
    cfg = ExperimentConfig(
        nprocs=8, cb_nodes=3, data_size=64, comm_size=2, method=0,
        backend="local", verify=True,
        results_csv=str(tmp_path / "results.csv"))
    import io
    run_experiment(cfg, out=io.StringIO())
    main_rows = _rows(str(tmp_path / "results.csv"))
    prov_rows = _rows(provenance_path(str(tmp_path / "results.csv")))
    assert len(main_rows) == len(prov_rows) > 10
    assert [r["Method"] for r in main_rows] == \
        [r["Method"] for r in prov_rows]
    # the join key is explicit: row k of the sidecar names data row k
    assert [r["results row"] for r in prov_rows] == \
        [str(k + 1) for k in range(len(main_rows))]
    assert all(r["phase columns"] in PHASE_SOURCES for r in prov_rows)


def test_preexisting_results_csv_cannot_shift_labels(tmp_path):
    # a results.csv that predates the sidecar (append mode accumulates
    # across framework versions): the explicit row key must point at the
    # row actually described, never re-aligned from 1
    csv_path = tmp_path / "results.csv"
    with open(csv_path, "w") as fh:
        fh.write("Method,# of processes,x\n")
        fh.write("Old row,32,1\nOld row,32,2\n")      # 2 legacy data rows
    _, rows = _run(tmp_path, "local", 1)
    assert rows[-1]["results row"] == "3"
    assert rows[-1]["Method"] == "All to many"


def test_old_schema_sidecar_is_rotated_not_appended(tmp_path):
    # a sidecar from an older framework version (different header) must
    # never have current-schema rows appended beneath it — columns would
    # silently shift; it is rotated aside and a fresh file started
    sidecar = provenance_path(str(tmp_path / "results.csv"))
    with open(sidecar, "w") as fh:
        fh.write("Method,backend requested,backend executed,phase columns\n")
        fh.write("Old row,local,local,attributed\n")
    _, rows = _run(tmp_path, "local", 1)
    assert rows[-1]["results row"] == "1"
    assert rows[-1]["phase columns"] == "total-only"
    with open(sidecar + ".old-schema") as fh:
        assert "Old row" in fh.read()


def test_main_csv_stays_reference_compatible(tmp_path):
    # the provenance sidecar must not touch the main CSV's header
    # (byte-compat with mpi_test.c:2068-2118 is a CLAUDE.md invariant)
    _run(tmp_path, "local", 1)
    with open(tmp_path / "results.csv") as fh:
        header = fh.readline()
    assert header.startswith("Method,# of processes,")
    assert "backend" not in header
