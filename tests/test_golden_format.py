"""Golden-format tests: output parity with the reference, byte-for-byte.

The golden strings below are frozen transcriptions of the reference's
printf/fprintf formats (banner mpi_test.c:2170-2179; console block +
results.csv mpi_test.c:2068-2118; %lf = 6 decimal places), so format
parity cannot regress silently (VERDICT r1 item 9). The README example
block (README.md:40-71) predates the reference's current code — the
authoritative shape is summarize_results itself, which prints send and
recv waitall separately.
"""

import io

from tpu_aggcomm.harness.report import config_banner, summarize_results
from tpu_aggcomm.harness.timer import Timer


GOLDEN_BANNER = (
    "total number of processes = 32, cb_nodes = 14, proc_node = 1, "
    "data size = 2048, comm_size = 3, ntimes=1\n"
    "aggregators = 0, 3, 6, 8, 10, 12, 14, 16, 18, 20, 22, 24, 26, 28, \n"
)

GOLDEN_BLOCK = (
    "| --------------------------------------\n"
    "| All to many rank 0 request post time = 0.001556\n"
    "| All to many rank 0 send waitall time = 0.022929\n"
    "| All to many rank 0 recv waitall time = 0.000000\n"
    "| All to many rank 0 total time = 0.024494\n"
    "| All to many max request post time = 0.011989\n"
    "| All to many max send waitall time = 0.045943\n"
    "| All to many max recv waitall time = 0.000000\n"
    "| All to many max total time = 0.055115\n"
)

GOLDEN_CSV_HEADER = (
    "Method,# of processes,# of aggregators,data size,max comm,ntimes,"
    "aggregator type,rank 0 post_request_time,rank 0 send waitall time,"
    "rank 0 recv waitall time,rank 0 total time,max post_request_time,"
    "max send waitall time,max recv waitall time,max total time\n"
)

GOLDEN_CSV_ROW = (
    "All to many,32,14,2048,3,1,1,"
    "0.001556,0.022929,0.000000,0.024494,"
    "0.011989,0.045943,0.000000,0.055115\n"
)


def _timers():
    # the README example's exp-1 numbers (README.md:44-49)
    t0 = Timer(post_request_time=0.001556, send_wait_all_time=0.022929,
               total_time=0.024494)
    tm = Timer(post_request_time=0.011989, send_wait_all_time=0.045943,
               total_time=0.055115)
    return t0, tm


def test_banner_bytes():
    """The README example's aggregator list: n=32, a=14, t=1 (placement 1
    ceiling/floor spread, mpi_test.c:1952-2006) reproduces 0,3,6,8,...,28
    — and the banner is the exact printf shape of mpi_test.c:2171-2177."""
    from tpu_aggcomm.core.pattern import AggregatorPattern

    p = AggregatorPattern(nprocs=32, cb_nodes=14, data_size=2048,
                          comm_size=3)
    got = config_banner(32, 14, 1, 2048, 3, 1, p.rank_list)
    assert got == GOLDEN_BANNER


def test_console_block_bytes():
    t0, tm = _timers()
    out = io.StringIO()
    summarize_results(32, 14, 2048, 3, 1, 1, None, "All to many",
                      t0, tm, out=out)
    assert out.getvalue() == GOLDEN_BLOCK


def test_results_csv_bytes(tmp_path):
    t0, tm = _timers()
    csv = tmp_path / "results.csv"
    summarize_results(32, 14, 2048, 3, 1, 1, str(csv), "All to many",
                      t0, tm, out=io.StringIO())
    summarize_results(32, 14, 2048, 3, 1, 1, str(csv), "All to many",
                      t0, tm, out=io.StringIO())
    lines = csv.read_text().splitlines(keepends=True)
    assert lines[0] == GOLDEN_CSV_HEADER     # auto-header once
    assert lines[1] == GOLDEN_CSV_ROW
    assert lines[2] == GOLDEN_CSV_ROW        # append mode, no second header
    assert len(lines) == 3


def test_per_rank_csv_naming(tmp_path):
    """save_all_timing writes the reference's four files with the
    {prefix}{kind}_{comm_size}.csv naming (mpi_test.c:2024-2063)."""
    import os

    from tpu_aggcomm.harness.report import save_all_timing

    rep_timers = [[Timer(total_time=1.0, send_wait_all_time=0.5,
                         post_request_time=0.25, barrier_time=0.125)
                   for _ in range(4)] for _ in range(2)]
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        save_all_timing(4, 2, 7, rep_timers, "x_")
    finally:
        os.chdir(cwd)
    for kind in ("send_wait_all_times", "total_times", "post_request_time",
                 "barrier_time"):
        assert (tmp_path / f"x_{kind}_7.csv").exists(), kind


def test_pt2pt_console_golden(tmp_path):
    """The pt2pt stat line is field-for-field the reference printf
    (mpi_sendrecv_test.c:64): 'rank %d, mean = %lf, std = %lf,
    ntimes = %d, total_timing = %lf, mean*ntimes = %lf'."""
    import io
    import re

    from tpu_aggcomm.harness.pt2pt import pt2pt_statistics

    buf = io.StringIO()
    pt2pt_statistics(64, 2, 3, filename=str(tmp_path / "s.csv"), out=buf)
    line = buf.getvalue().splitlines()[0]
    assert re.fullmatch(
        r"rank 0, mean = \d+\.\d{6}, std = \d+\.\d{6}, ntimes = 2, "
        r"total_timing = \d+\.\d{6}, mean\*ntimes = \d+\.\d{6}", line), line
    # per-rep CSV: one %lf per line (mpi_sendrecv_test.c:58)
    rows = (tmp_path / "s.csv").read_text().splitlines()
    assert len(rows) == 2
    assert all(re.fullmatch(r"\d+\.\d{6}", r) for r in rows)
