"""Resilience subsystem (ISSUE 7): error taxonomy, seeded retry with
deterministic replay, crash-safe journal + sweep --resume, watchdog
deadlines + round-boundary cancellation, advisory fault detection, and
atomic artifact writes.

The pins that matter:

- same seed + same error sequence ⟹ same attempt timeline, and
  ``replay_attempts`` re-derives it jax-free from records alone (the
  tune --replay discipline applied to retries);
- the policy/journal/watchdog/detect core imports (and works) where
  ``import jax`` raises — poisoned-jax subprocess, the obs discipline;
- a verify-class error is NEVER retried;
- ``sweep --resume`` skips journal-done cells and re-runs (naming the
  drifted keys) when the manifest fingerprint changed;
- a writer SIGKILLed mid-``atomic_write`` leaves the target intact.
"""

import contextlib
import io
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tpu_aggcomm.obs import ledger
from tpu_aggcomm.resilience import (RETRYABLE, RetryPolicy, classify_error,
                                    replay_attempts, retry_call, RunJournal,
                                    CancelledAtBoundary, check_boundary,
                                    derive_deadline, safe_cancellation)
from tpu_aggcomm.resilience import policy as rpolicy
from tpu_aggcomm.resilience.detect import (propose_fault_specs,
                                           render_proposals)
from tpu_aggcomm.resilience.watchdog import (cancellation_pending,
                                             soft_deadline_check)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(argv):
    from tpu_aggcomm.cli import main
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    return rc, buf.getvalue()


@pytest.fixture(autouse=True)
def _clean_ledger():
    ledger.reset()
    yield
    ledger.reset()


# ------------------------------------------------------------- taxonomy

class VerificationError(AssertionError):
    """Name-matched stand-in (classification is by type NAME so the
    policy core never imports backend modules)."""


class DeadlockError(RuntimeError):
    pass


def test_classify_taxonomy():
    assert classify_error(VerificationError("rank 3 byte 7")) == "verify"
    # a verify error mentioning tunnel words STAYS verify (precedence)
    assert classify_error(
        VerificationError("connection reset in diff")) == "verify"
    assert classify_error(DeadlockError("cycle")) == "program"
    assert classify_error(ConnectionResetError()) == "transient-tunnel"
    assert classify_error(TimeoutError()) == "transient-tunnel"
    assert classify_error(
        RuntimeError("UNAVAILABLE: socket closed")) == "transient-tunnel"
    assert classify_error(
        RuntimeError("Mosaic lowering failed: bad layout")) == "compile"
    assert classify_error(ValueError("boom")) == "program"
    # OSError is deliberately NOT transient: FileNotFoundError must
    # never be retried as if it were a tunnel blip
    assert classify_error(FileNotFoundError("gone")) == "program"
    assert RETRYABLE == {"transient-tunnel"}


# ----------------------------------------------------- seeded retry core

def _flaky(n_failures: int, exc=None):
    state = {"left": n_failures}

    def fn():
        if state["left"] > 0:
            state["left"] -= 1
            raise exc if exc is not None \
                else ConnectionError("UNAVAILABLE: blip")
        return "converged"
    return fn


def _run_retry(seed: int, n_failures: int = 2):
    ledger.reset()
    sleeps = []
    pol = RetryPolicy(max_attempts=4, backoff_base_s=0.01, seed=seed)
    out = retry_call(_flaky(n_failures), site="t", policy=pol,
                     sleep=sleeps.append)
    return out, sleeps, ledger.resilience_records()


def test_retry_timeline_is_deterministic_from_seed():
    out_a, sleeps_a, recs_a = _run_retry(seed=0)
    out_b, sleeps_b, recs_b = _run_retry(seed=0)
    assert out_a == out_b == "converged"
    assert sleeps_a == sleeps_b               # exact same backoffs slept
    assert recs_a == recs_b                   # exact same attempt records
    assert [r["outcome"] for r in recs_a] == ["retry", "retry", "ok"]
    assert all(r["error_class"] == "transient-tunnel"
               for r in recs_a if r["outcome"] == "retry")
    # recorded backoffs are the slept backoffs, verbatim
    assert [r["backoff_s"] for r in recs_a
            if r["outcome"] == "retry"] == sleeps_a
    # a different seed jitters differently
    _, sleeps_c, _ = _run_retry(seed=1)
    assert sleeps_c != sleeps_a


def test_non_retryable_raises_immediately():
    sleeps = []
    with pytest.raises(ValueError):
        retry_call(_flaky(1, ValueError("bad arg")), site="t",
                   policy=RetryPolicy(max_attempts=5, seed=0),
                   sleep=sleeps.append)
    assert sleeps == []                       # no backoff, no retry
    recs = ledger.resilience_records()
    assert len(recs) == 1 and recs[0]["outcome"] == "raise"
    assert recs[0]["error_class"] == "program"


def test_verify_error_never_retried():
    with pytest.raises(VerificationError):
        retry_call(_flaky(1, VerificationError("wrong bytes")), site="v",
                   policy=RetryPolicy(max_attempts=5, seed=0),
                   sleep=lambda s: None)
    assert ledger.resilience_records()[0]["error_class"] == "verify"


def test_retry_exhaustion_reraises_original():
    with pytest.raises(ConnectionError):
        retry_call(_flaky(99), site="t",
                   policy=RetryPolicy(max_attempts=2, backoff_base_s=0.001,
                                      seed=0),
                   sleep=lambda s: None)
    recs = ledger.resilience_records()
    assert [r["outcome"] for r in recs] == ["retry", "raise"]


def test_replay_attempts_reproduced_then_mismatch_on_tamper():
    _, _, recs = _run_retry(seed=7)
    verdict, problems = replay_attempts(recs)
    assert verdict == "REPRODUCED" and problems == []
    tampered = [dict(r) for r in recs]
    for r in tampered:
        if r["outcome"] == "retry":
            r["backoff_s"] = r["backoff_s"] + 1e-3
    verdict, problems = replay_attempts(tampered)
    assert verdict == "MISMATCH"
    assert any("seeded schedule says" in p for p in problems)


def test_chaos_injection_consumes_budget(monkeypatch):
    monkeypatch.setenv("TPU_AGGCOMM_CHAOS", "unit.site:2")
    rpolicy._reset_chaos()
    ledger.reset()
    out = retry_call(lambda: "ok", site="unit.site:x",
                     policy=RetryPolicy(max_attempts=4,
                                        backoff_base_s=0.001, seed=0),
                     sleep=lambda s: None)
    assert out == "ok"
    recs = ledger.resilience_records()
    assert [r["outcome"] for r in recs] == ["retry", "retry", "ok"]
    assert replay_attempts(recs)[0] == "REPRODUCED"
    # budget spent: the next call at the same site passes untouched
    ledger.reset()
    retry_call(lambda: "ok", site="unit.site:x", sleep=lambda s: None)
    assert [r["outcome"] for r in ledger.resilience_records()] == ["ok"]
    monkeypatch.delenv("TPU_AGGCOMM_CHAOS")
    rpolicy._reset_chaos()


# ------------------------------------------------------------- journal

def test_journal_completed_drift_and_torn_tail(tmp_path):
    man_a = {"schema": 3, "versions": {"jax": "0.4.1"}, "python": "3.11"}
    man_b = {"schema": 3, "versions": {"jax": "0.9.9"}, "python": "3.11"}
    j = RunJournal(str(tmp_path / "j.jsonl"))
    fp_a = j.begin_session(man_a)
    key = {"stage": "bench"}
    j.record(key, fingerprint=fp_a, status="done",
             shape_keys=["('a2m', 1)"], artifacts=["BENCH.json"])
    assert j.completed(key, fingerprint=fp_a, manifest=man_a) == (True, None)
    assert j.seen(key)
    assert not j.seen({"stage": "other"})
    # a failed entry never satisfies resume
    j.record({"stage": "flaky"}, fingerprint=fp_a, status="fail")
    done, reason = j.completed({"stage": "flaky"}, fingerprint=fp_a,
                               manifest=man_a)
    assert done is False and reason is None
    # drift: same key, new environment — the drifted key is NAMED
    fp_b = j.begin_session(man_b)
    assert fp_b != fp_a
    done, reason = j.completed(key, fingerprint=fp_b, manifest=man_b)
    assert done is False
    assert "versions.jax" in reason and "re-running" in reason
    # torn final line (killed mid-append): reader skips it
    with open(j.path, "a") as fh:
        fh.write('{"key": {"stage": "torn"')
    assert j.completed(key, fingerprint=fp_a, manifest=man_a) == (True, None)


# --------------------------------------------- watchdog + cancellation

def test_derive_deadline_floors_and_walls():
    assert derive_deadline() == 30.0                       # absolute floor
    d = derive_deadline(floor_s=0.01, ntimes=100, rpc_probe_s=0.08)
    assert d == pytest.approx(max(30.0, 50.0 * 0.01 * 100 + 0.8))
    # a slow prior wall dominates everything
    assert derive_deadline(floor_s=0.01, prior_walls=[2.0, 40.0]) == 200.0


def test_soft_deadline_check_records_but_never_raises():
    out = io.StringIO()
    assert soft_deadline_check("dispatch:m1:i0", wall_s=5.0,
                               deadline_s=10.0, out=out) is False
    assert out.getvalue() == ""
    assert soft_deadline_check("dispatch:m1:i0", wall_s=50.0,
                               deadline_s=10.0, out=out) is True
    assert "advisory only" in out.getvalue()
    recs = ledger.resilience_records()
    assert recs and recs[-1]["kind"] == "deadline"


def test_safe_cancellation_defers_sigint_to_boundary():
    assert cancellation_pending() is None     # inert outside the scope
    with safe_cancellation():
        check_boundary("m1:i0")               # nothing pending: no-op
        os.kill(os.getpid(), signal.SIGINT)
        for _ in range(10_000):               # let the signal deliver
            if cancellation_pending():
                break
            time.sleep(0.001)
        assert cancellation_pending() == "SIGINT"
        with pytest.raises(CancelledAtBoundary, match="--resume"):
            check_boundary("m1:i1")
        assert cancellation_pending() is None  # honored exactly once
    assert cancellation_pending() is None
    recs = ledger.resilience_records()
    assert any(r["kind"] == "cancel" and r["signal"] == "SIGINT"
               for r in recs)


# -------------------------------------------------------- fault detect

def _synthetic_events(slow_rank=None, factor=4.0, ranks=4, rounds=4):
    events = [{"ev": "run", "id": 0, "method": 1, "name": "All to many"}]
    for rnd in range(rounds):
        for rank in range(ranks):
            dur = 0.004 if rank == slow_rank else 0.001
            events.append({"ev": "span", "run": 0, "rep": 0, "rank": rank,
                           "round": rnd, "bucket": "send_wait_all",
                           "dur_s": dur})
    return events


def test_detect_proposes_slow_rank_spec():
    props = propose_fault_specs(_synthetic_events(slow_rank=3))
    assert len(props) == 1
    p = props[0]
    assert p["rank"] == 3 and p["crit_rounds"] == 4 and p["rounds"] == 4
    assert p["spec"].startswith("slow:r3*")
    # the proposal round-trips through the PR 6 parser by construction
    from tpu_aggcomm.faults import parse_fault
    assert parse_fault(p["spec"]).canonical() == p["spec"]
    text = render_proposals(props)
    assert "rank 3" in text and "--fault" in text and p["spec"] in text


def test_detect_stays_silent_on_healthy_and_thin_traces():
    assert propose_fault_specs(_synthetic_events(slow_rank=None)) == []
    # below MIN_FACTOR: scheduling jitter, not a degraded rank
    events = _synthetic_events(slow_rank=2)
    for e in events:
        if e.get("rank") == 2:
            e["dur_s"] = 0.0012
    assert propose_fault_specs(events) == []
    # single-rank rounds carry no skew information
    assert propose_fault_specs(_synthetic_events(ranks=1)) == []
    # two rounds cannot show persistence (MIN_ROUNDS), and critical in
    # exactly half the rounds is a coin flip, not a strict majority
    assert propose_fault_specs(
        _synthetic_events(slow_rank=3, rounds=2)) == []
    events = _synthetic_events(slow_rank=0, rounds=4)
    for e in events:
        if e.get("ev") == "span" and e["round"] >= 2:
            e["dur_s"] = 0.004 if e["rank"] == 1 else 0.001
    assert propose_fault_specs(events) == []  # 2/4 each: no majority
    assert render_proposals([]) == ""
    # the COMMITTED healthy trace must stay silent — this exact artifact
    # once tripped the detector on 1-of-2-rounds host jitter
    healthy = os.path.join(REPO, "FAULT_healthy.trace.jsonl")
    if os.path.exists(healthy):
        from tpu_aggcomm.obs.trace import load_events
        assert propose_fault_specs(load_events(healthy)) == []


# ------------------------------------------------- ledger + bench schema

def test_ledger_render_and_load(tmp_path):
    ledger.record_resilience("dispatch:m1:i0", kind="attempt", attempt=1,
                             outcome="retry", error_class="transient-tunnel",
                             error="ConnectionError: blip", backoff_s=0.01,
                             max_attempts=3, backoff_base_s=0.01,
                             backoff_mult=2.0, jitter_frac=0.25, seed=0)
    ledger.record_resilience("dispatch:m1:i0", kind="attempt", attempt=2,
                             outcome="ok", max_attempts=3,
                             backoff_base_s=0.01, backoff_mult=2.0,
                             jitter_frac=0.25, seed=0)
    ledger.record_resilience("xprof", kind="suppressed",
                             error_class="program", error="boom")
    text = ledger.render_resilience(ledger.resilience_records())
    assert "dispatch:m1:i0" in text and "converged" in text
    assert "suppressed" in text
    # a BENCH-style artifact round-trips its resilience list
    blob = {"n": 9, "cmd": "python bench.py", "rc": 0, "tail": "",
            "parsed": {"metric": "m", "value": 1.0, "unit": "s",
                       "resilience": ledger.resilience_records()}}
    p = tmp_path / "BENCH_r09.json"
    p.write_text(json.dumps(blob))
    loaded = ledger.load_ledger(str(p))
    assert len(loaded["resilience"]) == 3
    ledger.reset()
    assert ledger.resilience_records() == []


def test_validate_bench_types_resilience():
    from tpu_aggcomm.obs.regress import validate_bench
    good = {"n": 1, "cmd": "c", "rc": 0, "tail": "",
            "parsed": {"metric": "m", "value": 1.0, "unit": "s",
                       "resilience": [{"site": "t", "kind": "attempt"}]}}
    assert validate_bench(good) == []
    bad = json.loads(json.dumps(good))
    bad["parsed"]["resilience"] = ["not-a-dict"]
    assert any("resilience" in e for e in validate_bench(bad))
    bad["parsed"]["resilience"] = [{"kind": "attempt"}]   # site missing
    assert any("resilience" in e for e in validate_bench(bad))


# ------------------------------------------------------- jax-free pins

def _poisoned_env(tmp_path):
    """Shared recipe (tests/_jaxfree.py, parameterized by the linter's
    purity contract)."""
    import _jaxfree
    return _jaxfree.poisoned_env(
        tmp_path, "resilience core must not import jax")


def test_resilience_core_survives_poisoned_jax(tmp_path):
    """policy + journal + watchdog + detect, end to end, where ``import
    jax`` raises — the resume/replay paths run on hosts where a dead
    tunnel hangs any jax init."""
    code = (
        "from tpu_aggcomm.resilience import (RetryPolicy, classify_error,"
        " replay_attempts, retry_call, RunJournal, derive_deadline,"
        " propose_fault_specs)\n"
        "from tpu_aggcomm.obs import ledger\n"
        "assert classify_error(ConnectionError('x')) == 'transient-tunnel'\n"
        "pol = RetryPolicy(max_attempts=3, backoff_base_s=0.001, seed=5)\n"
        "state = {'left': 1}\n"
        "def fn():\n"
        "    if state['left']:\n"
        "        state['left'] -= 1\n"
        "        raise TimeoutError('tunnel')\n"
        "    return 1\n"
        "assert retry_call(fn, site='s', policy=pol,"
        " sleep=lambda s: None) == 1\n"
        "v, p = replay_attempts(ledger.resilience_records())\n"
        "assert v == 'REPRODUCED', p\n"
        "j = RunJournal('j.jsonl')\n"
        "fp = j.begin_session({'versions': {'jax': 'none'}})\n"
        "j.record({'cell': 1}, fingerprint=fp)\n"
        "assert j.completed({'cell': 1}, fingerprint=fp)[0]\n"
        "assert derive_deadline(floor_s=0.001) >= 30.0\n"
        "assert propose_fault_specs([]) == []\n"
        "print('JAXFREE OK')\n")
    r = subprocess.run([sys.executable, "-c", code], cwd=str(tmp_path),
                       env=_poisoned_env(tmp_path), capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "JAXFREE OK" in r.stdout


# ------------------------------------------------------- atomic writes

def test_atomic_write_survives_sigkill_mid_write(tmp_path):
    target = tmp_path / "artifact.json"
    target.write_text('{"round": "prior", "intact": true}\n')
    code = (
        "import sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from tpu_aggcomm.obs.atomic import atomic_write\n"
        f"with atomic_write({str(target)!r}) as fh:\n"
        "    fh.write('{\"torn\": ')\n"
        "    fh.flush()\n"
        "    print('WRITING', flush=True)\n"
        "    time.sleep(60)\n"
        "    fh.write('true}')\n")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "WRITING"
        proc.kill()                           # SIGKILL: no cleanup runs
    finally:
        proc.wait(timeout=30)
    # the target is byte-identical to the prior round's artifact
    assert json.loads(target.read_text()) == {"round": "prior",
                                              "intact": True}


def test_atomic_write_lands_complete_content(tmp_path):
    from tpu_aggcomm.obs import atomic_write
    target = tmp_path / "out.json"
    with atomic_write(str(target)) as fh:
        json.dump({"ok": 1}, fh)
    assert json.loads(target.read_text()) == {"ok": 1}
    # no temp litter after a clean write
    assert os.listdir(tmp_path) == ["out.json"]
    # an exception inside the block leaves no target and no litter
    with pytest.raises(RuntimeError):
        with atomic_write(str(tmp_path / "never.json")) as fh:
            fh.write("partial")
            raise RuntimeError("writer died")
    assert os.listdir(tmp_path) == ["out.json"]


# ------------------------------------------------------ sweep --resume

def test_sweep_resume_journal_skips_then_drift_reruns(tmp_path):
    csv = tmp_path / "results.csv"
    base = ["sweep", "-n", "8", "-m", "1", "-a", "2", "-d", "32", "-i", "1",
            "--backend", "local", "--results-csv", str(csv),
            "--comm-sizes", "2,4"]
    rc, out = run_cli(base)
    assert rc == 0
    jpath = str(csv) + ".journal.jsonl"
    assert os.path.exists(jpath)
    entries = [json.loads(ln) for ln in open(jpath)]
    cells = [e for e in entries if "key" in e]
    assert len(cells) == 2
    assert all(e["status"] == "done" and e["shape_keys"] for e in cells)
    # resume under the same manifest: every cell skipped. (reset the
    # process-global ledger between calls: each real sweep is its own
    # process with a fresh manifest — without this, device facts
    # recorded mid-first-run would read as in-process "drift")
    ledger.reset()
    rc, out = run_cli(base + ["--resume"])
    assert rc == 0
    assert "skipping already-recorded comm sizes [2, 4]" in out
    assert "RUN_OPTS" not in out
    # tamper the journal into a drifted environment: the resume must
    # re-run and NAME the drifted manifest key
    from tpu_aggcomm.tune.cache import manifest_fingerprint
    tampered = []
    stale_man = None
    for e in entries:
        if e.get("journal"):
            stale_man = dict(e["manifest"])
            stale_man["python"] = "0.0.0-tampered"
            e = dict(e, manifest=stale_man,
                     fingerprint=manifest_fingerprint(stale_man))
        else:
            e = dict(e, fingerprint=manifest_fingerprint(stale_man))
        tampered.append(e)
    with open(jpath, "w") as fh:
        for e in tampered:
            fh.write(json.dumps(e) + "\n")
    ledger.reset()
    rc, out = run_cli(base + ["--resume"])
    assert rc == 0
    assert "manifest drift" in out and "python" in out
    assert out.count("RUN_OPTS:") == 2        # both cells re-ran
    # ... and having re-run under THIS manifest, resume skips again
    ledger.reset()
    rc, out = run_cli(base + ["--resume"])
    assert "skipping already-recorded comm sizes [2, 4]" in out


def test_run_records_carry_shape_key():
    from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment
    cfg = ExperimentConfig(nprocs=8, cb_nodes=2, data_size=32, comm_size=4,
                           method=1, backend="local", verify=True,
                           results_csv=None)
    recs = run_experiment(cfg, out=io.StringIO())
    assert recs and all("shape_key" in r for r in recs)
    assert "method_id=1" in recs[0]["shape_key"] \
        or "1" in recs[0]["shape_key"]
