"""Run-ledger (tpu_aggcomm/obs/ledger.py) guarantees:

- the manifest carries versions from package METADATA (never an import),
  the scrubbed env summary (arming vars by NAME only — pool IPs must
  never land in a committed artifact), and device facts only when a
  jax-side caller recorded them;
- parsed-schema v3 (manifest + compile_seconds + hbm_peak_bytes)
  validates in obs/regress.py, v1/v2 artifacts stay valid, and
  ``parsed_schema_version`` tells them apart;
- the ``--check-regression`` compile gate fires only when BOTH compared
  rounds carry compile_seconds (pre-v3 history: gate inactive, said so),
  and manifest drift between the compared rounds rides in the verdict;
- ``cli inspect ledger`` flags injected environment drift (differing
  jax version strings) — the ISSUE 3 acceptance pin;
- obs.ledger / obs.regress / obs.compare and ``bench.py
  --check-regression`` survive a POISONED jax on PYTHONPATH (a dead
  tunnel can hang ``import jax``; the supervisor side must never try);
- ``--xprof`` produces a divergence report without touching the timed
  path's records.
"""

import io
import json
import os
import subprocess
import sys

import pytest

from tpu_aggcomm.harness.hostenv import env_summary
from tpu_aggcomm.obs import ledger
from tpu_aggcomm.obs.regress import (check_regression,
                                     parsed_schema_version, validate_bench)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh_ledger():
    ledger.reset()
    yield
    ledger.reset()


# ----------------------------------------------------------------- manifest

def test_manifest_contents_and_caching(fresh_ledger):
    m = ledger.manifest()
    assert m["schema"] == ledger.SCHEMA_VERSION == 3
    assert set(m["versions"]) == {"jax", "jaxlib", "libtpu"}
    assert m["python"].count(".") == 2
    assert "armed_vars" in m["env"] and "tunnel_armed" in m["env"]
    assert m["platform"] is None  # no jax-side caller recorded yet
    # cached: collect_manifest returns the live dict, manifest() a copy
    assert ledger.collect_manifest() is ledger.collect_manifest()
    m["versions"]["jax"] = "tampered"
    assert ledger.collect_manifest()["versions"]["jax"] != "tampered"


def test_record_device_fills_manifest(fresh_ledger):
    ledger.record_device(platform="tpu", device_kind="TPU v5e",
                         rpc_probe_s=0.07)
    m = ledger.manifest()
    assert m["platform"] == "tpu"
    assert m["device_kind"] == "TPU v5e"
    assert m["rpc_probe_s"] == pytest.approx(0.07)


def test_env_summary_never_records_arming_values(monkeypatch):
    """Arming variables appear by NAME only: the pool IP value must not
    be reproducible from any committed artifact."""
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.11.12.13")
    s = env_summary()
    assert "PALLAS_AXON_POOL_IPS" in s["armed_vars"]
    assert s["tunnel_armed"] is True
    assert "10.11.12.13" not in json.dumps(s)


def test_compile_records_and_total(fresh_ledger):
    ledger.record_compile("a", seconds=0.5, kind="schedule-build",
                          backend="local", cost=None)
    rec = ledger.record_compile("b", seconds=1.5, kind="first-dispatch")
    assert "cost" not in ledger.compile_records()[0]  # None extras dropped
    assert rec["kind"] == "first-dispatch"
    assert ledger.total_compile_seconds() == pytest.approx(2.0)


def test_hbm_peak_tracks_max(fresh_ledger):
    assert ledger.hbm_peak() is None
    ledger.record_hbm_peak(100)
    ledger.record_hbm_peak(None)   # absent sample: ignored, not zeroed
    ledger.record_hbm_peak(50)
    assert ledger.hbm_peak() == 100


# -------------------------------------------------------------------- drift

def _manifest(jax="0.4.37", platform="cpu", sha="abc"):
    return {"schema": 3, "python": "3.11.0",
            "versions": {"jax": jax, "jaxlib": "0.4.36", "libtpu": None},
            "git_sha": sha, "env": {"tunnel_armed": False},
            "platform": platform, "device_kind": None,
            "rpc_probe_s": 0.001, "created_unix": 1.0}


def test_diff_manifests_flags_versions_not_ignored_keys():
    a = _manifest(jax="0.4.37", sha="abc")
    b = _manifest(jax="0.4.99", sha="def")
    b["created_unix"] = 2.0
    b["rpc_probe_s"] = 0.09
    drift = ledger.diff_manifests(a, b)
    assert [d["key"] for d in drift] == ["versions.jax"]
    assert drift[0]["a"] == "0.4.37" and drift[0]["b"] == "0.4.99"
    assert ledger.diff_manifests(a, dict(a)) == []
    assert ledger.diff_manifests(None, b) == []  # pre-v3 side: no drift


# ------------------------------------------------------------- schema v3

def _blob(value=1e-5, platform="cpu", **parsed_extra):
    parsed = {"metric": "m", "value": value, "unit": "s",
              "platform": platform}
    parsed.update(parsed_extra)
    return {"n": 32, "cmd": "bench", "rc": 0, "tail": "", "parsed": parsed}


def test_validate_bench_v3_fields():
    good = _blob(manifest=_manifest(), compile_seconds=2.5,
                 hbm_peak_bytes=1024)
    assert validate_bench(good) == []
    assert validate_bench(_blob(hbm_peak_bytes=None)) == []
    assert any("manifest" in e
               for e in validate_bench(_blob(manifest="not-a-dict")))
    assert any("compile_seconds" in e
               for e in validate_bench(_blob(compile_seconds=-1.0)))
    assert any("hbm_peak_bytes" in e
               for e in validate_bench(_blob(hbm_peak_bytes=1.5)))


def test_parsed_schema_version():
    assert parsed_schema_version(None) == 1
    assert parsed_schema_version(_blob()["parsed"]) == 1
    assert parsed_schema_version(
        _blob(samples=[1e-5, 1e-5, 1e-5])["parsed"]) == 2
    assert parsed_schema_version(_blob(compile_seconds=1.0)["parsed"]) == 3
    assert parsed_schema_version(_blob(manifest=_manifest())["parsed"]) == 3


# ------------------------------------------------------------ compile gate

def _write_round(tmp_path, rnd, blob):
    (tmp_path / f"BENCH_r{rnd:02d}.json").write_text(json.dumps(blob))


def test_compile_gate_fires_on_regression(tmp_path):
    _write_round(tmp_path, 1, _blob(compile_seconds=1.0))
    _write_round(tmp_path, 2, _blob(compile_seconds=2.0))  # +100% > 50%
    v = check_regression(str(tmp_path))
    assert not v["ok"]
    assert v["delta_pct"] == pytest.approx(0.0)  # runtime unchanged
    assert v["compile_delta_pct"] == pytest.approx(100.0)
    assert "compile time regressed" in v["compile_note"]


def test_compile_gate_within_tolerance(tmp_path):
    _write_round(tmp_path, 1, _blob(compile_seconds=1.0))
    _write_round(tmp_path, 2, _blob(compile_seconds=1.2))
    v = check_regression(str(tmp_path))
    assert v["ok"]
    assert v["compile_delta_pct"] == pytest.approx(20.0)
    assert v["compile_note"] is None


def test_compile_gate_inactive_on_pre_v3(tmp_path):
    _write_round(tmp_path, 1, _blob())               # pre-v3 baseline
    _write_round(tmp_path, 2, _blob(compile_seconds=99.0))
    v = check_regression(str(tmp_path))
    assert v["ok"]
    assert v["compile_delta_pct"] is None
    assert "compile gate inactive" in v["compile_note"]


def test_verdict_carries_manifest_drift(tmp_path):
    _write_round(tmp_path, 1, _blob(manifest=_manifest(jax="0.4.37")))
    _write_round(tmp_path, 2, _blob(manifest=_manifest(jax="0.4.99")))
    v = check_regression(str(tmp_path))
    assert v["ok"]  # drift is informational, not a regression
    assert {"key": "versions.jax", "a": "0.4.37", "b": "0.4.99"} \
        in v["manifest_drift"]
    # the one-JSON-line contract: no env blocks inside history rows
    assert all("manifest" not in r for r in v["history"])


# ------------------------------------------------------- inspect ledger CLI

def test_cli_inspect_ledger_flags_injected_drift(tmp_path, capsys):
    """ISSUE 3 acceptance pin: two artifacts with differing jax version
    strings must produce a DRIFT line."""
    from tpu_aggcomm.cli import main

    _write_round(tmp_path, 1, _blob(manifest=_manifest(jax="0.4.37"),
                                    compile_seconds=1.0))
    _write_round(tmp_path, 2, _blob(manifest=_manifest(jax="0.4.99"),
                                    compile_seconds=1.1))
    rc = main(["inspect", "ledger",
               str(tmp_path / "BENCH_r01.json"),
               str(tmp_path / "BENCH_r02.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "DRIFT versions.jax: 0.4.37 -> 0.4.99" in out
    assert "compile 1 s" in out


def test_cli_inspect_ledger_pre_v3_and_no_drift(tmp_path, capsys):
    from tpu_aggcomm.cli import main

    _write_round(tmp_path, 1, _blob())                       # pre-v3
    _write_round(tmp_path, 2, _blob(manifest=_manifest()))
    _write_round(tmp_path, 3, _blob(manifest=_manifest()))
    rc = main(["inspect", "ledger"] + [
        str(tmp_path / f"BENCH_r{r:02d}.json") for r in (1, 2, 3)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "(no ledger: pre-v3 artifact)" in out
    assert "no environment drift" in out
    assert "DRIFT" not in out


def test_load_ledger_from_trace_jsonl(tmp_path):
    p = tmp_path / "x.trace.jsonl"
    with open(p, "w") as fh:
        fh.write(json.dumps({"ev": "meta", "t0": 0}) + "\n")
        fh.write(json.dumps({"ev": "ledger",
                             "manifest": _manifest(platform="tpu")}) + "\n")
    ent = ledger.load_ledger(str(p))
    assert ent["manifest"]["versions"]["jax"] == "0.4.37"
    assert ent["platform"] == "tpu"


# ------------------------------------------------------------- jax freedom

def test_supervisor_surface_survives_poisoned_jax(tmp_path):
    """obs.ledger / obs.regress / obs.compare and the --check-regression
    supervisor must keep working when ``import jax`` would blow up (the
    dead-tunnel hang, made loud) — shared recipe in tests/_jaxfree.py,
    parameterized by the linter's purity contract."""
    import _jaxfree
    env = _jaxfree.poisoned_env(
        tmp_path, "supervisor code must not import jax")

    r = subprocess.run(
        [sys.executable, "-c",
         "import tpu_aggcomm.obs.ledger, tpu_aggcomm.obs.regress, "
         "tpu_aggcomm.obs.compare; "
         "import tpu_aggcomm.obs.ledger as L; L.manifest()"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr

    r = subprocess.run(
        [sys.executable, "bench.py", "--check-regression"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1                     # one-JSON-line contract
    verdict = json.loads(lines[0])
    assert verdict["check"] == "regression" and verdict["ok"]


# ----------------------------------------------------- harness integration

def test_chained_warmup_records_compile(fresh_ledger):
    import jax
    import numpy as np

    from tpu_aggcomm.harness.chained import differenced_trials

    def chain_factory(iters):
        import jax.numpy as jnp
        from jax import lax

        @jax.jit
        def chain(x):
            def body(c, r):
                return c + r.astype(jnp.uint32), ()
            out, _ = lax.scan(body, x,
                              jnp.arange(iters, dtype=jnp.int32))
            return out
        return chain

    x0 = jax.device_put(np.zeros((64, 256), np.uint32))
    differenced_trials(chain_factory, x0, iters_small=5, iters_big=505,
                       trials=2, windows=1)
    recs = [r for r in ledger.compile_records()
            if r["kind"] == "compile+warmup"]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["seconds"] > 0
    assert rec["warmup_small_s"] > 0 and rec["warmup_big_s"] > 0
    # jitted chains expose .lower(): the explicit lowering wall rides too
    assert rec["lower_seconds"] > 0


def test_runner_records_schedule_build_and_first_dispatch(fresh_ledger):
    from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment

    cfg = ExperimentConfig(nprocs=8, cb_nodes=2, data_size=64, comm_size=2,
                           method=1, ntimes=2, backend="local", verify=True,
                           results_csv=None)
    run_experiment(cfg, out=io.StringIO())
    kinds = {r["kind"] for r in ledger.compile_records()}
    assert {"schedule-build", "first-dispatch"} <= kinds
    assert ledger.total_compile_seconds() > 0


def test_xprof_crosscheck_reports_divergence(tmp_path, fresh_ledger):
    """--xprof: one extra profiled rep per method, a divergence report,
    and the timed path's records untouched (same record count/fields as
    a plain run)."""
    from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment

    out = io.StringIO()
    cfg = ExperimentConfig(nprocs=8, cb_nodes=2, data_size=64, comm_size=2,
                           method=1, ntimes=2, backend="local", verify=True,
                           results_csv=None, xprof=str(tmp_path / "xp"))
    recs = run_experiment(cfg, out=out)
    assert len(recs) == 1 and recs[0]["method"] == 1
    reports = ledger.xprof_reports()
    assert len(reports) == 1
    rep = reports[0]
    assert rep["label"].startswith("m1 ") and "[local]" in rep["label"]
    assert rep["reconstructed_s"] > 0
    if rep["error"] is None:
        # column-accurate source label: device span when a device plane
        # parsed out of the profile, host wall otherwise
        assert rep["source"] in ("xplane-device-span",
                                 "host-wall(profiled)")
        assert rep["total_s"] > 0 and rep["divergence_pct"] is not None
    assert "xprof m1" in out.getvalue()
