"""Differenced serial-chain timing scaffold."""

import numpy as np
import pytest

from tpu_aggcomm.harness.chained import differenced_per_rep, differenced_trials


def _factory():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def chain_factory(iters):
        @jax.jit
        def chain(x):
            def body(c, r):
                return c + r.astype(jnp.uint32), ()
            out, _ = lax.scan(body, x, jnp.arange(iters, dtype=jnp.int32))
            return out
        return chain
    return chain_factory


def test_differenced_positive_and_finite():
    import jax
    # a heavy enough chain that T(big) - T(small) is reliably positive
    x0 = jax.device_put(np.zeros((256, 1024), np.uint32))
    v = differenced_per_rep(_factory(), x0, iters_small=5, iters_big=2005,
                            trials=2, windows=2)
    assert np.isfinite(v) and v > 0


def test_differenced_records_samples_instant(tmp_path):
    """With tracing on, the accepted trial set lands in the event log as
    ONE ``chained.samples`` instant — the evidence obs/compare.py
    bootstraps whole-rep deltas from."""
    import jax

    from tpu_aggcomm.obs import trace
    from tpu_aggcomm.obs.trace import load_events

    x0 = jax.device_put(np.zeros((64, 256), np.uint32))
    trace.enable()
    try:
        per = differenced_trials(_factory(), x0, iters_small=5,
                                 iters_big=505, trials=2, windows=1)
        paths = trace.flush(str(tmp_path / "ch"))
    finally:
        trace.disable()
    insts = [e for e in load_events(paths[0])
             if e["ev"] == "instant" and e["name"] == "chained.samples"]
    assert len(insts) == 1
    assert insts[0]["args"]["samples"] == per


def test_differenced_rejects_bad_lengths():
    import jax
    x0 = jax.device_put(np.zeros((4, 4), np.uint32))
    with pytest.raises(ValueError, match="exceed"):
        differenced_trials(_factory(), x0, iters_small=5, iters_big=5)


def test_jax_ici_chained_rep_rows_do_not_alias():
    """The chained branch must hand out fresh Timer objects per rep —
    rep rows must not alias (jax_sim/jax_shard already deep-copy via
    Timer.from_array; jax_ici used to reuse ONE list for every rep, so
    mutating any rep's timer silently rewrote all of save_all_timing's
    rows)."""
    from tpu_aggcomm.backends.jax_ici import JaxIciBackend
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern

    p = AggregatorPattern(8, 3, data_size=16, comm_size=2)
    b = JaxIciBackend()
    b.run(compile_method(1, p), verify=True, chained=True, ntimes=2)
    rows = b.last_rep_timers
    assert len(rows) == 2
    assert rows[0] is not rows[1]
    assert rows[0][0] is not rows[1][0]
    before = rows[1][0].total_time
    rows[0][0].total_time += 1.0
    assert rows[1][0].total_time == before


def test_differenced_raises_when_unstable(monkeypatch):
    # force every diff non-positive by monkeypatching the clock to run
    # backwards an ACCELERATING step per call: a fixed step cancels to
    # ~ulp noise whose sign depends on how many clock reads precede the
    # timed windows (the warmup/ledger instrumentation also reads it)
    import itertools
    import tpu_aggcomm.harness.chained as ch
    import jax
    ticks = (-k * k * 1e-3 for k in itertools.count())
    monkeypatch.setattr(ch.time, "perf_counter", lambda: next(ticks))
    x0 = jax.device_put(np.zeros((4, 4), np.uint32))
    with pytest.raises(RuntimeError, match="unstable"):
        differenced_trials(_factory(), x0, iters_small=2, iters_big=50,
                           trials=2, windows=1)
