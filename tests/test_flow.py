"""Causal flow-tracing tests (obs/flow.py — ISSUE 20).

The pins that define the subsystem:

- **One decomposition arithmetic**: ``client_wall_s = t_recv -
  t_send``, ``server_wall_s`` = the workload profiler's canonical
  phase sum, ``wire_s = client_wall_s - server_wall_s``, ``residual_s
  = dispatch - joined run wall`` — each defined by ONE expression in
  obs/flow.py, re-run verbatim by ``validate_flow`` over a committed
  artifact's own rows (identical-computation float-exactness, never
  algebraic re-summation).
- **Named verdicts**: every joined request carries a dominant
  component from ``COMPONENT_ORDER`` mapped through ``VERDICTS`` —
  a bare number is a regression; ties break to the earlier component.
- **Crash honesty**: a SIGKILL-torn client journal loses at most one
  line; a send with no recv is named LOST in flight, torn lines are
  COUNTED into the integrity block, and the serve.request trace
  instants (which carry cid) stand in when the serve journal is torn.
- **Seeded determinism**: the warm-overhead bootstrap CI follows the
  regression-gate seed discipline — same streams + same seed ⟹ the
  same artifact body byte-for-byte.
- **Artifacts are self-proving**: ``FLOW_r*.json`` validates, replays
  REPRODUCED from the stream basenames recorded inside it, and every
  doctored number is named, not absorbed.
- **jax-free**: obs/flow.py and ``cli inspect flow`` run where
  ``import jax`` raises (poisoned-jax subprocess, the obs discipline).
"""

import glob
import json
import os
import subprocess
import sys

import pytest

import _jaxfree

REPO = _jaxfree.REPO

from tpu_aggcomm.obs.flow import (COMPONENT_ORDER, VERDICTS,
                                  decompose_request, dominant_component,
                                  flow_registry, flow_streams, render_flow,
                                  replay_flow, tail_client,
                                  warm_overhead_block, write_flow)
from tpu_aggcomm.obs.regress import validate_flow
from tpu_aggcomm.obs.workload import BOUNDARIES, attribute_phases
from tpu_aggcomm.resilience.journal import RunJournal

_SHAPE = {"method": 3, "nprocs": 8, "cb_nodes": 2, "comm_size": 2,
          "data_size": 64}


# ---------------------------------------------------------------------------
# Synthetic streams: the loadgen client journal, the serve journal with
# cid-stamped terminal records, and a cid-stamped trace.


def _stamps(*, queue=0.001, batch=0.0005, cache=0.0002, dispatch=0.010,
            respond=0.0003):
    """Cumulative boundary stamps (the serve journal's ``phases``
    payload) with the given per-phase durations."""
    s = {"admit": 0.0}
    s["queue"] = s["admit"] + queue
    s["batch"] = s["queue"] + batch
    s["cache"] = s["batch"] + cache
    s["dispatch"] = s["cache"] + dispatch
    s["respond"] = s["dispatch"] + respond
    return s


def _write_client(path, rows, *, torn_tail=False):
    """``rows``: {"i", "wall_s", optional "lost"/"rid"} — the
    serve_loadgen --client-journal line shapes, stamps computed with
    the loadgen's own expression so the stream agrees with itself."""
    with open(path, "w") as fh:
        for row in rows:
            i = row["i"]
            t0 = 100.0 + 0.5 * i
            fh.write(json.dumps({"ev": "send", "i": i, "t_send": t0,
                                 "shape": "m3 n8 a2 c2 d64"}) + "\n")
            if row.get("lost"):
                continue
            t1 = t0 + row["wall_s"]
            fh.write(json.dumps(
                {"ev": "recv", "i": i, "rid": row.get("rid", i),
                 "t_send": t0, "t_recv": t1, "client_wall_s": t1 - t0,
                 "ok": True, "shed": None,
                 "cache": row.get("cache", "hit"),
                 "error": None}) + "\n")
        if torn_tail:
            fh.write('{"ev": "recv", "i": 99, "t_se')
    return str(path)


def _write_serve(path, rows, *, torn_tail=False):
    """``rows``: {"rid", "stamps", "cache", "cid", optional "status"} —
    the server's admitted + terminal journal records (serve/server.py
    shapes, cid riding in the terminal record)."""
    j = RunJournal(str(path))
    fp = j.begin_session({"jax": "0.0-test"})
    for row in rows:
        rid = row["rid"]
        j.record({"request": rid}, fingerprint=fp, status="admitted",
                 shape=dict(_SHAPE), backend="jax_sim", iter=rid,
                 t_unix=1_700_000_000.0 + rid, queue_depth=0)
        if row.get("status", "done") == "admitted-only":
            continue
        stamps = row["stamps"]
        j.record({"request": rid}, fingerprint=fp,
                 status=row.get("status", "done"),
                 latency_s=stamps.get("respond"), batch_n=1,
                 cache=row.get("cache", "hit"), phases=dict(stamps),
                 batch_seq=row.get("seq", 0), batch_padded=1,
                 cid=row.get("cid"), queue_depth=None)
    if torn_tail:
        with open(path, "a") as fh:
            fh.write('{"key": {"request": 500}, "status": "don')
    return str(path)


def _write_trace(path, runs, *, with_instants=(), torn_tail=False):
    """``runs``: {"id", "cid", "total", "rounds": [wall...]} — one
    cid-stamped run event per dispatch plus its attribution cells (two
    ranks per round so round_stats' wall lands exactly on the given
    values), optionally serve.request instants (the torn-journal
    stand-in)."""
    events = []
    for r in runs:
        events.append({"ev": "run", "id": r["id"], "method": 3,
                       "name": "theta", "backend": "jax_sim",
                       "nprocs": 8, "data_size": 64, "ntimes": 1,
                       "combine": "sum", "cid": r["cid"]})
        events.append({"ev": "span", "run": r["id"], "rep": 0,
                       "rank": 0, "round": -1, "bucket": "total",
                       "dur_s": r["total"], "src": "measured",
                       "ts": 0.0, "dur": r["total"] * 1e6})
        for rnd, wall in enumerate(r["rounds"]):
            for rank in (0, 1):
                events.append({"ev": "span", "run": r["id"], "rep": 0,
                               "rank": rank, "round": rnd,
                               "bucket": "recv_wait",
                               "dur_s": wall if rank == 0
                               else wall * 0.5,
                               "src": "measured",
                               "ts": 1e3 * rnd, "dur": wall * 1e6})
    for inst in with_instants:
        events.append({"ev": "instant", "name": "serve.request",
                       "ts": 0.0, "args": inst})
    with open(path, "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")
        if torn_tail:
            fh.write('{"ev": "span", "run')
    return str(path)


def _streams(tmp_path, *, n=4, walls=None, torn=False):
    """One coherent three-stream set: n requests, one batch (cid b0)
    with a traced run, per-request walls large enough for positive
    wire."""
    walls = walls or [0.020 + 0.001 * i for i in range(n)]
    client = _write_client(
        tmp_path / "client.journal.jsonl",
        [{"i": i, "wall_s": walls[i]} for i in range(n)],
        torn_tail=torn)
    serve = _write_serve(
        tmp_path / "serve.journal.jsonl",
        [{"rid": i, "stamps": _stamps(), "cid": "b0"} for i in range(n)],
        torn_tail=torn)
    trace = _write_trace(
        tmp_path / "flow.trace.jsonl",
        [{"id": 1, "cid": "b0", "total": 0.004,
          "rounds": [0.002, 0.0015]}],
        torn_tail=torn)
    return client, serve, trace


# ---------------------------------------------------------------------------
# The decomposition arithmetic (identical-computation float-exactness).


def test_decompose_request_is_the_one_arithmetic():
    stamps = _stamps()
    client = {"t_send": 100.0, "t_recv": 100.0 + 0.02}
    server = {"phases": stamps}
    run = {"wall_s": 0.004}
    dec = decompose_request(client, server, run)
    # every derived number re-computes with the identical expression
    assert dec["client_wall_s"] == client["t_recv"] - client["t_send"]
    phases, _ = attribute_phases(stamps)
    want_server = sum(phases[b] for b in BOUNDARIES if b in phases)
    assert dec["server_wall_s"] == want_server
    assert dec["wire_s"] == dec["client_wall_s"] - want_server
    assert dec["components"]["round"] == 0.004
    assert dec["residual_s"] == phases["dispatch"] - 0.004
    assert dec["components"]["overhead"] == dec["residual_s"]
    for k, v in dec["components"].items():
        assert dec["fractions"][k] == v / dec["client_wall_s"]
    assert dec["dominant"] in COMPONENT_ORDER
    assert dec["verdict"] == VERDICTS[dec["dominant"]]
    assert dec["problems"] == []


def test_decompose_without_run_keeps_dispatch_unsplit():
    dec = decompose_request({"t_send": 0.0, "t_recv": 0.05},
                            {"phases": _stamps()}, None)
    phases, _ = attribute_phases(_stamps())
    # no joined run: the whole dispatch phase is the round component
    # and the overhead inside it is NOT quantifiable — never zeroed
    assert dec["components"]["round"] == phases["dispatch"]
    assert dec["residual_s"] is None
    assert "overhead" not in dec["components"]


def test_dominant_tie_breaks_to_earlier_component():
    assert dominant_component({"wire": 1.0, "round": 1.0}) == "wire"
    assert dominant_component({"round": 1.0, "overhead": 1.0}) == "round"
    assert dominant_component({}) is None


def test_stream_disagreement_is_a_named_problem():
    # client wall smaller than the server phase sum: wire < 0
    dec = decompose_request({"t_send": 0.0, "t_recv": 0.001},
                            {"phases": _stamps()}, None)
    assert dec["wire_s"] < 0
    assert any("disagree" in p for p in dec["problems"])
    # journal dispatch smaller than the joined run wall: residual < 0
    dec = decompose_request({"t_send": 0.0, "t_recv": 0.05},
                            {"phases": _stamps(dispatch=0.001)},
                            {"wall_s": 0.004})
    assert dec["residual_s"] < 0
    assert any("residual" in p for p in dec["problems"])


# ---------------------------------------------------------------------------
# The joiner over the three streams.


def test_flow_streams_joins_end_to_end(tmp_path):
    client, serve, trace = _streams(tmp_path)
    body = flow_streams(client, serve, [trace], seed=0)
    assert body["requests"]["client"] == 4
    assert body["requests"]["joined"] == 4
    assert body["requests"]["lost"] == []
    assert body["problems"] == []
    for row in body["per_request"]:
        assert row["server_source"] == "journal"
        assert row["cid"] == "b0"
        assert row["run"]["run_id"] == 1
        assert row["run"]["rounds_total_s"] == sum(
            r["wall_s"] for r in row["run"]["rounds"])
        assert row["verdict"] in VERDICTS.values()
    assert sum(body["verdicts"].values()) == 4
    # the render answers "where do the warm ms go" with named parts
    text = render_flow(body)
    assert "warm overhead ledger" in text and "rounds (" in text


def test_warm_overhead_ledger_arithmetic(tmp_path):
    client, serve, trace = _streams(tmp_path)
    body = flow_streams(client, serve, [trace], seed=0)
    wo = body["warm_overhead"]
    assert wo["n"] == 4 and len(wo["fractions"]) == 4
    by_rid = {r["rid"]: r for r in body["per_request"]}
    for rid, frac in zip(wo["rids"], wo["fractions"]):
        r = by_rid[rid]
        w = r["client_wall_s"]
        assert frac == (w - r["components"]["round"]) / w
    assert wo["mean"] == sum(wo["fractions"]) / len(wo["fractions"])
    assert len(wo["ci95"]) == 2 and wo["ci95"][0] <= wo["ci95"][1]
    # cold/failed requests never enter the warm ledger
    assert warm_overhead_block(
        [{"status": "done", "cache": "miss", "rid": 0,
          "client_wall_s": 1.0, "components": {"round": 0.5}}],
        seed=0) is None


def test_lost_request_named_and_torn_lines_counted(tmp_path):
    client = _write_client(
        tmp_path / "client.journal.jsonl",
        [{"i": 0, "wall_s": 0.02}, {"i": 1, "lost": True}],
        torn_tail=True)   # the SIGKILL mid-line tail
    serve = _write_serve(tmp_path / "serve.journal.jsonl",
                         [{"rid": 0, "stamps": _stamps(), "cid": "b0"}],
                         torn_tail=True)
    trace = _write_trace(tmp_path / "flow.trace.jsonl",
                         [{"id": 1, "cid": "b0", "total": 0.004,
                           "rounds": [0.002]}], torn_tail=True)
    tail = tail_client(client)
    assert tail["skipped_lines"] == 1   # exactly the torn line
    body = flow_streams(client, serve, [trace], seed=0)
    assert body["requests"]["lost"] == [1]
    assert any("LOST in flight" in p for p in body["problems"])
    assert body["integrity"]["client_torn_lines"] == 1
    assert body["integrity"]["journal_torn_lines"] == 1
    assert body["integrity"]["trace_torn_lines"] == 1
    assert "LOST" in render_flow(body)


def test_trace_instants_stand_in_for_torn_journal(tmp_path):
    # the serve journal never terminated rid 0 (torn tail) but the
    # serve.request instant carries rid + phases + cache + cid — the
    # joiner must still decompose, marked server_source == "trace"
    client = _write_client(tmp_path / "client.journal.jsonl",
                           [{"i": 0, "wall_s": 0.02}])
    serve = _write_serve(
        tmp_path / "serve.journal.jsonl",
        [{"rid": 0, "stamps": _stamps(), "status": "admitted-only"}])
    trace = _write_trace(
        tmp_path / "flow.trace.jsonl",
        [{"id": 1, "cid": "b0", "total": 0.004, "rounds": [0.002]}],
        with_instants=[{"rid": 0, "ok": True, "cache": "hit",
                        "cid": "b0", "phases": _stamps()}])
    body = flow_streams(client, serve, [trace], seed=0)
    [row] = body["per_request"]
    assert row["server_source"] == "trace"
    assert row["run"]["run_id"] == 1   # the cid join still lands
    assert row["verdict"] in VERDICTS.values()
    assert body["problems"] == []


def test_client_journal_disagreeing_with_itself_is_named(tmp_path):
    client = tmp_path / "client.journal.jsonl"
    with open(client, "w") as fh:
        fh.write(json.dumps({"ev": "send", "i": 0,
                             "t_send": 100.0}) + "\n")
        fh.write(json.dumps({"ev": "recv", "i": 0, "rid": 0,
                             "t_send": 100.0, "t_recv": 100.02,
                             "client_wall_s": 0.5}) + "\n")
    serve = _write_serve(tmp_path / "serve.journal.jsonl",
                         [{"rid": 0, "stamps": _stamps(), "cid": "b0"}])
    body = flow_streams(str(client), serve, [], seed=0)
    assert any("disagrees with itself" in p for p in body["problems"])


# ---------------------------------------------------------------------------
# Seeded determinism + the artifact contract.


def test_flow_streams_seeded_and_deterministic(tmp_path):
    client, serve, trace = _streams(tmp_path)
    a = flow_streams(client, serve, [trace], seed=7)
    b = flow_streams(client, serve, [trace], seed=7)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    c = flow_streams(client, serve, [trace], seed=8)
    assert c["seed"] == 8 and c["warm_overhead"]["seed"] == 8
    # everything but the seeded CI + recorded seed is seed-independent
    # (with n=4 fractions two seeds may land on the same percentile
    # bounds, so the CI itself is not asserted to differ)
    for blob in (a, c):
        blob["warm_overhead"].pop("ci95")
        blob["warm_overhead"].pop("seed")
        blob.pop("seed")
    assert json.dumps(a, sort_keys=True) == json.dumps(c, sort_keys=True)


def test_artifact_validates_replays_and_names_corruption(tmp_path):
    client, serve, trace = _streams(tmp_path)
    body = flow_streams(client, serve, [trace], seed=0)
    art = tmp_path / "FLOW_r01.json"
    blob = write_flow(str(art), body)
    assert blob["schema"] == "flow-v1"
    assert validate_flow(blob, "FLOW_r01.json") == []
    rep = replay_flow(str(art))
    assert rep["verdict"] == "REPRODUCED", rep["problems"]

    # a doctored derived number is named by the validator, not absorbed
    bad = json.loads(json.dumps(blob))
    bad["per_request"][0]["wire_s"] += 1e-9
    errs = validate_flow(bad, "FLOW_bad.json")
    assert errs and any("wire_s" in e for e in errs)

    bad = json.loads(json.dumps(blob))
    bad["warm_overhead"]["mean"] += 1e-12
    errs = validate_flow(bad, "FLOW_bad.json")
    assert errs and any("warm_overhead" in e for e in errs)

    # a doctored artifact MISMATCHes on replay, the key named
    doctored = json.loads(json.dumps(blob))
    doctored["verdicts"] = {"wire-bound": 99}
    art2 = tmp_path / "FLOW_r02.json"
    with open(art2, "w") as fh:
        json.dump(doctored, fh)
    rep = replay_flow(str(art2))
    assert rep["verdict"] == "MISMATCH"
    assert any("verdicts" in p for p in rep["problems"])

    # a shrunk stream is a named MISMATCH too, never a silent pass
    os.unlink(trace)
    rep = replay_flow(str(art))
    assert rep["verdict"] == "MISMATCH"
    assert any("not found" in p for p in rep["problems"])


def test_validator_refuses_disagreeing_streams(tmp_path):
    client, serve, trace = _streams(tmp_path)
    body = flow_streams(client, serve, [trace], seed=0)
    blob = dict(body, schema="flow-v1", manifest={}, created_unix=0.0,
                problems=["request rid=0: the streams disagree"])
    errs = validate_flow(blob, "FLOW_bad.json")
    assert errs and any("disagree" in e for e in errs)


# ---------------------------------------------------------------------------
# /metrics gauges + history discovery.


def test_flow_registry_folds_artifact_numbers_verbatim(tmp_path):
    from tpu_aggcomm.obs import export
    from tpu_aggcomm.obs.regress import parse_openmetrics
    client, serve, trace = _streams(tmp_path)
    blob = write_flow(str(tmp_path / "FLOW_r01.json"),
                      flow_streams(client, serve, [trace], seed=0))
    reg = export.MetricsRegistry()
    flow_registry(blob, reg)
    samples = {(s["name"], tuple(sorted(s["labels"].items()))): s["value"]
               for s in parse_openmetrics(reg.render())["samples"]}
    assert samples[("tpu_aggcomm_flow_warm_overhead_fraction", ())] \
        == blob["warm_overhead"]["mean"]
    for comp, st in blob["warm_components"].items():
        assert samples[("tpu_aggcomm_flow_warm_component_fraction",
                        (("component", comp),))] == st["mean_fraction"]
    for verdict, n in blob["verdicts"].items():
        assert samples[("tpu_aggcomm_flow_requests",
                        (("verdict", verdict),))] == float(n)


def test_history_discovers_flow_series(tmp_path):
    from tpu_aggcomm.obs.history import build_index, check_trends
    for rnd in (1, 2):
        client, serve, trace = _streams(tmp_path)
        write_flow(str(tmp_path / f"FLOW_r{rnd:02d}.json"),
                   flow_streams(client, serve, [trace], seed=0))
    idx = build_index(str(tmp_path))
    assert [f["file"] for f in idx["flow"]] == ["FLOW_r01.json",
                                               "FLOW_r02.json"]
    key = "flow warm overhead fraction"
    from tpu_aggcomm.obs.history import flow_series
    pts = flow_series(str(tmp_path))[key]
    assert [p["round"] for p in pts] == [1, 2]
    assert all(p["unit"] == "frac" for p in pts)
    gates = check_trends(str(tmp_path))
    assert key in gates["series"] and "verdict" in gates["series"][key]
    assert gates["ok"]


# ---------------------------------------------------------------------------
# The watchtower's flow evidence stream (satellite 3).


def test_watch_attributes_dominant_shift_from_flow():
    from tpu_aggcomm.obs.watch import EVIDENCE_STREAMS, attribute_anomaly
    assert "flow" in EVIDENCE_STREAMS
    rows = [{"rid": i, "wall_s": 0.01 if i < 4 else 0.03, "status": "done"}
            for i in range(8)]
    detection = {"at_index": 4, "direction": "up", "delta_rel": 2.0}
    doms = ([{"rid": i, "verdict": "round-bound"} for i in range(4)]
            + [{"rid": i, "verdict": "compile-bound"}
               for i in range(4, 8)])
    got = attribute_anomaly(
        detection, rows=rows, split_rid=4,
        evidence={"flow": {"artifact": "FLOW_r01.json",
                           "dominants": doms}})
    assert got["evidence"] == "flow"
    assert got["cause"] == "dominant-shift:round-bound->compile-bound"
    assert "FLOW_r01.json" in got["detail"]
    # no shift -> the UNEXPLAINED fallback keeps its committed wording
    same = [{"rid": i, "verdict": "round-bound"} for i in range(8)]
    got = attribute_anomaly(
        detection, rows=rows, split_rid=4,
        evidence={"flow": {"artifact": "FLOW_r01.json",
                           "dominants": same}})
    assert got["cause"] == "UNEXPLAINED"
    assert "no ledger/resilience/shed/explain evidence" in got["detail"]


# ---------------------------------------------------------------------------
# Perfetto: cid on request slices, flow links to the dispatch run.


def test_perfetto_emits_cid_and_flow_links():
    from tpu_aggcomm.obs.perfetto import RANKS_PID, SERVE_PID, \
        to_chrome_trace
    stamps = _stamps()
    events = [
        {"ev": "run", "id": 1, "method": 3, "name": "theta",
         "backend": "jax_sim", "cid": "b0"},
        {"ev": "span", "run": 1, "rep": 0, "rank": 0, "round": 0,
         "bucket": "recv_wait", "dur_s": 0.002, "ts": 50.0,
         "dur": 2000.0, "src": "measured"},
        {"ev": "instant", "name": "serve.request", "ts": 10_000.0,
         "args": {"rid": 0, "ok": True, "cache": "hit", "cid": "b0",
                  "phases": stamps}},
    ]
    tr = to_chrome_trace(events)["traceEvents"]
    serve_slices = [e for e in tr if e.get("cat") == "serve"]
    assert serve_slices and all(
        s["args"]["cid"] == "b0" for s in serve_slices)
    flows = [e for e in tr if e.get("cat") == "flow"]
    assert len(flows) == 2
    start = next(e for e in flows if e["ph"] == "s")
    finish = next(e for e in flows if e["ph"] == "f")
    assert start["id"] == finish["id"]
    assert start["pid"] == SERVE_PID and finish["pid"] == RANKS_PID
    assert finish["bp"] == "e"
    # the arrow departs from the dispatch slice's start
    dispatch = next(s for s in serve_slices
                    if s["args"]["phase"] == "dispatch")
    assert start["ts"] == dispatch["ts"]
    # no cid -> no dangling flow events
    tr2 = to_chrome_trace([events[2]])["traceEvents"]
    assert not [e for e in tr2 if e.get("cat") == "flow"]


# ---------------------------------------------------------------------------
# The jax-free pins (the obs discipline, subprocess-enforced).


def test_flow_is_jaxfree(tmp_path):
    client, serve, trace = _streams(tmp_path)
    code = (
        _jaxfree.pure_import_code("tpu_aggcomm.obs.flow") +
        "; from tpu_aggcomm.obs.flow import flow_streams, write_flow, "
        "replay_flow"
        f"; b = flow_streams({client!r}, {serve!r}, [{trace!r}], seed=0)"
        "; assert b['problems'] == [] and b['requests']['joined'] == 4"
        f"; write_flow({str(tmp_path / 'FLOW_r01.json')!r}, b)"
        f"; r = replay_flow({str(tmp_path / 'FLOW_r01.json')!r})"
        "; assert r['verdict'] == 'REPRODUCED', r['problems']"
        "; import sys; assert 'jax' not in sys.modules")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=str(tmp_path),
        env=_jaxfree.poisoned_env(
            tmp_path, "the flow joiner must answer where a wedged "
                      "tunnel hangs import jax"),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_cli_inspect_flow_is_jaxfree(tmp_path):
    client, serve, trace = _streams(tmp_path)
    env = _jaxfree.poisoned_env(
        tmp_path, "inspect flow must answer on a wedged tunnel")
    art = tmp_path / "FLOW_r01.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "flow",
         client, serve, trace, "--seed", "0", "--json", str(art)],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "flow trace over" in proc.stdout
    assert "warm overhead ledger" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "flow",
         "--replay", str(art)],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "REPRODUCED" in proc.stdout


def test_cli_refuses_artifact_over_disagreeing_streams(tmp_path):
    # negative wire: the CLI must print the problem and refuse --json
    client = _write_client(tmp_path / "client.journal.jsonl",
                           [{"i": 0, "wall_s": 0.001}])
    serve = _write_serve(tmp_path / "serve.journal.jsonl",
                         [{"rid": 0, "stamps": _stamps(), "cid": "b0"}])
    art = tmp_path / "FLOW_r01.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "flow",
         client, serve, "--json", str(art)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode != 0
    assert "PROBLEM" in proc.stdout
    assert not art.exists()


# ---------------------------------------------------------------------------
# The committed exemplar (the ci_tier1.sh gate's subject).


def test_committed_exemplar_artifact_accepts():
    paths = sorted(glob.glob(os.path.join(REPO, "FLOW_r*.json")))
    assert paths, "no committed FLOW_r*.json exemplar at the repo root"
    for path in paths:
        with open(path) as fh:
            blob = json.load(fh)
        name = os.path.basename(path)
        assert validate_flow(blob, name) == [], name
        rep = replay_flow(path)
        assert rep["verdict"] == "REPRODUCED", (name, rep["problems"])
        # the exemplar answers the headline question: named verdicts
        # and a warm overhead ledger with a seeded CI
        assert blob["verdicts"]
        wo = blob["warm_overhead"]
        assert wo and wo["n"] >= 1 and wo["ci95"] is not None
