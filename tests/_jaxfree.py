"""Shared poisoned-jax subprocess harness for the per-suite jax-free pins.

Seven suites (obs, traffic, tune, faults, resilience, telemetry, ledger —
plus analysis) pin that their subsystem runs where ``import jax`` raises:
a dead axon tunnel makes ``import jax`` HANG, and the poison turns that
hang into an immediate, named failure so a test can assert the import
never happens at all. The recipe used to be copy-pasted per suite; it
now lives here, parameterized by the purity CONTRACT itself
(``tpu_aggcomm.analysis.lint.PURE_PACKAGES``) so the static linter and
the runtime pins can never disagree about what "jax-free" means.

tests/ has no ``__init__.py`` — import this as ``import _jaxfree``
(pytest puts each test file's directory on ``sys.path``).
"""

import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def poisoned_env(tmp_path, reason="declared-pure code must not import jax"):
    """A subprocess env where ``import jax`` raises ImportError loudly.

    ``tmp_path`` gains a fake ``jax`` package whose ``__init__`` raises,
    and PYTHONPATH puts it AHEAD of the real one; the repo root rides
    along so ``tpu_aggcomm`` stays importable from any cwd.
    """
    poison = tmp_path / "jax"
    poison.mkdir(exist_ok=True)
    (poison / "__init__.py").write_text(
        "raise ImportError('poisoned jax: %s')\n" % reason)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(tmp_path) + os.pathsep + REPO
    return env


def pure_modules(prefix=None):
    """Every module the linter declares jax-pure (analysis.lint's
    PURE_PACKAGES, resolved against the tree), optionally restricted to
    those under a dotted ``prefix``."""
    from tpu_aggcomm.analysis.lint import pure_modules as _pure
    mods = _pure()
    if prefix is not None:
        mods = [m for m in mods
                if m == prefix or m.startswith(prefix + ".")]
        assert mods, "no declared-pure modules under %r" % (prefix,)
    return mods


def pure_import_code(prefix=None):
    """A ``python -c`` snippet importing every declared-pure module
    (optionally just those under ``prefix``) and asserting jax never
    loaded — the linter's rule list, executed."""
    mods = pure_modules(prefix)
    return ("import " + ", ".join(mods) + ", sys; "
            "assert 'jax' not in sys.modules, 'pure module imported jax'")
