"""Single-chip vectorized backend: every method delivers byte-exact data on
ONE device (ranks as an array axis), matching the local oracle — the path
that lets the whole registry run on the single tunneled TPU chip."""

import numpy as np
import pytest

from tpu_aggcomm.backends.jax_sim import JaxSimBackend
from tpu_aggcomm.backends.local import LocalBackend
from tpu_aggcomm.core.methods import METHODS, compile_method, method_ids
from tpu_aggcomm.core.pattern import AggregatorPattern

NON_TAM = [m for m in method_ids(include_dead=True) if not METHODS[m].tam]


@pytest.mark.parametrize("method", NON_TAM)
def test_sim_matches_oracle(method):
    p = AggregatorPattern(8, 3, data_size=32, comm_size=3)
    sched = compile_method(method, p)
    recv_s, timers = JaxSimBackend().run(sched, verify=True, iter_=0)
    recv_o, _ = LocalBackend().run(sched, verify=True, iter_=0)
    for a, b in zip(recv_s, recv_o):
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(a, b)
    assert timers[0].total_time > 0


@pytest.mark.parametrize("method,cs", [(1, 1), (2, 2), (3, 8), (5, 3),
                                       (13, 2), (17, 3), (20, 4)])
def test_sim_throttle_sweep(method, cs):
    # larger than the device count on purpose: rank count is free here
    p = AggregatorPattern(12, 5, data_size=16, comm_size=cs, proc_node=2)
    sched = compile_method(method, p)
    JaxSimBackend().run(sched, verify=True)


@pytest.mark.parametrize("placement", [0, 1, 2, 3])
def test_sim_placements(placement):
    p = AggregatorPattern(16, 6, data_size=8, comm_size=4,
                          placement=placement, proc_node=4)
    JaxSimBackend().run(compile_method(1, p), verify=True)


def test_sim_ntimes_and_iters():
    p = AggregatorPattern(8, 3, data_size=16, comm_size=3)
    sched = compile_method(2, p)
    b = JaxSimBackend()
    _, timers = b.run(sched, ntimes=3, verify=True, iter_=2)
    assert len(b.last_rep_timers) == 3
    assert timers[0].total_time > 0


def test_sim_chained_measurement():
    p = AggregatorPattern(8, 3, data_size=16, comm_size=3)
    sched = compile_method(1, p)
    b = JaxSimBackend()
    per_rep = b.measure_per_rep(sched, iters_small=5, iters_big=505,
                                trials=1, windows=2)
    assert np.isfinite(per_rep)
    # run(chained=True) synthesizes timers from the chained measurement
    recv, timers = b.run(sched, ntimes=2, verify=True, chained=True)
    assert timers[0].total_time != 0


@pytest.mark.parametrize("direction_m,pn", [(15, 2), (15, 4), (16, 2),
                                            (16, 4)])
def test_sim_tam_matches_oracle(direction_m, pn):
    from tpu_aggcomm.tam.engine import gen_tam_schedule, tam_oracle
    from tpu_aggcomm.core.pattern import Direction
    d = (Direction.ALL_TO_MANY if direction_m == 15
         else Direction.MANY_TO_ALL)
    p = AggregatorPattern(8, 3, data_size=32, proc_node=pn, direction=d)
    tam = gen_tam_schedule(p)
    recv_s, timers = JaxSimBackend().run(tam, verify=True, iter_=1)
    recv_o = tam_oracle(tam, iter_=1)
    for a, b in zip(recv_s, recv_o):
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(a, b)
    assert timers[0].total_time > 0


def test_sim_cli_sweep(tmp_path, capsys):
    from tpu_aggcomm.cli import main
    csv = tmp_path / "results.csv"
    rc = main(["sweep", "-n", "8", "-m", "1", "-a", "3", "-d", "64",
               "--backend", "jax_sim", "--verify",
               "--comm-sizes", "2,8", "--results-csv", str(csv)])
    assert rc == 0
    assert csv.exists()
    out = capsys.readouterr().out
    assert "RUN_OPTS" in out


def test_sim_profile_rounds():
    p = AggregatorPattern(8, 3, data_size=16, comm_size=2)
    sched = compile_method(1, p)
    b = JaxSimBackend()
    recv, timers = b.run(sched, verify=True, profile_rounds=True)
    assert timers[0].recv_wait_all_time > 0
    assert len(b.last_round_times) == 1
    from tpu_aggcomm.backends.jax_sim import _round_tables
    n_rounds = len(_round_tables(sched)[0])
    assert len(b.last_round_times[0]) == n_rounds > 1


def test_sim_profile_rounds_dense_single_segment():
    p = AggregatorPattern(8, 3, data_size=16)
    b = JaxSimBackend()
    recv, timers = b.run(compile_method(8, p), verify=True,
                         profile_rounds=True)
    assert len(b.last_round_times[0]) == 1
    assert timers[0].recv_wait_all_time == 0


def test_sim_profile_rounds_excludes_chained():
    p = AggregatorPattern(8, 3, data_size=16)
    with pytest.raises(ValueError, match="exclusive"):
        JaxSimBackend().run(compile_method(1, p), chained=True,
                            profile_rounds=True)


def test_sim_scanned_rounds_byte_exact():
    """Many-round schedules take the lax.scan lowering (>=32 rounds);
    delivery stays byte-exact vs the local oracle, including a barrier
    method."""
    from tpu_aggcomm.backends.local import LocalBackend
    for m, kwargs in ((1, {}), (2, {}), (17, dict(proc_node=2))):
        p = AggregatorPattern(64, 5, data_size=16, comm_size=1, **kwargs)
        sched = compile_method(m, p)
        recv_s, _ = JaxSimBackend().run(sched, verify=True)
        recv_o, _ = LocalBackend().run(sched, verify=True)
        for a, b in zip(recv_s, recv_o):
            if a is not None:
                np.testing.assert_array_equal(a, b)
