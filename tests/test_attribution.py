"""Per-phase timer attribution (harness/attribution.py) — the
fenced-segment approximation that fills post/send-wait/recv-wait columns
on the compiled backends (VERDICT r2 item 1; reference brackets at
mpi_test.c:1768-1815, max-reduce at 2184)."""

import numpy as np
import pytest

from tpu_aggcomm.core.methods import compile_method, method_ids
from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.core.schedule import TimerBucket
from tpu_aggcomm.harness.attribution import (POST_COST_BYTES,
                                             attribute_rounds,
                                             attribute_tam_total,
                                             attribute_total,
                                             rank_round_weights,
                                             tam_rank_weights)
from tpu_aggcomm.harness.timer import max_reduce


def _pattern(n=8, a=3, d=256, c=3, p=1):
    return AggregatorPattern(nprocs=n, cb_nodes=a, data_size=d,
                             comm_size=c, proc_node=p)


def test_m1_aggregator_weights_pinned():
    """Hand-computed weights for m=1, n=8, a=3, c=3, d=256 (aggregators =
    ranks 0/3/6, steps = 3). Rank 0: 3 Issend + 8 Irecv posts, per-round
    recv Waitalls over 3+3+2 messages, final send Waitall over 3."""
    sched = compile_method(1, _pattern())
    acc = rank_round_weights(sched)[0]
    post = sum(w for (r, b), w in acc.items() if b is TimerBucket.POST)
    recv = sum(w for (r, b), w in acc.items() if b is TimerBucket.RECV_WAIT)
    send = sum(w for (r, b), w in acc.items() if b is TimerBucket.SEND_WAIT)
    assert post == 11 * POST_COST_BYTES == 5632
    assert recv == 8 * 256 == 2048
    assert send == 3 * 256 == 768


def test_m1_attribute_total_fractions_pinned():
    sched = compile_method(1, _pattern())
    timers = attribute_total(sched, 1.0)
    t0 = timers[0]                       # aggregator
    assert t0.total_time == 1.0
    assert np.isclose(t0.post_request_time, 5632 / 8448)
    assert np.isclose(t0.recv_wait_all_time, 2048 / 8448)
    assert np.isclose(t0.send_wait_all_time, 768 / 8448)
    t1 = timers[1]                       # non-aggregator: 3 posts + send wait
    assert np.isclose(t1.post_request_time, 2 / 3)
    assert np.isclose(t1.send_wait_all_time, 1 / 3)
    assert t1.recv_wait_all_time == 0.0


def test_phase_sum_equals_total_every_method():
    """Every dispatched method: each rank's phase columns sum to the
    measured total (RECV_AND_SEND_WAIT ranks double-charge, exactly like
    the reference's non-aggregator Waitall bracket, mpi_test.c:1505-1510,
    so the sum may exceed but never undershoot)."""
    for m in method_ids():
        sched = compile_method(m, _pattern())
        for t in attribute_total(sched, 1.0):
            assert t.total_time == 1.0
            s = (t.post_request_time + t.send_wait_all_time
                 + t.recv_wait_all_time + t.barrier_time)
            if s > 0:
                assert s >= 0.999, (m, s)
                assert s <= 2.001, (m, s)


def test_attribute_rounds_respects_round_structure():
    """All measured time in round 0: rank 0 (aggregator) splits it between
    its round-0 posts and Waitall; rank 1 (posts in round 1) gets nothing
    but keeps the full elapsed total."""
    sched = compile_method(1, _pattern())
    timers = attribute_rounds(sched, {0: 1.0, 1: 0.0, 2: 0.0})
    t0 = timers[0]
    # round 0 weights for rank 0: (3 Issend + 3 Irecv) posts, Waitall of 3
    assert np.isclose(t0.post_request_time, 3072 / 3840)
    assert np.isclose(t0.recv_wait_all_time, 768 / 3840)
    assert t0.send_wait_all_time == 0.0
    assert t0.total_time == 1.0
    t1 = timers[1]
    assert t1.post_request_time == 0.0 and t1.total_time == 1.0


def test_collective_methods_total_only():
    """m=5/8 bracket only the Alltoallw loop in the reference
    (mpi_test.c:624-648) — phases stay zero."""
    for m in (5, 8):
        sched = compile_method(m, _pattern())
        for t in attribute_total(sched, 2.0):
            assert t.total_time == 2.0
            assert t.post_request_time == t.recv_wait_all_time == \
                t.send_wait_all_time == t.barrier_time == 0.0


def test_readme_calibration_post_share():
    """The README config (n=32, a=14, d=2048, c=3, README.md:40-49)
    reports a ~21.8% post share; the weight model gives the aggregator
    rank exactly 20% — the calibration POST_COST_BYTES=512 is pinned."""
    sched = compile_method(1, _pattern(n=32, a=14, d=2048, c=3))
    t = attribute_total(sched, 1.0)[0]
    assert np.isclose(t.post_request_time, 0.2)
    assert 0.15 < t.post_request_time < 0.25


def test_tam_weights_proxy_structure():
    """m=15, 2 nodes of 4: proxies (0, 4) carry the inter-node send_wait
    weight; non-proxies have intra-only recv_wait weight."""
    sched = compile_method(15, _pattern(n=8, a=3, d=256, c=3, p=4))
    rw, sw = tam_rank_weights(sched)
    assert sw[0] > 0 and sw[4] > 0
    for r in (1, 2, 3, 5, 6, 7):
        assert sw[r] == 0.0
        assert rw[r] > 0
    timers = attribute_tam_total(sched, 1.0)
    for t in timers:
        assert t.total_time == 1.0
        assert np.isclose(t.recv_wait_all_time + t.send_wait_all_time, 1.0)
    assert timers[0].send_wait_all_time > 0
    assert timers[1].send_wait_all_time == 0.0


def test_jax_sim_phase_columns_nonzero():
    """End-to-end: a jax_sim run of m=1 c=3 yields non-zero post/send/recv
    columns summing to total on the aggregator rank (VERDICT r2 'Done'
    criterion)."""
    from tpu_aggcomm.backends.jax_sim import JaxSimBackend
    sched = compile_method(1, _pattern())
    recv, timers = JaxSimBackend().run(sched, verify=True)
    t0 = timers[0]
    assert t0.post_request_time > 0
    assert t0.recv_wait_all_time > 0
    assert t0.send_wait_all_time > 0
    assert np.isclose(t0.post_request_time + t0.recv_wait_all_time
                      + t0.send_wait_all_time, t0.total_time)
    mx = max_reduce(timers)
    assert mx.post_request_time > 0 and mx.recv_wait_all_time > 0


def test_jax_sim_profiled_phase_columns():
    from tpu_aggcomm.backends.jax_sim import JaxSimBackend
    sched = compile_method(1, _pattern())
    b = JaxSimBackend()
    recv, timers = b.run(sched, verify=True, profile_rounds=True)
    t0 = timers[0]
    assert t0.post_request_time > 0
    assert t0.recv_wait_all_time > 0
    s = (t0.post_request_time + t0.recv_wait_all_time
         + t0.send_wait_all_time + t0.barrier_time)
    assert np.isclose(s, t0.total_time)


def test_jax_sim_tam_phase_columns():
    from tpu_aggcomm.backends.jax_sim import JaxSimBackend
    sched = compile_method(15, _pattern(n=8, a=3, d=256, c=3, p=4))
    recv, timers = JaxSimBackend().run(sched, verify=True)
    assert timers[0].send_wait_all_time > 0      # proxy: inter-node P3
    assert timers[1].recv_wait_all_time > 0      # non-proxy: intra-node
    assert timers[1].send_wait_all_time == 0.0


def test_weights_for_distinguishes_methods():
    """Regression (round-3 review): m=4 and m=11 lower to the same comm
    shape but charge different buckets; a reused backend instance must not
    attribute one method's time with the other's structure."""
    from tpu_aggcomm.harness.attribution import weights_for
    p = _pattern()
    w4 = weights_for(compile_method(4, p))
    w11 = weights_for(compile_method(11, p))
    assert w4 != w11
    t4 = attribute_total(compile_method(4, p), 1.0, weights=w4)
    t4_fresh = attribute_total(compile_method(4, p), 1.0)
    for a, b in zip(t4, t4_fresh):
        assert a == b


def test_jax_ici_reused_instance_keeps_method_attribution():
    """End-to-end collision regression: run m=4 then m=11 on ONE backend
    (the -m 0 run-all pattern); m=11's attribution must match a fresh
    instance's."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from tpu_aggcomm.backends.jax_ici import JaxIciBackend
    b = JaxIciBackend()
    b.run(compile_method(4, _pattern()), verify=True)
    _, t_shared = b.run(compile_method(11, _pattern()), verify=True)
    _, t_fresh = JaxIciBackend().run(compile_method(11, _pattern()),
                                     verify=True)
    for a, c in zip(t_shared, t_fresh):
        for f in ("post_request_time", "send_wait_all_time",
                  "recv_wait_all_time", "barrier_time"):
            ra = getattr(a, f) / a.total_time if a.total_time else 0.0
            rc = getattr(c, f) / c.total_time if c.total_time else 0.0
            assert np.isclose(ra, rc), (f, ra, rc)


def test_jax_ici_phase_columns_nonzero():
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from tpu_aggcomm.backends.jax_ici import JaxIciBackend
    sched = compile_method(1, _pattern())
    recv, timers = JaxIciBackend().run(sched, verify=True,
                                       profile_rounds=True)
    t0 = timers[0]
    assert t0.post_request_time > 0
    assert t0.recv_wait_all_time > 0
    s = (t0.post_request_time + t0.recv_wait_all_time
         + t0.send_wait_all_time + t0.barrier_time)
    assert np.isclose(s, t0.total_time)
