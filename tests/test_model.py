"""Analytic cost model (ISSUE 11) guarantees:

- jax-free: every ``tpu_aggcomm.model`` module runs where ``import
  jax`` raises (poisoned-jax subprocess pin via tests/_jaxfree.py — the
  model must price schedules precisely when a wedged tunnel makes jax
  unimportable), and so does a full ``tune --synthetic --model-prune``
  round trip;
- seeded determinism: ``build_artifact`` twice with the same seed over
  the same committed inputs is byte-identical minus ``created_unix``,
  and the committed ``PREDICT_r11.json`` replays to REPRODUCED — the
  same artifact-replay discipline as ``tune --replay``;
- rank-order transfer (the validation headline): parameters fitted on
  the committed n=256/n=1024 quiet-chip grids predict the HELD-OUT
  n=32 grid's method rank order at Kendall tau_b >= 0.6 with top-1
  agreement — pinned against the committed artifact so a calibration
  change that silently degrades transfer fails here by name;
- verdict taxonomy on the committed fault-trace pair: the dead-link
  detour's inflation is ATTRIBUTED (slow-injected envelope — jax_sim's
  per-rep delay smears across attributed round walls), the healthy
  rounds are bandwidth-bound, and nothing is UNEXPLAINED;
- self-contradiction is schema-invalid: ``validate_predict`` fails an
  artifact whose UNEXPLAINED verdict sits inside its own recorded
  tolerance, the same "a verdict its numbers contradict" rule as the
  traffic auditor; ``validate_compare`` covers the compare-v1 family;
- the live floor: ``floor_from_trace_events`` over the committed
  healthy trace and the committed artifact's parameters yields a
  positive per-rep floor (what ``inspect live`` feeds the watchdog).
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_aggcomm.model.artifact import (build_artifact, load_artifact,
                                        replay_artifact)
from tpu_aggcomm.model.calibrate import parse_results_grids
from tpu_aggcomm.model.features import PARAM_NAMES
from tpu_aggcomm.model.fit import kendall_tau_b, nnls
from tpu_aggcomm.model.predict import (floor_from_trace_events,
                                       predict_schedule)
from tpu_aggcomm.obs.regress import validate_compare, validate_predict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PREDICT = os.path.join(REPO, "PREDICT_r11.json")
COMPARE = os.path.join(REPO, "COMPARE_r11.json")
HEALTHY = os.path.join(REPO, "FAULT_healthy.trace.jsonl")


def _poisoned_env(tmp_path):
    import _jaxfree
    return _jaxfree.poisoned_env(tmp_path,
                                 "the cost model must not import jax")


def test_model_modules_survive_poisoned_jax(tmp_path):
    import _jaxfree
    code = _jaxfree.pure_import_code("tpu_aggcomm.model")
    res = subprocess.run([sys.executable, "-c", code],
                         env=_poisoned_env(tmp_path),
                         capture_output=True, text=True, cwd=REPO)
    assert res.returncode == 0, res.stderr


def test_explain_replay_survives_poisoned_jax(tmp_path):
    """The full replay path — calibration, grid validation, crossover,
    every explain verdict — re-derives with jax unimportable."""
    res = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "explain",
         "--replay", PREDICT],
        env=_poisoned_env(tmp_path), capture_output=True, text=True,
        cwd=REPO)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "REPRODUCED" in res.stdout


def test_parse_results_grids_shapes():
    grids = parse_results_grids(os.path.join(REPO, "RESULTS_TPU.md"))
    for name in ("n32", "n256", "n1024"):
        assert name in grids, sorted(grids)
    g32 = grids["n32"]
    assert g32["nprocs"] == 32 and g32["cb_nodes"] == 14
    # two method columns per table row, infinity mapped to the sentinel
    comms = {c["comm"] for c in g32["cells"]}
    assert 999_999_999 in comms
    assert {c["method"] for c in g32["cells"]} == {1, 2}


def test_kendall_tau_b_units():
    def tau(a, b):
        return kendall_tau_b(list(zip(a, b)))

    assert tau([1, 2, 3], [10, 20, 30]) == pytest.approx(1.0)
    assert tau([1, 2, 3], [30, 20, 10]) == pytest.approx(-1.0)
    assert tau([1], [2]) is None
    assert tau([1, 1, 1], [1, 2, 3]) is None  # zero denominator
    # ties on one side shrink |tau| without flipping sign
    t = tau([1, 2, 2, 3], [1, 2, 3, 4])
    assert t is not None and 0 < t < 1


def test_nnls_nonnegative_and_recovers():
    # y = 2*x0 + 0*x1 + 3*x2 exactly, nonneg truth -> exact recovery
    rows = [[1, 0, 0], [0, 1, 0], [0, 0, 1], [1, 1, 1]]
    y = [2, 0, 3, 5]
    coef = nnls(rows, y, [1.0] * 4)
    assert coef == pytest.approx([2, 0, 3], abs=1e-9)
    # a negative-truth column clamps to zero, never goes negative
    coef2 = nnls([[1, 1], [1, 2], [1, 3]], [3, 2, 1], [1.0] * 3)
    assert all(c >= 0 for c in coef2)


@pytest.mark.slow  # ~16 s; ci_tier1.sh gates the same replay jax-free
def test_committed_artifact_validates_and_replays():
    art = load_artifact(PREDICT)
    assert validate_predict(art, "PREDICT_r11.json") == []
    same, diverged = replay_artifact(PREDICT)
    assert same, f"divergent keys: {diverged}"


@pytest.mark.slow  # double calibration ~33 s; the replay gate pins the
def test_build_artifact_seeded_deterministic():  # same seed discipline
    a = build_artifact(REPO, seed=0)
    b = build_artifact(REPO, seed=0)
    a.pop("created_unix"), b.pop("created_unix")
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_rank_order_transfer_headline():
    """The acceptance pin: held-out n32 tau_b >= 0.6 with top-1
    agreement, and the fit grids agree on top-1 too."""
    val = load_artifact(PREDICT)["validation"]
    n32 = val["n32"]
    assert n32["held_out"] is True
    assert n32["tau_b"] >= 0.6, n32["tau_b"]
    assert n32["top1"]["agree"] is True
    for name in ("n256", "n1024"):
        assert val[name]["top1"]["agree"] is True, name


def test_explain_verdict_taxonomy_on_committed_traces():
    art = load_artifact(PREDICT)
    by_trace = {e["trace"]: e for e in art["explain"]}
    healthy = by_trace["FAULT_healthy.trace.jsonl"]
    deadlink = by_trace["FAULT_deadlink.trace.jsonl"]
    for run in healthy["runs"]:
        for row in run["rounds"]:
            assert row["verdict"] == "bandwidth-bound", row
    for run in deadlink["runs"]:
        # the detour + injected slow rank: every round attributed to
        # the fault's smear envelope, never UNEXPLAINED
        for row in run["rounds"]:
            assert row["verdict"] == "slow-injected", row
        assert run["total"]["verdict"] == "slow-injected"
    for e in art["explain"]:
        for run in e["runs"]:
            for row in run["rounds"] + [run["total"]]:
                assert not row["verdict"].startswith("UNEXPLAINED"), row


def test_validate_predict_catches_self_contradiction():
    art = json.loads(json.dumps(load_artifact(PREDICT)))
    row = art["explain"][0]["runs"][0]["rounds"][0]
    row["verdict"] = "UNEXPLAINED (+0% vs model)"
    row["deviation_rel"] = 0.0
    errs = validate_predict(art, "mut")
    assert any("contradicts" in e for e in errs), errs


def test_validate_predict_rejects_negative_param():
    art = json.loads(json.dumps(load_artifact(PREDICT)))
    art["platforms"]["tpu"]["params"][PARAM_NAMES[1]] = -1.0
    assert validate_predict(art, "mut") != []


def test_validate_compare_committed_artifact():
    blob = json.load(open(COMPARE))
    assert validate_compare(blob, "COMPARE_r11.json") == []
    bad = json.loads(json.dumps(blob))
    bad["result"]["by"] = "banana"
    assert validate_compare(bad, "mut") != []


def test_predict_total_is_sum_of_rounds():
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern

    sched = compile_method(1, AggregatorPattern(
        nprocs=8, cb_nodes=2, data_size=64, comm_size=4))
    params = load_artifact(PREDICT)["platforms"]["tpu"]["params"]
    pred = predict_schedule(sched, params)
    assert pred["total_s"] == pytest.approx(
        pred["rpc_s"] + sum(r["wall_s"] for r in pred["rounds"]))
    assert all(r["wall_s"] > 0 for r in pred["rounds"])


def test_live_floor_from_committed_trace():
    events = [json.loads(l) for l in open(HEALTHY)]
    platforms = load_artifact(PREDICT)["platforms"]
    floor, ntimes = floor_from_trace_events(events, platforms)
    assert floor is not None and floor > 0
    assert ntimes >= 1
    # an artifact missing the trace's platform degrades to None
    assert floor_from_trace_events(events, {}) == (None, 1)


def test_tune_model_prune_records_and_replays(tmp_path):
    """tune --synthetic --model-prune end to end under poisoned jax:
    the prune is recorded in TUNE_*.json, schema-valid, and --replay
    re-derives the split + race to REPRODUCED."""
    import shutil
    shutil.copy(PREDICT, tmp_path / "PREDICT_r11.json")
    env = _poisoned_env(tmp_path)
    common = [sys.executable, "-m", "tpu_aggcomm.cli", "tune",
              "-n", "32", "-d", "2048", "--methods", "1,3",
              "--cb-nodes", "8", "--comm-sizes", "4,999999999",
              "--synthetic", "100,m3*0.5",
              "--tune-root", str(tmp_path)]
    res = subprocess.run(common + ["--model-prune", "1.2"],
                         env=env, capture_output=True, text=True,
                         cwd=str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr
    tunes = [p for p in os.listdir(tmp_path) if p.startswith("TUNE_")]
    assert len(tunes) == 1
    blob = json.load(open(tmp_path / tunes[0]))
    mp = blob["model_prune"]
    assert mp["artifact"] == "PREDICT_r11.json"
    assert mp["margin"] == 1.2
    assert sorted(mp["kept"]) + sorted(mp["pruned"]) and \
        set(mp["kept"]).isdisjoint(mp["pruned"])
    assert blob["race"]["order"] == mp["kept"]
    from tpu_aggcomm.obs.regress import validate_tune
    assert validate_tune(blob, tunes[0]) == []
    rep = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "tune", "--replay",
         str(tmp_path / tunes[0])],
        env=env, capture_output=True, text=True, cwd=str(tmp_path))
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert rep.stdout.count("REPRODUCED") == 2  # race AND prune


def test_tune_model_prune_missing_artifact_degrades(tmp_path):
    """No PREDICT artifact: the prune warns and races the full space —
    a missing model must never block tuning."""
    env = _poisoned_env(tmp_path)
    res = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "tune",
         "-n", "8", "-d", "64", "--methods", "1,3", "--cb-nodes", "2",
         "--comm-sizes", "4", "--synthetic", "50",
         "--tune-root", str(tmp_path), "--model-prune"],
        env=env, capture_output=True, text=True, cwd=str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "racing the full space" in res.stderr
    tunes = [p for p in os.listdir(tmp_path) if p.startswith("TUNE_")]
    blob = json.load(open(tmp_path / tunes[0]))
    assert "model_prune" not in blob


def test_model_prune_margin_below_one_refused(tmp_path):
    env = _poisoned_env(tmp_path)
    res = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "tune",
         "-n", "8", "-d", "64", "--methods", "1", "--cb-nodes", "2",
         "--comm-sizes", "4", "--synthetic", "50",
         "--tune-root", str(tmp_path), "--model-prune", "0.5"],
        env=env, capture_output=True, text=True, cwd=str(tmp_path))
    assert res.returncode != 0
    assert "margin must be >= 1.0" in res.stderr
