"""Workload-profiler tests (obs/workload.py + the serve journal's
phase-boundary stamps — ISSUE 16).

The pins that define the subsystem:

- **Phase stamps attribute float-exact**: every request the server
  journals carries the full admit → queue → batch → cache → dispatch →
  respond boundary set, and a row's ``wall_s`` IS the canonical sum of
  its phase durations (the validate_serve discipline: float-exact by
  identical computation, never tolerance).
- **Artifacts are self-proving**: ``WORKLOAD_r*.json`` validates
  (``obs.regress.validate_workload``), replays REPRODUCED from the
  journal named inside it, and every corruption — a mutated wall, a
  contradicted aggregate, a bogus status — is named, not absorbed.
- **Seeded determinism**: same journal + seed ⟹ byte-identical profile
  and byte-identical re-injection plan (the tune/regress seed
  discipline).
- **Crash honesty**: a SIGKILL-torn journal tail is skipped line-wise,
  and an admitted request with no terminal record is named ``lost`` —
  the serve/recover.py semantics, never silent.
- **Monotone or named**: reordered phase stamps are refused by NAME
  (rid + the offending boundaries) by the profiler AND by
  ``serve/recover.replay_journal`` — one attribution arithmetic.
- **jax-free**: obs/workload.py and ``cli inspect workload`` run where
  ``import jax`` raises (poisoned-jax subprocess, the obs discipline —
  profiling a journal must work exactly where a wedged tunnel hangs).
"""

import json
import subprocess
import sys
import threading
from types import SimpleNamespace

import pytest

import _jaxfree

REPO = _jaxfree.REPO

from tpu_aggcomm.obs.regress import validate_workload
from tpu_aggcomm.obs.workload import (BOUNDARIES, attribute_phases,
                                      batch_fill_ratio, padded_slots,
                                      profile_journal, replay_workload,
                                      workload_scenario, write_workload)
from tpu_aggcomm.resilience.journal import RunJournal
from tpu_aggcomm.serve.protocol import ServeClient
from tpu_aggcomm.serve.server import ScheduleServer

_SHAPE = {"method": 3, "nprocs": 8, "cb_nodes": 2, "comm_size": 2,
          "data_size": 64}


@pytest.fixture
def fake_executor(monkeypatch):
    """The real serve/executor with instant fakes — the journal's phase
    stamps come from the control plane, which is what's under test."""
    from tpu_aggcomm.serve import executor

    def fake_build(schedule, backend_name):
        return object(), 1e-3

    def fake_exec(chain, reqs):
        return [{"verified": True if r.verify else None, "error": None}
                for r in reqs]

    monkeypatch.setattr(executor, "build_chain", fake_build)
    monkeypatch.setattr(executor, "execute_batch", fake_exec)


# ---------------------------------------------------------------------------
# The server side: journal records carry the full boundary set.


def test_server_journal_phases_attribute_float_exact(fake_executor,
                                                     tmp_path):
    jpath = tmp_path / "serve.journal.jsonl"
    srv = ScheduleServer(port=0, max_batch=2, batch_window_s=0.01,
                         journal_path=str(jpath))
    srv.start()
    try:
        results = []

        def fire(i):
            with ServeClient(srv.port, timeout=120.0) as c:
                results.append(c.run(**dict(_SHAPE, iter=i)))

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert len(results) == 6 and all(r["ok"] for r in results)
    finally:
        srv.stop()
        srv.close()

    profile = profile_journal([str(jpath)])
    assert profile["problems"] == []
    req = profile["requests"]
    assert req["admitted"] == 6 and req["completed"] == 6
    assert req["lost"] == []
    for row in profile["per_request"]:
        assert row["status"] == "done"
        # every completed request traversed every boundary...
        assert set(row["phases"]) == set(BOUNDARIES[1:])
        assert all(d >= 0 for d in row["phases"].values())
        # ...and wall_s IS the canonical sum — identical expression,
        # so == on floats is the test
        assert row["wall_s"] == sum(
            row["phases"][b] for b in BOUNDARIES if b in row["phases"])
        assert isinstance(row["queue_depth"], int)
        assert row["batch"] is not None and row["batch"]["n"] >= 1
    # batch accounting closes: the per-batch rows partition the requests
    b = profile["batching"]
    assert b["requests_batched"] == 6
    assert b["padded_slots"] == sum(e["padded"] for e in b["per_batch"])
    assert b["fill_ratio"] == batch_fill_ratio(6, b["padded_slots"])


def test_padded_slots_mirrors_executor():
    # jax_sim pads multi-request batches to the next power of two;
    # singletons and pallas_fused execute unpadded (serve/executor.py)
    assert [padded_slots(n, "jax_sim") for n in (1, 2, 3, 5, 8)] \
        == [1, 2, 4, 8, 8]
    assert padded_slots(5, "pallas_fused") == 5
    assert batch_fill_ratio(0, 0) is None
    assert batch_fill_ratio(3, 4) == 0.75


# ---------------------------------------------------------------------------
# Synthetic journals: deterministic stamps for artifact-level pins.


def _write_journal(path, rows, *, torn_tail=False, lost_rid=None):
    j = RunJournal(str(path))
    fp = j.begin_session({"jax": "0.0-test"})
    t0 = 1_700_000_000.0
    for i, stamps in enumerate(rows):
        j.record({"request": i}, fingerprint=fp, status="admitted",
                 shape=dict(_SHAPE), backend="jax_sim", iter=i,
                 t_unix=t0 + 0.05 * i, queue_depth=i % 3)
        j.record({"request": i}, fingerprint=fp, status="done",
                 latency_s=stamps.get("respond"), batch_n=1, cache="hit",
                 phases=dict(stamps), batch_seq=i, batch_padded=1,
                 queue_depth=None)
    if lost_rid is not None:
        j.record({"request": lost_rid}, fingerprint=fp,
                 status="admitted", shape=dict(_SHAPE),
                 backend="jax_sim", t_unix=t0 + 99.0, queue_depth=0)
    if torn_tail:
        with open(path, "a") as fh:
            fh.write('{"key": {"request": 500}, "status": "don')
    return path


def _stamps(scale=1.0):
    return {"admit": 0.0, "queue": 0.001 * scale, "batch": 0.002 * scale,
            "cache": 0.0021 * scale, "dispatch": 0.004 * scale,
            "respond": 0.0042 * scale}


def test_artifact_validates_replays_and_names_corruption(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [_stamps(1 + 0.3 * i) for i in range(9)])
    profile = profile_journal([str(jpath)])
    assert profile["problems"] == []
    art = tmp_path / "WORKLOAD_r07.json"
    blob = write_workload(str(art), profile)
    assert validate_workload(blob) == []
    rep = replay_workload(str(art))
    assert rep["verdict"] == "REPRODUCED", rep["problems"]

    # corruption probes: every self-contradiction must be NAMED
    def probe(mutate, want):
        bad = json.loads(json.dumps(blob))
        mutate(bad)
        errs = validate_workload(bad)
        assert errs and any(want in e for e in errs), (want, errs)

    probe(lambda b: b["per_request"][0].__setitem__("wall_s", 1.0),
          "wall_s")
    probe(lambda b: b["per_request"][0].__setitem__("status", "bogus"),
          "status")
    probe(lambda b: b["batching"].__setitem__("fill_ratio", 0.5),
          "batching")
    probe(lambda b: b.__setitem__("problems", ["oops"]),
          "must not be committed")
    # ...and a doctored artifact must fail --replay with the key named
    doctored = json.loads(json.dumps(blob))
    doctored["arrivals"]["rps"] = 1e9
    with open(tmp_path / "WORKLOAD_r08.json", "w") as fh:
        json.dump(doctored, fh)
    rep = replay_workload(str(tmp_path / "WORKLOAD_r08.json"))
    assert rep["verdict"] == "MISMATCH"
    assert any("arrivals" in p for p in rep["problems"])


def test_seeded_determinism_profile_and_scenario(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [_stamps(1 + 0.5 * i) for i in range(8)])
    a = profile_journal([str(jpath)], seed=3)
    b = profile_journal([str(jpath)], seed=3)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    blob = write_workload(str(tmp_path / "WORKLOAD_r01.json"), a)
    # the re-injection plan is a pure function of (artifact, seed)
    p1 = workload_scenario(blob, seed=5, requests=12)
    p2 = workload_scenario(blob, seed=5, requests=12)
    assert json.dumps(p1) == json.dumps(p2)
    assert len(p1) == 12 and p1[0]["at_s"] == 0.0
    assert all(x["at_s"] <= y["at_s"] for x, y in zip(p1, p1[1:]))
    # default request count = the artifact's admitted count
    assert len(workload_scenario(blob)) == 8


def test_torn_tail_skipped_and_lost_named(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [_stamps(), _stamps(2.0)],
                           torn_tail=True, lost_rid=99)
    profile = profile_journal([str(jpath)])
    # the torn line vanished (line-granular crash safety), the admitted-
    # but-never-finished request is named lost — never silently dropped
    req = profile["requests"]
    assert req["admitted"] == 3 and req["completed"] == 2
    assert req["lost"] == [99]
    lost_row = [r for r in profile["per_request"] if r["rid"] == 99][0]
    assert lost_row["status"] == "lost" and lost_row["phases"] == {}


def test_non_monotone_phases_named_by_profiler_and_recover(tmp_path):
    bad = {"admit": 0.0, "queue": 0.05, "cache": 0.02, "respond": 0.06}
    phases, problems = attribute_phases(bad)
    assert any("monotone" in p for p in problems)
    # the recorded prefix still attributes (honest partial accounting)
    assert phases["queue"] == 0.05
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [_stamps(), bad])
    profile = profile_journal([str(jpath)])
    assert any("request 1" in p and "monotone" in p
               for p in profile["problems"])
    # serve/recover runs the SAME arithmetic and refuses by name too
    from tpu_aggcomm.serve.recover import replay_journal
    rep = replay_journal(str(jpath))
    assert rep["verdict"] == "MISMATCH"
    assert any("request 1" in p and "monotone" in p
               for p in rep["problems"])


# ---------------------------------------------------------------------------
# The loadgen plan (scripts/serve_loadgen.py): pure and seeded.


def _loadgen():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "serve_loadgen", f"{REPO}/scripts/serve_loadgen.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _args(**over):
    base = dict(workload=None, seed=None, requests=None, rate=None,
                burst=8, gap_ms=30.0,
                shapes=["m3 n8 a2 c4 d64", "m11 n8 a2 c8 d64"])
    base.update(over)
    return SimpleNamespace(**base)


def test_loadgen_plan_seeded_and_reinjects_workload(tmp_path):
    lg = _loadgen()
    # seeded normal mode: byte-identical plans, jittered arrivals
    p1 = lg.build_plan(_args(seed=7, requests=16))
    p2 = lg.build_plan(_args(seed=7, requests=16))
    assert json.dumps(p1) == json.dumps(p2)
    assert len(p1) == 16
    # unseeded mode cycles shapes deterministically with no jitter
    p0 = lg.build_plan(_args(requests=16))
    assert [it["at_s"] for it in p0] == \
        [(i // 8) * 0.03 for i in range(16)]
    # --workload mode IS workload_scenario — same artifact + seed in,
    # byte-identical sequence out
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [_stamps(1 + i) for i in range(8)])
    blob = write_workload(str(tmp_path / "WORKLOAD_r01.json"),
                          profile_journal([str(jpath)]))
    plan = lg.build_plan(_args(workload=str(tmp_path / "WORKLOAD_r01.json"),
                               seed=None, requests=6))
    assert json.dumps(plan) == json.dumps(
        workload_scenario(blob, requests=6))
    wrong = tmp_path / "not_a_workload.json"
    wrong.write_text(json.dumps({"schema": "serve-v1"}))
    with pytest.raises(SystemExit, match="workload-v1"):
        lg.build_plan(_args(workload=str(wrong)))


def test_shape_spec_roundtrips_parse_shape():
    lg = _loadgen()
    for spec in ("m3 n8 a2 c4 d64", "m11 n8 a2 c8 d64 p1"):
        assert lg.shape_spec(lg.parse_shape(spec)) == spec


# ---------------------------------------------------------------------------
# Discovery + the jax-free pins.


def test_history_discovers_workload_series(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [_stamps(1 + i) for i in range(8)])
    write_workload(str(tmp_path / "WORKLOAD_r02.json"),
                   profile_journal([str(jpath)]))
    from tpu_aggcomm.obs.history import build_index, workload_series
    series = workload_series(str(tmp_path))
    pts = series["workload padding waste"]
    assert len(pts) == 1 and pts[0]["round"] == 2
    assert pts[0]["unit"] == "B" and pts[0]["samples_n"] == 8
    idx = build_index(str(tmp_path))
    assert [w["file"] for w in idx["workload"]] == ["WORKLOAD_r02.json"]
    assert "workload padding waste" in idx["workload_series"]
    from tpu_aggcomm.obs.history import check_trends
    assert "workload padding waste" in check_trends(str(tmp_path))["series"]


def test_workload_profiler_is_jaxfree(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [_stamps(1 + i) for i in range(8)])
    code = (
        _jaxfree.pure_import_code("tpu_aggcomm.obs.workload") +
        "; from tpu_aggcomm.obs.workload import profile_journal, "
        "write_workload, replay_workload"
        f"; p = profile_journal([{str(jpath)!r}])"
        "; assert p['problems'] == [] and p['requests']['admitted'] == 8"
        f"; write_workload({str(tmp_path / 'WORKLOAD_r01.json')!r}, p)"
        f"; r = replay_workload({str(tmp_path / 'WORKLOAD_r01.json')!r})"
        "; assert r['verdict'] == 'REPRODUCED', r['problems']"
        "; import sys; assert 'jax' not in sys.modules")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=str(tmp_path),
        env=_jaxfree.poisoned_env(
            tmp_path, "the workload profiler must run where a wedged "
                      "tunnel hangs import jax"),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_cli_inspect_workload_is_jaxfree(tmp_path):
    jpath = _write_journal(tmp_path / "serve.journal.jsonl",
                           [_stamps(1 + i) for i in range(8)])
    env = _jaxfree.poisoned_env(
        tmp_path, "inspect workload must answer on a wedged tunnel")
    art = tmp_path / "WORKLOAD_r03.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "workload",
         str(jpath), "--seed", "0", "--json", str(art)],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "workload profile over" in proc.stdout
    assert "workload artifact written" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "workload",
         "--replay", str(art)],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "REPRODUCED" in proc.stdout
