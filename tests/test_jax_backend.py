"""JAX/ICI backend on the virtual 8-device CPU mesh: every non-TAM method
delivers byte-exact data, matching the local oracle."""

import numpy as np
import pytest

from tpu_aggcomm.backends.jax_ici import JaxIciBackend, color_rounds
from tpu_aggcomm.backends.local import LocalBackend
from tpu_aggcomm.core.methods import METHODS, compile_method, method_ids
from tpu_aggcomm.core.pattern import AggregatorPattern

NON_TAM = [m for m in method_ids(include_dead=True) if not METHODS[m].tam]


def test_color_rounds_partial_permutations():
    edges = np.array([[0, 1], [0, 2], [1, 2], [3, 1], [2, 2]])
    colors = color_rounds(edges)
    # every color: unique srcs and unique dsts; all edges covered
    assert sum(len(c) for c in colors) == len(edges)
    for c in colors:
        srcs = [s for s, _ in c]
        dsts = [d for _, d in c]
        assert len(set(srcs)) == len(srcs)
        assert len(set(dsts)) == len(dsts)


@pytest.mark.parametrize("method", NON_TAM)
def test_jax_matches_oracle(method):
    p = AggregatorPattern(8, 3, data_size=32, comm_size=3)
    sched = compile_method(method, p)
    recv_j, timers = JaxIciBackend().run(sched, verify=True, iter_=0)
    recv_o, _ = LocalBackend().run(sched, verify=True, iter_=0)
    for a, b in zip(recv_j, recv_o):
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(a, b)
    assert timers[0].total_time > 0


@pytest.mark.parametrize("method,cs", [(1, 1), (2, 2), (3, 8), (5, 3),
                                       (13, 2), (17, 3), (20, 4)])
def test_jax_throttle_sweep(method, cs):
    p = AggregatorPattern(8, 5, data_size=16, comm_size=cs,
                          proc_node=2)
    sched = compile_method(method, p)
    JaxIciBackend().run(sched, verify=True)


def test_jax_profile_rounds():
    p = AggregatorPattern(8, 3, data_size=16, comm_size=2)
    sched = compile_method(1, p)
    recv, timers = JaxIciBackend().run(sched, verify=True, profile_rounds=True)
    assert timers[0].recv_wait_all_time > 0


def test_jax_ntimes():
    p = AggregatorPattern(8, 3, data_size=16, comm_size=3)
    sched = compile_method(2, p)
    _, timers = JaxIciBackend().run(sched, ntimes=3, verify=True)
    assert timers[0].total_time > 0


def test_jax_too_few_devices():
    p = AggregatorPattern(16, 3, data_size=16)
    sched = compile_method(1, p)
    with pytest.raises(ValueError, match="devices"):
        JaxIciBackend().run(sched)


@pytest.mark.parametrize("method", [1, 8, 17])
def test_jax_chained_measurement(method):
    """Serial-chained differenced per-rep measurement on the one-rank-per-
    device tier (the honest mode through a tunneled dispatch path, as on
    jax_sim/jax_shard): throttled rounds (m=1), the dense collective
    (m=8), and in-round psum barriers (m=17) all measure positive,
    attribute onto the phase buckets, and still deliver verified bytes."""
    import numpy as np
    p = AggregatorPattern(8, 3, data_size=16, comm_size=2)
    b = JaxIciBackend()
    sched = compile_method(method, p)
    recv, timers = b.run(sched, verify=True, chained=True, ntimes=2)
    assert timers[0].total_time > 0
    per = b.measure_per_rep(sched)          # cached, no remeasure
    assert np.isclose(timers[0].total_time, per * 2)


def test_jax_chained_rejects_tam_and_profile():
    p = AggregatorPattern(8, 3, data_size=16, comm_size=2, proc_node=2)
    b = JaxIciBackend()
    with pytest.raises(ValueError, match="TAM"):
        b.run(compile_method(15, p), chained=True)
    with pytest.raises(ValueError, match="exclusive"):
        b.run(compile_method(1, p), chained=True, profile_rounds=True)


def test_runner_rejects_chained_run_all_with_tam_upfront():
    """-m 0 --chained on jax_ici must fail BEFORE any method runs (not
    crash at m=15 mid-sweep leaving a partial CSV): its two-level mesh
    engine times whole reps. jax_shard chains TAM through the blocked
    engine since round 5, so its chained run-all covers m=15/16."""
    import io
    from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment
    cfg = ExperimentConfig(nprocs=8, cb_nodes=3, data_size=16,
                           comm_size=2, method=0, backend="jax_ici",
                           chained=True, results_csv=None)
    with pytest.raises(ValueError, match="TAM methods"):
        run_experiment(cfg, out=io.StringIO())
