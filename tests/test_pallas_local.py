"""Fused single-chip exchange kernel: interpret-mode equivalence with the
XLA formulation and with a direct numpy replay (the TPU-compiled path is
exercised by bench.py on real hardware)."""

import numpy as np
import pytest

from tpu_aggcomm.backends.pallas_local import (fused_exchange_chain,
                                               host_replay,
                                               xla_exchange_chain)
from tpu_aggcomm.core.pattern import AggregatorPattern


def _send0(p):
    import jax
    w = p.data_size // 4
    return jax.device_put(
        np.arange(p.nprocs * p.cb_nodes * w, dtype=np.uint32).reshape(
            p.nprocs, p.cb_nodes, w))


@pytest.mark.parametrize("nprocs,cb,iters", [(8, 3, 1), (8, 3, 5),
                                             (32, 14, 4), (6, 6, 3)])
def test_fused_matches_xla(nprocs, cb, iters):
    import jax
    p = AggregatorPattern(nprocs, cb, data_size=256, comm_size=3)
    s0 = _send0(p)
    got = np.asarray(jax.device_get(
        fused_exchange_chain(p, iters, interpret=True)(s0)))
    want = np.asarray(jax.device_get(xla_exchange_chain(p, iters)(s0)))
    np.testing.assert_array_equal(got, want)


def test_fused_matches_numpy_replay():
    import jax
    p = AggregatorPattern(8, 3, data_size=64, comm_size=2)
    s0 = _send0(p)
    ref = host_replay(p, np.asarray(jax.device_get(s0)), 7)
    got = np.asarray(jax.device_get(
        fused_exchange_chain(p, 7, interpret=True)(s0)))
    np.testing.assert_array_equal(got, ref)


def test_rejects_unaligned_data_size():
    p = AggregatorPattern(8, 3, data_size=30)
    with pytest.raises(ValueError, match="multiple of 4"):
        fused_exchange_chain(p, 1, interpret=True)


@pytest.mark.parametrize("entry", ["xla", "replay"])
def test_all_entry_points_reject_unaligned(entry):
    p = AggregatorPattern(8, 3, data_size=30)
    with pytest.raises(ValueError, match="multiple of 4"):
        if entry == "xla":
            xla_exchange_chain(p, 1)
        else:
            host_replay(p, np.zeros((8, 3, 7), np.uint32), 1)
