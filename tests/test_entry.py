"""Driver entry points (__graft_entry__.py) stay importable and jittable —
the artifacts the round driver compile-checks (entry single-chip) must
never regress silently. The full dryrun_multichip is exercised by the
driver itself (and manually: `python __graft_entry__.py 8`); it re-execs
into a scrubbed child, which pytest need not re-run."""

import sys
import os

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_entry_compiles_and_runs():
    from __graft_entry__ import entry
    fn, args = entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    # the flagship rep: 32 ranks, 32 recv slots + trash row, uint32 lanes
    assert out.shape == (32, 33, 512)
    assert str(out.dtype) == "uint32"
