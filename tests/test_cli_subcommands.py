"""CLI `tam` and `sweep` subcommands (DEBUG driver + Theta job scripts)."""

import contextlib
import io

import pytest

from tpu_aggcomm.cli import THETA_COMM_SIZES, main


def run_cli(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    return rc, buf.getvalue()


def test_theta_grid_matches_job_scripts():
    # script_theta_*.sh sweeps powers of two 1..8192 plus "unthrottled"
    assert THETA_COMM_SIZES == (1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                                1024, 2048, 4096, 8192, 999_999_999)


@pytest.mark.parametrize("engine", ["benchmark", "proxy", "local_agg"])
def test_tam_subcommand_engines(engine):
    rc, out = run_cli(["tam", "-n", "12", "-p", "4", "-b", "5", "-t", "3",
                       "-c", "2", "--engine", engine])
    assert rc == 0
    assert "correctness: PASSED" in out
    assert "blocklen = 5, nprocs_node = 4" in out


def test_tam_subcommand_shared_mode1():
    rc, out = run_cli(["tam", "-n", "8", "-p", "4", "-t", "2", "-c", "4",
                       "--mode", "1", "--engine", "shared"])
    assert rc == 0
    assert "correctness: PASSED" in out


def test_tam_subcommand_jax_engine():
    rc, out = run_cli(["tam", "-n", "8", "-p", "4", "-b", "3", "-t", "1",
                       "-c", "2", "--mode", "1", "--engine", "jax", "-k", "2"])
    assert rc == 0
    assert "two-level mesh (compiled)" in out
    assert "correctness: PASSED" in out


def test_sweep_subcommand_accumulates_csv(tmp_path):
    csv = tmp_path / "results.csv"
    rc, out = run_cli(["sweep", "-n", "8", "-a", "2", "-d", "64", "-i", "1",
                       "-m", "1", "--backend", "local", "--verify",
                       "--comm-sizes", "1,2", "--results-csv", str(csv)])
    assert rc == 0
    assert out.count("RUN_OPTS:") == 2
    lines = csv.read_text().strip().splitlines()
    assert len(lines) == 3  # header + one row per grid point
    assert lines[0].startswith("Method,")


def test_analyze_subcommand(tmp_path):
    csv = tmp_path / "results.csv"
    run_cli(["sweep", "-n", "8", "-a", "2", "-d", "64", "-i", "1", "-m", "1",
             "--backend", "local", "--comm-sizes", "1,4",
             "--results-csv", str(csv)])
    rc, out = run_cli(["analyze", "--results-csv", str(csv)])
    assert rc == 0
    assert "config: procs=8 aggregators=2 data_size=64" in out
    assert "winner: All to many" in out


def test_sweep_measured_phases_rows_and_resume(tmp_path):
    """sweep --measured-phases: cells emit measured-rounds rows, the
    resume sidecar distinguishes a measured sweep from a chained one
    (same grid must NOT be skipped), and re-resume of the measured sweep
    itself skips."""
    csv = tmp_path / "results.csv"
    base = ["sweep", "-n", "8", "-m", "1", "-a", "2", "-d", "64", "-i", "1",
            "--backend", "jax_sim", "--results-csv", str(csv),
            "--comm-sizes", "4"]
    run_cli(base + ["--measured-phases"])
    from tpu_aggcomm.harness.report import provenance_path
    with open(provenance_path(str(csv))) as fh:
        # the 2-round cell is unrolled: the full 2-D measurement applies
        assert "measured-rounds(post,deliver)+attributed(waits)" in fh.read()
    rc, out = run_cli(base + ["--measured-phases", "--resume"])
    assert "resume: skipping already-recorded comm sizes [4]" in out
    # a CHAINED sweep over the same grid is a different experiment
    rc, out = run_cli(base + ["--chained", "--resume"])
    assert "skipping" not in out


def test_analyze_shows_provenance_tags(tmp_path):
    """The winner table annotates each best row with its sidecar
    provenance — a measured row and an attributed row must not read as
    equals."""
    csv = tmp_path / "results.csv"
    run_cli(["-n", "8", "-m", "1", "-a", "2", "-d", "64", "-c", "2",
             "--backend", "local", "--verify",
             "--results-csv", str(csv)])
    rc, out = run_cli(["analyze", "--results-csv", str(csv)])
    assert rc == 0
    assert "[local, total-only]" in out


def test_analyze_missing_file(tmp_path):
    with pytest.raises(SystemExit):
        run_cli(["analyze", "--results-csv", str(tmp_path / "nope.csv")])


def test_analyze_rejects_wrong_schema(tmp_path):
    bad = tmp_path / "bad.csv"
    bad.write_text("foo,bar\n1,2\n")
    with pytest.raises(SystemExit, match="no parseable"):
        run_cli(["analyze", "--results-csv", str(bad)])


def test_analyze_skips_truncated_row(tmp_path):
    csv = tmp_path / "results.csv"
    run_cli(["sweep", "-n", "8", "-a", "2", "-d", "64", "-i", "1", "-m", "1",
             "--backend", "local", "--comm-sizes", "1",
             "--results-csv", str(csv)])
    with open(csv, "a") as f:
        f.write("All to many,8,2,64,4\n")  # killed-mid-append remnant
    rc, out = run_cli(["analyze", "--results-csv", str(csv)])
    assert rc == 0 and "winner: All to many" in out


def test_sweep_resume_skips_recorded(tmp_path):
    csv = tmp_path / "results.csv"
    base = ["sweep", "-n", "8", "-m", "1", "-a", "3", "-d", "32", "-i", "2",
            "--backend", "local", "--results-csv", str(csv)]
    run_cli(base + ["--comm-sizes", "2,4"])
    rc, out = run_cli(base + ["--comm-sizes", "2,4,8", "--resume"])
    assert rc == 0
    assert "skipping already-recorded comm sizes [2, 4]" in out
    assert "RUN_OPTS: -a 3 -d 32 -c 8" in out
    assert "RUN_OPTS: -a 3 -d 32 -c 2" not in out


def test_sweep_resume_partial_iters_reruns(tmp_path):
    csv = tmp_path / "results.csv"
    base = ["sweep", "-n", "8", "-m", "1", "-a", "3", "-d", "32",
            "--backend", "local", "--results-csv", str(csv)]
    run_cli(base + ["-i", "1", "--comm-sizes", "2"])
    # asking for more iters than recorded: the config is NOT complete
    rc, out = run_cli(base + ["-i", "2", "--comm-sizes", "2", "--resume"])
    assert rc == 0
    assert "skipping" not in out
    assert "RUN_OPTS: -a 3 -d 32 -c 2" in out


def test_sweep_resume_respects_ntimes_and_placement(tmp_path):
    csv = tmp_path / "results.csv"
    base = ["sweep", "-n", "8", "-m", "1", "-a", "3", "-d", "32", "-i", "1",
            "--backend", "local", "--results-csv", str(csv)]
    run_cli(base + ["--comm-sizes", "2", "-k", "1", "-t", "1"])
    # different -k: not complete, must rerun
    rc, out = run_cli(base + ["--comm-sizes", "2", "-k", "5", "-t", "1",
                              "--resume"])
    assert "skipping" not in out
    # different -t: not complete, must rerun
    rc, out = run_cli(base + ["--comm-sizes", "2", "-k", "1", "-t", "0",
                              "--resume"])
    assert "skipping" not in out
    # identical parameters: skipped
    rc, out = run_cli(base + ["--comm-sizes", "2", "-k", "1", "-t", "1",
                              "--resume"])
    assert "skipping already-recorded comm sizes [2]" in out


def test_sweep_resume_rejects_unknown_method(tmp_path):
    csv = tmp_path / "results.csv"
    with pytest.raises(SystemExit, match="unknown method id 99"):
        run_cli(["sweep", "-n", "8", "-m", "99", "-a", "3", "-d", "32",
                 "--backend", "local", "--results-csv", str(csv),
                 "--comm-sizes", "2", "--resume"])


def test_inspect_round_structured():
    rc, out = run_cli(["inspect", "-m", "1", "-n", "32", "-a", "14",
                       "-c", "3"])
    assert rc == 0
    assert "448 messages over 11 rounds" in out
    assert "round   0:    42 msgs" in out


def test_inspect_roofline_and_waves():
    rc, out = run_cli(["inspect", "-m", "1", "-n", "8", "-a", "3",
                       "-c", "2", "--roofline", "--waves"])
    assert rc == 0
    assert "roofline (floors at 819 GB/s HBM):" in out
    assert "jax_sim(ndev=1):" in out and "us/rep" in out
    assert "pallas_dma lockstep" in out
    assert "max in-flight = 1" in out          # lockstep law
    assert "pallas_dma concurrent" in out
    # roofline also covers the dense collective
    rc, out = run_cli(["inspect", "-m", "8", "-n", "8", "-a", "3",
                       "--roofline"])
    assert rc == 0 and "roofline" in out and "1 rounds" in out


def test_inspect_dense_and_tam_and_barriers():
    rc, out = run_cli(["inspect", "-m", "8", "-n", "8", "-a", "3"])
    assert "dense vendor collective" in out and "24 messages" in out
    rc, out = run_cli(["inspect", "-m", "15", "-n", "8", "-a", "3",
                       "-p", "2"])
    assert "hierarchical engine over 4 nodes" in out
    assert "inter_exchange" in out
    rc, out = run_cli(["inspect", "-m", "17", "-n", "8", "-a", "3",
                       "-c", "2"])
    assert "1 barrier(s)" in out


def test_sweep_resume_distinguishes_proc_node(tmp_path):
    """ADVICE r1: rows from a sweep with a different -p (or backend) must
    not satisfy --resume. The reference CSV cannot record proc_node, so
    completion is tracked in the sweep sidecar."""
    csv = tmp_path / "results.csv"
    base = ["sweep", "-n", "8", "-m", "1", "-a", "3", "-d", "32", "-i", "1",
            "--backend", "local", "--results-csv", str(csv)]
    run_cli(base + ["--comm-sizes", "2", "-p", "1"])
    assert (tmp_path / "results.csv.sweep.jsonl").exists()
    # different -p: same CSV rows, but NOT complete for this config
    rc, out = run_cli(base + ["--comm-sizes", "2", "-p", "2", "--resume"])
    assert rc == 0 and "skipping" not in out
    # identical -p: skipped
    rc, out = run_cli(base + ["--comm-sizes", "2", "-p", "1", "--resume"])
    assert "skipping already-recorded comm sizes [2]" in out


def test_sweep_resume_pre_sidecar_fallback(tmp_path):
    """CSV-only heuristic still works for sweeps recorded before the
    sidecar existed — even when a DIFFERENT config has since written
    sidecar lines into the same results.csv."""
    import os
    csv = tmp_path / "results.csv"
    base = ["sweep", "-n", "8", "-m", "1", "-a", "3", "-d", "32", "-i", "1",
            "--backend", "local", "--results-csv", str(csv)]
    run_cli(base + ["--comm-sizes", "2"])
    os.remove(str(csv) + ".sweep.jsonl")   # simulate a pre-sidecar sweep
    rc, out = run_cli(base + ["--comm-sizes", "2,4", "--resume"])
    assert rc == 0
    assert "skipping already-recorded comm sizes [2]" in out
    # another config (-a 2) writes the sidecar; config A's pre-sidecar
    # completions must still be honored through the CSV fallback
    os.remove(str(csv) + ".sweep.jsonl")
    run_cli(["sweep", "-n", "8", "-m", "1", "-a", "2", "-d", "32", "-i", "1",
             "--backend", "local", "--results-csv", str(csv),
             "--comm-sizes", "2"])
    rc, out = run_cli(base + ["--comm-sizes", "2,4", "--resume"])
    assert rc == 0
    assert "skipping already-recorded comm sizes [2, 4]" in out


def test_tam_banner_golden():
    """The tam banner's first line is byte-identical to the reference
    DEBUG driver's rank-0 printf (lustre_driver_test.c:1454)."""
    rc, out = run_cli(["tam", "-n", "8", "-p", "4", "-b", "16", "-t", "0",
                       "-c", "1", "-r", "0", "--engine", "benchmark"])
    assert rc == 0
    assert out.splitlines()[0] == \
        "blocklen = 16, nprocs_node = 4, rank_assignment = 0, type = 0, co = 1"
    # --reorder keeps the reference banner as the first line
    rc, out = run_cli(["tam", "-n", "8", "-p", "4", "-b", "16", "-t", "3",
                       "-c", "1", "--reorder", "--engine", "benchmark"])
    assert rc == 0
    assert out.splitlines()[0] == \
        "blocklen = 16, nprocs_node = 4, rank_assignment = 0, type = 3, co = 1"


@pytest.mark.parametrize("engine", ["proxy", "local_agg", "benchmark",
                                    "jax", "sim"])
def test_tam_reorder_flag(engine):
    """--reorder applies reorder_ranklist (the reference driver's
    commented-out flow, l_d_t.c:1495-1499) before the engine: the
    destination list is dealt round-robin across nodes and every engine
    still delivers byte-exact with the unsorted order."""
    rc, out = run_cli(["tam", "-n", "8", "-p", "4", "-b", "5", "-t", "3",
                       "-c", "2", "--reorder", "--engine", engine])
    assert rc == 0
    assert "correctness: PASSED" in out
    # ALL workload on 2 nodes of 4: round-robin deal alternates nodes
    assert "reordered aggregators = 0, 4, 1, 5, 2, 6, 3, 7" in out


def test_tam_reorder_interleaves_nodes():
    from tpu_aggcomm.core.pattern import reorder_ranklist
    from tpu_aggcomm.core.topology import static_node_assignment
    import numpy as np
    na = static_node_assignment(8, 4, 0)
    out = reorder_ranklist(na.node_of, np.array([0, 1, 2, 4]), na.nnodes)
    # consecutive entries land on distinct nodes while both have supply
    assert list(out) == [0, 4, 1, 2]


def test_inspect_ndev_block_view():
    rc, out = run_cli(["inspect", "-n", "16", "-m", "1", "-a", "5", "-d",
                       "64", "-c", "4", "--ndev", "8"])
    assert rc == 0
    assert "jax_shard over 8 devices (2 ranks/device)" in out
    assert "block M =" in out and "padding x" in out


def test_sweep_jax_shard_chained(tmp_path):
    """The Theta-grid sweep drives the sharded flagship tier with chained
    differenced timing — the exact command shape a pod run uses."""
    csv = tmp_path / "results.csv"
    rc, out = run_cli(["sweep", "-n", "16", "-a", "4", "-d", "32", "-i", "1",
                       "-m", "1", "--backend", "jax_shard", "--chained",
                       "--verify", "--comm-sizes", "2,8",
                       "--results-csv", str(csv)])
    assert rc == 0
    rows = csv.read_text().strip().splitlines()
    assert len(rows) == 3
    # phase columns are attributed (non-zero), not zeros
    post = float(rows[1].split(",")[7])
    assert post > 0
