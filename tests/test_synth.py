"""Schedule synthesizer tests (tpu_aggcomm/synth/, ISSUE 15).

The contract under test, layer by layer:

- compositions are a canonical, parseable identity (two spellings can
  never alias) and every named validation error stays named;
- ``build_schedule`` emits ordinary Schedule IR: every PROVEN
  composition passes ``--verify`` byte-exact on the local oracle (and
  the registered winner on jax_sim + pallas_fused interpret), while the
  deliberately cyclic ``sync=crossed`` compositions are REFUTED by the
  model checker AND deadlock the oracle — checker<->oracle agreement,
  the analysis-suite discipline;
- the seeded search replays byte-for-byte (same config + seed + params
  in, same trace out) and its prune bookkeeping is self-consistent;
- registration is opt-in, idempotent, and conflict-refusing by name;
- the CLI round trip (``synth --synthetic`` -> validate_synth ->
  ``synth --replay`` REPRODUCED, tamper -> MISMATCH) runs end to end
  where ``import jax`` raises — the whole pipeline is jax-free.
"""

import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_aggcomm.backends.local import DeadlockError, run_schedule_local
from tpu_aggcomm.core.methods import METHODS, compile_method
from tpu_aggcomm.core.pattern import AggregatorPattern, Direction
from tpu_aggcomm.core.schedule import schedule_shape_key
from tpu_aggcomm.synth import (SYNTH_ID_BASE, Composition, CompositionError,
                               RegisterError, build_schedule,
                               enumerate_space, parse_composition,
                               register_composition, registered_synth_ids)
from tpu_aggcomm.synth.search import (UNREGISTERED_ID, evaluate_composition,
                                      search)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED_SYNTH = os.path.join(REPO, "SYNTH_r01.json")


@pytest.fixture(autouse=True)
def _registry_guard():
    """Registration mutates the global METHODS table; every test leaves
    it exactly as found (the opt-in contract extends to the suite)."""
    before = set(METHODS)
    yield
    for mid in set(METHODS) - before:
        del METHODS[mid]


def _pattern(**kw):
    kw.setdefault("data_size", 64)
    kw.setdefault("comm_size", 3)
    return AggregatorPattern(kw.pop("nprocs", 8), kw.pop("cb_nodes", 3),
                             **kw)


# ---------------------------------------------------------------------------
# compositions: canonical identity + named validation


class TestComposition:
    def test_canonical_roundtrip(self):
        for comp in enumerate_space():
            assert parse_composition(comp.canonical()) == comp

    def test_spellings_cannot_alias(self):
        # reordered, padded, defaulted — one canonical form
        a = parse_composition("sync=eager|order=strided")
        b = parse_composition(" order=strided |sync=eager|relay=0")
        assert a == b
        assert a.canonical() == b.canonical()

    def test_defaults_are_the_reference_shape(self):
        c = parse_composition("")
        assert (c.order, c.sync, c.selfedge, c.wait, c.window) == \
            ("rotated", "rendezvous", "wire", "round", "chunk")

    @pytest.mark.parametrize("text,needle", [
        ("order=spiral", "order="),
        ("sync=psync", "sync="),
        ("self=ptr", "self="),
        ("wait=never", "wait="),
        ("window=sliding", "window="),
        ("flavor=mild", "unknown composition key"),
        ("fanin=two|order=tree", "not an integer"),
        ("order=tree", "fanin >= 2"),
        ("fanin=2", "only composes with order=tree"),
        ("sync=crossed|wait=tail", "wait=round"),
        ("relay=-1", "must be >= 0"),
        ("orderstrided", "not key=value"),
        ("window=posted|wait=tail", "wait=round"),
        ("window=posted|order=tree|fanin=2", "chunk width"),
        ("window=posted|relay=1", "window=chunk"),
        ("window=drain|wait=tail", "wait=round"),
        ("window=drain|order=tree|fanin=2", "cannot collapse"),
        ("window=drain|relay=1", "window=chunk"),
    ])
    def test_errors_are_named(self, text, needle):
        with pytest.raises(CompositionError) as ei:
            parse_composition(text)
        assert needle in str(ei.value)

    def test_enumerate_space_is_sorted_and_valid(self):
        space = enumerate_space(fanins=(2, 3), relays=(0, 1))
        canons = [c.canonical() for c in space]
        assert canons == sorted(canons)
        assert len(canons) == len(set(canons))
        # crossed+tail and fanin-without-tree never enumerate, and the
        # window axis only opens where its constraints allow
        for c in space:
            assert not (c.sync == "crossed" and c.wait == "tail")
            assert (c.fanin >= 2) == (c.order == "tree")
            if c.window != "chunk":
                assert (c.wait, c.relay) == ("round", 0)
                assert c.order != "tree"


# ---------------------------------------------------------------------------
# build_schedule: ordinary IR, oracle-verified


PROVEN_COMPS = [
    "order=rotated|sync=rendezvous|self=wire|wait=round",
    "order=rotated|sync=eager|self=copy|wait=round",
    "order=strided|sync=eager|self=copy|wait=tail",
    "order=blocked|sync=rendezvous|self=wire|wait=tail",
    "order=tree|fanin=2|sync=rendezvous|self=wire|wait=round",
    "order=tree|fanin=4|sync=eager|self=copy|wait=round",
    "order=rotated|sync=rendezvous|self=wire|wait=round|relay=2",
    "order=rotated|sync=eager|self=copy|wait=round|window=posted",
    "order=blocked|sync=rendezvous|self=wire|wait=round|window=posted",
    "order=rotated|sync=eager|self=copy|wait=round|window=drain",
    "order=blocked|sync=rendezvous|self=wire|wait=round|window=drain",
]


class TestBuildSchedule:
    @pytest.mark.parametrize("text", PROVEN_COMPS)
    def test_local_verify_byte_exact(self, text):
        comp = parse_composition(text)
        sched = build_schedule(comp, _pattern())
        run_schedule_local(sched, verify=True)

    def test_m2a_mirror_verifies(self):
        comp = parse_composition("order=rotated|sync=eager|self=copy")
        sched = build_schedule(
            comp, _pattern(direction=Direction.MANY_TO_ALL))
        assert sched.pattern.direction is Direction.MANY_TO_ALL
        run_schedule_local(sched, verify=True)

    def test_relay_is_the_repair_detour_ir(self):
        comp = parse_composition("relay=2")
        sched = build_schedule(comp, _pattern())
        # 2 ring-predecessor sources per aggregator, one staging row each
        assert sched.n_staging == 2 * sched.pattern.cb_nodes
        assert len(sched.dead_edges) == 2 * sched.pattern.cb_nodes
        ops = [op for prog in sched.programs for op in prog]
        assert any(op.to_stage for op in ops)
        assert any(op.from_stage for op in ops)
        assert any(op.chan > 0 for op in ops)
        run_schedule_local(sched, verify=True)

    def test_relay_refuses_m2a_by_name(self):
        comp = parse_composition("relay=1")
        with pytest.raises(CompositionError, match="all-to-many"):
            build_schedule(comp,
                           _pattern(direction=Direction.MANY_TO_ALL))

    def test_relay_refuses_tiny_patterns_by_name(self):
        with pytest.raises(CompositionError, match="relay\\+2 ranks"):
            build_schedule(parse_composition("relay=7"), _pattern())

    def test_posted_resizes_rounds_to_the_budget(self):
        """window=posted must find strictly fewer rounds than the
        conservative chunker at this shape, while the in-flight audit
        still CONFORMS — the whole point of budgeting against the
        documented min(c,n)+cb bound instead of the chunk width."""
        chunk = build_schedule(
            parse_composition("order=rotated|sync=eager|self=copy"),
            _pattern())
        posted = build_schedule(
            parse_composition(
                "order=rotated|sync=eager|self=copy|window=posted"),
            _pattern())
        r_chunk = int(chunk.data_edges()[:, 4].max()) + 1
        r_posted = int(posted.data_edges()[:, 4].max()) + 1
        assert r_posted < r_chunk
        row = evaluate_composition(
            parse_composition(
                "order=rotated|sync=eager|self=copy|window=posted"),
            _pattern())
        assert row["verdict"] == "PROVEN"
        assert row["peak"] <= row["bound"]
        run_schedule_local(posted, verify=True)

    def test_drain_is_one_data_round(self):
        """window=drain collapses the schedule to a single data round:
        every send posted up front, the incast drained by blocking
        receives that post nothing against the -c bound (the
        m=6/10/12 conformance precedent, taken to its fixed point)."""
        sched = build_schedule(
            parse_composition(
                "order=rotated|sync=eager|self=copy|window=drain"),
            _pattern())
        assert int(sched.data_edges()[:, 4].max()) == 0
        row = evaluate_composition(
            parse_composition(
                "order=rotated|sync=eager|self=copy|window=drain"),
            _pattern())
        assert row["verdict"] == "PROVEN"
        assert row["rounds"] == 1
        assert row["peak"] <= row["bound"]
        run_schedule_local(sched, verify=True)

    def test_variant_isolates_shape_keys_before_registration(self):
        # two compositions sharing the placeholder id must never alias a
        # shape-keyed cache entry: the canonical string rides variant
        p = _pattern()
        a = build_schedule(parse_composition(PROVEN_COMPS[0]), p,
                           method_id=UNREGISTERED_ID)
        b = build_schedule(parse_composition(PROVEN_COMPS[1]), p,
                           method_id=UNREGISTERED_ID)
        assert a.variant.startswith("synth:")
        assert schedule_shape_key(a) != schedule_shape_key(b)


# ---------------------------------------------------------------------------
# checker <-> oracle agreement (the hard-pruning contract)


class TestCheckerAgreement:
    @pytest.mark.parametrize("text", [
        "sync=crossed|order=strided",
        # crossed+drain waits the rendezvous sends BEFORE the blocking
        # drain posts any matching receive — the same cycle, one window
        # deeper
        "sync=crossed|order=rotated|self=copy|window=drain",
    ])
    def test_crossed_refuted_and_oracle_deadlocks(self, text):
        """The deliberately cyclic sync=crossed shapes: the checker must
        REFUTE them by name AND the local oracle must deadlock on the
        very same schedule — a static verdict the runtime contradicts
        would make the search's hard pruning meaningless."""
        comp = parse_composition(text)
        row = evaluate_composition(comp, _pattern())
        assert row["verdict"] == "REFUTED"
        assert row["pruned_by"].startswith("check:deadlock_freedom")
        assert row["check_detail"]  # the waits-for cycle, named
        with pytest.raises(DeadlockError):
            run_schedule_local(build_schedule(comp, _pattern()))

    def test_proven_row_carries_static_features(self):
        row = evaluate_composition(
            parse_composition(PROVEN_COMPS[0]), _pattern())
        assert row["verdict"] == "PROVEN" and row["pruned_by"] is None
        assert row["rounds"] > 0 and row["bytes"] > 0
        assert row["peak"] <= row["bound"]
        assert row["price_s"] is None          # no params passed

    def test_pricing_orders_but_never_gates(self):
        params = {"rpc_s": 1e-4, "fence_s": 1e-5, "bytes_s_per_kb": 1e-6,
                  "bottleneck_s_per_kb": 1e-6, "spill_s_per_kb": 0.0}
        row = evaluate_composition(
            parse_composition(PROVEN_COMPS[0]), _pattern(), params)
        assert row["verdict"] == "PROVEN"
        assert row["price_s"] > 0


# ---------------------------------------------------------------------------
# seeded search


class TestSearch:
    def _cfg(self, **kw):
        kw.setdefault("nprocs", 8)
        kw.setdefault("cb_nodes", 3)
        kw.setdefault("comm_size", 4)
        kw.setdefault("data_size", 64)
        kw.setdefault("init", 12)
        kw.setdefault("mutate_rounds", 2)
        kw.setdefault("beam", 3)
        return kw

    def test_deterministic_given_seed(self):
        a = search(seed=7, **self._cfg())
        b = search(seed=7, **self._cfg())
        assert json.loads(json.dumps(a)) == json.loads(json.dumps(b))

    def test_bookkeeping_is_self_consistent(self):
        sr = search(seed=0, **self._cfg())
        rows = sr["rows"]
        assert sr["evaluated"] == len(rows)
        assert len({r["composition"] for r in rows}) == len(rows)
        # prune counters match the recorded prefixes exactly
        for key, prefix in (("invalid", "build:"), ("check", "check:"),
                            ("traffic", "traffic:"),
                            ("dominated", "dominated:")):
            assert sr["pruned"][key] == sum(
                1 for r in rows
                if (r["pruned_by"] or "").startswith(prefix))
        by_comp = {r["composition"]: r for r in rows}
        for i, canon in enumerate(sr["survivors"]):
            r = by_comp[canon]
            assert r["pruned_by"] is None and r["verdict"] == "PROVEN"
            assert r["rank"] == i + 1
        assert sr["finalists"] == sr["survivors"][:sr["top_k"]]
        # every check-pruned row names the refuted property
        for r in rows:
            if (r["pruned_by"] or "").startswith("check:"):
                assert r["pruned_by"] != "check:unknown"

    def test_every_finalist_verifies_on_the_oracle(self):
        sr = search(seed=0, **self._cfg())
        assert sr["finalists"]
        for canon in sr["finalists"]:
            sched = build_schedule(parse_composition(canon), _pattern(
                comm_size=4))
            run_schedule_local(sched, verify=True)


# ---------------------------------------------------------------------------
# registration


class TestRegister:
    CANON = parse_composition(PROVEN_COMPS[0]).canonical()

    def test_reserved_range_guard(self):
        with pytest.raises(RegisterError, match="SYNTH_ID_BASE"):
            register_composition(self.CANON, method_id=SYNTH_ID_BASE)
        with pytest.raises(RegisterError, match="SYNTH_ID_BASE"):
            register_composition(self.CANON, method_id=13)

    def test_idempotent_then_conflict_named(self):
        spec = register_composition(self.CANON, method_id=150)
        assert register_composition(self.CANON, method_id=150) is spec
        with pytest.raises(RegisterError, match="alias"):
            register_composition(
                parse_composition(PROVEN_COMPS[1]), method_id=150)
        with pytest.raises(RegisterError, match="alias"):
            register_composition(self.CANON, method_id=150,
                                 direction="m2a")

    def test_registered_method_is_first_class(self):
        register_composition(self.CANON, method_id=151)
        assert 151 in registered_synth_ids()
        sched = compile_method(151, _pattern())
        assert sched.method_id == 151
        assert sched.variant == f"synth:{self.CANON}"
        run_schedule_local(sched, verify=True)
        key = schedule_shape_key(sched)
        assert self.CANON in str(key)


# ---------------------------------------------------------------------------
# CLI round trip (jax-free synthetic race)


def _synth_cli(tmp_path, *extra):
    from tpu_aggcomm.cli import main
    return main(["synth", "-n", "8", "-a", "3", "-c", "4", "-d", "64",
                 "--init", "12", "--mutate-rounds", "1", "--beam", "2",
                 "--max-batches", "3", "--predict-root", str(tmp_path),
                 "--synth-root", str(tmp_path), *extra])


class TestCli:
    def test_synthetic_win_roundtrip(self, tmp_path, capsys):
        from tpu_aggcomm.cli import main
        from tpu_aggcomm.obs.regress import validate_synth

        # m101 (the first registered finalist) injected 2x faster than
        # the reference field: the synthesized schedule must win and the
        # artifact must validate and replay REPRODUCED
        rc = _synth_cli(tmp_path, "--synthetic", "250,m101*0.5")
        out = capsys.readouterr().out
        assert rc == 0
        assert "winner: m101:" in out
        path = tmp_path / "SYNTH_r01.json"
        assert path.exists()
        blob = json.loads(path.read_text())
        assert validate_synth(blob, "SYNTH_r01.json") == []
        assert blob["winner"]["synthesized"] is True
        assert blob["synthetic"] == "250,m101*0.5"

        rc = main(["synth", "--replay", str(path)])
        out = capsys.readouterr().out
        assert rc == 0 and "REPRODUCED" in out

    def test_reference_win_writes_nothing(self, tmp_path, capsys):
        # the references injected faster: no artifact, named refusal
        rc = _synth_cli(tmp_path, "--synthetic", "250,m3*0.1")
        cap = capsys.readouterr()
        assert rc == 1
        assert "reference method m=3 won the race" in cap.err
        assert not list(tmp_path.glob("SYNTH_r*.json"))

    def test_replay_detects_tampered_search(self, tmp_path, capsys):
        """A schema-valid search block that was not produced by the
        recorded (config, seed) must MISMATCH on replay."""
        from tpu_aggcomm.cli import main
        rc = _synth_cli(tmp_path, "--synthetic", "250,m101*0.5")
        capsys.readouterr()
        assert rc == 0
        blob = json.loads((tmp_path / "SYNTH_r01.json").read_text())
        bad = copy.deepcopy(blob)
        bad["search"]["init"] += 1      # different seeded frontier
        p = tmp_path / "SYNTH_r90.json"
        p.write_text(json.dumps(bad))
        rc = main(["synth", "--replay", str(p)])
        out = capsys.readouterr().out
        assert rc == 1 and "MISMATCH" in out

    def test_replay_detects_tampered_race(self, tmp_path, capsys):
        """A forged elimination timeline the samples do not support
        must MISMATCH (an internally-INCONSISTENT forgery — e.g. a
        swapped winner — already fails schema validation upstream)."""
        from tpu_aggcomm.cli import main
        rc = _synth_cli(tmp_path, "--synthetic", "250,m101*0.5")
        capsys.readouterr()
        assert rc == 0
        blob = json.loads((tmp_path / "SYNTH_r01.json").read_text())
        bad = copy.deepcopy(blob)
        assert bad["race"]["eliminations"], "race should separate refs"
        bad["race"]["eliminations"][0]["batch"] += 1
        p = tmp_path / "SYNTH_r91.json"
        p.write_text(json.dumps(bad))
        rc = main(["synth", "--replay", str(p)])
        out = capsys.readouterr().out
        assert rc == 1 and "MISMATCH" in out

    def test_inconsistent_winner_fails_schema(self, tmp_path, capsys):
        """validate_synth refuses a winner the recorded race
        contradicts, before replay even runs."""
        from tpu_aggcomm.cli import main
        rc = _synth_cli(tmp_path, "--synthetic", "250,m101*0.5")
        capsys.readouterr()
        assert rc == 0
        blob = json.loads((tmp_path / "SYNTH_r01.json").read_text())
        bad = copy.deepcopy(blob)
        loser = next(c for c in bad["race"]["order"]
                     if c != bad["race"]["winner"])
        bad["race"]["winner"] = loser
        p = tmp_path / "SYNTH_r92.json"
        p.write_text(json.dumps(bad))
        with pytest.raises(SystemExit, match="schema validation"):
            main(["synth", "--replay", str(p)])

    def test_registration_is_opt_in(self, tmp_path, capsys,
                                    monkeypatch):
        """Synthesized ids resolve only through --synth-root (or the
        implicit cwd scan a >100 -m triggers): with the flag the id
        compiles; in an artifact-less cwd without it, the same -m fails
        exactly as an unknown method always has."""
        from tpu_aggcomm.cli import main
        rc = _synth_cli(tmp_path, "--synthetic", "250,m101*0.5")
        capsys.readouterr()
        assert rc == 0
        for mid in list(METHODS):
            if mid > SYNTH_ID_BASE:
                del METHODS[mid]
        rc = main(["inspect", "check", "-m", "101", "-n", "8", "-a", "3",
                   "-c", "4", "--synth-root", str(tmp_path)])
        capsys.readouterr()
        assert rc == 0
        for mid in list(METHODS):
            if mid > SYNTH_ID_BASE:
                del METHODS[mid]
        empty = tmp_path / "empty"
        empty.mkdir()
        monkeypatch.chdir(empty)
        with pytest.raises((SystemExit, KeyError)):
            main(["inspect", "check", "-m", "101", "-n", "8", "-a", "3",
                  "-c", "4"])


# ---------------------------------------------------------------------------
# the committed artifact (the ci_tier1.sh gate, in-process)


class TestCommittedArtifact:
    def _blob(self):
        assert os.path.exists(COMMITTED_SYNTH), \
            "committed SYNTH artifact gone"
        with open(COMMITTED_SYNTH) as f:
            return json.load(f)

    def test_validates_and_replays(self, capsys):
        from tpu_aggcomm.cli import main
        from tpu_aggcomm.obs.regress import validate_synth
        blob = self._blob()
        assert validate_synth(blob, "SYNTH_r01.json") == []
        rc = main(["synth", "--replay", COMMITTED_SYNTH])
        out = capsys.readouterr().out
        assert rc == 0 and "REPRODUCED" in out

    def test_winner_beats_every_reference_on_record(self):
        """The acceptance criterion, read off the committed samples: the
        synthesized winner's pooled median is strictly the smallest."""
        import statistics
        blob = self._blob()
        assert blob["winner"]["synthesized"] is True
        meds = {cid: statistics.median([x for b in bl for x in b])
                for cid, bl in blob["race"]["samples"].items()}
        win = blob["race"]["winner"]
        assert all(meds[win] < m for c, m in meds.items() if c != win)
        assert int(win.split(":", 1)[0][1:]) > SYNTH_ID_BASE

    def test_winner_verifies_on_every_backend(self):
        """Byte-exact --verify for the committed winner on the local
        oracle AND jax_sim (and pallas_fused interpret when the
        composition is fusable — no staging rows)."""
        from tpu_aggcomm.backends.jax_sim import JaxSimBackend
        from tpu_aggcomm.synth import ensure_registered
        blob = self._blob()
        ensure_registered(REPO)
        mid = blob["winner"]["method_id"]
        cfg = blob["config"]
        p = AggregatorPattern(
            nprocs=8, cb_nodes=3, data_size=64, proc_node=1,
            comm_size=cfg["comm_size"], placement=cfg["agg_type"])
        sched = compile_method(mid, p)
        recv_o, _ = run_schedule_local(sched, verify=True)
        recv_s, _ = JaxSimBackend().run(sched, verify=True, iter_=0)
        for a, b in zip(recv_o, recv_s):
            if a is None:
                assert b is None
            else:
                np.testing.assert_array_equal(a, np.asarray(b))
        if sched.n_staging == 0 and not sched.collective:
            from tpu_aggcomm.backends.pallas_fused import \
                PallasFusedBackend
            recv_f, _ = PallasFusedBackend(interpret=True).run(
                sched, verify=True, iter_=0)
            for a, b in zip(recv_o, recv_f):
                if a is None:
                    assert b is None
                else:
                    np.testing.assert_array_equal(a, np.asarray(b))


# ---------------------------------------------------------------------------
# jax-free pins (the purity contract, executed)


def test_full_pipeline_survives_poisoned_jax(tmp_path):
    """The WHOLE synthetic pipeline — search, check-pruning, traffic
    audit, registration, race, artifact write, then replay — must run
    where ``import jax`` raises (shared recipe, tests/_jaxfree.py)."""
    import _jaxfree
    env = _jaxfree.poisoned_env(
        tmp_path, "synth must not import jax")
    out = tmp_path / "work"
    out.mkdir()
    r = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "synth", "-n", "8",
         "-a", "3", "-c", "4", "-d", "64", "--init", "12",
         "--mutate-rounds", "1", "--beam", "2", "--max-batches", "3",
         "--synthetic", "250,m101*0.5", "--predict-root", str(out),
         "--synth-root", str(out)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    path = out / "SYNTH_r01.json"
    assert path.exists()
    r = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "synth", "--replay",
         str(path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "REPRODUCED" in r.stdout


def test_committed_replay_survives_poisoned_jax(tmp_path):
    """The exact ci_tier1.sh gate, under the poison."""
    import _jaxfree
    if not os.path.exists(COMMITTED_SYNTH):
        pytest.skip("no committed SYNTH artifact")
    env = _jaxfree.poisoned_env(
        tmp_path, "synth --replay must not import jax")
    r = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "synth", "--replay",
         COMMITTED_SYNTH],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "REPRODUCED" in r.stdout
