"""Pallas remote-DMA backend (interpret mode on the CPU mesh): the
sync-family methods the backend exists for, plus permutation completion."""

import numpy as np
import pytest

from tpu_aggcomm.backends.pallas_dma import PallasDmaBackend, complete_permutation
from tpu_aggcomm.core.methods import compile_method
from tpu_aggcomm.core.pattern import AggregatorPattern


def test_complete_permutation():
    perm = complete_permutation([(0, 3), (2, 1)], 4)
    assert perm[0] == 3 and perm[2] == 1
    assert sorted(perm.tolist()) == [0, 1, 2, 3]
    # self-loops preferred for idle devices
    perm2 = complete_permutation([(1, 2)], 4)
    assert perm2[0] == 0 and perm2[3] == 3


# the sync/half-sync/signal family — the methods whose rendezvous semantics
# this backend exists to express (SURVEY.md §7 hard part 1)
@pytest.mark.parametrize("method", [6, 7, 11, 12, 18])
def test_pallas_sync_family(method):
    p = AggregatorPattern(8, 3, data_size=64, comm_size=3)
    sched = compile_method(method, p)
    recv, timers = PallasDmaBackend().run(sched, verify=True)
    assert timers[0].total_time > 0


@pytest.mark.parametrize("method", [1, 3, 20])
def test_pallas_general_methods(method):
    p = AggregatorPattern(8, 3, data_size=32, comm_size=2)
    sched = compile_method(method, p)
    PallasDmaBackend().run(sched, verify=True)


def test_pallas_dense_delegates():
    p = AggregatorPattern(8, 3, data_size=32)
    sched = compile_method(8, p)
    recv, _ = PallasDmaBackend().run(sched, verify=True)


def test_pallas_barrier_method():
    # m=17 barriers every round; m=13 -b 1 barriers at rep end
    p = AggregatorPattern(8, 3, data_size=32, comm_size=4)
    PallasDmaBackend().run(compile_method(17, p), verify=True)
    PallasDmaBackend().run(compile_method(13, p, barrier_type=1), verify=True)


def test_pallas_unpadded_data_size():
    # data_size not a multiple of 128 exercises the pad/slice path
    p = AggregatorPattern(8, 3, data_size=100, comm_size=3)
    PallasDmaBackend().run(compile_method(12, p), verify=True)


def test_pallas_routes_tam_to_jax_sim():
    # run-all (-m 0) must complete on this backend (VERDICT r1 item 2):
    # TAM methods route to the device-resident jax_sim hierarchical route
    p = AggregatorPattern(8, 3, data_size=16, proc_node=2)
    for m in (15, 16):
        recv, timers = PallasDmaBackend().run(compile_method(m, p),
                                              verify=True)
        assert timers[0].total_time > 0


def test_barrier_shifts_log_depth():
    from tpu_aggcomm.backends.pallas_dma import barrier_shifts
    assert barrier_shifts(1) == []
    assert barrier_shifts(2) == [1]
    assert barrier_shifts(5) == [1, 2, 4]
    assert barrier_shifts(8) == [1, 2, 4]
    assert len(barrier_shifts(4096)) == 12      # log depth at pod scale


def test_barrier_step_count_is_logarithmic():
    """A barrier costs ceil(log2 n) permutation steps, not n (VERDICT r2
    weak 3): for n=8, m=1 unthrottled the program is 3 init-barrier steps
    + (CTS + data) per color."""
    from jax.sharding import Mesh
    import jax
    p = AggregatorPattern(8, 3, data_size=32, comm_size=100)
    sched = compile_method(1, p)
    b = PallasDmaBackend()
    mesh = Mesh(np.array(jax.devices()[:8]), ("ranks",))
    _fn, _pds, _ns, _nr, tabs, _waves = b._lower(sched, mesh, interpret=True)
    from tpu_aggcomm.backends.jax_ici import lower_schedule
    C = lower_schedule(sched).n_colors
    assert tabs[0].shape[1] == 3 + 2 * C


def test_barrier_method_delivery_unchanged_log_barrier():
    """m=17 (a barrier inside every round, mpi_test.c:1188) still delivers
    byte-exact through the dissemination barrier."""
    p = AggregatorPattern(8, 3, data_size=32, comm_size=3, proc_node=2)
    sched = compile_method(17, p)
    recv, _ = PallasDmaBackend().run(sched, verify=True)


def test_pallas_compiled_on_tpu():
    """Platform-gated (runs only with a real TPU attached): the semaphore
    kernel compiled through Mosaic — not interpret mode — on a degenerate
    1-device mesh (self-loop remote DMA, real semaphore waits), delivery
    verified. The CI CPU mesh always skips this; scripts/tpu_pallas_probe.py
    is the manual driver (VERDICT r2 item 4)."""
    import jax
    if jax.devices()[0].platform != "tpu":
        pytest.skip("needs a real TPU (see scripts/tpu_pallas_probe.py)")
    p = AggregatorPattern(1, 1, data_size=2048, comm_size=1)
    sched = compile_method(1, p)
    b = PallasDmaBackend(devices=[jax.devices()[0]], interpret=False)
    recv, _ = b.run(sched, ntimes=1, verify=True)


class TestConcurrentMode:
    """Concurrent posting discipline (VERDICT r3 item 3): a round's DMAs
    are all in flight before any wait — in-flight per round = throttle c
    (the Issend storm then Waitall, mpi_test.c:1789-1815). Lockstep stays
    the deterministic baseline; both must deliver identical bytes."""

    @pytest.mark.parametrize("method", [1, 6, 7, 11, 12, 17, 18])
    def test_delivery_matches_lockstep(self, method):
        import numpy as np

        p = AggregatorPattern(8, 3, data_size=52, comm_size=2, proc_node=2)
        sched = compile_method(method, p)
        r_lock, _ = PallasDmaBackend().run(sched, verify=True, iter_=3)
        r_conc, _ = PallasDmaBackend(concurrent=True).run(sched,
                                                          verify=True,
                                                          iter_=3)
        for a, b in zip(r_lock, r_conc):
            assert (a is None) == (b is None)
            if a is not None:
                np.testing.assert_array_equal(a, b)

    def test_wave_structure(self):
        p = AggregatorPattern(8, 3, data_size=64, comm_size=1)
        sched = compile_method(1, p)   # c=1: many single-color rounds
        w_lock = PallasDmaBackend().wave_profile(sched)
        w_conc = PallasDmaBackend(concurrent=True).wave_profile(sched)
        # lockstep: every wave is exactly one step
        assert w_lock["max_in_flight"] == 1
        # same total step count: concurrency changes posting, not steps
        assert w_lock["steps"] == w_conc["steps"]
        # m=1 is rendezvous: each data wave is preceded by a grant wave
        # of the same width; multi-step waves appear only in conc mode
        assert w_conc["n_waves"] <= w_lock["n_waves"]

    def test_throttle_widens_concurrent_waves(self):
        widths = {}
        for c in (1, 8):
            p = AggregatorPattern(8, 4, data_size=64, comm_size=c)
            sched = compile_method(1, p)
            widths[c] = PallasDmaBackend(
                concurrent=True).wave_profile(sched)["max_in_flight"]
        # a deeper throttle admits more concurrent copies per round: the
        # widest wave grows with c — the property the mode exists for.
        # (Small c is floor-bounded by sender-side serialization: each
        # sender's a slabs of a round need a colors regardless of the
        # receiver-side c bound, so compare the unthrottled end.)
        assert widths[8] > widths[1]

    @pytest.mark.parametrize("method", [1, 18])
    def test_wave_count_law_across_throttle_sweep(self, method):
        """The lockstep-vs-concurrent divergence, quantified (VERDICT r4
        item 2, interpret-mesh branch — the RESULTS_TPU.md table): as c
        sweeps 1..n, the SAME steps repartition into ever-wider
        concurrent waves while lockstep stays at in-flight=1. Pins, per
        c: (a) step counts identical across disciplines; (b) lockstep
        max in-flight == 1; (c) concurrent max in-flight nondecreasing
        in c, reaching n unthrottled; (d) the init dissemination barrier
        stays lockstep (log2 n one-step waves) in both modes; (e) the
        rendezvous discipline (both m=1 and m=18 Issend): after the init
        barrier, concurrent waves come in (grant, data) pairs of equal
        width — CTS fully drains before RTS posts, at round granularity
        (mpi_test.c:1789-1815)."""
        import math

        n = 8
        prev = 0
        for c in (1, 2, 4, 8):
            p = AggregatorPattern(n, 3, data_size=256, comm_size=c)
            sched = compile_method(method, p)
            wl = PallasDmaBackend().wave_profile(sched)
            wc = PallasDmaBackend(concurrent=True).wave_profile(sched)
            assert wl["steps"] == wc["steps"] == sum(wc["widths"])  # (a)
            assert wl["max_in_flight"] == 1                        # (b)
            assert wc["max_in_flight"] >= prev                     # (c)
            prev = wc["max_in_flight"]
            nbar = int(math.log2(n))
            assert wc["widths"][:nbar] == [1] * nbar               # (d)
            body = wc["widths"][nbar:]
            assert len(body) % 2 == 0                              # (e)
            for g, d in zip(body[::2], body[1::2]):
                assert g == d
        assert prev == n    # unthrottled: the whole round in flight

    def test_registry_and_provenance(self):
        from tpu_aggcomm.backends import get_backend

        b = get_backend("pallas_dma_conc")
        assert b.name == "pallas_dma_conc"
        p = AggregatorPattern(8, 3, data_size=64, comm_size=2)
        b.run(compile_method(1, p), verify=True)
        assert b.last_provenance == ("pallas_dma_conc", "attributed")


def test_pallas_concurrent_compiled_on_tpu():
    """Platform-gated: the concurrent (round-wide wave) kernel through
    the real Mosaic pipeline on the degenerate 1-device mesh, verified —
    the compile proof VERDICT r3 item 3 asks for alongside the interpret
    equality pins."""
    import jax
    if jax.devices()[0].platform != "tpu":
        pytest.skip("needs a real TPU (see scripts/tpu_pallas_probe.py)")
    p = AggregatorPattern(1, 1, data_size=2048, comm_size=1)
    sched = compile_method(1, p)
    b = PallasDmaBackend(devices=[jax.devices()[0]], interpret=False,
                         concurrent=True)
    recv, _ = b.run(sched, ntimes=1, verify=True)
