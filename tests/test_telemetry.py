"""Live-telemetry pipeline (ISSUE 8) guarantees:

- jax-free: obs/export.py, obs/live.py and obs/history.py — plus the
  ``inspect history`` / ``inspect live`` CLI paths — run where ``import
  jax`` raises (poisoned-jax subprocess pins, the traffic/tune recipe);
- float-exact: the OpenMetrics exposition's round gauges are
  ``obs.metrics.round_stats`` VERBATIM and the ``_exact`` summary
  quantiles are the same ``percentile`` arithmetic over the same
  attribution cells — parse-and-compare equality, not approx;
- OFF by default, zero-cost when off: ``serve_from_env`` with no
  port/env returns None, and a plain sweep never imports
  ``tpu_aggcomm.obs.export`` at all (sys.modules pin);
- live endpoint: a sweep run with the endpoint armed prints its URL and
  serves parseable OpenMetrics mid-run (scraped from the parent);
- trend gate: seeded, deterministic, verdicts match construction
  (drifting-up/down/stable/insufficient), and over the COMMITTED
  artifacts ``inspect history`` agrees verdict-for-verdict with the
  ``trend`` block inside ``bench.py --check-regression``;
- history index writes go through ``obs.atomic_write``: a SIGKILL
  mid-write (fsync patched to die) leaves the previous index intact;
- ``inspect live`` renders a real sweep's journal: done cells, the
  remaining grid, and a watchdog-model ETA;
- ``inspect ledger`` drift additionally summarizes resilience records
  (retries per site, suppressed classes) between consecutive rounds.
"""

import io
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment
from tpu_aggcomm.obs import export, trace
from tpu_aggcomm.obs.history import (build_index, check_trends, trend_gate,
                                     write_index)
from tpu_aggcomm.obs.ledger import diff_resilience
from tpu_aggcomm.obs.live import attach, sweep_status, tail_events
from tpu_aggcomm.obs.metrics import cell_means, percentile, round_stats
from tpu_aggcomm.obs.regress import (check_regression, parse_openmetrics,
                                     validate_openmetrics)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _poisoned_env(tmp_path):
    """Shared recipe (tests/_jaxfree.py, parameterized by the linter's
    purity contract): the telemetry pipeline must run on a host whose
    tunnel is wedged so badly that importing jax would hang forever."""
    import _jaxfree
    return _jaxfree.poisoned_env(tmp_path,
                                 "telemetry must not import jax")


def _traced_run(prefix, **kw):
    cfg = ExperimentConfig(nprocs=8, cb_nodes=2, data_size=64,
                           comm_size=2, method=1, ntimes=3,
                           backend="jax_sim", verify=True, **kw)
    trace.enable()
    try:
        run_experiment(cfg, out=io.StringIO())
    finally:
        paths = trace.flush(prefix)
        trace.disable()
    return paths


# ------------------------------------------------------------ jax-free pins

def test_telemetry_modules_survive_poisoned_jax(tmp_path):
    """export/live/history import AND do real work where jax raises."""
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "from tpu_aggcomm.obs import export, live, history\n"
         "reg = export.MetricsRegistry()\n"
         "reg.counter('x', 2.0, kind='a'); reg.observe('y_seconds', 0.5)\n"
         "text = reg.render()\n"
         "assert text.endswith('# EOF\\n'), text\n"
         "assert history.trend_gate([(1, 1.0), (2, 1.0)])['verdict'] "
         "== 'insufficient'\n"
         "assert live.tail_events('/nonexistent') == []\n"
         "assert 'jax' not in sys.modules"],
        cwd=REPO, env=_poisoned_env(tmp_path), capture_output=True,
        text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_inspect_history_survives_poisoned_jax(tmp_path):
    """The ci_tier1.sh gate command, byte-for-byte, where jax is broken."""
    r = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "history"],
        cwd=REPO, env=_poisoned_env(tmp_path), capture_output=True,
        text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "trend:" in r.stdout
    assert "measurable rounds" in r.stdout


def test_inspect_live_survives_poisoned_jax(tmp_path):
    """Attaching to a not-yet-started sweep (no journal) where jax is
    broken: a board, a nonzero exit (work remains), no traceback."""
    r = subprocess.run(
        [sys.executable, "-m", "tpu_aggcomm.cli", "inspect", "live",
         "--results-csv", str(tmp_path / "absent.csv"),
         "--comm-sizes", "2,4"],
        cwd=REPO, env=_poisoned_env(tmp_path), capture_output=True,
        text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "no journal entries yet" in r.stdout
    assert "remaining: 2 cell(s)" in r.stdout
    assert "Traceback" not in r.stderr


def test_telemetry_gate_survives_poisoned_jax(tmp_path):
    """The whole CI gate script is itself a jax-free supervisor tool."""
    r = subprocess.run(
        [sys.executable, "scripts/telemetry_gate.py"],
        cwd=REPO, env=_poisoned_env(tmp_path), capture_output=True,
        text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "float-exact" in r.stdout


# -------------------------------------------------- OpenMetrics round trip

def test_openmetrics_roundtrip_float_exact(tmp_path):
    """Acceptance: exported quantiles match ``inspect trace``'s round
    stats float-exactly — gauge == round_stats value, ``_exact``
    summary == the same percentile arithmetic, via parse-and-compare."""
    paths = _traced_run(str(tmp_path / "om"))
    events = trace.load_events(paths[0])
    text = export.trace_registry(events).render()
    assert validate_openmetrics(text) == []
    parsed = parse_openmetrics(text)
    assert parsed["eof"]
    samples = {(s["name"], tuple(sorted(s["labels"].items()))): s["value"]
               for s in parsed["samples"]}
    run = next(e for e in events if e.get("ev") == "run")
    lab = {"run": str(run["id"]), "method": str(run["name"]),
           "backend": str(run["backend"])}
    stats = round_stats(events, run["id"])
    assert stats, "traced throttled run produced no round stats"
    for rs in stats:
        rl = tuple(sorted(dict(lab, round=str(rs["round"])).items()))
        for gauge, want in (("round_wall_seconds", rs["wall"]),
                            ("round_p50_seconds", rs["p50"]),
                            ("round_p95_seconds", rs["p95"])):
            got = samples[(f"{export.PREFIX}_{gauge}", rl)]
            assert got == want, (gauge, rs["round"], got, want)
    vals = [v for _k, v in sorted(cell_means(events, run["id"]).items())]
    for q in export.QUANTILES:
        key = (f"{export.PREFIX}_rank_round_seconds_exact",
               tuple(sorted(dict(lab, quantile=repr(float(q))).items())))
        assert samples[key] == percentile(vals, q * 100.0)
    # the histogram count covers every attribution cell exactly once
    cnt_key = (f"{export.PREFIX}_rank_round_seconds",
               tuple(sorted(lab.items())))
    assert samples[(f"{export.PREFIX}_rank_round_seconds_count",
                    tuple(sorted(lab.items())))] == len(vals)
    del cnt_key


def test_validate_openmetrics_rejects_breakage():
    reg = export.MetricsRegistry()
    reg.observe("t_seconds", 0.25)
    good = reg.render()
    assert validate_openmetrics(good) == []
    # no terminator
    assert any("EOF" in e for e in
               validate_openmetrics(good.replace("# EOF\n", "")))
    # sample without a TYPE declaration
    assert any("no TYPE" in e for e in
               validate_openmetrics("orphan_total 1\n# EOF\n"))
    # junk line = loud single-error verdict
    assert len(validate_openmetrics("!!!\n# EOF\n")) == 1
    # non-cumulative histogram buckets
    bad = ("# TYPE h histogram\n"
           'h_bucket{le="0.1"} 5\nh_bucket{le="0.2"} 3\n'
           'h_bucket{le="+Inf"} 5\nh_count 5\nh_sum 1.0\n# EOF\n')
    assert any("cumulative" in e or "decreas" in e
               for e in validate_openmetrics(bad))


def test_latency_histogram_exact_quantiles():
    h = export.LatencyHistogram()
    vals = [1e-6, 5e-6, 2e-6, 9e-6, 4e-6]
    for v in vals:
        h.observe(v)
    assert h.count == len(vals)
    assert h.sum == pytest.approx(sum(vals))
    for q in (0.5, 0.95, 0.99):
        assert h.quantile(q) == percentile(vals, q * 100.0)
    assert sum(h.counts) == len(vals)


# ------------------------------------------------------------- the endpoint

def test_metrics_server_http():
    reg = export.MetricsRegistry()
    reg.counter("tpu_aggcomm_demo", 3.0, stage="x")
    reg.observe("tpu_aggcomm_demo_wall_seconds", 0.125)
    srv = export.MetricsServer(reg.render, port=0)
    try:
        assert srv.port > 0
        with urllib.request.urlopen(srv.url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "application/openmetrics-text")
            body = resp.read().decode()
        assert validate_openmetrics(body) == []
        assert "tpu_aggcomm_demo_total" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                srv.url.replace("/metrics", "/other"), timeout=10)
    finally:
        srv.close()


def test_serve_from_env_off_by_default():
    """Absent/empty/garbage env = no server, no socket, no thread."""
    assert export.serve_from_env(lambda: "", env={}) is None
    assert export.serve_from_env(
        lambda: "", env={export.METRICS_PORT_ENV: ""}) is None
    assert export.serve_from_env(
        lambda: "", env={export.METRICS_PORT_ENV: "not-a-port"}) is None
    srv = export.serve_from_env(
        lambda: "# EOF\n", env={export.METRICS_PORT_ENV: "0"})
    try:
        assert srv is not None and srv.port > 0
    finally:
        srv.close()


def test_sweep_without_endpoint_never_imports_export(tmp_path):
    """Zero-cost pin: a plain sweep (no flag, no env var) must not load
    the telemetry module at all — the gate is on the import itself."""
    env = dict(os.environ)
    env.pop(export.METRICS_PORT_ENV, None)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "from tpu_aggcomm.cli import main\n"
         "rc = main(['sweep', '-n', '8', '-a', '2', '-d', '64',\n"
         "           '-m', '1', '--backend', 'local',\n"
         "           '--comm-sizes', '2', '--results-csv', 'r.csv'])\n"
         "assert rc == 0, rc\n"
         "assert 'tpu_aggcomm.obs.export' not in sys.modules, \\\n"
         "    'telemetry code loaded on the unarmed hot path'\n"
         "assert 'jax' not in sys.modules"],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr


def test_sweep_endpoint_serves_openmetrics_midrun(tmp_path):
    """Acceptance: scrape /metrics from the parent while a CPU sweep
    runs; the exposition parses and carries the sweep counters."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.pop(export.METRICS_PORT_ENV, None)
    # enough cells that the endpoint outlives the first scrape; the
    # child prints its URL on stderr before the first cell runs
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_aggcomm.cli", "sweep",
         "-n", "32", "-a", "8", "-d", "2048", "-m", "1", "-i", "100",
         "--backend", "local", "--comm-sizes", "1,2,4,8,16",
         "--results-csv", "r.csv", "--metrics-port", "0"],
        cwd=str(tmp_path), env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE, text=True)
    url = None
    try:
        for line in proc.stderr:
            if line.startswith("# metrics endpoint:"):
                url = line.split(":", 1)[1].strip()
                break
        assert url, "sweep never announced its metrics endpoint"
        body = None
        with urllib.request.urlopen(url, timeout=10) as resp:
            body = resp.read().decode()
        assert validate_openmetrics(body) == []
        parsed = parse_openmetrics(body)
        names = {s["name"] for s in parsed["samples"]}
        assert f"{export.PREFIX}_sweep_cells_total" in names
    finally:
        proc.stderr.close()
        rc = proc.wait(timeout=300)
    assert rc == 0


# --------------------------------------------------------------- trend gate

def test_trend_gate_verdicts():
    up = trend_gate([(1, 1.0), (2, 1.4), (3, 1.9), (4, 2.5), (5, 3.2)])
    assert up["verdict"] == "drifting-up"
    down = trend_gate([(1, 3.2), (2, 2.5), (3, 1.9), (4, 1.4), (5, 1.0)])
    assert down["verdict"] == "drifting-down"
    flat = trend_gate([(1, 1.00), (2, 1.01), (3, 0.99), (4, 1.00),
                       (5, 1.02)])
    assert flat["verdict"] == "stable"
    short = trend_gate([(1, 1.0), (2, 9.9)])
    assert short["verdict"] == "insufficient"
    assert "trend gate inactive" in short["note"]


def test_trend_gate_seeded_deterministic():
    """Same points + same seed => byte-identical verdict (the
    regression-gate seed discipline)."""
    pts = [(1, 1.0), (2, 1.2), (3, 1.1), (4, 1.5), (5, 1.4)]
    a = trend_gate(pts, seed=7)
    b = trend_gate(pts, seed=7)
    assert a == b
    c = trend_gate(pts, seed=8)
    assert c["ci_pct_per_round"] != a["ci_pct_per_round"]


def test_trend_gate_needs_ci_confirmation():
    """A steep point slope whose bootstrap CI includes zero must stay
    stable — a two-round blip cannot fake a trajectory."""
    g = trend_gate([(1, 1.0), (2, 1.0), (3, 5.0)])
    assert g["verdict"] == "stable"
    assert g["note"] and "CI includes zero" in g["note"]


def test_check_trends_matches_check_regression():
    """Over the COMMITTED artifacts: the history gate and the trend
    block inside --check-regression agree verdict-for-verdict (same
    artifacts + same seed => same verdict)."""
    trends = check_trends(REPO)
    assert trends["errors"] == []
    verdict = check_regression(REPO)
    tr = verdict.get("trend")
    if tr is None:
        pytest.skip("no measurable current round in the committed history")
    gate = trends["series"][tr["series"]]
    for k in ("verdict", "rounds", "slope_pct_per_round",
              "ci_pct_per_round", "seed"):
        assert gate[k] == tr[k], (k, gate[k], tr[k])
    # and the whole thing is deterministic call-over-call
    assert check_trends(REPO) == trends


# ------------------------------------------------------------ history index

def test_history_index_schema_and_families(tmp_path):
    index = build_index(REPO)
    assert index["schema"] == "history-v1"
    assert index["bench"], "committed bench history missing from index"
    assert index["errors"] == []
    assert any(t["verdict"] for t in index["traffic"])
    path = write_index(str(tmp_path / "HISTORY.json"), index)
    with open(path) as fh:
        assert json.load(fh)["schema"] == "history-v1"


def test_history_write_index_atomic_under_sigkill(tmp_path):
    """SIGKILL mid-write (fsync patched to die) must leave the previous
    index byte-intact — write_index goes through obs.atomic_write."""
    target = tmp_path / "HISTORY.json"
    original = '{"schema": "history-v1", "sentinel": true}\n'
    target.write_text(original)
    r = subprocess.run(
        [sys.executable, "-c",
         "import os, signal\n"
         "os.fsync = lambda fd: os.kill(os.getpid(), signal.SIGKILL)\n"
         "from tpu_aggcomm.obs.history import write_index\n"
         f"write_index({str(target)!r}, {{'schema': 'history-v1', "
         "'huge': 'x' * 100000})\n"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == -9, (r.returncode, r.stderr)
    assert target.read_text() == original
    # the aborted temp file must not linger as a fake artifact either
    leftovers = [p for p in os.listdir(tmp_path)
                 if p.endswith(".tmp")]
    del leftovers  # mkstemp leftovers are allowed; the TARGET is what
    #                 must stay intact (reader globs *.json, not *.tmp)


# ------------------------------------------------------------- live monitor

def test_live_attach_over_real_sweep(tmp_path, capsys):
    """Run a real (tiny, local) sweep, then attach: done cells render,
    a missing grid cell shows as remaining with exit 1, and the full
    grid exits 0."""
    from tpu_aggcomm.cli import main
    csv = str(tmp_path / "r.csv")
    rc = main(["sweep", "-n", "8", "-a", "2", "-d", "64", "-m", "1",
               "--backend", "local", "--comm-sizes", "2,4",
               "--results-csv", csv])
    capsys.readouterr()
    assert rc == 0
    status = sweep_status(csv, comm_sizes=[2, 4])
    assert [c["comm"] for c in status["cells"]] == [2, 4]
    assert all(c["status"] == "done" for c in status["cells"])
    assert status["remaining"] == []
    assert status["eta"]["per_cell_s"] is not None
    assert status["eta"]["soft_budget_s"] >= 30.0   # watchdog floor
    out = io.StringIO()
    assert attach(csv, comm_sizes=[2, 4], out=out) == 0
    assert "done  comm 2" in out.getvalue()
    out = io.StringIO()
    assert attach(csv, comm_sizes=[2, 4, 8], out=out) == 1
    assert "remaining: 1 cell(s)" in out.getvalue()
    assert "next comm 8" in out.getvalue()


def test_tail_events_tolerates_torn_line(tmp_path):
    p = tmp_path / "t.trace.jsonl"
    p.write_text('{"ev": "run", "id": 0}\n'
                 '{"ev": "span", "run": 0}\n'
                 '{"ev": "instant", "na')      # torn mid-append
    evs = tail_events(str(p))
    assert [e["ev"] for e in evs] == ["run", "span"]
    # trace.load_events must still refuse the same file (committed
    # artifacts with torn lines are corrupt, not "live")
    with pytest.raises(ValueError):
        trace.load_events(str(p))


# ----------------------------------------------------------- ledger RESIL

def test_diff_resilience_lines():
    a = [{"kind": "attempt", "site": "dispatch", "outcome": "retry"},
         {"kind": "attempt", "site": "dispatch", "outcome": "ok"},
         {"kind": "suppressed", "error_class": "TRANSIENT"}]
    b = [{"kind": "attempt", "site": "dispatch", "outcome": "retry"},
         {"kind": "attempt", "site": "dispatch", "outcome": "retry"},
         {"kind": "attempt", "site": "dispatch", "outcome": "retry"},
         {"kind": "suppressed", "error_class": "TRANSIENT"},
         {"kind": "suppressed", "error_class": "TRANSIENT"}]
    lines = diff_resilience(a, b)
    assert any("retries at dispatch: 1 -> 3" in ln for ln in lines)
    assert any("suppressed TRANSIENT errors: 1 -> 2" in ln
               for ln in lines)
    # identical records = no drift lines
    assert diff_resilience(a, a) == []
    # absent on both sides = nothing to say
    assert diff_resilience(None, None) == []
