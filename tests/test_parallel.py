"""Multi-host mesh helpers on the virtual CPU mesh (single process: the
discovery path collapses to one node; the fabricated split carries the same
program shape the multi-host path would)."""

import numpy as np
import pytest

from tpu_aggcomm.parallel import (distributed_init, hierarchical_mesh,
                                  host_major_devices)


def test_host_major_is_stable_permutation():
    import jax
    devs = jax.devices()
    out = host_major_devices(list(reversed(devs)))
    # one process -> caller order preserved (stable sort, single key)
    assert out == list(reversed(devs))
    assert sorted(d.id for d in out) == sorted(d.id for d in devs)


def test_hierarchical_mesh_fabricated_split():
    mesh, na = hierarchical_mesh(proc_node=2)
    assert mesh.axis_names == ("node", "local")
    assert mesh.devices.shape == (4, 2)
    assert na.nnodes == 4
    assert list(na.node_sizes) == [2, 2, 2, 2]
    # proxy = first rank of each node in mesh order
    assert list(na.proxies) == [0, 2, 4, 6]


def test_hierarchical_mesh_default_single_node():
    mesh, na = hierarchical_mesh()
    assert mesh.devices.shape == (1, 8)
    assert na.nnodes == 1


def test_hierarchical_mesh_rejects_nondividing_proc_node():
    with pytest.raises(ValueError, match="divide"):
        hierarchical_mesh(proc_node=3)  # 8 % 3 != 0 -> straddling nodes


def test_straddle_warning():
    import warnings

    import jax

    from tpu_aggcomm.parallel import warn_if_node_straddles_hosts

    devs = jax.devices()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # single host: no warning expected
        assert not warn_if_node_straddles_hosts(devs, 4, "test")


def test_distributed_init_single_process_is_noop():
    # single process: initialize() raises internally -> False, no crash
    assert distributed_init() in (False, True)


def test_tam_engine_runs_on_hierarchical_order():
    import jax

    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.harness.verify import verify_recv
    from tpu_aggcomm.tam.engine import gen_tam_schedule, tam_two_level_jax

    p = AggregatorPattern(8, 3, data_size=32, proc_node=2)
    tam = gen_tam_schedule(p)
    # pass deliberately shuffled devices: host-major reordering inside the
    # engine must still produce a correct (node, local) program
    devs = list(jax.devices())
    recv, _ = tam_two_level_jax(tam, devs, ntimes=1)
    verify_recv(p, recv, 0)
