"""Multi-host mesh helpers on the virtual CPU mesh (single process: the
discovery path collapses to one node; the fabricated split carries the same
program shape the multi-host path would)."""

import numpy as np
import pytest

from tpu_aggcomm.parallel import (distributed_init, hierarchical_mesh,
                                  host_major_devices)


def test_host_major_is_stable_permutation():
    import jax
    devs = jax.devices()
    out = host_major_devices(list(reversed(devs)))
    # one process -> caller order preserved (stable sort, single key)
    assert out == list(reversed(devs))
    assert sorted(d.id for d in out) == sorted(d.id for d in devs)


def test_hierarchical_mesh_fabricated_split():
    mesh, na = hierarchical_mesh(proc_node=2)
    assert mesh.axis_names == ("node", "local")
    assert mesh.devices.shape == (4, 2)
    assert na.nnodes == 4
    assert list(na.node_sizes) == [2, 2, 2, 2]
    # proxy = first rank of each node in mesh order
    assert list(na.proxies) == [0, 2, 4, 6]


def test_hierarchical_mesh_default_single_node():
    mesh, na = hierarchical_mesh()
    assert mesh.devices.shape == (1, 8)
    assert na.nnodes == 1


def test_hierarchical_mesh_rejects_nondividing_proc_node():
    with pytest.raises(ValueError, match="divide"):
        hierarchical_mesh(proc_node=3)  # 8 % 3 != 0 -> straddling nodes


def test_straddle_warning():
    import warnings

    import jax

    from tpu_aggcomm.parallel import warn_if_node_straddles_hosts

    devs = jax.devices()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # single host: no warning expected
        assert not warn_if_node_straddles_hosts(devs, 4, "test")


def test_distributed_init_single_process_is_noop():
    # single process: initialize() raises internally -> False, no crash
    assert distributed_init() in (False, True)


def test_tam_engine_runs_on_hierarchical_order():
    import jax

    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.harness.verify import verify_recv
    from tpu_aggcomm.tam.engine import gen_tam_schedule, tam_two_level_jax

    p = AggregatorPattern(8, 3, data_size=32, proc_node=2)
    tam = gen_tam_schedule(p)
    # pass deliberately shuffled devices: host-major reordering inside the
    # engine must still produce a correct (node, local) program
    devs = list(jax.devices())
    recv, _ = tam_two_level_jax(tam, devs, ntimes=1)
    verify_recv(p, recv, 0)


class TestDistributedInitIdempotency:
    """ADVICE r1 (medium): only a genuine double-init may be swallowed;
    every other explicit-arg bring-up failure must propagate, even when
    its message happens to contain the word 'initialize'."""

    def _reset(self):
        import tpu_aggcomm.parallel as par
        par._distributed_up = False
        return par

    def test_explicit_failure_mentioning_initialize_propagates(
            self, monkeypatch):
        par = self._reset()
        import jax

        def boom(**kw):
            raise RuntimeError(
                "Unable to initialize backend: coordinator unreachable")
        monkeypatch.setattr(jax.distributed, "initialize", boom)
        with pytest.raises(RuntimeError, match="coordinator unreachable"):
            par.distributed_init("1.2.3.4:1234", 2, 0)
        self._reset()

    def test_already_initialized_is_swallowed_and_latched(self, monkeypatch):
        par = self._reset()
        import jax

        calls = []

        def dup(**kw):
            calls.append(1)
            # jax 0.9's real double-init message
            raise RuntimeError(
                "distributed.initialize should only be called once.")
        monkeypatch.setattr(jax.distributed, "initialize", dup)
        assert par.distributed_init("1.2.3.4:1234", 2, 0) is False
        # latched: the second call never re-enters jax
        assert par.distributed_init("1.2.3.4:1234", 2, 0) is False
        assert len(calls) == 1
        self._reset()

    def test_argless_failure_is_single_process_fallback(self, monkeypatch):
        par = self._reset()
        import jax

        def boom(**kw):
            raise RuntimeError("cluster auto-detect failed to initialize")
        monkeypatch.setattr(jax.distributed, "initialize", boom)
        assert par.distributed_init() is False
        self._reset()


def test_bringup_single_process_degenerate():
    """run_rep_across_processes on the single-process 8-device CPU mesh:
    every shard is addressable, so the multi-controller code path
    (put_global shard feeding + addressable_shards verification) runs in
    its degenerate form — delivery byte-verified for every aggregator."""
    import jax

    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.parallel.bringup import run_rep_across_processes

    p = AggregatorPattern(nprocs=8, cb_nodes=3, data_size=256, comm_size=2)
    stats = run_rep_across_processes(p, 1, devices=jax.devices()[:8])
    assert stats["process_count"] == 1
    assert stats["ranks_verified"] == [0, 3, 6]   # placement-1 aggregators


def _cpu_multiprocess_supported():
    # jaxlib 0.4.x's CPU backend refuses cross-process computations
    # outright ("Multiprocess computations aren't implemented on the CPU
    # backend"); the capability arrived with the gloo CPU collectives in
    # later jaxlib releases. On a TPU mesh the path is unaffected.
    import jax
    major, minor = (int(x) for x in jax.__version__.split(".")[:2])
    return (major, minor) >= (0, 5)


@pytest.mark.skipif(
    not _cpu_multiprocess_supported(),
    reason="jaxlib 0.4.x CPU backend cannot run multiprocess "
           "computations (no gloo collectives); needs jaxlib >= 0.5 or "
           "a real TPU mesh")
def test_two_process_bringup_end_to_end():
    """VERDICT r3 item 5 + r4 item 6: the multi-host path end-to-end —
    two REAL processes joined via jax.distributed (coordinator on
    localhost), a global 8-device mesh, the hierarchical (node x local)
    mesh from live topology, one m=1 rep over cross-process collectives
    AND one m=15 TAM rep through the two-level engine with the node axis
    crossing the process boundary (the reference engine's P3
    proxy<->proxy hop, lustre_driver_test.c:944-1309), per-process
    local-shard verification (scripts/two_process_bringup.py)."""
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "two_process_bringup.py")
    out = subprocess.run([sys.executable, script], capture_output=True,
                         text=True, timeout=600)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "TWO-PROCESS BRING-UP: OK" in out.stdout
    assert "node axis across processes OK" in out.stdout


def test_run_tam_across_processes_single_process_mesh():
    """The degenerate single-process case of run_tam_across_processes on
    the virtual CPU mesh: every shard addressable, all aggregators
    verified, mesh = (2 nodes x 4 locals)."""
    from tpu_aggcomm.core.pattern import AggregatorPattern
    from tpu_aggcomm.parallel.bringup import run_tam_across_processes

    p = AggregatorPattern(nprocs=8, cb_nodes=3, data_size=256,
                          proc_node=4)
    stats = run_tam_across_processes(p, 15, iter_=2)
    assert stats["mesh_shape"] == (2, 4)
    assert len(stats["ranks_verified"]) == 3
    stats16 = run_tam_across_processes(p, 16, iter_=2)
    assert len(stats16["ranks_verified"]) == 8   # many-to-all: everyone
