"""Workload layer + collective_write-family engines.

Pins the initialize_setting semantics (lustre_driver_test.c:447-549), the
four engine routes' delivery (test_correctness, l_d_t.c:46-58), their
per-hop byte accounting, and the JAX two-level mesh engine against the
oracles on the virtual 8-device CPU mesh.
"""

import numpy as np
import pytest

from tpu_aggcomm.core.meta import aggregator_meta_information
from tpu_aggcomm.core.topology import static_node_assignment
from tpu_aggcomm.core.workload import StripeType, Workload, initialize_setting
from tpu_aggcomm.harness.verify import VerificationError, fill_slab_tam
from tpu_aggcomm.tam.workload_engines import (
    RouteStats, cw2_local_agg, cw2_local_agg_jax, cw3_shared, cw_benchmark,
    cw_proxy, recv_index_map, run_workload_engine)


def _mk(nprocs=8, per_node=4, blocklen=5, stripe=StripeType.ALL, kind=0):
    na = static_node_assignment(nprocs, per_node, kind)
    return na, initialize_setting(na, blocklen, stripe)


# ---------------------------------------------------------------------------
# initialize_setting semantics

def test_stripe_aggregator_sets():
    na = static_node_assignment(8, 4, 0)
    assert list(initialize_setting(na, 3, StripeType.SAME).aggregators) == [0, 4]
    assert list(initialize_setting(na, 3, StripeType.GREATER).aggregators) == [1, 3, 5, 7]
    assert list(initialize_setting(na, 3, StripeType.LESS).aggregators) == [0, 1, 2, 3]
    assert list(initialize_setting(na, 3, StripeType.ALL).aggregators) == list(range(8))


def test_sizes_match_reference_formula():
    # send_size[dst] = 1 + rank % blocklen for dst in aggregator set, else 0
    # (l_d_t.c:471-472 and siblings)
    na, wl = _mk(blocklen=3, stripe=StripeType.GREATER)
    for rank in range(8):
        ss = wl.send_size(rank)
        for dst in range(8):
            expect = (1 + rank % 3) if dst % 2 else 0
            assert ss[dst] == expect
        rs = wl.recv_size(rank)
        if rank % 2:
            assert list(rs) == [1 + i % 3 for i in range(8)]
        else:
            assert not rs.any()


def test_fill_is_map_data3():
    _, wl = _mk(blocklen=4)
    msg = wl.fill(3, 5)
    assert len(msg) == 1 + 3 % 4
    np.testing.assert_array_equal(msg, fill_slab_tam(3, 5, len(msg)))
    # MAP_DATA(a,b,c) = 1 + 3a + 5b + 7c (l_d_t.c:20)
    assert msg[0] == (1 + 3 * 3 + 5 * 5) % 256


def test_verify_catches_corruption():
    na, wl = _mk()
    recv, _ = cw_benchmark(wl)
    wl.verify_all(recv)
    recv[3][2][0] ^= 0xFF
    with pytest.raises(VerificationError):
        wl.verify_recv(3, recv[3])


def test_workload_validation():
    na = static_node_assignment(4, 2, 0)
    with pytest.raises(ValueError):
        Workload(nprocs=4, blocklen=0, stripe=StripeType.ALL,
                 aggregators=np.arange(4))
    with pytest.raises(ValueError):
        Workload(nprocs=4, blocklen=2, stripe=StripeType.ALL,
                 aggregators=np.array([4]))


# ---------------------------------------------------------------------------
# oracle engines: delivery + route accounting

STRIPES = list(StripeType)


@pytest.mark.parametrize("stripe", STRIPES)
@pytest.mark.parametrize("kind", [0, 1])
def test_benchmark_and_proxy_deliver(stripe, kind):
    na, wl = _mk(nprocs=12, per_node=4, blocklen=5, stripe=stripe, kind=kind)
    for engine in ("benchmark", "proxy"):
        recv, stats = run_workload_engine(engine, wl, na)
        wl.verify_all(recv)
        assert isinstance(stats, RouteStats)


@pytest.mark.parametrize("stripe", STRIPES)
@pytest.mark.parametrize("co,mode", [(1, 0), (2, 0), (2, 1), (4, 1)])
def test_local_agg_delivers(stripe, co, mode):
    na, wl = _mk(nprocs=12, per_node=4, blocklen=5, stripe=stripe)
    meta = aggregator_meta_information(na, wl.aggregators, co, mode)
    recv, stats = cw2_local_agg(wl, na, meta)
    wl.verify_all(recv)
    # every byte crosses the exchange hop exactly once
    assert (stats.exchange_intra_bytes + stats.exchange_inter_bytes
            == wl.total_bytes)


def test_shared_requires_local_agg_destinations():
    na, wl = _mk(nprocs=8, per_node=4, stripe=StripeType.ALL)
    meta = aggregator_meta_information(na, wl.aggregators, 2, 0)
    # co=2 < ranks per node: some destination is not a local aggregator
    with pytest.raises(ValueError):
        cw3_shared(wl, na, meta)


@pytest.mark.parametrize("stripe", STRIPES)
def test_shared_delivers_with_mode1(stripe):
    na, wl = _mk(nprocs=8, per_node=4, blocklen=3, stripe=stripe)
    # mode 1 with co = node size makes every destination a local aggregator
    meta = aggregator_meta_information(na, wl.aggregators, 4, 1)
    recv, stats = cw3_shared(wl, na, meta)
    wl.verify_all(recv)
    assert stats.staged_bytes == wl.total_bytes  # everyone stages everything
    assert stats.gather_bytes == 0               # no link crossed intra-group


def test_benchmark_route_stats():
    na, wl = _mk(nprocs=8, per_node=4, blocklen=4, stripe=StripeType.LESS)
    _, stats = cw_benchmark(wl)
    assert stats.direct_bytes == wl.total_bytes == stats.network_bytes


def test_proxy_route_stats_split_by_node():
    na, wl = _mk(nprocs=8, per_node=4, blocklen=4, stripe=StripeType.SAME)
    _, stats = cw_proxy(wl, na)
    sizes = wl.msg_size
    # inter-node: every (src, dst) pair whose nodes differ, relayed by proxies
    expect_inter = sum(int(sizes[s]) for s in range(8)
                       for d in wl.aggregators
                       if na.node_of[s] != na.node_of[int(d)])
    assert stats.exchange_inter_bytes == expect_inter
    # gather: non-proxy senders forward their full pack to the proxy
    expect_gather = sum(int(sizes[s]) * len(wl.aggregators)
                        for s in range(8) if not na.is_proxy(s))
    assert stats.gather_bytes == expect_gather


def test_recv_index_map_partitions_ranks():
    na, wl = _mk(nprocs=12, per_node=4, blocklen=5)
    meta = aggregator_meta_information(na, wl.aggregators, 2, 0)
    rim = recv_index_map(wl, meta)
    seen = sorted(src for group in rim.values() for (src, _sz) in group)
    assert seen == list(range(12))
    for group in rim.values():  # ascending source order within a group
        srcs = [s for (s, _) in group]
        assert srcs == sorted(srcs)


def test_run_workload_engine_dispatch_errors():
    na, wl = _mk()
    with pytest.raises(ValueError):
        run_workload_engine("local_agg", wl, na)  # meta required
    with pytest.raises(ValueError):
        run_workload_engine("nope", wl, na)


# ---------------------------------------------------------------------------
# JAX mesh engine vs oracle

@pytest.mark.parametrize("stripe", STRIPES)
@pytest.mark.parametrize("co,mode", [(1, 0), (2, 0), (2, 1)])
def test_cw2_jax_matches_oracle(stripe, co, mode):
    import jax

    na, wl = _mk(nprocs=8, per_node=4, blocklen=5, stripe=stripe)
    meta = aggregator_meta_information(na, wl.aggregators, co, mode)
    recv, times = cw2_local_agg_jax(wl, na, meta, jax.devices(), ntimes=2)
    wl.verify_all(recv)
    assert len(times) == 2
    oracle, _ = cw2_local_agg(wl, na, meta)
    for g in recv:
        for src in range(8):
            np.testing.assert_array_equal(recv[g][src], oracle[g][src])


def test_cw2_jax_rejects_bad_topology():
    import jax

    na = static_node_assignment(8, 4, 1)  # round-robin map: not mesh-able
    wl = initialize_setting(na, 3, StripeType.ALL)
    meta = aggregator_meta_information(na, wl.aggregators, 1, 0)
    with pytest.raises(ValueError):
        cw2_local_agg_jax(wl, na, meta, jax.devices())


@pytest.mark.parametrize("stripe", list(StripeType))
@pytest.mark.parametrize("kind,per_node", [(0, 4), (0, 2), (1, 4), (0, 8)])
def test_cw_proxy_sim_matches_oracle(stripe, kind, per_node):
    from tpu_aggcomm.tam.workload_engines import cw_proxy_sim
    na, wl = _mk(nprocs=8, per_node=per_node, blocklen=5, stripe=stripe,
                 kind=kind)
    recv_sim, times = cw_proxy_sim(wl, na, ntimes=2)
    wl.verify_all(recv_sim)
    recv_o, _ = cw_proxy(wl, na)
    for dst in recv_o:
        for src in range(wl.nprocs):
            np.testing.assert_array_equal(recv_sim[dst][src],
                                          recv_o[dst][src])
    assert len(times) == 2


def test_cw_proxy_sim_uneven_last_node():
    # nprocs not divisible by per_node: last node smaller
    from tpu_aggcomm.tam.workload_engines import cw_proxy_sim
    na = static_node_assignment(7, 3, 0)
    wl = initialize_setting(na, 4, StripeType.GREATER)
    recv, _ = cw_proxy_sim(wl, na)
    wl.verify_all(recv)


def test_cw_proxy_sim_chained_matches_oracle():
    """ADVICE r1: the sim engine's chained differenced mode — delivery
    stays byte-exact and every rep time is the differenced per-rep figure."""
    from tpu_aggcomm.core.topology import static_node_assignment
    from tpu_aggcomm.core.workload import StripeType, initialize_setting
    from tpu_aggcomm.tam.workload_engines import cw_proxy_sim

    na = static_node_assignment(8, 4, 0)
    wl = initialize_setting(na, 5, StripeType.SAME)
    recv, times = cw_proxy_sim(wl, na, ntimes=3, chained=True)
    wl.verify_all(recv)
    assert len(times) == 3
    assert all(t > 0 for t in times)
    assert times[0] == times[1] == times[2]


# ---------------------------------------------------------------------------
# collective_write3 executable realizations (VERDICT r1 item 4)

@pytest.mark.parametrize("stripe", [StripeType.SAME, StripeType.GREATER,
                                    StripeType.LESS, StripeType.ALL])
def test_cw3_shared_jax_matches_oracle(stripe):
    """The compiled shared-window route (in-slice all_gather staging +
    outer-axis hindexed exchange) delivers byte-for-byte what the
    cw3_shared oracle accounts for, on every stripe workload."""
    import jax

    from tpu_aggcomm.tam.workload_engines import cw3_shared, cw3_shared_jax

    na = static_node_assignment(8, 4, 0)
    wl = initialize_setting(na, 5, stripe)
    meta = aggregator_meta_information(na, wl.aggregators, 4, 1)
    recv_o, _stats = cw3_shared(wl, na, meta)
    recv_j, times = cw3_shared_jax(wl, na, meta, jax.devices(), ntimes=2)
    wl.verify_all(recv_j)
    assert set(recv_j) == set(recv_o)
    for g in recv_o:
        for s in range(wl.nprocs):
            assert np.array_equal(recv_o[g][s], recv_j[g][s]), (g, s)
    assert len(times) == 2


def test_cw3_shared_jax_rejects_non_local_destination():
    import jax

    from tpu_aggcomm.tam.workload_engines import cw3_shared_jax

    na = static_node_assignment(8, 4, 0)
    wl = initialize_setting(na, 5, StripeType.LESS)
    meta = aggregator_meta_information(na, wl.aggregators, 1, 0)  # mode 0
    with pytest.raises(ValueError, match="local aggregators"):
        cw3_shared_jax(wl, na, meta, jax.devices())


# ---------------------------------------------------------------------------
# mutation tests: the oracles route REAL bytes (VERDICT r2 item 6) — a
# corrupted staged payload must surface as a VerificationError, proving
# delivery reads the staging structures instead of re-filling


def _flip_first_byte(arr: np.ndarray) -> None:
    arr[0] ^= 0xFF


def test_proxy_mutation_caught():
    na, wl = _mk(nprocs=8, per_node=4, stripe=StripeType.SAME)

    def corrupt(holdings):
        # one staged run at node 0's proxy, between P2 and P3
        _src, _dst, payload = holdings[0][0]
        _flip_first_byte(payload)

    recv, _ = cw_proxy(wl, na, corrupt_hook=corrupt)
    with pytest.raises(VerificationError):
        wl.verify_all(recv)


def test_local_agg_mutation_caught():
    na, wl = _mk(nprocs=8, per_node=4, stripe=StripeType.GREATER)
    meta = aggregator_meta_information(na, wl.aggregators, 2, 0)

    def corrupt(staged):
        agg = next(iter(staged))
        src = next(iter(staged[agg]))
        dst = next(iter(staged[agg][src]))
        _flip_first_byte(staged[agg][src][dst])

    recv, _ = cw2_local_agg(wl, na, meta, corrupt_hook=corrupt)
    with pytest.raises(VerificationError):
        wl.verify_all(recv)


def test_shared_mutation_caught():
    na, wl = _mk(nprocs=8, per_node=4, stripe=StripeType.SAME)
    meta = aggregator_meta_information(na, wl.aggregators, 4, 1)

    def corrupt(windows):
        agg = next(iter(windows))
        src = next(iter(windows[agg]))
        dst = next(iter(windows[agg][src]))
        _flip_first_byte(windows[agg][src][dst])

    recv, _ = cw3_shared(wl, na, meta, corrupt_hook=corrupt)
    with pytest.raises(VerificationError):
        wl.verify_all(recv)


def test_uncorrupted_oracles_still_verify():
    """The staging rewire changes no delivered byte."""
    na, wl = _mk(nprocs=8, per_node=4, stripe=StripeType.SAME)
    recv, _ = cw_proxy(wl, na)
    wl.verify_all(recv)
    meta = aggregator_meta_information(na, wl.aggregators, 2, 0)
    recv, _ = cw2_local_agg(wl, na, meta)
    wl.verify_all(recv)
    meta1 = aggregator_meta_information(na, wl.aggregators, 4, 1)
    recv, _ = cw3_shared(wl, na, meta1, corrupt_hook=None)
    wl.verify_all(recv)
