"""Autotuner (tpu_aggcomm/tune/) guarantees:

- the search space refuses dead / TAM / unknown method ids and mixed
  traffic directions, NAMING the offending ids (the ``inspect compare``
  TraceCompareError discipline applied to tuning grids);
- the seeded racing loop converges to the injected-fast oracle winner
  on a synthetic skew grid, deterministically (same samples in → same
  eliminations and winner out);
- ``cli tune --replay`` re-derives the stored elimination order and
  winner byte for byte from the committed TUNE artifact — including in
  a subprocess where ``import jax`` is POISONED (the --auto/replay path
  must run on the supervisor side of a dead tunnel);
- the tuned-schedule cache is keyed by the v3 ledger manifest
  fingerprint: manifest drift (e.g. a jax version change) turns a hit
  into a named miss, and ``--auto`` falls back to the explicit flags
  with a stderr warning;
- ``obs/regress.validate_tune`` accepts every artifact ``save_tune``
  writes and rejects corrupted ones;
- ``JaxSimBackend.measure_trial_samples`` returns FRESH differenced
  trials per call (no per-schedule sample memoization — racing needs
  new measurements every batch) while reusing the compiled chains;
- the ``inspect report`` dashboard inlines a tuner pane from
  ``TUNE_*.json`` jax-free.
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_aggcomm.tune import cache
from tpu_aggcomm.tune.race import (RaceError, make_synthetic_sampler, race,
                                   replay_record)
from tpu_aggcomm.tune.space import (Candidate, SpaceError, build_space,
                                    parse_cid, space_direction)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED_TUNE = os.path.join(REPO, "TUNE_local_n8_d64_p1_a2m.json")


# ------------------------------------------------------------ search space

class TestSpace:
    def test_grid_is_cartesian_in_input_order(self):
        cands = build_space([3, 1], [2, 4], [8], [1], nprocs=8)
        assert [c.cid for c in cands] == [
            "m3:a2:c8:t1", "m3:a4:c8:t1", "m1:a2:c8:t1", "m1:a4:c8:t1"]

    def test_cid_roundtrip(self):
        c = Candidate(method=3, cb_nodes=14, comm_size=8, agg_type=2)
        assert parse_cid(c.cid) == c
        with pytest.raises(SpaceError, match="malformed"):
            parse_cid("m3:a14")

    def test_unknown_ids_named(self):
        with pytest.raises(SpaceError, match=r"unknown method id\(s\) \[99\]"):
            build_space([1, 99], [2], [8], [1], nprocs=8)

    def test_dead_ids_named_with_method_name(self):
        from tpu_aggcomm.core.methods import METHODS
        with pytest.raises(SpaceError) as ei:
            build_space([21], [2], [8], [1], nprocs=8)
        assert "m=21" in str(ei.value)
        assert METHODS[21].name in str(ei.value)

    def test_tam_ids_need_opt_in(self):
        with pytest.raises(SpaceError, match=r"TAM method id\(s\) \[15\]"):
            build_space([15], [2], [8], [1], nprocs=8)

    def test_mixed_directions_named_per_direction(self):
        with pytest.raises(SpaceError) as ei:
            build_space([1, 2], [2], [8], [1], nprocs=8)
        msg = str(ei.value)
        assert "all_to_many: [1]" in msg and "many_to_all: [2]" in msg

    def test_axis_range_guards(self):
        with pytest.raises(SpaceError, match=r"cb_nodes value\(s\) \[9\]"):
            build_space([1], [9], [8], [1], nprocs=8)
        with pytest.raises(SpaceError, match=r"agg_type value\(s\) \[7\]"):
            build_space([1], [2], [8], [7], nprocs=8)
        with pytest.raises(SpaceError, match="empty tuning grid"):
            build_space([1], [], [8], [1], nprocs=8)

    def test_space_direction(self):
        assert space_direction([1, 3]) == "all_to_many"
        assert space_direction([2]) == "many_to_all"


# ------------------------------------------------------------- racing loop

def _oracle_race(**kw):
    cids = [c.cid for c in build_space([1, 3, 7], [4], [8], [1], nprocs=8)]
    sampler = make_synthetic_sampler("100,m3*0.5", batch_trials=3, seed=0)
    return cids, race(cids, sampler, **kw)


class TestRace:
    def test_converges_to_injected_oracle_winner(self):
        cids, res = _oracle_race()
        assert parse_cid(res.winner).method == 3
        # the 2x-slower candidates must actually be ELIMINATED by the
        # CI gate, not merely outlived
        out = {e["candidate"] for e in res.eliminations}
        assert out == {c for c in cids if parse_cid(c).method != 3}
        for e in res.eliminations:
            lo, hi = e["ci_pct"]
            assert 0 < lo < hi
            assert e["leader"] == res.winner

    def test_deterministic(self):
        _, a = _oracle_race()
        _, b = _oracle_race()
        assert a.winner == b.winner
        assert a.eliminations == b.eliminations
        assert a.samples == b.samples

    def test_replay_reproduces_from_record(self):
        cids, res = _oracle_race(max_batches=4, alpha=0.05, seed=7)
        rec = {"seed": 7, "alpha": 0.05, "n_boot": 2000, "max_batches": 4,
               "order": cids, "samples": res.samples,
               "eliminations": res.eliminations, "winner": res.winner}
        # JSON round trip first: the replay path consumes artifacts
        rec = json.loads(json.dumps(rec))
        out = replay_record(rec)
        assert out.winner == res.winner
        assert json.loads(json.dumps(out.eliminations)) == rec["eliminations"]

    def test_replay_truncated_record_raises(self):
        cids, res = _oracle_race()
        rec = {"seed": 0, "alpha": 0.05, "n_boot": 2000, "max_batches": 6,
               "order": cids,
               "samples": {c: b[:0] for c, b in res.samples.items()}}
        with pytest.raises(RaceError, match="no recorded batch"):
            replay_record(rec)

    def test_bad_inputs(self):
        with pytest.raises(RaceError, match="at least one"):
            race([], lambda c, b: [1.0])
        with pytest.raises(RaceError, match="duplicate"):
            race(["x", "x"], lambda c, b: [1.0])
        with pytest.raises(RaceError, match="empty batch"):
            race(["x", "y"], lambda c, b: [])
        with pytest.raises(RaceError, match="malformed synthetic spec"):
            make_synthetic_sampler("100,m3x0.5")

    def test_inseparable_candidates_survive(self):
        # identical distributions: nobody should be eliminated
        cids = ["m1:a2:c8:t1", "m1:a4:c8:t1"]
        sampler = make_synthetic_sampler("100", batch_trials=3, seed=0)
        res = race(cids, sampler, max_batches=3)
        assert res.survivors == cids
        assert res.eliminations == []


# ------------------------------------------------------------- tuned cache

def _manifest(jax="0.9.9"):
    return {"schema": 3, "versions": {"jax": jax, "jaxlib": jax},
            "python": "3.11.0", "platform": "cpu",
            "env": {"tunnel_armed": False, "armed_vars": []},
            "created_unix": 1e9, "git_sha": "abc"}


class TestCache:
    def test_fingerprint_tracks_drift_only(self):
        a = cache.manifest_fingerprint(_manifest())
        assert a == cache.manifest_fingerprint(_manifest())
        # DRIFT_IGNORE keys (timestamps, git sha) don't move it
        m = _manifest()
        m["created_unix"] = 2e9
        m["git_sha"] = "def"
        assert cache.manifest_fingerprint(m) == a
        # a drift-relevant key does
        assert cache.manifest_fingerprint(_manifest(jax="1.0.0")) != a

    def _save(self, root, man):
        cids, res = _oracle_race()
        key = cache.tune_key(nprocs=8, data_size=64, proc_node=1,
                             direction="all_to_many", backend="local",
                             manifest=man)
        win = parse_cid(res.winner)
        return key, cache.save_tune(
            str(root), key=key, manifest=man,
            space={"methods": [1, 3, 7], "cb_nodes": [4],
                   "comm_sizes": [8], "agg_types": [1]},
            race={"seed": 0, "alpha": 0.05, "n_boot": 2000,
                  "max_batches": 6, "batch_trials": 3, "order": cids,
                  "samples": res.samples, "eliminations": res.eliminations,
                  "winner": res.winner, "batches_run": res.batches_run,
                  "survivors": res.survivors},
            winner={"method": win.method, "cb_nodes": win.cb_nodes,
                    "comm_size": win.comm_size, "agg_type": win.agg_type},
            synthetic=True)

    def test_lookup_hit_and_drift_miss(self, tmp_path):
        man = _manifest()
        key, path = self._save(tmp_path, man)
        entry, note = cache.lookup(str(tmp_path), key, manifest=man)
        assert note is None and entry["winner"]["method"] == 3
        # same shape, drifted environment: named miss
        man2 = _manifest(jax="1.0.0")
        key2 = cache.tune_key(nprocs=8, data_size=64, proc_node=1,
                              direction="all_to_many", backend="local",
                              manifest=man2)
        entry, note = cache.lookup(str(tmp_path), key2, manifest=man2)
        assert entry is None
        assert "manifest drift" in note and "versions.jax" in note

    def test_lookup_misses_are_distinguished(self, tmp_path):
        key = cache.tune_key(nprocs=8, data_size=64, proc_node=1,
                             direction="all_to_many", backend="local",
                             manifest=_manifest())
        entry, note = cache.lookup(str(tmp_path), key, manifest=_manifest())
        assert entry is None and note.startswith("no tuned entry")
        path = cache.artifact_path(str(tmp_path), key)
        with open(path, "w") as fh:
            fh.write("{not json")
        entry, note = cache.lookup(str(tmp_path), key, manifest=_manifest())
        assert entry is None and "unreadable" in note
        with open(path, "w") as fh:
            json.dump({"schema": "tune-v0"}, fh)
        entry, note = cache.lookup(str(tmp_path), key, manifest=_manifest())
        assert entry is None and "invalid tune artifact" in note

    def test_lookup_different_context(self, tmp_path):
        man = _manifest()
        key, path = self._save(tmp_path, man)
        # overwrite the stored key's nprocs: the filename matches but
        # the context does not — must be a named miss, not a hit
        blob = cache.load_tune(path)
        blob["key"]["nprocs"] = 16
        with open(path, "w") as fh:
            json.dump(blob, fh)
        entry, note = cache.lookup(str(tmp_path), key, manifest=man)
        assert entry is None and "different context" in note

    def test_artifact_filename_excludes_fingerprint(self):
        k1 = cache.tune_key(nprocs=8, data_size=64, proc_node=1,
                            direction="all_to_many", backend="local",
                            manifest=_manifest())
        k2 = cache.tune_key(nprocs=8, data_size=64, proc_node=1,
                            direction="all_to_many", backend="local",
                            manifest=_manifest(jax="1.0.0"))
        assert k1["fingerprint"] != k2["fingerprint"]
        assert (cache.artifact_path(".", k1) == cache.artifact_path(".", k2)
                == "./TUNE_local_n8_d64_p1_a2m.json")

    def test_validate_tune_accepts_saved_rejects_corrupt(self, tmp_path):
        from tpu_aggcomm.obs.regress import validate_tune
        man = _manifest()
        _, path = self._save(tmp_path, man)
        blob = json.loads(json.dumps(cache.load_tune(path)))
        assert validate_tune(blob, "t") == []
        bad = json.loads(json.dumps(blob))
        bad["schema"] = "tune-v0"
        assert validate_tune(bad, "t")
        bad = json.loads(json.dumps(blob))
        bad["race"]["winner"] = "m9:a9:c9:t9"      # no samples for it
        assert validate_tune(bad, "t")
        bad = json.loads(json.dumps(blob))
        bad["winner"]["method"] = 7                # cid inconsistency
        assert validate_tune(bad, "t")
        bad = json.loads(json.dumps(blob))
        bad["race"]["samples"] = {}
        assert validate_tune(bad, "t")


# ------------------------------------------------------------- CLI surface

class TestCli:
    def test_tune_synthetic_then_replay(self, tmp_path, capsys):
        from tpu_aggcomm.cli import main
        rc = main(["tune", "-n", "8", "-d", "64", "--backend", "local",
                   "--methods", "1,3,7", "--synthetic", "100,m3*0.5",
                   "--tune-root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "winner: m3:a4:c8:t1" in out
        path = os.path.join(str(tmp_path), "TUNE_local_n8_d64_p1_a2m.json")
        assert os.path.exists(path)
        rc = main(["tune", "--replay", path])
        out = capsys.readouterr().out
        assert rc == 0
        assert "REPRODUCED" in out

    def test_tune_space_error_exits_named(self, tmp_path, capsys):
        from tpu_aggcomm.cli import main
        with pytest.raises(SystemExit) as ei:
            main(["tune", "-n", "8", "--methods", "1,2",
                  "--tune-root", str(tmp_path)])
        assert "all_to_many: [1]" in str(ei.value)

    def test_committed_artifact_replays(self, capsys):
        """The checked-in TUNE artifact must reproduce its verdict —
        the exact check ci_tier1.sh runs."""
        from tpu_aggcomm.cli import main
        assert os.path.exists(COMMITTED_TUNE), "committed TUNE artifact gone"
        rc = main(["tune", "--replay", COMMITTED_TUNE])
        out = capsys.readouterr().out
        assert rc == 0
        assert "REPRODUCED" in out

    def test_replay_detects_tampered_record(self, tmp_path, capsys):
        from tpu_aggcomm.cli import main
        blob = cache.load_tune(COMMITTED_TUNE)
        # claim a different winner than the samples support
        loser = next(c for c in blob["race"]["order"]
                     if c != blob["race"]["winner"])
        blob["race"]["winner"] = loser
        blob["winner"] = {
            "method": parse_cid(loser).method,
            "cb_nodes": parse_cid(loser).cb_nodes,
            "comm_size": parse_cid(loser).comm_size,
            "agg_type": parse_cid(loser).agg_type}
        p = tmp_path / "TUNE_local_n8_d64_p1_a2m.json"
        p.write_text(json.dumps(blob))
        rc = main(["tune", "--replay", str(p)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "MISMATCH" in out

    def test_auto_hit_applies_winner(self, tmp_path, capsys):
        from tpu_aggcomm.cli import main
        rc = main(["tune", "-n", "8", "-d", "64", "--backend", "local",
                   "--methods", "1,3", "--cb-nodes", "4", "--comm-sizes",
                   "8", "--agg-types", "1", "--synthetic", "100,m3*0.5",
                   "--tune-root", str(tmp_path)])
        assert rc == 0
        capsys.readouterr()
        rc = main(["-n", "8", "-a", "2", "-d", "64", "-c", "2", "-m", "1",
                   "--backend", "local", "--auto",
                   "--tune-root", str(tmp_path),
                   "--results-csv", str(tmp_path / "r.csv")])
        cap = capsys.readouterr()
        assert rc == 0
        assert ("auto: tuned -m 3 -a 4 -c 8 -t 1 [synthetic tune]"
                in cap.out)

    def test_auto_miss_warns_and_falls_back(self, tmp_path, capsys):
        from tpu_aggcomm.cli import main
        rc = main(["-n", "8", "-a", "2", "-d", "64", "-c", "2", "-m", "1",
                   "--backend", "local", "--auto",
                   "--tune-root", str(tmp_path),
                   "--results-csv", str(tmp_path / "r.csv")])
        cap = capsys.readouterr()
        assert rc == 0
        assert "no tuned entry" in cap.err
        assert "falling back to -m 1" in cap.err

    def test_replay_survives_poisoned_jax(self, tmp_path):
        """The tier-1 replay step must run where jax cannot import —
        shared recipe (tests/_jaxfree.py, parameterized by the linter's
        purity contract)."""
        import _jaxfree
        env = _jaxfree.poisoned_env(
            tmp_path, "tune --replay must not import jax")
        r = subprocess.run(
            [sys.executable, "-m", "tpu_aggcomm.cli", "tune", "--replay",
             COMMITTED_TUNE],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "REPRODUCED" in r.stdout


# ---------------------------------------------------------- measured batches

def test_measure_trial_samples_fresh_per_call():
    """Racing needs NEW samples every batch: the tune hook must bypass
    measure_per_rep's per-schedule sample memoization while keeping the
    compiled chains cached (one tune_chains entry, reused)."""
    from tpu_aggcomm.backends.jax_sim import JaxSimBackend
    from tpu_aggcomm.core.methods import compile_method
    from tpu_aggcomm.core.pattern import AggregatorPattern

    backend = JaxSimBackend()
    sched = compile_method(1, AggregatorPattern(
        nprocs=8, cb_nodes=2, data_size=64, proc_node=1, comm_size=2))
    a = backend.measure_trial_samples(sched, iters_small=2, iters_big=12,
                                      trials=2, windows=1)
    b = backend.measure_trial_samples(sched, iters_small=2, iters_big=12,
                                      trials=2, windows=1)
    assert len(a) == len(b) == 2
    assert all(isinstance(x, float) for x in a + b)
    assert a is not b                       # no memoized list handed back
    keys = [k for k in backend._chain_cache if "tune_chains" in k]
    assert len(keys) == 1                   # chains compiled exactly once


def test_jax_sim_sampler_races_end_to_end(tmp_path):
    """Small measured race on the CPU mesh: the full sampler → race →
    save → lookup loop with a real backend (no assertion on who wins —
    CPU timings are not the oracle; the artifact contract is)."""
    from tpu_aggcomm.obs.ledger import manifest
    from tpu_aggcomm.tune.measure import make_jax_sim_sampler

    cands = [c.cid for c in build_space([1], [2, 4], [2], [1], nprocs=8)]
    sampler = make_jax_sim_sampler(nprocs=8, data_size=64, proc_node=1,
                                   iters_small=2, iters_big=12,
                                   batch_trials=2, windows=1)
    res = race(cands, sampler, max_batches=2)
    assert res.winner in cands
    assert all(len(b) == 2 for bl in res.samples.values() for b in bl)
    man = manifest()
    key = cache.tune_key(nprocs=8, data_size=64, proc_node=1,
                         direction="all_to_many", backend="jax_sim",
                         manifest=man)
    win = parse_cid(res.winner)
    cache.save_tune(
        str(tmp_path), key=key, manifest=man,
        space={"methods": [1], "cb_nodes": [2, 4], "comm_sizes": [2],
               "agg_types": [1]},
        race={"seed": 0, "alpha": 0.05, "n_boot": 2000, "max_batches": 2,
              "batch_trials": 2, "order": cands, "samples": res.samples,
              "eliminations": res.eliminations, "winner": res.winner,
              "batches_run": res.batches_run, "survivors": res.survivors},
        winner={"method": win.method, "cb_nodes": win.cb_nodes,
                "comm_size": win.comm_size, "agg_type": win.agg_type})
    entry, note = cache.lookup(str(tmp_path), key, manifest=man)
    assert note is None
    assert entry["winner"]["method"] == 1


# ------------------------------------------------------------ report pane

def test_report_payload_and_pane(tmp_path):
    import shutil

    from tpu_aggcomm.obs.report_html import build_payload, render_html

    shutil.copy(COMMITTED_TUNE, tmp_path / os.path.basename(COMMITTED_TUNE))
    (tmp_path / "TUNE_local_n9_d64_p1_a2m.json").write_text("{corrupt")
    payload = build_payload(history_root=str(tmp_path))
    rows = {r["file"]: r for r in payload["tune"]}
    good = rows[os.path.basename(COMMITTED_TUNE)]
    assert good["error"] is None
    assert parse_cid(good["winner_cid"]).method == good["winner"]["method"]
    assert good["synthetic"] is True
    assert good["eliminations"] and good["medians"]
    assert "unparsable JSON" in rows["TUNE_local_n9_d64_p1_a2m.json"]["error"]
    html = render_html(payload)
    assert 'id="tune"' in html and "tunePane" in html
    assert os.path.basename(COMMITTED_TUNE) in html
