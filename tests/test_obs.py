"""Flight-recorder (tpu_aggcomm/obs) guarantees:

- zero-cost when disabled: no-op spans, no recorder, no jax import from
  the obs package (bench.py's jax-free supervisor imports obs.regress);
- overhead guard: a traced local run produces structurally byte-identical
  results.csv rows (every non-timing column) and timer values within
  tolerance of the untraced run;
- round trip: the JSONL event log of a multi-round ``-c``-throttled run
  re-aggregates to the Timer's phase columns FLOAT-EXACTLY (the trace
  records the attribution's exact Timer.add arithmetic in order —
  harness/attribution.py cell sinks), with a column-accurate
  PHASE_SOURCES label on every reconstructed slice;
- the Perfetto export is valid JSON with monotonically non-decreasing
  ``ts`` per (pid, tid) track;
- the bench-history schema (obs/regress.py + scripts/check_bench_schema.py)
  accepts every committed BENCH_r*/MULTICHIP_r*.json and rejects
  malformed artifacts; regression verdicts compare only same-(metric,
  platform) rounds.
"""

import io
import json
import os
import subprocess
import sys

import pytest

from tpu_aggcomm.harness.report import PHASE_SOURCES
from tpu_aggcomm.harness.runner import ExperimentConfig, run_experiment
from tpu_aggcomm.obs import trace
from tpu_aggcomm.obs.perfetto import RANKS_PID, to_chrome_trace
from tpu_aggcomm.obs.regress import (check_regression, validate_bench,
                                     validate_multichip)
from tpu_aggcomm.obs.trace import aggregate_run, load_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _timer_cols(t):
    return {"post": t.post_request_time, "send_wait": t.send_wait_all_time,
            "recv_wait": t.recv_wait_all_time, "barrier": t.barrier_time,
            "total": t.total_time}


def _run(backend, *, tmp_path=None, csv_name="results.csv", traced=False,
         prefix=None, **kw):
    cfg = ExperimentConfig(
        nprocs=8, cb_nodes=2, data_size=64, comm_size=2, method=1,
        ntimes=3, backend=backend, verify=True,
        results_csv=str(tmp_path / csv_name) if tmp_path else None, **kw)
    if traced:
        trace.enable()
        try:
            recs = run_experiment(cfg, out=io.StringIO())
        finally:
            paths = trace.flush(prefix)
            trace.disable()
        return recs, paths
    return run_experiment(cfg, out=io.StringIO()), None


# ---------------------------------------------------------------- disabled

def test_disabled_tracing_is_noop():
    assert trace.current() is None
    s1 = trace.span("anything", rank=3)
    s2 = trace.span("else")
    assert s1 is s2          # shared no-op singleton — zero allocation
    with s1:
        pass
    trace.instant("nothing")  # must not raise
    assert trace.flush("/nonexistent/prefix") is None


def test_obs_package_imports_no_jax(tmp_path):
    """bench.py's supervisor process is deliberately jax-free (a dead
    tunnel hangs ``import jax``); obs must stay importable there. The
    module list comes from the linter's purity contract (tests/_jaxfree
    over analysis.lint.PURE_PACKAGES), so a NEW obs module is pinned
    here the moment it exists — no hand-maintained import list to rot —
    and the poisoned env makes any jax import raise instead of hang."""
    import _jaxfree
    mods = _jaxfree.pure_modules("tpu_aggcomm.obs")
    assert "tpu_aggcomm.obs.traffic" in mods      # the list is real
    r = subprocess.run(
        [sys.executable, "-c", _jaxfree.pure_import_code("tpu_aggcomm.obs")],
        cwd=REPO, env=_jaxfree.poisoned_env(tmp_path, "obs must not "
                                            "import jax"),
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr


# ----------------------------------------------------------- overhead guard

def test_overhead_guard_local(tmp_path):
    """Satellite 2: tracing must not change WHAT the local oracle computes
    (verify=True pins recv bytes both times) nor the CSV row structure —
    every non-timing column byte-identical — and the traced timers must
    stay within a generous same-order-of-magnitude tolerance (the 1-core
    build host jitters; this guards against pathological overhead, not
    percent-level noise)."""
    recs_u, _ = _run("local", tmp_path=tmp_path, csv_name="untraced.csv")
    recs_t, paths = _run("local", tmp_path=tmp_path, csv_name="traced.csv",
                         traced=True, prefix=str(tmp_path / "tr"))
    assert paths is not None and os.path.exists(paths[0])

    rows_u = (tmp_path / "untraced.csv").read_text().splitlines()
    rows_t = (tmp_path / "traced.csv").read_text().splitlines()
    assert len(rows_u) == len(rows_t)
    for ru, rt in zip(rows_u, rows_t):
        # first 7 CSV columns are method/config (report.py): byte-identical
        assert ru.split(",")[:7] == rt.split(",")[:7]
    tu = recs_u[0]["timer0"].total_time
    tt = recs_t[0]["timer0"].total_time
    assert tt <= tu * 10 + 1e-2, (
        f"traced local run pathologically slower: {tt:.6f}s vs {tu:.6f}s")
    # provenance must be untouched by tracing
    assert recs_u[0]["phase_source"] == recs_t[0]["phase_source"]


# ---------------------------------------------------------------- round trip

@pytest.mark.parametrize("backend", ["local", "jax_sim"])
def test_roundtrip_exact(tmp_path, backend):
    """Satellite 3: the JSONL events of a multi-round throttled run
    re-aggregate to the Timer's phase columns float-exactly, for every
    rank — total-only rep timers (local) and attributed cells (jax_sim)
    both replay the exact accumulation arithmetic."""
    recs, paths = _run(backend, traced=True,
                       prefix=str(tmp_path / backend))
    events = load_events(paths[0])
    agg = aggregate_run(events, 0)
    assert set(agg) == set(range(8))
    exp = _timer_cols(recs[0]["timer0"])
    assert agg[0] == exp, f"rank 0 re-aggregation differs: {agg[0]} != {exp}"
    # the max-over-ranks reduction must also be reproducible from events
    max_total = max(a["total"] for a in agg.values())
    assert max_total == recs[0]["max_timer"].total_time


def test_roundtrip_exact_measured_phases(tmp_path):
    """The measured-rounds path (combine mode "scale": rep-0 columns ×
    ntimes, mirroring Timer.from_array(as_array() * ntimes)) round-trips
    exactly too."""
    recs, paths = _run("jax_sim", traced=True, measured_phases=True,
                       prefix=str(tmp_path / "mp"))
    agg = aggregate_run(load_events(paths[0]), 0)
    assert agg[0] == _timer_cols(recs[0]["timer0"])
    assert recs[0]["phase_source"] in PHASE_SOURCES


def test_perfetto_valid_and_monotone(tmp_path):
    """Satellite 3b: the Perfetto file is valid JSON; within every
    (pid, tid) track, ts never decreases; every reconstructed slice
    carries a column-accurate PHASE_SOURCES label."""
    _recs, paths = _run("jax_sim", traced=True,
                        prefix=str(tmp_path / "pf"))
    with open(paths[1]) as fh:
        pf = json.load(fh)
    evs = pf["traceEvents"]
    assert evs, "empty Perfetto export"
    last = {}
    for e in evs:
        if e.get("ph") not in ("X", "i", "C"):
            continue
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last.get(key, float("-inf")), (
            f"ts regressed on track {key}")
        last[key] = e["ts"]
    slices = [e for e in evs
              if e.get("ph") == "X" and e["pid"] == RANKS_PID]
    assert slices, "no reconstructed rank slices"
    for e in slices:
        assert e["args"]["phase_source"] in PHASE_SOURCES
    # counter tracks: byte-valued ones carry args.bytes, the traffic_*
    # count-valued ones carry args.value (never mislabeled as bytes)
    counters = [e for e in evs if e.get("ph") == "C"]
    assert counters
    for e in counters:
        key = ("bytes" if e["name"] == "bytes_in_flight"
               or e["name"].startswith("hbm_") else "value")
        assert key in e["args"], (e["name"], e["args"])
    names = {e["name"] for e in counters}
    assert {"bytes_in_flight", "traffic_msgs",
            "traffic_max_incast", "latency_p99_ms"} <= names
    # the per-round latency quantile tracks (obs/export.py projected
    # onto the timeline) must carry p50/p95 as round_stats VERBATIM
    from tpu_aggcomm.obs.metrics import round_stats
    events = load_events(paths[0])
    for rs in round_stats(events, 0):
        for q in ("p50", "p95"):
            want = rs[q] * 1e3
            got = [e["args"]["value"] for e in counters
                   if e["name"] == f"latency_{q}_ms"]
            assert want in got, (rs["round"], q, want, got)


def test_perfetto_rank_tracks(tmp_path):
    """One thread-name metadata entry per logical rank."""
    _recs, paths = _run("jax_sim", traced=True,
                        prefix=str(tmp_path / "tk"))
    pf = to_chrome_trace(load_events(paths[0]))
    names = {e["args"]["name"] for e in pf["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"
             and e["pid"] == RANKS_PID}
    assert {f"rank {r}" for r in range(8)} <= names


def test_perfetto_named_tracks_and_ledger(tmp_path):
    """Satellite 6: the export names its process/thread tracks (method +
    backend in the process_labels metadata, a named host-timeline
    thread) and carries the run-ledger preamble as an instant at t=0."""
    _recs, paths = _run("jax_sim", traced=True,
                        prefix=str(tmp_path / "nm"))
    pf = to_chrome_trace(load_events(paths[0]))
    evs = pf["traceEvents"]
    meta = [e for e in evs if e.get("ph") == "M"]
    pnames = {e["args"]["name"] for e in meta
              if e["name"] == "process_name"}
    assert any(n.startswith("ranks (reconstructed)") for n in pnames)
    labels = [e["args"]["labels"] for e in meta
              if e["name"] == "process_labels"]
    assert labels and any("m1" in lb and "[jax_sim]" in lb
                          for lb in labels)
    tnames = {e["args"]["name"] for e in meta if e["name"] == "thread_name"}
    assert "host timeline" in tnames
    ledgers = [e for e in evs
               if e.get("ph") == "i" and e["name"] == "ledger.manifest"]
    assert len(ledgers) == 1 and ledgers[0]["ts"] == 0.0
    man = ledgers[0]["args"]["manifest"]
    assert man["schema"] >= 3 and "versions" in man


def test_cli_inspect_trace(tmp_path, capsys):
    from tpu_aggcomm.cli import main

    _recs, paths = _run("jax_sim", traced=True,
                        prefix=str(tmp_path / "ci"))
    rc = main(["inspect", "trace", paths[0]])
    out = capsys.readouterr().out
    assert rc == 0
    assert "run 0:" in out and "rounds" in out


def test_cli_trace_flag_writes_artifacts(tmp_path):
    from tpu_aggcomm.cli import main

    prefix = str(tmp_path / "cli_tr")
    rc = main(["-n", "8", "-a", "2", "-d", "64", "-c", "2", "-m", "1",
               "--backend", "local", "--verify",
               "--results-csv", str(tmp_path / "r.csv"),
               "--trace", prefix])
    assert rc == 0
    assert os.path.exists(prefix + ".trace.jsonl")
    assert os.path.exists(prefix + ".trace.json")
    assert trace.current() is None   # CLI must disable tracing on exit


# ------------------------------------------------------- bench history tools

def test_committed_bench_history_validates():
    """Satellite 5 wiring: every committed artifact passes the shared
    schema; the checker script agrees."""
    r = subprocess.run(
        [sys.executable, os.path.join("scripts", "check_bench_schema.py")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 schema error(s)" in r.stdout


def test_check_bench_schema_rejects_malformed(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"n": "not-an-int", "cmd": "x", "rc": 0}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_bench_schema.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "FAIL" in r.stdout


def test_validate_bench_schema_units():
    good = {"n": 32, "cmd": "python bench.py", "rc": 0, "tail": "",
            "parsed": {"metric": "m", "value": 1e-6, "unit": "s"}}
    assert validate_bench(good) == []
    assert validate_bench({"n": 32}) != []
    bad = dict(good, parsed=dict(good["parsed"], value="fast"))
    assert any("value" in e for e in validate_bench(bad))
    assert validate_multichip({"n_devices": 8, "rc": 0, "ok": True,
                               "skipped": False, "tail": ""}) == []
    assert validate_multichip({"rc": 0}) != []


def _bench_blob(rnd, value, platform):
    return {"n": 32, "cmd": "bench", "rc": 0, "tail": "",
            "parsed": {"metric": "m", "value": value, "unit": "s",
                       "platform": platform}}


def test_check_regression_same_platform_only(tmp_path):
    """A slower CPU-fallback round after a fast TPU round is NOT a
    regression (no comparable prior); a same-platform 2x slowdown is."""
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(_bench_blob(1, 2e-6, "tpu")))
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps(_bench_blob(2, 6e-5, "cpu")))
    v = check_regression(str(tmp_path))
    assert v["ok"] and v["delta_pct"] is None

    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps(_bench_blob(3, 1.2e-4, "cpu")))
    v = check_regression(str(tmp_path))
    assert not v["ok"]
    assert v["baseline"]["round"] == 2
    assert v["delta_pct"] == pytest.approx(100.0)

    # within tolerance: ok
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps(_bench_blob(3, 6.5e-5, "cpu")))
    assert check_regression(str(tmp_path))["ok"]


def test_bench_check_regression_one_json_line():
    """The one-JSON-line stdout contract holds for --check-regression
    too (history detail goes to stderr); jax-free and fast."""
    r = subprocess.run(
        [sys.executable, "bench.py", "--check-regression"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, r.stdout
    verdict = json.loads(lines[0])
    assert verdict["check"] == "regression"
    assert verdict["ok"] is (r.returncode == 0)
